"""Logical-axis sharding rules: the TP / SP / EP / FSDP partitioning table.

The reference framework has exactly one collective composition, assembled by
hand out of NCCL subgroups (``ddp_n_pp.py:139-155``).  Here partitioning is a
*table*: every parameter and activation in the transformer family
(``models/transformer.py``) is annotated with logical axis names
(``flax.linen.with_logical_partitioning`` / ``nn.with_logical_constraint``),
and this module maps those names onto mesh axes.  Changing the parallelism
strategy — pure DP, 2-D tensor parallelism, expert parallelism, FSDP-style
parameter sharding, or any combination — is a rule-table edit, not a code
change; XLA's SPMD partitioner then inserts the collectives
(all-reduce for TP sums, all-to-all for expert dispatch, all-gather /
reduce-scatter for FSDP) and routes them over ICI.

Mesh axes (``build_lm_mesh``):
    data    — batch / gradient data parallelism (and FSDP param sharding)
    pipe    — pipeline parallelism over decoder-layer stages
              (``parallel/lm_pipeline.py``)
    seq     — sequence/context parallelism (ring attention,
              ``parallel/ring_attention.py``)
    model   — tensor parallelism (attention heads, MLP hidden, vocab)
    expert  — expert parallelism (MoE expert dimension)
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "LMMeshSpec",
    "build_lm_mesh",
    "lm_logical_rules",
    "SEQ_AXIS",
    "MODEL_AXIS",
    "EXPERT_AXIS",
    "PIPE_AXIS",
]

SEQ_AXIS = "seq"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"


@dataclasses.dataclass(frozen=True)
class LMMeshSpec:
    """5-axis mesh for the transformer family.

    Mesh axis order is ``(data, pipe, seq, model, expert)`` — but note the
    *field* order below is ``(data, seq, model, expert, pipe)``: ``pipe``
    was added last to keep existing positional constructions valid.  Pass
    ``pipe`` by keyword."""

    data: int = 1
    seq: int = 1
    model: int = 1
    expert: int = 1
    pipe: int = 1

    @property
    def num_devices(self) -> int:
        return self.data * self.pipe * self.seq * self.model * self.expert

    @property
    def axis_names(self) -> tuple[str, ...]:
        return ("data", PIPE_AXIS, SEQ_AXIS, MODEL_AXIS, EXPERT_AXIS)


def build_lm_mesh(spec: LMMeshSpec, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """``model`` innermost so TP all-reduces ride the shortest ICI hops;
    ``data`` outermost so gradient reduction can cross DCN (the same
    inner/outer split as the (data, pipe) mesh, ``parallel/mesh.py``).
    ``pipe`` sits next to ``data``: stage handoffs move one boundary
    activation per microbatch tick — tiny volume, DCN-tolerant — while
    seq/expert/model collectives stay on short ICI hops."""
    devices = list(devices if devices is not None else jax.devices())
    need = spec.num_devices
    if len(devices) < need:
        raise ValueError(f"mesh {spec} needs {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(
        spec.data, spec.pipe, spec.seq, spec.expert, spec.model
    )
    # axis order in the Mesh matches axis_names: (data, pipe, seq, model,
    # expert); physically, model varies fastest, then expert, then seq,
    # then pipe, then data.
    return Mesh(grid.transpose(0, 1, 2, 4, 3), spec.axis_names)


def lm_logical_rules(fsdp: bool = False) -> tuple[tuple[str, str | None], ...]:
    """Logical-name → mesh-axis table for the transformer family.

    With ``fsdp=True`` the ``embed`` parameter dimension is additionally
    sharded over ``data`` (ZeRO-3-style: params/optimizer state live sharded;
    XLA all-gathers them per layer in forward/backward and reduce-scatters
    the gradients — absent from the reference, whose DDP keeps full replicas,
    SURVEY.md §2.3).
    """
    return (
        # activations
        ("batch", "data"),
        ("act_seq", SEQ_AXIS),
        ("act_embed", None),
        ("act_heads", MODEL_AXIS),
        ("act_mlp", MODEL_AXIS),
        ("act_vocab", MODEL_AXIS),
        ("act_expert", EXPERT_AXIS),
        # parameters
        ("embed", "data" if fsdp else None),
        ("vocab", MODEL_AXIS),
        ("heads", MODEL_AXIS),
        ("head_dim", None),
        ("mlp", MODEL_AXIS),
        ("expert", EXPERT_AXIS),
        ("norm", None),
    )
