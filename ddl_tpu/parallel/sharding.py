"""Logical-axis sharding rules: the TP / SP / EP / FSDP partitioning table.

The reference framework has exactly one collective composition, assembled by
hand out of NCCL subgroups (``ddp_n_pp.py:139-155``).  Here partitioning is a
*table*: every parameter and activation in the transformer family
(``models/transformer.py``) is annotated with logical axis names
(``flax.linen.with_logical_partitioning`` / ``nn.with_logical_constraint``),
and this module maps those names onto mesh axes.  Changing the parallelism
strategy — pure DP, 2-D tensor parallelism, expert parallelism, FSDP-style
parameter sharding, or any combination — is a rule-table edit, not a code
change; XLA's SPMD partitioner then inserts the collectives
(all-reduce for TP sums, all-to-all for expert dispatch, all-gather /
reduce-scatter for FSDP) and routes them over ICI.

Mesh axes (``build_lm_mesh``):
    data    — batch / gradient data parallelism (and FSDP param sharding)
    pipe    — pipeline parallelism over decoder-layer stages
              (``parallel/lm_pipeline.py``)
    seq     — sequence/context parallelism (ring attention,
              ``parallel/ring_attention.py``)
    model   — tensor parallelism (attention heads, MLP hidden, vocab)
    expert  — expert parallelism (MoE expert dimension)
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "LMMeshSpec",
    "build_lm_mesh",
    "lm_logical_rules",
    "resolve_auto_flash",
    "normalize_flash",
    "validate_kv_head_sharding",
    "validate_ulysses_kv_heads",
    "FLASH_AUTO_MIN_T",
    "FLASH_AUTO_MIN_T_LOCAL_RING",
    "SEQ_AXIS",
    "MODEL_AXIS",
    "EXPERT_AXIS",
    "PIPE_AXIS",
]

# Training-step crossover for flash="auto", measured on one v5e chip
# (PERF.md): at T=512 the XLA dense path wins (78.6 vs 86.0 ms/step,
# batch 16); from T=1024 the Pallas kernel wins (93.9 vs 107.6 ms at
# batch 8) and the gap grows with T (backward dominates training, and
# flash backward wins at every measured length).
FLASH_AUTO_MIN_T = 1024
# Ring crossover operates on the per-device shard: each hop is a
# T_local x T_local block, and the device-only kernel table (PERF.md,
# round-3 slope method) shows flash beating dense in BOTH directions
# from 2048 — below that the per-hop kernels sit at the grid-overhead
# floor and the dense blocks win.
FLASH_AUTO_MIN_T_LOCAL_RING = 2048


def resolve_auto_flash(cfg, spec: "LMMeshSpec", seq_len: int) -> bool:
    """Resolve ``LMConfig.flash == "auto"`` to a concrete bool for a run.

    Lives here (not ``train/lm_steps.py``) so both the flat-step and the
    pipeline factories can share it without an import cycle.  Picks the
    Pallas kernel only where it is both *supported* — causal; not
    dense-with-sharded-seq, where the kernel cannot see the full sequence;
    heads divisible over ``model``, which the head-parallel manual core
    requires — and *measured faster*.  Ulysses attends the full sequence
    per head group after its all-to-all, so the global ``seq_len`` is the
    right scale; ring attends T_local-sized blocks per hop, so its
    threshold applies to ``seq_len / spec.seq``
    (``FLASH_AUTO_MIN_T_LOCAL_RING`` — flash-inside-ring is the
    long-per-device-sequence composition)."""
    if not cfg.causal:
        return False
    if cfg.attn_impl == "dense" and spec.seq > 1:
        return False
    if cfg.n_heads % spec.model:
        return False  # manual core shards heads over 'model'
    if cfg.attn_impl == "ulysses" and (cfg.n_heads // spec.model) % spec.seq:
        # Ulysses re-splits local heads over 'seq' in its all-to-all; flash
        # under Ulysses needs that split exact, so auto falls back to dense.
        return False
    if cfg.attn_impl == "ring":
        if spec.seq == 1:
            # degenerate ring: one diagonal hop = full-sequence kernel,
            # same regime as the dense+flash path
            return seq_len >= FLASH_AUTO_MIN_T
        return seq_len // spec.seq >= FLASH_AUTO_MIN_T_LOCAL_RING
    return seq_len >= FLASH_AUTO_MIN_T


def validate_ulysses_kv_heads(cfg, spec: "LMMeshSpec") -> None:
    """Grouped-query Ulysses: the head/sequence all-to-all exchanges K/V at
    Hkv heads, so the model-local K/V head count must split over ``seq``.
    One check shared by the flat and pipeline step factories."""
    if (
        cfg.kv_heads != cfg.n_heads
        and (cfg.kv_heads // spec.model) % spec.seq
    ):
        raise ValueError(
            f"local K/V head count {cfg.kv_heads // spec.model} "
            f"(n_kv_heads/model) must divide by mesh seq={spec.seq} for "
            "grouped-query Ulysses (the all-to-all exchanges K/V at Hkv "
            "heads; use attn_impl='ring' otherwise)"
        )


def validate_kv_head_sharding(cfg, spec: "LMMeshSpec") -> None:
    """Grouped-query attention under tensor parallelism: every model-axis
    shard must hold whole K/V heads.  One check shared by all three TP
    entry points (flat steps, pipeline steps, decode generator) so the
    invariant is enforced consistently."""
    if spec.model > 1 and cfg.kv_heads % spec.model:
        raise ValueError(
            f"n_kv_heads {cfg.kv_heads} must divide by mesh "
            f"model={spec.model} (each shard must hold whole K/V heads)"
        )


def normalize_flash(cfg, spec: "LMMeshSpec", seq_len: int):
    """Return ``cfg`` with ``flash`` resolved to a concrete bool.

    Called at the top of every step-fn factory (flat and pipeline) so no
    downstream check ever sees the "auto" string — and so a stray string
    like ``flash='off'`` fails loudly instead of being truthy."""
    if cfg.flash == "auto":
        return dataclasses.replace(
            cfg, flash=resolve_auto_flash(cfg, spec, seq_len)
        )
    if isinstance(cfg.flash, str):
        raise ValueError(
            f"flash must be True, False, or 'auto'; got {cfg.flash!r}"
        )
    return cfg

SEQ_AXIS = "seq"
MODEL_AXIS = "model"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"


@dataclasses.dataclass(frozen=True)
class LMMeshSpec:
    """5-axis mesh for the transformer family.

    Mesh axis order is ``(data, pipe, seq, model, expert)`` — but note the
    *field* order below is ``(data, seq, model, expert, pipe)``: ``pipe``
    was added last to keep existing positional constructions valid.  Pass
    ``pipe`` by keyword."""

    data: int = 1
    seq: int = 1
    model: int = 1
    expert: int = 1
    pipe: int = 1

    @property
    def num_devices(self) -> int:
        return self.data * self.pipe * self.seq * self.model * self.expert

    @property
    def axis_names(self) -> tuple[str, ...]:
        return ("data", PIPE_AXIS, SEQ_AXIS, MODEL_AXIS, EXPERT_AXIS)


def build_lm_mesh(spec: LMMeshSpec, devices: Sequence[jax.Device] | None = None) -> Mesh:
    """``model`` innermost so TP all-reduces ride the shortest ICI hops;
    ``data`` outermost so gradient reduction can cross DCN (the same
    inner/outer split as the (data, pipe) mesh, ``parallel/mesh.py``).
    ``pipe`` sits next to ``data``: stage handoffs move one boundary
    activation per microbatch tick — tiny volume, DCN-tolerant — while
    seq/expert/model collectives stay on short ICI hops."""
    devices = list(devices if devices is not None else jax.devices())
    need = spec.num_devices
    if len(devices) < need:
        raise ValueError(f"mesh {spec} needs {need} devices, have {len(devices)}")
    grid = np.array(devices[:need]).reshape(
        spec.data, spec.pipe, spec.seq, spec.expert, spec.model
    )
    # axis order in the Mesh matches axis_names: (data, pipe, seq, model,
    # expert); physically, model varies fastest, then expert, then seq,
    # then pipe, then data.
    return Mesh(grid.transpose(0, 1, 2, 4, 3), spec.axis_names)


def lm_logical_rules(fsdp: bool = False) -> tuple[tuple[str, str | None], ...]:
    """Logical-name → mesh-axis table for the transformer family.

    With ``fsdp=True`` the ``embed`` parameter dimension is additionally
    sharded over ``data`` (ZeRO-3-style: params/optimizer state live sharded;
    XLA all-gathers them per layer in forward/backward and reduce-scatters
    the gradients — absent from the reference, whose DDP keeps full replicas,
    SURVEY.md §2.3).
    """
    return (
        # activations.  ``batch`` shards over data AND expert: outside the
        # MoE layers the expert axis acts as extra data parallelism —
        # without it every non-MoE op (attention, norms, the loss edge)
        # would run REPLICATED on each expert shard, an ep-fold compute
        # duplication.  Inside ``MoeMlp`` the dispatch resharding batch
        # (data, expert) -> expert-sharded slots is the GShard all-to-all
        # (GSPMD inserts it; ``moe_ep='alltoall'`` issues it manually).
        ("batch", ("data", EXPERT_AXIS)),
        # batch sharded over data only — the expert-sharded dispatch
        # tensors inside the MoE layer use this for their token dim (the
        # expert axis already shards their expert dim; one mesh axis
        # cannot shard two dims of the same array)
        ("moe_batch", "data"),
        ("act_seq", SEQ_AXIS),
        ("act_embed", None),
        ("act_heads", MODEL_AXIS),
        ("act_mlp", MODEL_AXIS),
        ("act_vocab", MODEL_AXIS),
        ("act_expert", EXPERT_AXIS),
        # parameters
        ("embed", "data" if fsdp else None),
        ("vocab", MODEL_AXIS),
        ("heads", MODEL_AXIS),
        ("head_dim", None),
        ("mlp", MODEL_AXIS),
        ("expert", EXPERT_AXIS),
        ("norm", None),
    )
