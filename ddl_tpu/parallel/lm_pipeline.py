"""Pipeline parallelism for the transformer LM family.

The CNN path implements GPipe fully manually over a ``(data, pipe)`` mesh
(``parallel/pipeline.py`` — every collective hand-placed inside one
``shard_map``).  The transformer family instead expresses TP / SP / EP /
FSDP as *logical-axis rules* resolved by XLA's SPMD partitioner
(``parallel/sharding.py``), and this module adds the pipeline axis without
giving that up: the GPipe clock loop runs inside a **partial-manual**
``jax.shard_map`` that is manual over ``pipe`` only (``axis_names={'pipe'}``)
— stage handoffs are explicit ``lax.ppermute`` hops, while everything inside
a stage (batch over ``data``, sequence over ``seq``, heads/MLP over
``model``, experts over ``expert``, FSDP parameter sharding) stays in auto
mode and is partitioned by GSPMD exactly as in the non-pipelined path.

This is the composition the reference builds by hand out of NCCL subgroups
plus a DDP wrapper per pipeline stage (``ddp_n_pp.py:139-155``), extended to
the axes its design cannot express, with no subgroup bookkeeping at all.

Design (scan-over-ticks, stage-stacked params):

* the ``n_layers`` decoder blocks are split into ``pipe`` equal stages;
  per-stage block params are **stacked** on a leading stage axis and sharded
  ``P('pipe', ...)`` — each device holds only its own stage's parameters and
  optimizer state (unlike the CNN pipeline, which replicates the full tuple
  and switches on stage index).  Gradients and Adam state inherit the same
  sharding, so pipeline parallelism here also shards memory.
* embedding and LM head run *outside* the manual region in plain GSPMD land
  (they are cheap next to the block stack; MaxText's pipeline makes the same
  cut).  Their gradients arrive through the shard_map transpose: the
  embedded microbatch array enters replicated-over-pipe, so its cotangent is
  the pipe-psum of per-device cotangents — only stage 0 contributes.
* the GPipe schedule is a ``lax.scan`` over ``T = M + P - 1`` clock ticks.
  Every device runs its stage every tick (the off-schedule ticks are the
  GPipe bubble); there is no ``lax.switch`` because stages are uniform.
  Stage 0 reads microbatch ``t`` from the embedded input; others read the
  ``ppermute``'d boundary buffer.  The last stage's outputs accumulate into
  a per-microbatch buffer; off-schedule writes land on clamped indices that
  later valid writes overwrite, so no masking is needed on the data path.
* the backward schedule is autodiff through the scan: each ``ppermute``
  transposes into the reverse hop and the ticks replay backwards — the same
  property the CNN pipeline exploits (``parallel/pipeline.py``).  The
  hand-written alternatives interleave forward and backward in one scan:
  ``make_blocks_pipeline_1f1b`` (joint per-tick ``jax.vjp``) and
  ``make_blocks_pipeline_zb`` (zero-bubble: the vjp split into an
  activation-cotangent B pass on the critical path and a weight-gradient
  W pass deferred through a per-stage queue into the cooldown ticks).
* per-stage MoE aux losses leave the manual region as a ``P('pipe')``-sharded
  ``(pipe,)`` vector and are summed outside, keeping loss reductions out of
  the differentiated manual region (psum-under-grad transposes into a psum
  and scales cotangents — the trap documented in ``train/steps.py``).

Sequence parallelism composes through **nested** partial-manual shard_maps:
the ring / Ulysses attention cores become inner ``shard_map``s that inherit
the context mesh (no ``mesh=`` argument) and are manual over ``seq`` only —
their ``ppermute`` / ``all_to_all`` collectives run over the ``seq`` axis
while batch and heads stay auto-partitioned over ``data``/``model`` by
GSPMD, inside the outer manual-over-``pipe`` region.  ``flash=True``
composes the same way but needs the nested region *fully* manual over
(data, seq, model): GSPMD cannot auto-partition a Pallas custom call, so
the kernel instead runs on fully-local operands — the non-pipelined path's
manual attention region, minus ``pipe``.  ``n_layers`` must divide evenly
into ``pipe`` stages and the batch into ``num_microbatches * data`` shards.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddl_tpu.models.transformer import (
    LMConfig,
    TransformerLM,
    apply_final_norm_and_head,
    make_embed,
    remat_block,
)
from ddl_tpu.ops.losses import onehot_cross_entropy_mean
from ddl_tpu.ops.quant import head_kernel
from ddl_tpu.parallel.buffers import masked_slice_update, masked_slot_update
from ddl_tpu.parallel.sharding import (
    PIPE_AXIS,
    LMMeshSpec,
    build_lm_mesh,
    lm_logical_rules,
    normalize_flash,
    validate_kv_head_sharding,
    validate_ulysses_kv_heads,
)
from ddl_tpu.train.lm_steps import (
    LMStepFns,
    LMTrainState,
    _token_ce,
    chunked_ce_loss,
    dropout_step_key,
    finalize_step_fns,
)

__all__ = [
    "make_lm_pipeline_step_fns",
    "make_blocks_pipeline",
    "make_blocks_pipeline_1f1b",
    "make_blocks_pipeline_interleaved",
    "make_blocks_pipeline_zb",
    "blocks_pipeline_api",
    "split_lm_params",
    "merge_lm_params",
    "convert_lm_state",
    "abstract_lm_state",
    "saved_pipe_stages",
    "saved_virtual_stages",
]


def _mb_stage_key(step_key, mb_idx, s):
    """Dropout key for one (microbatch, stage) — the single fold chain both
    schedules share.  GPipe-vs-1F1B mask equality (and hence their gradient
    parity with dropout on, ``tests/test_dropout.py``) requires this exact
    derivation at every call site; never fork it per schedule."""
    return jax.random.fold_in(jax.random.fold_in(step_key, mb_idx), s)


def _make_stage_fn(block_mod: nn.Module, dropout: bool = False):
    """Stage forward: scan ``block_mod`` over the stage's stacked layer
    params.  Returns ``(y, aux)`` with ``aux`` the f32 sum of the stage's
    per-layer aux losses (MoE load balancing).

    With ``dropout=True`` the returned ``stage_fn(stage_blocks, x, key)``
    takes a per-(microbatch, stage) base key and folds the layer index in
    per scan step — the mask is a pure function of that key, so every
    recomputation of the same microbatch's forward (GPipe's autodiff
    replay, 1F1B's backward-tick vjp) reproduces it exactly."""
    if not dropout:

        def stage_fn(stage_blocks, x):
            def layer(carry, p):
                # full positional signature (x, kv_cache, offset,
                # deterministic): nn.remat's static_argnums for
                # `deterministic` indexes positional args
                y, aux = block_mod.apply({"params": p}, carry, None, None, True)
                return y, aux

            y, auxs = lax.scan(layer, x, stage_blocks)
            return y, auxs.astype(jnp.float32).sum()

        return stage_fn

    def stage_fn(stage_blocks, x, key):
        lps = jax.tree.leaves(stage_blocks)[0].shape[0]

        def layer(carry, xs):
            p, i = xs
            # deterministic rides positionally (arg 4) so nn.remat's
            # static_argnums sees it as a Python bool, not a tracer
            y, aux = block_mod.apply(
                {"params": p},
                carry,
                None,
                None,
                False,
                rngs={"dropout": jax.random.fold_in(key, i)},
            )
            return y, aux

        y, auxs = lax.scan(layer, x, (stage_blocks, jnp.arange(lps)))
        return y, auxs.astype(jnp.float32).sum()

    return stage_fn


def make_blocks_pipeline(
    mesh: Mesh,
    block_mod: nn.Module,
    *,
    n_stages: int,
    num_microbatches: int,
    mb: int,
    d_model: int,
    compute_dtype,
    dropout: bool = False,
):
    """The GPipe clock loop over a stack of uniform decoder/encoder blocks,
    as a partial-manual shard_map (manual over ``pipe`` only) — shared by
    the LM (``make_lm_pipeline_step_fns``) and ViT
    (``train/vit_steps.py``) pipelines.

    Returns ``pipeline(blocks_stacked, x_mb)`` where ``blocks_stacked`` is
    the ``(pipe, layers_per_stage, ...)`` param stack sharded
    ``P('pipe', ...)`` and ``x_mb`` is ``(M, mb, T, d_model)`` microbatched
    activations; yields ``(acc, aux_vec)`` with ``acc`` the last stage's
    per-microbatch outputs (callers slice ``[-1]``) and ``aux_vec`` the
    ``(pipe,)`` per-stage aux-loss vector.  See the module docstring for
    the schedule design.

    With ``dropout=True`` the callable takes a trailing per-step base key
    (``pipeline(blocks_stacked, x_mb, step_key)``) and each (microbatch,
    stage, layer) gets a deterministic mask folded from it (bubble-tick
    draws land on clamped microbatch indices whose outputs are overwritten
    or never read, so they are harmless).
    """
    M = num_microbatches
    d = d_model
    stage_fn = _make_stage_fn(block_mod, dropout)

    def pipeline_body(blocks_stacked, x_mb, *step_key):
        stage_blocks = jax.tree.map(lambda a: a[0], blocks_stacked)
        s = lax.axis_index(PIPE_AXIS)
        t_len = x_mb.shape[2]
        buf0 = jnp.zeros((mb, t_len, d), compute_dtype)
        acc0 = jnp.zeros((M, mb, t_len, d), compute_dtype)

        def tick(carry, t):
            buf, acc, aux = carry
            mb_idx = jnp.clip(t - s, 0, M - 1)
            x_first = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            x_in = jnp.where(s == 0, x_first, buf)
            if dropout:
                key = _mb_stage_key(step_key[0], mb_idx, s)
                out, aux_t = stage_fn(stage_blocks, x_in, key)
            else:
                out, aux_t = stage_fn(stage_blocks, x_in)
            valid = (t >= s) & (t - s < M)
            aux = aux + jnp.where(valid, aux_t, 0.0)
            # Off-schedule writes land on clamped indices; the valid write
            # for microbatch i happens at tick P-1+i, after any clamped
            # garbage, so the final buffer needs no masking (and only the
            # last pipe coordinate's buffer is ever read).
            acc = lax.dynamic_update_index_in_dim(
                acc, out, jnp.clip(t - (n_stages - 1), 0, M - 1), 0
            )
            buf = lax.ppermute(
                out, PIPE_AXIS, [(i, i + 1) for i in range(n_stages - 1)]
            )
            return (buf, acc, aux), None

        init = (buf0, acc0, jnp.zeros((), jnp.float32))
        (_, acc, aux), _ = lax.scan(tick, init, jnp.arange(M + n_stages - 1))
        return acc[None], aux[None]

    return jax.shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=(P(PIPE_AXIS), P()) + ((P(),) if dropout else ()),
        out_specs=(P(PIPE_AXIS), P(PIPE_AXIS)),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )


def make_blocks_pipeline_interleaved(
    mesh: Mesh,
    block_mod: nn.Module,
    *,
    n_stages: int,
    virtual: int,
    num_microbatches: int,
    mb: int,
    d_model: int,
    compute_dtype,
    dropout: bool = False,
):
    """Interleaved (virtual-stage) pipeline clock loop: device ``s`` holds
    ``V = virtual`` non-contiguous layer chunks — global stage
    ``sigma = c*P + s`` — so each microbatch laps the device ring V times
    (Megatron-LM's interleaved schedule).  The pipeline fill/drain bubble
    shrinks by V: the schedule closes in ``M*V + P - 1`` ticks of
    1/V-stage work vs GPipe's ``M + P - 1`` ticks of full-stage work —
    same total compute, bubble fraction (P-1)/(MV+P-1) vs (P-1)/(M+P-1) —
    at the cost of V-1 extra wrap hops per microbatch.

    Schedule: microbatches advance in groups of P (``M % P == 0``
    required).  Within group ``g``, device ``s`` runs chunk ``c`` on
    group-microbatch ``r`` at tick ``t = g*V*P + c*P + r + s`` — unit
    ``(m, sigma)`` depends on ``(m, sigma-1)`` finishing one tick earlier
    on device ``s-1`` (or on device P-1's previous chunk via the wrap hop
    P-1 -> 0), and consecutive groups tile with no inter-group bubble.
    The boundary ``ppermute`` is the full ring including the wrap; the
    backward schedule is autodiff through the scan, as in
    ``make_blocks_pipeline``.

    Interface matches ``make_blocks_pipeline`` with ``blocks_stacked``
    shaped ``(P, V, layers_per_chunk, ...)`` sharded ``P('pipe', ...)``;
    the caller slices ``acc[-1]`` for the last global stage's outputs.
    """
    P_, V, M = n_stages, virtual, num_microbatches
    d = d_model
    stage_fn = _make_stage_fn(block_mod, dropout)

    def pipeline_body(blocks_stacked, x_mb, *step_key):
        local_chunks = jax.tree.map(lambda a: a[0], blocks_stacked)  # (V,lps,..)
        s = lax.axis_index(PIPE_AXIS)
        t_len = x_mb.shape[2]
        VP = V * P_
        buf0 = jnp.zeros((mb, t_len, d), compute_dtype)
        acc0 = jnp.zeros((M, mb, t_len, d), compute_dtype)

        def tick(carry, t):
            buf, acc, aux = carry
            rel = t - s
            g = jnp.clip(rel // VP, 0, M // P_ - 1)
            u = jnp.clip(rel - g * VP, 0, VP - 1)
            c = u // P_
            r = u - c * P_
            m = jnp.clip(g * P_ + r, 0, M - 1)
            valid = (rel >= 0) & (rel < M * V)
            chunk = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, c, 0, keepdims=False),
                local_chunks,
            )
            x_first = lax.dynamic_index_in_dim(x_mb, m, 0, keepdims=False)
            x_in = jnp.where((s == 0) & (c == 0), x_first, buf)
            if dropout:
                key = _mb_stage_key(step_key[0], m, c * P_ + s)
                out, aux_t = stage_fn(chunk, x_in, key)
            else:
                out, aux_t = stage_fn(chunk, x_in)
            aux = aux + jnp.where(valid, aux_t, 0.0)
            # Last-global-stage output lands at acc[m].  As in the plain
            # GPipe loop, no masking: within a group every chunk writes the
            # same m range in increasing-u order, so chunk V-1's valid
            # write is last; later groups only touch later m; only the
            # last pipe coordinate's acc is ever read.
            acc = lax.dynamic_update_index_in_dim(acc, out, m, 0)
            # full ring: the wrap P-1 -> 0 carries the chunk c -> c+1
            # boundary back to device 0
            buf = lax.ppermute(
                out, PIPE_AXIS, [(i, (i + 1) % P_) for i in range(P_)]
            )
            return (buf, acc, aux), None

        init = (buf0, acc0, jnp.zeros((), jnp.float32))
        (_, acc, aux), _ = lax.scan(
            tick, init, jnp.arange(M * V + P_ - 1)
        )
        return acc[None], aux[None]

    return jax.shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=(P(PIPE_AXIS), P()) + ((P(),) if dropout else ()),
        out_specs=(P(PIPE_AXIS), P(PIPE_AXIS)),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )


def blocks_pipeline_api(virtual: int):
    """(make_pipe, wrap_blocks, blocks_of) for a virtual-stage count — the
    single source both step builders (LM and ViT) use to pick the clock
    loop and apply/strip the self-describing ``{"interleaved": ...}``
    layout marker, so the three pieces cannot drift apart."""
    if virtual > 1:
        from functools import partial

        return (
            partial(make_blocks_pipeline_interleaved, virtual=virtual),
            lambda blocks: {"interleaved": blocks},
            lambda blocks: blocks["interleaved"],
        )
    return make_blocks_pipeline, (lambda b: b), (lambda b: b)


def make_blocks_pipeline_1f1b(
    mesh: Mesh,
    block_mod: nn.Module,
    head_loss,
    *,
    n_stages: int,
    num_microbatches: int,
    mb: int,
    d_model: int,
    compute_dtype,
    aux_cotangent: float,
    zero_metrics,
    dropout: bool = False,
    virtual: int = 1,
):
    """One-forward-one-backward interleaved schedule over the uniform block
    stack — the forward AND backward pipeline in a single scan, with the loss
    fused into the last stage (the piece GPipe-by-autodiff keeps outside).

    Because forward and backward interleave, this cannot be expressed as
    autodiff through the forward scan (that *is* GPipe); the backward is
    hand-written with per-tick ``jax.vjp``, the same construction as the CNN
    pipeline's 1F1B (``parallel/pipeline.py::per_device_train_1f1b``), lifted
    to the partial-manual region: everything inside a stage stays GSPMD-auto
    over data/seq/model/expert while ticks and hops are manual over ``pipe``.

    Schedule: at tick ``t`` the device at pipe coordinate ``s`` runs the
    forward of microbatch ``t - s`` and the backward of microbatch
    ``t - (2(P-1) - s)``; on the last stage these coincide, and the loss
    epilogue supplies the output cotangent in place of the (absent) next
    stage's reverse hop.  Activations ride a forward ``ppermute``,
    cotangents the reverse one; stage inputs wait for their backward in a
    ring buffer of depth ``min(2(P-1)+1, M)`` — O(P), independent of the
    microbatch count, vs the GPipe scan's O(M) saved per-tick stage inputs —
    and the schedule closes in ``M + 2(P-1)`` ticks vs autodiff-GPipe's
    ``2(M + P - 1)``.  The O(P) bound covers the *stage-activation*
    residency only: the embedded input ``x_mb`` and its cotangent
    accumulator ``dx_acc`` are full-batch ``(M, mb, T, d)`` buffers under
    either schedule — they are the embed/head edge, not pipeline state.

    ``head_loss(head_params, y, tgt) -> (loss_contribution, metrics)`` is the
    caller's last-stage epilogue (e.g. final-norm + vocab projection + CE/M
    for the LM); ``metrics`` must match ``zero_metrics`` in structure and is
    accumulated over microbatches.  ``aux_cotangent`` is the weight each
    stage's summed aux loss carries in the total loss (MoE balancing:
    ``moe_aux_weight / M``).

    Returns ``pipeline(blocks_stacked, head_params, x_mb, tgt_mb) ->
    (d_blocks, d_head, dx_mb, metrics, aux_sum)`` where ``d_blocks`` is
    ``P('pipe')``-stacked like its primal, and ``d_head``/``dx_mb``/
    ``metrics``/``aux_sum`` are pipe-replicated (``dx_mb`` is the cotangent
    of the embedded input — the caller backpropagates it through the
    embedding with its own ``jax.vjp``, closing the gradient path that
    autodiff's shard_map transpose handles on the GPipe path).  Gradients are
    numerically equivalent to the GPipe schedule (tested to 1e-5 by
    ``tests/test_lm_pipeline.py``): same math and microbatch order, though
    the last-stage CE uses a different formulation.

    ``virtual > 1`` runs the *interleaved* 1F1B (Megatron's combined
    schedule): device ``s`` holds ``V`` non-contiguous chunks (global stage
    ``sigma = c*P + s``, same placement as
    ``make_blocks_pipeline_interleaved``), the forward follows that
    schedule's group-of-P timing ``t_f = g*V*P + c*P + r + s``, and the
    backward mirrors it at ``t_b = (VP-1) + g*V*P + (V-1-c)*P + r +
    (P-1-s)`` — for ``V = 1`` these reduce exactly to the timetable above
    (``t_b = m + 2(P-1) - s``).  The schedule closes in ``MV + VP + P - 2``
    ticks of 1/V-stage fwd+bwd work vs autodiff-interleaved-GPipe's
    ``2(MV + P - 1)``, and stage-input residency is ``V * min(2VP, M)``
    microbatch buffers vs the GPipe scan's ``M * V``.  Requires
    ``M % P == 0`` (microbatches advance in groups of P, like the
    interleaved forward); ``blocks_stacked`` leaves are
    ``(P, V, layers_per_chunk, ...)``.
    """
    P_, V, M = n_stages, virtual, num_microbatches
    last = P_ - 1
    VP = V * P_
    d = d_model
    raw_stage_fn = _make_stage_fn(block_mod, dropout)
    if V == 1:
        # A microbatch's stage input is written at tick f+s and consumed by
        # its backward at tick f+2(P-1)-s: lifetime 2(P-1-s) ticks, so depth
        # 2(P-1)+1 (stage 0's worst case) always suffices; M slots suffice
        # when M is smaller because at most M microbatches are in flight.
        depth = min(2 * last + 1, M)
        n_ticks = M + 2 * last
        # forward handoff crosses stage boundaries only; no wrap traffic
        fwd_ring = [(i, i + 1) for i in range(last)]
        bwd_ring = [(i + 1, i) for i in range(last)]
    else:
        # interleaved: worst-case input lifetime is 2VP-2 ticks (chunk 0,
        # device 0); consecutive microbatches of one chunk are >= 1 tick
        # apart, so min(2VP, M) slots (both multiples of P) suffice.
        depth = min(2 * VP, M)
        n_ticks = M * V + VP + P_ - 2
        # full rings: the wrap carries chunk boundaries (c -> c+1 forward
        # on P-1 -> 0, and the reverse on 0 -> P-1)
        fwd_ring = [(i, (i + 1) % P_) for i in range(P_)]
        bwd_ring = [((i + 1) % P_, i) for i in range(P_)]

    def pipeline_body(blocks_stacked, head_params, x_mb, tgt_mb, *step_key):
        local_blocks = jax.tree.map(lambda a: a[0], blocks_stacked)
        s = lax.axis_index(PIPE_AXIS)
        t_len = x_mb.shape[2]

        def tick(carry, t):
            fwd_buf, bwd_buf, resid, dx_acc, g_blocks, g_head, met, aux = carry
            if V == 1:
                c_f = c_b = 0
                f_idx = jnp.clip(t - s, 0, M - 1)
                fwd_valid = (t >= s) & (t - s < M)
                off = 2 * last - s
                b_idx = jnp.clip(t - off, 0, M - 1)
                bwd_valid = (t >= off) & (t - off < M)
                chunk_f = chunk_b = local_blocks
            else:
                rel_f = t - s
                g_f = jnp.clip(rel_f // VP, 0, M // P_ - 1)
                u_f = jnp.clip(rel_f - g_f * VP, 0, VP - 1)
                c_f = u_f // P_
                f_idx = jnp.clip(g_f * P_ + (u_f - c_f * P_), 0, M - 1)
                fwd_valid = (rel_f >= 0) & (rel_f < M * V)
                rel_b = t - (VP - 1) - (last - s)
                g_b = jnp.clip(rel_b // VP, 0, M // P_ - 1)
                u_b = jnp.clip(rel_b - g_b * VP, 0, VP - 1)
                cp = u_b // P_
                c_b = (V - 1) - cp
                b_idx = jnp.clip(g_b * P_ + (u_b - cp * P_), 0, M - 1)
                bwd_valid = (rel_b >= 0) & (rel_b < M * V)
                chunk_f = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(
                        a, c_f, 0, keepdims=False
                    ),
                    local_blocks,
                )
                chunk_b = jax.tree.map(
                    lambda a: lax.dynamic_index_in_dim(
                        a, c_b, 0, keepdims=False
                    ),
                    local_blocks,
                )

            if dropout:
                # the same (microbatch, global stage) key on the forward
                # tick and on the backward tick's recompute — identical
                # masks, exact gradients (matches interleaved GPipe keying)
                fwd_stage_fn = lambda blocks, x: raw_stage_fn(
                    blocks, x, _mb_stage_key(step_key[0], f_idx, c_f * P_ + s)
                )
                bwd_stage_fn = lambda blocks, x: raw_stage_fn(
                    blocks, x, _mb_stage_key(step_key[0], b_idx, c_b * P_ + s)
                )
            else:
                fwd_stage_fn = bwd_stage_fn = raw_stage_fn

            x_first = lax.dynamic_index_in_dim(x_mb, f_idx, 0, keepdims=False)
            x_in = jnp.where((s == 0) & (c_f == 0), x_first, fwd_buf)
            if V == 1:
                resid = masked_slot_update(
                    resid, x_in, f_idx % depth, fwd_valid
                )
                x_b = lax.dynamic_index_in_dim(
                    resid, b_idx % depth, 0, keepdims=False
                )
            else:
                resid = masked_slice_update(
                    resid,
                    x_in[None, None],
                    (c_f, f_idx % depth, 0, 0, 0),
                    fwd_valid,
                )
                x_b = lax.dynamic_slice(
                    resid,
                    (c_b, b_idx % depth, 0, 0, 0),
                    (1, 1, mb, t_len, d),
                )[0, 0]
            tgt_b = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, b_idx, 0, keepdims=False),
                tgt_mb,
            )

            # Every collective-bearing computation runs UNCONDITIONALLY on
            # every device: the forward-for-handoff and the stage vjp both
            # contain the nested seq cores' ppermute / all_to_all (and the
            # MoE dispatch), which XLA compiles as single whole-mesh
            # channel ops — inside a branch that only some pipe coordinates
            # take, the other coordinates never join the rendezvous and the
            # program deadlocks (observed).  Only the head epilogue sits in
            # a cond: its collectives (TP/data/seq all-reduces from GSPMD)
            # are per-group ops whose groups lie within one pipe
            # coordinate, so every participant agrees on the branch.
            out, _ = fwd_stage_fn(chunk_f, x_in)
            (y_b, aux_b), stage_vjp = jax.vjp(bwd_stage_fn, chunk_b, x_b)

            def last_branch(y):
                # the loss supplies the output cotangent: vjp through
                # head_loss in place of the (absent) next stage's hop
                _, head_vjp, m = jax.vjp(
                    lambda hp, yy: head_loss(hp, yy, tgt_b),
                    head_params,
                    y,
                    has_aux=True,
                )
                dh, g_y = head_vjp(jnp.ones((), jnp.float32))
                return dh, g_y.astype(y.dtype), m

            def mid_branch(y):
                # cotangent arrived from stage s+1 on the reverse hop
                dh = jax.tree.map(jnp.zeros_like, head_params)
                return dh, bwd_buf.astype(y.dtype), zero_metrics

            # head epilogue on the last GLOBAL stage (device P-1, chunk V-1)
            dh, g_y, m = lax.cond(
                (s == last) & (c_b == V - 1), last_branch, mid_branch, y_b
            )
            db, dx = stage_vjp(
                (g_y, jnp.asarray(aux_cotangent, jnp.float32))
            )

            def acc(old, new):
                return jax.tree.map(
                    lambda o, n: o + jnp.where(bwd_valid, n, jnp.zeros_like(n)),
                    old,
                    new,
                )

            if V == 1:
                g_blocks = acc(g_blocks, db)
            else:
                # scatter-accumulate this tick's chunk gradient at c_b
                g_blocks = jax.tree.map(
                    lambda g, n: lax.dynamic_update_index_in_dim(
                        g,
                        lax.dynamic_index_in_dim(g, c_b, 0, keepdims=False)
                        + jnp.where(bwd_valid, n, jnp.zeros_like(n)),
                        c_b,
                        0,
                    ),
                    g_blocks,
                    db,
                )
            g_head, met = acc(g_head, dh), acc(met, m)
            aux = aux + jnp.where(bwd_valid, aux_b, 0.0)
            dx_acc = masked_slot_update(
                dx_acc, dx, b_idx, bwd_valid & (s == 0) & (c_b == 0)
            )
            fwd_buf = lax.ppermute(
                out.astype(compute_dtype), PIPE_AXIS, fwd_ring
            )
            bwd_buf = lax.ppermute(
                dx.astype(compute_dtype), PIPE_AXIS, bwd_ring
            )
            return (fwd_buf, bwd_buf, resid, dx_acc, g_blocks, g_head, met, aux), None

        buf0 = jnp.zeros((mb, t_len, d), compute_dtype)
        resid_shape = (
            (depth, mb, t_len, d) if V == 1 else (V, depth, mb, t_len, d)
        )
        init = (
            buf0,
            buf0,
            jnp.zeros(resid_shape, compute_dtype),
            jnp.zeros((M, mb, t_len, d), compute_dtype),
            jax.tree.map(jnp.zeros_like, local_blocks),
            jax.tree.map(jnp.zeros_like, head_params),
            zero_metrics,
            jnp.zeros((), jnp.float32),
        )
        (_, _, _, dx_acc, g_blocks, g_head, met, aux), _ = lax.scan(
            tick, init, jnp.arange(n_ticks)
        )
        # stage grads stay pipe-stacked like their primal; everything else
        # lives on one coordinate (head/metrics on the last, dx on the
        # first) and the psum broadcasts it pipe-replicated
        g_blocks = jax.tree.map(lambda g: g[None], g_blocks)
        g_head = jax.tree.map(lambda g: lax.psum(g, PIPE_AXIS), g_head)
        dx_acc = lax.psum(dx_acc, PIPE_AXIS)
        met = jax.tree.map(lambda x: lax.psum(x, PIPE_AXIS), met)
        aux = lax.psum(aux, PIPE_AXIS)
        return g_blocks, g_head, dx_acc, met, aux

    return jax.shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=(P(PIPE_AXIS), P(), P(), P()) + ((P(),) if dropout else ()),
        out_specs=(P(PIPE_AXIS), P(), P(), P(), P()),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )


def make_blocks_pipeline_zb(
    mesh: Mesh,
    block_mod: nn.Module,
    head_loss,
    *,
    n_stages: int,
    num_microbatches: int,
    mb: int,
    d_model: int,
    compute_dtype,
    aux_cotangent: float,
    zero_metrics,
    dropout: bool = False,
):
    """Zero-bubble (ZB-H1-style) schedule: the 1F1B clock loop with the
    full per-tick backward split into its two halves — the activation
    cotangent (**B**) stays on the critical path, the weight gradient
    (**W**) is deferred into a per-stage queue and drained during the
    ticks the stage would otherwise idle.

    The 1F1B tick runs one joint ``jax.vjp`` per tick: cotangents for
    the stage *input* (which the reverse hop needs THIS tick — the next
    stage's backward blocks on it) and for the stage *weights* (which
    nothing consumes until the optimizer update after the scan) are
    computed together, so the weight half of the backward sits on the
    inter-stage critical path for no reason.  Here the B pass is a
    ``jax.vjp`` w.r.t. the stage input only (weights closed over) and
    the W pass a ``jax.vjp`` w.r.t. the weights only (input closed
    over), applied to the SAME output cotangent — by linearity of the
    vjp in which inputs are held fixed, the two halves are exactly the
    joint vjp's two components, so gradients match GPipe/1F1B to float
    tolerance (``tests/test_lm_pipeline.py`` asserts <= 1e-6).

    Schedule: F and B keep the 1F1B timetable — at tick ``t`` stage
    ``s`` runs the forward of microbatch ``t - s`` and the B pass of
    microbatch ``t - (2(P-1) - s)`` — and the scan still closes in
    ``M + 2(P-1)`` ticks.  Each B tick enqueues its W work item (the
    stage input, the output cotangent, and the microbatch index for the
    dropout-key refold) into a ring queue of ``min(P-1, M) + 1`` slots;
    one item drains per tick when the queue is over its deferral
    capacity or the stage's B schedule has gone quiet.  The capacity is
    the stage's tail-idle tick count: stage ``s`` finishes its B passes
    ``s`` ticks before the scan ends (its last B is at tick
    ``M - 1 + 2(P-1) - s``), so deferring up to ``s`` W passes lands
    them exactly in the cooldown ticks where 1F1B computes nothing —
    the ZB-H1 move of filling the drain bubble with weight-gradient
    work.  Every queued item is drained by the final tick (steady state
    is one-in-one-out above capacity; the tail holds at most ``s``
    items and has ``s`` ticks), so no microbatch's weight gradient is
    dropped, and items drain oldest-first — microbatch order, the same
    accumulation order as 1F1B.

    On the uniform-tick SPMD realisation every device still executes
    every slot every tick, so the win is *modeled*, not wall-clock on a
    sim mesh: ``obs/schedule_model.py`` quantifies it (zb idles half of
    1F1B's stage-time at t_F = t_B = t_W), ``obs trace --step`` renders
    the lanes, and the PERF.md round-19 protocol banks the chip number.
    Memory: the queue adds ``2 * (min(P-1, M) + 1)`` microbatch-sized
    buffers on top of 1F1B's ``min(2(P-1)+1, M)``-deep stage-input ring
    — still O(P), independent of M.

    Dropout masks are a pure function of ``_mb_stage_key(step_key,
    microbatch, stage)``; the W pass refolds the key from the queued
    microbatch index, so the forward-for-handoff, the B-tick recompute,
    and the deferred W-tick recompute all draw the identical mask —
    schedule-invariant gradients, same fold chain as GPipe/1F1B.

    Interface matches ``make_blocks_pipeline_1f1b`` with ``virtual=1``
    (the B/W split is single-chunk; virtual stages compose with 1F1B).
    """
    P_, M = n_stages, num_microbatches
    last = P_ - 1
    d = d_model
    raw_stage_fn = _make_stage_fn(block_mod, dropout)
    depth = min(2 * last + 1, M)
    n_ticks = M + 2 * last
    # W queue slots: the in-flight count peaks at cap_s + 1 = s + 1
    # (enqueue lands before the over-capacity drain), bounded by M + 1
    # when M is smaller than the deepest capacity
    K = min(last, M) + 1
    fwd_ring = [(i, i + 1) for i in range(last)]
    bwd_ring = [(i + 1, i) for i in range(last)]

    def pipeline_body(blocks_stacked, head_params, x_mb, tgt_mb, *step_key):
        local_blocks = jax.tree.map(lambda a: a[0], blocks_stacked)
        s = lax.axis_index(PIPE_AXIS)
        t_len = x_mb.shape[2]
        cap = jnp.minimum(s, M)  # deferral depth = stage s's tail-idle ticks

        def tick(carry, t):
            (fwd_buf, bwd_buf, resid, dx_acc, g_blocks, g_head, met, aux,
             qx, qg, qm, q_tail, q_len) = carry
            f_idx = jnp.clip(t - s, 0, M - 1)
            fwd_valid = (t >= s) & (t - s < M)
            off = 2 * last - s
            b_idx = jnp.clip(t - off, 0, M - 1)
            bwd_valid = (t >= off) & (t - off < M)

            if dropout:
                fwd_stage_fn = lambda blocks, x: raw_stage_fn(
                    blocks, x, _mb_stage_key(step_key[0], f_idx, s)
                )
                bwd_stage_fn = lambda blocks, x: raw_stage_fn(
                    blocks, x, _mb_stage_key(step_key[0], b_idx, s)
                )
            else:
                fwd_stage_fn = bwd_stage_fn = raw_stage_fn

            x_first = lax.dynamic_index_in_dim(x_mb, f_idx, 0, keepdims=False)
            x_in = jnp.where(s == 0, x_first, fwd_buf)
            resid = masked_slot_update(resid, x_in, f_idx % depth, fwd_valid)
            x_b = lax.dynamic_index_in_dim(
                resid, b_idx % depth, 0, keepdims=False
            )
            tgt_b = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, b_idx, 0, keepdims=False),
                tgt_mb,
            )

            # As in the 1F1B loop, every collective-bearing computation
            # runs unconditionally on every device (nested seq cores /
            # MoE dispatch compile to whole-mesh channel ops); only the
            # head epilogue sits in a cond.
            out, _ = fwd_stage_fn(local_blocks, x_in)
            # B: input-cotangent-only vjp — the stage params are closed
            # over, so this computes exactly the dx half of 1F1B's
            # joint vjp and nothing of the weight half
            (y_b, aux_b), b_vjp = jax.vjp(
                lambda x: bwd_stage_fn(local_blocks, x), x_b
            )

            def last_branch(y):
                _, head_vjp, m = jax.vjp(
                    lambda hp, yy: head_loss(hp, yy, tgt_b),
                    head_params,
                    y,
                    has_aux=True,
                )
                dh, g_y = head_vjp(jnp.ones((), jnp.float32))
                return dh, g_y.astype(y.dtype), m

            def mid_branch(y):
                dh = jax.tree.map(jnp.zeros_like, head_params)
                return dh, bwd_buf.astype(y.dtype), zero_metrics

            dh, g_y, m = lax.cond(s == last, last_branch, mid_branch, y_b)
            (dx,) = b_vjp(
                (g_y, jnp.asarray(aux_cotangent, jnp.float32))
            )

            def acc(old, new):
                return jax.tree.map(
                    lambda o, n: o + jnp.where(bwd_valid, n, jnp.zeros_like(n)),
                    old,
                    new,
                )

            g_head, met = acc(g_head, dh), acc(met, m)
            aux = aux + jnp.where(bwd_valid, aux_b, 0.0)
            dx_acc = masked_slot_update(
                dx_acc, dx, b_idx, bwd_valid & (s == 0)
            )

            # enqueue this tick's W work: the stage input, the output
            # cotangent, and the microbatch index (dropout-key refold)
            slot = q_tail % K
            qx = masked_slot_update(qx, x_b, slot, bwd_valid)
            qg = masked_slot_update(
                qg, g_y.astype(compute_dtype), slot, bwd_valid
            )
            qm = masked_slot_update(qm, b_idx, slot, bwd_valid)
            q_tail = q_tail + bwd_valid.astype(jnp.int32)
            q_len = q_len + bwd_valid.astype(jnp.int32)

            # drain the oldest item when over the deferral capacity or
            # when the B schedule has gone quiet (the cooldown ticks)
            do_drain = (q_len > 0) & ((q_len > cap) | ~bwd_valid)
            head_slot = (q_tail - q_len) % K
            xw = lax.dynamic_index_in_dim(qx, head_slot, 0, keepdims=False)
            gw = lax.dynamic_index_in_dim(qg, head_slot, 0, keepdims=False)
            mw = lax.dynamic_index_in_dim(qm, head_slot, 0, keepdims=False)
            if dropout:
                w_stage_fn = lambda blocks, x: raw_stage_fn(
                    blocks, x, _mb_stage_key(step_key[0], mw, s)
                )
            else:
                w_stage_fn = raw_stage_fn
            # W: weight-cotangent-only vjp at the queued (input,
            # cotangent) — the dual closure of the B pass; runs
            # unconditionally (collectives), accumulated under the
            # drain mask
            (y_w, _aux_w), w_vjp = jax.vjp(
                lambda blocks: w_stage_fn(blocks, xw), local_blocks
            )
            (db,) = w_vjp(
                (gw.astype(y_w.dtype), jnp.asarray(aux_cotangent, jnp.float32))
            )
            g_blocks = jax.tree.map(
                lambda g, n: g + jnp.where(do_drain, n, jnp.zeros_like(n)),
                g_blocks,
                db,
            )
            q_len = q_len - do_drain.astype(jnp.int32)

            fwd_buf = lax.ppermute(
                out.astype(compute_dtype), PIPE_AXIS, fwd_ring
            )
            bwd_buf = lax.ppermute(
                dx.astype(compute_dtype), PIPE_AXIS, bwd_ring
            )
            return (fwd_buf, bwd_buf, resid, dx_acc, g_blocks, g_head,
                    met, aux, qx, qg, qm, q_tail, q_len), None

        buf0 = jnp.zeros((mb, t_len, d), compute_dtype)
        init = (
            buf0,
            buf0,
            jnp.zeros((depth, mb, t_len, d), compute_dtype),
            jnp.zeros((M, mb, t_len, d), compute_dtype),
            jax.tree.map(jnp.zeros_like, local_blocks),
            jax.tree.map(jnp.zeros_like, head_params),
            zero_metrics,
            jnp.zeros((), jnp.float32),
            jnp.zeros((K, mb, t_len, d), compute_dtype),
            jnp.zeros((K, mb, t_len, d), compute_dtype),
            jnp.zeros((K,), jnp.int32),
            jnp.zeros((), jnp.int32),
            jnp.zeros((), jnp.int32),
        )
        (_, _, _, dx_acc, g_blocks, g_head, met, aux, *_), _ = lax.scan(
            tick, init, jnp.arange(n_ticks)
        )
        g_blocks = jax.tree.map(lambda g: g[None], g_blocks)
        g_head = jax.tree.map(lambda g: lax.psum(g, PIPE_AXIS), g_head)
        dx_acc = lax.psum(dx_acc, PIPE_AXIS)
        met = jax.tree.map(lambda x: lax.psum(x, PIPE_AXIS), met)
        aux = lax.psum(aux, PIPE_AXIS)
        return g_blocks, g_head, dx_acc, met, aux

    return jax.shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=(P(PIPE_AXIS), P(), P(), P()) + ((P(),) if dropout else ()),
        out_specs=(P(PIPE_AXIS), P(), P(), P(), P()),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )


class _Embed(nn.Module):
    """Stage-0 prologue.  Uses ``make_embed`` — the same construction
    ``TransformerLM`` composes — so full-model checkpoints restructure 1:1
    (``split_lm_params``)."""

    cfg: LMConfig

    @nn.compact
    def __call__(self, tokens):
        x = make_embed(self.cfg)(tokens)
        return nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))


class _Head(nn.Module):
    """Last-stage epilogue: final RMSNorm + vocab projection (shared
    construction with ``TransformerLM``)."""

    cfg: LMConfig

    @nn.compact
    def __call__(self, x):
        return apply_final_norm_and_head(self.cfg, x)


class _HeadNorm(nn.Module):
    """Norm-only view of the head params: applies ``norm_f`` and leaves the
    vocab projection to the chunked head+CE fusion
    (``ops/losses.fused_chunked_ce``) — apply with the same ``head`` param
    subtree as ``_Head`` (``lm_head`` simply goes unused)."""

    cfg: LMConfig

    @nn.compact
    def __call__(self, x):
        from ddl_tpu.models.transformer import RMSNorm

        return RMSNorm(self.cfg.dtype, name="norm_f")(x)


def stack_block_params(full_params: Any, n_stages: int, virtual: int = 1):
    """Stack a param tree's ``block{i}`` subtrees into the pipeline layout —
    the unit every blocks pipeline shards ``P('pipe', ...)``.  Shared by the
    LM and ViT splits.

    ``virtual == 1``: ``(n_stages, layers_per_stage, ...)``, stage-major
    (stage p owns layers ``[p*Lps, (p+1)*Lps)``).

    ``virtual > 1`` (interleaved schedule): ``(n_stages, virtual,
    layers_per_chunk, ...)`` with the Megatron virtual-stage assignment —
    global stage ``sigma = c*n_stages + s`` lives at ``[s, c]``, so device
    ``s`` owns the *non-contiguous* layer chunks ``{c*P+s : c}`` and a
    microbatch visits every device V times."""
    layer_keys = sorted(
        (k for k in full_params if k.startswith("block")),
        key=lambda k: int(k.removeprefix("block")),
    )
    lps = len(layer_keys) // (n_stages * virtual)

    def gather(*xs):
        a = jnp.stack(xs)
        if virtual == 1:
            return a.reshape(n_stages, lps, *xs[0].shape)
        # layer ell = (c*P + s)*lps + j  ->  reshape (V, P, lps) indexes
        # [c, s, j]; transpose to the device-major (P, V, lps) layout
        a = a.reshape(virtual, n_stages, lps, *xs[0].shape)
        return a.transpose(1, 0, *range(2, a.ndim))

    return jax.tree.map(gather, *(full_params[k] for k in layer_keys))


def split_lm_params(full_params: Any, n_stages: int, virtual: int = 1) -> dict:
    """Restructure a full ``TransformerLM`` param tree into the pipeline
    layout ``{embed, blocks, head}`` (see ``stack_block_params``).  With
    ``virtual > 1`` the stack nests under ``blocks["interleaved"]`` — a
    structural marker, so a snapshot records its own virtual-stage count
    (leading dims alone cannot distinguish (P, V, lps) from (P, lps);
    parameter ranks vary)."""
    blocks = stack_block_params(full_params, n_stages, virtual)
    return {
        "embed": {"embed": full_params["embed"]},
        "blocks": {"interleaved": blocks} if virtual > 1 else blocks,
        "head": {"norm_f": full_params["norm_f"], "lm_head": full_params["lm_head"]},
    }


def merge_lm_params(pp_params: dict) -> dict:
    """Inverse of ``split_lm_params``: pipeline layout ``{embed, blocks,
    head}`` back to the flat ``TransformerLM`` tree (``block{i}`` keyed).
    The interleaved layout is self-describing (the ``"interleaved"``
    wrapper plus the stack's (P, V, lps) leading dims)."""
    blocks = pp_params["blocks"]
    full = {
        "embed": pp_params["embed"]["embed"],
        "norm_f": pp_params["head"]["norm_f"],
        "lm_head": pp_params["head"]["lm_head"],
    }
    if not _is_interleaved_blocks(blocks):
        shape_leaf = jax.tree.leaves(blocks)[0]
        n_stages, lps = shape_leaf.shape[:2]
        for p in range(n_stages):
            for j in range(lps):
                full[f"block{p * lps + j}"] = jax.tree.map(
                    lambda x: x[p, j], blocks
                )
        return full
    blocks = blocks["interleaved"]
    n_stages, virtual, lps = jax.tree.leaves(blocks)[0].shape[:3]
    for c in range(virtual):
        for s in range(n_stages):
            for j in range(lps):
                ell = (c * n_stages + s) * lps + j
                full[f"block{ell}"] = jax.tree.map(lambda x: x[s, c, j], blocks)
    return full


def _is_interleaved_blocks(blocks) -> bool:
    return isinstance(blocks, dict) and "interleaved" in blocks


def _is_pipeline_tree(x) -> bool:
    return isinstance(x, dict) and set(x) == {"embed", "blocks", "head"}


def _is_full_tree(x) -> bool:
    return isinstance(x, dict) and "lm_head" in x and "block0" in x


def _map_param_subtrees(x, convert):
    """Apply ``convert`` to every param-layout dict inside an arbitrary
    optimizer-state structure (NamedTuples / tuples / lists / dicts of
    arrays and param-shaped trees, e.g. Adam's ``mu``/``nu``).  The layout
    checks run first so a param tree is converted whole, not recursed into."""
    if _is_pipeline_tree(x) or _is_full_tree(x):
        return convert(x)
    if isinstance(x, tuple) and hasattr(x, "_fields"):  # NamedTuple state
        return type(x)(*(_map_param_subtrees(f, convert) for f in x))
    if isinstance(x, (tuple, list)):
        return type(x)(_map_param_subtrees(f, convert) for f in x)
    if isinstance(x, dict):  # e.g. multi_transform's inner_states
        return {k: _map_param_subtrees(v, convert) for k, v in x.items()}
    return x


def saved_pipe_stages(params: Any) -> int:
    """Pipe stage count a params tree was written with (1 = full layout).
    Works on real trees and on checkpoint *metadata* trees (anything whose
    leaves carry ``.shape`` — see ``checkpoint.snapshot_metadata``), so a
    resuming run can discover a snapshot's layout without flags."""
    if _is_pipeline_tree(params):
        return int(jax.tree.leaves(params["blocks"])[0].shape[0])
    if not _is_full_tree(params):
        raise ValueError(
            f"unrecognized params layout (keys: {sorted(params)[:8]}...)"
            if isinstance(params, dict)
            else f"unrecognized params layout: {type(params)}"
        )
    return 1


def saved_virtual_stages(params: Any) -> int:
    """Virtual-stage (interleaved) count a params tree was written with
    (1 = plain stage-contiguous layout).  Like ``saved_pipe_stages``, works
    on metadata trees — the interleaved layout is marked structurally by
    the ``blocks["interleaved"]`` wrapper, so a resuming run discovers it
    from the snapshot itself."""
    if _is_pipeline_tree(params) and _is_interleaved_blocks(params["blocks"]):
        return int(
            jax.tree.leaves(params["blocks"]["interleaved"])[0].shape[1]
        )
    saved_pipe_stages(params)  # layout sanity check
    return 1


def abstract_lm_state(
    cfg: LMConfig,
    tx: optax.GradientTransformation,
    n_stages: int = 1,
    mesh: Mesh | None = None,
    virtual: int = 1,
) -> LMTrainState:
    """Shape/dtype skeleton of an ``LMTrainState`` in the given layout
    (``n_stages=1`` = full, ``>1`` = pipeline), for use as a restore target
    (``checkpoint.load_snapshot``) without building step functions, running
    an init on devices, or needing the saved run's mesh: param shapes depend
    only on ``cfg`` (RoPE — no seq-length-shaped params), so a snapshot's
    tree is reconstructible from config alone.

    Pass ``mesh`` (the *restoring* run's mesh) to attach replicated
    shardings to the skeleton — without it Orbax falls back to the sharding
    file written at save time, which only resolves on the exact saving
    topology.  The restored replicated arrays are then re-placed by
    ``convert_lm_state(..., like=...)``."""
    model = TransformerLM(cfg, None)
    dummy = jnp.zeros((1, 1), jnp.int32)

    def build(rng):
        params = nn.meta.unbox(model.init(rng, dummy)["params"])
        if n_stages > 1:
            params = split_lm_params(params, n_stages, virtual)
        return LMTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )

    abstract = jax.eval_shape(build, jax.random.key(0))
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        abstract = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=rep),
            abstract,
        )
    return abstract


def convert_lm_state(
    state: LMTrainState,
    *,
    n_stages: int | None = None,
    virtual: int = 1,
    like: LMTrainState | None = None,
) -> LMTrainState:
    """Convert an ``LMTrainState`` between the full (non-pipelined) and
    pipeline param layouts, including every param-shaped subtree of the
    optimizer state (Adam ``mu``/``nu`` mirror the param tree, so the same
    structural transform applies).

    Pass ``n_stages`` (and ``virtual`` for the interleaved schedule) to go
    full -> pipeline; omit ``n_stages`` to go pipeline -> full (interleaved
    layouts self-describe via the ``blocks["interleaved"]`` wrapper).  ``like`` (a state from the destination step functions'
    ``init_state``) re-places the converted arrays onto the destination
    mesh/shardings — required when the source and destination meshes
    differ.  Together with Orbax's mesh-elastic restore (``checkpoint.py``)
    this makes the parallelism topology a *resume-time* choice: a snapshot
    from a plain TP/FSDP run continues as a pipelined run and vice versa
    (``tests/test_lm_pipeline.py::test_lm_pipeline_checkpoint_interop``).
    """
    if n_stages is None:
        convert = merge_lm_params
        if not _is_pipeline_tree(state.params):
            raise ValueError(
                "state is not in pipeline layout; pass n_stages to convert "
                "full -> pipeline"
            )
    else:
        if not _is_full_tree(state.params):
            raise ValueError("state is not in full layout")
        convert = lambda p: split_lm_params(p, n_stages, virtual)
    out = state.replace(
        params=convert(state.params),
        opt_state=_map_param_subtrees(state.opt_state, convert),
    )
    if like is not None:
        out = jax.device_put(out, jax.tree.map(lambda x: x.sharding, like))
    return out


def make_lm_pipeline_step_fns(
    cfg: LMConfig,
    spec: LMMeshSpec,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    batch: int,
    seq_len: int,
    num_microbatches: int,
    devices=None,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> LMStepFns:
    """Pipeline-parallel LM step functions (same interface as
    ``make_lm_step_fns``).  Requires ``spec.pipe > 1``.

    ``virtual_stages > 1`` selects the interleaved schedule
    (``make_blocks_pipeline_interleaved``): each device holds that many
    non-contiguous layer chunks, shrinking the pipeline bubble by the same
    factor.  Requires ``n_layers % (pipe * virtual_stages) == 0`` and
    ``num_microbatches % pipe == 0``; gpipe schedule only (the 1F1B
    interleave is not implemented for virtual stages).

    ``schedule``: ``"gpipe"`` (all forwards then all backwards, derived by
    autodiff of the forward scan), ``"1f1b"`` (explicit interleaved
    forward/backward, ``make_blocks_pipeline_1f1b`` — O(pipe) instead of
    O(microbatches) *stage-activation* residency; the embed/head edge
    buffers stay O(batch) under both schedules — same gradients), or
    ``"zb"`` (zero-bubble, ``make_blocks_pipeline_zb`` — the 1F1B clock
    loop with the backward split into B/W passes and the weight
    gradients deferred into the cooldown ticks; single-chunk only, so
    ``virtual_stages`` must be 1).  Evaluation always uses the
    forward-only GPipe schedule."""
    cfg = normalize_flash(cfg, spec, seq_len)  # resolve flash="auto"
    validate_kv_head_sharding(cfg, spec)
    n_stages, M = spec.pipe, num_microbatches
    V = virtual_stages
    if n_stages < 2:
        raise ValueError("make_lm_pipeline_step_fns needs spec.pipe >= 2")
    from ddl_tpu.parallel.rules import PIPELINE_SCHEDULES, lm_rules

    if schedule not in PIPELINE_SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if cfg.ce_vocab_chunk and schedule in ("1f1b", "zb"):
        raise ValueError(
            f"ce_vocab_chunk is not supported with the {schedule.upper()} "
            "schedule (its per-microbatch head loss runs inside the manual "
            "region, where the vocab-scan custom VJP is unverified); use "
            "the GPipe schedule or ce_chunk"
        )
    if V < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {V}")
    if schedule == "zb" and V > 1:
        raise ValueError(
            f"virtual_stages={V} requires schedule='gpipe' or '1f1b' "
            "(the zero-bubble B/W-split clock loop is single-chunk; "
            "compose virtual stages with 1f1b instead)"
        )
    if V > 1 and M % n_stages:
        raise ValueError(
            f"num_microbatches {M} % pipe {n_stages} != 0 (the interleaved "
            "schedule advances microbatches in groups of pipe)"
        )
    if cfg.attn_impl not in ("dense", "ring", "ulysses"):
        raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}")
    if not cfg.causal and (cfg.attn_impl != "dense" or cfg.flash):
        raise ValueError(
            "causal=False is only implemented for the XLA dense attention "
            "path (the nested ring/Ulysses/flash cores are built causal)"
        )
    if cfg.flash and cfg.attn_impl == "dense" and spec.seq > 1:
        raise ValueError(
            "flash=True with attn_impl='dense' requires mesh seq=1 "
            "(the kernel attends within one device's sequence; use "
            "attn_impl='ulysses' to combine flash with sequence parallelism)"
        )
    if cfg.flash and cfg.n_heads % spec.model:
        raise ValueError(
            f"n_heads {cfg.n_heads} % mesh model={spec.model} != 0 (the "
            "flash kernel runs head-local inside a fully-manual region)"
        )
    if cfg.attn_impl == "ulysses" and cfg.n_heads % spec.seq:
        raise ValueError(
            f"n_heads {cfg.n_heads} % mesh seq={spec.seq} != 0 (the nested "
            "Ulysses all-to-all splits the global head dim across seq)"
        )
    if cfg.n_layers % (n_stages * V):
        raise ValueError(
            f"n_layers {cfg.n_layers} % (pipe {n_stages} * virtual {V}) != 0"
        )
    if M < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M}")
    if batch % M:
        raise ValueError(f"batch {batch} % microbatches {M} != 0")
    mb = batch // M
    if mb % (spec.data * spec.expert):
        raise ValueError(
            f"microbatch {mb} must divide by mesh data*expert="
            f"{spec.data * spec.expert} (batch shards over both)"
        )
    if seq_len % spec.seq:
        raise ValueError(f"seq_len {seq_len} % mesh seq={spec.seq} != 0")
    if cfg.num_experts and cfg.num_experts % spec.expert:
        raise ValueError(
            f"num_experts {cfg.num_experts} % mesh expert={spec.expert} != 0"
        )
    mesh = build_lm_mesh(spec, devices)
    rules = lm_logical_rules(cfg.fsdp)

    # Sequence-parallel attention cores nest as inner shard_maps: no mesh
    # argument (they inherit the context mesh, in which 'pipe' is already
    # manual), manual over 'seq' only, specs naming only 'seq' — batch and
    # heads remain auto-partitioned over data/model by GSPMD.
    #
    # With ``flash=True`` the nested region must instead be manual over
    # every axis the kernel's operands touch (data, seq, model): GSPMD
    # cannot auto-partition a Pallas custom call, but a fully-local call
    # inside a fully-manual nested region needs no partitioning at all —
    # the same construction as the non-pipelined path's manual attention,
    # minus ``pipe`` (already manual in the enclosing region).
    seq_spec = P(None, "seq")
    # batch over data AND expert (the 'batch' logical rule): the fully-
    # manual flash regions must make 'expert' manual too, or XLA would
    # have to auto-partition the Pallas call over the residual expert
    # sharding (which GSPMD cannot do)
    manual_spec = P(("data", "expert"), "seq", "model", None)
    if cfg.flash:
        from functools import partial

        from ddl_tpu.ops.flash_attention import flash_attention

        if cfg.attn_impl == "ring":
            from ddl_tpu.parallel.ring_attention import ring_attention

            # flash inside ring, fully-manual like the other flash cores;
            # the ring coordinate rides in as data (axis_index cannot
            # lower inside nested manual regions)
            ring_flash_sm = jax.shard_map(
                lambda q, k, v, pos: ring_attention(
                    q, k, v, axis_name="seq", causal=True, pos=pos[0],
                    use_flash=True, window=cfg.attn_window,
                ),
                in_specs=(manual_spec,) * 3 + (P("seq"),),
                out_specs=manual_spec,
                axis_names={"data", "seq", "model", "expert"},
                check_vma=False,
            )

            def attn_core(q, k, v):
                return ring_flash_sm(
                    q, k, v, jnp.arange(spec.seq, dtype=jnp.int32)
                )
        else:
            if cfg.attn_impl == "ulysses":
                if (cfg.n_heads // spec.model) % spec.seq:
                    raise ValueError(
                        f"local head count {cfg.n_heads // spec.model} "
                        f"(n_heads/model) % mesh seq={spec.seq} != 0 for "
                        "flash-under-Ulysses (heads are model-local in the "
                        "fully-manual region)"
                    )
                validate_ulysses_kv_heads(cfg, spec)
                from ddl_tpu.parallel.ulysses import ulysses_attention

                inner = partial(
                    ulysses_attention,
                    axis_name="seq",
                    causal=True,
                    attn_fn=flash_attention,
                    window=cfg.attn_window,
                )
            else:  # dense + flash, seq=1: the kernel is the whole core
                inner = partial(
                    flash_attention, causal=True, window=cfg.attn_window
                )
            attn_core = jax.shard_map(
                inner,
                in_specs=(manual_spec,) * 3,
                out_specs=manual_spec,
                axis_names={"data", "seq", "model", "expert"},
                check_vma=False,
            )
    elif cfg.attn_impl == "ring":
        from ddl_tpu.parallel.ring_attention import ring_attention

        # The ring coordinate rides in as data (a P('seq')-sharded arange):
        # lax.axis_index cannot lower inside nested manual regions.
        ring_sm = jax.shard_map(
            lambda q, k, v, pos: ring_attention(
                q, k, v, axis_name="seq", causal=True, pos=pos[0],
                window=cfg.attn_window,
            ),
            in_specs=(seq_spec,) * 3 + (P("seq"),),
            out_specs=seq_spec,
            axis_names={"seq"},
            check_vma=False,
        )

        def attn_core(q, k, v):
            return ring_sm(q, k, v, jnp.arange(spec.seq, dtype=jnp.int32))

    elif cfg.attn_impl == "ulysses":
        from functools import partial

        from ddl_tpu.parallel.ulysses import ulysses_attention

        attn_core = jax.shard_map(
            partial(ulysses_attention, axis_name="seq", causal=True,
                    window=cfg.attn_window),
            in_specs=(seq_spec,) * 3,
            out_specs=seq_spec,
            axis_names={"seq"},
            check_vma=False,
        )
    else:
        attn_core = None
    block_cls = remat_block(cfg)
    block_mod = block_cls(cfg, attn_core)
    embed_mod = _Embed(cfg)
    head_mod = _Head(cfg)
    compute_dtype = cfg.dtype
    d = cfg.d_model

    use_dropout = cfg.dropout_rate > 0.0
    pipe_kwargs = dict(
        n_stages=n_stages,
        num_microbatches=M,
        mb=mb,
        d_model=d,
        compute_dtype=compute_dtype,
    )
    make_pipe, wrap_blocks, unwrap_blocks = blocks_pipeline_api(V)
    # deterministic instance (eval always; train when dropout is off)
    pipeline = make_pipe(mesh, block_mod, **pipe_kwargs)
    pipeline_drop = (
        make_pipe(mesh, block_mod, dropout=True, **pipe_kwargs)
        if use_dropout
        else None
    )

    mb_spec = NamedSharding(mesh, P(None, ("data", "expert"), "seq"))

    def blocks_of(params):
        return unwrap_blocks(params["blocks"])

    def forward(params, tokens, step=None, return_hidden=False):
        with nn.logical_axis_rules(rules):
            x = embed_mod.apply({"params": params["embed"]}, tokens)  # (B,T,D)
            x = x.reshape(M, mb, seq_len, d)
            x = lax.with_sharding_constraint(x, mb_spec)
            if use_dropout and step is not None:
                acc, aux_vec = pipeline_drop(
                    blocks_of(params), x, dropout_step_key(rng, step)
                )
            else:
                acc, aux_vec = pipeline(blocks_of(params), x)
            x_out = acc[-1].reshape(batch, seq_len, d)
            if return_hidden:  # norm only; the chunked CE applies the head
                out = _HeadNorm(cfg).apply({"params": params["head"]}, x_out)
            else:
                out = head_mod.apply({"params": params["head"]}, x_out)
        # Each (stage, microbatch) aux term is a mean over that microbatch's
        # rows; dividing the sum by M recovers the full-batch per-layer mean
        # the non-pipelined model computes.
        return out, aux_vec.sum() / M

    # ---- init: build the full (non-pipelined) model's params and
    # restructure, so pipeline and single-program checkpoints interconvert
    # and parity tests can share initialisation. ----
    dummy = jnp.zeros((batch, seq_len), jnp.int32)
    full_model = TransformerLM(cfg, None)

    def init_params(rng):
        full = nn.meta.unbox(full_model.init(rng, dummy)["params"])
        return split_lm_params(full, n_stages, V)

    # Shardings: embed/head from the logical rule table; stacked blocks get
    # ('pipe', None) prepended to each leaf's rule-resolved spec.
    abs_params = jax.eval_shape(lambda r: full_model.init(r, dummy)["params"], rng)
    logical = nn.get_partition_spec(abs_params)
    mesh_sharding = nn.logical_to_mesh_sharding(logical, mesh, rules)
    block0 = mesh_sharding["block0"]
    stack_dims = (None,) * (1 if V == 1 else 2)  # (lps,) or (V, lps)
    blocks_sharding = jax.tree.map(
        lambda sh: NamedSharding(mesh, P(PIPE_AXIS, *stack_dims, *sh.spec)),
        block0,
    )
    param_shardings = {
        "embed": {"embed": mesh_sharding["embed"]},
        "blocks": wrap_blocks(blocks_sharding),
        "head": {
            "norm_f": mesh_sharding["norm_f"],
            "lm_head": mesh_sharding["lm_head"],
        },
    }

    def create_state(rng):
        params = init_params(rng)
        params = jax.lax.with_sharding_constraint(params, param_shardings)
        return LMTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )

    def loss_fn(params, inputs, targets, step=None):
        if cfg.ce_chunk or cfg.ce_vocab_chunk:
            # The GPipe head runs OUTSIDE the manual region on the full
            # (B, T, V) logits — the same loss-edge memory wall as the
            # flat path, fixed the same way: norm-only head, then the
            # chunked head+CE fusion, token-chunked or vocab-streamed
            # (shared tail: lm_steps.chunked_ce_loss).
            hidden, aux = forward(params, inputs, step, return_hidden=True)
            with nn.logical_axis_rules(rules):
                return chunked_ce_loss(
                    cfg, hidden, head_kernel(params["head"]["lm_head"]),
                    targets, aux, with_accuracy=step is None,
                )
        logits, aux = forward(params, inputs, step)
        ce = _token_ce(logits, targets)
        loss = ce + cfg.moe_aux_weight * aux
        return loss, (logits, {"loss": loss, "ce": ce, "moe_aux": aux})

    manual_grad_fn = None
    if schedule in ("1f1b", "zb"):
        # Loss inside the manual region: per-microbatch CE on the last
        # stage, contributing ce/M to the full-batch mean; the raw ce rides
        # out as a metric.
        def head_loss(head_p, y, tgt):
            with nn.logical_axis_rules(rules):
                if cfg.ce_chunk:
                    # chunked head+CE per microbatch, one-hot gather form
                    # (take_along_axis does not partition in manual
                    # subgroups — see onehot_cross_entropy_mean)
                    from ddl_tpu.ops.losses import fused_chunked_ce

                    hidden = _HeadNorm(cfg).apply({"params": head_p}, y)
                    ce, _ = fused_chunked_ce(
                        hidden,
                        head_kernel(head_p["lm_head"]),
                        tgt,
                        cfg.ce_chunk,
                        use_onehot=True,
                        constrain=lambda z: nn.with_logical_constraint(
                            z, ("batch", "act_seq", "act_vocab")
                        ),
                    )
                    return ce / M, ce
                logits = head_mod.apply({"params": head_p}, y)
            ce, _ = onehot_cross_entropy_mean(logits, tgt)
            return ce / M, ce

        bw_kwargs = dict(
            n_stages=n_stages,
            num_microbatches=M,
            mb=mb,
            d_model=d,
            compute_dtype=compute_dtype,
            aux_cotangent=cfg.moe_aux_weight / M,
            zero_metrics=jnp.zeros((), jnp.float32),
            dropout=use_dropout,
        )
        if schedule == "zb":
            pipeline_bw = make_blocks_pipeline_zb(
                mesh, block_mod, head_loss, **bw_kwargs
            )
        else:
            pipeline_bw = make_blocks_pipeline_1f1b(
                mesh, block_mod, head_loss, virtual=V, **bw_kwargs
            )

        def manual_grad_fn(params, inputs, targets, step=None):
            with nn.logical_axis_rules(rules):
                x, embed_vjp = jax.vjp(
                    lambda ep: embed_mod.apply({"params": ep}, inputs),
                    params["embed"],
                )
                x_mb = lax.with_sharding_constraint(
                    x.reshape(M, mb, seq_len, d), mb_spec
                )
                tgt_mb = lax.with_sharding_constraint(
                    targets.reshape(M, mb, seq_len),
                    NamedSharding(mesh, P(None, ("data", "expert"), "seq")),
                )
                key_args = (
                    (dropout_step_key(rng, step),) if use_dropout else ()
                )
                g_blocks, g_head, dx_mb, ce_sum, aux_sum = pipeline_bw(
                    blocks_of(params), params["head"], x_mb, tgt_mb, *key_args
                )
                # close the gradient path GPipe's shard_map transpose handles
                (g_embed,) = embed_vjp(
                    dx_mb.reshape(batch, seq_len, d).astype(x.dtype)
                )
            ce = ce_sum / M
            moe_aux = aux_sum / M
            loss = ce + cfg.moe_aux_weight * moe_aux
            grads = {
                "embed": g_embed,
                "blocks": wrap_blocks(g_blocks),
                "head": g_head,
            }
            return grads, {"loss": loss, "ce": ce, "moe_aux": moe_aux}

    # the family rule table's contract, extended with the pipeline facts
    # the zb contract probe (analysis/contracts.py) validates: which
    # schedule this factory compiled and its stage/chunk geometry
    contract = lm_rules(cfg.fsdp).contract(
        pipeline_schedule=schedule,
        pipeline_stages=n_stages,
        virtual_stages=V,
    )
    return finalize_step_fns(
        mesh, tx, loss_fn, create_state, rng, manual_grad_fn=manual_grad_fn,
        contract=contract,
        probe_inputs=lambda n=batch: (
            jax.ShapeDtypeStruct((n, seq_len), jnp.int32),
            jax.ShapeDtypeStruct((n, seq_len), jnp.int32),
        ),
    )
