"""Pipeline parallelism for the transformer LM family.

The CNN path implements GPipe fully manually over a ``(data, pipe)`` mesh
(``parallel/pipeline.py`` — every collective hand-placed inside one
``shard_map``).  The transformer family instead expresses TP / SP / EP /
FSDP as *logical-axis rules* resolved by XLA's SPMD partitioner
(``parallel/sharding.py``), and this module adds the pipeline axis without
giving that up: the GPipe clock loop runs inside a **partial-manual**
``jax.shard_map`` that is manual over ``pipe`` only (``axis_names={'pipe'}``)
— stage handoffs are explicit ``lax.ppermute`` hops, while everything inside
a stage (batch over ``data``, sequence over ``seq``, heads/MLP over
``model``, experts over ``expert``, FSDP parameter sharding) stays in auto
mode and is partitioned by GSPMD exactly as in the non-pipelined path.

This is the composition the reference builds by hand out of NCCL subgroups
plus a DDP wrapper per pipeline stage (``ddp_n_pp.py:139-155``), extended to
the axes its design cannot express, with no subgroup bookkeeping at all.

Design (scan-over-ticks, stage-stacked params):

* the ``n_layers`` decoder blocks are split into ``pipe`` equal stages;
  per-stage block params are **stacked** on a leading stage axis and sharded
  ``P('pipe', ...)`` — each device holds only its own stage's parameters and
  optimizer state (unlike the CNN pipeline, which replicates the full tuple
  and switches on stage index).  Gradients and Adam state inherit the same
  sharding, so pipeline parallelism here also shards memory.
* embedding and LM head run *outside* the manual region in plain GSPMD land
  (they are cheap next to the block stack; MaxText's pipeline makes the same
  cut).  Their gradients arrive through the shard_map transpose: the
  embedded microbatch array enters replicated-over-pipe, so its cotangent is
  the pipe-psum of per-device cotangents — only stage 0 contributes.
* the GPipe schedule is a ``lax.scan`` over ``T = M + P - 1`` clock ticks.
  Every device runs its stage every tick (the off-schedule ticks are the
  GPipe bubble); there is no ``lax.switch`` because stages are uniform.
  Stage 0 reads microbatch ``t`` from the embedded input; others read the
  ``ppermute``'d boundary buffer.  The last stage's outputs accumulate into
  a per-microbatch buffer; off-schedule writes land on clamped indices that
  later valid writes overwrite, so no masking is needed on the data path.
* the backward schedule is autodiff through the scan: each ``ppermute``
  transposes into the reverse hop and the ticks replay backwards — the same
  property the CNN pipeline exploits (``parallel/pipeline.py``).
* per-stage MoE aux losses leave the manual region as a ``P('pipe')``-sharded
  ``(pipe,)`` vector and are summed outside, keeping loss reductions out of
  the differentiated manual region (psum-under-grad transposes into a psum
  and scales cotangents — the trap documented in ``train/steps.py``).

Sequence parallelism composes through **nested** partial-manual shard_maps:
the ring / Ulysses attention cores become inner ``shard_map``s that inherit
the context mesh (no ``mesh=`` argument) and are manual over ``seq`` only —
their ``ppermute`` / ``all_to_all`` collectives run over the ``seq`` axis
while batch and heads stay auto-partitioned over ``data``/``model`` by
GSPMD, inside the outer manual-over-``pipe`` region.  ``flash=True`` stays
unsupported here: a Pallas call cannot be auto-partitioned over the
remaining axes, so it requires the fully-manual region of the non-pipelined
path.  ``n_layers`` must divide evenly into ``pipe`` stages and the batch
into ``num_microbatches * data`` shards.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddl_tpu.models.transformer import (
    Block,
    LMConfig,
    TransformerLM,
    apply_final_norm_and_head,
    make_embed,
)
from ddl_tpu.parallel.sharding import (
    PIPE_AXIS,
    LMMeshSpec,
    build_lm_mesh,
    lm_logical_rules,
)
from ddl_tpu.train.lm_steps import (
    LMStepFns,
    LMTrainState,
    _token_ce,
    finalize_step_fns,
)

__all__ = [
    "make_lm_pipeline_step_fns",
    "make_blocks_pipeline",
    "split_lm_params",
    "merge_lm_params",
    "convert_lm_state",
    "abstract_lm_state",
    "saved_pipe_stages",
]


def make_blocks_pipeline(
    mesh: Mesh,
    block_mod: nn.Module,
    *,
    n_stages: int,
    num_microbatches: int,
    mb: int,
    d_model: int,
    compute_dtype,
):
    """The GPipe clock loop over a stack of uniform decoder/encoder blocks,
    as a partial-manual shard_map (manual over ``pipe`` only) — shared by
    the LM (``make_lm_pipeline_step_fns``) and ViT
    (``train/vit_steps.py``) pipelines.

    Returns ``pipeline(blocks_stacked, x_mb)`` where ``blocks_stacked`` is
    the ``(pipe, layers_per_stage, ...)`` param stack sharded
    ``P('pipe', ...)`` and ``x_mb`` is ``(M, mb, T, d_model)`` microbatched
    activations; yields ``(acc, aux_vec)`` with ``acc`` the last stage's
    per-microbatch outputs (callers slice ``[-1]``) and ``aux_vec`` the
    ``(pipe,)`` per-stage aux-loss vector.  See the module docstring for
    the schedule design.
    """
    M = num_microbatches
    d = d_model

    def stage_fn(stage_blocks, x):
        def layer(carry, p):
            y, aux = block_mod.apply({"params": p}, carry)
            return y, aux

        y, auxs = lax.scan(layer, x, stage_blocks)
        return y, auxs.sum()

    def pipeline_body(blocks_stacked, x_mb):
        stage_blocks = jax.tree.map(lambda a: a[0], blocks_stacked)
        s = lax.axis_index(PIPE_AXIS)
        t_len = x_mb.shape[2]
        buf0 = jnp.zeros((mb, t_len, d), compute_dtype)
        acc0 = jnp.zeros((M, mb, t_len, d), compute_dtype)

        def tick(carry, t):
            buf, acc, aux = carry
            x_first = lax.dynamic_index_in_dim(
                x_mb, jnp.clip(t, 0, M - 1), 0, keepdims=False
            )
            x_in = jnp.where(s == 0, x_first, buf)
            out, aux_t = stage_fn(stage_blocks, x_in)
            valid = (t >= s) & (t - s < M)
            aux = aux + jnp.where(valid, aux_t, 0.0)
            # Off-schedule writes land on clamped indices; the valid write
            # for microbatch i happens at tick P-1+i, after any clamped
            # garbage, so the final buffer needs no masking (and only the
            # last pipe coordinate's buffer is ever read).
            acc = lax.dynamic_update_index_in_dim(
                acc, out, jnp.clip(t - (n_stages - 1), 0, M - 1), 0
            )
            buf = lax.ppermute(
                out, PIPE_AXIS, [(i, i + 1) for i in range(n_stages - 1)]
            )
            return (buf, acc, aux), None

        init = (buf0, acc0, jnp.zeros((), jnp.float32))
        (_, acc, aux), _ = lax.scan(tick, init, jnp.arange(M + n_stages - 1))
        return acc[None], aux[None]

    return jax.shard_map(
        pipeline_body,
        mesh=mesh,
        in_specs=(P(PIPE_AXIS), P()),
        out_specs=(P(PIPE_AXIS), P(PIPE_AXIS)),
        axis_names={PIPE_AXIS},
        check_vma=False,
    )


class _Embed(nn.Module):
    """Stage-0 prologue.  Uses ``make_embed`` — the same construction
    ``TransformerLM`` composes — so full-model checkpoints restructure 1:1
    (``split_lm_params``)."""

    cfg: LMConfig

    @nn.compact
    def __call__(self, tokens):
        x = make_embed(self.cfg)(tokens)
        return nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))


class _Head(nn.Module):
    """Last-stage epilogue: final RMSNorm + vocab projection (shared
    construction with ``TransformerLM``)."""

    cfg: LMConfig

    @nn.compact
    def __call__(self, x):
        return apply_final_norm_and_head(self.cfg, x)


def stack_block_params(full_params: Any, n_stages: int):
    """Stack a param tree's ``block{i}`` subtrees to
    ``(n_stages, layers_per_stage, ...)``, stage-major in layer order
    (stage p owns layers ``[p*Lps, (p+1)*Lps)``) — the unit every blocks
    pipeline shards ``P('pipe', ...)``.  Shared by the LM and ViT splits."""
    layer_keys = sorted(
        (k for k in full_params if k.startswith("block")),
        key=lambda k: int(k.removeprefix("block")),
    )
    lps = len(layer_keys) // n_stages
    return jax.tree.map(
        lambda *xs: jnp.stack(xs).reshape(n_stages, lps, *xs[0].shape),
        *(full_params[k] for k in layer_keys),
    )


def split_lm_params(full_params: Any, n_stages: int) -> dict:
    """Restructure a full ``TransformerLM`` param tree into the pipeline
    layout ``{embed, blocks, head}`` (see ``stack_block_params``)."""
    return {
        "embed": {"embed": full_params["embed"]},
        "blocks": stack_block_params(full_params, n_stages),
        "head": {"norm_f": full_params["norm_f"], "lm_head": full_params["lm_head"]},
    }


def merge_lm_params(pp_params: dict) -> dict:
    """Inverse of ``split_lm_params``: pipeline layout ``{embed, blocks,
    head}`` back to the flat ``TransformerLM`` tree (``block{i}`` keyed,
    stage-major layer order)."""
    blocks = pp_params["blocks"]
    shape_leaf = jax.tree.leaves(blocks)[0]
    n_stages, lps = shape_leaf.shape[:2]
    full = {
        "embed": pp_params["embed"]["embed"],
        "norm_f": pp_params["head"]["norm_f"],
        "lm_head": pp_params["head"]["lm_head"],
    }
    for p in range(n_stages):
        for j in range(lps):
            full[f"block{p * lps + j}"] = jax.tree.map(
                lambda x: x[p, j], blocks
            )
    return full


def _is_pipeline_tree(x) -> bool:
    return isinstance(x, dict) and set(x) == {"embed", "blocks", "head"}


def _is_full_tree(x) -> bool:
    return isinstance(x, dict) and "lm_head" in x and "block0" in x


def _map_param_subtrees(x, convert):
    """Apply ``convert`` to every param-layout dict inside an arbitrary
    optimizer-state structure (NamedTuples / tuples / lists / dicts of
    arrays and param-shaped trees, e.g. Adam's ``mu``/``nu``).  The layout
    checks run first so a param tree is converted whole, not recursed into."""
    if _is_pipeline_tree(x) or _is_full_tree(x):
        return convert(x)
    if isinstance(x, tuple) and hasattr(x, "_fields"):  # NamedTuple state
        return type(x)(*(_map_param_subtrees(f, convert) for f in x))
    if isinstance(x, (tuple, list)):
        return type(x)(_map_param_subtrees(f, convert) for f in x)
    if isinstance(x, dict):  # e.g. multi_transform's inner_states
        return {k: _map_param_subtrees(v, convert) for k, v in x.items()}
    return x


def saved_pipe_stages(params: Any) -> int:
    """Pipe stage count a params tree was written with (1 = full layout).
    Works on real trees and on checkpoint *metadata* trees (anything whose
    leaves carry ``.shape`` — see ``checkpoint.snapshot_metadata``), so a
    resuming run can discover a snapshot's layout without flags."""
    if _is_pipeline_tree(params):
        return int(jax.tree.leaves(params["blocks"])[0].shape[0])
    if not _is_full_tree(params):
        raise ValueError(
            f"unrecognized params layout (keys: {sorted(params)[:8]}...)"
            if isinstance(params, dict)
            else f"unrecognized params layout: {type(params)}"
        )
    return 1


def abstract_lm_state(
    cfg: LMConfig,
    tx: optax.GradientTransformation,
    n_stages: int = 1,
    mesh: Mesh | None = None,
) -> LMTrainState:
    """Shape/dtype skeleton of an ``LMTrainState`` in the given layout
    (``n_stages=1`` = full, ``>1`` = pipeline), for use as a restore target
    (``checkpoint.load_snapshot``) without building step functions, running
    an init on devices, or needing the saved run's mesh: param shapes depend
    only on ``cfg`` (RoPE — no seq-length-shaped params), so a snapshot's
    tree is reconstructible from config alone.

    Pass ``mesh`` (the *restoring* run's mesh) to attach replicated
    shardings to the skeleton — without it Orbax falls back to the sharding
    file written at save time, which only resolves on the exact saving
    topology.  The restored replicated arrays are then re-placed by
    ``convert_lm_state(..., like=...)``."""
    model = TransformerLM(cfg, None)
    dummy = jnp.zeros((1, 1), jnp.int32)

    def build(rng):
        params = nn.meta.unbox(model.init(rng, dummy)["params"])
        if n_stages > 1:
            params = split_lm_params(params, n_stages)
        return LMTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )

    abstract = jax.eval_shape(build, jax.random.key(0))
    if mesh is not None:
        rep = NamedSharding(mesh, P())
        abstract = jax.tree.map(
            lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=rep),
            abstract,
        )
    return abstract


def convert_lm_state(
    state: LMTrainState,
    *,
    n_stages: int | None = None,
    like: LMTrainState | None = None,
) -> LMTrainState:
    """Convert an ``LMTrainState`` between the full (non-pipelined) and
    pipeline param layouts, including every param-shaped subtree of the
    optimizer state (Adam ``mu``/``nu`` mirror the param tree, so the same
    structural transform applies).

    Pass ``n_stages`` to go full -> pipeline; omit it to go pipeline ->
    full.  ``like`` (a state from the destination step functions'
    ``init_state``) re-places the converted arrays onto the destination
    mesh/shardings — required when the source and destination meshes
    differ.  Together with Orbax's mesh-elastic restore (``checkpoint.py``)
    this makes the parallelism topology a *resume-time* choice: a snapshot
    from a plain TP/FSDP run continues as a pipelined run and vice versa
    (``tests/test_lm_pipeline.py::test_lm_pipeline_checkpoint_interop``).
    """
    if n_stages is None:
        convert = merge_lm_params
        if not _is_pipeline_tree(state.params):
            raise ValueError(
                "state is not in pipeline layout; pass n_stages to convert "
                "full -> pipeline"
            )
    else:
        if not _is_full_tree(state.params):
            raise ValueError("state is not in full layout")
        convert = lambda p: split_lm_params(p, n_stages)
    out = state.replace(
        params=convert(state.params),
        opt_state=_map_param_subtrees(state.opt_state, convert),
    )
    if like is not None:
        out = jax.device_put(out, jax.tree.map(lambda x: x.sharding, like))
    return out


def make_lm_pipeline_step_fns(
    cfg: LMConfig,
    spec: LMMeshSpec,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    batch: int,
    seq_len: int,
    num_microbatches: int,
    devices=None,
) -> LMStepFns:
    """Pipeline-parallel LM step functions (same interface as
    ``make_lm_step_fns``).  Requires ``spec.pipe > 1``."""
    n_stages, M = spec.pipe, num_microbatches
    if n_stages < 2:
        raise ValueError("make_lm_pipeline_step_fns needs spec.pipe >= 2")
    if cfg.attn_impl not in ("dense", "ring", "ulysses"):
        raise ValueError(f"unknown attn_impl {cfg.attn_impl!r}")
    if not cfg.causal and cfg.attn_impl != "dense":
        raise ValueError(
            "causal=False is only implemented for dense attention "
            "(the nested ring/Ulysses cores are built causal)"
        )
    if cfg.dropout_rate > 0.0:
        raise ValueError(
            "dropout is not supported with pipeline parallelism (the blocks "
            "run inside the manual-over-pipe scan with no dropout rng "
            "plumbing); train with dropout on the non-pipelined path"
        )
    if cfg.flash:
        raise ValueError(
            "flash=True is not supported with pipeline parallelism: the "
            "Pallas kernel needs the fully-manual attention region of the "
            "non-pipelined path (GSPMD cannot auto-partition a custom call "
            "over the data/model axes inside the manual-over-pipe region)"
        )
    if cfg.attn_impl == "ulysses" and cfg.n_heads % spec.seq:
        raise ValueError(
            f"n_heads {cfg.n_heads} % mesh seq={spec.seq} != 0 (the nested "
            "Ulysses all-to-all splits the global head dim across seq)"
        )
    if cfg.n_layers % n_stages:
        raise ValueError(f"n_layers {cfg.n_layers} % pipe {n_stages} != 0")
    if M < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M}")
    if batch % M:
        raise ValueError(f"batch {batch} % microbatches {M} != 0")
    mb = batch // M
    if mb % spec.data:
        raise ValueError(f"microbatch {mb} % mesh data={spec.data} != 0")
    if seq_len % spec.seq:
        raise ValueError(f"seq_len {seq_len} % mesh seq={spec.seq} != 0")
    if cfg.num_experts and cfg.num_experts % spec.expert:
        raise ValueError(
            f"num_experts {cfg.num_experts} % mesh expert={spec.expert} != 0"
        )
    lps = cfg.n_layers // n_stages
    mesh = build_lm_mesh(spec, devices)
    rules = lm_logical_rules(cfg.fsdp)

    # Sequence-parallel attention cores nest as inner shard_maps: no mesh
    # argument (they inherit the context mesh, in which 'pipe' is already
    # manual), manual over 'seq' only, specs naming only 'seq' — batch and
    # heads remain auto-partitioned over data/model by GSPMD.
    seq_spec = P(None, "seq")
    if cfg.attn_impl == "ring":
        from ddl_tpu.parallel.ring_attention import ring_attention

        # The ring coordinate rides in as data (a P('seq')-sharded arange):
        # lax.axis_index cannot lower inside nested manual regions.
        ring_sm = jax.shard_map(
            lambda q, k, v, pos: ring_attention(
                q, k, v, axis_name="seq", causal=True, pos=pos[0]
            ),
            in_specs=(seq_spec,) * 3 + (P("seq"),),
            out_specs=seq_spec,
            axis_names={"seq"},
            check_vma=False,
        )

        def attn_core(q, k, v):
            return ring_sm(q, k, v, jnp.arange(spec.seq, dtype=jnp.int32))

    elif cfg.attn_impl == "ulysses":
        from functools import partial

        from ddl_tpu.parallel.ulysses import ulysses_attention

        attn_core = jax.shard_map(
            partial(ulysses_attention, axis_name="seq", causal=True),
            in_specs=(seq_spec,) * 3,
            out_specs=seq_spec,
            axis_names={"seq"},
            check_vma=False,
        )
    else:
        attn_core = None
    block_cls = nn.remat(Block) if cfg.remat else Block
    block_mod = block_cls(cfg, attn_core)
    embed_mod = _Embed(cfg)
    head_mod = _Head(cfg)
    compute_dtype = cfg.dtype
    d = cfg.d_model

    pipeline = make_blocks_pipeline(
        mesh,
        block_mod,
        n_stages=n_stages,
        num_microbatches=M,
        mb=mb,
        d_model=d,
        compute_dtype=compute_dtype,
    )

    mb_spec = NamedSharding(mesh, P(None, "data", "seq"))

    def forward(params, tokens):
        with nn.logical_axis_rules(rules):
            x = embed_mod.apply({"params": params["embed"]}, tokens)  # (B,T,D)
            x = x.reshape(M, mb, seq_len, d)
            x = lax.with_sharding_constraint(x, mb_spec)
            acc, aux_vec = pipeline(params["blocks"], x)
            x_out = acc[-1].reshape(batch, seq_len, d)
            logits = head_mod.apply({"params": params["head"]}, x_out)
        # Each (stage, microbatch) aux term is a mean over that microbatch's
        # rows; dividing the sum by M recovers the full-batch per-layer mean
        # the non-pipelined model computes.
        return logits, aux_vec.sum() / M

    # ---- init: build the full (non-pipelined) model's params and
    # restructure, so pipeline and single-program checkpoints interconvert
    # and parity tests can share initialisation. ----
    dummy = jnp.zeros((batch, seq_len), jnp.int32)
    full_model = TransformerLM(cfg, None)

    def init_params(rng):
        full = nn.meta.unbox(full_model.init(rng, dummy)["params"])
        return split_lm_params(full, n_stages)

    # Shardings: embed/head from the logical rule table; stacked blocks get
    # ('pipe', None) prepended to each leaf's rule-resolved spec.
    abs_params = jax.eval_shape(lambda r: full_model.init(r, dummy)["params"], rng)
    logical = nn.get_partition_spec(abs_params)
    mesh_sharding = nn.logical_to_mesh_sharding(logical, mesh, rules)
    block0 = mesh_sharding["block0"]
    blocks_sharding = jax.tree.map(
        lambda sh: NamedSharding(mesh, P(PIPE_AXIS, None, *sh.spec)), block0
    )
    param_shardings = {
        "embed": {"embed": mesh_sharding["embed"]},
        "blocks": blocks_sharding,
        "head": {
            "norm_f": mesh_sharding["norm_f"],
            "lm_head": mesh_sharding["lm_head"],
        },
    }

    def create_state(rng):
        params = init_params(rng)
        params = jax.lax.with_sharding_constraint(params, param_shardings)
        return LMTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )

    def loss_fn(params, inputs, targets, step=None):
        logits, aux = forward(params, inputs)
        ce = _token_ce(logits, targets)
        loss = ce + cfg.moe_aux_weight * aux
        return loss, (logits, {"loss": loss, "ce": ce, "moe_aux": aux})

    return finalize_step_fns(mesh, tx, loss_fn, create_state, rng)
