"""Declarative partition rules: regex-over-param-path -> PartitionSpec.

Before this module, parameter placement lived in two places that could
drift: the flax logical-axis annotations inside the models (resolved
through ``lm_logical_rules``) and the hand-written ``PartitionSpec``
literals + ``.contract`` dicts in every step factory.  Onboarding a new
model family meant re-deriving both, and the optimizer-state sharding
work (ZeRO) had nowhere to hang: the moments' placement was whatever
``tx.init`` propagation produced.

This module makes partitioning a *table*, in the ``match_partition_rules``
style of the public LLM-training frameworks (SNIPPETS.md [1]/[3]): an
ordered list of ``(regex, PartitionSpec)`` rules matched against each
parameter's ``/``-joined tree path, **first match wins**, scalars and
single-element leaves replicate, and a leaf no rule matches is a loud
``UnmatchedLeafError`` — a new parameter cannot be silently replicated
by omission.  Per-family tables (CNN / LM / ViT / decode) carry the
family's jit-boundary batch specs and derive the machine-readable
``.contract`` the step factories attach, so the sharding-contract
checker (``analysis/contracts.py``) validates the *table* instead of a
hand-maintained waiver list.  Because ``re.search`` matches anywhere in
the path, the same table resolves optimizer moments: a ``mu/nu`` leaf's
path embeds the parameter path (``0/mu/block0/attn/q/kernel``), so
Adam state inherits parameter placement for free (``strict=False`` lets
non-parameter leaves — counts, the step — fall through to replicated).

The LM/ViT tables reproduce the models' logical-axis resolution exactly
(asserted leaf-by-leaf by ``tests/test_partition_rules.py``); the
*activations* keep their ``nn.with_logical_constraint`` annotations —
this table owns parameter (and derived optimizer-state) placement.

``zero_shard_spec`` is the ZeRO-1 derivation on top of a resolved rule
table: given a parameter's spec and shape, pick the first unsharded
dimension divisible by the ``data``-axis size and shard the *optimizer
state and weight update* over it (the cross-replica weight-update
sharding of PAPERS.md's "Automatic Cross-Replica Sharding" paper —
``train/fused_optim.py`` consumes it).

Because placement is a pure function of the parameter path — never of
the mesh extent — the tables are what make elastic pod scale-down a
*derivable* respec: a relaunch on N-1 hosts re-enters the same table
with a smaller ``data`` axis (``DDL_NUM_PROCESSES`` from the agreed
membership, see ``supervisor.py``) and every parameter lands in the
same logical position; only the data-parallel extent shrinks.  The
same property carries the GROW direction (elastic scale-up, round 24):
a relaunch into a larger world re-enters the table with the bigger
``data`` axis, ``zero_shard_spec`` re-picks the same dimension (the
divisibility test only loosens as the axis grows back toward the size
the model was originally validated for), and the restore re-shards the
moments into the new layout with no extra mechanism
(``checkpoint.state_rule_shardings`` + the global-array restore —
tests/test_zero_sharding.py pins dp=2 -> dp=4 -> dp=2 bit-identity).
"""

from __future__ import annotations

import dataclasses
import math
import re
from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = [
    "UnmatchedLeafError",
    "RuleTable",
    "match_partition_rules",
    "match_with_provenance",
    "make_shard_and_gather_fns",
    "tree_path_str",
    "cnn_rules",
    "lm_rules",
    "vit_rules",
    "decode_rules",
    "zero_shard_spec",
    "zero_gather_plan",
    "spec_axes",
    "spec_num_shards",
    "optimizer_hbm_bytes",
    "ZERO_THRESHOLD",
    "PIPELINE_SCHEDULES",
    "BATCH_SPEC",
    "IMAGE_SPEC",
    "TOKEN_SPEC",
    "DECODE_TOKEN_SPEC",
    "LM_MANUAL_ATTN_SPEC",
]

# Parameter leaves at or above this many elements get their optimizer
# state ZeRO-sharded over 'data' (below it the all-gather latency costs
# more than the replicated bytes); the same line the contract checker
# draws for silent replication (analysis/contracts.REPLICATION_THRESHOLD).
ZERO_THRESHOLD = 8192

# The blocks-pipeline schedule vocabulary (parallel/lm_pipeline.py):
# "gpipe" (autodiff through the forward scan; virtual_stages > 1 makes
# it the interleaved schedule), "1f1b" (hand-written interleaved
# forward/backward), "zb" (zero-bubble: 1F1B with the backward split
# into B/W and W deferred into the cooldown ticks).  The step
# factories validate against this tuple and stamp the selected
# schedule into their boundary contract (``pipeline_schedule``), which
# the contract probes (analysis/contracts.py) check membership of —
# one vocabulary, declared where the rest of the partitioning facts
# live.
PIPELINE_SCHEDULES = ("gpipe", "1f1b", "zb")

# ---------------------------------------------------------------------------
# Named jit-boundary batch specs.  Defined HERE (not in the step
# factories) so factories, contracts, and tests agree by construction —
# the step-factory modules themselves are lint-banned from hand-writing
# PartitionSpec axis literals (astlint 'pspec-hand-rolled').
# ---------------------------------------------------------------------------

# CNN image/label batches on the (data, pipe) mesh.
BATCH_SPEC = P("data")
# ViT image/label batches (the family does not use the expert axis).
IMAGE_SPEC = P("data")
# LM token batches: batch over data x expert (outside MoE layers the
# expert axis is extra data parallelism), sequence over seq.
TOKEN_SPEC = P(("data", "expert"), "seq")
# Decode prompt/output batches: batch over data; heads shard over
# 'model' inside the program.
DECODE_TOKEN_SPEC = P("data")
# Boundary of the manual attention cores (ring / Ulysses / flash
# shard_map): batch over data x expert, sequence over seq, heads over
# model, head_dim local.
LM_MANUAL_ATTN_SPEC = P(("data", "expert"), "seq", "model", None)


class UnmatchedLeafError(ValueError):
    """A non-scalar leaf matched no partition rule.  Carries the paths so
    the fix (add a rule) is obvious from the message."""

    def __init__(self, family: str, paths: list[str]) -> None:
        self.family = family
        self.paths = list(paths)
        listed = ", ".join(self.paths[:8])
        more = f" (+{len(self.paths) - 8} more)" if len(self.paths) > 8 else ""
        super().__init__(
            f"no partition rule in the {family!r} table matches leaf path(s) "
            f"{listed}{more}; every parameter must be placed explicitly "
            "(add a rule to parallel/rules.py — P() for deliberate "
            "replication)"
        )


def tree_path_str(key_path) -> str:
    """``/``-joined tree path (DictKey / GetAttrKey / SequenceKey all
    stringify differently; normalise like ``checkpoint._kp_norm``)."""
    return "/".join(
        str(getattr(k, "key", getattr(k, "name", getattr(k, "idx", k))))
        for k in key_path
    )


def _is_spec(x) -> bool:
    return isinstance(x, P)


def _leaf_size(leaf) -> int:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return 1
    return math.prod(shape) if shape else 1


def _match_leaves(rules, tree, family: str, strict: bool):
    """Yield ``(path, leaf, spec, pattern)`` per leaf; ``pattern`` is the
    matched rule's regex (None for the scalar/fallthrough default)."""
    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    out, unmatched = [], []
    for kp, leaf in flat:
        name = tree_path_str(kp)
        if _leaf_size(leaf) <= 1:
            out.append((name, leaf, P(), None))
            continue
        for pattern, spec in rules:
            if re.search(pattern, name) is not None:
                out.append((name, leaf, spec, pattern))
                break
        else:
            unmatched.append(name)
            out.append((name, leaf, P(), None))
    if strict and unmatched:
        raise UnmatchedLeafError(family, unmatched)
    return out, treedef


def match_partition_rules(rules, tree, *, strict: bool = True):
    """PartitionSpec pytree for ``tree`` under first-match-wins ``rules``
    (``[(regex, PartitionSpec), ...]`` or a ``RuleTable``).  Scalar and
    single-element leaves replicate without consulting the table; with
    ``strict`` (the default) an unmatched non-scalar leaf raises
    ``UnmatchedLeafError``, with ``strict=False`` it replicates — the
    mode for whole *state* trees, whose non-parameter leaves (step,
    Adam's count) have no rules but whose moment leaves embed the
    parameter path and match normally."""
    family = getattr(rules, "family", "<anonymous>")
    rules = getattr(rules, "rules", rules)
    leaves, treedef = _match_leaves(rules, tree, family, strict)
    return treedef.unflatten([spec for _, _, spec, _ in leaves])


def match_with_provenance(rules, tree, *, strict: bool = True):
    """Like ``match_partition_rules`` but returns a flat list of
    ``(path, leaf, spec, matched_pattern)`` — the contract probes use the
    pattern to distinguish *explicit* replication (a rule that maps to
    ``P()``) from a replication bug."""
    family = getattr(rules, "family", "<anonymous>")
    rules = getattr(rules, "rules", rules)
    leaves, _ = _match_leaves(rules, tree, family, strict)
    return leaves


def make_shard_and_gather_fns(mesh: Mesh, specs):
    """``(shard, gather)`` tree functions from a resolved spec pytree.

    ``shard(tree)`` device_puts every leaf onto ``mesh`` under its spec —
    how a checkpoint restored as host/replicated arrays enters rule
    placement; ``gather(tree)`` fetches every leaf fully to host (numpy)
    — the inverse, for writing topology-independent snapshots or
    comparing sharded and replicated states leaf-by-leaf."""
    import numpy as np

    shardings = jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs, is_leaf=_is_spec
    )

    def shard(tree):
        return jax.tree.map(jax.device_put, tree, shardings)

    def gather(tree):
        return jax.tree.map(
            lambda x: np.asarray(jax.device_get(x)), tree
        )

    return shard, gather


@dataclasses.dataclass(frozen=True)
class RuleTable:
    """One model family's partitioning, as data.

    ``rules`` place parameters (and, via path-embedding, optimizer
    moments); ``in_specs`` are the family's jit-boundary batch specs;
    ``replicated_params_ok``/``donate_state`` feed the derived contract.
    """

    family: str
    rules: tuple[tuple[str, P], ...]
    in_specs: dict[str, P]
    replicated_params_ok: bool = False
    donate_state: bool = True

    def specs(self, tree, *, strict: bool = True):
        return match_partition_rules(self, tree, strict=strict)

    def shardings(self, tree, mesh: Mesh, *, strict: bool = True):
        return jax.tree.map(
            lambda s: NamedSharding(mesh, s),
            self.specs(tree, strict=strict),
            is_leaf=_is_spec,
        )

    def provenance(self, tree, *, strict: bool = True):
        return match_with_provenance(self, tree, strict=strict)

    def contract(self, **extra) -> dict:
        """The machine-readable ``.contract`` dict the step factories
        attach to their jitted train/generate functions — derived from
        the table instead of hand-written, and carrying the table itself
        so ``analysis/contracts.py`` validates rules, not waivers."""
        c = {
            "in_specs": dict(self.in_specs),
            "donate_state": self.donate_state,
            "replicated_params_ok": self.replicated_params_ok,
            "rule_table": self,
        }
        c.update(extra)
        return c


# ---------------------------------------------------------------------------
# family tables
# ---------------------------------------------------------------------------


def _transformer_block_rules(E) -> tuple[tuple[str, P], ...]:
    """The decoder/encoder block shared by the LM and ViT families:
    attention QKV column-parallel and the out projection row-parallel
    over 'model' (Megatron split), MLP the same, MoE experts over
    'expert'; ``E`` is the embed-dimension axis — 'data' under FSDP
    (ZeRO-3-style parameter sharding), unsharded otherwise."""
    return (
        (r"attn/(q|k|v)/kernel$", P(E, "model")),
        (r"attn/out/kernel$", P("model", E)),
        (r"mlp/wi/kernel$", P(E, "model")),
        (r"mlp/wo/kernel$", P("model", E)),
        (r"moe/router/kernel$", P(E, "expert")),
        (r"moe/wi$", P("expert", E, "model")),
        (r"moe/wo$", P("expert", "model", E)),
        (r"norm\w*/scale$", P()),
    )


def lm_rules(fsdp: bool = False) -> RuleTable:
    """The transformer LM family (``models/transformer.py``): TP over
    'model' (vocab/heads/MLP-hidden), experts over 'expert', embed dim
    over 'data' with ``fsdp`` — leaf-for-leaf the resolution the model's
    logical-axis annotations produce."""
    E = "data" if fsdp else None
    return RuleTable(
        family="lm",
        rules=_transformer_block_rules(E) + (
            (r"embed/embedding$", P("model", E)),
            (r"lm_head/kernel$", P("model", E)),
        ),
        in_specs={"inputs": TOKEN_SPEC, "targets": TOKEN_SPEC},
    )


def vit_rules(fsdp: bool = False) -> RuleTable:
    """The ViT family (``models/vit.py``).  The patch/position embeddings
    and the tiny classifier head replicate by *explicit rule* (formerly
    contract waivers): their embed dimension is the only shardable one,
    deliberately left whole without FSDP — the probes report these as
    explicit replication, not silent."""
    E = "data" if fsdp else None
    return RuleTable(
        family="vit",
        rules=_transformer_block_rules(E) + (
            (r"patch_embed/kernel$", P(None, None, None, E)),
            (r"patch_embed/bias$", P()),
            (r"pos_embed$", P(None, None, E)),
            (r"head/kernel$", P(E, None)),
            (r"head/bias$", P()),
        ),
        in_specs={"images": IMAGE_SPEC, "labels": IMAGE_SPEC},
    )


def cnn_rules() -> RuleTable:
    """The DenseNet family: DDP keeps full parameter replicas by design
    (gradients all-reduce over 'data'; there is no tensor-parallel axis
    in this family), so one explicit catch-all replication rule places
    everything — and the derived contract says replication is
    contractual, which is the probe waiver."""
    return RuleTable(
        family="cnn",
        rules=((r".", P()),),
        in_specs={"images": BATCH_SPEC, "labels": BATCH_SPEC},
        replicated_params_ok=True,
    )


def decode_rules() -> RuleTable:
    """The LM decode/serving surface: the same parameter placement as LM
    training (a training snapshot decodes as-is), no state donation, and
    replication allowed by contract — serving replicas on a
    model-axis-free mesh intentionally hold full copies."""
    base = lm_rules(fsdp=False)
    return RuleTable(
        family="decode",
        rules=base.rules,
        in_specs={"prompt": DECODE_TOKEN_SPEC},
        replicated_params_ok=True,
        donate_state=False,
    )


# ---------------------------------------------------------------------------
# ZeRO derivation + optimizer-state HBM accounting
# ---------------------------------------------------------------------------


def _norm_entries(spec, ndim: int) -> tuple:
    entries = tuple(spec) if spec is not None else ()
    return entries + (None,) * (ndim - len(entries))


def spec_axes(spec) -> set[str]:
    """Mesh-axis names a PartitionSpec draws on (tuples flattened)."""
    axes: set[str] = set()
    for e in tuple(spec or ()):
        if e is None:
            continue
        for a in (e if isinstance(e, tuple) else (e,)):
            axes.add(a)
    return axes


_spec_axes = spec_axes


def spec_num_shards(spec, mesh: Mesh) -> int:
    """Devices one leaf is split across under ``spec`` (its per-device
    byte divisor)."""
    n = 1
    for a in _spec_axes(spec):
        n *= mesh.shape.get(a, 1)
    return n


def zero_shard_spec(
    spec,
    shape,
    mesh: Mesh,
    axis: str = "data",
    threshold: int = ZERO_THRESHOLD,
):
    """The ZeRO-1 spec for one parameter leaf, or None when the leaf
    stays replicated over ``axis``.

    Adds ``axis`` to the first unsharded dimension whose size divides by
    the axis size — the shard the optimizer moments live at and the
    weight update computes at (reduce-scattered gradients in,
    all-gathered parameters out).  None when: the leaf is under
    ``threshold`` elements (gather latency would cost more than the
    replicated bytes), the axis is trivial, the spec already uses it
    (FSDP — the state is already sharded over data), or no dimension
    divides."""
    size = math.prod(shape) if shape else 1
    if size < threshold:
        return None
    dp = mesh.shape.get(axis, 1)
    if dp <= 1:
        return None
    entries = _norm_entries(spec, len(shape))
    if axis in _spec_axes(entries):
        return None
    for i, (e, dim) in enumerate(zip(entries, shape)):
        if e is None and dim % dp == 0:
            return P(*entries[:i], axis, *entries[i + 1:])
    return None


def zero_gather_plan(
    table: RuleTable,
    abstract_params,
    mesh: Mesh,
    axis: str = "data",
    threshold: int | None = None,
) -> dict:
    """The expected all-gather geometry of a ZeRO-1 program, derived
    from the rule table — the leaf-size/spec provenance the compiled-IR
    lint (``analysis/hlolint.py``) checks GSPMD's emitted gathers
    against.

    Per eligible leaf (``zero_shard_spec`` accepts it): its *gather
    shape* — the full shape divided by the leaf's non-``axis`` shard
    counts — which is what the weight-update all-gather must produce
    (shard-sized operand in, non-data-shard out).  ``leaf_shard_shapes``
    additionally lists every ≥threshold leaf's shard shape, eligible or
    not: backward-pass gathers (embedding scatter-add) legitimately
    produce param-shaped outputs, so they are allowed, while a gather
    producing any *other* large shape has no business in the step."""
    if threshold is None:
        threshold = ZERO_THRESHOLD
    eligible: list[dict] = []
    leaf_shard_shapes: set[tuple[int, ...]] = set()
    for name, leaf, spec, _pat in table.provenance(
        abstract_params, strict=False
    ):
        shape = tuple(getattr(leaf, "shape", ()) or ())
        size = math.prod(shape) if shape else 1
        if size < threshold:
            continue
        entries = _norm_entries(spec, len(shape))
        shard = tuple(
            dim // math.prod(
                mesh.shape.get(a, 1)
                for a in ((e,) if not isinstance(e, tuple) else e)
                if a != axis
            ) if e is not None else dim
            for e, dim in zip(entries, shape)
        )
        leaf_shard_shapes.add(shard)
        zspec = zero_shard_spec(spec, shape, mesh, axis, threshold)
        if zspec is None:
            continue
        eligible.append({
            "name": name,
            "size": size,
            "shape": list(shape),
            "gather_shape": list(shard),
        })
    return {
        "axis": axis,
        "threshold": threshold,
        "eligible": eligible,
        "gather_shapes": sorted(
            {tuple(leaf["gather_shape"]) for leaf in eligible}
        ),
        "leaf_shard_shapes": sorted(leaf_shard_shapes),
    }


def optimizer_hbm_bytes(
    table: RuleTable,
    abstract_params,
    mesh: Mesh,
    axis: str = "data",
    threshold: int = ZERO_THRESHOLD,
    moment_bytes_per_param: int = 8,
) -> dict:
    """Per-device Adam-state HBM estimate from the rule table: mu + nu
    per parameter leaf (f32, ``moment_bytes_per_param`` = 2 x 4 bytes),
    divided by each leaf's shard count — replicated-over-data vs
    ZeRO-sharded.  Pure accounting (eval_shape trees in, bytes out); the
    ``ddl_tpu bench`` HBM column and the ``opt_hbm_bytes`` obs gauge
    read it."""
    replicated = zero = 0.0
    leaves = sharded = 0
    for _name, leaf, spec, _pat in table.provenance(abstract_params):
        shape = getattr(leaf, "shape", ())
        size = math.prod(shape) if shape else 1
        bytes_ = size * moment_bytes_per_param
        leaves += 1
        replicated += bytes_ / spec_num_shards(spec, mesh)
        zspec = zero_shard_spec(spec, shape, mesh, axis, threshold)
        if zspec is not None:
            sharded += 1
            zero += bytes_ / spec_num_shards(zspec, mesh)
        else:
            zero += bytes_ / spec_num_shards(spec, mesh)
    return {
        "replicated_bytes": int(replicated),
        "zero_bytes": int(zero),
        "dp": mesh.shape.get(axis, 1),
        "leaves": leaves,
        "zero_sharded_leaves": sharded,
    }
