"""In-place ring-buffer updates for pipeline schedules.

Every pipeline schedule in this package keeps stage-input residency and
per-microbatch accumulators in ring buffers carried through ``lax.scan``.
Ticks outside the valid range must leave the buffer untouched — but a
full-buffer ``jnp.where(valid, updated, old)`` forces XLA to read and
write the whole buffer every tick, doubling its HBM traffic.  Selecting
at *slot* granularity instead (invalid ticks re-write the slot with its
own old value) keeps the carry update in-place: XLA sees a plain
dynamic-update-slice on the scan carry and aliases it.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

__all__ = ["masked_slot_update", "masked_slice_update"]


def masked_slot_update(buf, value, idx, valid):
    """``buf[idx] = value if valid else buf[idx]`` along axis 0, in place.

    ``idx`` is clamped by XLA's dynamic-slice semantics, so out-of-range
    schedule indices are safe as long as ``valid`` masks them.
    """
    old = lax.dynamic_index_in_dim(buf, idx, 0, keepdims=False)
    return lax.dynamic_update_index_in_dim(
        buf, jnp.where(valid, value.astype(buf.dtype), old), idx, 0
    )


def masked_slice_update(buf, value, start, valid):
    """N-d variant: ``buf[start : start+value.shape] = value`` when valid."""
    old = lax.dynamic_slice(buf, start, value.shape)
    return lax.dynamic_update_slice(
        buf, jnp.where(valid, value.astype(buf.dtype), old), start
    )
