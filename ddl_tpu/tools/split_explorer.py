"""Pipeline split-point explorer (reference ``debug.py`` equivalent).

The reference explored candidate FX split specs offline, printing per-stage
parameter counts and recording which splits failed — mid-denseblock cuts
break on DenseNet's concatenative skip connections (``debug.py:9-18``) and a
4-stage split regressed epoch time (``debug.py:20-29``).  Here splits are
*constructive* (block boundaries only, so the failure mode cannot occur) and
the explorer reports, for every legal ``split_blocks`` choice at a given
stage count: per-stage parameter counts, per-stage forward FLOP estimates
(the quantity that actually balances a pipeline — DenseNet's late blocks
hold most params but early blocks, at high resolution, most FLOPs), and the
boundary-activation bytes each cut ships over ICI per microbatch.

    python -m ddl_tpu.tools.split_explorer --stages 2 --image-size 224
"""

from __future__ import annotations

import argparse
import itertools

import jax
import jax.numpy as jnp

from ddl_tpu.config import ModelConfig
from ddl_tpu.models import build_stages, count_params, stage_boundary_shapes


def _stage_costs(cfg: ModelConfig, image_size: int):
    """Per-stage (params, flops) via abstract evaluation + XLA cost analysis."""
    stages = build_stages(cfg)
    out = []
    x = jnp.zeros((1, image_size, image_size, 3), jnp.float32)
    for stage in stages:
        variables = jax.eval_shape(
            lambda k, v, s=stage: s.init(k, v, train=False), jax.random.key(0), x
        )
        n_params = count_params(variables["params"])
        fwd = jax.jit(lambda v, y, s=stage: s.apply(v, y, train=False))
        try:
            cost = fwd.lower(variables, x).compile().cost_analysis()
            flops = float(cost.get("flops", float("nan")))
        except Exception:
            flops = float("nan")
        x = jax.eval_shape(lambda v, y, s=stage: s.apply(v, y, train=False), variables, x)
        out.append((n_params, flops))
    return out


def explore(num_stages: int, image_size: int, microbatch: int, cfg: ModelConfig | None = None):
    base = cfg or ModelConfig()
    n_blocks = len(base.block_config)
    rows = []
    for splits in itertools.combinations(range(1, n_blocks), num_stages - 1):
        c = ModelConfig(
            growth_rate=base.growth_rate,
            block_config=base.block_config,
            num_init_features=base.num_init_features,
            bn_size=base.bn_size,
            num_classes=base.num_classes,
            split_blocks=splits,
            compute_dtype=base.compute_dtype,
        )
        costs = _stage_costs(c, image_size)
        boundaries = stage_boundary_shapes(c, image_size)
        rows.append(
            {
                "split_blocks": splits,
                "stage_params": [p for p, _ in costs],
                "stage_flops": [f for _, f in costs],
                "boundary_bytes_per_microbatch": [
                    microbatch * h * w * ch * 2 for (h, w, ch) in boundaries  # bf16
                ],
            }
        )
    return rows


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--stages", type=int, default=2)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--microbatch", type=int, default=6)
    args = ap.parse_args(argv)
    rows = explore(args.stages, args.image_size, args.microbatch)
    for r in rows:
        total_f = sum(f for f in r["stage_flops"])
        balance = (
            max(r["stage_flops"]) / (total_f / len(r["stage_flops"]))
            if total_f == total_f  # not NaN
            else float("nan")
        )
        print(
            f"split_blocks={r['split_blocks']}: params={r['stage_params']} "
            f"flops={[f'{f:.3g}' for f in r['stage_flops']]} "
            f"flop_imbalance={balance:.2f} "
            f"boundary_bytes/mb={r['boundary_bytes_per_microbatch']}"
        )


if __name__ == "__main__":
    main()
