"""Cluster smoke test (reference ``test.py``): bring up the mesh, print the
world layout, and run one collective over each mesh axis.

Where the reference prints rank/world/device-name and all_reduces over the
``pp`` subgroup on a live 3x2 NCCL cluster (``test.py:8-30``), this checks
the same plumbing on whatever devices are present: builds the ``(data,pipe)``
mesh, runs a ``psum`` over each axis inside ``shard_map``, and verifies the
result against the closed form.

    python -m ddl_tpu.tools.smoke --data 3 --pipe 2
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ddl_tpu.launch import bootstrap, world_info
from ddl_tpu.parallel.mesh import DATA_AXIS, PIPE_AXIS, MeshSpec, build_mesh


def run_smoke(data: int, pipe: int) -> bool:
    info = world_info()
    print(f"[smoke] world: {info}")
    mesh = build_mesh(MeshSpec(data, pipe))
    print(f"[smoke] mesh: {mesh}")

    n = data * pipe

    @jax.jit
    @jax.shard_map(
        mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(DATA_AXIS), check_vma=False
    )
    def axis_sums(x):
        d = lax.axis_index(DATA_AXIS)
        p = lax.axis_index(PIPE_AXIS)
        flat = d * pipe + p
        return (
            x
            + lax.psum(jnp.float32(flat), PIPE_AXIS)
            + lax.psum(jnp.float32(flat), DATA_AXIS)
        )

    out = np.asarray(axis_sums(jnp.zeros((n,), jnp.float32)))
    ok = True
    for d in range(data):
        for p in range(pipe):
            flat = d * pipe + p
            pipe_sum = sum(d * pipe + q for q in range(pipe))
            data_sum = sum(e * pipe + p for e in range(data))
            expected = pipe_sum + data_sum
            # each data-row block of the output holds that row's value
            block = out[d * (n // data) : (d + 1) * (n // data)]
            if not np.allclose(block, block[0]):
                ok = False
            if p == 0 and not np.isclose(block[0], expected):
                print(f"[smoke] mismatch at (d={d},p={p}): {block[0]} != {expected}")
                ok = False
    print(f"[smoke] psum over '{PIPE_AXIS}' and '{DATA_AXIS}': {'OK' if ok else 'FAIL'}")
    return ok


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", type=int, default=1)
    ap.add_argument("--pipe", type=int, default=1)
    args = ap.parse_args(argv)
    bootstrap()
    if not run_smoke(args.data, args.pipe):
        raise SystemExit(1)


if __name__ == "__main__":
    main()
