"""Build a byte-level LM corpus from a source tree.

The committed learning-evidence runs through round 3 were synthetic-only
(Markov byte streams, statistics-learnable vision labels).  This tool
turns any code/doc tree — by default this repository itself — into a real
text corpus for the byte-level LM (``data/lm_corpus.encode_text_file``
reads plain text; vocab 256 covers it by construction), giving an offline
environment honest held-out-perplexity curves on real data.

    python -m ddl_tpu.tools.repo_corpus --out /tmp/repo_corpus.txt
    python examples/train_lm.py --corpus /tmp/repo_corpus.txt --eval-every 25 ...

Files are concatenated in sorted order with a path header line, so the
corpus is deterministic for a given tree and the model sees file
boundaries as text structure (the header is itself learnable context).
"""

from __future__ import annotations

import argparse
from pathlib import Path

# source + doc extensions; binaries and generated artifacts are skipped
EXTS = {".py", ".md", ".cpp", ".cc", ".h", ".hpp", ".toml", ".txt",
        ".json", ".sh", ".yaml", ".yml", ".cfg", ".ini"}
SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "checkpoints",
             "training_logs", "node_modules", ".venv", "venv"}


def iter_files(root: Path):
    for p in sorted(root.rglob("*")):
        if not p.is_file() or p.suffix.lower() not in EXTS:
            continue
        # skip-list applies to directories INSIDE the tree, not to the
        # root's own ancestors (harvesting a tree that happens to live
        # under e.g. a venv must work)
        if any(part in SKIP_DIRS for part in p.relative_to(root).parts):
            continue
        yield p


def build_corpus(root: Path, out: Path, max_bytes: int = 0) -> int:
    total = 0
    with out.open("wb") as f:
        for p in iter_files(root):
            try:
                data = p.read_bytes()
            except OSError:
                continue
            header = f"\n===== {p.relative_to(root)} =====\n".encode()
            f.write(header)
            f.write(data)
            total += len(header) + len(data)
            if max_bytes and total >= max_bytes:
                break
    return total


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=".",
                    help="tree to harvest (default: current directory)")
    ap.add_argument("--out", required=True, help="output text file")
    ap.add_argument("--max-bytes", type=int, default=0,
                    help="stop after this many bytes (0 = everything)")
    args = ap.parse_args()
    n = build_corpus(Path(args.root), Path(args.out), args.max_bytes)
    print(f"wrote {n} bytes to {args.out}")


if __name__ == "__main__":
    main()
