"""Compiled-IR lint: collective/memory inventory over lowered programs.

The AST rules see source and the contract probes see *traces* — neither
sees what GSPMD actually emitted.  The bugs that cost chips live one
layer lower: an accidental all-gather of a ZeRO-sharded moment, a lost
donation alias, a ppermute regression in the zb schedule, a decode step
that quietly copies the whole KV pool.  This module closes that gap
with zero chips: every contract probe program is lowered (and, where
the CPU backend can, compiled) on its simulated mesh, the
StableHLO/optimized-HLO **text** is parsed into a structured
inventory, a small rule family runs over it, and the inventory is
drift-gated against a committed ``HLO_BASELINE.json``.

Inventory per program (JSON-stable, the baseline unit):

* ``collectives`` — per-kind counts and payload bytes (all-reduce /
  all-gather / reduce-scatter / collective-permute / all-to-all),
  keyed by the **mesh axes the replica groups span** (``all-gather@data``)
  — replica groups are decoded from both the explicit ``{{0,2},{1,3}}``
  and the iota ``[4,2]<=[8]`` / ``T(perm)`` forms and mapped back to
  mesh coordinates;
* ``permutes`` — collective-permute source→target pair sets (the
  pipeline boundary rings), kept exactly for the symmetry rule;
* ``mem`` — transpose/copy/convert counts, total and max payload bytes;
* ``aliases`` — donation aliasing pairs (``input_output_alias``), plus
  ``donation`` effectiveness (aliased bytes / donatable bytes);
* ``fingerprint`` — a shape-normalized structural hash of the lowered
  StableHLO (the dialect-op token stream), equal across batch sizes for
  a shape-generic program — the two-shape lowering diff that catches
  recompile hazards the AST rules can't see.

Rule family (absolute — no baseline needed):

* ``oversized-all-gather`` — a ≥threshold-element data-axis all-gather
  in a ZeRO program whose output shape is not one of the gather shapes
  the rule table derives for eligible leaves
  (``parallel/rules.zero_gather_plan``);
* ``zero-missing-reduce-scatter`` — a ZeRO-eligible leaf with no
  evidence of the scatter→update→gather cycle: neither a literal
  reduce-scatter nor a data-axis all-gather producing the leaf's
  gather shape (XLA:CPU lowers reduce-scatter to
  all-reduce+dynamic-slice, so the gather side is the portable
  evidence);
* ``pipeline-collective-symmetry`` — the collective-permute pair sets
  of a pipeline program must be closed under inversion (every forward
  boundary ring has the matching reverse ring) and each must be a
  bijection over the stage boundary;
* ``steady-state-copy-hotspot`` — a single copy in a decode/serve
  program at least as large as the whole KV pool (the paged pool
  degenerating to copy-per-step);
* ``shape-specialized-constant`` — the two-shape structural
  fingerprints differ: some op count or structure depends on the batch
  size, so every new batch shape is a recompile.

Drift gates (vs ``HLO_BASELINE.json``, ``LINT_BASELINE.json``
semantics: shrink-only, stale entries reported, ``--update-baseline``
rewrites): a **new** collective key, a collective **count** increase, a
>10% payload-**bytes** increase, a **lost** donation alias, and >10%
copy-bytes growth in a steady-state program each fail ``lint --hlo``
with a ``file:probe:op`` finding; shrinks are reported stale so the
baseline only ever shrinks through an intentional rewrite.

The text parsers are pure (no JAX import) so the fixture tests under
``tests/lint_fixtures/hlo/`` run in milliseconds; the probe registry
imports JAX lazily and reuses the contract probes' builders — one tiny
model zoo, no drift between the trace-level and IR-level gates.
"""

from __future__ import annotations

import dataclasses
import hashlib
import inspect
import json
import math
import re
from pathlib import Path

from ddl_tpu.analysis.findings import Finding

__all__ = [
    "HLO_RULES",
    "HloLintResult",
    "ProgramInventory",
    "affected_probes",
    "build_inventories",
    "diff_baseline",
    "load_hlo_baseline",
    "parse_hlo_ops",
    "parse_replica_groups",
    "parse_stablehlo_ops",
    "parse_aliases",
    "probe_names",
    "run_hlo_lint",
    "save_hlo_baseline",
    "structural_fingerprint",
]

HLO_RULES = (
    "oversized-all-gather",
    "zero-missing-reduce-scatter",
    "pipeline-collective-symmetry",
    "steady-state-copy-hotspot",
    "shape-specialized-constant",
)

# payload growth tolerated before drift fails (the ISSUE's 10%)
DRIFT_BYTES_RATIO = 1.10

# smallest data-axis all-gather the oversized rule flags: leaves under
# this never rate ZeRO sharding, and sub-floor gathers in a compiled
# step are activation resharding rather than re-materialised state
OVERSIZED_GATHER_ELEMS = 8192

_COLLECTIVE_KINDS = (
    "all-reduce",
    "all-gather",
    "reduce-scatter",
    "collective-permute",
    "all-to-all",
)
_MEM_KINDS = ("copy", "transpose", "convert")

_ITEMSIZE = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
    "c64": 8, "c128": 16, "token": 0, "opaque": 0,
}

# ---------------------------------------------------------------------------
# pure text parsing — optimized HLO
# ---------------------------------------------------------------------------

_SHAPE_RE = re.compile(r"(\w+)\[([0-9,]*)\]")
# one HLO instruction head: `%name = <shape-or-tuple> opcode(` — the
# shape may be a tuple `(f32[..]{..}, u32[..]{..})`; capture lazily up
# to the opcode token
_HLO_OP_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\([^=]*?\)|\S+)\s+([\w\-]+)\(",
    re.M,
)
_OP_NAME_RE = re.compile(r'metadata=\{[^}]*?op_name="([^"]+)"')
_REPLICA_GROUPS_RE = re.compile(
    r"replica_groups=(\{\{[\d,{} ]*\}\}|\[[\d,]+\]<=\[[\d,]+\](?:T\([\d,]+\))?)"
)
# collective-permute carries pairs, not groups — same {{s,t},...} shape
_SOURCE_TARGET_RE = re.compile(r"source_target_pairs=(\{\{[\d,{} ]*\}\})")
_PARAM_RE = re.compile(
    r"^\s*%?[\w.\-]+\s*=\s*(\S+)\s+parameter\((\d+)\)", re.M
)
# entries end with `)`, so the block closes at the last `)}` — a plain
# lazy-to-`}` match would stop inside the first entry's empty `{}` index
_ALIAS_BLOCK_RE = re.compile(r"input_output_alias=\{(.*?\))\s*\}")
_ALIAS_ENTRY_RE = re.compile(
    r"\{([\d, ]*)\}:\s*\((\d+),\s*\{([\d, ]*)\}(?:,\s*([\w\-]+))?\)"
)


def _shape_dims(shape_text: str) -> tuple[str, tuple[int, ...]] | None:
    m = _SHAPE_RE.match(shape_text)
    if m is None:
        return None
    dtype, dims = m.groups()
    return dtype, tuple(int(d) for d in dims.split(",") if d)


def shape_bytes(shape_text: str) -> int:
    """Total bytes of one HLO shape string — a plain ``f32[8,64]{1,0}``
    or a tuple ``(f32[8]{0}, u32[2]{0})`` (summed)."""
    total = 0
    for m in _SHAPE_RE.finditer(shape_text):
        dtype, dims = m.groups()
        n = math.prod(int(d) for d in dims.split(",") if d) if dims else 1
        total += n * _ITEMSIZE.get(dtype, 4)
    return total


def shape_elems(shape_text: str) -> int:
    parsed = _shape_dims(shape_text)
    if parsed is None:
        return 0
    return math.prod(parsed[1]) if parsed[1] else 1


def _iota_replica_groups(
    dims: list[int], reshape: list[int], perm: list[int] | None
) -> list[list[int]]:
    """Decode the iota replica-group form ``[d0,d1]<=[r0,...](T(p...))?``:
    arange(prod(r)).reshape(r).transpose(p).reshape(d) → rows."""
    n = math.prod(reshape)
    if perm is None:
        flat = list(range(n))
    else:
        shape_t = [reshape[p] for p in perm]
        # strides of the ORIGINAL (row-major) layout, permuted
        strides = [1] * len(reshape)
        for i in range(len(reshape) - 2, -1, -1):
            strides[i] = strides[i + 1] * reshape[i + 1]
        strides_t = [strides[p] for p in perm]
        flat = []
        idx = [0] * len(shape_t)
        for _ in range(n):
            flat.append(sum(i * s for i, s in zip(idx, strides_t)))
            for d in range(len(shape_t) - 1, -1, -1):
                idx[d] += 1
                if idx[d] < shape_t[d]:
                    break
                idx[d] = 0
    group_size = dims[-1] if dims else n
    return [
        flat[i:i + group_size] for i in range(0, len(flat), group_size)
    ]


def parse_replica_groups(text: str) -> list[list[int]]:
    """Decode one ``replica_groups=`` value — explicit ``{{0,2},{1,3}}``
    or iota ``[4,2]<=[8]`` / ``[2,4]<=[2,2,2]T(1,0,2)``."""
    text = text.strip()
    if text.startswith("{"):
        return [
            [int(x) for x in grp.split(",") if x.strip()]
            for grp in re.findall(r"\{([\d, ]+)\}", text)
        ]
    m = re.match(r"\[([\d,]+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?", text)
    if m is None:
        return []
    dims = [int(d) for d in m.group(1).split(",")]
    reshape = [int(d) for d in m.group(2).split(",")]
    perm = (
        [int(d) for d in m.group(3).split(",")] if m.group(3) else None
    )
    return _iota_replica_groups(dims, reshape, perm)


def group_axes(
    groups: list[list[int]], mesh_axes: list[tuple[str, int]]
) -> str:
    """Which mesh axes the replica groups span, as a stable label
    (``"data"``, ``"data+model"``; ``"none"`` for singleton groups,
    ``"devices"`` when no mesh is known).  Device id → coordinates is
    row-major over the probe mesh axis order, which is how
    ``build_mesh`` lays simulated devices out."""
    if not mesh_axes:
        return "devices"
    sizes = [s for _, s in mesh_axes]
    varying: set[int] = set()
    for grp in groups:
        coords = []
        for dev in grp:
            c = []
            rem = dev
            for s in reversed(sizes):
                c.append(rem % s)
                rem //= s
            coords.append(tuple(reversed(c)))
        for i in range(len(sizes)):
            if len({c[i] for c in coords}) > 1:
                varying.add(i)
    if not varying:
        return "none"
    return "+".join(mesh_axes[i][0] for i in sorted(varying))


@dataclasses.dataclass
class HloOp:
    """One parsed collective/memory instruction."""

    kind: str
    shape: str  # output shape text
    bytes: int
    dims: tuple[int, ...] | None
    groups: list[list[int]]
    op_name: str  # JAX provenance from metadata
    line: str  # raw instruction text (for findings)


def parse_hlo_ops(text: str) -> list[HloOp]:
    """Every collective and memory-traffic instruction of an optimized
    HLO module text.  Async pairs are normalised: ``-start`` variants
    count as the op, ``-done`` halves are skipped."""
    ops: list[HloOp] = []
    for m in _HLO_OP_RE.finditer(text):
        shape, opcode = m.groups()
        kind = opcode[:-6] if opcode.endswith("-start") else opcode
        if kind not in _COLLECTIVE_KINDS and kind not in _MEM_KINDS:
            continue
        if opcode.endswith("-done"):
            continue
        line_end = text.find("\n", m.start())
        line = text[m.start():line_end if line_end != -1 else len(text)]
        rg = _REPLICA_GROUPS_RE.search(line) or _SOURCE_TARGET_RE.search(line)
        groups = parse_replica_groups(rg.group(1)) if rg else []
        name = _OP_NAME_RE.search(line)
        parsed = _shape_dims(shape.lstrip("("))
        ops.append(HloOp(
            kind=kind,
            shape=shape,
            bytes=shape_bytes(shape),
            dims=parsed[1] if parsed else None,
            groups=groups,
            op_name=name.group(1) if name else "",
            line=line.strip(),
        ))
    return ops


def parse_aliases(text: str) -> list[tuple[str, int, str]]:
    """Donation aliasing pairs from a compiled module's
    ``input_output_alias={ {0}: (0, {}, may-alias), ... }`` header:
    ``(output_index, param_number, param_index)``."""
    block = _ALIAS_BLOCK_RE.search(text)
    if block is None:
        return []
    return sorted(
        (out.replace(" ", ""), int(param), pidx.replace(" ", ""))
        for out, param, pidx, _kind in _ALIAS_ENTRY_RE.findall(block.group(1))
    )


def parse_param_bytes(text: str) -> dict[int, int]:
    """``parameter(N)`` instruction shapes → bytes, for alias-payload
    accounting (jit flattens pytrees, so leaves are numbered params)."""
    out: dict[int, int] = {}
    for m in _PARAM_RE.finditer(text):
        shape, num = m.groups()
        out.setdefault(int(num), shape_bytes(shape))
    return out


# ---------------------------------------------------------------------------
# pure text parsing — lowered StableHLO
# ---------------------------------------------------------------------------

_FP_TOKEN_RE = re.compile(r"(?:stablehlo|func|sdy|mhlo|chlo)\.[\w.]+")
_SHLO_PERMUTE_RE = re.compile(
    r"stablehlo\.collective_permute\"?[^\n]*?source_target_pairs\s*=\s*"
    r"dense<\[?\[([\d\], \[]+)\]?\]>[^\n]*?->\s*tensor<([\dx]+x)?(\w+)>"
)
_SHLO_COLLECTIVE_RE = re.compile(
    r"stablehlo\.(all_reduce|all_gather|reduce_scatter|all_to_all|"
    r"collective_permute)\b"
)


def structural_fingerprint(stablehlo_text: str) -> str:
    """Shape-normalized structural hash of a lowered module: the ordered
    dialect-op token stream, minus ``stablehlo.constant`` (constant
    hoisting order tracks batch-derived *values*, not structure).  Equal
    across batch sizes for a shape-generic program; any structural
    specialization (an op count that tracks the batch dim) changes
    it."""
    tokens = " ".join(
        t for t in _FP_TOKEN_RE.findall(stablehlo_text)
        if t != "stablehlo.constant"
    )
    return hashlib.sha256(tokens.encode()).hexdigest()


def parse_stablehlo_ops(text: str) -> tuple[dict[str, int], list[dict]]:
    """Collective census of a lowered StableHLO module: per-op counts
    (names normalised to the HLO spellings) and the collective_permute
    pair sets with payload bytes — the level the pipeline programs are
    inventoried at when the CPU backend cannot compile them
    (PartitionId is unimplemented for SPMD on XLA:CPU)."""
    counts: dict[str, int] = {}
    for m in _SHLO_COLLECTIVE_RE.finditer(text):
        kind = m.group(1).replace("_", "-")
        kind = {"all-to-all": "all-to-all"}.get(kind, kind)
        counts[kind] = counts.get(kind, 0) + 1
    permutes: list[dict] = []
    for m in _SHLO_PERMUTE_RE.finditer(text):
        nums = [int(x) for x in re.findall(r"\d+", m.group(1))]
        pairs = [
            [nums[i], nums[i + 1]] for i in range(0, len(nums) - 1, 2)
        ]
        dims_txt, dtype = m.group(2) or "", m.group(3)
        n = math.prod(
            int(d) for d in dims_txt.rstrip("x").split("x") if d
        ) if dims_txt else 1
        permutes.append({
            "pairs": pairs,
            "bytes": n * _ITEMSIZE.get(dtype, 4),
        })
    return counts, permutes


# ---------------------------------------------------------------------------
# inventory construction
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ProgramSpec:
    """One lowered probe program plus the facts the rules need."""

    name: str
    lowered: object  # jax .lower() result: .compile(), .as_text()
    path: str  # factory source, repo-relative (finding attribution)
    line: int
    mesh_axes: list[tuple[str, int]]
    alt_lowered: object | None = None  # second-shape lowering
    zero_plan: dict | None = None  # rules.zero_gather_plan output
    pool_bytes: int | None = None  # steady-state KV pool/state bytes
    pipeline: bool = False
    donatable_bytes: int | None = None


@dataclasses.dataclass
class ProgramInventory:
    spec: ProgramSpec
    data: dict  # the JSON-stable baseline entry
    ops: list[HloOp]  # per-op detail (rules only; not baselined)
    permutes: list[dict]
    notes: list[str]


def _aggregate(ops: list[HloOp], mesh_axes) -> tuple[dict, dict]:
    collectives: dict[str, dict] = {}
    mem: dict[str, dict] = {}
    for op in ops:
        if op.kind in _COLLECTIVE_KINDS:
            key = f"{op.kind}@{group_axes(op.groups, mesh_axes)}"
            ent = collectives.setdefault(key, {"count": 0, "bytes": 0})
        else:
            ent = mem.setdefault(
                op.kind, {"count": 0, "bytes": 0, "max_bytes": 0}
            )
            ent["max_bytes"] = max(ent["max_bytes"], op.bytes)
        ent["count"] += 1
        ent["bytes"] += op.bytes
    return collectives, mem


def build_inventory(spec: ProgramSpec) -> ProgramInventory:
    """Lower→compile→parse one program; falls back to the StableHLO
    census when the simulated backend cannot compile it."""
    notes: list[str] = []
    shlo = spec.lowered.as_text()
    fingerprint = structural_fingerprint(shlo)
    two_shape = None
    if spec.alt_lowered is not None:
        alt_fp = structural_fingerprint(spec.alt_lowered.as_text())
        two_shape = "equal" if alt_fp == fingerprint else "differs"
    try:
        compiled_text = spec.lowered.compile().as_text()
        level = "hlo"
    except Exception as e:
        compiled_text = None
        level = "stablehlo"
        notes.append(
            f"{spec.name}: compiled-HLO inventory unavailable on this "
            f"backend ({type(e).__name__}: {str(e).splitlines()[0][:120]}); "
            "inventoried at the StableHLO level"
        )
    if compiled_text is not None:
        ops = parse_hlo_ops(compiled_text)
        collectives, mem = _aggregate(ops, spec.mesh_axes)
        aliases = parse_aliases(compiled_text)
        param_bytes = parse_param_bytes(compiled_text)
        aliased = sum(
            param_bytes.get(p, 0) for _out, p, pidx in aliases if pidx == ""
        )
        permutes = [
            {"pairs": [list(g[:2]) for g in op.groups], "bytes": op.bytes}
            for op in ops if op.kind == "collective-permute"
        ]
    else:
        ops = []
        counts, permutes = parse_stablehlo_ops(shlo)
        collectives = {
            f"{kind}@manual": {"count": n, "bytes": 0}
            for kind, n in sorted(counts.items())
            if kind != "collective-permute"
        }
        for p in permutes:
            key = "collective-permute@manual"
            ent = collectives.setdefault(key, {"count": 0, "bytes": 0})
            ent["count"] += 1
            ent["bytes"] += p["bytes"]
        mem = {}
        aliases = []
        aliased = 0
    donation = None
    if spec.donatable_bytes:
        donation = {
            "aliased_bytes": aliased,
            "donatable_bytes": spec.donatable_bytes,
        }
    data = {
        "level": level,
        "mesh": [[name, size] for name, size in spec.mesh_axes],
        "collectives": collectives,
        "mem": mem,
        "aliases": [list(a) for a in aliases],
        "donation": donation,
        "fingerprint": fingerprint,
        "two_shape": two_shape,
    }
    # permute pair-set summary is baselined too (symmetry regressions
    # that keep counts/bytes equal still show here)
    data["permutes"] = sorted(
        {json.dumps(sorted(map(tuple, p["pairs"]))) for p in permutes}
    )
    return ProgramInventory(
        spec=spec, data=data, ops=ops, permutes=permutes, notes=notes
    )


# ---------------------------------------------------------------------------
# the rule family
# ---------------------------------------------------------------------------


def _finding(spec: ProgramSpec, rule: str, msg: str) -> Finding:
    return Finding(spec.path, spec.line, rule, f"{spec.name}: {msg}")


def _rule_zero(inv: ProgramInventory) -> list[Finding]:
    """oversized-all-gather + zero-missing-reduce-scatter over a ZeRO
    program's data-axis gathers, against the gather geometry the rule
    table derives (``zero_gather_plan``)."""
    spec = inv.spec
    plan = spec.zero_plan
    findings: list[Finding] = []
    if plan is None or inv.data["level"] != "hlo":
        return findings
    # the oversized flag keeps the ISSUE floor even when the probe's
    # resolved ZeRO threshold is tiny: sub-floor data-axis gathers are
    # activation resharding (jvp/transpose provenance), not state
    floor = max(plan["threshold"] or 0, OVERSIZED_GATHER_ELEMS)
    allowed = {tuple(s) for s in plan["gather_shapes"]}
    allowed |= {tuple(s) for s in plan["leaf_shard_shapes"]}
    seen_gather_shapes: set[tuple[int, ...]] = set()
    has_reduce_scatter = False
    for op in inv.ops:
        axes = group_axes(op.groups, spec.mesh_axes)
        if "data" not in axes.split("+"):
            continue
        if op.kind == "reduce-scatter":
            has_reduce_scatter = True
        if op.kind != "all-gather" or op.dims is None:
            continue
        seen_gather_shapes.add(op.dims)
        if math.prod(op.dims) < floor:
            continue
        if op.dims not in allowed:
            findings.append(_finding(
                spec, "oversized-all-gather",
                f"data-axis all-gather produces {op.shape} "
                f"({math.prod(op.dims)} elements) but no ZeRO-eligible "
                f"leaf gathers at that shape (op_name "
                f"{op.op_name!r}); an un-constrained gather re-"
                "materialises state the update should touch shard-wise",
            ))
    for leaf in plan["eligible"]:
        gshape = tuple(leaf["gather_shape"])
        if gshape in seen_gather_shapes or has_reduce_scatter:
            continue
        findings.append(_finding(
            spec, "zero-missing-reduce-scatter",
            f"eligible leaf {leaf['name']} ({leaf['size']} elements) "
            f"shows no scatter→update→gather cycle: no reduce-scatter "
            f"and no data-axis all-gather producing its gather shape "
            f"{gshape} — the update is running replicated",
        ))
    return findings


def _rule_pipeline_symmetry(inv: ProgramInventory) -> list[Finding]:
    spec = inv.spec
    if not spec.pipeline:
        return []
    findings: list[Finding] = []
    pair_sets = []
    for p in inv.permutes:
        pairs = sorted(tuple(pr[:2]) for pr in p["pairs"])
        pair_sets.append(pairs)
        sources = [s for s, _t in pairs]
        targets = [t for _s, t in pairs]
        if len(set(sources)) != len(sources) or len(set(targets)) != len(
            targets
        ):
            findings.append(_finding(
                spec, "pipeline-collective-symmetry",
                f"collective-permute pair set {pairs} is not a bijection "
                "over the stage boundary (duplicated source or target)",
            ))
    if not pair_sets:
        findings.append(_finding(
            spec, "pipeline-collective-symmetry",
            "pipeline program contains no collective-permute: the stage "
            "boundary ring is gone (stages are exchanging activations "
            "through replicated memory, or the schedule collapsed)",
        ))
        return findings
    multiset = {}
    for pairs in pair_sets:
        multiset[json.dumps(pairs)] = multiset.get(json.dumps(pairs), 0) + 1
    for pairs in pair_sets:
        inverse = json.dumps(sorted((t, s) for s, t in pairs))
        if multiset.get(inverse, 0) == 0:
            findings.append(_finding(
                spec, "pipeline-collective-symmetry",
                f"collective-permute pair set {pairs} has no inverse "
                "partner: the forward/backward boundary rings are "
                "asymmetric across stages",
            ))
    return findings


def _rule_copy_hotspot(inv: ProgramInventory) -> list[Finding]:
    spec = inv.spec
    if spec.pool_bytes is None or inv.data["level"] != "hlo":
        return []
    copy = inv.data["mem"].get("copy")
    if not copy or copy["max_bytes"] < spec.pool_bytes:
        return []
    return [_finding(
        spec, "steady-state-copy-hotspot",
        f"a single copy moves {copy['max_bytes']} bytes — at least the "
        f"whole KV pool ({spec.pool_bytes} bytes) — every step; the "
        "paged pool update has degenerated to a full-pool copy",
    )]


def _rule_two_shape(inv: ProgramInventory) -> list[Finding]:
    if inv.data.get("two_shape") != "differs":
        return []
    return [_finding(
        inv.spec, "shape-specialized-constant",
        "lowering at a second batch shape changes the structural "
        "fingerprint: some op structure is specialized on the batch "
        "dimension, so every new shape is a full recompile (a hazard "
        "the AST recompile rules cannot see)",
    )]


def apply_rules(inv: ProgramInventory) -> list[Finding]:
    findings = []
    findings += _rule_zero(inv)
    findings += _rule_pipeline_symmetry(inv)
    findings += _rule_copy_hotspot(inv)
    findings += _rule_two_shape(inv)
    return findings


# ---------------------------------------------------------------------------
# baseline: shrink-only / stale-entry semantics, HLO_BASELINE.json
# ---------------------------------------------------------------------------


def load_hlo_baseline(path: str | Path) -> dict:
    data = json.loads(Path(path).read_text())
    return data.get("programs", {})


def save_hlo_baseline(path: str | Path, programs: dict) -> None:
    payload = {"version": 1, "programs": programs}
    Path(path).write_text(
        json.dumps(payload, indent=1, sort_keys=True) + "\n"
    )


def diff_baseline(
    inventories: dict[str, ProgramInventory],
    baseline: dict,
    scope: set[str] | None = None,
) -> tuple[list[Finding], list[str]]:
    """Drift findings + stale notes.  ``scope`` (``lint --changed``)
    restricts the comparison to those program names: out-of-scope
    baseline entries are neither matched nor reported stale."""
    findings: list[Finding] = []
    stale: list[str] = []
    for name, inv in sorted(inventories.items()):
        spec = inv.spec
        base = baseline.get(name)
        if base is None:
            findings.append(_finding(
                spec, "hlo-unbaselined-program",
                "program has no HLO_BASELINE.json entry; run "
                "`ddl_tpu lint --hlo --update-baseline` to commit its "
                "inventory",
            ))
            continue
        cur_c = inv.data["collectives"]
        base_c = base.get("collectives", {})
        for key, ent in sorted(cur_c.items()):
            bent = base_c.get(key)
            if bent is None:
                findings.append(_finding(
                    spec, "hlo-drift-new-collective",
                    f"new collective {key} (count {ent['count']}, "
                    f"{ent['bytes']} bytes) not in the committed "
                    "baseline",
                ))
            elif ent["count"] > bent["count"]:
                findings.append(_finding(
                    spec, "hlo-drift-collective-count",
                    f"{key} count grew {bent['count']} -> {ent['count']}",
                ))
            elif ent["bytes"] > bent["bytes"] * DRIFT_BYTES_RATIO:
                findings.append(_finding(
                    spec, "hlo-drift-collective-bytes",
                    f"{key} payload grew {bent['bytes']} -> "
                    f"{ent['bytes']} bytes (>10%)",
                ))
            elif ent["count"] < bent["count"] or ent["bytes"] < bent["bytes"]:
                stale.append(
                    f"{name}: {key} shrank "
                    f"(count {bent['count']}->{ent['count']}, bytes "
                    f"{bent['bytes']}->{ent['bytes']}) — run "
                    "--update-baseline to bank the improvement"
                )
        for key in sorted(set(base_c) - set(cur_c)):
            stale.append(
                f"{name}: baseline collective {key} no longer emitted — "
                "run --update-baseline"
            )
        cur_aliases = {tuple(a) for a in inv.data["aliases"]}
        for a in base.get("aliases", []):
            if tuple(a) not in cur_aliases:
                findings.append(_finding(
                    spec, "hlo-drift-lost-alias",
                    f"donation alias {tuple(a)} present in the baseline "
                    "is gone from the compiled program: a donated buffer "
                    "stopped aliasing its output (state HBM doubles "
                    "across the update)",
                ))
        for a in sorted(cur_aliases - {tuple(a) for a in base.get("aliases", [])}):
            stale.append(
                f"{name}: new donation alias {a} not in the baseline — "
                "run --update-baseline to bank it"
            )
        if spec.pool_bytes is not None:
            cur_copy = inv.data["mem"].get("copy", {})
            base_copy = base.get("mem", {}).get("copy", {})
            if base_copy and cur_copy.get("bytes", 0) > base_copy.get(
                "bytes", 0
            ) * DRIFT_BYTES_RATIO:
                findings.append(_finding(
                    spec, "hlo-drift-copy-bytes",
                    f"steady-state copy traffic grew "
                    f"{base_copy['bytes']} -> {cur_copy['bytes']} bytes "
                    "(>10%)",
                ))
        if not findings_for(findings, name) and base.get(
            "fingerprint"
        ) not in (None, inv.data["fingerprint"]):
            stale.append(
                f"{name}: program fingerprint changed with no inventory "
                "drift (a structural edit with identical communication) "
                "— run --update-baseline to refresh it"
            )
    for name in sorted(set(baseline) - set(inventories)):
        if scope is not None and name not in scope:
            continue
        stale.append(
            f"baseline program {name!r} is no longer probed — run "
            "--update-baseline to drop it"
        )
    return findings, stale


def findings_for(findings: list[Finding], program: str) -> list[Finding]:
    return [f for f in findings if f.message.startswith(f"{program}: ")]


# ---------------------------------------------------------------------------
# probe registry — reuses the contract probes' builders (lazy JAX)
# ---------------------------------------------------------------------------


def _src_loc(factory) -> tuple[str, int]:
    src = inspect.getsourcefile(factory)
    root = Path(__file__).resolve().parents[2]
    path = Path(src).resolve()
    try:
        rel = path.relative_to(root).as_posix()
    except ValueError:
        rel = path.as_posix()
    return rel, inspect.getsourcelines(factory)[1]


def _mesh_axes(mesh) -> list[tuple[str, int]]:
    return [(name, int(size)) for name, size in mesh.shape.items()]


def _state_bytes(state) -> int:
    import jax

    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(state)
        if hasattr(leaf, "size")
    )


def _zero_plan(contract, params, mesh) -> dict | None:
    from ddl_tpu.parallel.rules import zero_gather_plan

    table = contract.get("rule_table")
    if table is None or not contract.get("zero_sharding"):
        return None
    threshold = contract.get("zero_threshold")
    return zero_gather_plan(table, params, mesh, threshold=threshold)


def _hlo_cnn(zero: bool = False, fused: bool = False) -> list[ProgramSpec]:
    import jax
    import jax.numpy as jnp

    from ddl_tpu.analysis.contracts import _cnn_build
    from ddl_tpu.train.steps import make_dp_step_fns

    path, line = _src_loc(make_dp_step_fns)
    kwargs = (
        dict(dense_block_impl="fused", dense_block_fused_blocks=(0, 1))
        if fused else {}
    )
    fns, state, mesh = _cnn_build(zero=zero, data=4 if zero else 2, **kwargs)
    img, lbl = fns.train.probe_inputs(8)
    img2, lbl2 = fns.train.probe_inputs(16)
    name = "cnn_dp_zero" if zero else ("cnn_dp_fused" if fused else "cnn_dp")
    return [ProgramSpec(
        name=name,
        lowered=fns.train.lower(state, img, lbl),
        alt_lowered=fns.train.lower(state, img2, lbl2),
        path=path, line=line,
        mesh_axes=_mesh_axes(mesh),
        zero_plan=(
            _zero_plan(fns.train.contract, state.params, mesh)
            if zero else None
        ),
        donatable_bytes=_state_bytes(state),
    )]


def _hlo_lm(zero: bool = False) -> list[ProgramSpec]:
    import jax
    import jax.numpy as jnp

    from ddl_tpu.analysis.contracts import _tiny_lm_cfg
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    path, line = _src_loc(make_lm_step_fns)
    if zero:
        from ddl_tpu.train.fused_optim import fused_adam

        fns = make_lm_step_fns(
            _tiny_lm_cfg(), LMMeshSpec(data=4, model=2), fused_adam(1e-3),
            jax.random.key(0), batch=8, seq_len=32, zero_sharding=True,
        )
    else:
        import optax

        fns = make_lm_step_fns(
            _tiny_lm_cfg(), LMMeshSpec(data=2, model=2), optax.adam(1e-3),
            jax.random.key(0), batch=8, seq_len=32,
        )
    state = fns.init_state()
    return [ProgramSpec(
        name="lm_zero" if zero else "lm_flat",
        lowered=fns.train.lower(state, *fns.train.probe_inputs(8)),
        alt_lowered=fns.train.lower(state, *fns.train.probe_inputs(16)),
        path=path, line=line,
        mesh_axes=_mesh_axes(fns.mesh),
        zero_plan=(
            _zero_plan(fns.train.contract, state.params, fns.mesh)
            if zero else None
        ),
        donatable_bytes=_state_bytes(state),
    )]


def _hlo_lm_pipeline(schedule: str) -> list[ProgramSpec]:
    import jax
    import optax

    from ddl_tpu.analysis.contracts import _tiny_lm_cfg
    from ddl_tpu.parallel.lm_pipeline import make_lm_pipeline_step_fns
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    path, line = _src_loc(make_lm_pipeline_step_fns)
    fns = make_lm_step_fns(
        _tiny_lm_cfg(), LMMeshSpec(data=2, pipe=2, model=2),
        optax.adam(1e-3), jax.random.key(0), batch=8, seq_len=32,
        num_microbatches=4 if schedule == "zb" else 2,
        pipeline_schedule=schedule,
    )
    state = fns.init_state()
    name = "lm_pipeline_zb" if schedule == "zb" else "lm_pipeline"
    # no alt_lowered: the microbatch split bakes the committed batch
    # into the schedule's reshape, so a second batch shape does not
    # trace — shape specialization is *contractual* for pipelines
    return [ProgramSpec(
        name=name,
        lowered=fns.train.lower(state, *fns.train.probe_inputs(8)),
        path=path, line=line,
        mesh_axes=_mesh_axes(fns.mesh),
        pipeline=True,
        donatable_bytes=_state_bytes(state),
    )]


def _hlo_vit(pipeline: bool = False) -> list[ProgramSpec]:
    import jax
    import optax

    from ddl_tpu.models.vit import ViTConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.vit_steps import make_vit_step_fns

    path, line = _src_loc(make_vit_step_fns)
    cfg = ViTConfig(
        image_size=16, patch_size=8, d_model=64, n_layers=2, n_heads=4,
        head_dim=16, d_ff=256, compute_dtype="float32", remat=False,
    )
    spec = (
        LMMeshSpec(data=2, pipe=2, model=2) if pipeline
        else LMMeshSpec(data=2, model=2)
    )
    fns = make_vit_step_fns(
        cfg, spec, optax.adam(1e-3), jax.random.key(0), batch=8,
        **(dict(num_microbatches=2) if pipeline else {}),
    )
    state = fns.init_state()
    # pipeline path: batch is baked into the microbatch reshape, so
    # only the committed shape traces (see _hlo_lm_pipeline)
    return [ProgramSpec(
        name="vit_pipeline" if pipeline else "vit_flat",
        lowered=fns.train.lower(state, *fns.train.probe_inputs(8)),
        alt_lowered=(
            None if pipeline
            else fns.train.lower(state, *fns.train.probe_inputs(16))
        ),
        path=path, line=line,
        mesh_axes=_mesh_axes(fns.mesh),
        pipeline=pipeline,
        donatable_bytes=_state_bytes(state),
    )]


def _hlo_decode() -> list[ProgramSpec]:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ddl_tpu.analysis.contracts import _tiny_lm_cfg
    from ddl_tpu.infer.decode import make_lm_generator
    from ddl_tpu.models.transformer import TransformerLM
    from ddl_tpu.parallel.sharding import LMMeshSpec

    path, line = _src_loc(make_lm_generator)
    cfg = _tiny_lm_cfg()
    gen = make_lm_generator(
        cfg, LMMeshSpec(data=2, model=2), prompt_len=8, max_new=4, batch=2,
    )
    params = nn.meta.unbox(jax.eval_shape(
        lambda r: TransformerLM(cfg, None).init(
            r, jnp.zeros((2, 8), jnp.int32)
        )["params"],
        jax.random.key(0),
    ))
    return [ProgramSpec(
        name="lm_decode",
        lowered=gen.jitted.lower(params, *gen.probe_inputs()),
        path=path, line=line,
        mesh_axes=_mesh_axes(gen.mesh),
    )]


def _hlo_serve() -> list[ProgramSpec]:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ddl_tpu.analysis.contracts import _tiny_lm_cfg
    from ddl_tpu.models.transformer import TransformerLM
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.serve.engine import make_serve_step_fns

    path, line = _src_loc(make_serve_step_fns)
    cfg = _tiny_lm_cfg()
    fns = make_serve_step_fns(
        cfg, LMMeshSpec(data=2, model=2),
        block_size=8, num_blocks=16, max_batch=4,
    )
    params = nn.meta.unbox(jax.eval_shape(
        lambda r: TransformerLM(cfg, None).init(
            r, jnp.zeros((2, 8), jnp.int32)
        )["params"],
        jax.random.key(0),
    ))
    pools = jax.eval_shape(fns.init_pools)
    pool_bytes = sum(
        leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(pools)
    )
    mesh_axes = _mesh_axes(fns.mesh)
    decode, _ = fns.decode_for(4, fns.max_blocks_per_seq)
    decode2, _ = fns.decode_for(2, fns.max_blocks_per_seq)
    prefill = fns.prefill_for(8)
    chunk, _ = fns.chunk_for(8, fns.max_blocks_per_seq, "final")
    out = [
        ProgramSpec(
            name="serve_decode",
            lowered=decode.lower(
                params, pools, *fns.probe_inputs("decode", 4)
            ),
            alt_lowered=decode2.lower(
                params, pools, *fns.probe_inputs("decode", 2)
            ),
            path=path, line=line, mesh_axes=mesh_axes,
            pool_bytes=pool_bytes,
        ),
        # prefill/chunk run once per admitted request, not every decode
        # tick, and legitimately rewrite pool-sized slabs when writing
        # a prompt's KV — the steady-state hotspot rule only guards the
        # per-token decode program, so no pool_bytes here
        ProgramSpec(
            name="serve_prefill",
            lowered=prefill.lower(
                params, pools, *fns.probe_inputs("prefill", 8)
            ),
            path=path, line=line, mesh_axes=mesh_axes,
        ),
        ProgramSpec(
            name="serve_chunk",
            lowered=chunk.lower(
                params, pools, *fns.probe_inputs("chunk", 8)
            ),
            path=path, line=line, mesh_axes=mesh_axes,
        ),
    ]
    return out


# (probe name, factory module, builder) — the factory module drives the
# ``lint --changed --hlo`` mapping through the import/call graph
HLO_PROBES = (
    ("cnn_dp", "ddl_tpu.train.steps", lambda: _hlo_cnn()),
    ("cnn_dp_fused", "ddl_tpu.train.steps", lambda: _hlo_cnn(fused=True)),
    ("cnn_dp_zero", "ddl_tpu.train.steps", lambda: _hlo_cnn(zero=True)),
    ("lm_flat", "ddl_tpu.train.lm_steps", lambda: _hlo_lm()),
    ("lm_zero", "ddl_tpu.train.lm_steps", lambda: _hlo_lm(zero=True)),
    ("vit_flat", "ddl_tpu.train.vit_steps", lambda: _hlo_vit()),
    ("lm_decode", "ddl_tpu.infer.decode", _hlo_decode),
    ("serve", "ddl_tpu.serve.engine", _hlo_serve),
    (
        "lm_pipeline", "ddl_tpu.parallel.lm_pipeline",
        lambda: _hlo_lm_pipeline("gpipe"),
    ),
    (
        "lm_pipeline_zb", "ddl_tpu.parallel.lm_pipeline",
        lambda: _hlo_lm_pipeline("zb"),
    ),
    (
        "vit_pipeline", "ddl_tpu.train.vit_steps",
        lambda: _hlo_vit(pipeline=True),
    ),
)


def probe_names() -> list[str]:
    return [name for name, _mod, _build in HLO_PROBES]


def affected_probes(closure_modules: set[str]) -> list[str]:
    """Probe names whose factory module is in the reverse-dependency
    closure of the changed modules (``lint --changed --hlo``)."""
    return [
        name for name, mod, _build in HLO_PROBES if mod in closure_modules
    ]


# ---------------------------------------------------------------------------
# runner
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class HloLintResult:
    findings: list[Finding]  # absolute-rule + drift findings
    notes: list[str]
    stale: list[str]
    inventories: dict[str, ProgramInventory]

    @property
    def ok(self) -> bool:
        return not self.findings

    def baseline_programs(self) -> dict:
        return {
            name: inv.data for name, inv in sorted(self.inventories.items())
        }


def build_inventories(
    probes: list[str] | None = None,
) -> tuple[dict[str, ProgramInventory], list[Finding], list[str]]:
    """Build, lower, and inventory every (selected) probe program on the
    simulated mesh.  A probe that cannot even build is a finding, like
    the contract probes treat it."""
    from ddl_tpu.analysis.contracts import ensure_simulated_mesh

    notes: list[str] = []
    findings: list[Finding] = []
    n = ensure_simulated_mesh()
    if n < 4:
        notes.append(
            f"hlo lint SKIPPED: only {n} simulated device(s); the probe "
            "meshes need 4+"
        )
        return {}, findings, notes
    inventories: dict[str, ProgramInventory] = {}
    for name, _mod, build in HLO_PROBES:
        if probes is not None and name not in probes:
            continue
        try:
            specs = build()
        except Exception as e:
            msg = str(e).splitlines()[0][:200] if str(e) else ""
            findings.append(Finding(
                "ddl_tpu/analysis/hlolint.py", 1, "hlo-probe-build",
                f"probe {name!r} failed to build its programs: "
                f"{type(e).__name__}: {msg}",
            ))
            continue
        for spec in specs:
            inv = build_inventory(spec)
            inventories[spec.name] = inv
            notes.extend(inv.notes)
    return inventories, findings, notes


def run_hlo_lint(
    probes: list[str] | None = None,
    baseline_path: str | Path | None = None,
    scope: set[str] | None = None,
) -> HloLintResult:
    """The full IR pass: build inventories, run the rule family, and
    drift-gate against the committed baseline (when given)."""
    inventories, findings, notes = build_inventories(probes)
    for inv in inventories.values():
        findings.extend(apply_rules(inv))
    stale: list[str] = []
    if baseline_path is not None and Path(baseline_path).exists():
        baseline = load_hlo_baseline(baseline_path)
        if scope is None and probes is not None:
            scope = set(inventories)
        if scope is not None:
            baseline = {
                k: v for k, v in baseline.items()
                if k in scope or k in inventories
            }
        drift, stale = diff_baseline(inventories, baseline, scope=scope)
        findings.extend(drift)
    elif baseline_path is not None:
        notes.append(
            f"hlo baseline {baseline_path} does not exist; run "
            "`ddl_tpu lint --hlo --update-baseline` to create it"
        )
    return HloLintResult(
        findings=sorted(findings), notes=notes, stale=stale,
        inventories=inventories,
    )
