"""AST lint rules over the ddl_tpu package — no JAX import required.

The classes of bug these rules catch share one property: they are
*silent* on a TPU run.  A ``float()`` inside a jitted step either throws
a ConcretizationError at trace time (best case) or forces a host
round-trip per step (worst case — the step graph is cut and MFU halves
with no error anywhere); an unknown mesh axis in a ``PartitionSpec``
replicates the array instead of sharding it; an obs event emitted under
a typo'd name silently never matches any dashboard/CI query.

Engine: per module, build the set of **traced functions** — functions
whose code runs under a JAX trace — then apply host-interop rules only
inside that set (a ``float()`` in the host-side logging path is fine;
the same call inside ``loss_fn`` is a bug).  Traced functions are found
by reference, not by name:

* a function passed to (or decorating with) a JAX transform —
  ``jax.jit`` / ``grad`` / ``value_and_grad`` / ``vmap`` / ``shard_map``
  / ``lax.scan|cond|while_loop|fori_loop`` / ``checkpoint`` /
  ``pallas_call`` — is a traced root;
* **sink parameters** propagate interprocedurally within a module: if
  function ``F`` passes its parameter ``p`` into a transform (or into
  another function's sink parameter, or calls ``p`` from traced code),
  then any local function passed as ``p`` at an ``F`` call site is
  traced — this is how ``loss_fn`` handed through
  ``finalize_step_fns`` → ``jax.value_and_grad`` is found;
* functions lexically nested in a traced function, and functions called
  by name from traced code, are traced (closure to fixpoint).

**Cross-module inference** (``infer_traced_program``): when linting the
whole package, the per-module fixpoint runs inside an outer fixpoint
over the import/call graph (``callgraph.py``) — a function in
``utils/`` called from traced code in ``train/`` (directly, through an
``import`` alias, a re-export, or by being passed into another module's
sink parameter) becomes traced in *its* module, and the host-interop
rules fire there with a ``(traced via …)`` provenance note.  A host
sync hidden behind a helper in a different module is no longer
invisible.  ``lint_file`` on an explicit path stays single-file (fast,
editor-on-save); the sharding-contract checker (``contracts.py``)
still covers composition at trace level.

Beyond the host-interop rules, the module also carries the
**collective-symmetry** family (a ``coord`` barrier/agree/arrive, a
``lax`` collective, or a ``Rendezvous`` method reachable only under a
host-dependent condition — ``host_id``/``process_index``/``DDL_*`` env
— is a split-brain hang: the hosts that don't take the branch never
arrive) and the **recompile-hazard** family (Python branching on traced
``.shape``/``.dtype``, unhashable or freshly-constructed static args at
``jit`` boundaries, traced functions closing over mutable module
globals — the failure class where steps/s craters with no error
anywhere because XLA silently compiles a new program per step).
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from ddl_tpu.analysis.findings import Finding, suppressed

__all__ = [
    "Registry",
    "infer_traced_program",
    "lint_file",
    "lint_package",
    "load_registry",
    "MESH_AXES",
]

# The mesh-axis vocabulary (parallel/mesh.py + parallel/sharding.py).
# PartitionSpec literals anywhere in the package must draw from this set
# (or from an axis tuple declared in a same-module Mesh(...) literal).
MESH_AXES = frozenset({"data", "pipe", "seq", "model", "expert"})

# Calls that put their function arguments under a JAX trace.
_TRANSFORMS = frozenset({
    "jax.jit", "jit", "nn.jit",
    "jax.grad", "jax.value_and_grad", "jax.vjp", "jax.jvp", "jax.linearize",
    "jax.vmap", "jax.pmap",
    "jax.shard_map", "shard_map",
    "jax.checkpoint", "jax.remat", "nn.remat", "checkpoint", "remat",
    "jax.eval_shape",
    "jax.lax.scan", "lax.scan", "jax.lax.cond", "lax.cond",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.map", "lax.map",
    "jax.lax.associative_scan", "lax.associative_scan",
    "pl.pallas_call", "pallas_call",
})

# Host-synchronisation calls: inside traced code these either fail the
# trace or silently cut the compiled program at a host round-trip.
_HOST_SYNC_DOTTED = frozenset({
    "jax.device_get", "device_get",
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.block_until_ready",
})
_HOST_SYNC_METHODS = frozenset({"item", "block_until_ready"})

_NONDET_DOTTED = frozenset({
    "time.time", "time.perf_counter", "time.monotonic", "time.time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
})

# Modules whose exception handling gates checkpoint/recovery decisions:
# an over-broad swallow here turns a real corruption into silent data
# loss, so `except Exception` without a re-raise is flagged.
_RECOVERY_MODULES = frozenset({
    "checkpoint.py",
    "coord.py",
    "supervisor.py",
    "train/recovery.py",
    "train/loop.py",
    "utils/preemption.py",
    "utils/backoff.py",
    "utils/faultinject.py",
    "obs/watchdog.py",
    "obs/steptrace.py",
})

# Step-function factory modules: every jitted train step must declare
# buffer donation (checked here) — whether the runtime honors it is the
# contract checker's runtime concern (compat.py strips donation on old
# jaxlib, an explicit waiver).
_STEP_MODULES = frozenset({
    "train/steps.py",
    "train/lm_steps.py",
    "train/vit_steps.py",
    "parallel/lm_pipeline.py",
})

# Step-factory modules where parameter/batch placement must come from
# the partition-rule engine (parallel/rules.py): a hand-written
# PartitionSpec axis literal here bypasses the rule tables the contract
# probes validate — the exact drift the engine exists to prevent.
# Derived specs (P(), P(None, *TOKEN_SPEC), axis *variables*) are fine;
# only hard-coded axis name strings are flagged.
_RULE_ENGINE_MODULES = frozenset({
    "train/steps.py",
    "train/lm_steps.py",
    "train/vit_steps.py",
})

# Pod-coordination paths: a process that hard-exits here without first
# publishing exit intent through the rendezvous strands its peers inside
# a dead collective until heartbeat ageout — the exact hang the coord
# layer exists to prevent.  Any os._exit/sys.exit use (call OR the
# function object handed around as an escape hatch) inside a function
# that never publishes intent is flagged.
_COORD_EXIT_MODULES = frozenset({
    "supervisor.py",
    "coord.py",
    "obs/watchdog.py",
})

# Collective-symmetry scope: the modules where a host-conditionally-
# reachable collective/barrier is a pod-hang, not a style nit.  The
# coordination layer itself, the shared training loop, and the step
# factories (whose traced collectives must be identical on every host
# of the SPMD world).
_COLLECTIVE_MODULES = frozenset({
    "coord.py",
    "supervisor.py",
    "train/loop.py",
}) | _STEP_MODULES

# lax collectives: every host of the mesh must execute the same sequence
# or the program hangs (PAPERS.md "Collective Communication for 100k+
# GPUs" — asymmetric collectives are the dominant at-scale hang class).
_COLLECTIVE_LAST = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter",
    "all_gather", "all_to_all", "ppermute", "pshuffle",
})
_COLLECTIVE_PREFIXES = ("", "lax", "jax.lax")

# Blocking Rendezvous primitives (coord.py): `barrier` and `agree` wait
# for peers, and a host-conditional `arrive` starves every peer's
# blocking wait on that barrier — all three must be symmetric.
_BARRIER_ATTRS = frozenset({"barrier", "agree", "arrive"})

# Names whose appearance in a branch condition makes the branch
# host-dependent: different hosts of one pod evaluate it differently.
_HOST_COND_NAMES = frozenset({
    "host", "host_id", "rank", "process_index", "process_id",
})

# Constructor calls that are safe as jit static args: value-hashed
# built-ins (a fresh `tuple(...)` of equal elements cache-hits; a fresh
# instance of an arbitrary class identity-hashes and never does).
_VALUE_HASHED_CTORS = frozenset({
    "tuple", "frozenset", "int", "float", "bool", "str", "bytes", "len",
})

# Call forms that build a mutable container (module-global hazard).
_MUTABLE_CTORS = frozenset({
    "dict", "list", "set", "bytearray",
    "defaultdict", "collections.defaultdict",
    "deque", "collections.deque",
    "Counter", "collections.Counter",
    "OrderedDict", "collections.OrderedDict",
})
_MUTABLE_LITERALS = (
    ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp,
)


@dataclasses.dataclass
class Registry:
    """Names the obs-event rules validate against, parsed from
    ``<package>/obs/events.py`` without importing it.  ``kind_lines``
    maps each EVENT_KINDS entry to its source line (where the
    dead-event-kind rule anchors its finding and reads suppressions)."""

    event_kinds: frozenset[str]
    anomaly_types: frozenset[str]
    kind_lines: dict[str, int] = dataclasses.field(default_factory=dict)


def load_registry(package_root: Path) -> Registry:
    """Parse EVENT_KINDS / ANOMALY_TYPES tuples out of obs/events.py.
    A package without one (fixture packages) gets an empty registry —
    the obs rules simply have nothing to check against."""
    try:
        src = (Path(package_root) / "obs" / "events.py").read_text()
    except OSError:
        return Registry(frozenset(), frozenset())
    tree = ast.parse(src)
    found: dict[str, frozenset] = {}
    kind_lines: dict[str, int] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if target.id in ("EVENT_KINDS", "ANOMALY_TYPES"):
            consts = [
                e
                for e in ast.walk(node.value)
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
            found[target.id] = frozenset(e.value for e in consts)
            if target.id == "EVENT_KINDS":
                kind_lines = {e.value: e.lineno for e in consts}
    return Registry(
        event_kinds=found.get("EVENT_KINDS", frozenset()),
        anomaly_types=found.get("ANOMALY_TYPES", frozenset()),
        kind_lines=kind_lines,
    )


# ---------------------------------------------------------------------------
# module model: functions, imports, traced-set inference
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> str | None:
    """'jax.lax.scan' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclasses.dataclass
class _Func:
    node: ast.AST
    name: str
    parent: "_Func | None"
    params: tuple[str, ...]
    sink_params: set[str] = dataclasses.field(default_factory=set)


class _Module:
    """One parsed module with enough structure for the traced-set
    inference: functions (with lexical nesting), every call site (with
    its innermost enclosing function), the import alias map, and —
    since the class-method round — classes: each class's direct
    methods, its base-name list, every function's enclosing class
    context (what ``self.m()`` resolves against), and a conservative
    ``var = ClassName(...)`` instance map (what ``obj.m()`` resolves
    against)."""

    def __init__(self, tree: ast.Module) -> None:
        self.funcs: dict[int, _Func] = {}
        self.by_name: dict[str, list[_Func]] = {}
        self.calls: list[tuple[ast.Call, _Func | None]] = []
        self.imports: dict[str, str] = {}  # local alias -> real module
        # class name -> {direct method name -> _Func}
        self.classes: dict[str, dict[str, _Func]] = {}
        # class name -> dotted base names (single-expression bases only)
        self.class_bases: dict[str, list[str]] = {}
        # id(func node) -> name of the class whose body (transitively)
        # contains it — the receiver type of ``self``/``cls`` there
        self.cls_context: dict[int, str] = {}
        # (id(enclosing func node) | None, var) -> constructor dotted
        # name, from simple ``var = C(...)`` assignments (last wins)
        self.var_classes: dict[tuple, str] = {}
        self._index(tree)

    def _index(self, tree: ast.Module) -> None:
        stack: list[_Func] = []
        class_stack: list[tuple[str, int]] = []  # (name, func depth)

        def visit(node: ast.AST) -> None:
            if isinstance(node, _FUNC_NODES):
                name = getattr(node, "name", "<lambda>")
                args = node.args
                params = tuple(
                    a.arg
                    for a in (
                        args.posonlyargs + args.args + args.kwonlyargs
                    )
                )
                fn = _Func(node, name, stack[-1] if stack else None, params)
                self.funcs[id(node)] = fn
                self.by_name.setdefault(name, []).append(fn)
                if class_stack:
                    cname, depth = class_stack[-1]
                    self.cls_context[id(node)] = cname
                    if len(stack) == depth:  # directly in the class body
                        self.classes.setdefault(cname, {})[name] = fn
                stack.append(fn)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                stack.pop()
                return
            if isinstance(node, ast.ClassDef):
                self.classes.setdefault(node.name, {})
                self.class_bases[node.name] = [
                    b for b in (_dotted(base) for base in node.bases)
                    if b is not None
                ]
                class_stack.append((node.name, len(stack)))
                for child in ast.iter_child_nodes(node):
                    visit(child)
                class_stack.pop()
                return
            if isinstance(node, ast.Call):
                self.calls.append((node, stack[-1] if stack else None))
            elif isinstance(node, ast.Assign):
                # conservative instance typing: ``var = C(...)`` with a
                # single Name target; re-assignment rebinds (last wins)
                if (
                    len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                ):
                    ctor = _dotted(node.value.func)
                    if ctor is not None:
                        scope = id(stack[-1].node) if stack else None
                        self.var_classes[
                            (scope, node.targets[0].id)
                        ] = ctor
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name
                    )
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}" if node.module
                        else alias.name
                    )
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(tree)

    # -- resolution helpers -------------------------------------------------

    def resolve_func(
        self, expr: ast.AST, enclosing: "_Func | None" = None
    ) -> _Func | None:
        """A Name (or functools.partial(Name, ...)) referring to a
        module function, else None.  With ``enclosing`` (the call
        site's innermost function) resolution is scope-aware: among
        same-named definitions, the one defined in the NEAREST lexical
        scope of the call site wins — so three factories each defining
        a local ``step`` resolve their own, not whichever parsed last."""
        if isinstance(expr, ast.Call) and _is_partial(expr.func):
            return (
                self.resolve_func(expr.args[0], enclosing)
                if expr.args else None
            )
        if not isinstance(expr, ast.Name):
            return None
        candidates = self.by_name.get(expr.id)
        if not candidates:
            return None
        if enclosing is not None:
            chain_ids = [
                id(f.node) for f in self.enclosing_chain(enclosing)
            ]  # innermost -> outermost
            best, best_rank = None, None
            for c in candidates:
                if c.parent is None:
                    rank = len(chain_ids)  # module scope: outermost
                elif id(c.parent.node) in chain_ids:
                    rank = chain_ids.index(id(c.parent.node))
                else:
                    continue  # not lexically visible from the call site
                # <=: a later definition at the same depth rebinds
                if best_rank is None or rank <= best_rank:
                    best, best_rank = c, rank
            if best is not None:
                return best
        top = [c for c in candidates if c.parent is None]
        return (top or candidates)[-1]

    def enclosing_chain(self, fn: _Func | None):
        while fn is not None:
            yield fn
            fn = fn.parent

    # -- class-method resolution --------------------------------------------

    def lookup_method(
        self, cls_name: str, meth: str, _depth: int = 0
    ) -> "_Func | None":
        """``cls_name``'s method ``meth``, chasing same-module base
        classes to a bounded depth (cross-module bases resolve at the
        call-graph layer)."""
        if _depth > 8:
            return None
        methods = self.classes.get(cls_name)
        if methods is None:
            return None
        if meth in methods:
            return methods[meth]
        for base in self.class_bases.get(cls_name, ()):
            found = self.lookup_method(base, meth, _depth + 1)
            if found is not None:
                return found
        return None

    def instance_class(
        self, name: str, enclosing: "_Func | None"
    ) -> str | None:
        """The constructor dotted name a variable was bound to
        (``obj = C(...)``), nearest enclosing scope first, module scope
        last — or None when the variable's type is not statically
        evident."""
        for outer in self.enclosing_chain(enclosing):
            ctor = self.var_classes.get((id(outer.node), name))
            if ctor is not None:
                return ctor
        return self.var_classes.get((None, name))

    def resolve_method(
        self, expr: ast.AST, enclosing: "_Func | None"
    ) -> "_Func | None":
        """A method call/reference resolved WITHIN this module:
        ``self.m()`` / ``cls.m()`` against the call site's enclosing
        class, ``C.m`` against a local class, ``obj.m()`` against a
        local ``obj = C(...)`` binding.  Cross-module receivers return
        None here and are chased by ``_resolve_callable`` through the
        call graph."""
        if not isinstance(expr, ast.Attribute) or not isinstance(
            expr.value, (ast.Name, ast.Attribute)
        ):
            return None
        meth = expr.attr
        base = _dotted(expr.value)
        if base is None:
            return None
        if base in ("self", "cls"):
            for outer in self.enclosing_chain(enclosing):
                cname = self.cls_context.get(id(outer.node))
                if cname is not None:
                    return self.lookup_method(cname, meth)
            return None
        if base in self.classes:  # C.m (unbound reference)
            return self.lookup_method(base, meth)
        ctor = self.instance_class(base, enclosing)
        if ctor is not None and ctor in self.classes:
            return self.lookup_method(ctor, meth)
        return None


def _is_partial(func_expr: ast.AST) -> bool:
    d = _dotted(func_expr)
    return d in ("partial", "functools.partial")


def _is_transform(call: ast.Call) -> bool:
    d = _dotted(call.func)
    if d in _TRANSFORMS:
        return True
    # partial(jax.jit, ...) / partial(lax.scan, ...) as the callee
    if _is_partial(call.func):
        return False  # handled at the inner-arg level by callers
    return False


def _func_args(call: ast.Call):
    """Every expression passed to a call (positional + keyword)."""
    yield from call.args
    for kw in call.keywords:
        if kw.value is not None:
            yield kw.value


def _resolve_local(mod: _Module, expr: ast.AST, enclosing) -> _Func | None:
    """Local callable resolution: module functions (scope-aware) first,
    then class methods (``self.m`` / ``C.m`` / ``obj.m`` with a local
    ``obj = C(...)`` binding); partial-wrapped references unwrap."""
    if isinstance(expr, ast.Call) and _is_partial(expr.func):
        return (
            _resolve_local(mod, expr.args[0], enclosing)
            if expr.args else None
        )
    fn = mod.resolve_func(expr, enclosing)
    if fn is not None:
        return fn
    return mod.resolve_method(expr, enclosing)


def _infer_traced(
    mod: _Module, traced: set[int] | None = None
) -> set[int]:
    """Fixpoint over {traced functions} x {sink parameters}.  An
    existing ``traced`` set (cross-module seeds from
    ``infer_traced_program``) is extended in place."""
    traced = set() if traced is None else traced

    # seeds: decorators that are transforms
    for fn in mod.funcs.values():
        for dec in getattr(fn.node, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = _dotted(target)
            if d in _TRANSFORMS:
                traced.add(id(fn.node))
            elif isinstance(dec, ast.Call) and _is_partial(dec.func):
                if dec.args and _dotted(dec.args[0]) in _TRANSFORMS:
                    traced.add(id(fn.node))

    changed = True
    while changed:
        changed = False

        for call, enclosing in mod.calls:
            callee_d = _dotted(call.func)

            # (1) function reference passed into a transform -> traced root
            transform_call = callee_d in _TRANSFORMS or (
                _is_partial(call.func)
                and call.args
                and _dotted(call.args[0]) in _TRANSFORMS
            )
            if transform_call:
                for arg in _func_args(call):
                    target = _resolve_local(mod, arg, enclosing)
                    if target is not None and id(target.node) not in traced:
                        traced.add(id(target.node))
                        changed = True
                # a parameter of an enclosing function fed to a transform
                # makes that parameter a sink
                for arg in _func_args(call):
                    base = arg
                    if isinstance(arg, ast.Call) and _is_partial(arg.func):
                        base = arg.args[0] if arg.args else arg
                    if isinstance(base, ast.Name) and enclosing is not None:
                        for outer in mod.enclosing_chain(enclosing):
                            if base.id in outer.params and (
                                base.id not in outer.sink_params
                            ):
                                outer.sink_params.add(base.id)
                                changed = True

            # (2) call to a local function with sink params: map args
            callee_fn = _resolve_local(mod, call.func, enclosing)
            if callee_fn is not None and callee_fn.sink_params:
                bound: list[tuple[str, ast.AST]] = []
                for i, arg in enumerate(call.args):
                    if i < len(callee_fn.params):
                        bound.append((callee_fn.params[i], arg))
                for kw in call.keywords:
                    if kw.arg is not None:
                        bound.append((kw.arg, kw.value))
                for pname, arg in bound:
                    if pname not in callee_fn.sink_params:
                        continue
                    target = _resolve_local(mod, arg, enclosing)
                    if target is not None and id(target.node) not in traced:
                        traced.add(id(target.node))
                        changed = True
                    base = arg
                    if isinstance(arg, ast.Call) and _is_partial(arg.func):
                        base = arg.args[0] if arg.args else arg
                    if isinstance(base, ast.Name) and enclosing is not None:
                        for outer in mod.enclosing_chain(enclosing):
                            if base.id in outer.params and (
                                base.id not in outer.sink_params
                            ):
                                outer.sink_params.add(base.id)
                                changed = True

            # (3) inside a traced function: called names become traced,
            # and a *called parameter* of an enclosing function is a sink
            # (accumulate_grads' scan body calling grad_fn)
            if enclosing is not None and id(enclosing.node) in traced:
                target = _resolve_local(mod, call.func, enclosing)
                if target is not None and id(target.node) not in traced:
                    traced.add(id(target.node))
                    changed = True
                if isinstance(call.func, ast.Name):
                    for outer in mod.enclosing_chain(enclosing):
                        if call.func.id in outer.params and (
                            call.func.id not in outer.sink_params
                        ):
                            outer.sink_params.add(call.func.id)
                            changed = True

        # (4) lexical nesting: children of traced functions are traced
        for fn in mod.funcs.values():
            if id(fn.node) in traced:
                continue
            if fn.parent is not None and id(fn.parent.node) in traced:
                traced.add(id(fn.node))
                changed = True

    return traced


# ---------------------------------------------------------------------------
# cross-module traced-set inference (over callgraph.CallGraph)
# ---------------------------------------------------------------------------


def _resolve_callable(graph, info, expr, enclosing=None):
    """A Target for a callee/argument expression: a Name or dotted
    Attribute chain (optionally wrapped in functools.partial).  Local
    scope-aware resolution first (the call site's own module binds
    tightest), then the cross-module import/re-export chase."""
    if isinstance(expr, ast.Call) and _is_partial(expr.func):
        return (
            _resolve_callable(graph, info, expr.args[0], enclosing)
            if expr.args else None
        )
    if isinstance(expr, ast.Name):
        local = info.mod.resolve_func(expr, enclosing)
        if local is not None:
            from ddl_tpu.analysis.callgraph import Target

            return Target(info.name, local)
    d = _dotted(expr)
    if d is not None:
        t = graph.resolve_dotted(info, d)
        if t is not None:
            return t
    # class-method edges: self.m()/C.m/obj.m() resolved locally first,
    # then an imported receiver class chased through the call graph
    if isinstance(expr, ast.Attribute):
        local_m = info.mod.resolve_method(expr, enclosing)
        if local_m is not None:
            from ddl_tpu.analysis.callgraph import Target

            return Target(info.name, local_m)
        if isinstance(expr.value, ast.Name):
            ctor = info.mod.instance_class(expr.value.id, enclosing)
            if ctor is not None:
                return graph.resolve_class_method(info, ctor, expr.attr)
    return None


def infer_traced_program(graph):
    """Traced sets for every module of a ``callgraph.CallGraph``,
    propagated interprocedurally ACROSS module boundaries.

    Returns ``(traced, reasons)`` where ``traced`` maps module name to
    the set of traced function-node ids and ``reasons`` maps
    ``(module, node_id)`` to a human-readable provenance string for
    functions traced only through a cross-module edge (so a finding in
    ``utils/helpers.py`` can say which step factory pulled it under a
    trace).

    The outer fixpoint interleaves three cross-module edges with the
    per-module closure (``_infer_traced``):

    * a function *reference* resolved into another module passed to a
      JAX transform (``jax.jit(helpers.step)``) → traced root there;
    * a *call* from traced code resolved into another module
      (``helpers.sync_mean(loss)`` inside ``loss_fn``) → callee traced;
    * an argument bound to another module's **sink parameter**
      (``wrap_loss(inner)`` where ``wrap_loss`` in another module feeds
      its parameter into ``value_and_grad``) → the argument is traced,
      and a parameter of the *calling* function forwarded that way
      becomes a sink itself.
    """
    traced: dict[str, set[int]] = {}
    reasons: dict[tuple[str, int], str] = {}
    for name, info in graph.modules.items():
        traced[name] = _infer_traced(info.mod)

    def mark(target, why: str) -> bool:
        s = traced[target.module]
        if id(target.func.node) in s:
            return False
        s.add(id(target.func.node))
        reasons.setdefault((target.module, id(target.func.node)), why)
        return True

    def size() -> int:
        return sum(len(s) for s in traced.values()) + sum(
            len(fn.sink_params)
            for info in graph.modules.values()
            for fn in info.mod.funcs.values()
        )

    while True:
        before = size()
        for name, info in graph.modules.items():
            tset = traced[name]
            for call, enclosing in info.mod.calls:
                callee_d = _dotted(call.func)
                transform_call = callee_d in _TRANSFORMS or (
                    _is_partial(call.func)
                    and call.args
                    and _dotted(call.args[0]) in _TRANSFORMS
                )
                if transform_call:
                    for arg in _func_args(call):
                        t = _resolve_callable(graph, info, arg, enclosing)
                        if t is not None and t.module != name:
                            mark(t, f"passed to a JAX transform in {info.rel}")
                    continue
                callee = _resolve_callable(graph, info, call.func, enclosing)
                # call FROM traced code into another module
                if (
                    enclosing is not None
                    and id(enclosing.node) in tset
                    and callee is not None
                    and callee.module != name
                ):
                    mark(
                        callee,
                        f"called from traced code in "
                        f"{info.rel}::{enclosing.name}",
                    )
                # arguments bound to a cross-module callee's sink params
                if callee is not None and callee.func.sink_params:
                    bound: list[tuple[str, ast.AST]] = []
                    for i, arg in enumerate(call.args):
                        if i < len(callee.func.params):
                            bound.append((callee.func.params[i], arg))
                    for kw in call.keywords:
                        if kw.arg is not None:
                            bound.append((kw.arg, kw.value))
                    for pname, arg in bound:
                        if pname not in callee.func.sink_params:
                            continue
                        t = _resolve_callable(graph, info, arg, enclosing)
                        if t is not None:
                            mark(
                                t,
                                f"flows into traced sink parameter "
                                f"{pname!r} of {callee.module}."
                                f"{callee.func.name}",
                            )
                        # forwarding an own parameter into a foreign sink
                        # makes it a sink here too
                        base = arg
                        if isinstance(arg, ast.Call) and _is_partial(arg.func):
                            base = arg.args[0] if arg.args else arg
                        if isinstance(base, ast.Name) and enclosing is not None:
                            for outer in info.mod.enclosing_chain(enclosing):
                                if base.id in outer.params:
                                    outer.sink_params.add(base.id)
            # close locally with the augmented set (lexical children and
            # same-module calls of newly-traced functions)
            _infer_traced(info.mod, traced=tset)
        if size() == before:
            return traced, reasons


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _iter_with_enclosing(tree: ast.Module, mod: _Module):
    """(node, innermost enclosing _Func or None) for every node."""
    stack: list[_Func] = []

    def visit(node: ast.AST):
        entered = False
        if isinstance(node, _FUNC_NODES):
            stack.append(mod.funcs[id(node)])
            entered = True
        yield node, (stack[-1] if stack else None)
        for child in ast.iter_child_nodes(node):
            yield from visit(child)
        if entered:
            stack.pop()

    # yield with the *enclosing* function, so a FunctionDef node itself
    # reports under its own scope (fine for our rules)
    yield from visit(tree)


def _rule_traced_interop(
    tree, mod: _Module, traced: set[int], rel: str, add,
    reasons: dict[int, str] | None = None,
) -> None:
    def via(enclosing) -> str:
        # provenance for functions traced only through a cross-module
        # edge: names the step factory (etc.) that pulled them under a
        # trace, so a finding in utils/ is actionable without grepping
        why = (reasons or {}).get(id(enclosing.node))
        return f" (traced: {why})" if why else ""

    for node, enclosing in _iter_with_enclosing(tree, mod):
        if enclosing is None or id(enclosing.node) not in traced:
            continue
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            full = None
            if d is not None:
                first, *rest = d.split(".")
                full = ".".join([mod.imports.get(first, first)] + rest)
            if d in _HOST_SYNC_DOTTED or full in _HOST_SYNC_DOTTED:
                add(node, "host-sync",
                    f"{d}() inside traced function "
                    f"'{enclosing.name}' forces a host sync (or fails the "
                    "trace); keep device values on device until the period "
                    f"fence{via(enclosing)}")
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_METHODS
                and not node.args
            ):
                add(node, "host-sync",
                    f".{node.func.attr}() inside traced function "
                    f"'{enclosing.name}' forces a host sync per call{via(enclosing)}")
            elif isinstance(node.func, ast.Name) and node.func.id == "float":
                add(node, "host-sync",
                    f"float() inside traced function '{enclosing.name}' "
                    "concretizes a tracer (host sync / trace error); use "
                    f"jnp.float32 or .astype for dtype casts{via(enclosing)}")
            elif full is not None:
                if d in _NONDET_DOTTED or full in _NONDET_DOTTED:
                    add(node, "nondeterminism",
                        f"{d}() inside traced function '{enclosing.name}': "
                        "wall-clock reads bake a constant into the compiled "
                        f"program (and differ across hosts){via(enclosing)}")
                elif full.startswith(("random.", "numpy.random.")):
                    add(node, "nondeterminism",
                        f"{d}() inside traced function '{enclosing.name}': "
                        "Python/NumPy RNG is host-side and per-process; use "
                        f"jax.random with an explicit key{via(enclosing)}")
        elif isinstance(node, (ast.For, ast.comprehension)):
            it = node.iter
            is_set = isinstance(it, ast.Set) or (
                isinstance(it, ast.Call)
                and _dotted(it.func) in ("set", "frozenset")
            )
            if is_set:
                add(node if isinstance(node, ast.For) else it,
                    "nondeterminism",
                    f"iteration over a set inside traced function "
                    f"'{enclosing.name}': set order varies per process, so "
                    "traced program structure diverges across hosts; sort "
                    f"or use a tuple{via(enclosing)}")


def _rule_excepts(tree, rel: str, add) -> None:
    in_recovery = rel_suffix(rel) in _RECOVERY_MODULES
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            add(node, "bare-except",
                "bare 'except:' swallows KeyboardInterrupt/SystemExit too; "
                "name the exceptions (or 'except Exception' plus a re-raise)")
            continue
        if not in_recovery:
            continue
        names = []
        exprs = (
            node.type.elts if isinstance(node.type, ast.Tuple)
            else [node.type]
        )
        for e in exprs:
            d = _dotted(e)
            if d is not None:
                names.append(d.split(".")[-1])
        if any(n in ("Exception", "BaseException") for n in names):
            has_raise = any(
                isinstance(n, ast.Raise) for n in ast.walk(node)
            )
            if not has_raise:
                add(node, "broad-except",
                    f"'except {'/'.join(names)}' without re-raise in a "
                    "checkpoint/recovery path can mask corruption as "
                    "success; narrow the exception list or re-raise")


def _rule_compat(tree, rel: str, add) -> None:
    if rel_suffix(rel) == "compat.py":
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            m = node.module or ""
            if m.startswith("jax.experimental.shard_map") or (
                m == "jax.experimental"
                and any(a.name in ("shard_map", "pjit") for a in node.names)
            ):
                add(node, "compat-bypass",
                    "legacy jax.experimental.shard_map/pjit import bypasses "
                    "the compat.py shim; use jax.shard_map / jax.jit "
                    "(compat installs them on old runtimes)")
            elif m.startswith("jax.experimental.pjit"):
                add(node, "compat-bypass",
                    "legacy pjit import; use jax.jit (compat.py guarantees "
                    "the modern surface)")
        elif isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d and (
                d.startswith("jax.experimental.shard_map")
                or d.startswith("jax.experimental.pjit")
            ):
                add(node, "compat-bypass",
                    f"direct {d} use bypasses the compat.py shim; use the "
                    "modern jax.* name")
            elif node.attr == "TPUCompilerParams":
                add(node, "compat-bypass",
                    "TPUCompilerParams is the legacy spelling; use "
                    "pltpu.CompilerParams (compat.py aliases it on old "
                    "runtimes)")
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "check_rep":
                    add(node, "compat-bypass",
                        "check_rep= is the legacy shard_map kwarg; pass "
                        "check_vma= (compat.py translates on old runtimes)")


# Call attrs treated as obs-event emission sites: the writer itself and
# the thin `_emit` forwarders (Supervisor/PodSupervisor wrap EventWriter
# behind one) — their literal kinds must be registered too, and they
# count as "emitted" for the dead-kind rule.
_EMIT_ATTRS = frozenset({"emit", "_emit"})


def _emit_kind_literal(node: ast.Call) -> str | None:
    """The literal event kind an emit/_emit call names, else None."""
    kind = None
    if node.args and isinstance(node.args[0], ast.Constant):
        kind = node.args[0].value
    for kw in node.keywords:
        if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
            kind = kw.value.value
    return kind if isinstance(kind, str) else None


def _rule_obs_events(tree, registry: Registry, rel: str, add) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if node.func.attr in _EMIT_ATTRS:
            kind = _emit_kind_literal(node)
            if kind is not None and kind not in registry.event_kinds:
                add(node, "obs-event-unregistered",
                    f"obs event kind {kind!r} is not in "
                    "obs/events.py EVENT_KINDS; register it (or fix the "
                    "typo) so dashboards and CI queries can rely on the "
                    "name")
        elif node.func.attr == "record":
            base = _dotted(node.func.value)
            if base is None or not base.split(".")[-1] == "anomaly":
                continue
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                t = node.args[1].value
                if isinstance(t, str) and t not in registry.anomaly_types:
                    add(node, "anomaly-type-unregistered",
                        f"anomaly type {t!r} is not in obs/events.py "
                        "ANOMALY_TYPES; register it so the obs summary and "
                        "alert queries see it")


def _pspec_names(tree, mod: _Module) -> set[str]:
    """Local aliases bound to jax.sharding.PartitionSpec."""
    names = set()
    for alias, real in mod.imports.items():
        if real.endswith("PartitionSpec"):
            names.add(alias)
    names.update({"PartitionSpec"})
    return names


def _rule_pspec(tree, mod: _Module, rel: str, add) -> None:
    pnames = _pspec_names(tree, mod)
    # axis names declared by a same-module Mesh((...), ("ring",)) literal
    # extend the allowed set (bench/comm.py builds its own ring mesh)
    extra: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _dotted(node.func) in (
            "Mesh", "jax.sharding.Mesh"
        ):
            for arg in list(node.args[1:]) + [
                kw.value for kw in node.keywords if kw.arg == "axis_names"
            ]:
                for e in ast.walk(arg):
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, str
                    ):
                        extra.add(e.value)
    allowed = MESH_AXES | extra
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d not in pnames and d != "jax.sharding.PartitionSpec":
            continue
        for arg in node.args:
            consts = (
                [arg] if isinstance(arg, ast.Constant)
                else list(ast.walk(arg)) if isinstance(arg, ast.Tuple)
                else []
            )
            for e in consts:
                if (
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                    and e.value not in allowed
                ):
                    add(node, "pspec-unknown-axis",
                        f"PartitionSpec axis {e.value!r} is not a mesh axis "
                        f"({'/'.join(sorted(allowed))}); XLA would treat "
                        "the dimension as replicated — a silent memory/"
                        "throughput loss, never an error")


def _rule_pspec_hand_rolled(tree, mod: _Module, rel: str, add) -> None:
    """In the step-factory modules, flag ``PartitionSpec`` calls that
    hard-code axis-name strings: placement belongs to the family rule
    tables (``parallel/rules.py``), and a literal here silently bypasses
    the table the contract probes validate."""
    if rel_suffix(rel) not in _RULE_ENGINE_MODULES:
        return
    pnames = _pspec_names(tree, mod)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d not in pnames and d != "jax.sharding.PartitionSpec":
            continue
        literals = []
        for arg in node.args:
            consts = (
                [arg] if isinstance(arg, ast.Constant)
                else list(ast.walk(arg)) if isinstance(arg, ast.Tuple)
                else []
            )
            literals.extend(
                e.value for e in consts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
        if literals:
            add(node, "pspec-hand-rolled",
                f"hand-written PartitionSpec axis literal(s) "
                f"{sorted(set(literals))} in a step-factory module bypass "
                "the partition-rule engine; use the family rule table / "
                "named boundary specs from parallel/rules.py (derive "
                "variants like P(None, *TOKEN_SPEC))")


def _rule_donation(tree, mod: _Module, rel: str, add) -> None:
    if rel_suffix(rel) not in _STEP_MODULES:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or _dotted(node.func) not in (
            "jax.jit", "jit"
        ):
            continue
        if not node.args or not isinstance(node.args[0], ast.Name):
            continue
        if "train" not in node.args[0].id:
            continue
        kwargs = {kw.arg for kw in node.keywords}
        if not kwargs & {"donate_argnums", "donate_argnames"}:
            add(node, "donation-missing",
                f"jax.jit({node.args[0].id}, ...) without donate_argnums: "
                "the train state is copied instead of donated — 2x state "
                "HBM held across the update (compat.py strips donation on "
                "old runtimes; new step factories must still declare it)")


def _rule_exit_intent(tree, mod: _Module, rel: str, add) -> None:
    """In coord/supervisor/watchdog paths, an ``os._exit``/``sys.exit``
    whose enclosing function never publishes exit intent bypasses the
    pod protocol (the dying host's peers wait for its heartbeat to age
    out instead of reacting to the marker).  'Publishes intent' is
    lexical: some call in the same function whose name mentions
    ``intent`` (``coord.publish_exit_intent_from_env``,
    ``rv.publish_intent``)."""
    if rel_suffix(rel) not in _COORD_EXIT_MODULES:
        return
    intent_scopes: set[int | None] = set()
    exit_uses: list[tuple[ast.AST, _Func | None, str]] = []
    call_funcs: set[int] = set()  # Attribute nodes already seen as callees

    def scope_key(enclosing: _Func | None):
        return id(enclosing.node) if enclosing is not None else None

    for node, enclosing in _iter_with_enclosing(tree, mod):
        if isinstance(node, ast.Call):
            call_funcs.add(id(node.func))
            d = _dotted(node.func) or ""
            if "intent" in d.lower():
                intent_scopes.add(scope_key(enclosing))
            if d in ("os._exit", "sys.exit"):
                exit_uses.append((node, enclosing, f"{d}()"))
        elif isinstance(node, ast.Attribute) and id(node) not in call_funcs:
            d = _dotted(node)
            if d in ("os._exit", "sys.exit"):
                exit_uses.append((node, enclosing, d))
    for node, enclosing, what in exit_uses:
        if scope_key(enclosing) not in intent_scopes:
            add(node, "exit-without-intent",
                f"{what} in a coord/supervisor path without publishing "
                "exit intent first: peer hosts block inside the dead "
                "collective until heartbeat ageout; call "
                "coord.publish_exit_intent_from_env (or "
                "Rendezvous.publish_intent) before exiting")


# ---------------------------------------------------------------------------
# collective-symmetry rule family
# ---------------------------------------------------------------------------


def _host_dependent_why(test: ast.AST) -> str | None:
    """A short description of why a branch condition is host-dependent
    (different hosts of one pod evaluate it differently), or None."""
    for n in ast.walk(test):
        if isinstance(n, ast.Name) and n.id in _HOST_COND_NAMES:
            return f"reads '{n.id}'"
        if isinstance(n, ast.Attribute) and n.attr in _HOST_COND_NAMES:
            d = _dotted(n)
            return f"reads '{d or n.attr}'"
        if (
            isinstance(n, ast.Constant)
            and isinstance(n.value, str)
            and n.value.startswith("DDL_")
        ):
            return f"branches on env {n.value!r}"
    return None


def _collective_callee(call: ast.Call) -> str | None:
    """'lax.psum' / 'rv.barrier' when the call is a collective or a
    blocking rendezvous primitive, else None."""
    d = _dotted(call.func)
    if d is not None:
        parts = d.split(".")
        if parts[-1] in _COLLECTIVE_LAST and (
            ".".join(parts[:-1]) in _COLLECTIVE_PREFIXES
        ):
            return d
    if (
        isinstance(call.func, ast.Attribute)
        and call.func.attr in _BARRIER_ATTRS
    ):
        return d or f".{call.func.attr}"
    return None


def _suite_terminates(stmts: list) -> bool:
    """True when a statement suite always leaves the enclosing scope /
    loop iteration (its last statement is a return/raise/continue/
    break) — the early-exit shape the asymmetry extension keys on."""
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break)
    )


def _rule_collective_symmetry(tree, mod: _Module, rel: str, add) -> None:
    """In the coordination layer, the shared loop, and the step modules,
    a collective / barrier / agree call reachable only under a
    host-dependent condition is a split-brain hang: the hosts that don't
    take the branch never make the matching call, and the ones that do
    block forever (barrier timeout at best, a wedged all-reduce at
    worst).  Conditions inside a *nested function definition* reset the
    stack — the definition site does not gate the call's execution.

    Two reachability shapes are covered:

    * a collective lexically INSIDE a host-dependent branch (the
      condition-stack walk);
    * **early-return asymmetry**: ``if host...: return`` (or raise /
      continue / break) makes every later statement in the same suite
      reachable only by the hosts that did NOT take the branch — the
      same split brain with the collective OUTSIDE the branch, which
      the condition stack alone cannot see.  A host-dependent ``if``
      whose taken branch terminates while the other continues taints
      the rest of its suite.
    """
    if rel_suffix(rel) not in _COLLECTIVE_MODULES:
        return

    def visit(node: ast.AST, why: str | None) -> None:
        if isinstance(node, ast.Module):
            visit_suite(node.body, why)
            return
        if isinstance(node, ast.ClassDef):
            for expr in (*node.decorator_list, *node.bases,
                         *(kw.value for kw in node.keywords)):
                visit(expr, why)
            visit_suite(node.body, why)
            return
        if isinstance(node, _FUNC_NODES):
            if isinstance(node.body, list):
                visit_suite(node.body, None)
            else:  # lambda: body is a single expression
                visit(node.body, None)
            return
        if isinstance(node, ast.Call) and why is not None:
            callee = _collective_callee(node)
            if callee is not None:
                add(node, "collective-symmetry",
                    f"collective/barrier call '{callee}' is reachable "
                    f"only under a host-dependent condition ({why}): "
                    "hosts that don't take this branch never make the "
                    "matching call — a split-brain hang at pod scale. "
                    "Make the call unconditional (same sequence on every "
                    "host) or restructure so all hosts branch "
                    "identically")
        if isinstance(node, (ast.If, ast.While)):
            new_why = _host_dependent_why(node.test) or why
            visit(node.test, why)
            visit_suite(node.body, new_why)
            visit_suite(node.orelse, new_why)
            return
        if isinstance(node, ast.IfExp):
            new_why = _host_dependent_why(node.test) or why
            visit(node.test, why)
            visit(node.body, new_why)
            visit(node.orelse, new_why)
            return
        # every other statement suite walks suite-aware too, so a
        # host-gated continue/break/return INSIDE a loop / with / try
        # taints the rest of that suite (the shapes _suite_terminates
        # lists can only occur here)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            visit(node.target, why)
            visit(node.iter, why)
            visit_suite(node.body, why)
            visit_suite(node.orelse, why)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                visit(item, why)
            visit_suite(node.body, why)
            return
        if isinstance(node, ast.Try):
            visit_suite(node.body, why)
            for h in node.handlers:
                visit_suite(h.body, why)
            visit_suite(node.orelse, why)
            visit_suite(node.finalbody, why)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, why)

    def visit_suite(stmts: list, why: str | None) -> None:
        for stmt in stmts:
            visit(stmt, why)
            if why is None and isinstance(stmt, ast.If):
                host_why = _host_dependent_why(stmt.test)
                if host_why is None:
                    continue
                body_exits = _suite_terminates(stmt.body)
                else_exits = (
                    _suite_terminates(stmt.orelse) if stmt.orelse else False
                )
                # asymmetric continuation: one side leaves, the other
                # falls through — everything after this statement runs
                # on a host-dependent subset.  Both sides terminating
                # is symmetric (nothing after is reachable at all).
                if body_exits != else_exits:
                    why = (
                        "code after an early "
                        f"{'return' if body_exits else 'fall-through'} "
                        f"behind a host-dependent branch ({host_why})"
                    )

    visit(tree, None)


# ---------------------------------------------------------------------------
# recompile-hazard rule family
# ---------------------------------------------------------------------------


def _rule_recompile_shape_branch(
    tree, mod: _Module, traced: set[int], rel: str, add
) -> None:
    """Python branching on ``.shape``/``.dtype`` inside traced code:
    legal (shapes are Python values under trace) but it specializes the
    compiled program per input shape — every new shape silently
    recompiles, the exact steps/s cliff the pjit paper chases.  Where
    the dispatch is intentional (a fixed bucket grid the factory
    precompiles), suppress with a justification."""
    for node, enclosing in _iter_with_enclosing(tree, mod):
        if enclosing is None or id(enclosing.node) not in traced:
            continue
        if not isinstance(node, (ast.If, ast.While, ast.IfExp)):
            continue
        # a guard clause (body is a lone `raise`, no else) is a shape
        # ASSERTION: the other program variant doesn't exist, invalid
        # shapes just error — not the dispatch hazard this rule hunts
        if (
            isinstance(node, ast.If)
            and not node.orelse
            and len(node.body) == 1
            and isinstance(node.body[0], ast.Raise)
        ):
            continue
        attrs = sorted({
            n.attr
            for n in ast.walk(node.test)
            if isinstance(n, ast.Attribute) and n.attr in ("shape", "dtype")
        })
        if attrs:
            add(node, "recompile-shape-branch",
                f"branch on .{'/.'.join(attrs)} inside traced function "
                f"'{enclosing.name}': the Python branch specializes the "
                "compiled program per input shape/dtype, so every new "
                "shape recompiles silently (steps/s craters with no "
                "error); pad/bucket inputs, or keep the dispatch but "
                "bound the bucket set and precompile it")


def _mutable_globals(tree: ast.Module) -> dict[str, str]:
    """Module-level names bound to mutable containers (plus names
    reassigned through ``global``), with a short description each."""
    out: dict[str, str] = {}
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t for t in node.targets if isinstance(t, ast.Name)]
            value = node.value
        elif isinstance(node, ast.AnnAssign) and isinstance(
            node.target, ast.Name
        ):
            targets = [node.target]
            value = node.value
        else:
            continue
        if value is None:
            continue
        kind = None
        if isinstance(value, _MUTABLE_LITERALS):
            kind = type(value).__name__.lower().replace("comp", " comp")
        elif isinstance(value, ast.Call):
            d = _dotted(value.func)
            if d in _MUTABLE_CTORS:
                kind = f"{d}()"
        if kind:
            for t in targets:
                out[t.id] = kind
    for node in ast.walk(tree):
        if isinstance(node, ast.Global):
            for name in node.names:
                out.setdefault(name, "reassigned via 'global'")
    return out


def _rule_recompile_mutable_global(
    tree, mod: _Module, traced: set[int], rel: str, add
) -> None:
    """A traced function reading a mutable module global bakes its
    trace-time value into the compiled program: later mutations silently
    don't apply (or, if the object participates in a hash, force
    retraces).  Pass the value as an argument or make it an immutable
    constant."""
    mutables = _mutable_globals(tree)
    if not mutables:
        return
    seen: set[tuple[int, str]] = set()
    for node, enclosing in _iter_with_enclosing(tree, mod):
        if enclosing is None or id(enclosing.node) not in traced:
            continue
        if not isinstance(node, ast.Name) or not isinstance(
            node.ctx, ast.Load
        ):
            continue
        name = node.id
        if name not in mutables:
            continue
        # shadowed by a parameter anywhere up the lexical chain -> the
        # load reads the local, not the module global
        if any(
            name in outer.params
            for outer in mod.enclosing_chain(enclosing)
        ):
            continue
        key = (id(enclosing.node), name)
        if key in seen:
            continue
        seen.add(key)
        add(node, "recompile-mutable-global",
            f"traced function '{enclosing.name}' closes over mutable "
            f"module global '{name}' ({mutables[name]}): its value is "
            "baked in at trace time — later mutations silently don't "
            "apply to the compiled program; pass it as an argument or "
            "freeze it into an immutable constant")


def _static_decls(call: ast.Call) -> tuple[set[int], set[str]] | None:
    """(static positions, static names) a jit call declares, else None."""
    if _dotted(call.func) not in ("jax.jit", "jit"):
        return None
    nums: set[int] = set()
    names: set[str] = set()
    for kw in call.keywords:
        if kw.arg not in ("static_argnums", "static_argnames"):
            continue
        consts = (
            [kw.value] if isinstance(kw.value, ast.Constant)
            else list(ast.walk(kw.value))
        )
        for e in consts:
            if isinstance(e, ast.Constant):
                if isinstance(e.value, int):
                    nums.add(e.value)
                elif isinstance(e.value, str):
                    names.add(e.value)
    if not nums and not names:
        return None
    return nums, names


def _rule_recompile_static_args(tree, mod: _Module, rel: str, add) -> None:
    """Hazards at ``jit(..., static_argnums/static_argnames=...)``
    boundaries, seen from the call sites of the jitted wrapper:

    * an unhashable literal (list/dict/set) as a static arg — jit hashes
      static args for its compile cache, so this throws at dispatch;
    * a freshly-constructed object (``Cfg(...)`` at the call site) — a
      new instance per call identity-hashes, so the compile cache
      misses EVERY call and the program silently recompiles each step
      (the fresh-PRNGKey-as-static class of bug).  Value-hashed
      built-ins (``tuple(...)``/``frozenset(...)``) are fine.
    """
    jitted: dict[str, tuple[set[int], set[str]]] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign) and isinstance(
            node.value, ast.Call
        ):
            decls = _static_decls(node.value)
            if decls is not None:
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        jitted[t.id] = decls
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                if isinstance(dec, ast.Call):
                    target = dec
                    if _is_partial(dec.func) and dec.args:
                        # @partial(jax.jit, static_argnames=...)
                        if _dotted(dec.args[0]) not in ("jax.jit", "jit"):
                            continue
                        target = ast.Call(
                            func=dec.args[0], args=[], keywords=dec.keywords
                        )
                    decls = _static_decls(target)
                    if decls is not None:
                        jitted[node.name] = decls
    if not jitted:
        return

    def check(arg: ast.AST, where: str) -> None:
        if isinstance(arg, _MUTABLE_LITERALS):
            add(arg, "recompile-unhashable-static",
                f"unhashable {type(arg).__name__.lower()} literal as the "
                f"static arg {where}: jit hashes static args for its "
                "compile cache — this raises at dispatch; pass a tuple/"
                "frozen structure (or make the arg traced)")
        elif isinstance(arg, ast.Call):
            d = _dotted(arg.func) or "<call>"
            if d in _VALUE_HASHED_CTORS or _is_partial(arg.func):
                return
            add(arg, "recompile-fresh-static",
                f"freshly-constructed '{d}(...)' as the static arg "
                f"{where}: a new instance per call identity-hashes, so "
                "the jit compile cache misses EVERY call — a silent "
                "recompile per step; construct it once at factory level "
                "(or use a value-hashed/immutable type)")

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Name
        ):
            continue
        decls = jitted.get(node.func.id)
        if decls is None:
            continue
        nums, names = decls
        for i, arg in enumerate(node.args):
            if i in nums:
                check(arg, f"(position {i}) of '{node.func.id}'")
        for kw in node.keywords:
            if kw.arg in names:
                check(kw.value, f"'{kw.arg}=' of '{node.func.id}'")


# ---------------------------------------------------------------------------
# package-level rule: dead event kinds (needs every module's emits)
# ---------------------------------------------------------------------------


def _collect_emitted_kinds(trees) -> set[str]:
    kinds: set[str] = set()
    for tree in trees:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _EMIT_ATTRS
            ):
                kind = _emit_kind_literal(node)
                if kind is not None:
                    kinds.add(kind)
    return kinds


def _rule_dead_event_kinds(
    trees, registry: Registry, events_rel: str, events_src: str | None
) -> list[Finding]:
    """Every EVENT_KINDS entry must be emitted somewhere in the package:
    a kind nothing emits is either dead weight or evidence the emitter
    was deleted while its dashboards still query the name.  Anchored at
    the registry line, so a justified keep is a suppression comment on
    that entry."""
    if not registry.event_kinds:
        return []
    emitted = _collect_emitted_kinds(trees)
    lines = (events_src or "").splitlines()
    findings: list[Finding] = []
    for kind in sorted(registry.event_kinds - emitted):
        line = registry.kind_lines.get(kind, 1)
        src_line = lines[line - 1] if 0 < line <= len(lines) else ""
        if suppressed(src_line, "obs-event-dead"):
            continue
        findings.append(Finding(
            events_rel, line, "obs-event-dead",
            f"event kind {kind!r} is registered in EVENT_KINDS but "
            "nothing in the package emits it; prune it (or suppress "
            "with a justification if an external emitter owns it)",
        ))
    return findings


def rel_suffix(rel: str) -> str:
    """'ddl_tpu/train/loop.py' -> 'train/loop.py' (module path within
    the package, for the per-module rule scopes)."""
    parts = Path(rel).parts
    if parts and parts[0] == "ddl_tpu":
        parts = parts[1:]
    return "/".join(parts)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def _run_rules(
    tree,
    mod: _Module,
    traced: set[int],
    rel: str,
    src: str,
    registry: Registry,
    reasons: dict[int, str] | None = None,
) -> list[Finding]:
    """Every per-module rule over one parsed module, with ``traced``
    supplied by the caller (local inference for ``lint_file``, the
    cross-module program inference for ``lint_package``)."""
    lines = src.splitlines()
    findings: list[Finding] = []

    def add(node: ast.AST, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 1)
        src_line = lines[line - 1] if 0 < line <= len(lines) else ""
        if suppressed(src_line, rule):
            return
        findings.append(Finding(rel, line, rule, message))

    _rule_traced_interop(tree, mod, traced, rel, add, reasons)
    _rule_excepts(tree, rel, add)
    _rule_compat(tree, rel, add)
    _rule_obs_events(tree, registry, rel, add)
    _rule_pspec(tree, mod, rel, add)
    _rule_pspec_hand_rolled(tree, mod, rel, add)
    _rule_donation(tree, mod, rel, add)
    _rule_exit_intent(tree, mod, rel, add)
    _rule_collective_symmetry(tree, mod, rel, add)
    _rule_recompile_shape_branch(tree, mod, traced, rel, add)
    _rule_recompile_mutable_global(tree, mod, traced, rel, add)
    _rule_recompile_static_args(tree, mod, rel, add)
    return findings


def lint_file(
    path: str | Path, repo_root: str | Path, registry: Registry
) -> list[Finding]:
    """Single-file run (explicit CLI paths, editor-on-save): every
    per-module rule with module-local traced inference — no cross-module
    propagation, no package-level rules."""
    path = Path(path)
    try:
        rel = path.relative_to(repo_root).as_posix()
    except ValueError:  # explicit file outside the repo (CLI paths arg)
        rel = path.as_posix()
    src = path.read_text()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 1, "syntax-error", str(e.msg))]
    mod = _Module(tree)
    traced = _infer_traced(mod)
    return sorted(_run_rules(tree, mod, traced, rel, src, registry))


def lint_package(
    package_root: str | Path,
    files: list[Path] | None = None,
    graph=None,
) -> list[Finding]:
    """Run every AST rule over the package with WHOLE-PROGRAM traced-set
    inference: the import/call graph (``callgraph.CallGraph``) is always
    built over the full package, so a host sync hidden behind a helper
    in another module is attributed correctly even when ``files``
    narrows the *reported* set (``lint --changed``).  Package-level
    rules (dead event kinds) run only on full-package reports.
    ``package_root`` is the ``ddl_tpu`` directory; paths in findings are
    relative to its parent (the repo root).  A caller that already built
    the ``graph`` (the ``--changed`` CLI computes the closure from one)
    passes it in to avoid a second full parse — it MUST reflect the
    current on-disk sources."""
    from ddl_tpu.analysis.callgraph import CallGraph

    package_root = Path(package_root)
    repo_root = package_root.parent
    registry = load_registry(package_root)
    if graph is None:
        graph = CallGraph(package_root)
    traced, reasons = infer_traced_program(graph)
    full_run = files is None
    if files is None:
        files = sorted(package_root.rglob("*.py"))
    findings: list[Finding] = []
    for f in files:
        f = Path(f)
        try:
            rel = f.relative_to(repo_root).as_posix()
        except ValueError:
            rel = f.as_posix()
        info = graph.by_rel.get(rel)
        if info is None:
            # outside the package, or a syntax error the graph skipped:
            # single-file fallback (reports the syntax error)
            findings.extend(lint_file(f, repo_root, registry))
            continue
        mod_reasons = {
            node_id: why
            for (mname, node_id), why in reasons.items()
            if mname == info.name
        }
        findings.extend(_run_rules(
            info.tree, info.mod, traced[info.name], rel, info.src,
            registry, mod_reasons,
        ))
    events_rel = f"{package_root.name}/obs/events.py"
    if full_run or any(
        Path(f).name == "events.py" for f in files
    ):
        events_info = graph.by_rel.get(events_rel)
        findings.extend(_rule_dead_event_kinds(
            [i.tree for i in graph.modules.values()],
            registry,
            events_rel,
            events_info.src if events_info is not None else None,
        ))
    return sorted(findings)
