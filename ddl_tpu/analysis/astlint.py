"""AST lint rules over the ddl_tpu package — no JAX import required.

The classes of bug these rules catch share one property: they are
*silent* on a TPU run.  A ``float()`` inside a jitted step either throws
a ConcretizationError at trace time (best case) or forces a host
round-trip per step (worst case — the step graph is cut and MFU halves
with no error anywhere); an unknown mesh axis in a ``PartitionSpec``
replicates the array instead of sharding it; an obs event emitted under
a typo'd name silently never matches any dashboard/CI query.

Engine: per module, build the set of **traced functions** — functions
whose code runs under a JAX trace — then apply host-interop rules only
inside that set (a ``float()`` in the host-side logging path is fine;
the same call inside ``loss_fn`` is a bug).  Traced functions are found
by reference, not by name:

* a function passed to (or decorating with) a JAX transform —
  ``jax.jit`` / ``grad`` / ``value_and_grad`` / ``vmap`` / ``shard_map``
  / ``lax.scan|cond|while_loop|fori_loop`` / ``checkpoint`` /
  ``pallas_call`` — is a traced root;
* **sink parameters** propagate interprocedurally within a module: if
  function ``F`` passes its parameter ``p`` into a transform (or into
  another function's sink parameter, or calls ``p`` from traced code),
  then any local function passed as ``p`` at an ``F`` call site is
  traced — this is how ``loss_fn`` handed through
  ``finalize_step_fns`` → ``jax.value_and_grad`` is found;
* functions lexically nested in a traced function, and functions called
  by name from traced code, are traced (closure to fixpoint).

Cross-module calls are not followed — the rules are per-file by design
(fast, no imports); the sharding-contract checker (``contracts.py``)
covers the cross-module composition at trace level.
"""

from __future__ import annotations

import ast
import dataclasses
from pathlib import Path

from ddl_tpu.analysis.findings import Finding, suppressed

__all__ = ["Registry", "lint_file", "lint_package", "load_registry", "MESH_AXES"]

# The mesh-axis vocabulary (parallel/mesh.py + parallel/sharding.py).
# PartitionSpec literals anywhere in the package must draw from this set
# (or from an axis tuple declared in a same-module Mesh(...) literal).
MESH_AXES = frozenset({"data", "pipe", "seq", "model", "expert"})

# Calls that put their function arguments under a JAX trace.
_TRANSFORMS = frozenset({
    "jax.jit", "jit", "nn.jit",
    "jax.grad", "jax.value_and_grad", "jax.vjp", "jax.jvp", "jax.linearize",
    "jax.vmap", "jax.pmap",
    "jax.shard_map", "shard_map",
    "jax.checkpoint", "jax.remat", "nn.remat", "checkpoint", "remat",
    "jax.eval_shape",
    "jax.lax.scan", "lax.scan", "jax.lax.cond", "lax.cond",
    "jax.lax.while_loop", "lax.while_loop",
    "jax.lax.fori_loop", "lax.fori_loop",
    "jax.lax.map", "lax.map",
    "jax.lax.associative_scan", "lax.associative_scan",
    "pl.pallas_call", "pallas_call",
})

# Host-synchronisation calls: inside traced code these either fail the
# trace or silently cut the compiled program at a host round-trip.
_HOST_SYNC_DOTTED = frozenset({
    "jax.device_get", "device_get",
    "np.asarray", "numpy.asarray", "np.array", "numpy.array",
    "jax.block_until_ready",
})
_HOST_SYNC_METHODS = frozenset({"item", "block_until_ready"})

_NONDET_DOTTED = frozenset({
    "time.time", "time.perf_counter", "time.monotonic", "time.time_ns",
    "datetime.datetime.now", "datetime.datetime.utcnow",
})

# Modules whose exception handling gates checkpoint/recovery decisions:
# an over-broad swallow here turns a real corruption into silent data
# loss, so `except Exception` without a re-raise is flagged.
_RECOVERY_MODULES = frozenset({
    "checkpoint.py",
    "coord.py",
    "supervisor.py",
    "train/recovery.py",
    "train/loop.py",
    "utils/preemption.py",
    "utils/backoff.py",
    "utils/faultinject.py",
    "obs/watchdog.py",
    "obs/steptrace.py",
})

# Step-function factory modules: every jitted train step must declare
# buffer donation (checked here) — whether the runtime honors it is the
# contract checker's runtime concern (compat.py strips donation on old
# jaxlib, an explicit waiver).
_STEP_MODULES = frozenset({
    "train/steps.py",
    "train/lm_steps.py",
    "train/vit_steps.py",
    "parallel/lm_pipeline.py",
})

# Step-factory modules where parameter/batch placement must come from
# the partition-rule engine (parallel/rules.py): a hand-written
# PartitionSpec axis literal here bypasses the rule tables the contract
# probes validate — the exact drift the engine exists to prevent.
# Derived specs (P(), P(None, *TOKEN_SPEC), axis *variables*) are fine;
# only hard-coded axis name strings are flagged.
_RULE_ENGINE_MODULES = frozenset({
    "train/steps.py",
    "train/lm_steps.py",
    "train/vit_steps.py",
})

# Pod-coordination paths: a process that hard-exits here without first
# publishing exit intent through the rendezvous strands its peers inside
# a dead collective until heartbeat ageout — the exact hang the coord
# layer exists to prevent.  Any os._exit/sys.exit use (call OR the
# function object handed around as an escape hatch) inside a function
# that never publishes intent is flagged.
_COORD_EXIT_MODULES = frozenset({
    "supervisor.py",
    "coord.py",
    "obs/watchdog.py",
})


@dataclasses.dataclass
class Registry:
    """Names the obs-event rule validates against, parsed from
    ``ddl_tpu/obs/events.py`` without importing it."""

    event_kinds: frozenset[str]
    anomaly_types: frozenset[str]


def load_registry(package_root: Path) -> Registry:
    """Parse EVENT_KINDS / ANOMALY_TYPES tuples out of obs/events.py."""
    src = (Path(package_root) / "obs" / "events.py").read_text()
    tree = ast.parse(src)
    found: dict[str, frozenset] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name):
            continue
        if target.id in ("EVENT_KINDS", "ANOMALY_TYPES"):
            values = [
                e.value
                for e in ast.walk(node.value)
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            ]
            found[target.id] = frozenset(values)
    return Registry(
        event_kinds=found.get("EVENT_KINDS", frozenset()),
        anomaly_types=found.get("ANOMALY_TYPES", frozenset()),
    )


# ---------------------------------------------------------------------------
# module model: functions, imports, traced-set inference
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> str | None:
    """'jax.lax.scan' for nested Attribute/Name chains, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


_FUNC_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)


@dataclasses.dataclass
class _Func:
    node: ast.AST
    name: str
    parent: "_Func | None"
    params: tuple[str, ...]
    sink_params: set[str] = dataclasses.field(default_factory=set)


class _Module:
    """One parsed module with enough structure for the traced-set
    inference: functions (with lexical nesting), every call site (with
    its innermost enclosing function), and the import alias map."""

    def __init__(self, tree: ast.Module) -> None:
        self.funcs: dict[int, _Func] = {}
        self.by_name: dict[str, list[_Func]] = {}
        self.calls: list[tuple[ast.Call, _Func | None]] = []
        self.imports: dict[str, str] = {}  # local alias -> real module
        self._index(tree)

    def _index(self, tree: ast.Module) -> None:
        stack: list[_Func] = []

        def visit(node: ast.AST) -> None:
            if isinstance(node, _FUNC_NODES):
                name = getattr(node, "name", "<lambda>")
                args = node.args
                params = tuple(
                    a.arg
                    for a in (
                        args.posonlyargs + args.args + args.kwonlyargs
                    )
                )
                fn = _Func(node, name, stack[-1] if stack else None, params)
                self.funcs[id(node)] = fn
                self.by_name.setdefault(name, []).append(fn)
                stack.append(fn)
                for child in ast.iter_child_nodes(node):
                    visit(child)
                stack.pop()
                return
            if isinstance(node, ast.Call):
                self.calls.append((node, stack[-1] if stack else None))
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    self.imports[alias.asname or alias.name.split(".")[0]] = (
                        alias.name
                    )
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (
                        f"{node.module}.{alias.name}" if node.module
                        else alias.name
                    )
            for child in ast.iter_child_nodes(node):
                visit(child)

        visit(tree)

    # -- resolution helpers -------------------------------------------------

    def resolve_func(self, expr: ast.AST) -> _Func | None:
        """A Name (or functools.partial(Name, ...)) referring to a
        module function, else None."""
        if isinstance(expr, ast.Call) and _is_partial(expr.func):
            return self.resolve_func(expr.args[0]) if expr.args else None
        if isinstance(expr, ast.Name):
            candidates = self.by_name.get(expr.id)
            return candidates[-1] if candidates else None
        return None

    def enclosing_chain(self, fn: _Func | None):
        while fn is not None:
            yield fn
            fn = fn.parent


def _is_partial(func_expr: ast.AST) -> bool:
    d = _dotted(func_expr)
    return d in ("partial", "functools.partial")


def _is_transform(call: ast.Call) -> bool:
    d = _dotted(call.func)
    if d in _TRANSFORMS:
        return True
    # partial(jax.jit, ...) / partial(lax.scan, ...) as the callee
    if _is_partial(call.func):
        return False  # handled at the inner-arg level by callers
    return False


def _func_args(call: ast.Call):
    """Every expression passed to a call (positional + keyword)."""
    yield from call.args
    for kw in call.keywords:
        if kw.value is not None:
            yield kw.value


def _infer_traced(mod: _Module) -> set[int]:
    """Fixpoint over {traced functions} x {sink parameters}."""
    traced: set[int] = set()

    # seeds: decorators that are transforms
    for fn in mod.funcs.values():
        for dec in getattr(fn.node, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            d = _dotted(target)
            if d in _TRANSFORMS:
                traced.add(id(fn.node))
            elif isinstance(dec, ast.Call) and _is_partial(dec.func):
                if dec.args and _dotted(dec.args[0]) in _TRANSFORMS:
                    traced.add(id(fn.node))

    changed = True
    while changed:
        changed = False

        for call, enclosing in mod.calls:
            callee_d = _dotted(call.func)

            # (1) function reference passed into a transform -> traced root
            transform_call = callee_d in _TRANSFORMS or (
                _is_partial(call.func)
                and call.args
                and _dotted(call.args[0]) in _TRANSFORMS
            )
            if transform_call:
                for arg in _func_args(call):
                    target = mod.resolve_func(arg)
                    if target is not None and id(target.node) not in traced:
                        traced.add(id(target.node))
                        changed = True
                # a parameter of an enclosing function fed to a transform
                # makes that parameter a sink
                for arg in _func_args(call):
                    base = arg
                    if isinstance(arg, ast.Call) and _is_partial(arg.func):
                        base = arg.args[0] if arg.args else arg
                    if isinstance(base, ast.Name) and enclosing is not None:
                        for outer in mod.enclosing_chain(enclosing):
                            if base.id in outer.params and (
                                base.id not in outer.sink_params
                            ):
                                outer.sink_params.add(base.id)
                                changed = True

            # (2) call to a local function with sink params: map args
            callee_fn = mod.resolve_func(call.func)
            if callee_fn is not None and callee_fn.sink_params:
                bound: list[tuple[str, ast.AST]] = []
                for i, arg in enumerate(call.args):
                    if i < len(callee_fn.params):
                        bound.append((callee_fn.params[i], arg))
                for kw in call.keywords:
                    if kw.arg is not None:
                        bound.append((kw.arg, kw.value))
                for pname, arg in bound:
                    if pname not in callee_fn.sink_params:
                        continue
                    target = mod.resolve_func(arg)
                    if target is not None and id(target.node) not in traced:
                        traced.add(id(target.node))
                        changed = True
                    base = arg
                    if isinstance(arg, ast.Call) and _is_partial(arg.func):
                        base = arg.args[0] if arg.args else arg
                    if isinstance(base, ast.Name) and enclosing is not None:
                        for outer in mod.enclosing_chain(enclosing):
                            if base.id in outer.params and (
                                base.id not in outer.sink_params
                            ):
                                outer.sink_params.add(base.id)
                                changed = True

            # (3) inside a traced function: called names become traced,
            # and a *called parameter* of an enclosing function is a sink
            # (accumulate_grads' scan body calling grad_fn)
            if enclosing is not None and id(enclosing.node) in traced:
                target = mod.resolve_func(call.func)
                if target is not None and id(target.node) not in traced:
                    traced.add(id(target.node))
                    changed = True
                if isinstance(call.func, ast.Name):
                    for outer in mod.enclosing_chain(enclosing):
                        if call.func.id in outer.params and (
                            call.func.id not in outer.sink_params
                        ):
                            outer.sink_params.add(call.func.id)
                            changed = True

        # (4) lexical nesting: children of traced functions are traced
        for fn in mod.funcs.values():
            if id(fn.node) in traced:
                continue
            if fn.parent is not None and id(fn.parent.node) in traced:
                traced.add(id(fn.node))
                changed = True

    return traced


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------


def _iter_with_enclosing(tree: ast.Module, mod: _Module):
    """(node, innermost enclosing _Func or None) for every node."""
    stack: list[_Func] = []

    def visit(node: ast.AST):
        entered = False
        if isinstance(node, _FUNC_NODES):
            stack.append(mod.funcs[id(node)])
            entered = True
        yield node, (stack[-1] if stack else None)
        for child in ast.iter_child_nodes(node):
            yield from visit(child)
        if entered:
            stack.pop()

    # yield with the *enclosing* function, so a FunctionDef node itself
    # reports under its own scope (fine for our rules)
    yield from visit(tree)


def _rule_traced_interop(
    tree, mod: _Module, traced: set[int], rel: str, add
) -> None:
    for node, enclosing in _iter_with_enclosing(tree, mod):
        if enclosing is None or id(enclosing.node) not in traced:
            continue
        if isinstance(node, ast.Call):
            d = _dotted(node.func)
            full = None
            if d is not None:
                first, *rest = d.split(".")
                full = ".".join([mod.imports.get(first, first)] + rest)
            if d in _HOST_SYNC_DOTTED or full in _HOST_SYNC_DOTTED:
                add(node, "host-sync",
                    f"{d}() inside traced function "
                    f"'{enclosing.name}' forces a host sync (or fails the "
                    "trace); keep device values on device until the period "
                    "fence")
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in _HOST_SYNC_METHODS
                and not node.args
            ):
                add(node, "host-sync",
                    f".{node.func.attr}() inside traced function "
                    f"'{enclosing.name}' forces a host sync per call")
            elif isinstance(node.func, ast.Name) and node.func.id == "float":
                add(node, "host-sync",
                    f"float() inside traced function '{enclosing.name}' "
                    "concretizes a tracer (host sync / trace error); use "
                    "jnp.float32 or .astype for dtype casts")
            elif full is not None:
                if d in _NONDET_DOTTED or full in _NONDET_DOTTED:
                    add(node, "nondeterminism",
                        f"{d}() inside traced function '{enclosing.name}': "
                        "wall-clock reads bake a constant into the compiled "
                        "program (and differ across hosts)")
                elif full.startswith(("random.", "numpy.random.")):
                    add(node, "nondeterminism",
                        f"{d}() inside traced function '{enclosing.name}': "
                        "Python/NumPy RNG is host-side and per-process; use "
                        "jax.random with an explicit key")
        elif isinstance(node, (ast.For, ast.comprehension)):
            it = node.iter
            is_set = isinstance(it, ast.Set) or (
                isinstance(it, ast.Call)
                and _dotted(it.func) in ("set", "frozenset")
            )
            if is_set:
                add(node if isinstance(node, ast.For) else it,
                    "nondeterminism",
                    f"iteration over a set inside traced function "
                    f"'{enclosing.name}': set order varies per process, so "
                    "traced program structure diverges across hosts; sort "
                    "or use a tuple")


def _rule_excepts(tree, rel: str, add) -> None:
    in_recovery = rel_suffix(rel) in _RECOVERY_MODULES
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            add(node, "bare-except",
                "bare 'except:' swallows KeyboardInterrupt/SystemExit too; "
                "name the exceptions (or 'except Exception' plus a re-raise)")
            continue
        if not in_recovery:
            continue
        names = []
        exprs = (
            node.type.elts if isinstance(node.type, ast.Tuple)
            else [node.type]
        )
        for e in exprs:
            d = _dotted(e)
            if d is not None:
                names.append(d.split(".")[-1])
        if any(n in ("Exception", "BaseException") for n in names):
            has_raise = any(
                isinstance(n, ast.Raise) for n in ast.walk(node)
            )
            if not has_raise:
                add(node, "broad-except",
                    f"'except {'/'.join(names)}' without re-raise in a "
                    "checkpoint/recovery path can mask corruption as "
                    "success; narrow the exception list or re-raise")


def _rule_compat(tree, rel: str, add) -> None:
    if rel_suffix(rel) == "compat.py":
        return
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            m = node.module or ""
            if m.startswith("jax.experimental.shard_map") or (
                m == "jax.experimental"
                and any(a.name in ("shard_map", "pjit") for a in node.names)
            ):
                add(node, "compat-bypass",
                    "legacy jax.experimental.shard_map/pjit import bypasses "
                    "the compat.py shim; use jax.shard_map / jax.jit "
                    "(compat installs them on old runtimes)")
            elif m.startswith("jax.experimental.pjit"):
                add(node, "compat-bypass",
                    "legacy pjit import; use jax.jit (compat.py guarantees "
                    "the modern surface)")
        elif isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d and (
                d.startswith("jax.experimental.shard_map")
                or d.startswith("jax.experimental.pjit")
            ):
                add(node, "compat-bypass",
                    f"direct {d} use bypasses the compat.py shim; use the "
                    "modern jax.* name")
            elif node.attr == "TPUCompilerParams":
                add(node, "compat-bypass",
                    "TPUCompilerParams is the legacy spelling; use "
                    "pltpu.CompilerParams (compat.py aliases it on old "
                    "runtimes)")
        elif isinstance(node, ast.Call):
            for kw in node.keywords:
                if kw.arg == "check_rep":
                    add(node, "compat-bypass",
                        "check_rep= is the legacy shard_map kwarg; pass "
                        "check_vma= (compat.py translates on old runtimes)")


def _rule_obs_events(tree, registry: Registry, rel: str, add) -> None:
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not isinstance(
            node.func, ast.Attribute
        ):
            continue
        if node.func.attr == "emit":
            kind = None
            if node.args and isinstance(node.args[0], ast.Constant):
                kind = node.args[0].value
            for kw in node.keywords:
                if kw.arg == "kind" and isinstance(kw.value, ast.Constant):
                    kind = kw.value.value
            if isinstance(kind, str) and kind not in registry.event_kinds:
                add(node, "obs-event-unregistered",
                    f"obs event kind {kind!r} is not in "
                    "obs/events.py EVENT_KINDS; register it (or fix the "
                    "typo) so dashboards and CI queries can rely on the "
                    "name")
        elif node.func.attr == "record":
            base = _dotted(node.func.value)
            if base is None or not base.split(".")[-1] == "anomaly":
                continue
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                t = node.args[1].value
                if isinstance(t, str) and t not in registry.anomaly_types:
                    add(node, "anomaly-type-unregistered",
                        f"anomaly type {t!r} is not in obs/events.py "
                        "ANOMALY_TYPES; register it so the obs summary and "
                        "alert queries see it")


def _pspec_names(tree, mod: _Module) -> set[str]:
    """Local aliases bound to jax.sharding.PartitionSpec."""
    names = set()
    for alias, real in mod.imports.items():
        if real.endswith("PartitionSpec"):
            names.add(alias)
    names.update({"PartitionSpec"})
    return names


def _rule_pspec(tree, mod: _Module, rel: str, add) -> None:
    pnames = _pspec_names(tree, mod)
    # axis names declared by a same-module Mesh((...), ("ring",)) literal
    # extend the allowed set (bench/comm.py builds its own ring mesh)
    extra: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _dotted(node.func) in (
            "Mesh", "jax.sharding.Mesh"
        ):
            for arg in list(node.args[1:]) + [
                kw.value for kw in node.keywords if kw.arg == "axis_names"
            ]:
                for e in ast.walk(arg):
                    if isinstance(e, ast.Constant) and isinstance(
                        e.value, str
                    ):
                        extra.add(e.value)
    allowed = MESH_AXES | extra
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d not in pnames and d != "jax.sharding.PartitionSpec":
            continue
        for arg in node.args:
            consts = (
                [arg] if isinstance(arg, ast.Constant)
                else list(ast.walk(arg)) if isinstance(arg, ast.Tuple)
                else []
            )
            for e in consts:
                if (
                    isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                    and e.value not in allowed
                ):
                    add(node, "pspec-unknown-axis",
                        f"PartitionSpec axis {e.value!r} is not a mesh axis "
                        f"({'/'.join(sorted(allowed))}); XLA would treat "
                        "the dimension as replicated — a silent memory/"
                        "throughput loss, never an error")


def _rule_pspec_hand_rolled(tree, mod: _Module, rel: str, add) -> None:
    """In the step-factory modules, flag ``PartitionSpec`` calls that
    hard-code axis-name strings: placement belongs to the family rule
    tables (``parallel/rules.py``), and a literal here silently bypasses
    the table the contract probes validate."""
    if rel_suffix(rel) not in _RULE_ENGINE_MODULES:
        return
    pnames = _pspec_names(tree, mod)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        d = _dotted(node.func)
        if d not in pnames and d != "jax.sharding.PartitionSpec":
            continue
        literals = []
        for arg in node.args:
            consts = (
                [arg] if isinstance(arg, ast.Constant)
                else list(ast.walk(arg)) if isinstance(arg, ast.Tuple)
                else []
            )
            literals.extend(
                e.value for e in consts
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            )
        if literals:
            add(node, "pspec-hand-rolled",
                f"hand-written PartitionSpec axis literal(s) "
                f"{sorted(set(literals))} in a step-factory module bypass "
                "the partition-rule engine; use the family rule table / "
                "named boundary specs from parallel/rules.py (derive "
                "variants like P(None, *TOKEN_SPEC))")


def _rule_donation(tree, mod: _Module, rel: str, add) -> None:
    if rel_suffix(rel) not in _STEP_MODULES:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or _dotted(node.func) not in (
            "jax.jit", "jit"
        ):
            continue
        if not node.args or not isinstance(node.args[0], ast.Name):
            continue
        if "train" not in node.args[0].id:
            continue
        kwargs = {kw.arg for kw in node.keywords}
        if not kwargs & {"donate_argnums", "donate_argnames"}:
            add(node, "donation-missing",
                f"jax.jit({node.args[0].id}, ...) without donate_argnums: "
                "the train state is copied instead of donated — 2x state "
                "HBM held across the update (compat.py strips donation on "
                "old runtimes; new step factories must still declare it)")


def _rule_exit_intent(tree, mod: _Module, rel: str, add) -> None:
    """In coord/supervisor/watchdog paths, an ``os._exit``/``sys.exit``
    whose enclosing function never publishes exit intent bypasses the
    pod protocol (the dying host's peers wait for its heartbeat to age
    out instead of reacting to the marker).  'Publishes intent' is
    lexical: some call in the same function whose name mentions
    ``intent`` (``coord.publish_exit_intent_from_env``,
    ``rv.publish_intent``)."""
    if rel_suffix(rel) not in _COORD_EXIT_MODULES:
        return
    intent_scopes: set[int | None] = set()
    exit_uses: list[tuple[ast.AST, _Func | None, str]] = []
    call_funcs: set[int] = set()  # Attribute nodes already seen as callees

    def scope_key(enclosing: _Func | None):
        return id(enclosing.node) if enclosing is not None else None

    for node, enclosing in _iter_with_enclosing(tree, mod):
        if isinstance(node, ast.Call):
            call_funcs.add(id(node.func))
            d = _dotted(node.func) or ""
            if "intent" in d.lower():
                intent_scopes.add(scope_key(enclosing))
            if d in ("os._exit", "sys.exit"):
                exit_uses.append((node, enclosing, f"{d}()"))
        elif isinstance(node, ast.Attribute) and id(node) not in call_funcs:
            d = _dotted(node)
            if d in ("os._exit", "sys.exit"):
                exit_uses.append((node, enclosing, d))
    for node, enclosing, what in exit_uses:
        if scope_key(enclosing) not in intent_scopes:
            add(node, "exit-without-intent",
                f"{what} in a coord/supervisor path without publishing "
                "exit intent first: peer hosts block inside the dead "
                "collective until heartbeat ageout; call "
                "coord.publish_exit_intent_from_env (or "
                "Rendezvous.publish_intent) before exiting")


def rel_suffix(rel: str) -> str:
    """'ddl_tpu/train/loop.py' -> 'train/loop.py' (module path within
    the package, for the per-module rule scopes)."""
    parts = Path(rel).parts
    if parts and parts[0] == "ddl_tpu":
        parts = parts[1:]
    return "/".join(parts)


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------


def lint_file(
    path: str | Path, repo_root: str | Path, registry: Registry
) -> list[Finding]:
    path = Path(path)
    try:
        rel = path.relative_to(repo_root).as_posix()
    except ValueError:  # explicit file outside the repo (CLI paths arg)
        rel = path.as_posix()
    src = path.read_text()
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding(rel, e.lineno or 1, "syntax-error", str(e.msg))]
    lines = src.splitlines()
    mod = _Module(tree)
    traced = _infer_traced(mod)
    findings: list[Finding] = []

    def add(node: ast.AST, rule: str, message: str) -> None:
        line = getattr(node, "lineno", 1)
        src_line = lines[line - 1] if 0 < line <= len(lines) else ""
        if suppressed(src_line, rule):
            return
        findings.append(Finding(rel, line, rule, message))

    _rule_traced_interop(tree, mod, traced, rel, add)
    _rule_excepts(tree, rel, add)
    _rule_compat(tree, rel, add)
    _rule_obs_events(tree, registry, rel, add)
    _rule_pspec(tree, mod, rel, add)
    _rule_pspec_hand_rolled(tree, mod, rel, add)
    _rule_donation(tree, mod, rel, add)
    _rule_exit_intent(tree, mod, rel, add)
    return sorted(findings)


def lint_package(
    package_root: str | Path, files: list[Path] | None = None
) -> list[Finding]:
    """Run every AST rule over the package (or an explicit file list).
    ``package_root`` is the ``ddl_tpu`` directory; paths in findings are
    relative to its parent (the repo root)."""
    package_root = Path(package_root)
    repo_root = package_root.parent
    registry = load_registry(package_root)
    if files is None:
        files = sorted(package_root.rglob("*.py"))
    findings: list[Finding] = []
    for f in files:
        findings.extend(lint_file(f, repo_root, registry))
    return sorted(findings)
