"""Static analysis for the ddl_tpu framework: ``ddl_tpu lint``.

Two engines behind one CLI (``analysis/cli.py``):

* **AST lint rules** (``astlint.py``) — host-sync and nondeterminism
  inside traced functions (traced sets inferred ACROSS module
  boundaries over the package call graph, ``callgraph.py``),
  collective-symmetry (host-conditional barriers/collectives),
  recompile hazards (traced shape/dtype branches, unhashable/fresh jit
  static args, mutable-global closures), bare/over-broad excepts in
  recovery paths, legacy-JAX spellings that bypass ``compat.py``,
  unregistered AND dead obs event names, unknown ``PartitionSpec``
  axes, missing jit donation.  Pure ``ast`` — no JAX import, runs
  anywhere in milliseconds.
* **Sharding contract checker** (``contracts.py``) — abstract-evals the
  registered step-function factories (CNN / LM / ViT / decode) under a
  small simulated mesh and validates the trace-level composition the
  AST rules cannot see: trace-clean lowering, no silently replicated
  large parameters, boundary specs drawn from the mesh vocabulary.

Findings flow through a committed baseline (``LINT_BASELINE.json``) and
per-line ``# ddl-lint: disable=<rule>`` suppressions (``findings.py``),
so CI fails only on *new* findings.  The mechanical classes are
auto-repairable: ``lint --fix`` (``fixes.py``) applies deterministic,
idempotent rewrites and ``--fix --check`` diffs them for CI;
``lint --changed`` scopes a run to the git diff plus its
reverse-dependency closure over the import graph.
"""

from ddl_tpu.analysis.findings import Finding, load_baseline, save_baseline

__all__ = ["Finding", "load_baseline", "save_baseline"]
