"""Static analysis for the ddl_tpu framework: ``ddl_tpu lint``.

Two engines behind one CLI (``analysis/cli.py``):

* **AST lint rules** (``astlint.py``) — host-sync and nondeterminism
  inside traced functions, bare/over-broad excepts in recovery paths,
  legacy-JAX spellings that bypass ``compat.py``, unregistered obs
  event names, unknown ``PartitionSpec`` axes, missing jit donation.
  Pure ``ast`` — no JAX import, runs anywhere in milliseconds.
* **Sharding contract checker** (``contracts.py``) — abstract-evals the
  registered step-function factories (CNN / LM / ViT / decode) under a
  small simulated mesh and validates the cross-module composition the
  AST rules cannot see: trace-clean lowering, no silently replicated
  large parameters, boundary specs drawn from the mesh vocabulary.

Findings flow through a committed baseline (``LINT_BASELINE.json``) and
per-line ``# ddl-lint: disable=<rule>`` suppressions (``findings.py``),
so CI fails only on *new* findings.
"""

from ddl_tpu.analysis.findings import Finding, load_baseline, save_baseline

__all__ = ["Finding", "load_baseline", "save_baseline"]
