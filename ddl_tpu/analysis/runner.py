"""Orchestration for ``ddl_tpu lint``: engines → baseline → verdict."""

from __future__ import annotations

import dataclasses
from pathlib import Path

from ddl_tpu.analysis.findings import (
    Finding,
    load_baseline,
    split_by_baseline,
)

__all__ = ["LintResult", "package_root", "run_lint"]


def package_root() -> Path:
    return Path(__file__).resolve().parents[1]


@dataclasses.dataclass
class LintResult:
    findings: list[Finding]  # everything produced this run
    new: list[Finding]  # not covered by the baseline -> CI fails
    known: list[Finding]  # baselined (pre-existing, tracked)
    stale: list[Finding]  # baseline entries no longer produced
    notes: list[str]  # informational (waivers, skips)

    @property
    def ok(self) -> bool:
        return not self.new


def run_lint(
    root: Path | None = None,
    files: list[Path] | None = None,
    contracts: bool = True,
    baseline_path: str | Path | None = None,
    scope_rels: set[str] | None = None,
    graph=None,
) -> LintResult:
    """Run both engines and fold in the baseline.

    ``contracts=False`` keeps the run pure-AST (no JAX import — usable
    on a log-analysis host, and what editors want on save).
    ``scope_rels`` narrows the *baseline comparison* to those
    repo-relative paths (``lint --changed``: baseline entries for
    out-of-scope files are neither matched nor reported stale).
    ``graph`` is an optional prebuilt, current ``CallGraph`` (the
    ``--changed`` CLI reuses the one it computed the closure from)."""
    from ddl_tpu.analysis.astlint import lint_package

    root = root or package_root()
    findings = list(lint_package(root, files=files, graph=graph))
    notes: list[str] = []
    if contracts and files is None:
        from ddl_tpu.analysis.contracts import run_contracts

        report = run_contracts()
        findings.extend(report.findings)
        notes.extend(report.notes)
    findings.sort()
    baseline = (
        load_baseline(baseline_path) if baseline_path is not None else []
    )
    if scope_rels is not None:
        baseline = [f for f in baseline if f.path in scope_rels]
    new, known, stale = split_by_baseline(findings, baseline)
    return LintResult(
        findings=findings, new=new, known=known, stale=stale, notes=notes
    )
