"""Deterministic autofixes for the mechanical lint finding classes.

``ddl_tpu lint --fix`` repairs exactly the findings whose fix is a
mechanical, behavior-preserving rewrite — the classes where the right
edit is implied by the finding itself:

* ``bare-except`` — ``except:`` → ``except Exception:`` (narrower is a
  human judgement; not swallowing SystemExit/KeyboardInterrupt is not);
* ``compat-bypass`` — legacy ``jax.experimental.shard_map`` imports
  rewritten to the compat-guaranteed ``from jax import shard_map``,
  ``check_rep=`` → ``check_vma=``, ``TPUCompilerParams`` →
  ``CompilerParams`` (the ``pjit`` variants need call-site rewrites and
  stay manual);
* ``pspec-hand-rolled`` — a ``PartitionSpec`` literal in a step-factory
  module whose value equals one of the ``parallel/rules.py`` boundary-
  spec constants is replaced by that constant's name, and the import is
  added/extended;
* ``obs-event-unregistered`` — the emitted-but-unregistered kind is
  appended to ``EVENT_KINDS`` in ``<package>/obs/events.py``;
* ``donation-missing`` — ``donate_argnums=(0,)`` is inserted into the
  flagged ``jax.jit(train_step, ...)`` call (behavior-safe: compat.py
  strips donation on runtimes that can't honor it, and on runtimes that
  can, donating the consumed train state is exactly what the finding
  demands).

The contract the tests pin: fixes are **deterministic** (same findings →
same bytes) and **idempotent** (fix → clean lint for these classes → a
second ``--fix`` run changes zero bytes).  ``--check`` renders the same
edits as a unified diff and writes nothing.

Everything here is span-edit based: per file, a list of
``(start_offset, end_offset, replacement)`` spans over the original
source, applied in one pass (descending, overlap-checked) — no
re-serialization of the AST, so untouched lines keep their bytes.
"""

from __future__ import annotations

import ast
import dataclasses
import difflib
import re
from pathlib import Path

from ddl_tpu.analysis.findings import Finding

__all__ = ["FIXABLE_RULES", "FixPlan", "plan_fixes"]

FIXABLE_RULES = frozenset({
    "bare-except",
    "compat-bypass",
    "pspec-hand-rolled",
    "obs-event-unregistered",
    "donation-missing",
})


@dataclasses.dataclass
class FixPlan:
    """The computed edits for one ``--fix`` run."""

    # abs path -> (old_source, new_source); only files that change
    edits: dict[Path, tuple[str, str]]
    fixed: list[Finding]
    unfixable: list[Finding]  # fixable-rule findings with no mechanical fix

    @property
    def changed(self) -> bool:
        return bool(self.edits)

    def unified_diff(self, repo_root: Path) -> str:
        chunks = []
        for path in sorted(self.edits):
            old, new = self.edits[path]
            try:
                rel = path.relative_to(repo_root).as_posix()
            except ValueError:
                rel = path.as_posix()
            chunks.append("".join(difflib.unified_diff(
                old.splitlines(keepends=True),
                new.splitlines(keepends=True),
                fromfile=f"a/{rel}", tofile=f"b/{rel}",
            )))
        return "".join(chunks)

    def apply(self) -> None:
        for path, (_old, new) in self.edits.items():
            path.write_text(new)


class _FileEditor:
    """Collects non-overlapping span edits over one source string."""

    def __init__(self, src: str) -> None:
        self.src = src
        self.spans: list[tuple[int, int, str]] = []
        self._line_offsets = [0]
        for line in src.splitlines(keepends=True):
            self._line_offsets.append(self._line_offsets[-1] + len(line))

    def offset(self, lineno: int, col: int) -> int:
        return self._line_offsets[lineno - 1] + col

    def line_span(self, lineno: int) -> tuple[int, int]:
        return self._line_offsets[lineno - 1], self._line_offsets[lineno]

    def line_text(self, lineno: int) -> str:
        a, b = self.line_span(lineno)
        return self.src[a:b]

    def node_span(self, node: ast.AST) -> tuple[int, int]:
        return (
            self.offset(node.lineno, node.col_offset),
            self.offset(node.end_lineno, node.end_col_offset),
        )

    def replace(self, start: int, end: int, text: str) -> None:
        self.spans.append((start, end, text))

    def replace_on_line(self, lineno: int, pattern: str, repl: str) -> bool:
        """Regex-replace the first match of ``pattern`` on ``lineno``."""
        a, _b = self.line_span(lineno)
        m = re.search(pattern, self.line_text(lineno))
        if m is None:
            return False
        self.replace(a + m.start(), a + m.end(), m.expand(repl))
        return True

    def render(self) -> str:
        spans = sorted(self.spans, key=lambda s: (s[0], s[1]))
        out = []
        pos = 0
        for start, end, text in spans:
            if start < pos:  # overlapping edits: keep the first, drop
                continue
            out.append(self.src[pos:start])
            out.append(text)
            pos = end
        out.append(self.src[pos:])
        return "".join(out)


# ---------------------------------------------------------------------------
# rule-table constants (for the pspec fixer), parsed without JAX
# ---------------------------------------------------------------------------


def _spec_value(call: ast.Call):
    """Structural value of a PartitionSpec(...) literal: a tuple whose
    entries are None, an axis string, or a tuple of axis strings — or
    None when any arg is not a literal."""
    out = []
    for arg in call.args:
        if isinstance(arg, ast.Constant) and (
            arg.value is None or isinstance(arg.value, str)
        ):
            out.append(arg.value)
        elif isinstance(arg, ast.Tuple) and all(
            isinstance(e, ast.Constant) and isinstance(e.value, str)
            for e in arg.elts
        ):
            out.append(tuple(e.value for e in arg.elts))
        else:
            return None
    return tuple(out)


def _rule_table_constants(package_root: Path) -> dict[tuple, str]:
    """value -> constant name for every module-level ``NAME = P(...)``
    literal in ``<package>/parallel/rules.py`` (first definition wins,
    so the mapping is deterministic)."""
    rules_py = package_root / "parallel" / "rules.py"
    try:
        tree = ast.parse(rules_py.read_text())
    except (OSError, SyntaxError):
        return {}
    out: dict[tuple, str] = {}
    for node in tree.body:
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            continue
        target = node.targets[0]
        if not isinstance(target, ast.Name) or not isinstance(
            node.value, ast.Call
        ):
            continue
        d = node.value.func
        name = d.id if isinstance(d, ast.Name) else getattr(d, "attr", "")
        if name not in ("P", "PartitionSpec"):
            continue
        value = _spec_value(node.value)
        if value is not None:
            out.setdefault(value, target.id)
    return out


# ---------------------------------------------------------------------------
# per-rule fixers
# ---------------------------------------------------------------------------


def _fix_bare_except(ed: _FileEditor, tree, finding: Finding) -> bool:
    return ed.replace_on_line(
        finding.line, r"\bexcept(\s*):", r"except Exception\1:"
    )


def _fix_compat(ed: _FileEditor, tree, finding: Finding) -> bool:
    msg = finding.message
    if "check_rep=" in msg:
        # the finding anchors at the Call; the kwarg may sit on a later
        # line of a multi-line call — use the keyword node's own span
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call) or node.lineno != finding.line:
                continue
            for kw in node.keywords:
                if kw.arg == "check_rep":
                    start = ed.offset(kw.lineno, kw.col_offset)
                    ed.replace(start, start + len("check_rep"), "check_vma")
                    return True
        return False
    if "TPUCompilerParams" in msg:
        return ed.replace_on_line(
            finding.line, r"\bTPUCompilerParams\b", "CompilerParams"
        )
    if "shard_map" in msg and "import" in msg:
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.ImportFrom)
                and node.lineno == finding.line
                and (node.module or "").startswith(
                    "jax.experimental.shard_map"
                )
                and len(node.names) == 1
                and node.names[0].name == "shard_map"
            ):
                alias = node.names[0]
                as_clause = f" as {alias.asname}" if alias.asname else ""
                start, end = ed.node_span(node)
                ed.replace(start, end, f"from jax import shard_map{as_clause}")
                return True
    return False  # pjit variants and compound imports stay manual


def _fix_donation(ed: _FileEditor, tree, finding: Finding) -> bool:
    """Insert ``donate_argnums=(0,)`` into the flagged ``jax.jit(...)``
    step-factory call (the train state is argument 0 by the step-fns
    convention the astlint rule checks)."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or node.lineno != finding.line:
            continue
        func = node.func
        fname = (
            func.id if isinstance(func, ast.Name)
            else getattr(func, "attr", "")
        )
        if fname != "jit" or not node.args:
            continue
        first = node.args[0]
        if not (isinstance(first, ast.Name) and "train" in first.id):
            continue
        if any(
            kw.arg in ("donate_argnums", "donate_argnames")
            for kw in node.keywords
        ):
            continue
        # anchor on the last argument's end, same discipline as
        # _register_event_kinds (never scan backwards over comments)
        last = max(
            list(node.args) + list(node.keywords),
            key=lambda n: (n.end_lineno, n.end_col_offset),
        )
        last_end = ed.offset(last.end_lineno, last.end_col_offset)
        close = ed.offset(node.end_lineno, node.end_col_offset) - 1
        tail = ed.src[last_end:close]
        if tail.lstrip().startswith(","):
            ins = last_end + tail.index(",") + 1
            prefix = ""
        else:
            ins = last_end
            prefix = ","
        if node.lineno != node.end_lineno:
            indent = re.match(r"\s*", ed.line_text(last.lineno)).group(0)
            text = prefix + f"\n{indent}donate_argnums=(0,),"
        else:
            text = prefix + " donate_argnums=(0,)"
        ed.replace(ins, ins, text)
        return True
    return False


_KIND_RE = re.compile(r"obs event kind '([^']+)'")


def _fix_pspec(
    ed: _FileEditor, tree, finding: Finding, constants: dict[tuple, str],
    needed_imports: set[str], used: set[int],
) -> bool:
    if not constants:
        return False
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or node.lineno != finding.line:
            continue
        if id(node) in used:
            continue  # two findings on one line: one node each
        func = node.func
        fname = (
            func.id if isinstance(func, ast.Name)
            else getattr(func, "attr", "")
        )
        if fname not in ("P", "PartitionSpec"):
            continue
        value = _spec_value(node)
        if value is None:
            continue
        name = constants.get(value)
        if name is None:
            continue
        start, end = ed.node_span(node)
        ed.replace(start, end, name)
        needed_imports.add(name)
        used.add(id(node))
        return True
    return False


def _ensure_rules_import(
    ed: _FileEditor, tree, package: str, names: set[str]
) -> None:
    """Add/extend ``from <package>.parallel.rules import ...`` so the
    constants the pspec fixer substituted resolve."""
    rules_mod = f"{package}.parallel.rules"
    for node in tree.body:
        if isinstance(node, ast.ImportFrom) and node.module == rules_mod:
            have = {a.name for a in node.names}
            if names <= have:
                return
            # rebuild preserving existing `as` aliases — dropping one
            # would break every use of the alias name
            clauses = {
                a.name: (
                    f"{a.name} as {a.asname}" if a.asname else a.name
                )
                for a in node.names
            }
            for n in names:
                clauses.setdefault(n, n)
            start, end = ed.node_span(node)
            ed.replace(
                start, end,
                f"from {rules_mod} import "
                + ", ".join(clauses[k] for k in sorted(clauses)),
            )
            return
    # no existing import: insert after the last top-level import (or the
    # module docstring, or at the top)
    last_import = None
    for node in tree.body:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            last_import = node
    line = (
        f"from {rules_mod} import {', '.join(sorted(names))}\n"
    )
    if last_import is not None:
        _a, b = ed.line_span(last_import.end_lineno)
        ed.replace(b, b, line)
    elif (
        tree.body
        and isinstance(tree.body[0], ast.Expr)
        and isinstance(tree.body[0].value, ast.Constant)
    ):
        _a, b = ed.line_span(tree.body[0].end_lineno)
        ed.replace(b, b, "\n" + line)
    else:
        ed.replace(0, 0, line)


def _register_event_kinds(ed: _FileEditor, tree, kinds: set[str]) -> bool:
    """Add spans appending ``kinds`` to the EVENT_KINDS tuple of an
    already-parsed events.py; composes with other edits to the same
    file through the shared editor."""
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "EVENT_KINDS"
            and isinstance(node.value, ast.Tuple)
        ):
            src = ed.src
            existing = {
                e.value
                for e in ast.walk(node.value)
                if isinstance(e, ast.Constant) and isinstance(e.value, str)
            }
            missing = sorted(kinds - existing)
            if not missing:
                return True  # already registered: nothing to do
            paren_end = ed.offset(
                node.value.end_lineno, node.value.end_col_offset
            )
            elts = node.value.elts
            if not elts:
                # empty tuple `()` — insert directly before the paren
                text = ", ".join(f'"{k}"' for k in missing) + ","
                ed.replace(paren_end - 1, paren_end - 1, text)
                return True
            # anchor on the LAST ELEMENT's end (never a backwards text
            # scan — a trailing `# comment` on that line must stay a
            # comment, not swallow the inserted comma)
            last = elts[-1]
            last_end = ed.offset(last.end_lineno, last.end_col_offset)
            tail = src[last_end:paren_end - 1]
            if tail.lstrip().startswith(","):
                # existing trailing comma: insert just after it
                ins = last_end + tail.index(",") + 1
                prefix = ""
            else:
                ins = last_end
                prefix = ","
            multiline = node.value.lineno != node.value.end_lineno
            if multiline:
                text = prefix + "".join(
                    f'\n    "{k}",' for k in missing
                )
            else:
                text = prefix + " " + ", ".join(f'"{k}"' for k in missing)
            ed.replace(ins, ins, text)
            return True
    return False


# ---------------------------------------------------------------------------
# planner
# ---------------------------------------------------------------------------


def plan_fixes(
    findings: list[Finding],
    repo_root: str | Path,
    package_root: str | Path,
) -> FixPlan:
    """Compute the edits for every fixable finding.  ``findings`` may
    include non-fixable rules (ignored); the same finding list a lint
    run produced keeps line numbers valid."""
    repo_root = Path(repo_root)
    package_root = Path(package_root)
    constants = _rule_table_constants(package_root)
    events_py = (package_root / "obs" / "events.py").resolve()
    by_path: dict[str, list[Finding]] = {}
    kind_findings: list[Finding] = []
    event_kinds: set[str] = set()
    for f in findings:
        if f.rule not in FIXABLE_RULES:
            continue
        if f.rule == "obs-event-unregistered":
            # resolved by editing the registry, not the emitting line
            m = _KIND_RE.search(f.message)
            if m is not None:
                event_kinds.add(m.group(1))
                kind_findings.append(f)
            continue
        by_path.setdefault(f.path, []).append(f)
    if event_kinds:
        # route the registry edit through the normal per-file pass so it
        # composes with line fixes landing in events.py itself
        by_path.setdefault(
            events_py.relative_to(repo_root).as_posix()
            if events_py.is_relative_to(repo_root) else str(events_py),
            [],
        )

    edits: dict[Path, tuple[str, str]] = {}
    fixed: list[Finding] = []
    unfixable: list[Finding] = []
    kinds_handled = False

    for rel in sorted(by_path):
        path = Path(rel)
        if not path.is_absolute():
            path = repo_root / rel
        try:
            src = path.read_text()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            unfixable.extend(by_path[rel])
            continue
        ed = _FileEditor(src)
        needed_imports: set[str] = set()
        used_pspec_nodes: set[int] = set()
        for f in sorted(by_path[rel]):
            if f.rule == "bare-except":
                ok = _fix_bare_except(ed, tree, f)
            elif f.rule == "compat-bypass":
                ok = _fix_compat(ed, tree, f)
            elif f.rule == "donation-missing":
                ok = _fix_donation(ed, tree, f)
            else:  # pspec-hand-rolled
                ok = _fix_pspec(
                    ed, tree, f, constants, needed_imports,
                    used_pspec_nodes,
                )
            (fixed if ok else unfixable).append(f)
        if needed_imports:
            _ensure_rules_import(ed, tree, package_root.name, needed_imports)
        if event_kinds and path.resolve() == events_py:
            registered = _register_event_kinds(ed, tree, event_kinds)
            (fixed if registered else unfixable).extend(kind_findings)
            kinds_handled = True
        if ed.spans:
            new = ed.render()
            if new != src:
                edits[path] = (src, new)

    if event_kinds and not kinds_handled:
        # registry missing OR unreadable/unparseable: the kind findings
        # must still surface as not-auto-fixable, never silently vanish
        unfixable.extend(kind_findings)

    return FixPlan(edits=edits, fixed=sorted(fixed), unfixable=sorted(unfixable))
