"""Findings, per-line suppressions, and the committed baseline.

A finding is one diagnostic from either analysis engine (the AST rules in
``astlint.py`` or the sharding-contract probes in ``contracts.py``):
``rule`` (stable id), ``path`` (repo-relative), ``line`` and ``message``.

Two escape hatches keep the linter honest instead of nagging:

* **per-line suppression** — a trailing ``# ddl-lint: disable=<rule>``
  (or bare ``# ddl-lint: disable`` for every rule) on the flagged line
  acknowledges an intentional violation *in the code itself*, next to
  the justification comment a reviewer will demand anyway;
* **the baseline** — ``LINT_BASELINE.json`` at the repo root records
  pre-existing findings so wiring the linter into CI doesn't require
  fixing the world first.  A finding matches a baseline entry on
  ``(rule, path, message)`` (line numbers drift with unrelated edits);
  CI fails only on findings *not* in the baseline, and reports stale
  entries so the baseline shrinks as code improves
  (``--update-baseline`` rewrites it).
"""

from __future__ import annotations

import dataclasses
import json
import re
from pathlib import Path

__all__ = [
    "Finding",
    "suppressed",
    "load_baseline",
    "save_baseline",
    "split_by_baseline",
]

_SUPPRESS_RE = re.compile(r"#\s*ddl-lint:\s*disable(?:=([\w\-,\s]+))?")


@dataclasses.dataclass(frozen=True, order=True)
class Finding:
    path: str  # repo-relative, posix separators
    line: int
    rule: str
    message: str

    @property
    def key(self) -> tuple[str, str, str]:
        """Baseline-matching key: line numbers drift, content doesn't."""
        return (self.rule, self.path, self.message)

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    def format(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


def suppressed(source_line: str, rule: str) -> bool:
    """True when ``source_line`` carries a suppression comment covering
    ``rule`` — ``# ddl-lint: disable`` (all rules) or
    ``# ddl-lint: disable=rule-a,rule-b``."""
    m = _SUPPRESS_RE.search(source_line)
    if not m:
        return False
    if m.group(1) is None:
        return True
    rules = {r.strip() for r in m.group(1).split(",")}
    return rule in rules


def load_baseline(path: str | Path) -> list[Finding]:
    data = json.loads(Path(path).read_text())
    return [Finding(**entry) for entry in data["findings"]]


def save_baseline(path: str | Path, findings: list[Finding]) -> None:
    payload = {
        "version": 1,
        "findings": [f.to_dict() for f in sorted(findings)],
    }
    Path(path).write_text(json.dumps(payload, indent=1) + "\n")


def split_by_baseline(
    findings: list[Finding], baseline: list[Finding]
) -> tuple[list[Finding], list[Finding], list[Finding]]:
    """``(new, known, stale)``: findings absent from the baseline (CI
    fails on these), findings the baseline covers, and baseline entries
    no longer produced (candidates for ``--update-baseline``)."""
    known_keys = {f.key for f in baseline}
    current_keys = {f.key for f in findings}
    new = [f for f in findings if f.key not in known_keys]
    known = [f for f in findings if f.key in known_keys]
    stale = [f for f in baseline if f.key not in current_keys]
    return new, known, stale
