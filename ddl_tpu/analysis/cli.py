"""``ddl_tpu lint`` — the CLI front of the static-analysis subsystem.

    python -m ddl_tpu.cli lint                       # human-readable
    python -m ddl_tpu.cli lint --json                # machine-readable
    python -m ddl_tpu.cli lint --baseline LINT_BASELINE.json
    python -m ddl_tpu.cli lint --baseline LINT_BASELINE.json --update-baseline
    python -m ddl_tpu.cli lint --no-contracts path/to/file.py ...
    python -m ddl_tpu.cli lint --changed             # git-diff scope +
                                                     #  reverse-dep closure
    python -m ddl_tpu.cli lint --fix                 # autofix mechanical
                                                     #  findings, then re-lint
    python -m ddl_tpu.cli lint --fix --check         # CI gate: diff + exit 1
                                                     #  if fixes are pending
    python -m ddl_tpu.cli lint --hlo                 # compiled-IR pass:
                                                     #  lower+compile the probe
                                                     #  programs, rule-check
                                                     #  the collective/memory
                                                     #  inventory
    python -m ddl_tpu.cli lint --hlo --hlo-baseline HLO_BASELINE.json
    python -m ddl_tpu.cli lint --hlo --update-baseline

Exit codes: 0 = clean (every finding baselined or suppressed), 1 = new
findings.  With ``--baseline`` the committed ``LINT_BASELINE.json``
gates CI: pre-existing findings don't fail the build, new ones do, and
stale entries are reported so the baseline only ever shrinks
(``--update-baseline`` rewrites it after intentional changes).

``--fix`` applies the deterministic autofixes (``analysis/fixes.py``:
bare excepts, compat-bypass imports/kwargs, hand-rolled PartitionSpec
literals → rule-table constants, unregistered emitted event kinds →
EVENT_KINDS) and then re-lints; a second ``--fix`` run is a byte-level
no-op.  ``--fix --check`` prints the unified diff instead of writing
and exits nonzero when any mechanical fix is pending — the pre-commit /
CI twin of ``git diff --exit-code``.

``--changed`` lints the modules git says changed (worktree vs HEAD,
staged + untracked) PLUS their reverse-dependency closure over the
package import graph (``analysis/callgraph.py``) — the whole set whose
verdict the edit can affect, because traced-set inference crosses
module boundaries.  Contract probes are skipped (fast pre-commit use);
the AST pass still builds the full-package call graph, so cross-module
findings inside the scope are exact, not approximated.

``--package-root DIR`` lints an alternate package tree (fixture
packages in tests); the baseline default and the fixers' registry/rule
-table lookups follow it.

``--hlo`` runs the *compiled-IR* pass (``analysis/hlolint.py``) instead
of the AST/contract pass: every contract probe program is lowered and
compiled on its simulated mesh, the StableHLO/optimized-HLO text is
parsed into a per-program collective + memory-traffic inventory, and
the IR rule family (oversized-all-gather, zero-missing-reduce-scatter,
pipeline-collective-symmetry, steady-state-copy-hotspot,
shape-specialized-constant) runs over it.  ``--hlo-baseline
HLO_BASELINE.json`` drift-gates the inventory against the committed
snapshot — a new collective kind/axis, a count increase, >10% payload
growth, a lost donation alias, or copy-traffic growth fails the run,
while shrinks and fingerprint-only changes are reported as stale
entries.  ``--hlo --update-baseline`` rewrites the snapshot after
intentional changes; ``--hlo --changed`` probes only the programs whose
factory module is in the changed set's reverse-dependency closure.
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

__all__ = ["main"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ddl_tpu lint", description=__doc__.splitlines()[0]
    )
    ap.add_argument(
        "paths", nargs="*",
        help="specific files to lint (default: the whole package; "
        "explicit paths run the AST rules only, without cross-module "
        "inference)",
    )
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline JSON: findings listed there do not fail the run",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite --baseline (default LINT_BASELINE.json) with the "
        "current findings and exit 0",
    )
    ap.add_argument(
        "--no-contracts", action="store_true",
        help="skip the sharding-contract probes (AST rules only — "
        "no JAX, runs in milliseconds)",
    )
    ap.add_argument(
        "--fix", action="store_true",
        help="apply deterministic autofixes for the mechanical finding "
        "classes, then re-lint (implies --no-contracts)",
    )
    ap.add_argument(
        "--check", action="store_true",
        help="with --fix: print the unified diff of pending fixes, "
        "write nothing, exit 1 if any fix is pending",
    )
    ap.add_argument(
        "--changed", action="store_true",
        help="lint only git-changed package modules plus their "
        "reverse-dependency closure (skips contract probes)",
    )
    ap.add_argument(
        "--package-root", default=None, metavar="DIR",
        help="lint this package directory instead of the installed "
        "ddl_tpu (fixture packages in tests)",
    )
    ap.add_argument(
        "--hlo", action="store_true",
        help="run the compiled-IR pass (lower + compile the probe "
        "programs, inventory collectives/memory traffic, apply the IR "
        "rule family) instead of the AST/contract pass",
    )
    ap.add_argument(
        "--hlo-baseline", default=None, metavar="FILE",
        help="with --hlo: drift-gate the inventory against this "
        "committed HLO_BASELINE.json snapshot",
    )
    args = ap.parse_args(argv)
    if args.check and not args.fix:
        ap.error("--check requires --fix")
    if args.fix and args.update_baseline:
        ap.error("--fix and --update-baseline are mutually exclusive")
    if args.changed and args.paths:
        ap.error("--changed and explicit paths are mutually exclusive")
    if args.changed and args.update_baseline:
        # a scoped run sees only the closure's findings — rewriting the
        # baseline from it would silently delete every out-of-scope entry
        ap.error("--update-baseline needs a full run, not --changed")
    if args.hlo_baseline and not args.hlo:
        ap.error("--hlo-baseline requires --hlo")
    if args.hlo:
        for flag, name in (
            (args.fix, "--fix"), (args.check, "--check"),
            (args.no_contracts, "--no-contracts"),
            (bool(args.paths), "explicit paths"),
            (bool(args.baseline), "--baseline"),
            (bool(args.package_root), "--package-root"),
        ):
            if flag:
                ap.error(
                    f"--hlo and {name} are mutually exclusive (the IR "
                    "pass probes whole programs, has its own baseline, "
                    "and has no autofixes)"
                )

    from ddl_tpu.analysis.findings import save_baseline
    from ddl_tpu.analysis.runner import package_root, run_lint

    pkg = (
        Path(args.package_root).resolve()
        if args.package_root else package_root()
    )
    repo_root = pkg.parent
    if args.hlo:
        return _hlo_main(args, repo_root, pkg)
    files = [Path(p) for p in args.paths] or None
    notes: list[str] = []
    graph = None  # prebuilt by --changed; reused by the first lint pass

    if args.changed:
        from ddl_tpu.analysis.callgraph import (
            CallGraph,
            changed_package_files,
        )

        changed = changed_package_files(repo_root)
        if changed is None:
            print("lint --changed: git unavailable; run a full lint")
            return 2
        graph = CallGraph(pkg)  # reused by lint_once below
        changed_mods = {
            graph.by_rel[rel].name
            for rel in changed if rel in graph.by_rel
        }
        if not changed_mods:
            print("lint --changed: no changed package modules")
            return 0
        closure = graph.reverse_closure(changed_mods)
        files = sorted(graph.modules[n].path for n in closure)
        notes.append(
            f"--changed scope: {len(changed_mods)} changed module(s) + "
            f"{len(closure) - len(changed_mods)} reverse dependent(s)"
        )

    baseline_path = args.baseline
    if args.update_baseline and baseline_path is None:
        baseline_path = repo_root / "LINT_BASELINE.json"
    contracts = (
        not args.no_contracts
        and files is None
        and not args.fix
        and not args.changed
        # the contract probes build the REAL package's step factories;
        # they don't apply to an alternate --package-root tree
        and args.package_root is None
    )
    scope_rels = (
        {
            Path(f).resolve().relative_to(repo_root).as_posix()
            for f in files
        }
        if args.changed else None
    )

    def lint_once(reuse_graph=None):
        return run_lint(
            root=pkg,
            files=files,
            contracts=contracts,
            baseline_path=(
                baseline_path
                if baseline_path and Path(baseline_path).exists()
                else None
            ),
            scope_rels=scope_rels,
            graph=reuse_graph,
        )

    result = lint_once(reuse_graph=graph)

    if args.fix:
        from ddl_tpu.analysis.fixes import plan_fixes

        plan = plan_fixes(result.findings, repo_root, pkg)
        if args.check:
            if plan.changed:
                print(plan.unified_diff(repo_root), end="")
                print(
                    f"lint --fix --check: {len(plan.fixed)} mechanical "
                    "fix(es) pending (nothing written); run "
                    "`ddl_tpu lint --fix`"
                )
                return 1
            print("lint --fix --check: nothing to fix")
            return 0
        if plan.changed:
            plan.apply()
            print(
                f"fixed {len(plan.fixed)} finding(s) in "
                f"{len(plan.edits)} file(s)"
            )
            for path in sorted(plan.edits):
                try:
                    print(f"  {path.relative_to(repo_root)}")
                except ValueError:
                    print(f"  {path}")
        else:
            print("lint --fix: nothing to fix")
        for f in plan.unfixable:
            print(f"not auto-fixable: {f.format()}")
        # re-lint so the verdict reflects the repaired tree (fresh
        # graph: --fix may have rewritten sources on disk)
        result = lint_once()

    if args.update_baseline:
        save_baseline(baseline_path, result.findings)
        print(
            f"wrote {len(result.findings)} finding(s) to {baseline_path}"
        )
        return 0

    notes = notes + result.notes
    if args.as_json:
        print(json.dumps(
            {
                "new": [f.to_dict() for f in result.new],
                "baselined": [f.to_dict() for f in result.known],
                "stale_baseline": [f.to_dict() for f in result.stale],
                "notes": notes,
                "ok": result.ok,
            },
            indent=1,
        ))
        return 0 if result.ok else 1

    for f in result.new:
        print(f.format())
    for note in notes:
        print(f"note: {note}")
    if result.known:
        print(f"{len(result.known)} baselined finding(s) (not failing)")
    if result.stale:
        print(
            f"{len(result.stale)} stale baseline entr(ies) — fixed or "
            "moved; run --update-baseline to shrink the baseline:"
        )
        for f in result.stale:
            print(f"  stale: {f.format()}")
    if result.ok:
        print("lint: clean")
        return 0
    print(f"lint: {len(result.new)} new finding(s)")
    return 1


def _hlo_main(args, repo_root: Path, pkg: Path) -> int:
    """The ``lint --hlo`` flow: probe selection (--changed), the IR
    pass, baseline update/drift, reporting."""
    from ddl_tpu.analysis.hlolint import (
        affected_probes,
        run_hlo_lint,
        save_hlo_baseline,
    )

    probes = None  # None = every registered probe
    notes: list[str] = []
    if args.changed:
        from ddl_tpu.analysis.callgraph import (
            CallGraph,
            changed_package_files,
        )

        changed = changed_package_files(repo_root)
        if changed is None:
            print("lint --changed: git unavailable; run a full lint")
            return 2
        graph = CallGraph(pkg)
        changed_mods = {
            graph.by_rel[rel].name
            for rel in changed if rel in graph.by_rel
        }
        if not changed_mods:
            print("lint --hlo --changed: no changed package modules")
            return 0
        closure = graph.reverse_closure(changed_mods)
        if closure & {
            "ddl_tpu.analysis.hlolint", "ddl_tpu.analysis.contracts"
        }:
            # the engine itself moved: every inventory may change
            notes.append(
                "--changed scope reaches the IR lint engine; probing "
                "every program"
            )
        else:
            probes = affected_probes(closure)
            if not probes:
                print(
                    "lint --hlo --changed: no probe program is affected "
                    f"by the {len(changed_mods)} changed module(s)"
                )
                return 0
            notes.append(
                f"--changed scope: probing {', '.join(probes)}"
            )

    baseline_path = args.hlo_baseline
    if baseline_path is None and args.update_baseline:
        baseline_path = repo_root / "HLO_BASELINE.json"

    result = run_hlo_lint(
        probes=probes,
        baseline_path=None if args.update_baseline else baseline_path,
    )

    if args.update_baseline:
        broken = [
            f for f in result.findings if f.rule == "hlo-probe-build"
        ]
        if broken:
            for f in broken:
                print(f.format())
            print(
                "lint --hlo --update-baseline: refusing to write an "
                "incomplete baseline while probes fail to build"
            )
            return 1
        save_hlo_baseline(baseline_path, result.baseline_programs())
        print(
            f"wrote {len(result.inventories)} program inventories to "
            f"{baseline_path}"
        )
        return 0

    notes = notes + result.notes
    if args.as_json:
        print(json.dumps(
            {
                "findings": [f.to_dict() for f in result.findings],
                "notes": notes,
                "stale_baseline": result.stale,
                "programs": result.baseline_programs(),
                "ok": result.ok,
            },
            indent=1,
        ))
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.format())
    for note in notes:
        print(f"note: {note}")
    if result.stale:
        print(
            f"{len(result.stale)} stale HLO baseline entr(ies) — run "
            "--hlo --update-baseline to refresh:"
        )
        for s in result.stale:
            print(f"  stale: {s}")
    if result.ok:
        print(
            f"lint --hlo: clean "
            f"({len(result.inventories)} programs inventoried)"
        )
        return 0
    print(f"lint --hlo: {len(result.findings)} finding(s)")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
