"""``ddl_tpu lint`` — the CLI front of the static-analysis subsystem.

    python -m ddl_tpu.cli lint                       # human-readable
    python -m ddl_tpu.cli lint --json                # machine-readable
    python -m ddl_tpu.cli lint --baseline LINT_BASELINE.json
    python -m ddl_tpu.cli lint --baseline LINT_BASELINE.json --update-baseline
    python -m ddl_tpu.cli lint --no-contracts path/to/file.py ...

Exit codes: 0 = clean (every finding baselined or suppressed), 1 = new
findings.  With ``--baseline`` the committed ``LINT_BASELINE.json``
gates CI: pre-existing findings don't fail the build, new ones do, and
stale entries are reported so the baseline only ever shrinks
(``--update-baseline`` rewrites it after intentional changes).
"""

from __future__ import annotations

import argparse
import json
from pathlib import Path

__all__ = ["main"]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="ddl_tpu lint", description=__doc__.splitlines()[0]
    )
    ap.add_argument(
        "paths", nargs="*",
        help="specific files to lint (default: the whole package; "
        "explicit paths run the AST rules only)",
    )
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument(
        "--baseline", default=None, metavar="FILE",
        help="baseline JSON: findings listed there do not fail the run",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite --baseline (default LINT_BASELINE.json) with the "
        "current findings and exit 0",
    )
    ap.add_argument(
        "--no-contracts", action="store_true",
        help="skip the sharding-contract probes (AST rules only — "
        "no JAX, runs in milliseconds)",
    )
    args = ap.parse_args(argv)

    from ddl_tpu.analysis.findings import save_baseline
    from ddl_tpu.analysis.runner import package_root, run_lint

    files = [Path(p) for p in args.paths] or None
    baseline_path = args.baseline
    if args.update_baseline and baseline_path is None:
        baseline_path = package_root().parent / "LINT_BASELINE.json"

    result = run_lint(
        files=files,
        contracts=not args.no_contracts and files is None,
        baseline_path=(
            baseline_path
            if baseline_path and Path(baseline_path).exists()
            else None
        ),
    )

    if args.update_baseline:
        save_baseline(baseline_path, result.findings)
        print(
            f"wrote {len(result.findings)} finding(s) to {baseline_path}"
        )
        return 0

    if args.as_json:
        print(json.dumps(
            {
                "new": [f.to_dict() for f in result.new],
                "baselined": [f.to_dict() for f in result.known],
                "stale_baseline": [f.to_dict() for f in result.stale],
                "notes": result.notes,
                "ok": result.ok,
            },
            indent=1,
        ))
        return 0 if result.ok else 1

    for f in result.new:
        print(f.format())
    for note in result.notes:
        print(f"note: {note}")
    if result.known:
        print(f"{len(result.known)} baselined finding(s) (not failing)")
    if result.stale:
        print(
            f"{len(result.stale)} stale baseline entr(ies) — fixed or "
            "moved; run --update-baseline to shrink the baseline:"
        )
        for f in result.stale:
            print(f"  stale: {f.format()}")
    if result.ok:
        print("lint: clean")
        return 0
    print(f"lint: {len(result.new)} new finding(s)")
    return 1


if __name__ == "__main__":
    raise SystemExit(main())
