"""Sharding-contract checker: abstract-eval the registered step functions.

The AST rules (``astlint.py``) see one file at a time; the bugs that cost
the most MFU live in the *composition* — a rule-table edit in
``parallel/sharding.py`` that quietly drops the ``model`` axis from the
MLP kernels replicates gigabytes per device without a single error
anywhere.  This module catches that class at trace level: each
registered step-function factory (``train/steps.py``,
``train/lm_steps.py``, ``train/vit_steps.py``, ``infer/decode.py``) is
built against a small **simulated mesh** (XLA host-platform devices — no
TPU required, the same trick the test suite uses) and validated:

* the factory's declared boundary contract (the ``.contract`` dict every
  factory attaches to its jitted train/generate function) names only
  real mesh axes, and its batch dimension is actually sharded over
  ``data`` — not silently replicated;
* the factory's **partition-rule table** (``parallel/rules.py``, carried
  in the contract as ``rule_table``) resolves every parameter leaf, its
  specs draw only on real mesh axes, and every ≥``REPLICATION_THRESHOLD``
  leaf is either sharded or replicated by an *explicit rule* — the rule
  IS the waiver, there is no hand-maintained waiver list anymore;
* the jitted program **lowers cleanly** with abstract inputs under the
  contract shardings (unknown axes, divisibility violations, and
  rule-table/spec disagreements all surface here as trace errors);
* no parameter leaf above ``REPLICATION_THRESHOLD`` elements is fully
  replicated when the mesh has a >1 axis to shard it over (unless the
  factory's contract says replication is by design — CNN DDP, serving
  replicas — or the rule table replicates it explicitly);
* with ``zero_sharding`` the optimizer moments of every eligible large
  leaf actually carry the ``data`` axis, and the probe reports the
  measured per-device optimizer-state bytes vs the replicated layout
  (the ~(dp-1)/dp reduction of PAPERS.md's cross-replica sharding);
* donation is declared by every train factory (the AST side checks the
  call sites; here the *runtime* is probed — on old jaxlib
  ``compat.py`` strips donation deliberately, which is reported as a
  waiver note; when compat retires, ``zero_donation`` asserts the
  donated buffers actually alias outputs in the compiled ZeRO step).

Probe configs are intentionally tiny (d_model 64, 2 layers) but sized so
the big kernels cross ``REPLICATION_THRESHOLD`` — a replication
regression on the probe is the same regression at 70B.
"""

from __future__ import annotations

import dataclasses
import inspect
import os
from pathlib import Path

from ddl_tpu.analysis.findings import Finding

__all__ = ["ContractReport", "REPLICATION_THRESHOLD", "run_contracts"]

# Parameter leaves at or above this many elements must not be fully
# replicated on a mesh that has a >1 non-data axis (unless the factory
# contract allows it).  Probe models are sized to push their matmul
# kernels over this line.
REPLICATION_THRESHOLD = 8192

_MIN_DEVICES = 8


def ensure_simulated_mesh(min_devices: int = _MIN_DEVICES) -> int:
    """Force the CPU host platform to expose ``min_devices`` simulated
    devices — must run before JAX initialises a backend (importing jax
    is fine; creating arrays is not).  Returns the device count actually
    available."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={min_devices}"
        ).strip()
    import jax

    try:
        # config.update wins over a registered-but-uninitialised TPU
        # plugin (same reasoning as tests/conftest.py); if a backend is
        # already up this is a no-op or a warning, never a crash
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    return len(jax.devices())


@dataclasses.dataclass
class ContractReport:
    findings: list[Finding]
    notes: list[str]


class _Probe:
    """Finding/note collector bound to one factory's source location."""

    def __init__(self, factory) -> None:
        src = inspect.getsourcefile(factory)
        root = Path(__file__).resolve().parents[2]  # repo root
        self.path = Path(src).resolve().relative_to(root).as_posix()
        self.line = inspect.getsourcelines(factory)[1]
        self.findings: list[Finding] = []
        self.notes: list[str] = []

    def add(self, rule: str, message: str) -> None:
        self.findings.append(Finding(self.path, self.line, rule, message))

    def note(self, message: str) -> None:
        self.notes.append(f"{self.path}: {message}")


def _spec_axes(spec) -> set[str]:
    axes: set[str] = set()
    for entry in spec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            axes.add(a)
    return axes


def _check_boundary(probe: _Probe, contract: dict, mesh) -> None:
    mesh_axes = set(mesh.axis_names)
    for name, spec in contract["in_specs"].items():
        unknown = _spec_axes(spec) - mesh_axes
        if unknown:
            probe.add(
                "contract-axis",
                f"boundary spec for {name!r} names non-mesh axes "
                f"{sorted(unknown)} (mesh has {sorted(mesh_axes)})",
            )
            continue
        first = spec[0] if len(spec) else None
        batch_axes = _spec_axes((first,))
        if "data" not in batch_axes:
            probe.add(
                "contract-boundary",
                f"batch dimension of {name!r} is not sharded over 'data' "
                f"(spec {spec}): every device would hold the full batch",
            )


def _explicit_replications(contract: dict, params) -> dict[str, str]:
    """``{leaf_path: matched_rule}`` for every leaf the factory's rule
    table replicates by explicit rule — the declarative successor of the
    retired ``replicated_ok_leaves`` waiver list."""
    table = contract.get("rule_table")
    if table is None:
        return {}
    from ddl_tpu.parallel.rules import spec_axes

    out: dict[str, str] = {}
    for name, _leaf, spec, pattern in table.provenance(params, strict=False):
        # an explicit rule whose spec names NO axis (P() or all-None —
        # the FSDP-conditional tables collapse to the latter) is
        # deliberate replication
        if pattern is not None and not spec_axes(spec):
            out[name] = pattern
    return out


def _check_params(probe: _Probe, params, mesh, contract: dict) -> None:
    import jax

    from ddl_tpu.parallel.rules import tree_path_str

    if contract["replicated_params_ok"]:
        probe.note(
            "replicated params are contractual for this factory "
            "(replication check skipped)"
        )
        return
    explicit = _explicit_replications(contract, params)
    # only non-data axes make replication a bug here: sharding params
    # over 'data' is FSDP, a deliberate opt-in, not a default expectation
    shardable = any(
        size > 1 for name, size in mesh.shape.items() if name != "data"
    )
    if not shardable:
        return
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        size = getattr(leaf, "size", 0)
        sharding = getattr(leaf, "sharding", None)
        if size < REPLICATION_THRESHOLD or sharding is None:
            continue
        if sharding.is_fully_replicated:
            name = tree_path_str(path)
            if name in explicit:
                probe.note(
                    f"replicated parameter {name} ({size} elements) is "
                    f"explicit in the rule table (rule "
                    f"{explicit[name]!r})"
                )
                continue
            probe.add(
                "contract-replicated",
                f"parameter {name} ({size} elements) is fully replicated "
                "on a shardable mesh — a silent per-device memory cost; "
                "add a rule to the family table (parallel/rules.py — "
                "an explicit P() rule if replication is intended)",
            )


def _check_rule_table(probe: _Probe, contract: dict, abs_params, mesh) -> None:
    """Validate the factory's partition-rule table directly: every leaf
    resolves, specs draw only on mesh axes, and every large leaf is
    sharded or *explicitly* replicated — the checks that used to lean on
    the hand-spec waiver list."""
    from ddl_tpu.parallel import rules as prules

    table = contract.get("rule_table")
    if table is None:
        probe.add(
            "contract-rules",
            "factory contract carries no rule_table: derive the contract "
            "from the family RuleTable (parallel/rules.py) so the probes "
            "can validate rules instead of hand-specs",
        )
        return
    mesh_axes = set(mesh.axis_names)
    for pattern, spec in table.rules:
        unknown = prules.spec_axes(spec) - mesh_axes
        if unknown:
            probe.add(
                "contract-axis",
                f"rule ({pattern!r} -> {spec}) in the {table.family!r} "
                f"table names non-mesh axes {sorted(unknown)} "
                f"(mesh has {sorted(mesh_axes)})",
            )
    try:
        prov = table.provenance(abs_params)
    except prules.UnmatchedLeafError as e:
        probe.add(
            "contract-rules",
            f"{table.family!r} rule table does not cover the family's "
            f"parameter tree: {e}",
        )
        return
    for name, leaf, spec, pattern in prov:
        size = getattr(leaf, "size", None)
        if size is None:
            import math

            shape = getattr(leaf, "shape", ())
            size = math.prod(shape) if shape else 1
        if size < REPLICATION_THRESHOLD:
            continue
        live = {
            a for a in prules.spec_axes(spec) if mesh.shape.get(a, 1) > 1
        }
        if live:
            continue
        if not prules.spec_axes(spec):
            probe.note(
                f"{table.family!r} table replicates {name} ({size} "
                f"elements) by explicit rule {pattern!r}"
            )
        else:
            probe.note(
                f"{table.family!r} table shards {name} over "
                f"{sorted(prules.spec_axes(spec))}, all trivial on this "
                "probe mesh"
            )


def _check_zero_state(probe: _Probe, state, contract: dict, mesh) -> None:
    """With ``zero_sharding`` declared: every eligible large leaf's
    moments must actually carry the 'data' axis, and the measured
    per-device optimizer bytes must show the ~(dp-1)/dp reduction."""
    import math

    import jax

    from ddl_tpu.parallel import rules as prules

    if not contract.get("zero_sharding"):
        return
    from jax.sharding import PartitionSpec as P

    table = contract.get("rule_table")
    params = state.params
    specs = (
        prules.match_partition_rules(table, params, strict=False)
        if table is not None
        else jax.tree.map(lambda _: P(), params)
    )
    spec_leaves = jax.tree.flatten(
        specs, is_leaf=lambda x: isinstance(x, P)
    )[0]
    adam_state = state.opt_state[0]
    dp = mesh.shape.get("data", 1)
    actual = replicated = 0.0
    threshold = contract.get("zero_threshold")
    if threshold is None:  # not `or`: threshold=0 (shard everything) is valid
        threshold = prules.ZERO_THRESHOLD
    for (path, p_leaf), mu_leaf, spec in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree.leaves(adam_state.mu),
        spec_leaves,
    ):
        zspec = prules.zero_shard_spec(
            spec, tuple(p_leaf.shape), mesh, threshold=threshold
        )
        sharding = getattr(mu_leaf, "sharding", None)
        shard_elems = (
            math.prod(sharding.shard_shape(mu_leaf.shape))
            if sharding is not None else mu_leaf.size
        )
        # mu + nu, per device; vs the data-replicated layout (the leaf
        # still shards over non-data axes in both layouts)
        non_data = prules.spec_num_shards(spec, mesh) if spec else 1
        actual += 2 * shard_elems * mu_leaf.dtype.itemsize
        replicated += 2 * mu_leaf.size * mu_leaf.dtype.itemsize / non_data
        if zspec is None:
            continue
        axes = (
            prules.spec_axes(sharding.spec)
            if sharding is not None and hasattr(sharding, "spec")
            else set()
        )
        if "data" not in axes:
            probe.add(
                "contract-zero",
                f"zero_sharding is declared but the moments of "
                f"{prules.tree_path_str(path)} ({p_leaf.size} elements) "
                "are not sharded over 'data' — the leaf is eligible "
                f"(zero spec {zspec}) and silently replicated",
            )
    if replicated > 0:
        probe.note(
            f"zero_sharding: optimizer state {actual / 1024:.0f} KiB/device "
            f"vs {replicated / 1024:.0f} KiB replicated over data "
            f"(dp={dp}, reduction x{replicated / max(actual, 1):.2f})"
        )


def _donation_alias_present(compiled_text: str) -> bool:
    """True when a compiled module's text shows donated input buffers
    aliasing outputs (XLA ``input_output_alias`` / StableHLO
    ``tf.aliasing_output`` markers)."""
    return (
        "input_output_alias" in compiled_text
        or "tf.aliasing_output" in compiled_text
    )


def _lower(probe: _Probe, fn, *args, what: str) -> None:
    try:
        fn.lower(*args)
    except Exception as e:  # trace errors ARE the findings here
        msg = str(e).splitlines()[0][:200]
        probe.add(
            "contract-trace",
            f"{what} failed to lower under the probe mesh: "
            f"{type(e).__name__}: {msg}",
        )


def _tiny_lm_cfg():
    from ddl_tpu.models.transformer import LMConfig

    # d_ff * d_model = 16384 and vocab * d_model = 32768: both cross
    # REPLICATION_THRESHOLD, so a dropped sharding rule is visible
    return LMConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, head_dim=16,
        d_ff=256, compute_dtype="float32",
    )


def _cnn_build(zero: bool = False, data: int = 2, **cfg_overrides):
    """Shared tiny-CNN build: config + mesh + optimizer (ZeRO-wrapped
    when asked) + step fns + committed state.  ONE definition so every
    CNN probe — plain, fused, ZeRO, and the donation probe — compiles
    the same composition and cannot drift."""
    import jax
    import jax.numpy as jnp

    from ddl_tpu.config import ModelConfig, TrainConfig
    from ddl_tpu.models import build_stages
    from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
    from ddl_tpu.train.state import create_train_state, make_optimizer
    from ddl_tpu.train.steps import make_dp_step_fns

    cfg = ModelConfig(
        growth_rate=4, block_config=(2, 2), num_init_features=8, bn_size=2,
        num_classes=5, split_blocks=(1,), compute_dtype="float32",
        remat=False, **cfg_overrides,
    )
    mesh = build_mesh(MeshSpec(data=data))
    stages = build_stages(cfg, num_stages=1)
    tx = make_optimizer(TrainConfig())  # fused Adam by default
    if zero:
        from ddl_tpu.train.fused_optim import with_zero

        # probe models are tiny; a small threshold exercises the sharded
        # expression on the same leaves a real model shards at 8192
        tx = with_zero(tx, mesh, threshold=64)
    fns = make_dp_step_fns(stages, tx, mesh, jnp.float32)
    state = create_train_state(
        stages, tx, jax.random.key(0), 16, mesh=mesh if zero else None
    )
    return fns, state, mesh


def _cnn_probe(what: str, check_fused_adam: bool = False,
               eval_too: bool = False, zero: bool = False, data: int = 2,
               **cfg_overrides) -> _Probe:
    """Shared CNN DP probe scaffolding (build via ``_cnn_build``):
    boundary/lowering/replication checks; variants differ only in model
    config overrides and extra checks."""
    import jax
    import jax.numpy as jnp

    from ddl_tpu.train.steps import make_dp_step_fns

    probe = _Probe(make_dp_step_fns)
    fns, state, mesh = _cnn_build(zero=zero, data=data, **cfg_overrides)
    _check_boundary(probe, fns.train.contract, mesh)
    if check_fused_adam and not fns.train.contract.get(
        "fused_optimizer_update"
    ):
        probe.add(
            "contract-trace",
            "fused CNN probe expected the fused Adam apply path "
            "(make_optimizer default) but the factory fell back to the "
            "two-pass optax path",
        )
    img = jax.ShapeDtypeStruct((8, 16, 16, 3), jnp.uint8)
    lbl = jax.ShapeDtypeStruct((8,), jnp.int32)
    _lower(probe, fns.train, state, img, lbl, what=f"CNN DP train step{what}")
    if eval_too:
        _lower(
            probe, fns.evaluate, state, img,
            what=f"CNN DP eval step{what}",
        )
    _check_params(probe, state.params, mesh, fns.train.contract)
    if zero:
        _check_zero_state(probe, state, fns.train.contract, mesh)
    return probe


def _probe_cnn() -> _Probe:
    return _cnn_probe("")


def _probe_cnn_zero() -> _Probe:
    """The CNN DP step with ZeRO-1 weight-update sharding on a data=4
    mesh: the reduce-scatter/fused-update/all-gather composition must
    lower, the moments must actually live data-sharded, and the probe
    reports the measured per-device optimizer-byte reduction."""
    return _cnn_probe(" (ZeRO)", zero=True, data=4)


def _probe_cnn_fused() -> _Probe:
    """The CNN DP step factory with the round-6 fused dense-block impl
    (Pallas VMEM-resident blocks + custom-VJP backward + fused Adam
    apply): the composition under test is the pallas_call pair and the
    single-pass optimizer update lowering inside the jitted SPMD step on
    a data mesh — a kernel-boundary or custom-VJP shape bug surfaces
    here before a chip bench ever runs."""
    return _cnn_probe(
        " (fused dense blocks)", check_fused_adam=True, eval_too=True,
        dense_block_impl="fused", dense_block_fused_blocks=(0, 1),
    )


def _probe_lm() -> _Probe:
    import jax
    import optax

    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    probe = _Probe(make_lm_step_fns)
    fns = make_lm_step_fns(
        _tiny_lm_cfg(), LMMeshSpec(data=2, model=2), optax.adam(1e-3),
        jax.random.key(0), batch=8, seq_len=32,
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    state = fns.init_state()
    _check_rule_table(probe, fns.train.contract, state.params, fns.mesh)
    tok = jax.ShapeDtypeStruct((8, 32), jax.numpy.int32)
    _lower(probe, fns.train, state, tok, tok, what="LM train step")
    _lower(probe, fns.evaluate, state, tok, tok, what="LM eval step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    return probe


def _probe_lm_zero() -> _Probe:
    """The LM flat step with ZeRO-1 over a (data=4, model=2) mesh at the
    REAL 8192-element threshold (the probe model's MLP and vocab kernels
    cross it): every eligible leaf's moments must carry 'data', the step
    must lower, and the per-device optimizer bytes must show the
    ~(dp-1)/dp reduction."""
    import jax

    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.fused_optim import fused_adam
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    probe = _Probe(make_lm_step_fns)
    fns = make_lm_step_fns(
        _tiny_lm_cfg(), LMMeshSpec(data=4, model=2), fused_adam(1e-3),
        jax.random.key(0), batch=8, seq_len=32, zero_sharding=True,
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    if not fns.train.contract.get("zero_sharding"):
        probe.add(
            "contract-zero",
            "zero_sharding=True was requested but the factory contract "
            "does not declare it (with_zero wiring lost)",
        )
    state = fns.init_state()
    _check_rule_table(probe, fns.train.contract, state.params, fns.mesh)
    tok = jax.ShapeDtypeStruct((8, 32), jax.numpy.int32)
    _lower(probe, fns.train, state, tok, tok, what="LM ZeRO train step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    _check_zero_state(probe, state, fns.train.contract, fns.mesh)
    return probe


def _probe_zero_donation() -> _Probe:
    """Donation effectiveness across the train-step families (PR-3
    carry-over, generalized): on runtimes where compat.py strips jit
    donation, report the waiver; once compat retires, compile one step
    per family (CNN-ZeRO, LM, ViT) and measure how much of the donated
    train state actually aliases outputs — aliased-bytes over
    donatable-bytes from the compiled module's ``input_output_alias``
    header, parsed by the compiled-IR lint (analysis/hlolint.py).
    Donation that silently stopped aliasing would double state HBM
    right where ZeRO/donation is trying to save it."""
    import jax

    from ddl_tpu.train.steps import make_dp_step_fns

    probe = _Probe(make_dp_step_fns)
    if hasattr(jax.jit, "__wrapped__"):
        probe.note(
            "donation-effectiveness waived: compat.py strips jit donation "
            "on this runtime (old jaxlib mis-aliases donated buffers "
            "under shard_map); when compat retires, this probe compiles "
            "one step per family (CNN-ZeRO, LM, ViT) and asserts "
            "input_output_alias coverage of the donated state"
        )
        return probe

    from ddl_tpu.analysis.hlolint import (
        _state_bytes,
        parse_aliases,
        parse_param_bytes,
    )

    def check(name: str, build) -> None:
        try:
            train, state = build()
            text = train.lower(state, *train.probe_inputs()).compile(
            ).as_text()
        except Exception as e:
            msg = str(e).splitlines()[0][:200] if str(e) else ""
            probe.add(
                "contract-trace",
                f"{name} donation probe failed to compile: "
                f"{type(e).__name__}: {msg}",
            )
            return
        aliases = parse_aliases(text)
        if not aliases:
            probe.add(
                "contract-donation",
                f"the compiled {name} train step shows no "
                "input_output_alias: the donated state is being copied, "
                "doubling state HBM across the update",
            )
            return
        param_bytes = parse_param_bytes(text)
        aliased = sum(
            param_bytes.get(p, 0)
            for _out, p, pidx in aliases if pidx == ""
        )
        donatable = _state_bytes(state)
        probe.note(
            f"{name} donation effectiveness: {aliased}/{donatable} "
            f"bytes aliased ({aliased / max(donatable, 1):.0%})"
        )
        # partial coverage is a real memory bill, not a style point:
        # every non-aliased donated byte is double-buffered across the
        # update (the HBM ledger's optimizer row shows the hit live —
        # obs/hbm.py).  10% slack tolerates legitimately un-aliasable
        # leaves (dtype-changing casts, scalar counters).
        copied = donatable - aliased
        if donatable > 0 and copied > donatable * 0.10:
            probe.add(
                "contract-donation",
                f"{name} donation only partially aliases: "
                f"{aliased}/{donatable} donated-state bytes alias "
                f"outputs ({aliased / donatable:.0%}) — the other "
                f"{copied} bytes are copied every step and held twice "
                "across the update",
            )

    def build_cnn():
        # the same ZeRO composition cnn_dp_zero validates — one
        # builder, no drift between the two probes
        fns, state, _mesh = _cnn_build(zero=True, data=4)
        return fns.train, state

    def build_lm():
        import optax

        from ddl_tpu.parallel.sharding import LMMeshSpec
        from ddl_tpu.train.lm_steps import make_lm_step_fns

        fns = make_lm_step_fns(
            _tiny_lm_cfg(), LMMeshSpec(data=2, model=2),
            optax.adam(1e-3), jax.random.key(0), batch=8, seq_len=32,
        )
        return fns.train, fns.init_state()

    def build_vit():
        import optax

        from ddl_tpu.models.vit import ViTConfig
        from ddl_tpu.parallel.sharding import LMMeshSpec
        from ddl_tpu.train.vit_steps import make_vit_step_fns

        cfg = ViTConfig(
            image_size=16, patch_size=8, d_model=64, n_layers=2,
            n_heads=4, head_dim=16, d_ff=256, compute_dtype="float32",
            remat=False,
        )
        fns = make_vit_step_fns(
            cfg, LMMeshSpec(data=2, model=2), optax.adam(1e-3),
            jax.random.key(0), batch=8,
        )
        return fns.train, fns.init_state()

    for name, build in (
        ("CNN-ZeRO", build_cnn), ("LM", build_lm), ("ViT", build_vit),
    ):
        check(name, build)
    return probe


def _probe_vit() -> _Probe:
    import jax
    import jax.numpy as jnp
    import optax

    from ddl_tpu.models.vit import ViTConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.vit_steps import make_vit_step_fns

    probe = _Probe(make_vit_step_fns)
    cfg = ViTConfig(
        image_size=16, patch_size=8, d_model=64, n_layers=2, n_heads=4,
        head_dim=16, d_ff=256, compute_dtype="float32", remat=False,
    )
    fns = make_vit_step_fns(
        cfg, LMMeshSpec(data=2, model=2), optax.adam(1e-3),
        jax.random.key(0), batch=8,
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    state = fns.init_state()
    # the former patch/pos-embedding waivers are explicit rules now —
    # validated against the table, not a hand list
    _check_rule_table(probe, fns.train.contract, state.params, fns.mesh)
    img = jax.ShapeDtypeStruct((8, 16, 16, 3), jnp.uint8)
    lbl = jax.ShapeDtypeStruct((8,), jnp.int32)
    _lower(probe, fns.train, state, img, lbl, what="ViT train step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    return probe


def _probe_decode() -> _Probe:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ddl_tpu.infer.decode import make_lm_generator
    from ddl_tpu.parallel.sharding import LMMeshSpec

    probe = _Probe(make_lm_generator)
    cfg = _tiny_lm_cfg()
    gen = make_lm_generator(
        cfg, LMMeshSpec(data=2, model=2), prompt_len=8, max_new=4, batch=2,
    )
    _check_boundary(probe, gen.contract, gen.mesh)
    from ddl_tpu.models.transformer import TransformerLM

    params = nn.meta.unbox(
        jax.eval_shape(
            lambda r: TransformerLM(cfg, None).init(
                r, jnp.zeros((2, 8), jnp.int32)
            )["params"],
            jax.random.key(0),
        )
    )
    prompt = jax.ShapeDtypeStruct((2, 8), jnp.int32)
    _lower(
        probe, gen.jitted, params, prompt, jax.random.key(0),
        what="decode generate",
    )
    return probe


def _probe_serve_decode() -> _Probe:
    """The continuous-batching serving engine's batched decode program
    (serve/engine.py): one token for every lane over the paged KV pool.
    Validates the serving boundary (pending tokens over 'data') and that
    the gathered-block-table attention lowers under a data+model mesh —
    a rule-table edit that breaks the per-lane cache constraints
    surfaces here before a serve-bench ever runs."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ddl_tpu.models.transformer import TransformerLM
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.serve.engine import make_serve_step_fns

    probe = _Probe(make_serve_step_fns)
    cfg = _tiny_lm_cfg()
    fns = make_serve_step_fns(
        cfg, LMMeshSpec(data=2, model=2),
        block_size=8, num_blocks=16, max_batch=4,
    )
    _check_boundary(probe, fns.contract, fns.mesh)
    params = nn.meta.unbox(
        jax.eval_shape(
            lambda r: TransformerLM(cfg, None).init(
                r, jnp.zeros((2, 8), jnp.int32)
            )["params"],
            jax.random.key(0),
        )
    )
    pools = jax.eval_shape(fns.init_pools)
    # arg structs come from the engine's own probe_inputs so the probe
    # can never drift from the real call sites (shared with the
    # compiled-IR probes in analysis/hlolint.py)
    decode, _ = fns.decode_for(4, fns.max_blocks_per_seq)
    _lower(
        probe, decode, params, pools, *fns.probe_inputs("decode", 4),
        what="serve continuous-batch decode chunk",
    )
    _lower(
        probe, fns.prefill_for(8), params, pools,
        *fns.probe_inputs("prefill", 8),
        what="serve bucketed prefill",
    )
    # the round-17 chunk prefill (prefix-cache tails / long-prompt
    # chunks): masked cached attention at a traced offset over a
    # gathered pool view must lower under the same sharded mesh
    chunk, _ = fns.chunk_for(8, fns.max_blocks_per_seq, "final")
    _lower(
        probe, chunk, params, pools, *fns.probe_inputs("chunk", 8),
        what="serve chunk prefill",
    )
    return probe


def _probe_lm_pipeline() -> _Probe:
    """The pipeline-parallel LM step factory (parallel/lm_pipeline.py):
    same contract surface as the flat path (it shares
    ``finalize_step_fns``), but the program composition under test is
    the GPipe shard_map schedule over the ``pipe`` axis — a rule-table
    edit that breaks stage-stacked param placement surfaces here, not in
    the flat probe."""
    import jax
    import optax

    from ddl_tpu.parallel.lm_pipeline import make_lm_pipeline_step_fns
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    probe = _Probe(make_lm_pipeline_step_fns)
    # model=2 alongside pipe: embed/head run OUTSIDE the pipe region and
    # shard over 'model' — on a pipe-only mesh they replicate by design,
    # which would drown the replication check in waivers
    fns = make_lm_step_fns(
        _tiny_lm_cfg(), LMMeshSpec(data=2, pipe=2, model=2),
        optax.adam(1e-3),
        jax.random.key(0), batch=8, seq_len=32, num_microbatches=2,
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    state = fns.init_state()
    tok = jax.ShapeDtypeStruct((8, 32), jax.numpy.int32)
    _lower(probe, fns.train, state, tok, tok, what="LM pipeline train step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    return probe


def _probe_lm_pipeline_zb() -> _Probe:
    """The zero-bubble (B/W-split) schedule on a (data=2, pipe=2,
    model=2) mesh: the input-cotangent-only and weight-cotangent-only
    vjps, the W ring queue carried through the scan, and the head
    epilogue cond must all lower under GSPMD auto axes beside the
    manual pipe axis — and the factory's contract must declare the
    schedule it compiled (``pipeline_schedule``, drawn from
    ``parallel/rules.PIPELINE_SCHEDULES``)."""
    import jax
    import optax

    from ddl_tpu.parallel import rules as prules
    from ddl_tpu.parallel.lm_pipeline import make_lm_pipeline_step_fns
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    probe = _Probe(make_lm_pipeline_step_fns)
    fns = make_lm_step_fns(
        _tiny_lm_cfg(), LMMeshSpec(data=2, pipe=2, model=2),
        optax.adam(1e-3),
        jax.random.key(0), batch=8, seq_len=32, num_microbatches=4,
        pipeline_schedule="zb",
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    declared = fns.train.contract.get("pipeline_schedule")
    if declared != "zb":
        probe.add(
            "contract-rules",
            f"pipeline factory contract declares pipeline_schedule="
            f"{declared!r} for a zb build — the schedule facts the "
            "contract carries drifted from the compiled program",
        )
    if declared is not None and declared not in prules.PIPELINE_SCHEDULES:
        probe.add(
            "contract-rules",
            f"contract pipeline_schedule {declared!r} is not in "
            f"parallel/rules.PIPELINE_SCHEDULES {prules.PIPELINE_SCHEDULES}",
        )
    state = fns.init_state()
    tok = jax.ShapeDtypeStruct((8, 32), jax.numpy.int32)
    _lower(probe, fns.train, state, tok, tok, what="LM zb pipeline train step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    return probe


def _probe_vit_pipeline() -> _Probe:
    """The pipeline-parallel ViT factory (vit_steps pipeline path over
    the shared blocks-pipeline clock loop)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ddl_tpu.models.vit import ViTConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.vit_steps import make_vit_step_fns

    probe = _Probe(make_vit_step_fns)
    cfg = ViTConfig(
        image_size=16, patch_size=8, d_model=64, n_layers=2, n_heads=4,
        head_dim=16, d_ff=256, compute_dtype="float32", remat=False,
    )
    fns = make_vit_step_fns(
        cfg, LMMeshSpec(data=2, pipe=2, model=2), optax.adam(1e-3),
        jax.random.key(0), batch=8, num_microbatches=2,
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    state = fns.init_state()
    img = jax.ShapeDtypeStruct((8, 16, 16, 3), jnp.uint8)
    lbl = jax.ShapeDtypeStruct((8,), jnp.int32)
    _lower(probe, fns.train, state, img, lbl, what="ViT pipeline train step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    return probe


PROBES = (
    ("cnn_dp", _probe_cnn),
    ("cnn_dp_fused", _probe_cnn_fused),
    ("cnn_dp_zero", _probe_cnn_zero),
    ("lm_flat", _probe_lm),
    ("lm_zero", _probe_lm_zero),
    ("zero_donation", _probe_zero_donation),
    ("vit_flat", _probe_vit),
    ("lm_decode", _probe_decode),
    ("serve_decode", _probe_serve_decode),
    ("lm_pipeline", _probe_lm_pipeline),
    ("lm_pipeline_zb", _probe_lm_pipeline_zb),
    ("vit_pipeline", _probe_vit_pipeline),
)


def run_contracts(min_devices: int = _MIN_DEVICES) -> ContractReport:
    """Run every registered probe; returns findings + waiver notes."""
    import jax

    n = ensure_simulated_mesh(min_devices)
    findings: list[Finding] = []
    notes: list[str] = []
    if n < 4:
        notes.append(
            f"contract probes SKIPPED: only {n} device(s) visible and the "
            "probe meshes need 4 (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "JAX initialises)"
        )
        return ContractReport(findings, notes)
    if hasattr(jax.jit, "__wrapped__"):
        notes.append(
            "donation waived: compat.py strips jit donation on this "
            "runtime (old jaxlib mis-aliases donated buffers under "
            "shard_map) — factories still declare it, the AST rule "
            "still enforces declaration"
        )
    for name, probe_fn in PROBES:
        try:
            probe = probe_fn()
        except Exception as e:  # a probe that cannot even build IS a finding
            msg = str(e).splitlines()[0][:200] if str(e) else ""
            findings.append(
                Finding(
                    "ddl_tpu/analysis/contracts.py", 1, "contract-trace",
                    f"probe {name!r} failed to build its step functions: "
                    f"{type(e).__name__}: {msg}",
                )
            )
            continue
        findings.extend(probe.findings)
        notes.extend(probe.notes)
    return ContractReport(sorted(findings), notes)
