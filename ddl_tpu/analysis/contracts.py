"""Sharding-contract checker: abstract-eval the registered step functions.

The AST rules (``astlint.py``) see one file at a time; the bugs that cost
the most MFU live in the *composition* — a rule-table edit in
``parallel/sharding.py`` that quietly drops the ``model`` axis from the
MLP kernels replicates gigabytes per device without a single error
anywhere.  This module catches that class at trace level: each
registered step-function factory (``train/steps.py``,
``train/lm_steps.py``, ``train/vit_steps.py``, ``infer/decode.py``) is
built against a small **simulated mesh** (XLA host-platform devices — no
TPU required, the same trick the test suite uses) and validated:

* the factory's declared boundary contract (the ``.contract`` dict every
  factory attaches to its jitted train/generate function) names only
  real mesh axes, and its batch dimension is actually sharded over
  ``data`` — not silently replicated;
* the jitted program **lowers cleanly** with abstract inputs under the
  contract shardings (unknown axes, divisibility violations, and
  rule-table/spec disagreements all surface here as trace errors);
* no parameter leaf above ``REPLICATION_THRESHOLD`` elements is fully
  replicated when the mesh has a >1 axis to shard it over (unless the
  factory's contract says replication is by design — CNN DDP, serving
  replicas);
* donation is declared by every train factory (the AST side checks the
  call sites; here the *runtime* is probed — on old jaxlib
  ``compat.py`` strips donation deliberately, which is reported as a
  waiver note, not a finding).

Probe configs are intentionally tiny (d_model 64, 2 layers) but sized so
the big kernels cross ``REPLICATION_THRESHOLD`` — a replication
regression on the probe is the same regression at 70B.
"""

from __future__ import annotations

import dataclasses
import inspect
import os
from pathlib import Path

from ddl_tpu.analysis.findings import Finding

__all__ = ["ContractReport", "REPLICATION_THRESHOLD", "run_contracts"]

# Parameter leaves at or above this many elements must not be fully
# replicated on a mesh that has a >1 non-data axis (unless the factory
# contract allows it).  Probe models are sized to push their matmul
# kernels over this line.
REPLICATION_THRESHOLD = 8192

_MIN_DEVICES = 8


def ensure_simulated_mesh(min_devices: int = _MIN_DEVICES) -> int:
    """Force the CPU host platform to expose ``min_devices`` simulated
    devices — must run before JAX initialises a backend (importing jax
    is fine; creating arrays is not).  Returns the device count actually
    available."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={min_devices}"
        ).strip()
    import jax

    try:
        # config.update wins over a registered-but-uninitialised TPU
        # plugin (same reasoning as tests/conftest.py); if a backend is
        # already up this is a no-op or a warning, never a crash
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass
    return len(jax.devices())


@dataclasses.dataclass
class ContractReport:
    findings: list[Finding]
    notes: list[str]


class _Probe:
    """Finding/note collector bound to one factory's source location."""

    def __init__(self, factory) -> None:
        src = inspect.getsourcefile(factory)
        root = Path(__file__).resolve().parents[2]  # repo root
        self.path = Path(src).resolve().relative_to(root).as_posix()
        self.line = inspect.getsourcelines(factory)[1]
        self.findings: list[Finding] = []
        self.notes: list[str] = []

    def add(self, rule: str, message: str) -> None:
        self.findings.append(Finding(self.path, self.line, rule, message))

    def note(self, message: str) -> None:
        self.notes.append(f"{self.path}: {message}")


def _spec_axes(spec) -> set[str]:
    axes: set[str] = set()
    for entry in spec:
        if entry is None:
            continue
        for a in (entry if isinstance(entry, tuple) else (entry,)):
            axes.add(a)
    return axes


def _check_boundary(probe: _Probe, contract: dict, mesh) -> None:
    mesh_axes = set(mesh.axis_names)
    for name, spec in contract["in_specs"].items():
        unknown = _spec_axes(spec) - mesh_axes
        if unknown:
            probe.add(
                "contract-axis",
                f"boundary spec for {name!r} names non-mesh axes "
                f"{sorted(unknown)} (mesh has {sorted(mesh_axes)})",
            )
            continue
        first = spec[0] if len(spec) else None
        batch_axes = _spec_axes((first,))
        if "data" not in batch_axes:
            probe.add(
                "contract-boundary",
                f"batch dimension of {name!r} is not sharded over 'data' "
                f"(spec {spec}): every device would hold the full batch",
            )


def _check_params(probe: _Probe, params, mesh, contract: dict) -> None:
    import jax

    if contract["replicated_params_ok"]:
        probe.note(
            "replicated params are contractual for this factory "
            "(replication check skipped)"
        )
        return
    waived = contract.get("replicated_ok_leaves", ())
    # only non-data axes make replication a bug here: sharding params
    # over 'data' is FSDP, a deliberate opt-in, not a default expectation
    shardable = any(
        size > 1 for name, size in mesh.shape.items() if name != "data"
    )
    if not shardable:
        return
    for path, leaf in jax.tree_util.tree_leaves_with_path(params):
        size = getattr(leaf, "size", 0)
        sharding = getattr(leaf, "sharding", None)
        if size < REPLICATION_THRESHOLD or sharding is None:
            continue
        if sharding.is_fully_replicated:
            name = jax.tree_util.keystr(path)
            if any(w in name for w in waived):
                probe.note(
                    f"replicated parameter {name} ({size} elements) "
                    "waived by the factory contract"
                )
                continue
            probe.add(
                "contract-replicated",
                f"parameter {name} ({size} elements) is fully replicated "
                "on a shardable mesh — a silent per-device memory cost; "
                "add a logical-axis rule (parallel/sharding.py) or waive "
                "the leaf in the factory contract "
                "(replicated_ok_leaves)",
            )


def _lower(probe: _Probe, fn, *args, what: str) -> None:
    try:
        fn.lower(*args)
    except Exception as e:  # trace errors ARE the findings here
        msg = str(e).splitlines()[0][:200]
        probe.add(
            "contract-trace",
            f"{what} failed to lower under the probe mesh: "
            f"{type(e).__name__}: {msg}",
        )


def _tiny_lm_cfg():
    from ddl_tpu.models.transformer import LMConfig

    # d_ff * d_model = 16384 and vocab * d_model = 32768: both cross
    # REPLICATION_THRESHOLD, so a dropped sharding rule is visible
    return LMConfig(
        vocab_size=512, d_model=64, n_layers=2, n_heads=4, head_dim=16,
        d_ff=256, compute_dtype="float32",
    )


def _cnn_probe(what: str, check_fused_adam: bool = False,
               eval_too: bool = False, **cfg_overrides) -> _Probe:
    """Shared CNN DP probe scaffolding: tiny config + data=2 mesh +
    boundary/lowering/replication checks; variants differ only in model
    config overrides and extra checks."""
    import jax
    import jax.numpy as jnp

    from ddl_tpu.config import ModelConfig, TrainConfig
    from ddl_tpu.models import build_stages
    from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
    from ddl_tpu.train.state import create_train_state, make_optimizer
    from ddl_tpu.train.steps import make_dp_step_fns

    probe = _Probe(make_dp_step_fns)
    cfg = ModelConfig(
        growth_rate=4, block_config=(2, 2), num_init_features=8, bn_size=2,
        num_classes=5, split_blocks=(1,), compute_dtype="float32",
        remat=False, **cfg_overrides,
    )
    mesh = build_mesh(MeshSpec(data=2))
    stages = build_stages(cfg, num_stages=1)
    tx = make_optimizer(TrainConfig())  # fused Adam by default
    fns = make_dp_step_fns(stages, tx, mesh, jnp.float32)
    _check_boundary(probe, fns.train.contract, mesh)
    if check_fused_adam and not fns.train.contract.get(
        "fused_optimizer_update"
    ):
        probe.add(
            "contract-trace",
            "fused CNN probe expected the fused Adam apply path "
            "(make_optimizer default) but the factory fell back to the "
            "two-pass optax path",
        )
    state = create_train_state(stages, tx, jax.random.key(0), 16)
    img = jax.ShapeDtypeStruct((8, 16, 16, 3), jnp.uint8)
    lbl = jax.ShapeDtypeStruct((8,), jnp.int32)
    _lower(probe, fns.train, state, img, lbl, what=f"CNN DP train step{what}")
    if eval_too:
        _lower(
            probe, fns.evaluate, state, img,
            what=f"CNN DP eval step{what}",
        )
    _check_params(probe, state.params, mesh, fns.train.contract)
    return probe


def _probe_cnn() -> _Probe:
    return _cnn_probe("")


def _probe_cnn_fused() -> _Probe:
    """The CNN DP step factory with the round-6 fused dense-block impl
    (Pallas VMEM-resident blocks + custom-VJP backward + fused Adam
    apply): the composition under test is the pallas_call pair and the
    single-pass optimizer update lowering inside the jitted SPMD step on
    a data mesh — a kernel-boundary or custom-VJP shape bug surfaces
    here before a chip bench ever runs."""
    return _cnn_probe(
        " (fused dense blocks)", check_fused_adam=True, eval_too=True,
        dense_block_impl="fused", dense_block_fused_blocks=(0, 1),
    )


def _probe_lm() -> _Probe:
    import jax
    import optax

    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    probe = _Probe(make_lm_step_fns)
    fns = make_lm_step_fns(
        _tiny_lm_cfg(), LMMeshSpec(data=2, model=2), optax.adam(1e-3),
        jax.random.key(0), batch=8, seq_len=32,
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    state = fns.init_state()
    tok = jax.ShapeDtypeStruct((8, 32), jax.numpy.int32)
    _lower(probe, fns.train, state, tok, tok, what="LM train step")
    _lower(probe, fns.evaluate, state, tok, tok, what="LM eval step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    return probe


def _probe_vit() -> _Probe:
    import jax
    import jax.numpy as jnp
    import optax

    from ddl_tpu.models.vit import ViTConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.vit_steps import make_vit_step_fns

    probe = _Probe(make_vit_step_fns)
    cfg = ViTConfig(
        image_size=16, patch_size=8, d_model=64, n_layers=2, n_heads=4,
        head_dim=16, d_ff=256, compute_dtype="float32", remat=False,
    )
    fns = make_vit_step_fns(
        cfg, LMMeshSpec(data=2, model=2), optax.adam(1e-3),
        jax.random.key(0), batch=8,
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    state = fns.init_state()
    img = jax.ShapeDtypeStruct((8, 16, 16, 3), jnp.uint8)
    lbl = jax.ShapeDtypeStruct((8,), jnp.int32)
    _lower(probe, fns.train, state, img, lbl, what="ViT train step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    return probe


def _probe_decode() -> _Probe:
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ddl_tpu.infer.decode import make_lm_generator
    from ddl_tpu.parallel.sharding import LMMeshSpec

    probe = _Probe(make_lm_generator)
    cfg = _tiny_lm_cfg()
    gen = make_lm_generator(
        cfg, LMMeshSpec(data=2, model=2), prompt_len=8, max_new=4, batch=2,
    )
    _check_boundary(probe, gen.contract, gen.mesh)
    from ddl_tpu.models.transformer import TransformerLM

    params = nn.meta.unbox(
        jax.eval_shape(
            lambda r: TransformerLM(cfg, None).init(
                r, jnp.zeros((2, 8), jnp.int32)
            )["params"],
            jax.random.key(0),
        )
    )
    prompt = jax.ShapeDtypeStruct((2, 8), jnp.int32)
    _lower(
        probe, gen.jitted, params, prompt, jax.random.key(0),
        what="decode generate",
    )
    return probe


def _probe_serve_decode() -> _Probe:
    """The continuous-batching serving engine's batched decode program
    (serve/engine.py): one token for every lane over the paged KV pool.
    Validates the serving boundary (pending tokens over 'data') and that
    the gathered-block-table attention lowers under a data+model mesh —
    a rule-table edit that breaks the per-lane cache constraints
    surfaces here before a serve-bench ever runs."""
    import flax.linen as nn
    import jax
    import jax.numpy as jnp

    from ddl_tpu.models.transformer import TransformerLM
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.serve.engine import make_serve_step_fns

    probe = _Probe(make_serve_step_fns)
    cfg = _tiny_lm_cfg()
    fns = make_serve_step_fns(
        cfg, LMMeshSpec(data=2, model=2),
        block_size=8, num_blocks=16, max_batch=4,
    )
    _check_boundary(probe, fns.contract, fns.mesh)
    params = nn.meta.unbox(
        jax.eval_shape(
            lambda r: TransformerLM(cfg, None).init(
                r, jnp.zeros((2, 8), jnp.int32)
            )["params"],
            jax.random.key(0),
        )
    )
    pools = jax.eval_shape(fns.init_pools)
    tables = jax.ShapeDtypeStruct((4, fns.max_blocks_per_seq), jnp.int32)
    lengths = jax.ShapeDtypeStruct((4,), jnp.int32)
    pending = jax.ShapeDtypeStruct((4,), jnp.int32)
    rngs = jax.ShapeDtypeStruct((4, 2), jnp.uint32)
    decode, _ = fns.decode_for(4, fns.max_blocks_per_seq)
    _lower(
        probe, decode, params, pools, tables, lengths, pending, rngs,
        what="serve continuous-batch decode chunk",
    )
    _lower(
        probe, fns.prefill_for(8), params, pools,
        jax.ShapeDtypeStruct((1, 8), jnp.int32),
        jax.ShapeDtypeStruct((1,), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32),
        jax.eval_shape(lambda: jax.random.PRNGKey(0)),
        what="serve bucketed prefill",
    )
    return probe


def _probe_lm_pipeline() -> _Probe:
    """The pipeline-parallel LM step factory (parallel/lm_pipeline.py):
    same contract surface as the flat path (it shares
    ``finalize_step_fns``), but the program composition under test is
    the GPipe shard_map schedule over the ``pipe`` axis — a rule-table
    edit that breaks stage-stacked param placement surfaces here, not in
    the flat probe."""
    import jax
    import optax

    from ddl_tpu.parallel.lm_pipeline import make_lm_pipeline_step_fns
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns

    probe = _Probe(make_lm_pipeline_step_fns)
    # model=2 alongside pipe: embed/head run OUTSIDE the pipe region and
    # shard over 'model' — on a pipe-only mesh they replicate by design,
    # which would drown the replication check in waivers
    fns = make_lm_step_fns(
        _tiny_lm_cfg(), LMMeshSpec(data=2, pipe=2, model=2),
        optax.adam(1e-3),
        jax.random.key(0), batch=8, seq_len=32, num_microbatches=2,
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    state = fns.init_state()
    tok = jax.ShapeDtypeStruct((8, 32), jax.numpy.int32)
    _lower(probe, fns.train, state, tok, tok, what="LM pipeline train step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    return probe


def _probe_vit_pipeline() -> _Probe:
    """The pipeline-parallel ViT factory (vit_steps pipeline path over
    the shared blocks-pipeline clock loop)."""
    import jax
    import jax.numpy as jnp
    import optax

    from ddl_tpu.models.vit import ViTConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.vit_steps import make_vit_step_fns

    probe = _Probe(make_vit_step_fns)
    cfg = ViTConfig(
        image_size=16, patch_size=8, d_model=64, n_layers=2, n_heads=4,
        head_dim=16, d_ff=256, compute_dtype="float32", remat=False,
    )
    fns = make_vit_step_fns(
        cfg, LMMeshSpec(data=2, pipe=2, model=2), optax.adam(1e-3),
        jax.random.key(0), batch=8, num_microbatches=2,
    )
    _check_boundary(probe, fns.train.contract, fns.mesh)
    state = fns.init_state()
    img = jax.ShapeDtypeStruct((8, 16, 16, 3), jnp.uint8)
    lbl = jax.ShapeDtypeStruct((8,), jnp.int32)
    _lower(probe, fns.train, state, img, lbl, what="ViT pipeline train step")
    _check_params(probe, state.params, fns.mesh, fns.train.contract)
    return probe


PROBES = (
    ("cnn_dp", _probe_cnn),
    ("cnn_dp_fused", _probe_cnn_fused),
    ("lm_flat", _probe_lm),
    ("vit_flat", _probe_vit),
    ("lm_decode", _probe_decode),
    ("serve_decode", _probe_serve_decode),
    ("lm_pipeline", _probe_lm_pipeline),
    ("vit_pipeline", _probe_vit_pipeline),
)


def run_contracts(min_devices: int = _MIN_DEVICES) -> ContractReport:
    """Run every registered probe; returns findings + waiver notes."""
    import jax

    n = ensure_simulated_mesh(min_devices)
    findings: list[Finding] = []
    notes: list[str] = []
    if n < 4:
        notes.append(
            f"contract probes SKIPPED: only {n} device(s) visible and the "
            "probe meshes need 4 (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 before "
            "JAX initialises)"
        )
        return ContractReport(findings, notes)
    if hasattr(jax.jit, "__wrapped__"):
        notes.append(
            "donation waived: compat.py strips jit donation on this "
            "runtime (old jaxlib mis-aliases donated buffers under "
            "shard_map) — factories still declare it, the AST rule "
            "still enforces declaration"
        )
    for name, probe_fn in PROBES:
        try:
            probe = probe_fn()
        except Exception as e:  # a probe that cannot even build IS a finding
            msg = str(e).splitlines()[0][:200] if str(e) else ""
            findings.append(
                Finding(
                    "ddl_tpu/analysis/contracts.py", 1, "contract-trace",
                    f"probe {name!r} failed to build its step functions: "
                    f"{type(e).__name__}: {msg}",
                )
            )
            continue
        findings.extend(probe.findings)
        notes.extend(probe.notes)
    return ContractReport(sorted(findings), notes)
