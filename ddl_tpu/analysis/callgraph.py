"""Package-wide import/call graph — the whole-program half of the lint.

``CallGraph`` parses every module of a package once and answers the two
questions the per-module AST engine (``astlint.py``) cannot:

* **name resolution across modules** — given ``helpers.sync_mean`` (or a
  bare ``sync_mean`` bound by ``from .helpers import sync_mean``) inside
  module M, which function *definition* does it refer to?  Handles
  ``import x.y as z`` attribute chains, ``from x import y`` (absolute and
  relative, any level), and re-export chains (``ddl_tpu.ops.__init__``
  re-exporting ``cross_entropy_loss`` from ``ops/losses.py``) to a
  bounded depth.  Resolution is *static and conservative*: only
  module-level ``def``s reachable through import bindings resolve;
  methods, dynamically-bound attributes, and anything outside the
  package return ``None``.
* **module dependency closure** — which modules (transitively) import a
  given module.  This is what ``ddl_tpu lint --changed`` uses to lint a
  git diff plus every module whose traced-set inference could have been
  changed by it.

The traced-set inference itself stays in ``astlint.py``
(``infer_traced_program``) — this module is pure structure, no rules, no
JAX import.
"""

from __future__ import annotations

import ast
import dataclasses
import subprocess
from pathlib import Path

from ddl_tpu.analysis.astlint import _Func, _Module

__all__ = ["CallGraph", "ModuleInfo", "Target", "changed_package_files"]

_MAX_REEXPORT_DEPTH = 8  # bound re-export chases (and import cycles)


@dataclasses.dataclass
class Target:
    """A resolved function definition: which module owns it + its node."""

    module: str
    func: _Func


@dataclasses.dataclass
class ModuleInfo:
    name: str  # dotted module name, e.g. "ddl_tpu.utils.backoff"
    path: Path
    rel: str  # repo-relative posix path, e.g. "ddl_tpu/utils/backoff.py"
    src: str
    tree: ast.Module
    mod: _Module
    # local binding -> fully-qualified dotted name.  For ``import x.y``
    # the binding is "x" -> "x" (the attribute chain completes it); for
    # ``from a.b import c as d`` it is "d" -> "a.b.c" with relative
    # levels resolved against this module's package.
    fq_imports: dict[str, str] = dataclasses.field(default_factory=dict)


def _module_name(package_root: Path, path: Path) -> str:
    rel = path.relative_to(package_root.parent)
    parts = list(rel.with_suffix("").parts)
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


class CallGraph:
    """Parsed view of one package: every module, its imports resolved to
    fully-qualified names, and the module-level dependency graph."""

    def __init__(self, package_root: str | Path) -> None:
        self.package_root = Path(package_root)
        self.repo_root = self.package_root.parent
        self.package = self.package_root.name
        self.modules: dict[str, ModuleInfo] = {}
        self.by_rel: dict[str, ModuleInfo] = {}
        for f in sorted(self.package_root.rglob("*.py")):
            src = f.read_text()
            try:
                tree = ast.parse(src)
            except SyntaxError:
                continue  # astlint reports the syntax error per-file
            name = _module_name(self.package_root, f)
            rel = f.relative_to(self.repo_root).as_posix()
            info = ModuleInfo(name, f, rel, src, tree, _Module(tree))
            self.modules[name] = info
            self.by_rel[rel] = info
        for info in self.modules.values():
            info.fq_imports = self._fq_imports(info)
        self._deps = {
            name: self._module_deps(info)
            for name, info in self.modules.items()
        }

    # ------------------------------------------------------------ imports

    def _fq_imports(self, info: ModuleInfo) -> dict[str, str]:
        out: dict[str, str] = {}
        parts = info.name.split(".")
        is_pkg = info.path.name == "__init__.py"
        # the package a level-1 relative import resolves against
        parent = parts if is_pkg else parts[:-1]
        for node in ast.walk(info.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        out[a.asname] = a.name
                    else:
                        head = a.name.split(".")[0]
                        out[head] = head
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = parent[: len(parent) - (node.level - 1)]
                    mod = ".".join(
                        base
                        + (node.module.split(".") if node.module else [])
                    )
                else:
                    mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    out[a.asname or a.name] = (
                        f"{mod}.{a.name}" if mod else a.name
                    )
        return out

    # --------------------------------------------------------- resolution

    def resolve_dotted(
        self, info: ModuleInfo, dotted: str, _depth: int = 0
    ) -> Target | None:
        """The function definition a dotted reference in ``info`` names,
        or None (method, external, or not statically resolvable)."""
        if not dotted or _depth > _MAX_REEXPORT_DEPTH:
            return None
        parts = dotted.split(".")
        head = parts[0]
        # a bare local name: the module's own def wins (it is the binding
        # the module actually calls in the common shadowing case)
        if len(parts) == 1:
            cands = info.mod.by_name.get(head)
            if cands:
                top = [c for c in cands if c.parent is None]
                if top:
                    return Target(info.name, top[-1])
        fq = info.fq_imports.get(head)
        if fq is None:
            return None
        return self._resolve_fq(fq.split(".") + parts[1:], _depth + 1)

    def _resolve_fq(
        self, parts: list[str], _depth: int = 0
    ) -> Target | None:
        if _depth > _MAX_REEXPORT_DEPTH:
            return None
        # longest module prefix inside the package
        for i in range(len(parts), 0, -1):
            mname = ".".join(parts[:i])
            if mname not in self.modules:
                continue
            rest = parts[i:]
            tinfo = self.modules[mname]
            if not rest:
                return None  # names a module, not a function
            if len(rest) == 1:
                cands = tinfo.mod.by_name.get(rest[0])
                top = [c for c in (cands or []) if c.parent is None]
                if top:
                    return Target(mname, top[-1])
            # Cls.method references (``mod.C.m`` passed to a transform)
            if len(rest) == 2 and rest[0] in tinfo.mod.classes:
                return self.resolve_class_method(
                    tinfo, rest[0], rest[1], _depth + 1
                )
            # re-export chase: the first remaining part is itself an
            # import binding in the matched module (package __init__
            # re-exporting a submodule's function, or a module alias)
            fq2 = tinfo.fq_imports.get(rest[0])
            if fq2:
                return self._resolve_fq(
                    fq2.split(".") + rest[1:], _depth + 1
                )
            return None
        return None

    # ------------------------------------------------- class methods

    def resolve_class_method(
        self, info: ModuleInfo, cls_dotted: str, meth: str,
        _depth: int = 0,
    ) -> Target | None:
        """Method ``meth`` of the class a (possibly imported) dotted
        constructor name refers to in ``info``'s namespace — the edge
        behind ``obj = C(...); obj.m()`` when ``C`` lives in another
        package module.  Base classes chase through import bindings to
        the same bounded depth as re-exports; anything outside the
        package resolves to None."""
        if not cls_dotted or _depth > _MAX_REEXPORT_DEPTH:
            return None
        parts = cls_dotted.split(".")
        if len(parts) == 1 and parts[0] in info.mod.classes:
            fn = info.mod.lookup_method(parts[0], meth)
            if fn is not None:
                return Target(info.name, fn)
            # same-module lookup exhausted: chase cross-module bases
            for base in info.mod.class_bases.get(parts[0], ()):
                if base.split(".")[0] in info.mod.classes:
                    continue  # local base, already chased above
                t = self.resolve_class_method(info, base, meth, _depth + 1)
                if t is not None:
                    return t
            return None
        fq = info.fq_imports.get(parts[0])
        if fq is None:
            return None
        return self._resolve_fq_method(
            fq.split(".") + parts[1:], meth, _depth + 1
        )

    def _resolve_fq_method(
        self, parts: list[str], meth: str, _depth: int = 0
    ) -> Target | None:
        if _depth > _MAX_REEXPORT_DEPTH:
            return None
        for i in range(len(parts), 0, -1):
            mname = ".".join(parts[:i])
            if mname not in self.modules:
                continue
            rest = parts[i:]
            tinfo = self.modules[mname]
            if len(rest) == 1:
                if rest[0] in tinfo.mod.classes:
                    return self.resolve_class_method(
                        tinfo, rest[0], meth, _depth + 1
                    )
                fq2 = tinfo.fq_imports.get(rest[0])
                if fq2:
                    return self._resolve_fq_method(
                        fq2.split("."), meth, _depth + 1
                    )
            return None
        return None

    # ------------------------------------------------------- dependencies

    def _module_deps(self, info: ModuleInfo) -> set[str]:
        deps: set[str] = set()
        for fq in info.fq_imports.values():
            parts = fq.split(".")
            for i in range(len(parts), 0, -1):
                m = ".".join(parts[:i])
                if m in self.modules:
                    deps.add(m)
                    break
        deps.discard(info.name)
        return deps

    def reverse_closure(self, names: set[str]) -> set[str]:
        """``names`` plus every module that (transitively) imports one of
        them — the set whose lint verdict a change to ``names`` can
        affect."""
        rev: dict[str, set[str]] = {}
        for m, ds in self._deps.items():
            for d in ds:
                rev.setdefault(d, set()).add(m)
        out = {n for n in names if n in self.modules}
        frontier = list(out)
        while frontier:
            n = frontier.pop()
            for m in rev.get(n, ()):
                if m not in out:
                    out.add(m)
                    frontier.append(m)
        return out


def changed_package_files(repo_root: str | Path) -> list[str] | None:
    """Paths (relative to ``repo_root``) of ``.py`` files touched in
    the working tree (staged + unstaged + untracked) vs HEAD, or None
    when git is unavailable (callers fall back to a full run).

    ``git diff`` reports paths relative to the git TOPLEVEL while
    ``git ls-files --others`` reports them relative to the cwd — both
    are normalized against the toplevel and re-relativized to
    ``repo_root``, so a package nested below the git root still
    matches the call graph's ``by_rel`` keys; files outside
    ``repo_root`` are dropped."""
    repo_root = Path(repo_root).resolve()
    try:
        toplevel = Path(subprocess.run(
            ["git", "rev-parse", "--show-toplevel"],
            cwd=repo_root, capture_output=True, text=True, check=True,
        ).stdout.strip())
        diff = subprocess.run(
            ["git", "diff", "--name-only", "HEAD"],
            cwd=toplevel, capture_output=True, text=True, check=True,
        ).stdout
        untracked = subprocess.run(
            ["git", "ls-files", "--others", "--exclude-standard"],
            cwd=toplevel, capture_output=True, text=True, check=True,
        ).stdout
    except (OSError, subprocess.CalledProcessError):
        return None
    out = set()
    for line in (diff + untracked).splitlines():
        line = line.strip()
        if not line.endswith(".py"):
            continue
        abs_path = toplevel / line
        try:
            out.add(abs_path.resolve().relative_to(repo_root).as_posix())
        except ValueError:
            continue  # outside repo_root (sibling package in a monorepo)
    return sorted(out)
