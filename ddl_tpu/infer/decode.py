"""Autoregressive inference for the transformer LM family.

The reference's only inference surface is a loss-less pipeline schedule used
for evaluation (``pp.py:146-150``); it has no generation path at all.  A
complete framework needs one, so this module adds KV-cached autoregressive
decoding over the *training* parameter tree — no weight export step, no
separate inference model:

* ``Attention``/``Block`` (``models/transformer.py``) expose an incremental
  mode sharing the training parameters by construction (same submodule
  names), so any training snapshot — including one restructured from the
  pipeline layout by ``parallel.lm_pipeline.convert_lm_state`` — decodes
  as-is.
* The KV cache is a static-shape ``(B, prompt+max_new, H, Dh)`` buffer per
  layer, updated in place via ``dynamic_update_slice`` — XLA keeps the
  update in-place on TPU, and the whole generate loop is ONE jitted
  program: prefill, then ``lax.scan`` over decode steps (compiler-friendly
  control flow; no per-token dispatch from Python).
* Sharding: the same logical-axis rule table as training
  (``parallel/sharding.py``) — batch over ``data``, heads over ``model`` —
  so tensor-parallel decode works on the same mesh as the training run.
  Sampling happens on replicated logits.
"""

from __future__ import annotations

import os
from functools import partial
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding

from ddl_tpu.models.transformer import (
    Block,
    LMConfig,
    apply_final_norm_and_head,
    make_embed,
)
from ddl_tpu.ops.quant import QuantKV
# Jit-boundary spec + the family rule table come from the partition-rule
# engine (parallel/rules.py); re-exported here for the generator's
# callers.
from ddl_tpu.parallel.rules import DECODE_TOKEN_SPEC, decode_rules
from ddl_tpu.parallel.sharding import (
    FLASH_AUTO_MIN_T,
    LMMeshSpec,
    build_lm_mesh,
    lm_logical_rules,
    validate_kv_head_sharding,
)

__all__ = ["LMDecode", "DECODE_TOKEN_SPEC", "init_kv_cache", "make_lm_generator"]


class LMDecode(nn.Module):
    """One incremental forward over the full layer stack.

    ``tokens`` (B, T) — the prompt at prefill (T = prompt length) or the
    last sampled token during decode (T = 1); ``caches`` — per-layer
    ``(k, v)`` tuples; ``offset`` — positions already in the cache.
    Returns (logits (B, T, V) f32, new caches).  Submodule names mirror
    ``TransformerLM`` exactly, so the training param tree applies as-is.
    """

    cfg: LMConfig
    rolling: bool = False  # ring cache of capacity attn_window
    # attention core for the PREFILL pass only (e.g. the flash kernel —
    # prefill is a training-style causal forward over the prompt); decode
    # steps (T=1) always use cached dense attention.
    attn_core: Optional[Callable] = None

    @nn.compact
    def __call__(
        self, tokens, caches, offset, last_only: bool = False,
        last_index=None,
    ):
        cfg = self.cfg
        x = make_embed(cfg)(tokens)
        x = nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))
        new_caches = []
        for i in range(cfg.n_layers):
            x, _aux, c = Block(cfg, self.attn_core, name=f"block{i}")(
                x, caches[i], offset, rolling=self.rolling
            )
            new_caches.append(c)
        if last_index is not None:
            # right-padded prefill (serve/engine.py bucketing): the
            # next-token logits live at the TRUE prompt end, not at -1.
            # Slicing before the head keeps the norm+head computation the
            # (B, 1, D) shape last_only compiles, so a padded prefill's
            # logits stay bit-identical to the unpadded single-request
            # program's (a full-width head + post-hoc index fuses
            # differently and drifts enough to flip near-tie argmaxes)
            x = lax.dynamic_slice_in_dim(x, last_index, 1, axis=1)
        elif last_only:  # prefill only needs the next-token logits
            x = x[:, -1:]
        return apply_final_norm_and_head(cfg, x), tuple(new_caches)


def init_kv_cache(
    cfg: LMConfig, batch: int, max_len: int, dtype=None,
    rolling: bool = False, quant: bool = False,
) -> tuple:
    """Per-layer zeroed ``(k, v)`` buffers of shape (B, L, Hkv*Dh).

    ``L`` is ``max_len``, or ``min(max_len, attn_window)`` with
    ``rolling=True`` — the ring cache holds only the window, so a
    windowed generation's cache memory is O(window) regardless of
    ``max_len`` (pair with ``LMDecode(rolling=True)``).

    With grouped-query attention (``cfg.n_kv_heads``) the cache holds only
    the K/V heads — an ``n_heads/n_kv_heads``-times smaller buffer, which
    is GQA's decode-bandwidth win (the grouped ``dense_attention`` reads it
    without re-materialising full heads).

    ``quant=True`` allocates ``ops.quant.QuantKV`` leaves instead: int8
    K/V plus per-(token, head) f32 scales — ~0.53x the bf16 bytes, the
    KV half of the int8 serving path (attention quantizes on write and
    reads the int8 buffers directly)."""
    if rolling and not cfg.attn_window:
        raise ValueError("rolling cache requires cfg.attn_window > 0")
    if quant and dtype is not None:
        raise ValueError(
            "quant=True fixes the cache layout (int8 + f32 scales); "
            "dtype cannot be combined with it"
        )
    dtype = dtype or cfg.dtype
    length = min(max_len, cfg.attn_window) if rolling else max_len
    # storage fuses (Hkv, Dh) -> Hkv*Dh so XLA's layout keeps the feature
    # dim in lanes and the per-token cache write is in place
    # (ops/quant.kv_fuse); readers unfuse at the attention einsum
    shape = (batch, length, cfg.kv_heads * cfg.head_dim)
    if quant:
        q = jnp.zeros(shape, jnp.int8)
        # scales keep L minor: the decode kernel reads one aligned (L,)
        # lane vector per head (ops/quant.QuantKV)
        s = jnp.zeros((batch, cfg.kv_heads, length), jnp.float32)
        return tuple(QuantKV(q, s, q, s) for _ in range(cfg.n_layers))
    zero = jnp.zeros(shape, dtype)
    return tuple((zero, zero) for _ in range(cfg.n_layers))


def make_lm_generator(
    cfg: LMConfig,
    spec: Optional[LMMeshSpec] = None,
    *,
    prompt_len: int,
    max_new: int,
    batch: int = 1,
    temperature: float = 0.0,
    top_k: int | None = None,
    devices=None,
    mesh=None,
    max_len: int | None = None,
    rolling: bool | None = None,
    kv_quant: bool = False,
    obs=None,
):
    """Build a jitted ``generate(params, prompt, rng) -> tokens`` function.

    ``prompt`` is (B, prompt_len) int32; the result is (B, max_new) int32.
    ``temperature=0`` decodes greedily; otherwise tokens are sampled from
    ``softmax(logits / temperature)``, optionally restricted to the
    ``top_k`` most likely tokens.  One XLA program: prefill + a
    ``lax.scan`` of single-token steps over a static-size KV cache.

    ``spec``/``devices`` (or an explicit ``mesh``) place the computation:
    batch over ``data``, attention heads over ``model`` (tensor-parallel
    decode), and the KV cache's sequence dimension over ``seq`` —
    context-parallel serving for prompts/caches one device cannot hold;
    the same logical-axis rules as training shard the cache, and GSPMD
    inserts the gather/reduce for the softmax over the sharded sequence
    (token-exact vs single device,
    ``tests/test_decode.py::test_seq_sharded_decode_matches_single_device``).
    ``cfg.attn_impl`` is ignored here — incremental decode is always
    cached dense attention; ring/Ulysses are training-time strategies
    for long-context *processing*.

    ``max_len`` overrides the KV-cache capacity (default
    ``prompt_len + max_new``).  Without a window every decode step reads
    the whole allocated buffer (masked), so per-step cost is set by the
    *capacity*, not the position — benchmarks comparing different
    ``max_new`` values must pin ``max_len`` to compare like with like.

    ``rolling`` selects the O(window)-memory ring cache (None = auto: on
    whenever ``cfg.attn_window`` is set and smaller than the cache
    length).  Windowed decode then allocates ``attn_window`` cache rows
    instead of ``max_len`` — identical outputs, ring-slot writes.

    ``kv_quant=True`` stores the KV cache int8 with per-(token, head)
    scales (``ops/quant.py``) — ~0.53x the cache bytes and HBM read
    traffic of bf16, the dominant decode cost at large batch.  Composes
    with GQA, sliding window and the rolling ring cache.  For int8
    *weights* too, pass ``ops.quant.quantize_lm_params(params)`` as the
    params — no generator flag needed (the matmul modules sniff the
    quantized tree).

    ``obs`` (an ``obs.events.EventWriter``) turns on per-request
    telemetry: each ``run()`` emits a ``decode_request`` span with
    ``dispatch``/``wait`` child spans and one ``decode`` event carrying
    prompt/output lengths, total latency, queueing delay,
    time-to-first-token, and tokens/s — the per-request fields
    ``obs summarize`` folds into serving-side p50/p95/p99
    (``obs/serving.py``).  Without obs, prefill and the per-token scan
    are ONE fused XLA program (no per-token dispatch from Python); with
    obs the program is split at the first sampled token — prefill+first
    token, then the remaining scan — so TTFT is a real fence on the
    first token rather than an estimate.  The split is sampling-exact
    (same RNG split sequence), costs one extra dispatch per request, and
    the second program is dispatched before the first is fenced, so the
    device pipeline stays full.  The fences make the request
    synchronous, which serving callers are anyway.

    ``run(..., submitted_at=perf_counter_value)`` lets a serving harness
    timestamp enqueue: the gap to dispatch is emitted as ``queue_delay``
    (0.0 for callers that dispatch inline).
    """
    if max_len is None:
        max_len = prompt_len + max_new
    elif max_len < prompt_len + max_new:
        raise ValueError(
            f"max_len {max_len} < prompt_len + max_new "
            f"({prompt_len} + {max_new})"
        )
    if rolling is None:
        rolling = bool(cfg.attn_window) and cfg.attn_window < max_len
    if rolling and not cfg.attn_window:
        raise ValueError("rolling=True requires cfg.attn_window > 0")
    if not cfg.causal:
        raise ValueError(
            "autoregressive decode requires a causal LM (cfg.causal=True); "
            "bidirectional-encoder configs (e.g. ViT's) have no decode order"
        )
    if top_k is not None:
        if temperature == 0.0:
            raise ValueError(
                "top_k has no effect with temperature=0 (greedy decoding); "
                "set a temperature or drop top_k"
            )
        if not 1 <= top_k <= cfg.vocab_size:
            raise ValueError(
                f"top_k {top_k} out of range [1, vocab_size={cfg.vocab_size}]"
            )
    validate_kv_head_sharding(cfg, spec or LMMeshSpec())
    if mesh is None:
        mesh = build_lm_mesh(spec or LMMeshSpec(), devices)
    rules = lm_logical_rules(cfg.fsdp)
    # Prefill is a training-style causal forward over the prompt, so it
    # can ride the flash kernel where training would (single-device mesh:
    # GSPMD cannot partition a Pallas custom call, and multi-device decode
    # keeps the dense prefill core inside its sharded program).
    attn_core = None
    if mesh.size == 1 and cfg.causal and (
        cfg.flash is True
        or (cfg.flash == "auto" and prompt_len >= FLASH_AUTO_MIN_T)
    ):
        from ddl_tpu.ops.flash_attention import flash_attention

        attn_core = partial(
            flash_attention, causal=True, window=cfg.attn_window
        )
    model = LMDecode(cfg, rolling=rolling, attn_core=attn_core)

    def sample(logits, rng):
        if temperature == 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if top_k is not None:
            kth = lax.top_k(logits, top_k)[0][..., -1:]
            logits = jnp.where(logits < kth, -jnp.inf, logits)
        return jax.random.categorical(
            rng, logits / jnp.float32(temperature), axis=-1
        ).astype(jnp.int32)

    def make_step(params):
        def step(carry, i):
            last, caches, rng = carry
            rng, sub = jax.random.split(rng)
            tok = sample(last, sub)
            with nn.logical_axis_rules(rules):
                logits, caches = model.apply(
                    {"params": params}, tok[:, None], caches, prompt_len + i
                )
            return (logits[:, 0], caches, rng), tok

        return step

    def _prefill(params, prompt, rng):
        """Prompt forward + the FIRST sampled token applied to the cache
        — everything TTFT covers."""
        caches = init_kv_cache(
            cfg, batch, max_len, rolling=rolling, quant=kv_quant
        )
        with nn.logical_axis_rules(rules):
            logits, caches = model.apply(
                {"params": params}, prompt, caches, 0, last_only=True
            )
        last = logits[:, -1]
        (last, caches, rng), tok0 = make_step(params)((last, caches, rng), 0)
        return tok0, last, caches, rng

    def _rest(params, tok0, last, caches, rng):
        """Decode steps 1..max_new-1 — the same RNG split sequence as
        one fused prefill+scan program, so the two-program split is
        token-identical to the fused path."""
        (_, _, _), toks = lax.scan(
            make_step(params), (last, caches, rng), jnp.arange(1, max_new)
        )
        return jnp.concatenate([tok0[:, None], toks.T], axis=1)

    def generate(params, prompt, rng):
        tok0, last, caches, rng = _prefill(params, prompt, rng)
        return _rest(params, tok0, last, caches, rng)

    tok_sharding = NamedSharding(mesh, DECODE_TOKEN_SPEC)

    jitted = jax.jit(
        generate,
        in_shardings=(None, tok_sharding, None),
        out_shardings=tok_sharding,
    )
    # the TTFT-splittable pair, compiled only when obs telemetry runs
    jitted_prefill = jax.jit(
        _prefill,
        in_shardings=(None, tok_sharding, None),
    )
    jitted_rest = jax.jit(_rest, out_shardings=tok_sharding)

    warmed = False
    # native request tracing (obs/trace.py span model): the one-shot
    # path emits the same trace_span chain the serve engine does —
    # request root, queue (when the caller timestamps enqueue), prefill
    # (dispatch -> first token), decode (the tail) — so `obs trace
    # --request/--slowest-request` works outside the serve engine.
    # Request ids are deterministic per generator (run id + sequence);
    # DDL_OBS_TRACE_SAMPLE=N thins to 1-in-N by sequence number, same
    # contract as ServeEngine(trace_sample=)
    seq = 0
    try:
        trace_sample = max(
            1, int(os.environ.get("DDL_OBS_TRACE_SAMPLE") or 1)
        )
    except ValueError:
        trace_sample = 1

    def _trace_span(name, t0_pc, t1_pc, *, trace, span, parent, **args):
        import time as _time

        wall, pc = _time.time(), _time.perf_counter()
        obs.emit(
            "trace_span", trace=trace, span=span, parent=parent,
            name=name, cat="decode",
            t0=wall - (pc - t0_pc), t1=wall - (pc - t1_pc), **args,
        )

    def run(params, prompt, rng=None, submitted_at=None):
        nonlocal warmed, seq
        if rng is None:
            rng = jax.random.key(0)
        if obs is None:
            with jax.set_mesh(mesh):
                return jitted(params, prompt, rng)
        from time import perf_counter

        from ddl_tpu.utils.timing import fence

        # the first request pays the XLA compile; flag it so summaries
        # can exclude it from steady-state percentiles (the same warmup
        # discipline as bench/analysis.comm_time_summary)
        warm, warmed = warmed, True
        req_id = f"{obs.run_id[:8]}-d{seq}"
        traced = seq % trace_sample == 0
        seq += 1
        t0 = perf_counter()
        # queueing delay: enqueue -> dispatch, when the serving harness
        # timestamps enqueue (perf_counter base); inline callers have no
        # queue, which 0.0 states honestly
        queue_delay = (
            max(0.0, t0 - submitted_at) if submitted_at is not None else 0.0
        )
        with obs.span(
            "decode_request", prompt_len=prompt_len, max_new=max_new,
            batch=batch,
        ):
            with obs.span("dispatch"):
                with jax.set_mesh(mesh):
                    # both programs dispatch back to back — the tail is
                    # queued behind prefill on the device, so fencing the
                    # first token below doesn't drain the pipeline
                    tok0, last, caches, rng2 = jitted_prefill(
                        params, prompt, rng
                    )
                    toks = jitted_rest(params, tok0, last, caches, rng2)
            with obs.span("wait"):
                with obs.span("first_token"):
                    fence(tok0)
                ttft = perf_counter() - t0
                fence(toks)
        dur = perf_counter() - t0
        if traced:
            end = perf_counter()
            first_tok = t0 + ttft
            root_t0 = submitted_at if submitted_at is not None else t0
            _trace_span(
                "request", root_t0, end,
                trace=req_id, span=f"{req_id}/req", parent=None,
                request_id=req_id, prompt_len=prompt_len,
                new_tokens=max_new, outcome="ok", dispatches=1,
            )
            if submitted_at is not None and submitted_at < t0:
                _trace_span(
                    "queue", submitted_at, t0,
                    trace=req_id, span=f"{req_id}/queue",
                    parent=f"{req_id}/req", request_id=req_id,
                )
            _trace_span(
                "prefill", t0, first_tok,
                trace=req_id, span=f"{req_id}/prefill",
                parent=f"{req_id}/req", tokens=prompt_len,
            )
            _trace_span(
                "decode", first_tok, end,
                trace=req_id, span=f"{req_id}/d0",
                parent=f"{req_id}/req", dispatch=0,
                new_tokens=max_new,
            )
        obs.emit(
            "decode",
            request_id=req_id,
            prompt_len=prompt_len,
            new_tokens=max_new,
            batch=batch,
            dur=dur,
            queue_delay=queue_delay,
            ttft=ttft,
            tok_per_s=batch * max_new / dur if dur > 0 else None,
            decode_tok_per_s=(
                batch * (max_new - 1) / (dur - ttft)
                if max_new > 1 and dur > ttft else None
            ),
            warm=warm,
        )
        return toks

    # sharding contract + lowering handles for `ddl_tpu lint`
    # (analysis/contracts.py), derived from the decode rule table:
    # decode has no train state to donate, and serving replicas
    # intentionally hold full parameter copies when the mesh has no
    # model axis — replication is contractual
    run.contract = decode_rules().contract()
    run.jitted = jitted
    run.mesh = mesh
    # abstract generate() args for the compiled-IR probes
    # (analysis/hlolint.py): the generator bakes batch/prompt_len in, so
    # the probe asks the factory for the committed shapes
    run.probe_inputs = lambda: (
        jax.ShapeDtypeStruct((batch, prompt_len), jnp.int32),
        jax.random.key(0),
    )
    return run
