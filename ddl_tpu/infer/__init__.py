from ddl_tpu.infer.decode import (
    LMDecode,
    init_kv_cache,
    make_lm_generator,
)

__all__ = ["LMDecode", "init_kv_cache", "make_lm_generator"]
