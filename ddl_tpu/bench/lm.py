"""LM training throughput benchmark (PERF.md's tokens/sec table).

    python -m ddl_tpu.bench.lm                  # GPT-2-small-ish, T=1024
    python -m ddl_tpu.bench.lm --seq-len 4096 --batch 2 --flash

True-fenced steady-state timing of the full train step (fwd + bwd +
AdamW) on the current default backend.
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ddl_tpu.models.transformer import LMConfig, REMAT_POLICIES
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.lm_steps import make_lm_step_fns
from ddl_tpu.utils.timing import fence


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--kv-heads", type=int, default=0,
                    help="grouped-query attention K/V head count (0 = MHA)")
    ap.add_argument("--attn-window", type=int, default=0,
                    help="sliding-window attention size (0 = full causal)")
    ap.add_argument("--vocab", type=int, default=50304)
    ap.add_argument("--flash", nargs="?", const="on", default="off",
                    choices=["on", "off", "auto"])
    ap.add_argument("--remat-policy", default="full",
                    choices=list(REMAT_POLICIES),
                    help="what the per-block checkpoint may save instead of "
                    "recomputing (LMConfig.remat_policy)")
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--ce-vocab-chunk", type=int, default=0,
                    help="vocab-streamed head+CE (losses."
                    "fused_vocab_chunked_ce): vocab-block size, 0 = off")
    ap.add_argument("--ce-chunk", type=int, default=0,
                    help="chunked head+CE fusion: sequence-chunk size for "
                    "the loss edge (0 = dense CE; the (B,T,V) logits are "
                    "never materialised when set)")
    ap.add_argument("--experts", type=int, default=0,
                    help="top-k MoE blocks with this many experts (0 = "
                    "dense MLP); combine with --d-ff to match active "
                    "FLOPs, e.g. 8 experts top-2 at half d_ff")
    ap.add_argument("--expert-top-k", type=int, default=2)
    ap.add_argument("--capacity-factor", type=float, default=1.5,
                    help="per-expert token capacity = k*S*cf/E; the router "
                    "drops overflow, so cf trades step time against "
                    "moe_drop_frac (watch both in the output)")
    ap.add_argument("--moe-dispatch", default="auto",
                    choices=["auto", "sort", "einsum"],
                    help="token routing path: one-hot einsum matmuls, "
                    "argsort + permutation gathers, or auto (einsum for "
                    "groups <= 2048 tokens)")
    ap.add_argument("--moe-group", type=int, default=256,
                    help="routing-group size in tokens (capacity is per "
                    "group; smaller groups cut dispatch cost ~linearly, "
                    "0 = whole sequence)")
    ap.add_argument("--d-ff", type=int, default=0,
                    help="MLP/expert hidden size (0 = 4*d_model)")
    ap.add_argument("--iters", type=int, default=10)
    args = ap.parse_args()

    from ddl_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    cfg = LMConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=args.d_model // 64,
        n_kv_heads=args.kv_heads,
        attn_window=args.attn_window,
        head_dim=64,
        d_ff=args.d_ff or 4 * args.d_model,
        num_experts=args.experts,
        expert_top_k=args.expert_top_k,
        capacity_factor=args.capacity_factor,
        moe_dispatch=args.moe_dispatch,
        moe_group=args.moe_group,
        compute_dtype="bfloat16",
        flash={"on": True, "off": False, "auto": "auto"}[args.flash],
        remat=not args.no_remat,
        remat_policy=args.remat_policy,
        ce_chunk=args.ce_chunk,
        ce_vocab_chunk=args.ce_vocab_chunk,
    )
    # resolve flash="auto" HERE and pass the concrete cfg down, so the
    # reported "flash" field is by construction the path benchmarked
    from ddl_tpu.parallel.sharding import normalize_flash

    cfg = normalize_flash(cfg, LMMeshSpec(), args.seq_len)
    fns = make_lm_step_fns(
        cfg, LMMeshSpec(), optax.adamw(3e-4), jax.random.key(0),
        args.batch, args.seq_len,
    )
    state = fns.init_state()
    toks = jnp.asarray(
        np.random.default_rng(0).integers(0, args.vocab, (args.batch, args.seq_len + 1))
    )
    inp, tgt = toks[:, :-1], toks[:, 1:]
    for _ in range(3):
        state, m = fns.train(state, inp, tgt)
    fence(m["loss"])
    t0 = time.perf_counter()
    for _ in range(args.iters):
        state, m = fns.train(state, inp, tgt)
    fence(m["loss"])
    dt = (time.perf_counter() - t0) / args.iters
    out = {
        "ms_per_step": round(dt * 1e3, 1),
        "tokens_per_sec": round(args.batch * args.seq_len / dt),
        "seq_len": args.seq_len,
        "batch": args.batch,
        "flash": bool(cfg.flash),  # the path auto actually picked
        "flash_mode": args.flash,
        "remat": "off" if args.no_remat else args.remat_policy,
        "ce_chunk": args.ce_chunk,
        "ce_vocab_chunk": args.ce_vocab_chunk,
        "loss": round(float(m["loss"]), 3),
    }
    if args.experts:
        out["experts"] = f"{args.experts}top{args.expert_top_k}"
        out["d_ff"] = cfg.d_ff
        out["capacity_factor"] = args.capacity_factor
        # record what the model RESOLVED, not what the CLI requested —
        # auto picks an impl and the group snaps to a divisor of S
        from ddl_tpu.models.transformer import moe_routing_plan

        out["moe_dispatch"], out["moe_group"] = moe_routing_plan(
            cfg, args.seq_len
        )
        for key in ("moe_drop_frac", "moe_load_max", "moe_load_min"):
            out[key] = round(float(m[key]), 4)
    from ddl_tpu.utils.memory import hbm_stats

    mem = hbm_stats()
    if mem is not None:
        out["hbm_peak_bytes"] = int(mem["peak_bytes_in_use"])
    from ddl_tpu.bench.mfu import (
        append_mfu,
        chunked_ce_extra_flops,
        flash_attention_train_flops,
    )

    # executed FLOPs: equals MFU with remat off, HFU otherwise.  Cost
    # analysis assigns zero FLOPs to the Pallas kernel, so flash rows add
    # the kernel's banded FLOPs analytically; it also counts scan bodies
    # once, so ce_chunk rows add the missing loss-edge trips (bench/mfu.py).
    # MFU rows count theoretical model matmuls; HFU rows count what the
    # program executes (incl. score recomputes / checkpoint replays).
    accounting = "model" if args.no_remat else "executed"
    extra_flops = (
        flash_attention_train_flops(
            args.batch, cfg.n_heads, args.seq_len, cfg.head_dim,
            cfg.n_layers, window=cfg.attn_window, remat=cfg.remat,
            accounting=accounting,
        )
        if cfg.flash
        else 0.0
    )
    if cfg.ce_vocab_chunk:
        from ddl_tpu.bench.mfu import vocab_chunked_ce_extra_flops

        extra_flops += vocab_chunked_ce_extra_flops(
            args.batch, args.seq_len, args.d_model, args.vocab,
            cfg.ce_vocab_chunk, accounting=accounting,
        )
    if cfg.ce_chunk:
        extra_flops += chunked_ce_extra_flops(
            args.batch, args.seq_len, args.d_model, args.vocab,
            cfg.ce_chunk, accounting=accounting,
        )
    append_mfu(out, fns.train, dt, state, inp, tgt,
               key="mfu" if args.no_remat else "hfu",
               extra_flops=extra_flops)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
