"""Shared per-op device-time trace analysis (`jax.profiler.ProfileData`).

Captures live on any workload: run the step a few times warm, trace N
steps, then aggregate the device plane's sync-op line — XLA-op exclusive
times — into opcode categories.  The async-DMA line is reported
separately (those copies overlap compute; summing them into the op time
double-counts).  Used by ``profile_densenet`` (the headline CNN story,
PERF.md round 4) and ``profile_lm``.
"""

from __future__ import annotations

import collections
import glob
import os
import re

__all__ = ["analyze", "opcode_of", "print_report", "CATEGORY"]

# HLO text looks like "%fusion.123 = bf16[...] fusion(...), kind=kLoop ..."
_OPCODE_RX = re.compile(r"=\s*(?:\([^)]*\)|[^ ]+)\s+([a-z][a-z0-9-]*)\(")


def opcode_of(name: str) -> str:
    """Pull the HLO opcode out of a profiler op-event name."""
    m = _OPCODE_RX.search(name)
    if m:
        op = m.group(1)
    else:
        # bare names like "fusion.123" / "copy-start.4"
        op = name.split(" ")[0].lstrip("%").split(".")[0]
    if "fusion" in name and (kind := re.search(r"kind=k(\w+)", name)):
        return f"fusion:{kind.group(1)}"
    return op


CATEGORY = {
    "convolution": "conv",
    "fusion:Output": "conv/matmul fusion (+fused elementwise)",
    "fusion:Convolution": "conv/matmul fusion (+fused elementwise)",
    "dot": "conv/matmul fusion (+fused elementwise)",
    "copy": "copy (layout/concat materialise)",
    "copy-start": "async copy (overlapped)",
    "copy-done": "copy-done (DMA wait)",
    "slice-start": "async slice (overlapped)",
    "slice-done": "slice-done (DMA wait)",
    "dynamic-update-slice": "copy (layout/concat materialise)",
    "concatenate": "copy (layout/concat materialise)",
    "fusion:Loop": "fusion (elementwise loops)",
    "fusion:Input": "fusion (reduce/stats)",
    "reduce": "fusion (reduce/stats)",
    "reduce-window": "fusion (reduce/stats)",
    "fusion:Custom": "custom call (Pallas)",
    "custom-call": "custom call (Pallas)",
    "all-gather-start": "collective",
    "all-reduce-start": "collective",
    "collective-permute-start": "collective",
    "sort": "sort",
    "scatter": "scatter",
    "gather": "gather",
}


def analyze(trace_dir: str):
    """Aggregate a captured trace.  Returns (per_op ms, per_op counts,
    async-DMA busy ms, XLA-module ms) — all totals over the traced steps."""
    from jax.profiler import ProfileData

    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    data = ProfileData.from_file(max(paths, key=os.path.getmtime))

    per_op: dict[str, float] = collections.defaultdict(float)
    per_op_count: dict[str, int] = collections.defaultdict(int)
    async_ms = 0.0
    module_ms = 0.0
    for plane in data.planes:
        if not plane.name.startswith("/device:"):
            continue
        for line in plane.lines:
            if line.name == "XLA Modules":
                module_ms += sum(
                    (e.end_ns - e.start_ns) / 1e6 for e in line.events
                )
            if line.name == "Async XLA Ops":
                async_ms += sum(
                    (e.end_ns - e.start_ns) / 1e6 for e in line.events
                )
            if line.name != "XLA Ops":
                continue  # Steps/Modules duplicate; Async overlaps compute
            for ev in line.events:
                dur = (ev.end_ns - ev.start_ns) / 1e6  # ms
                per_op[ev.name] += dur
                per_op_count[ev.name] += 1
    return per_op, per_op_count, async_ms, module_ms


def print_report(trace_dir: str, steps: int, top: int = 25, header: str = ""):
    """Analyze + print the category table, top ops, and one JSON line.
    Returns the category dict (ms/step)."""
    import json

    per_op, per_op_count, async_ms, module_ms = analyze(trace_dir)
    total = sum(per_op.values())
    cats: dict[str, float] = collections.defaultdict(float)
    for name, ms in per_op.items():
        op = opcode_of(name)
        cats[CATEGORY.get(op, f"other ({op})")] += ms

    print(f"# trace: {trace_dir}  ({steps} steps{header})")
    print(f"# XLA module time: {module_ms / steps:.2f} ms/step; "
          f"sync-op exclusive total: {total / steps:.2f} ms/step; "
          f"async-DMA busy (overlapped): {async_ms / steps:.2f} ms/step")
    print("\n== by category (ms/step, % of sync op time) ==")
    for cat, ms in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"  {cat:40s} {ms / steps:8.3f}  "
              f"({100 * ms / total:5.1f}%)")
    print(f"\n== top {top} ops (ms/step, count/step) ==")
    rows = sorted(per_op.items(), key=lambda kv: -kv[1])[:top]
    for name, ms in rows:
        n = per_op_count[name] // steps
        print(f"  {ms / steps:8.3f}  x{n:<4d} {name[:140]}")
    print(json.dumps({
        "module_ms_per_step": round(module_ms / steps, 3),
        "sync_op_ms_per_step": round(total / steps, 3),
        "async_dma_busy_ms_per_step": round(async_ms / steps, 3),
        "category_ms_per_step": {
            k: round(v / steps, 3) for k, v in cats.items()
        },
    }))
    return cats
