"""Shared per-op device-time trace analysis (`jax.profiler.ProfileData`).

Captures live on any workload: run the step a few times warm, trace N
steps, then aggregate the device plane's sync-op line — XLA-op exclusive
times — into opcode categories.  The async-DMA line is reported
separately (those copies overlap compute; summing them into the op time
double-counts).  Used by ``profile_densenet`` (the headline CNN story,
PERF.md round 4), ``profile_lm``, and the anomaly-triggered capture path
(``obs/profiler.py``), whose ``profile_capture`` events carry the
``op_digest`` summary so a regression is explainable without opening
TensorBoard.

Runtime compatibility: newer JAX exposes ``jax.profiler.ProfileData``;
the container's older runtime (see ``compat.py``) does not, so this
module carries a minimal protobuf *wire-format* reader for the stable
XSpace/XPlane schema — no TensorFlow/xprof import, just the handful of
field numbers the analysis needs.  CPU traces additionally have no
``/device:`` plane at all (XLA ops land on ``/host:CPU`` thread-pool
lines named ``tf_XLA*``), so the readers fall back to those when no
device plane exists — the same digest, host-sided, which is exactly what
a CPU-JAX CI run can check.
"""

from __future__ import annotations

import collections
import glob
import os
import re

__all__ = [
    "analyze", "op_digest", "opcode_of", "print_report", "read_trace",
    "CATEGORY",
]

# HLO text looks like "%fusion.123 = bf16[...] fusion(...), kind=kLoop ..."
_OPCODE_RX = re.compile(r"=\s*(?:\([^)]*\)|[^ ]+)\s+([a-z][a-z0-9-]*)\(")


def opcode_of(name: str) -> str:
    """Pull the HLO opcode out of a profiler op-event name."""
    m = _OPCODE_RX.search(name)
    if m:
        op = m.group(1)
    else:
        # bare names like "fusion.123" / "copy-start.4"
        op = name.split(" ")[0].lstrip("%").split(".")[0]
    if "fusion" in name and (kind := re.search(r"kind=k(\w+)", name)):
        return f"fusion:{kind.group(1)}"
    return op


CATEGORY = {
    "convolution": "conv",
    "fusion:Output": "conv/matmul fusion (+fused elementwise)",
    "fusion:Convolution": "conv/matmul fusion (+fused elementwise)",
    "dot": "conv/matmul fusion (+fused elementwise)",
    "copy": "copy (layout/concat materialise)",
    "copy-start": "async copy (overlapped)",
    "copy-done": "copy-done (DMA wait)",
    "slice-start": "async slice (overlapped)",
    "slice-done": "slice-done (DMA wait)",
    "dynamic-update-slice": "copy (layout/concat materialise)",
    "concatenate": "copy (layout/concat materialise)",
    "fusion:Loop": "fusion (elementwise loops)",
    "fusion:Input": "fusion (reduce/stats)",
    "reduce": "fusion (reduce/stats)",
    "reduce-window": "fusion (reduce/stats)",
    "fusion:Custom": "custom call (Pallas)",
    "custom-call": "custom call (Pallas)",
    "all-gather-start": "collective",
    "all-reduce-start": "collective",
    "collective-permute-start": "collective",
    "sort": "sort",
    "scatter": "scatter",
    "gather": "gather",
}


# ---------------------------------------------------------------------------
# Trace readers.  Both normalize to the same shape:
#     [(plane_name, line_name, [(event_name, dur_ms), ...]), ...]
# ---------------------------------------------------------------------------


def _pb_varint(buf: bytes, i: int) -> tuple[int, int]:
    shift = val = 0
    while True:
        b = buf[i]
        i += 1
        val |= (b & 0x7F) << shift
        if not b & 0x80:
            return val, i
        shift += 7


def _pb_fields(buf: bytes):
    """Iterate (field_number, value) over one serialized proto message —
    the minimal wire-format walk (varint + length-delimited + fixed)."""
    i, n = 0, len(buf)
    while i < n:
        key, i = _pb_varint(buf, i)
        fnum, wt = key >> 3, key & 7
        if wt == 0:  # varint
            val, i = _pb_varint(buf, i)
        elif wt == 1:  # fixed64
            val, i = buf[i:i + 8], i + 8
        elif wt == 2:  # length-delimited
            ln, i2 = _pb_varint(buf, i)
            val, i = buf[i2:i2 + ln], i2 + ln
        elif wt == 5:  # fixed32
            val, i = buf[i:i + 4], i + 4
        else:
            raise ValueError(f"unsupported wire type {wt}")
        yield fnum, val


def _read_xplane_wire(path: str):
    """Parse an ``*.xplane.pb`` without ``ProfileData``: XSpace.planes=1;
    XPlane{name=2, lines=3, event_metadata=4}; XLine{name=2,
    display_name=11, events=4}; XEvent{metadata_id=1, duration_ps=3};
    XEventMetadata{name=2, display_name=4} — the stable subset of the
    schema this analysis needs."""
    with open(path, "rb") as fh:
        space = fh.read()
    planes = []
    for fnum, plane_buf in _pb_fields(space):
        if fnum != 1:
            continue
        pname, line_bufs, meta = "", [], {}
        for f2, v2 in _pb_fields(plane_buf):
            if f2 == 2:
                pname = v2.decode("utf-8", "replace")
            elif f2 == 3:
                line_bufs.append(v2)
            elif f2 == 4:  # map<int64, XEventMetadata>
                key, name = None, ""
                for f3, v3 in _pb_fields(v2):
                    if f3 == 1:
                        key = v3
                    elif f3 == 2:
                        for f4, v4 in _pb_fields(v3):
                            if f4 == 2 and not name:
                                name = v4.decode("utf-8", "replace")
                            elif f4 == 4:  # display_name wins
                                name = v4.decode("utf-8", "replace")
                if key is not None:
                    meta[key] = name
        lines = []
        for lb in line_bufs:
            lname, ldisp, events = "", "", []
            for f3, v3 in _pb_fields(lb):
                if f3 == 2:
                    lname = v3.decode("utf-8", "replace")
                elif f3 == 11:
                    ldisp = v3.decode("utf-8", "replace")
                elif f3 == 4:
                    mid = dur_ps = 0
                    for f4, v4 in _pb_fields(v3):
                        if f4 == 1:
                            mid = v4
                        elif f4 == 3:
                            dur_ps = v4
                    events.append((meta.get(mid, f"op-{mid}"), dur_ps / 1e9))
            lines.append((ldisp or lname, events))
        planes.append((pname, lines))
    return planes


def _read_xplane_profiledata(path: str):
    from jax.profiler import ProfileData

    data = ProfileData.from_file(path)
    return [
        (
            plane.name,
            [
                (
                    line.name,
                    [
                        (ev.name, (ev.end_ns - ev.start_ns) / 1e6)
                        for ev in line.events
                    ],
                )
                for line in plane.lines
            ],
        )
        for plane in data.planes
    ]


def read_trace(trace_dir: str):
    """Read the newest ``*.xplane.pb`` under ``trace_dir`` into
    ``[(plane_name, [(line_name, [(event_name, dur_ms), ...]), ...])]``,
    via ``ProfileData`` when this runtime has it, else the wire reader."""
    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    path = max(paths, key=os.path.getmtime)
    try:
        from jax.profiler import ProfileData  # noqa: F401
    except ImportError:
        return _read_xplane_wire(path)
    return _read_xplane_profiledata(path)


# Host-plane lines that carry XLA op execution when there is no device
# plane (CPU backend): the thread-pool lines the CPU client names
# tf_XLAEigen/... and tf_XLATfrtCpuClient/....  Runtime bookkeeping
# events on those lines are filtered by name.
_HOST_XLA_LINE = re.compile(r"^tf_XLA")
_HOST_NOISE = re.compile(
    r"ThreadpoolListener|ThunkExecutor|^\$|^Execute$|Infeed|Outfeed"
)


def _op_events(planes):
    """(event_name, dur_ms) pairs of executed XLA ops: the device planes'
    sync-op line, or the host XLA thread-pool lines when no device plane
    exists (CPU traces)."""
    out = []
    for pname, lines in planes:
        if not pname.startswith("/device:"):
            continue
        for lname, events in lines:
            if lname == "XLA Ops":
                out.extend(events)
    if out:
        return out
    for pname, lines in planes:
        if not pname.startswith("/host:"):
            continue
        for lname, events in lines:
            if _HOST_XLA_LINE.search(lname):
                out.extend(
                    (n, d) for n, d in events if not _HOST_NOISE.search(n)
                )
    return out


def analyze(trace_dir: str):
    """Aggregate a captured trace.  Returns (per_op ms, per_op counts,
    async-DMA busy ms, XLA-module ms) — all totals over the traced steps."""
    planes = read_trace(trace_dir)

    per_op: dict[str, float] = collections.defaultdict(float)
    per_op_count: dict[str, int] = collections.defaultdict(int)
    async_ms = 0.0
    module_ms = 0.0
    for pname, lines in planes:
        if not pname.startswith("/device:"):
            continue
        for lname, events in lines:
            if lname == "XLA Modules":
                module_ms += sum(d for _, d in events)
            if lname == "Async XLA Ops":
                async_ms += sum(d for _, d in events)
    for name, dur in _op_events(planes):
        per_op[name] += dur
        per_op_count[name] += 1
    return per_op, per_op_count, async_ms, module_ms


def op_digest(trace_dir: str, top: int = 8) -> dict:
    """Compact per-op-category device-time summary of a captured trace —
    the payload ``profile_capture`` events carry so a throughput anomaly
    is explainable from the event stream alone.  ``{"total_ms", "ops":
    {category: ms (top N)}, "top_op": name}``; ms totals are over the
    whole traced window."""
    per_op, _counts, _async_ms, module_ms = analyze(trace_dir)
    cats: dict[str, float] = collections.defaultdict(float)
    for name, ms in per_op.items():
        op = opcode_of(name)
        cats[CATEGORY.get(op, f"other ({op})")] += ms
    ranked = sorted(cats.items(), key=lambda kv: -kv[1])
    top_op = max(per_op.items(), key=lambda kv: kv[1])[0] if per_op else None
    return {
        "total_ms": round(sum(per_op.values()), 3),
        "module_ms": round(module_ms, 3),
        "ops": {k: round(v, 3) for k, v in ranked[:top]},
        "top_op": top_op[:140] if top_op else None,
    }


def print_report(trace_dir: str, steps: int, top: int = 25, header: str = ""):
    """Analyze + print the category table, top ops, and one JSON line.
    Returns the category dict (ms/step)."""
    import json

    per_op, per_op_count, async_ms, module_ms = analyze(trace_dir)
    total = sum(per_op.values())
    cats: dict[str, float] = collections.defaultdict(float)
    for name, ms in per_op.items():
        op = opcode_of(name)
        cats[CATEGORY.get(op, f"other ({op})")] += ms

    print(f"# trace: {trace_dir}  ({steps} steps{header})")
    print(f"# XLA module time: {module_ms / steps:.2f} ms/step; "
          f"sync-op exclusive total: {total / steps:.2f} ms/step; "
          f"async-DMA busy (overlapped): {async_ms / steps:.2f} ms/step")
    print("\n== by category (ms/step, % of sync op time) ==")
    for cat, ms in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"  {cat:40s} {ms / steps:8.3f}  "
              f"({100 * ms / total:5.1f}%)")
    print(f"\n== top {top} ops (ms/step, count/step) ==")
    rows = sorted(per_op.items(), key=lambda kv: -kv[1])[:top]
    for name, ms in rows:
        n = per_op_count[name] // steps
        print(f"  {ms / steps:8.3f}  x{n:<4d} {name[:140]}")
    print(json.dumps({
        "module_ms_per_step": round(module_ms / steps, 3),
        "sync_op_ms_per_step": round(total / steps, 3),
        "async_dma_busy_ms_per_step": round(async_ms / steps, 3),
        "category_ms_per_step": {
            k: round(v / steps, 3) for k, v in cats.items()
        },
    }))
    return cats
