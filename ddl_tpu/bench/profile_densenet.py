"""Per-op device-time breakdown of the headline DenseNet121 train step.

Captures a ``jax.profiler`` trace of the bs-30 train step (the
``bench.py`` headline workload) and aggregates XLA-op device time via
the shared ``bench/xprof`` analysis.  This is the evidence channel for
PERF.md's "where do the headline milliseconds go" analysis (VERDICT r3
task 1: profile the headline instead of defending it).  The default
measures the packed impl (the config default since round 4); pass
``--impl concat`` to reproduce the textbook-form table in PERF.md, or
``--impl fused`` for the round-6 trainable Pallas-block path (blocks
per ``ModelConfig.dense_block_fused_blocks``).  The same table renders
from any stored trace with ``ddl_tpu bench digest <trace_dir|latest>``.

Usage::

    python -m ddl_tpu.bench.profile_densenet [--batch 30] [--steps 10]

Prints a per-category table, the top-N individual ops with their HLO
names, and one JSON line with the category split.
"""

from __future__ import annotations

import argparse
import tempfile


def capture(batch: int, steps: int, trace_dir: str, impl: str = "packed"):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddl_tpu.config import ModelConfig, TrainConfig
    from ddl_tpu.models import build_stages
    from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
    from ddl_tpu.train.state import create_train_state, make_optimizer
    from ddl_tpu.train.steps import make_dp_step_fns
    from ddl_tpu.utils.compile_cache import enable_compile_cache
    from ddl_tpu.utils.timing import fence

    enable_compile_cache()
    cfg = ModelConfig(compute_dtype="bfloat16", dense_block_impl=impl)
    stages = build_stages(cfg, num_stages=1)
    tx = make_optimizer(TrainConfig())
    state = create_train_state(stages, tx, jax.random.key(0), image_size=224)
    mesh = build_mesh(MeshSpec(1, 1))
    fns = make_dp_step_fns(stages, tx, mesh, jnp.bfloat16)

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.integers(0, 255, (batch, 224, 224, 3)), jnp.uint8)
    labels = jnp.asarray(rng.integers(0, 5, (batch,)), jnp.int32)

    for _ in range(3):  # compile + steady
        state, loss, _ = fns.train(state, images, labels)
    fence(loss)

    jax.profiler.start_trace(trace_dir)
    for _ in range(steps):
        state, loss, _ = fns.train(state, images, labels)
    fence(loss)
    jax.profiler.stop_trace()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=30)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--impl", default="packed",
                    choices=("concat", "buffer", "packed", "fused"))
    ap.add_argument("--trace-dir", default=None,
                    help="reuse an existing trace instead of capturing")
    args = ap.parse_args()

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="dn_prof_")
    if not args.trace_dir:
        capture(args.batch, args.steps, trace_dir, args.impl)

    from ddl_tpu.bench.xprof import print_report

    print_report(
        trace_dir, args.steps, args.top,
        header=f", batch {args.batch}, impl {args.impl}",
    )



if __name__ == "__main__":
    main()
