"""Per-op device-time breakdown of the headline DenseNet121 train step.

Captures a ``jax.profiler`` trace of the bs-30 train step (the
``bench.py`` headline workload) and aggregates XLA-op device time from
the trace's device plane (``jax.profiler.ProfileData`` — no TensorBoard
round-trip), attributing each fused op to a category (conv / batch-norm
reduction / elementwise / copy-concat / optimizer / other).  This is the
evidence channel for PERF.md's "where do 16 ms actually go" analysis
(VERDICT r3 task 1: profile the headline instead of defending it).

Usage::

    python -m ddl_tpu.bench.profile_densenet [--batch 30] [--steps 10]

Prints a per-category table, the top-N individual ops with their HLO
names, and one JSON line with the category split.
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import re
import tempfile


def capture(batch: int, steps: int, trace_dir: str, impl: str = "concat"):
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddl_tpu.config import ModelConfig, TrainConfig
    from ddl_tpu.models import build_stages
    from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
    from ddl_tpu.train.state import create_train_state, make_optimizer
    from ddl_tpu.train.steps import make_dp_step_fns
    from ddl_tpu.utils.compile_cache import enable_compile_cache
    from ddl_tpu.utils.timing import fence

    enable_compile_cache()
    cfg = ModelConfig(compute_dtype="bfloat16", dense_block_impl=impl)
    stages = build_stages(cfg, num_stages=1)
    tx = make_optimizer(TrainConfig())
    state = create_train_state(stages, tx, jax.random.key(0), image_size=224)
    mesh = build_mesh(MeshSpec(1, 1))
    fns = make_dp_step_fns(stages, tx, mesh, jnp.bfloat16)

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.integers(0, 255, (batch, 224, 224, 3)), jnp.uint8)
    labels = jnp.asarray(rng.integers(0, 5, (batch,)), jnp.int32)

    for _ in range(3):  # compile + steady
        state, loss, _ = fns.train(state, images, labels)
    fence(loss)

    jax.profiler.start_trace(trace_dir)
    for _ in range(steps):
        state, loss, _ = fns.train(state, images, labels)
    fence(loss)
    jax.profiler.stop_trace()


# HLO text looks like "%fusion.123 = bf16[...] fusion(...), kind=kLoop ..."
_OPCODE_RX = re.compile(r"=\s*(?:\([^)]*\)|[^ ]+)\s+([a-z][a-z0-9-]*)\(")


def opcode_of(name: str) -> str:
    """Pull the HLO opcode out of a profiler op-event name."""
    m = _OPCODE_RX.search(name)
    if m:
        op = m.group(1)
    else:
        # bare names like "fusion.123" / "copy-start.4"
        op = name.split(" ")[0].lstrip("%").split(".")[0]
    if "fusion" in name and (kind := re.search(r"kind=k(\w+)", name)):
        return f"fusion:{kind.group(1)}"
    return op


_CATEGORY = {
    "convolution": "conv",
    "fusion:Output": "conv-fusion (conv+fused elementwise)",
    "fusion:Convolution": "conv-fusion (conv+fused elementwise)",
    "copy": "copy (layout/concat materialise)",
    "copy-start": "async copy (overlapped)",
    "copy-done": "copy-done (DMA wait)",
    "slice-start": "async slice (overlapped)",
    "slice-done": "slice-done (DMA wait)",
    "dynamic-update-slice": "copy (layout/concat materialise)",
    "concatenate": "copy (layout/concat materialise)",
    "fusion:Loop": "fusion (elementwise loops)",
    "fusion:Input": "fusion (reduce/BN stats)",
    "reduce": "fusion (reduce/BN stats)",
    "reduce-window": "fusion (reduce/BN stats)",
}


def analyze(trace_dir: str):
    from jax.profiler import ProfileData

    paths = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not paths:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    data = ProfileData.from_file(max(paths, key=os.path.getmtime))

    per_op: dict[str, float] = collections.defaultdict(float)
    per_op_count: dict[str, int] = collections.defaultdict(int)
    async_ms = 0.0
    module_ms = 0.0
    for plane in data.planes:
        if not plane.name.startswith("/device:"):
            continue
        for line in plane.lines:
            if line.name == "XLA Modules":
                module_ms += sum(
                    (e.end_ns - e.start_ns) / 1e6 for e in line.events
                )
            if line.name == "Async XLA Ops":
                async_ms += sum(
                    (e.end_ns - e.start_ns) / 1e6 for e in line.events
                )
            if line.name != "XLA Ops":
                continue  # Steps/Modules duplicate; Async overlaps compute
            for ev in line.events:
                dur = (ev.end_ns - ev.start_ns) / 1e6  # ms
                per_op[ev.name] += dur
                per_op_count[ev.name] += 1
    return per_op, per_op_count, async_ms, module_ms


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=30)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--impl", default="concat",
                    choices=("concat", "buffer", "packed"))
    ap.add_argument("--trace-dir", default=None,
                    help="reuse an existing trace instead of capturing")
    args = ap.parse_args()

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="dn_prof_")
    if not args.trace_dir:
        capture(args.batch, args.steps, trace_dir, args.impl)

    per_op, per_op_count, async_ms, module_ms = analyze(trace_dir)
    total = sum(per_op.values())
    cats: dict[str, float] = collections.defaultdict(float)
    for name, ms in per_op.items():
        op = opcode_of(name)
        cats[_CATEGORY.get(op, f"other ({op})")] += ms

    print(f"# trace: {trace_dir}  ({args.steps} steps, batch {args.batch})")
    print(f"# XLA module time: {module_ms / args.steps:.2f} ms/step; "
          f"sync-op exclusive total: {total / args.steps:.2f} ms/step; "
          f"async-DMA busy (overlapped): {async_ms / args.steps:.2f} ms/step")
    print("\n== by category (ms/step, % of sync op time) ==")
    for cat, ms in sorted(cats.items(), key=lambda kv: -kv[1]):
        print(f"  {cat:40s} {ms / args.steps:8.3f}  "
              f"({100 * ms / total:5.1f}%)")
    print(f"\n== top {args.top} ops (ms/step, count/step) ==")
    rows = sorted(per_op.items(), key=lambda kv: -kv[1])[: args.top]
    for name, ms in rows:
        n = per_op_count[name] // args.steps
        print(f"  {ms / args.steps:8.3f}  x{n:<4d} {name[:140]}")
    print(json.dumps({
        "module_ms_per_step": round(module_ms / args.steps, 3),
        "sync_op_ms_per_step": round(total / args.steps, 3),
        "async_dma_busy_ms_per_step": round(async_ms / args.steps, 3),
        "category_ms_per_step": {
            k: round(v / args.steps, 3) for k, v in cats.items()
        },
    }))


if __name__ == "__main__":
    main()
