"""Offline results analysis (reference ``ipynb/main.ipynb`` equivalent).

The reference's only "published" numbers are pandas tables stored in a
notebook: mean epoch time per job (cell 3), final-epoch quality metrics
averaged per strategy (cell 5), and communication round-trip means excluding
iteration 0 (cell 9).  This module reproduces those aggregations as a plain
script over the CSV logs this framework (and the reference) writes.

    python -m ddl_tpu.bench.analysis --log-dir training_logs
"""

from __future__ import annotations

import argparse
import csv
from collections import defaultdict
from pathlib import Path

import numpy as np

from ddl_tpu.utils.csv_logger import read_metric_csv

QUALITY_METRICS = [
    "loss",
    "train_accuracy",
    "val_loss",
    "val_accuracy",
    "weighted_f1",
    "qwk",
]


def epoch_time_per_job(log_dir: Path) -> dict[str, float]:
    """Mean epoch_time per job id (notebook cell 3)."""
    out = {}
    for job_dir in sorted((log_dir / "by_job_id").glob("*")):
        f = job_dir / "epoch_time.csv"
        if f.exists():
            rows = read_metric_csv(f)
            if rows:
                out[job_dir.name] = float(np.mean([r["value"] for r in rows]))
    return out


def final_epoch_quality(log_dir: Path, final_epoch: int | None = None) -> dict:
    """Per-strategy mean of final-epoch quality metrics (notebook cell 5).

    Strategy is read as the job-id prefix before the first '-', matching the
    reference's '<strategy>-<hash>' TorchX job names.
    """
    per_strategy: dict[str, dict[str, list[float]]] = defaultdict(lambda: defaultdict(list))
    for job_dir in sorted((log_dir / "by_job_id").glob("*")):
        strategy = job_dir.name.split("-")[0]
        for metric in QUALITY_METRICS:
            f = job_dir / f"{metric}.csv"
            if not f.exists():
                continue
            rows = read_metric_csv(f)
            if not rows:
                continue
            last = final_epoch if final_epoch is not None else max(r["epoch"] for r in rows)
            vals = [r["value"] for r in rows if r["epoch"] == last]
            if vals:
                per_strategy[strategy][metric].append(float(np.mean(vals)))
    return {
        s: {m: float(np.mean(v)) for m, v in metrics.items()}
        for s, metrics in per_strategy.items()
    }


THROUGHPUT_METRICS = ["steps_per_sec", "tokens_per_sec", "img_per_sec"]


def throughput_per_job(log_dir: Path) -> dict[str, dict[str, float]]:
    """Mean throughput per job across whichever rate metrics it logged —
    covers all three families (CNN steps_per_sec, LM tokens_per_sec, ViT
    img_per_sec).  No analog in the reference notebook, which derives
    steps/sec offline from epoch_time."""
    out: dict[str, dict[str, float]] = {}
    for job_dir in sorted((log_dir / "by_job_id").glob("*")):
        rates = {}
        for metric in THROUGHPUT_METRICS:
            f = job_dir / f"{metric}.csv"
            if f.exists():
                rows = read_metric_csv(f)
                if rows:
                    rates[metric] = float(np.mean([r["value"] for r in rows]))
        if rates:
            out[job_dir.name] = rates
    return out


def obs_summaries_per_job(log_dir: Path) -> dict[str, dict]:
    """One ``summarize_run`` pass per job over the structured event
    streams (``ddl_tpu/obs/``) that trainers write beside the CSVs —
    shared by the phase-breakdown and profile-digest sections so the
    event corpus is parsed once per report.  Jobs without an event
    stream (reference-framework runs, pre-obs logs) are simply absent."""
    from ddl_tpu.obs.report import load_run, summarize_run

    out: dict[str, dict] = {}
    by_job = log_dir / "by_job_id"
    if not by_job.is_dir():
        return out
    for job_dir in sorted(by_job.glob("*")):
        events = load_run(log_dir, job_dir.name)
        if events:
            out[job_dir.name] = summarize_run(events)
    return out


def phase_breakdown_per_job(
    log_dir: Path, summaries: dict[str, dict] | None = None
) -> dict[str, dict[str, float]]:
    """Per-job step-phase totals (seconds) — the sub-period attribution
    the reference's CSV schema cannot carry."""
    if summaries is None:
        summaries = obs_summaries_per_job(log_dir)
    return {
        job: s["phases"] for job, s in summaries.items() if s["phases"]
    }


def profile_digests_per_job(
    log_dir: Path, summaries: dict[str, dict] | None = None
) -> dict[str, list[dict]]:
    """Per-job anomaly-triggered profile captures with their stored
    per-op digests (``profile_capture`` events, ``obs/profiler.py``) —
    the perf-PR evidence channel surfaced in the offline report, so a
    regression investigation starts from this table instead of a raw
    trace directory (render any trace in full with ``ddl_tpu bench
    digest <dir>``)."""
    if summaries is None:
        summaries = obs_summaries_per_job(log_dir)
    out: dict[str, list[dict]] = {}
    for job, s in summaries.items():
        captures = s.get("profile_captures") or []
        if captures:
            out[job] = captures
    return out


def comm_time_summary(log_dir: Path) -> dict[str, dict]:
    """Per-job mean round-trip excluding iteration 0 (notebook cell 9)."""
    f = log_dir / "communication_time.csv"
    if not f.exists():
        return {}
    per_job: dict[str, list[tuple[int, float]]] = defaultdict(list)
    with open(f, newline="") as fh:
        for rec in csv.reader(fh):
            if len(rec) == 3:
                per_job[rec[0]].append((int(rec[1]), float(rec[2])))
    out = {}
    for job, rows in per_job.items():
        steady = [t for i, t in rows if i > 0]
        out[job] = {
            "mean_ms": float(np.mean(steady)) if steady else float("nan"),
            "init_ms": next((t for i, t in rows if i == 0), float("nan")),
            "iterations": len(rows),
        }
    return out


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--log-dir", default="training_logs")
    args = ap.parse_args(argv)
    log_dir = Path(args.log_dir)

    print("== mean epoch time per job (s) ==")
    for job, t in epoch_time_per_job(log_dir).items():
        print(f"  {job}: {t:.2f}")
    print("== final-epoch quality per strategy ==")
    for s, metrics in final_epoch_quality(log_dir).items():
        print(f"  {s}: " + " ".join(f"{m}={v:.4f}" for m, v in metrics.items()))
    print("== mean throughput per job ==")
    for job, rates in throughput_per_job(log_dir).items():
        print(f"  {job}: " + " ".join(f"{m}={v:.1f}" for m, v in rates.items()))
    summaries = obs_summaries_per_job(log_dir)
    print("== step-phase breakdown per job (s, from event streams) ==")
    for job, phases in phase_breakdown_per_job(log_dir, summaries).items():
        body = " ".join(
            f"{name}={dur:.2f}"
            for name, dur in sorted(phases.items(), key=lambda kv: -kv[1])
        )
        print(f"  {job}: {body}")
    digests = profile_digests_per_job(log_dir, summaries)
    if digests:
        print("== profile captures per job (top op categories, ms) ==")
        for job, captures in digests.items():
            for c in captures:
                if not c.get("ok"):
                    print(f"  {job} [{c.get('trigger', '?')}]: "
                          f"capture failed ({c.get('error')})")
                    continue
                dig = c.get("digest") or {}
                ops = "  ".join(
                    f"{k}={v:.1f}" for k, v in list(
                        (dig.get("ops") or {}).items()
                    )[:5]
                )
                print(f"  {job} step {c.get('step')} "
                      f"[{c.get('trigger')}]: {ops or c.get('trace_dir')}")
    print("== communication round-trip per job ==")
    for job, r in comm_time_summary(log_dir).items():
        print(f"  {job}: mean={r['mean_ms']:.3f}ms init={r['init_ms']:.1f}ms n={r['iterations']}")


if __name__ == "__main__":
    main()
