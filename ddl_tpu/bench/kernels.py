"""Kernel microbenchmarks: Pallas flash attention vs XLA dense lowering.

Reproduces PERF.md's kernel table on real hardware:

    python -m ddl_tpu.bench.kernels                 # fwd/bwd sweep over T
    python -m ddl_tpu.bench.kernels --blocks        # block-size sweep

Method (round 3): sub-10 ms kernels are invisible to per-call timing
through the axon tunnel — each dispatch costs ~10 ms of RPC, so a
1 ms kernel "measures" as 11 ms and a genuine 2x kernel advantage
disappears into the floor (round 2's kernel table had exactly this
artifact; VERDICT round 2, Weak #3).  Here each kernel runs inside an
on-device ``lax.fori_loop`` chain and the reported figure is the
wall-clock SLOPE between an n1-iteration and an n2-iteration program —
launch cost, transfers, and fence round-trips cancel, leaving pure
device time per call.
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ddl_tpu.ops.attention import dense_attention
from ddl_tpu.ops.flash_attention import flash_attention
from ddl_tpu.utils.timing import fence

__all__ = ["time_device_slope", "attention_sweep", "block_sweep"]


def time_device_slope(
    fn, x0, n1: int = 10, n2: int = 50, reps: int = 4,
    target_s: float | None = None,
) -> float:
    """Pure device ms/call: slope between n1- and n2-iteration on-device
    chains (``y = fn(y)`` under ``lax.fori_loop``), best-of-``reps`` walls
    so tunnel-RPC variance drops out.

    ``target_s`` auto-scales the chain so the long wall is ~that many
    seconds: sub-0.1 ms kernels under a 50-iteration chain (5 ms wall)
    are invisible inside the tunnel's multi-ms jitter — round 3's small-T
    kernel rows carried exactly that bias (see PERF.md round 4)."""

    def wall(n: int) -> float:
        j = jax.jit(
            lambda x: lax.fori_loop(
                0, n, lambda i, y: fn(y).astype(y.dtype), x
            )
        )
        fence(j(x0))  # compile + warm
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            fence(j(x0))
            best = min(best, time.perf_counter() - t0)
        return best

    if target_s is not None:
        # calibrate per-call time from a short SLOPE (a single wall is
        # dominated by the fixed ~0.15 s tunnel round-trip for fast fns)
        per_call_s = max(
            (wall(4 * n1) - wall(n1)) / (3 * n1), 1e-7
        )
        n2 = max(int(target_s / per_call_s), n1 * 4)
        n2 = min(n2, 20000)
    return (wall(n2) - wall(n1)) / (n2 - n1) * 1e3


def attention_sweep(seq_lens=(1024, 2048, 4096, 8192), b=2, h=8, d=64):
    rows = []
    for t in seq_lens:
        q0 = jnp.asarray(
            np.random.default_rng(0).normal(size=(b, t, h, d)), jnp.bfloat16
        )
        fns = {
            "flash_fwd": lambda x: flash_attention(x, x, x, causal=True),
            "dense_fwd": lambda x: dense_attention(x, x, x, causal=True),
            "flash_bwd": jax.grad(
                lambda x: flash_attention(x, x, x, causal=True)
                .astype(jnp.float32).sum()
            ),
            "dense_bwd": jax.grad(
                lambda x: dense_attention(x, x, x, causal=True)
                .astype(jnp.float32).sum()
            ),
        }
        row = {"T": t}
        for name, fn in fns.items():
            row[name + "_ms"] = round(
                time_device_slope(fn, q0, n1=20, target_s=0.8), 4
            )
        rows.append(row)
        print(row, flush=True)
    return rows


def block_sweep(t=8192, b=2, h=8, d=64):
    q0 = jnp.asarray(
        np.random.default_rng(0).normal(size=(b, t, h, d)), jnp.bfloat16
    )
    rows = []
    for bq, bk in (
        (128, 128), (256, 256), (512, 512), (512, 1024), (1024, 1024),
    ):
        for direction in ("fwd", "bwd"):
            fn = (
                (lambda x, bq=bq, bk=bk: flash_attention(
                    x, x, x, causal=True, block_q=bq, block_k=bk
                ))
                if direction == "fwd"
                else jax.grad(
                    lambda x, bq=bq, bk=bk: flash_attention(
                        x, x, x, causal=True, block_q=bq, block_k=bk
                    ).astype(jnp.float32).sum()
                )
            )
            ms = round(time_device_slope(fn, q0, n1=5, target_s=0.8), 3)
            rows.append(
                {"block_q": bq, "block_k": bk, "dir": direction, "ms": ms}
            )
            print(rows[-1], flush=True)
    return rows


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--blocks", action="store_true", help="block-size sweep")
    ap.add_argument("--t", type=int, default=8192,
                    help="sequence length for --blocks")
    args = ap.parse_args()
    if args.blocks:
        block_sweep(t=args.t)
    else:
        attention_sweep()
