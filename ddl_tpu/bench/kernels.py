"""Kernel microbenchmarks: Pallas flash attention vs XLA dense lowering.

Reproduces PERF.md's kernel table on real hardware:

    python -m ddl_tpu.bench.kernels                 # fwd/bwd sweep over T
    python -m ddl_tpu.bench.kernels --blocks        # block-size sweep

All timings use the true device fence (``utils/timing.fence``) and chained
iterations so per-call dispatch latency amortises (PERF.md methodology).
"""

from __future__ import annotations

import argparse
import time

import jax
import jax.numpy as jnp
import numpy as np

from ddl_tpu.ops.attention import dense_attention
from ddl_tpu.ops.flash_attention import flash_attention
from ddl_tpu.utils.timing import fence

__all__ = ["time_chained", "attention_sweep", "block_sweep"]


def time_chained(fn, x0, iters: int) -> float:
    """Mean ms/call over ``iters`` chained calls (each consumes the last
    result, so the device cannot overlap them away)."""
    fence(fn(x0))  # compile + warm
    t0 = time.perf_counter()
    o = x0
    for _ in range(iters):
        o = fn(o)
    fence(o)
    return (time.perf_counter() - t0) / iters * 1e3


def attention_sweep(seq_lens=(1024, 2048, 4096, 8192), b=2, h=8, d=64):
    rows = []
    for t in seq_lens:
        q0 = jnp.asarray(
            np.random.default_rng(0).normal(size=(b, t, h, d)), jnp.bfloat16
        )
        fns = {
            "flash_fwd": (jax.jit(lambda x: flash_attention(x, x, x, causal=True)), 20),
            "dense_fwd": (jax.jit(lambda x: dense_attention(x, x, x, causal=True)), 20),
            "flash_bwd": (jax.jit(jax.grad(
                lambda x: flash_attention(x, x, x, causal=True)
                .astype(jnp.float32).sum())), 10),
            "dense_bwd": (jax.jit(jax.grad(
                lambda x: dense_attention(x, x, x, causal=True)
                .astype(jnp.float32).sum())), 10),
        }
        row = {"T": t}
        for name, (fn, iters) in fns.items():
            row[name + "_ms"] = round(time_chained(fn, q0, iters), 2)
        rows.append(row)
        print(row, flush=True)
    return rows


def block_sweep(t=8192, b=2, h=8, d=64):
    q0 = jnp.asarray(
        np.random.default_rng(0).normal(size=(b, t, h, d)), jnp.bfloat16
    )
    rows = []
    for bq, bk in ((128, 128), (256, 256), (512, 512), (1024, 1024)):
        fn = jax.jit(
            lambda x, bq=bq, bk=bk: flash_attention(
                x, x, x, causal=True, block_q=bq, block_k=bk
            )
        )
        ms = round(time_chained(fn, q0, 20), 2)
        rows.append({"block_q": bq, "block_k": bk, "ms": ms})
        print(rows[-1], flush=True)
    return rows


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--blocks", action="store_true", help="block-size sweep")
    args = ap.parse_args()
    if args.blocks:
        block_sweep()
    else:
        attention_sweep()
