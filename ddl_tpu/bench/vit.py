"""ViT training throughput benchmark (PERF.md's ViT row).

    python -m ddl_tpu.bench.vit                 # ViT-S/16, 224px, batch 64
    python -m ddl_tpu.bench.vit --no-remat

True-fenced steady-state timing of the full train step (uint8 normalize +
fwd + bwd + AdamW) on the current default backend, same data shapes as the
DenseNet headline bench (bench.py).
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax

from ddl_tpu.models.transformer import REMAT_POLICIES
from ddl_tpu.models.vit import ViTConfig
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.vit_steps import make_vit_step_fns
from ddl_tpu.utils.timing import fence


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--patch", type=int, default=16)
    ap.add_argument("--d-model", type=int, default=384)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--remat-policy", default="full",
                    choices=list(REMAT_POLICIES))
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    from ddl_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    cfg = ViTConfig(
        image_size=args.image_size,
        patch_size=args.patch,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=args.d_model // 64,
        head_dim=64,
        d_ff=4 * args.d_model,
        compute_dtype="bfloat16",
        remat=not args.no_remat,
        remat_policy=args.remat_policy,
    )
    fns = make_vit_step_fns(
        cfg, LMMeshSpec(), optax.adamw(3e-4), jax.random.key(0), args.batch
    )
    state = fns.init_state()
    rng = np.random.default_rng(0)
    imgs = jnp.asarray(
        rng.integers(0, 255, (args.batch, args.image_size, args.image_size, 3))
        .astype(np.uint8)
    )
    labels = jnp.asarray(rng.integers(0, 5, (args.batch,)).astype(np.int32))
    for _ in range(3):
        state, m = fns.train(state, imgs, labels)
    fence(m["loss"])
    t0 = time.perf_counter()
    for _ in range(args.iters):
        state, m = fns.train(state, imgs, labels)
    fence(m["loss"])
    dt = (time.perf_counter() - t0) / args.iters
    out = {
        "ms_per_step": round(dt * 1e3, 1),
        "images_per_sec": round(args.batch / dt),
        "batch": args.batch,
        "remat": "off" if args.no_remat else args.remat_policy,
        "loss": round(float(m["loss"]), 3),
    }
    from ddl_tpu.bench.mfu import append_mfu

    append_mfu(out, fns.train, dt, state, imgs, labels,
               key="mfu" if args.no_remat else "hfu")
    print(json.dumps(out))


if __name__ == "__main__":
    main()
