"""Autoregressive decode benchmark: prefill latency + steady-state tokens/sec.

The reference has no generation path at all (its only inference surface is
a loss-less eval pipeline, ``pp.py:146-150``); this framework ships one
(``infer/decode.py``) and makes two perf claims about it — the
``Hq/Hkv``-times smaller KV-cache reads of grouped-query attention and the
O(window) cache slice of sliding-window decode.  This bench measures both
on one chip instead of asserting them.

Method: the generator is ONE jitted program (prefill + ``lax.scan`` of
single-token steps), so prefill and decode cannot be fenced separately.
Prefill is measured with a ``max_new=1`` run (one decode token ~0.5-2 ms
against a 100+ ms prefill); the decode rate is the wall-clock slope
between ``max_new=n`` and ``2n`` runs, which cancels the tunnel's fixed
dispatch/fence cost.  All three runs pin the SAME KV-cache capacity
(``max_len = prompt + 2n``): without a window every step reads the whole
allocated buffer (masked) regardless of position, so per-step cost is a
function of capacity — equal allocations make the slope the true
steady-state per-token cost at that capacity.

    python -m ddl_tpu.bench.decode                 # 124M, prompt 4k, cache 8k
    python -m ddl_tpu.bench.decode --sweep         # MHA/GQA x full/window
"""

from __future__ import annotations

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

from ddl_tpu.infer.decode import make_lm_generator
from ddl_tpu.models.transformer import LMConfig, TransformerLM
from ddl_tpu.utils.timing import fence


def _is_oom(e: Exception) -> bool:
    """XLA allocation failure: the RESOURCE_EXHAUSTED runtime status, or
    the compiler's canonical compile-time OOM line — which some
    transports (the dev tunnel's remote-compile wrapper) re-wrap as
    INTERNAL, hiding the typed status.  Both are matched on exact XLA
    phrasing, not loose substrings like 'memory'."""
    return isinstance(e, jax.errors.JaxRuntimeError) and (
        "RESOURCE_EXHAUSTED" in str(e)
        or "Ran out of memory in memory space hbm" in str(e)
    )


def _bench_one(
    args, batch: int, kv_heads: int, window: int, quant: str = "none"
) -> dict:
    cfg = LMConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=args.d_model // 64,
        n_kv_heads=kv_heads,
        attn_window=window,
        head_dim=64,
        d_ff=4 * args.d_model,
        compute_dtype="bfloat16",
        remat=False,
        # prefill is a training-style causal forward, so it rides the
        # flash kernel from the auto threshold up; without it a large-
        # batch prefill materialises O(B*T^2) f32 scores and OOMs
        flash="auto",
    )
    params = TransformerLM(cfg, None).init(
        jax.random.key(0), jnp.zeros((batch, 8), jnp.int32)
    )["params"]
    import flax.linen as nn

    params = nn.meta.unbox(params)
    if quant not in ("none", "kv", "kv+w"):
        raise ValueError(f"quant mode must be none|kv|kv+w, got {quant!r}")
    kv_quant = quant != "none"
    if quant == "kv+w":
        from ddl_tpu.ops.quant import quantize_lm_params

        params = quantize_lm_params(params)
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, args.vocab, (batch, args.prompt)), jnp.int32
    )

    n1, n2 = args.new, 2 * args.new
    capacity = args.prompt + n2

    def timed(max_new: int) -> float:
        gen = make_lm_generator(
            cfg, prompt_len=args.prompt, max_new=max_new, batch=batch,
            max_len=capacity,  # equal allocations across the three runs
            kv_quant=kv_quant,
        )
        fence(gen(params, prompt))  # compile + warm
        t0 = time.perf_counter()
        for _ in range(args.iters):
            out = gen(params, prompt)
        fence(out)
        return (time.perf_counter() - t0) / args.iters

    t_pre, t1, t2 = timed(1), timed(n1), timed(n2)
    ms_per_tok = (t2 - t1) / (n2 - n1) * 1e3
    slope_fallback = False
    if ms_per_tok <= 0:
        # a host-contention spike in one of the two runs can make the
        # difference negative; one resample of the pair before reporting
        t1, t2 = timed(n1), timed(n2)
        ms_per_tok = (t2 - t1) / (n2 - n1) * 1e3
    if ms_per_tok <= 0:
        if jax.devices()[0].platform == "tpu":
            # a real-chip quote must be slope-honest or not reported
            raise RuntimeError(
                f"host contention: decode slope non-positive after "
                f"resample ({ms_per_tok:.4f} ms/tok) — rerun on a "
                f"quieter machine"
            )
        # CPU harness runs (tier-1's bench smoke): sub-microsecond CPU
        # walls make the two-length slope pure noise, and a raise here
        # was a suite-order-dependent flake (PR 6 verify).  Fall back to
        # the undifferenced long-run quote — deterministic and positive,
        # fixed dispatch cost included — and say so in the row.
        ms_per_tok = t2 / n2 * 1e3
        slope_fallback = True
    kv = cfg.kv_heads
    # windowed rows use the O(window)-memory ring cache (the generator's
    # rolling auto-mode); read the real allocation from init_kv_cache so
    # the reported bytes cannot drift from what the generator builds —
    # including the int8 + f32-scale layout of the quantized cache
    from ddl_tpu.infer.decode import init_kv_cache

    rolling = bool(window) and window < capacity
    layer0 = jax.eval_shape(
        lambda: init_kv_cache(
            cfg, batch, capacity, rolling=rolling, quant=kv_quant
        )
    )[0]
    alloc = layer0[0].shape[1]
    layer_bytes = sum(
        int(np.prod(a.shape)) * a.dtype.itemsize
        for a in jax.tree_util.tree_leaves(layer0)
    )
    span = min(window, capacity) if window else capacity
    param_bytes = sum(
        int(np.prod(a.shape)) * a.dtype.itemsize
        for a in jax.tree_util.tree_leaves(params)
    )
    return {
        "heads": f"{cfg.n_heads}q/{kv}kv",
        "window": window,
        "quant": quant,
        "prompt": args.prompt,
        "max_len": capacity,
        "batch": batch,
        "prefill_ms": round(t_pre * 1e3, 1),
        "decode_ms_per_tok": round(ms_per_tok, 3),
        # CPU-only: the slope was noise-negative and this row quotes the
        # undifferenced wall-clock rate instead (never set on TPU rows)
        **({"slope_fallback": True} if slope_fallback else {}),
        "decode_tok_per_sec": round(batch / (ms_per_tok / 1e3), 1),
        # allocation vs what one decode step actually reads per layer
        "cache_bytes_per_layer": layer_bytes,
        "read_bytes_per_step_layer": int(layer_bytes * span / max(alloc, 1)),
        "param_bytes": param_bytes,
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--prompt", type=int, default=4096)
    ap.add_argument("--new", type=int, default=2048,
                    help="decode lengths benched: --new and 2x --new "
                    "(slope method); max cache = prompt + 2x new")
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--vocab", type=int, default=50304)
    ap.add_argument("--kv-heads", type=int, default=0)
    ap.add_argument("--attn-window", type=int, default=0)
    ap.add_argument("--iters", type=int, default=3)
    ap.add_argument("--sweep", action="store_true",
                    help="run the PERF.md grid: MHA vs GQA (12q/4kv) x "
                    "full cache vs window 1024")
    ap.add_argument("--batches", default=None,
                    help="comma-separated batch sizes (e.g. 1,8,32), each "
                    "crossed with the config grid — the serving question: "
                    "how do weights/cache amortise across concurrent "
                    "streams (overrides --batch)")
    ap.add_argument("--quant", default="none",
                    help="comma-separated quant modes crossed with the "
                    "grid: none (bf16), kv (int8 KV cache), kv+w (int8 "
                    "cache AND int8 weight streaming) — ops/quant.py")
    args = ap.parse_args()

    from ddl_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    if args.iters < 1:
        ap.error("--iters must be >= 1")
    if args.new < 1:
        ap.error("--new must be >= 1 (decode lengths benched are --new "
                 "and 2x --new)")
    if args.sweep:
        if args.kv_heads or args.attn_window:
            ap.error("--sweep supplies its own grid; drop "
                     "--kv-heads/--attn-window")
        n_heads = args.d_model // 64
        # grouped rows use the largest >=3x grouping the head count allows
        kv = next(
            (n_heads // g for g in (3, 4, 2) if n_heads % g == 0), 0
        )
        if not kv:
            ap.error(f"--sweep needs a groupable head count, got {n_heads}")
        grid = [(0, 0), (kv, 0), (0, 1024), (kv, 1024)]
    else:
        grid = [(args.kv_heads, args.attn_window)]
    batches = (
        [int(x) for x in args.batches.split(",")]
        if args.batches
        else [args.batch]
    )
    quants = [q.strip() for q in args.quant.split(",")]
    bad = [q for q in quants if q not in ("none", "kv", "kv+w")]
    if bad:
        ap.error(f"--quant modes must be none|kv|kv+w, got {bad}")
    for b in batches:
        for kv, win in grid:
            for qm in quants:
                try:
                    print(json.dumps(_bench_one(args, b, kv, win, qm)),
                          flush=True)
                except Exception as e:  # OOM rows are results, not
                    # crashes: a B=32 MHA full cache is 2x9.7 GB through
                    # the scan carry and does not fit a 16 GB chip — that
                    # line IS the GQA/window/int8 story
                    if not _is_oom(e):
                        raise
                    print(json.dumps({
                        "heads": f"{args.d_model // 64}q/"
                                 f"{kv or args.d_model // 64}kv",
                        "window": win, "quant": qm, "batch": b,
                        "error": "hbm_oom",
                    }), flush=True)


if __name__ == "__main__":
    main()
