"""Collective-communication microbenchmarks over the device mesh.

TPU-native re-design of the reference's latency probe
(``communication_time.py``): there, rank0 times a 4 MiB fp32 NCCL ``send`` to
rank1 plus a 1-float ack ``recv`` with CUDA events, 1000 iterations appended
to a CSV, iteration 0 discarded as NCCL-init cost (``ipynb/main.ipynb`` cell
9).  Here the equivalent p2p primitive is a jitted ``lax.ppermute`` pair over
a 2-device mesh — payload one hop forward, ack one hop back — fenced with a
true device fence (``utils/timing.fence``: block + 1-element readback, since
bare ``block_until_ready`` can return before execution on tunneled
backends), with iteration 0 likewise the compile+warmup cost.  The fence's
own host round-trip is measured separately (``fence_floor_ms``) and
subtracted from the reported mean.  On top of the reference's
ping-pong, this module also measures the collectives the framework actually
trains with (``psum``, ``all_gather``, ``ppermute``) across a size sweep and
reports algorithmic bandwidth — the number that predicts DP-allreduce and
pipeline-handoff cost (BASELINE.json's "allreduce GB/s" target metric).

CSV output keeps the reference's row shape: ``job_id,iteration,elapsed_ms``.
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from functools import partial
from time import perf_counter

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from jax.sharding import Mesh, PartitionSpec as P

from ddl_tpu.utils.timing import fence

__all__ = [
    "PingPongResult",
    "ping_pong",
    "collective_bandwidth",
    "axis_bandwidth_sweep",
    "run_comm_bench",
]

COLLECTIVE_OPS = ("psum", "all_gather", "reduce_scatter", "ppermute", "all_to_all")

DEFAULT_PAYLOAD_ELEMS = 1024 * 1024  # 4 MiB fp32, reference communication_time.py:18


@dataclass
class PingPongResult:
    times_ms: np.ndarray  # per-iteration round-trip, iteration 0 = warmup/compile
    payload_bytes: int
    fence_floor_ms: float = 0.0  # host cost of the fence itself

    @property
    def mean_ms(self) -> float:
        """Mean excluding iteration 0 (init cost, per reference analysis),
        net of the measured per-sample fence overhead."""
        if len(self.times_ms) <= 1:
            return float("nan")
        return max(float(self.times_ms[1:].mean()) - self.fence_floor_ms, 1e-6)

    @property
    def one_way_gbps(self) -> float:
        return self.payload_bytes / (self.mean_ms * 1e-3) / 1e9


def _ring_mesh(n: int | None = None) -> Mesh:
    devices = jax.devices()
    n = n or min(2, len(devices))
    return Mesh(np.array(devices[:n]), ("ring",))


def ping_pong(
    iterations: int = 1000,
    payload_elems: int = DEFAULT_PAYLOAD_ELEMS,
    mesh: Mesh | None = None,
) -> PingPongResult:
    """Round-trip: payload device0 -> device1, 1-float ack device1 -> device0."""
    mesh = mesh or _ring_mesh(2)
    n = mesh.devices.size
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [((i + 1) % n, i) for i in range(n)]

    @jax.jit
    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=P("ring"),
        out_specs=P("ring"),
        check_vma=False,
    )
    def round_trip(x):
        y = lax.ppermute(x, "ring", fwd)
        ack = lax.ppermute(y[:1], "ring", bwd)
        return x + ack  # depend on the ack so the full round trip is timed

    x = jnp.ones((n * payload_elems,), jnp.float32)
    times = np.empty(iterations + 1)
    for i in range(iterations + 1):
        t0 = perf_counter()
        fence(round_trip(x))
        times[i] = (perf_counter() - t0) * 1e3
    # fence cost on an already-materialised array: the per-sample overhead
    # the fence adds on top of the round trip being measured
    floors = np.empty(20)
    for i in range(len(floors)):
        t0 = perf_counter()
        fence(x)
        floors[i] = (perf_counter() - t0) * 1e3
    return PingPongResult(
        times_ms=times,
        payload_bytes=payload_elems * 4,
        fence_floor_ms=float(np.median(floors)),
    )


def collective_bandwidth(
    op: str,
    mesh: Mesh | None = None,
    payload_elems: int = DEFAULT_PAYLOAD_ELEMS,
    iterations: int = 50,
    axis: str | None = None,
) -> dict:
    """Algorithmic bandwidth of one collective over one mesh axis.

    ``axis`` defaults to the mesh's first axis; on a multi-axis mesh the
    collective runs *within* the groups of that axis (the other axes stay
    idle), which is exactly how the training programs issue them — so a
    per-axis sweep attributes link bandwidth to the mesh axis that will
    carry each collective (DP grads on ``data``, Ulysses ``all_to_all`` on
    ``seq``, TP all-reduce on ``model``, stage handoff on ``pipe``).

    algbw = bytes_moved_per_device / time; for psum the standard convention
    bytes = 2 * (n-1)/n * payload (reduce-scatter + all-gather phases).
    """
    mesh = mesh or _ring_mesh()
    axis = axis or mesh.axis_names[0]
    n = mesh.shape[axis]
    # tiled reduce_scatter/all_to_all need the per-device shard divisible
    # by the axis size — round up so odd axis sizes (3, 5, 6 on real pods)
    # measure instead of aborting; payload_bytes reports the actual size
    payload_elems = -(-payload_elems // n) * n
    ring = [(i, (i + 1) % n) for i in range(n)]

    if op == "psum":
        body, out_spec = (lambda v: lax.psum(v, axis)), P(axis)
    elif op == "all_gather":
        body, out_spec = (lambda v: lax.all_gather(v, axis, tiled=True)), P()
    elif op == "reduce_scatter":
        body, out_spec = (lambda v: lax.psum_scatter(v, axis, tiled=True)), P(axis)
    elif op == "ppermute":
        body, out_spec = (lambda v: lax.ppermute(v, axis, ring)), P(axis)
    elif op == "all_to_all":
        # the Ulysses hot collective (parallel/ulysses.py): each device
        # splits its shard n ways and exchanges — (n-1)/n of it crosses
        # the links
        body, out_spec = (
            lambda v: lax.all_to_all(v, axis, 0, 0, tiled=True),
            P(axis),
        )
    else:
        raise ValueError(op)

    fn = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=P(axis),
            out_specs=out_spec,
            check_vma=False,
        )
    )
    x = jnp.ones((n * payload_elems,), jnp.float32)

    fence(fn(x))  # compile
    t0 = perf_counter()
    for _ in range(iterations):
        out = fn(x)
    fence(out)
    elapsed = (perf_counter() - t0) / iterations
    payload_bytes = payload_elems * 4
    if op == "psum":
        moved = 2 * (n - 1) / n * payload_bytes
    elif op == "all_gather":
        # per-device shard is payload_bytes; gathered result n * payload
        moved = (n - 1) / n * (payload_bytes * n)
    elif op in ("reduce_scatter", "all_to_all"):
        moved = (n - 1) / n * payload_bytes
    else:
        moved = payload_bytes
    return {
        "op": op,
        "axis": axis,
        "devices": n,
        "payload_bytes": payload_bytes,
        "mean_ms": elapsed * 1e3,
        "algbw_gbps": moved / elapsed / 1e9,
    }


def axis_bandwidth_sweep(
    mesh: Mesh,
    ops: tuple[str, ...] = COLLECTIVE_OPS,
    payload_elems: int = DEFAULT_PAYLOAD_ELEMS,
    iterations: int = 50,
) -> dict[str, dict[str, dict]]:
    """Run every collective over every non-trivial axis of ``mesh``.

    Returns ``{axis: {op: collective_bandwidth result}}`` — on a real pod
    this shows which axes ride ICI vs DCN (the reference measured exactly
    this split by hand: ~10.6 GB/s intra-node vs ~0.23 GB/s inter-node,
    SURVEY.md §6), so shardings can be laid out to put the chatty
    collectives on the fast axes."""
    out: dict[str, dict[str, dict]] = {}
    for axis in mesh.axis_names:
        if mesh.shape[axis] < 2:
            continue
        out[axis] = {
            op: collective_bandwidth(
                op, mesh, payload_elems, iterations, axis=axis
            )
            for op in ops
        }
    return out


def run_comm_bench(
    log_dir: str | os.PathLike = "training_logs",
    job_id: str | None = None,
    iterations: int = 1000,
    payload_elems: int = DEFAULT_PAYLOAD_ELEMS,
) -> dict:
    """Full microbenchmark: ping-pong CSV (reference-compatible rows) +
    collective bandwidth sweep.  Returns a summary dict."""
    from ddl_tpu.train.trainer import resolve_job_id

    job_id = job_id or resolve_job_id()
    os.makedirs(log_dir, exist_ok=True)

    summary: dict = {"job_id": job_id, "devices": len(jax.devices())}
    if len(jax.devices()) >= 2:
        pp = ping_pong(iterations=iterations, payload_elems=payload_elems)
        with open(os.path.join(log_dir, "communication_time.csv"), "a") as f:
            for i, t in enumerate(pp.times_ms):
                f.write(f"{job_id},{i},{t}\n")
        summary["ping_pong_mean_ms"] = pp.mean_ms
        summary["ping_pong_one_way_gbps"] = pp.one_way_gbps
        for op in COLLECTIVE_OPS:
            r = collective_bandwidth(op, payload_elems=payload_elems)
            summary[f"{op}_gbps"] = r["algbw_gbps"]
            summary[f"{op}_ms"] = r["mean_ms"]
    else:
        # Single-chip: report HBM-loopback psum as a degenerate datapoint.
        r = collective_bandwidth(
            "psum", mesh=_ring_mesh(1), payload_elems=payload_elems
        )
        summary["psum_ms"] = r["mean_ms"]
    return summary


if __name__ == "__main__":
    import argparse
    import json

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iterations", type=int, default=None,
                    help="samples per measurement (default: 1000 flat, "
                    "100 per op/axis with --mesh)")
    ap.add_argument("--payload-elems", type=int, default=DEFAULT_PAYLOAD_ELEMS)
    ap.add_argument(
        "--mesh", default=None,
        help="per-axis sweep over a named mesh, e.g. 'data=2,seq=2,model=2' "
        "(axis sizes must multiply to <= device count); omitted = flat "
        "2-device ping-pong + single-axis collective sweep",
    )
    ap.add_argument("--cpu-devices", type=int, default=0,
                    help="simulate N CPU devices (dev/test)")
    args = ap.parse_args()
    if args.cpu_devices:
        from ddl_tpu.launch import force_cpu_devices

        force_cpu_devices(args.cpu_devices)

    if args.mesh:
        axes = dict(kv.split("=") for kv in args.mesh.split(","))
        names, sizes = tuple(axes), tuple(int(v) for v in axes.values())
        need = int(np.prod(sizes))
        have = len(jax.devices())
        if need > have:
            ap.error(
                f"--mesh {args.mesh} needs {need} devices, have {have} "
                "(axis sizes must multiply to <= device count)"
            )
        mesh = Mesh(np.array(jax.devices()[:need]).reshape(sizes), names)
        sweep = axis_bandwidth_sweep(
            mesh, payload_elems=args.payload_elems,
            iterations=args.iterations or 100,
        )
        print(json.dumps(sweep, indent=2))
    else:
        print(json.dumps(run_comm_bench(
            iterations=args.iterations or 1000,
            payload_elems=args.payload_elems,
        ), indent=2))
