"""Quality bound for the int8 serving path: what does quantization cost?

The int8 levers (``ops/quant.py``) halve decode HBM traffic; this tool
pins what they cost in output quality, on REAL trained weights (any
``train_lm.py`` snapshot + its corpus):

1. **Held-out ppl delta** (weight-only int8): teacher-forced CE over the
   corpus's held-out tail through the standard eval path, f32/bf16
   params vs ``quantize_lm_params`` — the weight-quant quality bound.
2. **Greedy token agreement** (KV + weight int8): greedy generations
   from held-out prompts, bf16 generator vs ``kv`` vs ``kv+w`` —
   position-wise token match rate, plus the first-divergence histogram.
   (Greedy decode amplifies near-ties; agreement is the *strict* bound —
   a disagreement is usually an equally-likely token, not an error.)

Prints one JSON line per mode.

    python -m ddl_tpu.bench.decode_quality --checkpoint-dir ck --step N \
        --corpus corpus.npy --d-model 512 --layers 8
"""

from __future__ import annotations

import argparse
import json

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--checkpoint-dir", required=True)
    ap.add_argument("--job-id", default="lm")
    ap.add_argument("--step", type=int, required=True)
    ap.add_argument("--corpus", required=True, help="token .npy (byte-level)")
    ap.add_argument("--d-model", type=int, default=512)
    ap.add_argument("--layers", type=int, default=8)
    ap.add_argument("--heads", type=int, default=8)
    ap.add_argument("--kv-heads", type=int, default=0)
    ap.add_argument("--attn-window", type=int, default=0)
    ap.add_argument("--vocab", type=int, default=256)
    ap.add_argument("--seq-len", type=int, default=256,
                    help="eval window length (must match training windows)")
    ap.add_argument("--eval-frac", type=float, default=0.05)
    ap.add_argument("--eval-batches", type=int, default=8)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--prompt-len", type=int, default=64)
    ap.add_argument("--max-new", type=int, default=128)
    ap.add_argument("--gen-batches", type=int, default=4)
    ap.add_argument("--cpu-devices", type=int, default=0)
    args = ap.parse_args()

    if args.cpu_devices:
        from ddl_tpu.launch import force_cpu_devices

        force_cpu_devices(args.cpu_devices)
    import jax
    import jax.numpy as jnp
    import optax

    from ddl_tpu.checkpoint import load_params
    from ddl_tpu.data.lm_corpus import TokenCorpus
    from ddl_tpu.infer import make_lm_generator
    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.ops.quant import quantize_lm_params
    from ddl_tpu.parallel.sharding import LMMeshSpec, build_lm_mesh
    from ddl_tpu.train.lm_steps import LMTrainState, make_lm_step_fns
    from ddl_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()
    cfg = LMConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=args.heads,
        n_kv_heads=args.kv_heads,
        attn_window=args.attn_window,
        head_dim=args.d_model // args.heads,
        d_ff=4 * args.d_model,
        compute_dtype=(
            "bfloat16" if jax.default_backend() != "cpu" else "float32"
        ),
        remat=False,
    )
    spec = LMMeshSpec()
    mesh = build_lm_mesh(spec)
    # params-only restore: the skeleton comes from the snapshot's own
    # metadata, so any optimizer chain/schedule the training run used is
    # irrelevant here
    # vocab_size resolves a format-less snapshot's lm_head orientation
    params = load_params(
        args.checkpoint_dir, args.job_id, args.step, vocab_size=cfg.vocab_size
    )
    from ddl_tpu.parallel.lm_pipeline import saved_pipe_stages

    if saved_pipe_stages(params) > 1:
        raise SystemExit(
            "this snapshot is in the pipeline-parallel layout; "
            "decode_quality restores params only and does not "
            "restructure stages — resume it once with --pipe 1 (or "
            "decode via examples/generate_lm.py, which converts the "
            "layout) and point this tool at the re-saved snapshot"
        )
    qparams = quantize_lm_params(params)

    # --- held-out ppl: exact vs weight-only int8 -------------------------
    corpus = TokenCorpus(args.corpus, args.seq_len)
    _, eval_view = corpus.split(args.eval_frac)
    fns = make_lm_step_fns(
        cfg, spec, optax.adam(1e-3), jax.random.key(0), args.batch,
        args.seq_len,
    )
    n_eval = min(args.eval_batches, len(eval_view) // args.batch)
    if n_eval < 1:
        raise SystemExit(
            f"held-out split has {len(eval_view)} windows < one batch of "
            f"{args.batch}; grow --eval-frac or shrink --batch"
        )

    def heldout_ce(p) -> float:
        # evaluate only reads .params; a placeholder opt_state suffices
        st = LMTrainState(
            step=jnp.zeros((), jnp.int32), params=p, opt_state=()
        )
        ces = []
        for bi in range(n_eval):
            idx = range(bi * args.batch, (bi + 1) * args.batch)
            inp = np.stack([eval_view[i][0] for i in idx])
            tgt = np.stack([eval_view[i][1] for i in idx])
            m = fns.evaluate(st, jnp.asarray(inp), jnp.asarray(tgt))
            ces.append(float(m["ce"]))
        return float(np.mean(ces))

    ce_ref = heldout_ce(params)
    ce_q = heldout_ce(qparams)
    print(json.dumps({
        "metric": "heldout_ppl",
        "exact": round(float(np.exp(ce_ref)), 4),
        "int8_weights": round(float(np.exp(ce_q)), 4),
        "ppl_delta_pct": round(
            100 * (np.exp(ce_q) / np.exp(ce_ref) - 1), 3
        ),
        "eval_tokens": n_eval * args.batch * args.seq_len,
    }), flush=True)

    # --- greedy agreement: bf16 vs kv vs kv+w ----------------------------
    gen_exact = make_lm_generator(
        cfg, spec, prompt_len=args.prompt_len, max_new=args.max_new,
        batch=args.batch,
    )
    gen_kvq = make_lm_generator(
        cfg, spec, prompt_len=args.prompt_len, max_new=args.max_new,
        batch=args.batch, kv_quant=True,
    )
    gens = {
        "none": (gen_exact, params),
        "kv": (gen_kvq, params),
        # weight quant needs no generator flag — same compiled program,
        # int8 tree (QDense sniffs the scales)
        "kv+w": (gen_kvq, qparams),
    }
    outs = {k: [] for k in gens}
    gen_batches = min(args.gen_batches, len(eval_view) // args.batch)
    for bi in range(gen_batches):
        idx = range(bi * args.batch, (bi + 1) * args.batch)
        prompts = jnp.asarray(
            np.stack([eval_view[i][0][: args.prompt_len] for i in idx]),
            jnp.int32,
        )
        for k, (g, p) in gens.items():
            outs[k].append(np.asarray(g(p, prompts)))
    ref = np.concatenate(outs["none"])
    for k in ("kv", "kv+w"):
        got = np.concatenate(outs[k])
        match = (got == ref).mean()
        # first divergence per sequence (max_new = fully agreed)
        div = np.where(
            (got != ref).any(1),
            (got != ref).argmax(1),
            args.max_new,
        )
        print(json.dumps({
            "metric": "greedy_agreement",
            "quant": k,
            "token_match_rate": round(float(match), 4),
            "sequences": int(ref.shape[0]),
            "max_new": args.max_new,
            "median_first_divergence": int(np.median(div)),
            "fully_agreed_frac": round(float((div == args.max_new).mean()), 4),
        }), flush=True)


if __name__ == "__main__":
    main()
