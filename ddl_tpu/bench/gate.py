"""``ddl_tpu bench`` — the headline perf gate and the op-digest renderer.

Two jobs, both born from round 6's "the headline can never silently
regress again" rule:

* **MFU / steps-per-sec regression gate** (``ddl_tpu bench
  --fail-mfu-drop F [--fail-slowdown F]``): compares a headline bench
  result (run in-process on the chip, or read from a stored JSON line
  via ``--result``) against the ``headline`` block stored in
  ``BASELINE.json`` and exits nonzero when steps/sec or MFU dropped by
  more than the given fraction — the bench-side sibling of ``obs diff
  --fail-slowdown``.  ``--update-baseline`` stores an intentional new
  headline.

* **Digest renderer** (``ddl_tpu bench digest <trace_dir|latest>``):
  renders the ``bench/xprof.op_digest`` top-N per-op-category table for
  any captured trace — the ROADMAP's "open every perf PR with a digest"
  rule as one command instead of a Python one-liner.  ``latest``
  resolves the newest ``*.xplane.pb`` under the usual capture roots
  (``DDL_OBS_PROFILE_DIR``, ``<log dir>/xprof``, and the
  ``dn_prof_*``/``lm_prof_*``/``decode_prof_*`` temp dirs the profile
  benches write).  The digest also prints a per-device
  **optimizer-state HBM** table (rule-table-derived Adam moment bytes
  per family, replicated vs ZeRO at ``--opt-hbm-dp``) — the capacity
  axis a device-time trace cannot show — and the modeled
  **pipeline-schedule bubble** table (gpipe / 1f1b / interleaved / zb
  idle units at ``--sched-pipe``/``--sched-microbatches``,
  ``obs/schedule_model.py``) — the schedule axis the per-op digest
  cannot attribute — and the per-program **compiled-collective** table
  from the committed ``HLO_BASELINE.json`` (``lint --hlo``): the
  collective counts and payload bytes GSPMD actually scheduled for
  every probe program, the communication axis neither estimate covers.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
from pathlib import Path

__all__ = ["main"]

_HEADLINE_METRIC = "densenet121_train_steps_per_sec_bs30_1chip"


# ---------------------------------------------------------------------------
# digest
# ---------------------------------------------------------------------------


def _latest_trace_dir() -> str | None:
    """Newest ``*.xplane.pb`` under the known capture roots; returns its
    directory (op_digest globs recursively from there)."""
    roots: list[str] = []
    env_dir = os.environ.get("DDL_OBS_PROFILE_DIR")
    if env_dir:
        roots.append(env_dir)
    log_dir = os.environ.get("DDL_LOG_DIR", "training_logs")
    roots.extend([os.path.join(log_dir, "xprof"), "xprof"])
    tmp = tempfile.gettempdir()
    for prefix in ("dn_prof_", "lm_prof_", "decode_prof_"):
        roots.extend(glob.glob(os.path.join(tmp, prefix + "*")))
    newest: tuple[float, str] | None = None
    for root in roots:
        for p in glob.glob(
            os.path.join(root, "**", "*.xplane.pb"), recursive=True
        ):
            m = os.path.getmtime(p)
            if newest is None or m > newest[0]:
                newest = (m, os.path.dirname(p))
    return newest[1] if newest else None


class _AxisShape:
    """Minimal mesh stand-in for the HBM accounting: the rules engine
    only reads ``.shape`` (axis sizes), so estimates need no devices."""

    def __init__(self, **axes: int) -> None:
        self.shape = dict(axes)


def opt_hbm_rows(
    dp: int = 8, tp: int = 1, families: tuple[str, ...] | None = None
) -> list[dict]:
    """Per-family per-device optimizer-state HBM estimates from the
    partition-rule tables (``parallel/rules.optimizer_hbm_bytes``):
    Adam moments, replicated-over-data vs ZeRO-sharded at ``dp``.
    Abstract shapes only (eval_shape) — runs anywhere, no chip.
    ``families`` restricts which model families are built (keys
    'cnn'/'lm'/'vit'; None = all) — each row's ``family`` field starts
    with its key."""
    import jax

    from ddl_tpu.parallel import rules as prules

    mesh = _AxisShape(data=dp, model=tp, expert=1, seq=1, pipe=1)
    rows: list[dict] = []

    def wanted(key: str) -> bool:
        return families is None or key in families

    def add(family, table, abs_params):
        est = prules.optimizer_hbm_bytes(table, abs_params, mesh)
        rows.append({"family": family, **est})

    if wanted("cnn"):
        from ddl_tpu.config import ModelConfig
        from ddl_tpu.models import build_stages
        from ddl_tpu.models.densenet import init_stages

        stages = build_stages(ModelConfig(), num_stages=1)
        cnn_params = jax.eval_shape(
            lambda r: init_stages(stages, r, 224)[0], jax.random.key(0)
        )
        add("cnn (densenet121)", prules.cnn_rules(), cnn_params)

    if wanted("lm"):
        import flax.linen as nn
        import jax.numpy as jnp

        from ddl_tpu.models.transformer import LMConfig, TransformerLM

        lm_cfg = LMConfig()
        lm_params = nn.meta.unbox(jax.eval_shape(
            lambda r: TransformerLM(lm_cfg, None).init(
                r, jnp.zeros((1, 8), jnp.int32)
            )["params"],
            jax.random.key(0),
        ))
        add("lm (default cfg)", prules.lm_rules(lm_cfg.fsdp), lm_params)

    if wanted("vit"):
        import flax.linen as nn
        import jax.numpy as jnp

        from ddl_tpu.models.vit import ViT, ViTConfig

        vit_cfg = ViTConfig()
        vit_params = nn.meta.unbox(jax.eval_shape(
            lambda r: ViT(vit_cfg).init(
                r, jnp.zeros((1, vit_cfg.image_size, vit_cfg.image_size, 3),
                             jnp.float32)
            )["params"],
            jax.random.key(0),
        ))
        add("vit (default cfg)", prules.vit_rules(vit_cfg.fsdp), vit_params)
    return rows


def _print_opt_hbm(rows: list[dict]) -> None:
    if not rows:
        return
    dp = rows[0]["dp"]
    print(f"# optimizer-state HBM per device (Adam moments, rule-table "
          f"estimate, ZeRO dp={dp})")
    print(f"  {'family':20s} {'replicated':>12s} {'zero':>12s} "
          f"{'saving':>8s}  sharded-leaves")
    for r in rows:
        rep, z = r["replicated_bytes"], r["zero_bytes"]
        saving = 1.0 - z / rep if rep else 0.0
        print(f"  {r['family']:20s} {rep / 2**20:10.1f}MB {z / 2**20:10.1f}MB "
              f"{100 * saving:7.1f}%  {r['zero_sharded_leaves']}/{r['leaves']}")


def _print_schedule_table(rows: list[dict]) -> None:
    if not rows:
        return
    live = [r for r in rows if "skipped" not in r]
    if not live:
        return
    p, m = live[0]["pipe"], live[0]["microbatches"]
    print(f"# modeled pipeline-schedule bubble (pipe={p}, microbatches={m}, "
          "t_F=t_B=t_W=1 unit; obs/schedule_model.py)")
    print(f"  {'schedule':18s} {'makespan':>10s} {'idle':>10s} "
          f"{'bubble':>8s}  per-stage idle")
    for r in rows:
        if "skipped" in r:
            print(f"  {r['schedule']:18s} skipped: {r['skipped']}")
            continue
        label = r["schedule"] + (
            f" (V={r['virtual']})" if r["virtual"] > 1 else ""
        )
        idles = "/".join(f"{st['idle']:g}" for st in r["per_stage"])
        print(f"  {label:18s} {r['makespan']:>10g} {r['idle_units']:>10g} "
              f"{r['bubble_fraction']:>7.1%}  {idles}")


def _hlo_collective_rows() -> list[dict]:
    """Per-program collective summary from the committed compiled-IR
    baseline (HLO_BASELINE.json, `lint --hlo`) — the communication the
    compiler actually scheduled, not an estimate."""
    path = Path(__file__).resolve().parents[2] / "HLO_BASELINE.json"
    if not path.exists():
        return []
    try:
        programs = json.loads(path.read_text()).get("programs", {})
    except (OSError, ValueError):
        return []
    rows = []
    for name, data in sorted(programs.items()):
        coll = data.get("collectives", {})
        rows.append({
            "program": name,
            "level": data.get("level", "?"),
            "count": sum(e["count"] for e in coll.values()),
            "bytes": sum(e["bytes"] for e in coll.values()),
            "collectives": {
                k: [v["count"], v["bytes"]] for k, v in sorted(coll.items())
            },
        })
    return rows


def _print_hlo_collectives(rows: list[dict]) -> None:
    if not rows:
        return
    print("# compiled-program collectives (HLO_BASELINE.json, "
          "`lint --hlo`; stablehlo-level rows carry counts only)")
    print(f"  {'program':16s} {'level':>9s} {'colls':>6s} {'bytes':>10s}  "
          "breakdown (kind@axes count/bytes)")
    for r in rows:
        parts = " ".join(
            f"{k} {c}/{_fmt_bytes(b)}"
            for k, (c, b) in r["collectives"].items()
        )
        print(f"  {r['program']:16s} {r['level']:>9s} {r['count']:>6d} "
              f"{_fmt_bytes(r['bytes']):>10s}  {parts}")


def _fmt_bytes(n: int) -> str:
    if n >= 2**20:
        return f"{n / 2**20:.1f}MB"
    if n >= 2**10:
        return f"{n / 2**10:.1f}KB"
    return str(n)


def _digest(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="ddl_tpu bench digest",
        description="Render the per-op-category device-time digest of a "
        "captured jax.profiler trace (bench/xprof.op_digest).",
    )
    ap.add_argument(
        "trace", help="trace directory, or 'latest' for the newest "
        "capture under the standard roots",
    )
    ap.add_argument("--top", type=int, default=5,
                    help="categories to list (default 5)")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument(
        "--opt-hbm-dp", type=int, default=8, metavar="DP",
        help="data-axis size for the optimizer-state HBM column "
        "(default 8; 0 disables the section)",
    )
    ap.add_argument(
        "--sched-pipe", type=int, default=4, metavar="P",
        help="pipeline stages for the modeled schedule-bubble table "
        "(default 4; 0 disables the section)",
    )
    ap.add_argument(
        "--sched-microbatches", type=int, default=16, metavar="M",
        help="microbatches for the schedule-bubble table (default 16)",
    )
    ap.add_argument(
        "--sched-virtual", type=int, default=2, metavar="V",
        help="virtual stages for the table's interleaved row (default 2)",
    )
    args = ap.parse_args(argv)

    trace_dir = args.trace
    if trace_dir == "latest":
        trace_dir = _latest_trace_dir()
        if trace_dir is None:
            print("bench digest: no *.xplane.pb found under the capture "
                  "roots (DDL_OBS_PROFILE_DIR, <log dir>/xprof, temp "
                  "dn_prof_*/lm_prof_*/decode_prof_*)", file=sys.stderr)
            return 2
    from ddl_tpu.bench.xprof import op_digest

    try:
        dig = op_digest(trace_dir, top=args.top)
    except FileNotFoundError as e:
        print(f"bench digest: {e}", file=sys.stderr)
        return 2
    hbm_rows = opt_hbm_rows(args.opt_hbm_dp) if args.opt_hbm_dp > 0 else []
    sched_rows = []
    if args.sched_pipe > 0:
        from ddl_tpu.obs.schedule_model import schedule_table

        sched_rows = schedule_table(
            args.sched_pipe, args.sched_microbatches, args.sched_virtual
        )
    hlo_rows = _hlo_collective_rows()
    if args.as_json:
        print(json.dumps(
            {"trace_dir": trace_dir, **dig, "opt_hbm": hbm_rows,
             "schedules": sched_rows, "hlo_collectives": hlo_rows}
        ))
        return 0
    print(f"# digest: {trace_dir}")
    print(f"# total sync-op time: {dig['total_ms']:.3f} ms "
          f"(module {dig['module_ms']:.3f} ms)")
    total = dig["total_ms"] or 1.0
    for cat, ms in dig["ops"].items():
        print(f"  {cat:44s} {ms:10.3f} ms  ({100 * ms / total:5.1f}%)")
    if dig.get("top_op"):
        print(f"# top op: {dig['top_op']}")
    _print_opt_hbm(hbm_rows)
    _print_schedule_table(sched_rows)
    _print_hlo_collectives(hlo_rows)
    return 0


# ---------------------------------------------------------------------------
# gate
# ---------------------------------------------------------------------------


def _load_result(path: str | None) -> dict:
    """A headline bench result: the last JSON line of ``--result`` (file
    or '-') — or a fresh in-process run of the headline bench (real
    chip)."""
    if path is None:
        import io
        from contextlib import redirect_stdout

        import bench as headline_bench

        buf = io.StringIO()
        with redirect_stdout(buf):
            headline_bench.main()
        text = buf.getvalue()
        print(text, end="")
    elif path == "-":
        text = sys.stdin.read()
    else:
        with open(path) as fh:
            text = fh.read()
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise ValueError("no JSON result line found")


def _gate(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(
        prog="ddl_tpu bench",
        description="Headline-bench regression gate: compare steps/sec "
        "and MFU against the headline block in BASELINE.json.",
    )
    ap.add_argument(
        "--result", default=None,
        help="stored bench JSON line (file or '-'); default runs the "
        "headline bench in-process (needs the real chip)",
    )
    ap.add_argument("--baseline", default="BASELINE.json")
    ap.add_argument(
        "--fail-mfu-drop", type=float, default=None, metavar="F",
        help="exit 1 when MFU dropped by more than fraction F",
    )
    ap.add_argument(
        "--fail-slowdown", type=float, default=None, metavar="F",
        help="exit 1 when steps/sec dropped by more than fraction F",
    )
    ap.add_argument(
        "--update-baseline", action="store_true",
        help="store this result as the new headline in the baseline file",
    )
    args = ap.parse_args(argv)
    if (
        args.fail_mfu_drop is None
        and args.fail_slowdown is None
        and not args.update_baseline
    ):
        ap.error("nothing to do: pass --fail-mfu-drop/--fail-slowdown "
                 "and/or --update-baseline (digest: `bench digest ...`)")

    try:
        result = _load_result(args.result)
    except (OSError, ValueError, ImportError) as e:
        # ImportError: the in-process path imports the repo-root bench.py,
        # which needs cwd=/root/repo like every -m entry point
        print(f"bench gate: cannot load result: {e}", file=sys.stderr)
        return 2
    if result.get("metric") not in (None, _HEADLINE_METRIC):
        print(f"bench gate: unexpected metric {result.get('metric')!r}",
              file=sys.stderr)
        return 2

    with open(args.baseline) as fh:
        baseline = json.load(fh)

    if args.update_baseline:
        prev = baseline.get("headline") or {}
        baseline["headline"] = {
            "metric": result.get("metric", _HEADLINE_METRIC),
            "steps_per_sec": result["value"],
            "mfu": result.get("mfu"),
            "tflops_per_step": result.get("tflops_per_step"),
            # provenance survives updates (how/where the number was taken)
            "source": prev.get(
                "source", "ddl_tpu bench --update-baseline"
            ),
        }
        if result.get("mfu") is None:
            # a null stored MFU makes every future --fail-mfu-drop run
            # FAIL loudly (missing metrics gate closed, below) — say so
            print(
                "bench gate: WARNING — result carries no 'mfu' field "
                "(unknown chip peak?); storing null, which future "
                "--fail-mfu-drop runs will refuse to gate against",
                file=sys.stderr,
            )
        tmp = args.baseline + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(baseline, fh, indent=2)
            fh.write("\n")
        os.replace(tmp, args.baseline)
        print(f"bench gate: baseline headline updated -> "
              f"{baseline['headline']}")
        if args.fail_mfu_drop is None and args.fail_slowdown is None:
            return 0

    head = baseline.get("headline")
    if not head:
        print(f"bench gate: {args.baseline} has no 'headline' block — "
              "store one with --update-baseline", file=sys.stderr)
        return 2

    failures = []
    rows = []

    def check(name, new, old, frac):
        if old in (None, 0) or new is None:
            rows.append((name, new, old, None))
            if frac is not None:
                # fail CLOSED: a requested gate with a missing metric is
                # a failure, not a silent pass — otherwise a result
                # without an 'mfu' field (unknown chip peak) waves every
                # MFU regression through
                failures.append(
                    f"cannot gate {name}: metric missing "
                    f"({'baseline' if old in (None, 0) else 'result'} "
                    f"has no usable value; baseline={old!r}, new={new!r})"
                )
            return
        drop = 1.0 - float(new) / float(old)
        rows.append((name, new, old, drop))
        if frac is not None and drop > frac:
            failures.append(
                f"{name} dropped {100 * drop:.1f}% "
                f"({old} -> {new}, limit {100 * frac:.0f}%)"
            )

    check("steps_per_sec", result.get("value"),
          head.get("steps_per_sec"), args.fail_slowdown)
    check("mfu", result.get("mfu"), head.get("mfu"), args.fail_mfu_drop)

    print("== bench gate (vs baseline headline) ==")
    for name, new, old, drop in rows:
        d = "n/a" if drop is None else f"{-100 * drop:+.1f}%"
        print(f"  {name:14s} {old} -> {new}  ({d})")
    if failures:
        for f in failures:
            print(f"FAIL: {f}")
        return 1
    gates = [
        n for n, f in (("slowdown", args.fail_slowdown),
                       ("mfu-drop", args.fail_mfu_drop)) if f is not None
    ]
    print(f"OK (gates: {', '.join(gates) if gates else 'none'})")
    return 0


def main(argv: list[str] | None = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if argv and argv[0] == "digest":
        return _digest(argv[1:])
    return _gate(argv)


if __name__ == "__main__":
    raise SystemExit(main())
