"""Per-op device-time breakdown of autoregressive decode (bf16 or int8).

The serving bench (``bench/decode.py``) gives rates; this gives the
*why* — the same xprof evidence channel as ``profile_densenet`` /
``profile_lm``, pointed at the generator's one-program prefill + scan.
Built to answer the int8 question: does the int8→bf16 convert fuse into
the attention/matmul reads (HBM win) or materialise converted copies
(win lost)?

    python -m ddl_tpu.bench.profile_decode --batch 32 --kv-heads 4 \
        --attn-window 1024 --quant kv
"""

from __future__ import annotations

import argparse
import tempfile


def capture(args, trace_dir: str) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddl_tpu.infer.decode import make_lm_generator
    from ddl_tpu.models.transformer import LMConfig, TransformerLM
    from ddl_tpu.utils.compile_cache import enable_compile_cache
    from ddl_tpu.utils.timing import fence

    enable_compile_cache()
    cfg = LMConfig(
        vocab_size=args.vocab,
        d_model=args.d_model,
        n_layers=args.layers,
        n_heads=args.d_model // 64,
        n_kv_heads=args.kv_heads,
        attn_window=args.attn_window,
        head_dim=64,
        d_ff=4 * args.d_model,
        compute_dtype="bfloat16",
        remat=False,
        flash="auto",
    )
    import flax.linen as nn

    params = nn.meta.unbox(
        TransformerLM(cfg, None).init(
            jax.random.key(0), jnp.zeros((args.batch, 8), jnp.int32)
        )["params"]
    )
    if args.quant == "kv+w":
        from ddl_tpu.ops.quant import quantize_lm_params

        params = quantize_lm_params(params)
    gen = make_lm_generator(
        cfg, prompt_len=args.prompt, max_new=args.new, batch=args.batch,
        kv_quant=args.quant in ("kv", "kv+w"),
    )
    rng = np.random.default_rng(0)
    prompt = jnp.asarray(
        rng.integers(0, args.vocab, (args.batch, args.prompt)), jnp.int32
    )
    fence(gen(params, prompt))  # compile + warm
    jax.profiler.start_trace(trace_dir)
    for _ in range(args.steps):
        out = gen(params, prompt)
    fence(out)
    jax.profiler.stop_trace()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--prompt", type=int, default=512)
    ap.add_argument("--new", type=int, default=256,
                    help="decode tokens per profiled call")
    ap.add_argument("--d-model", type=int, default=768)
    ap.add_argument("--layers", type=int, default=12)
    ap.add_argument("--vocab", type=int, default=50304)
    ap.add_argument("--kv-heads", type=int, default=0)
    ap.add_argument("--attn-window", type=int, default=0)
    ap.add_argument("--quant", default="none", choices=["none", "kv", "kv+w"])
    ap.add_argument("--steps", type=int, default=3,
                    help="profiled generate() calls")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--trace-dir", default=None)
    args = ap.parse_args()

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="dec_prof_")
    if not args.trace_dir:
        capture(args, trace_dir)

    from ddl_tpu.bench.xprof import print_report

    print_report(
        trace_dir, args.steps, args.top,
        header=(f", decode batch {args.batch}, prompt {args.prompt}, "
                f"new {args.new}, quant {args.quant}"),
    )


if __name__ == "__main__":
    main()
