"""Per-op device-time breakdown of the LM train step (dense or MoE MLP).

Same evidence channel as ``profile_densenet`` (PERF.md round 4), pointed
at the transformer family: where does an LM/MoE step's device time go —
matmul fusions, the Pallas attention custom call, MoE dispatch
sort/gather or one-hot einsums, collectives, optimizer?

Usage::

    python -m ddl_tpu.bench.profile_lm [--batch 16] [--experts 8] \
        [--d-ff 1536] [--flash] [--no-remat]

Prints a per-category table, the top-N ops, and one JSON line.
"""

from __future__ import annotations

import argparse
import tempfile


def capture(args, trace_dir: str) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_steps import make_lm_step_fns
    from ddl_tpu.utils.compile_cache import enable_compile_cache
    from ddl_tpu.utils.timing import fence

    enable_compile_cache()
    cfg = LMConfig(
        vocab_size=50304,
        d_model=768,
        n_layers=12,
        n_heads=12,
        n_kv_heads=args.kv_heads,
        head_dim=64,
        d_ff=args.d_ff,
        num_experts=args.experts,
        compute_dtype="bfloat16",
        flash=bool(args.flash),
        remat=not args.no_remat,
        ce_chunk=args.ce_chunk,
        ce_vocab_chunk=args.ce_vocab_chunk,
    )
    import optax

    fns = make_lm_step_fns(
        cfg, LMMeshSpec(), optax.adamw(3e-4), jax.random.key(0),
        args.batch, args.seq_len,
    )
    state = fns.init_state()
    rng = np.random.default_rng(0)
    toks = jnp.asarray(
        rng.integers(0, cfg.vocab_size, (args.batch, args.seq_len + 1)),
        jnp.int32,
    )
    inp, tgt = toks[:, :-1], toks[:, 1:]
    for _ in range(3):  # compile + steady
        state, metrics = fns.train(state, inp, tgt)
    fence(metrics["loss"])

    jax.profiler.start_trace(trace_dir)
    for _ in range(args.steps):
        state, metrics = fns.train(state, inp, tgt)
    fence(metrics["loss"])
    jax.profiler.stop_trace()


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--seq-len", type=int, default=1024)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--experts", type=int, default=0)
    ap.add_argument("--d-ff", type=int, default=3072)
    ap.add_argument("--kv-heads", type=int, default=0)
    ap.add_argument("--ce-chunk", type=int, default=0)
    ap.add_argument("--ce-vocab-chunk", type=int, default=0)
    ap.add_argument("--flash", action="store_true")
    ap.add_argument("--no-remat", action="store_true")
    ap.add_argument("--trace-dir", default=None,
                    help="reuse an existing trace instead of capturing")
    args = ap.parse_args()

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="lm_prof_")
    if not args.trace_dir:
        capture(args, trace_dir)

    from ddl_tpu.bench.xprof import print_report

    print_report(
        trace_dir, args.steps, args.top,
        header=(f", batch {args.batch}, T {args.seq_len}, "
                f"experts {args.experts}"),
    )


if __name__ == "__main__":
    main()
