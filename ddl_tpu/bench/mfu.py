"""FLOPs accounting for bench rows: exact per-step FLOPs and MFU.

The reference publishes only wall-clock epoch times (``ipynb/main.ipynb``
cell 3) — a number that says nothing about how much of the accelerator is
used.  Here every bench row can also report

* ``tflops``: executed FLOPs per step from XLA's own cost analysis of the
  compiled program (``jit(...).lower().compile().cost_analysis()`` — the
  same machinery ``tools/split_explorer.py`` uses for stage balance), and
* ``mfu``: executed FLOP/s divided by the chip's peak dense bf16 FLOP/s.

Note on remat: cost analysis counts the FLOPs the program *executes*, so
with activation rematerialisation enabled the ratio is hardware-FLOPs
utilization (HFU) — it includes the recompute.  For rows with remat off
(the single-chip headline benches) executed == model FLOPs and the ratio
is the classic MFU.
"""

from __future__ import annotations

import jax

__all__ = [
    "device_peak_flops",
    "compiled_step_flops",
    "flash_attention_train_flops",
    "fused_dense_block_train_flops",
    "chunked_ce_extra_flops",
    "mfu",
    "append_mfu",
    "PEAK_BF16_FLOPS",
]

# jax device_kind prefix -> peak dense bf16 FLOP/s (public spec sheets)
PEAK_BF16_FLOPS = {
    "TPU v6": 918e12,  # v6e / Trillium
    "TPU v5p": 459e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5e": 197e12,
    "TPU v5": 197e12,  # bare "TPU v5" device_kind strings are v5e in practice
    "TPU v4": 275e12,
    "TPU v3": 123e12,
    "TPU v2": 46e12,
}


def device_peak_flops(device=None) -> float | None:
    """Peak dense bf16 FLOP/s for ``device`` (default: first device), or
    None when unknown (CPU, unlisted kind) — callers then omit the MFU
    column rather than print a wrong one."""
    d = device if device is not None else jax.devices()[0]
    kind = str(getattr(d, "device_kind", "")).strip()
    # longest prefix wins so "TPU v5p" does not fall through to "TPU v5"
    for k in sorted(PEAK_BF16_FLOPS, key=len, reverse=True):
        if kind.lower().startswith(k.lower()):
            return PEAK_BF16_FLOPS[k]
    return None


def compiled_step_flops(fn, *args) -> float:
    """Exact executed FLOPs of one invocation of ``fn(*args)``.

    ``fn`` may be a jitted function or a plain callable (jitted here).
    Returns NaN when the backend's cost analysis is unavailable."""
    try:
        lowered = (
            fn.lower(*args) if hasattr(fn, "lower") else jax.jit(fn).lower(*args)
        )
        cost = lowered.compile().cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0]
        return float(cost["flops"])
    except Exception:
        return float("nan")


def flash_attention_train_flops(
    batch: int,
    n_heads: int,
    seq_len: int,
    head_dim: int,
    n_layers: int,
    window: int = 0,
    remat: bool = False,
    accounting: str = "model",
) -> float:
    """Analytic attention-core FLOPs per train step for the Pallas kernel.

    XLA's cost analysis assigns ZERO FLOPs to a Pallas custom call (probed
    on v5e: an isolated `flash_attention` program reports none, and a
    flash train step's total equals the model's non-attention FLOPs
    exactly), so flash bench rows undercount MFU — increasingly with T.
    This closed form restores the kernel's executed FLOPs, counting only
    the visible (q, k) score pairs — the kernel really skips blocks
    outside the causal/window band via predicated execution, so banded
    rows are credited with banded FLOPs, not full causal ones (round-2's
    windowed-MFU caveat, resolved analytically):

    * visible pairs: causal ``T(T+1)/2``; with a window W, the first W
      rows keep their triangle and the rest see W keys each —
      ``W(W+1)/2 + (T-W)W``.
    * matmuls over those pairs, 2 FLOPs/MAC each.  ``accounting`` picks
      the convention:
      - ``"model"`` (the MFU convention): the theoretical attention
        matmuls only — forward 2 (QK^T, PV) + backward 4 (dV, dP, dQ,
        dK) = 6; implementation recomputes don't count.
      - ``"executed"`` (the HFU convention): what the flash kernels
        actually run — forward 2; dQ kernel 3 (score recompute, dP, dQ);
        dK/dV kernel 4 (score recompute, dV, dP, dK) = 9, +2 when remat
        replays the forward.
      Grouped-query K/V changes none of these (the kernel computes per
      *query* head).
    """
    if accounting not in ("model", "executed"):
        raise ValueError(f"accounting must be 'model' or 'executed', got {accounting!r}")
    if window and window < seq_len:
        pairs = window * (window + 1) / 2 + (seq_len - window) * window
    else:
        pairs = seq_len * (seq_len + 1) / 2
    matmul = 2.0 * batch * n_heads * head_dim * pairs
    if accounting == "model":
        n_matmuls = 6
    else:
        n_matmuls = 11 if remat else 9
    return n_matmuls * matmul * n_layers


def fused_dense_block_train_flops(
    batch: int,
    image_size: int,
    block_config,
    growth_rate: int,
    bn_size: int,
    num_init_features: int,
    fused_blocks,
    accounting: str = "model",
) -> float:
    """Analytic train-step FLOPs of the fused dense-block Pallas kernels
    (``ops/fused_dense_block``) — XLA cost analysis assigns ZERO FLOPs
    to a Pallas custom call (same probe result as the flash kernel), so
    ``dense_block_impl="fused"`` bench rows must add the kernels' work
    back for an honest MFU.  Counts only the blocks in ``fused_blocks``
    (the others run as XLA ops and are already counted), per layer:

    * ``"model"`` (MFU convention): the theoretical matmuls at the TRUE
      input width — forward 1x1 + 3x3, backward dW/dx for each = 3 of
      each; the kernel's zero-padded full-width execution and its
      backward recompute of the forward intermediates are implementation
      overhead and do not count.
    * ``"executed"`` (HFU convention): what the kernels actually run —
      four full-padded-width 1x1 matmuls (forward, backward recompute,
      dW1, dhid) and three nine-tap 3x3 sets (forward, dh2, dW2).

    The train forward's batch-stats pass is ordinary XLA and needs no
    correction."""
    if accounting not in ("model", "executed"):
        raise ValueError(
            f"accounting must be 'model' or 'executed', got {accounting!r}"
        )
    from ddl_tpu.ops.fused_dense_block import block_pad

    bn = bn_size * growth_rate
    f = num_init_features
    hw = image_size // 4  # stem conv /2 + maxpool /2
    total = 0.0
    n_blocks = len(block_config)
    for b, n_layers in enumerate(block_config):
        if b in tuple(fused_blocks):
            s = hw * hw
            _, p_total = block_pad(f, n_layers, growth_rate)
            for i in range(n_layers):
                c_in = f + i * growth_rate
                conv1 = 2.0 * s * (
                    c_in if accounting == "model" else p_total
                ) * bn
                conv2 = 2.0 * s * 9 * bn * growth_rate
                if accounting == "model":
                    total += 3 * conv1 + 3 * conv2
                else:
                    total += 4 * conv1 + 3 * conv2
        f += n_layers * growth_rate
        if b != n_blocks - 1:
            f //= 2
            hw //= 2
    return batch * total


def chunked_ce_extra_flops(
    batch: int,
    seq_len: int,
    d_model: int,
    vocab: int,
    token_chunk: int,
    accounting: str = "model",
) -> float:
    """FLOPs correction for ``ce_chunk`` rows: XLA cost analysis counts a
    ``lax.scan`` body ONCE regardless of trip count, so a chunked head+CE
    loss (``ops/losses.fused_chunked_ce``) is undercounted by a factor of
    ``T/chunk`` on its scan bodies.  Returns the signed delta to add to
    the cost-analysis total so the loss edge is accounted at full T.

    The loss edge is three model matmuls of ``2*B*T*D*V`` each (forward
    head projection, backward dx, backward dW); the ``jax.checkpoint``
    inside the scan body replays the forward, so the *executed* count is
    four.  Cost analysis sees one fwd-scan body plus one bwd-scan body —
    four chunk-sized matmuls — hence ``counted = 4 * matmul / trips``.
    ``accounting`` follows ``flash_attention_train_flops``: "model" (MFU
    rows) targets the three theoretical matmuls — the checkpoint replay is
    implementation overhead — and "executed" (HFU rows) targets all four.
    The delta can be negative at small trip counts under "model" (counted
    replay work that the MFU convention excludes); that is the correct
    correction, not an error.
    """
    if accounting not in ("model", "executed"):
        raise ValueError(
            f"accounting must be 'model' or 'executed', got {accounting!r}"
        )
    from ddl_tpu.ops.losses import effective_chunk

    trips = seq_len // effective_chunk(token_chunk, seq_len)
    matmul = 2.0 * batch * seq_len * d_model * vocab
    target = (3.0 if accounting == "model" else 4.0) * matmul
    counted = 4.0 * matmul / trips
    return target - counted


def vocab_chunked_ce_extra_flops(
    batch: int,
    seq_len: int,
    d_model: int,
    vocab: int,
    vocab_chunk: int,
    accounting: str = "model",
) -> float:
    """FLOPs correction for ``ce_vocab_chunk`` rows (same scan-counted-once
    rule as ``chunked_ce_extra_flops``, over the VOCAB scan of
    ``ops/losses.fused_vocab_chunked_ce``).  The forward scan body holds
    one chunk-sized matmul and the hand-written backward scan body three
    (logits recompute, dx, dW): counted = 4 chunk-sized matmuls; executed
    = 4 full-V matmuls; the "model" target excludes the backward's
    recompute (3 full-V matmuls), matching the MFU convention used for
    the flash kernel and ce_chunk."""
    if accounting not in ("model", "executed"):
        raise ValueError(
            f"accounting must be 'model' or 'executed', got {accounting!r}"
        )
    from ddl_tpu.ops.losses import _vocab_blocks

    vb = _vocab_blocks(vocab, vocab_chunk)
    per_v = 2.0 * batch * seq_len * d_model
    target = (3.0 if accounting == "model" else 4.0) * per_v * vocab
    counted = 4.0 * per_v * vb
    return target - counted


def mfu(flops_per_step: float, step_time_s: float, device=None) -> float | None:
    """Fraction of peak dense bf16 FLOP/s achieved; None when peak unknown."""
    peak = device_peak_flops(device)
    if peak is None or not step_time_s > 0 or not flops_per_step > 0:
        return None
    return flops_per_step / step_time_s / peak


def append_mfu(
    out: dict, fn, step_time_s: float, *args,
    key: str = "mfu", extra_flops: float = 0.0,
) -> dict:
    """Add ``tflops_per_step`` (whenever cost analysis works) and ``key``
    (only when the chip's peak is known) to a bench result dict — the one
    reporting path shared by bench.py / bench.lm / bench.vit.  ``key`` is
    ``"mfu"`` when executed == model FLOPs (no remat) and ``"hfu"``
    otherwise (see module docstring).  ``extra_flops`` adds work cost
    analysis cannot see — Pallas custom calls report zero, so flash rows
    pass ``flash_attention_train_flops``."""
    flops = compiled_step_flops(fn, *args)
    if flops > 0:  # NaN-safe: NaN > 0 is False
        flops += extra_flops
        out["tflops_per_step"] = round(flops / 1e12, 2)
        u = mfu(flops, step_time_s)
        if u is not None:
            out[key] = round(u, 4)
    return out
