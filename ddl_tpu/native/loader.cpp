// Native data-loader core: parallel PNG/JPEG decode + batch collation.
//
// The reference delegates its host-side data path to torch's C++ DataLoader
// workers (`DataLoader(num_workers=2)`, reference single.py:286) and
// torchvision's native `io.read_image` (single.py:59).  This is the
// equivalent for the TPU feed: a persistent pthread pool decodes a whole
// batch of image files straight into one contiguous uint8 NHWC buffer (the
// exact layout the device transfer wants), entirely outside the Python GIL.
// Python binds via ctypes (no pybind11 dependency).
//
// API (C linkage):
//   ddl_pool_init(n_threads)            -> 0 on success
//   ddl_load_batch(paths, n, h, w, out) -> number of images decoded OK;
//        each failed slot is zero-filled and its index reported via errs.
//   ddl_image_size(path, &h, &w)        -> probe dimensions
//   ddl_pool_shutdown()
//
// Build: make -C ddl_tpu/native   (g++ -O3 -shared -fPIC, links png/jpeg/z)

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <condition_variable>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

#include <png.h>
extern "C" {
#include <jpeglib.h>
}

namespace {

// ---------------------------------------------------------------- thread pool
class Pool {
 public:
  explicit Pool(int n) : stop_(false) {
    for (int i = 0; i < n; ++i) {
      workers_.emplace_back([this] {
        for (;;) {
          std::function<void()> job;
          {
            std::unique_lock<std::mutex> lk(mu_);
            cv_.wait(lk, [this] { return stop_ || !jobs_.empty(); });
            if (stop_ && jobs_.empty()) return;
            job = std::move(jobs_.front());
            jobs_.pop();
          }
          job();
        }
      });
    }
  }
  ~Pool() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_.notify_all();
    for (auto& w : workers_) w.join();
  }
  void submit(std::function<void()> f) {
    {
      std::lock_guard<std::mutex> lk(mu_);
      jobs_.push(std::move(f));
    }
    cv_.notify_one();
  }

 private:
  std::vector<std::thread> workers_;
  std::queue<std::function<void()>> jobs_;
  std::mutex mu_;
  std::condition_variable cv_;
  bool stop_;
};

Pool* g_pool = nullptr;

// ---------------------------------------------------------------- PNG decode
// Decodes to RGB8; returns 0 on success. Output must hold h*w*3 bytes and the
// file's dimensions must match (the APTOS set is pre-resized to 224x224).
int decode_png(const char* path, int h, int w, uint8_t* out) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return -1;
  png_byte header[8];
  if (fread(header, 1, 8, fp) != 8 || png_sig_cmp(header, 0, 8)) {
    fclose(fp);
    return -2;
  }
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  png_infop info = png ? png_create_info_struct(png) : nullptr;
  if (!png || !info || setjmp(png_jmpbuf(png))) {
    if (png) png_destroy_read_struct(&png, info ? &info : nullptr, nullptr);
    fclose(fp);
    return -3;
  }
  png_init_io(png, fp);
  png_set_sig_bytes(png, 8);
  png_read_info(png, info);

  png_uint_32 iw = png_get_image_width(png, info);
  png_uint_32 ih = png_get_image_height(png, info);
  int depth = png_get_bit_depth(png, info);
  int color = png_get_color_type(png, info);
  if ((int)iw != w || (int)ih != h) {
    png_destroy_read_struct(&png, &info, nullptr);
    fclose(fp);
    return -4;
  }
  // normalise every variant to 8-bit RGB
  if (color == PNG_COLOR_TYPE_PALETTE) png_set_palette_to_rgb(png);
  if (color == PNG_COLOR_TYPE_GRAY && depth < 8) png_set_expand_gray_1_2_4_to_8(png);
  if (png_get_valid(png, info, PNG_INFO_tRNS)) png_set_tRNS_to_alpha(png);
  if (depth == 16) png_set_strip_16(png);
  if (color == PNG_COLOR_TYPE_GRAY || color == PNG_COLOR_TYPE_GRAY_ALPHA)
    png_set_gray_to_rgb(png);
  png_set_strip_alpha(png);
  png_read_update_info(png, info);

  std::vector<png_bytep> rows(h);
  for (int y = 0; y < h; ++y) rows[y] = out + (size_t)y * w * 3;
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  fclose(fp);
  return 0;
}

// --------------------------------------------------------------- JPEG decode
int decode_jpeg(const char* path, int h, int w, uint8_t* out) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return -1;
  jpeg_decompress_struct cinfo;
  jpeg_error_mgr jerr;
  cinfo.err = jpeg_std_error(&jerr);
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, fp);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    fclose(fp);
    return -2;
  }
  cinfo.out_color_space = JCS_RGB;
  jpeg_start_decompress(&cinfo);
  if ((int)cinfo.output_width != w || (int)cinfo.output_height != h) {
    jpeg_abort_decompress(&cinfo);
    jpeg_destroy_decompress(&cinfo);
    fclose(fp);
    return -4;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* row = out + (size_t)cinfo.output_scanline * w * 3;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  fclose(fp);
  return 0;
}

int decode_any(const char* path, int h, int w, uint8_t* out) {
  size_t n = strlen(path);
  if (n > 4 && (strcmp(path + n - 4, ".jpg") == 0 || strcmp(path + n - 5, ".jpeg") == 0))
    return decode_jpeg(path, h, w, out);
  return decode_png(path, h, w, out);
}

}  // namespace

extern "C" {

int ddl_pool_init(int n_threads) {
  if (g_pool) return 0;
  if (n_threads < 1) n_threads = 1;
  g_pool = new Pool(n_threads);
  return 0;
}

void ddl_pool_shutdown() {
  delete g_pool;
  g_pool = nullptr;
}

int ddl_image_size(const char* path, int* h, int* w) {
  FILE* fp = fopen(path, "rb");
  if (!fp) return -1;
  png_byte header[8];
  if (fread(header, 1, 8, fp) != 8 || png_sig_cmp(header, 0, 8)) {
    fclose(fp);
    return -2;
  }
  png_structp png = png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  png_infop info = png_create_info_struct(png);
  if (setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, &info, nullptr);
    fclose(fp);
    return -3;
  }
  png_init_io(png, fp);
  png_set_sig_bytes(png, 8);
  png_read_info(png, info);
  *w = (int)png_get_image_width(png, info);
  *h = (int)png_get_image_height(png, info);
  png_destroy_read_struct(&png, &info, nullptr);
  fclose(fp);
  return 0;
}

// Decode `n` images (newline-joined `paths`) into `out` (n*h*w*3 uint8,
// NHWC).  Failed slots are zero-filled; their count is the return deficit.
int ddl_load_batch(const char* paths, int n, int h, int w, uint8_t* out) {
  if (!g_pool) ddl_pool_init((int)std::thread::hardware_concurrency());
  // split newline-joined paths
  std::vector<std::string> files;
  files.reserve(n);
  const char* p = paths;
  for (int i = 0; i < n; ++i) {
    const char* q = strchr(p, '\n');
    files.emplace_back(p, q ? (size_t)(q - p) : strlen(p));
    p = q ? q + 1 : p + files.back().size();
  }
  std::mutex mu;
  std::condition_variable cv;
  int done = 0, ok = 0;
  for (int i = 0; i < n; ++i) {
    g_pool->submit([&, i] {
      uint8_t* slot = out + (size_t)i * h * w * 3;
      int rc = decode_any(files[i].c_str(), h, w, slot);
      if (rc != 0) memset(slot, 0, (size_t)h * w * 3);
      std::lock_guard<std::mutex> lk(mu);
      ++done;
      if (rc == 0) ++ok;
      cv.notify_one();
    });
  }
  std::unique_lock<std::mutex> lk(mu);
  cv.wait(lk, [&] { return done == n; });
  return ok;
}

}  // extern "C"
