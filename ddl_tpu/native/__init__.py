"""ctypes bindings for the native loader core (``loader.cpp``).

Auto-builds ``libddl_loader.so`` with the repo's Makefile on first import if
a toolchain is present; every caller must handle ``loader_lib() is None``
and fall back to the pure-Python path (PIL), so the framework works with no
compiler at all.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

__all__ = ["loader_lib", "load_batch", "native_available", "image_size"]

_HERE = Path(__file__).parent
_SO = _HERE / "libddl_loader.so"
_lib = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["make", "-C", str(_HERE), "-s"],
            check=True,
            capture_output=True,
            timeout=120,
        )
        return _SO.exists()
    except Exception:
        return False


def loader_lib():
    """The loaded shared library, or None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not _SO.exists() and not _build():
        return None
    try:
        lib = ctypes.CDLL(str(_SO))
        lib.ddl_pool_init.argtypes = [ctypes.c_int]
        lib.ddl_load_batch.argtypes = [
            ctypes.c_char_p,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint8),
        ]
        lib.ddl_load_batch.restype = ctypes.c_int
        lib.ddl_image_size.argtypes = [
            ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int),
        ]
        lib.ddl_pool_init(max(2, (os.cpu_count() or 4) // 2))
        _lib = lib
    except OSError:
        _lib = None
    return _lib


def native_available() -> bool:
    return loader_lib() is not None


def image_size(path: str | os.PathLike) -> tuple[int, int] | None:
    """(height, width) of a PNG via the native probe, or None."""
    lib = loader_lib()
    if lib is None:
        return None
    h, w = ctypes.c_int(0), ctypes.c_int(0)
    if lib.ddl_image_size(str(path).encode(), ctypes.byref(h), ctypes.byref(w)) != 0:
        return None
    return h.value, w.value


def load_batch(paths: list[str | os.PathLike], height: int, width: int) -> np.ndarray | None:
    """Decode a batch of image files into one (N, H, W, 3) uint8 array using
    the native thread pool.  Returns None if the native core is unavailable
    or any image failed to decode (caller falls back to PIL)."""
    lib = loader_lib()
    if lib is None:
        return None
    n = len(paths)
    out = np.empty((n, height, width, 3), dtype=np.uint8)
    joined = "\n".join(str(p) for p in paths).encode()
    ok = lib.ddl_load_batch(
        joined, n, height, width, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    )
    return out if ok == n else None
