"""Sharded checkpoint / resume via Orbax.

TPU-native replacement for the reference's ``torch.distributed.checkpoint``
subsystem (``AppState`` at ``single.py:68-89``; save/load at
``single.py:121-134``): asynchronous-capable sharded writes of
``{params, opt_state, batch_stats, epoch}``, laid out as
``<checkpoint_dir>/<job_id>/epoch_<n>`` with resume-by-``(job_id, epoch)``
semantics — loading epoch N resumes training at epoch N+1
(``single.py:124``).  Because ``TrainState`` keeps per-stage pytrees, a
pipeline run checkpoints every stage into the same snapshot, matching the
rank-keyed state dicts of the reference's PP variants (``pp.py:84-90``)
without any rank bookkeeping.

The functions are pytree-generic: the same save/load path checkpoints the
CNN ``TrainState`` and the transformer family's ``LMTrainState``
(``train/lm_steps.py``), and because Orbax writes *global* arrays, a
snapshot saved on one mesh restores onto a different mesh/sharding
(elastic resharding — restore's ``abstract_state`` carries the target
shardings).  The reference's DCP resume is fixed-topology.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Any

import jax
import orbax.checkpoint as ocp

__all__ = [
    "save_snapshot",
    "load_snapshot",
    "snapshot_path",
    "snapshot_metadata",
    "latest_epoch",
    "resolve_resume",
    "run_resume_load",
    "SnapshotManager",
]


def snapshot_path(checkpoint_dir: str | os.PathLike, job_id: str, epoch: int) -> Path:
    return Path(checkpoint_dir).absolute() / job_id / f"epoch_{epoch}"


def save_snapshot(
    checkpoint_dir: str | os.PathLike, job_id: str, epoch: int, state: Any,
) -> Path:
    path = snapshot_path(checkpoint_dir, job_id, epoch)
    path.parent.mkdir(parents=True, exist_ok=True)
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, {"state": state, "epoch": epoch}, force=True)
    return path


def load_snapshot(
    checkpoint_dir: str | os.PathLike,
    job_id: str,
    epoch: int,
    abstract_state: Any,
) -> tuple[Any, int]:
    """Restore a snapshot; returns ``(state, epochs_run)`` where training
    resumes at ``epochs_run = saved_epoch + 1`` (reference ``single.py:124``)."""
    path = snapshot_path(checkpoint_dir, job_id, epoch)
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, abstract_state)
    with ocp.StandardCheckpointer() as ckptr:
        restored = ckptr.restore(path, {"state": abstract, "epoch": 0})
    return restored["state"], int(restored["epoch"]) + 1


def snapshot_metadata(
    checkpoint_dir: str | os.PathLike, job_id: str, epoch: int
) -> Any:
    """Structure of a saved snapshot — the ``{state, epoch}`` tree with
    shape/dtype/sharding metadata leaves, read without touching array data.
    Lets a resuming run discover how a snapshot was laid out (e.g. its
    pipeline stage count) instead of being told via flags."""
    path = snapshot_path(checkpoint_dir, job_id, epoch)
    if not path.is_dir():
        have = latest_epoch(checkpoint_dir, job_id)
        raise FileNotFoundError(
            f"no snapshot at {path}"
            + (f" (latest for job {job_id!r}: {have})" if have is not None
               else f" (job {job_id!r} has no snapshots)")
        )
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.metadata(path).item_metadata.tree


def resolve_resume(
    checkpoint_dir: str | os.PathLike | None,
    job_id: str,
    explicit: int | None = None,
    auto: bool = True,
    unit: str = "epoch",
) -> int | None:
    """Which snapshot a run should resume from — the one resume policy all
    three trainer families share (VERDICT round 3 #8): an explicit flag
    wins; otherwise (with ``auto``) the job id's latest snapshot, so a
    JobSet/SIGTERM relaunch with the same job id continues training with
    no extra arguments; otherwise None (fresh start).  The reference's
    manual ``snapshot_job_id``/``snapshot_epoch`` args (``ddp.py:109-110``)
    made automatic."""
    if explicit is not None:
        return explicit
    if not auto or not checkpoint_dir:
        return None
    last = latest_epoch(checkpoint_dir, job_id)
    if last is not None:
        print(
            f"auto-resume: job {job_id!r} has a snapshot at {unit} {last} "
            f"(disable auto_resume to start fresh)"
        )
    return last


def run_resume_load(load_fn, auto: bool, desc: str, hint: str):
    """Run a resume load, converting AUTO-resume failures into actionable
    advice.  An explicitly requested resume (``auto=False``) propagates the
    raw error — the user named a snapshot and should see exactly why it
    failed; an auto-discovered one most likely mismatches because the job
    id was reused with a different config, so say that and how to opt out."""
    try:
        return load_fn()
    except Exception as e:
        if not auto:
            raise
        raise RuntimeError(
            f"auto-resume from {desc} failed — the saved run's "
            f"model/optimizer/mesh config may not match this one; "
            f"{hint} or use a fresh job id to start fresh"
        ) from e


class SnapshotManager:
    """Asynchronous snapshot writer (SURVEY.md section 5: the TPU-native
    equivalent of DCP is *async* sharded checkpointing — training continues
    while the previous snapshot commits to storage in the background)."""

    def __init__(self, checkpoint_dir: str | os.PathLike, job_id: str) -> None:
        self.checkpoint_dir = checkpoint_dir
        self.job_id = job_id
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())

    def save(self, epoch: int, state: Any) -> Path:
        path = snapshot_path(self.checkpoint_dir, self.job_id, epoch)
        path.parent.mkdir(parents=True, exist_ok=True)
        # one outstanding save at a time: wait for the previous commit
        self._ckptr.wait_until_finished()
        self._ckptr.save(
            path,
            args=ocp.args.StandardSave({"state": state, "epoch": epoch}),
            force=True,
        )
        return path

    def wait(self) -> None:
        self._ckptr.wait_until_finished()

    def close(self) -> None:
        self._ckptr.wait_until_finished()
        self._ckptr.close()


def latest_epoch(checkpoint_dir: str | os.PathLike, job_id: str) -> int | None:
    """Highest epoch snapshot available for a job, or None."""
    job_dir = Path(checkpoint_dir) / job_id
    if not job_dir.is_dir():
        return None
    epochs = [
        int(p.name.removeprefix("epoch_"))
        for p in job_dir.iterdir()
        if p.name.startswith("epoch_") and p.name.removeprefix("epoch_").isdigit()
    ]
    return max(epochs) if epochs else None
