"""Sharded checkpoint / resume via Orbax.

TPU-native replacement for the reference's ``torch.distributed.checkpoint``
subsystem (``AppState`` at ``single.py:68-89``; save/load at
``single.py:121-134``): asynchronous-capable sharded writes of
``{params, opt_state, batch_stats, epoch}``, laid out as
``<checkpoint_dir>/<job_id>/epoch_<n>`` with resume-by-``(job_id, epoch)``
semantics — loading epoch N resumes training at epoch N+1
(``single.py:124``).  Because ``TrainState`` keeps per-stage pytrees, a
pipeline run checkpoints every stage into the same snapshot, matching the
rank-keyed state dicts of the reference's PP variants (``pp.py:84-90``)
without any rank bookkeeping.

The functions are pytree-generic: the same save/load path checkpoints the
CNN ``TrainState`` and the transformer family's ``LMTrainState``
(``train/lm_steps.py``), and because Orbax writes *global* arrays, a
snapshot saved on one mesh restores onto a different mesh/sharding
(elastic resharding — restore's ``abstract_state`` carries the target
shardings).  That contract is direction-free: a ZeRO snapshot sharded
over a SMALLER data axis restores bit-identically into a larger
world's layout (the elastic scale-UP grow epoch, round 24) just as a
full pod's snapshot restores onto survivors — the grow path is one
rank-0-agreed restore with the new world's shardings, nothing more.
The reference's DCP resume is fixed-topology.
"""

from __future__ import annotations

import json
import os
import zlib
from pathlib import Path
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import orbax.checkpoint as ocp

from ddl_tpu.utils import faultinject
from ddl_tpu.utils.backoff import Backoff, retry_with_backoff

__all__ = [
    "save_snapshot",
    "load_snapshot",
    "state_rule_shardings",
    "shard_and_gather",
    "snapshot_path",
    "snapshot_metadata",
    "latest_epoch",
    "latest_valid_epoch",
    "read_cursor",
    "resolve_resume",
    "run_resume_load",
    "verify_snapshot",
    "write_manifest",
    "gc_snapshots",
    "SnapshotCorruptError",
    "SnapshotManager",
]


def snapshot_path(checkpoint_dir: str | os.PathLike, job_id: str, epoch: int) -> Path:
    return Path(checkpoint_dir).absolute() / job_id / f"epoch_{epoch}"


# Snapshot layout version, written into every new snapshot so future
# migrations key off an explicit field instead of shape sniffing:
# 2 = vocab-major lm_head kernel (round 4's layout; see LMHead).
# Snapshots WITHOUT the field predate the marker — their lm_head
# orientation is detected by shape (_head_migration_abstract), which is
# ambiguous only for square heads (vocab == d_model).
SNAPSHOT_FORMAT = 2


# ---------------------------------------------------------------------------
# Snapshot integrity: commit manifest, verification, corrupt-aware discovery
# ---------------------------------------------------------------------------

# Written into the snapshot directory AFTER the Orbax write completes:
# its presence is the commit marker (a snapshot without one either
# predates this layer — "legacy" — or was torn mid-write), and its
# per-file size+CRC32 records are the integrity check restore runs
# against, catching the truncated/bit-rotted files a flaky shared NAS
# produces *after* a successful commit.
MANIFEST_NAME = "ddl_manifest.json"

# Bounded retry for snapshot-save I/O errors (shared-NAS writes flake):
# total attempts = _SAVE_RETRIES + 1.
_SAVE_RETRIES = 2


class SnapshotCorruptError(RuntimeError):
    """A snapshot failed its integrity check (truncated/corrupt/partial).
    Auto-resume reacts by falling back to the previous good snapshot."""


def _crc32(path: Path, chunk: int = 1 << 20) -> int:
    crc = 0
    with open(path, "rb") as fh:
        while True:
            block = fh.read(chunk)
            if not block:
                return crc
            crc = zlib.crc32(block, crc)


def _snapshot_files(path: Path):
    return sorted(
        p for p in path.rglob("*")
        if p.is_file() and p.name != MANIFEST_NAME
    )


def write_manifest(path: str | os.PathLike, **extra) -> Path:
    """Commit marker + checksum manifest, written atomically (temp file +
    ``os.replace``) so a torn manifest write cannot masquerade as a
    committed snapshot."""
    path = Path(path)
    files = {
        p.relative_to(path).as_posix(): {
            "size": p.stat().st_size,
            "crc32": _crc32(p),
        }
        for p in _snapshot_files(path)
    }
    manifest = path / MANIFEST_NAME
    tmp = manifest.with_suffix(".json.tmp")
    tmp.write_text(json.dumps({"files": files, **extra}, indent=0))
    os.replace(tmp, manifest)
    return manifest


def verify_snapshot(path: str | os.PathLike) -> tuple[bool, str]:
    """``(ok, reason)`` for a snapshot directory.

    Three validity states: *verified* (manifest present, every file's
    size and CRC32 match), *legacy* (no manifest — predates the
    integrity layer; restorable but unverifiable, so it stays valid),
    and *corrupt* (manifest unreadable, files missing, or contents
    drifted — truncation, torn writes, bit rot)."""
    path = Path(path)
    if not path.is_dir():
        return False, "missing"
    manifest = path / MANIFEST_NAME
    if not manifest.exists():
        return True, "legacy (no integrity manifest)"
    try:
        recorded = json.loads(manifest.read_text())["files"]
    except (OSError, ValueError, KeyError) as e:
        return False, f"unreadable manifest ({e!r})"
    for rel, rec in recorded.items():
        f = path / rel
        if not f.is_file():
            return False, f"missing file {rel}"
        size = f.stat().st_size
        if size != rec["size"]:
            return False, (
                f"size mismatch in {rel} ({size} != {rec['size']} bytes — "
                "truncated write?)"
            )
        if _crc32(f) != rec["crc32"]:
            return False, f"checksum mismatch in {rel}"
    return True, f"verified ({len(recorded)} files)"


def save_snapshot(
    checkpoint_dir: str | os.PathLike,
    job_id: str,
    epoch: int,
    state: Any,
    cursor: dict | None = None,
) -> Path:
    """``cursor`` (optional) is the data-stream position this snapshot
    represents — ``{"period", "offset", ...}`` from the training loop —
    recorded in the commit manifest so an exact resume replays no batch
    and skips none (``read_cursor``)."""
    path = snapshot_path(checkpoint_dir, job_id, epoch)
    path.parent.mkdir(parents=True, exist_ok=True)

    def attempt() -> None:
        faultinject.io_check("save")
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(
                path,
                {"state": state, "epoch": epoch, "format": SNAPSHOT_FORMAT},
                force=True,
            )

    def note(e, i):
        print(
            f"snapshot save to {path} failed ({e}); "
            f"retry {i + 1}/{_SAVE_RETRIES}"
        )

    retry_with_backoff(
        attempt, retries=_SAVE_RETRIES, exceptions=(OSError,),
        backoff=Backoff(base=0.5, factor=2.0, max_delay=10.0),
        on_retry=note,
    )
    extra = {"cursor": cursor} if cursor is not None else {}
    write_manifest(path, epoch=epoch, format=SNAPSHOT_FORMAT, **extra)
    faultinject.corrupt_check(path)
    return path


def read_cursor(
    checkpoint_dir: str | os.PathLike, job_id: str, epoch: int
) -> dict | None:
    """The data cursor recorded at commit time, or None (pre-cursor
    snapshots, manifest-less legacy ones, unreadable manifests).  Read
    from the manifest, not the Orbax tree: the cursor describes the
    HOST-side data stream and must be readable without touching array
    bytes.  Besides ``period``/``offset``/``step``, the LM cursor may
    carry ``shuffle_epoch``/``epoch_pos`` — the corpus reshuffle state
    that ``TokenBatches.anchor_resume`` pins so an elastic N-1 relaunch
    (whose shard layout changed the per-epoch length) continues the
    same shuffle trajectory instead of rewinding its epoch clock."""
    manifest = snapshot_path(checkpoint_dir, job_id, epoch) / MANIFEST_NAME
    try:
        cursor = json.loads(manifest.read_text()).get("cursor")
    except (OSError, ValueError):
        return None
    return cursor if isinstance(cursor, dict) else None


def _metadata_tree(ckptr, path):
    """The saved item's metadata tree, across orbax versions: newer
    checkpointers wrap it (``.item_metadata.tree``), older ones return
    the tree directly."""
    md = ckptr.metadata(path)
    md = getattr(md, "item_metadata", md)
    return getattr(md, "tree", md)


def _kp_norm(key_path) -> tuple:
    """Normalise a tree key path to comparable strings (DictKey /
    GetAttrKey / SequenceKey all stringify differently)."""
    return tuple(
        str(getattr(k, "key", getattr(k, "name", getattr(k, "idx", k))))
        for k in key_path
    )


def _is_head_kernel_path(key_path) -> bool:
    keys = _kp_norm(key_path)
    return any(k == "lm_head" for k in keys) and keys[-1] == "kernel"


def _head_migration_abstract(saved, abstract):
    """Detect pre-round-4 snapshots whose lm_head kernel (and its
    param-shaped optimizer moments) were saved (d_model, vocab): round 4
    transposed the stored kernel to vocab-major (``LMHead``, PERF.md).
    ``saved`` is the snapshot's metadata 'state' subtree.  Returns an
    abstract tree asking Orbax for the SAVED orientation (the loaded
    arrays are transposed after restore), or None if the snapshot already
    matches.  Only called for legacy snapshots (no 'format' field —
    load_snapshot checks first); square heads (vocab == d_model,
    realistically only toy configs) are orientation-ambiguous by shape
    and restore as-is, with a warning."""
    saved_shapes = {
        _kp_norm(kp): tuple(leaf.shape)
        for kp, leaf in jax.tree_util.tree_flatten_with_path(saved)[0]
        if hasattr(leaf, "shape")
    }
    hits = 0
    warned = False

    def fix(kp, leaf):
        nonlocal hits, warned
        key = _kp_norm(kp)
        if (
            _is_head_kernel_path(kp)
            and len(getattr(leaf, "shape", ())) == 2
            and leaf.shape[0] == leaf.shape[1]
        ):
            # a square head (vocab == d_model) is orientation-ambiguous by
            # shape: skip migration and restore as-is (pre-shim behavior)
            # — if the legacy snapshot was in fact d_model-major, the
            # restored kernel is silently transposed, so be loud about it
            if not warned:
                warned = True
                import warnings

                warnings.warn(
                    "legacy snapshot (no format field) with a SQUARE "
                    f"lm_head kernel {leaf.shape}: orientation cannot be "
                    "inferred from shape; restoring as-is.  If this "
                    "snapshot predates the vocab-major head layout, the "
                    "restored kernel is transposed.",
                    stacklevel=2,
                )
            return leaf
        if (
            _is_head_kernel_path(kp)
            and len(getattr(leaf, "shape", ())) == 2
            and leaf.shape[0] != leaf.shape[1]
            and saved_shapes.get(key) == leaf.shape[::-1]
        ):
            hits += 1
            sharding = getattr(leaf, "sharding", None)
            if sharding is not None and hasattr(sharding, "spec"):
                # keep cross-topology restore working: ask Orbax for the
                # transposed shape under the transposed partition spec
                from jax.sharding import NamedSharding, PartitionSpec

                spec = tuple(sharding.spec) + (None,) * (
                    2 - len(tuple(sharding.spec))
                )
                sharding = NamedSharding(
                    sharding.mesh, PartitionSpec(spec[1], spec[0])
                )
                return jax.ShapeDtypeStruct(
                    leaf.shape[::-1], leaf.dtype, sharding=sharding
                )
            return jax.ShapeDtypeStruct(leaf.shape[::-1], leaf.dtype)
        return leaf

    migrated = jax.tree_util.tree_map_with_path(fix, abstract)
    return migrated if hits else None


def state_rule_shardings(abstract_state: Any, table, mesh) -> Any:
    """NamedSharding tree for a whole train-state pytree from a
    partition-rule table (``parallel/rules.RuleTable``).

    The table's regexes match anywhere in the leaf path, so the
    optimizer moments — whose paths embed the parameter path
    (``opt_state/0/mu/block0/attn/q/kernel``) — inherit the parameter
    placement, and non-parameter leaves (step, Adam's count) fall
    through to replicated (``strict=False``).  This is how a snapshot
    from ANY topology restores straight into rule placement: hand the
    result to ``load_snapshot(shardings=...)``."""
    from ddl_tpu.parallel import rules as prules

    specs = prules.match_partition_rules(table, abstract_state, strict=False)
    from jax.sharding import NamedSharding, PartitionSpec

    return jax.tree.map(
        lambda s: NamedSharding(mesh, s), specs,
        is_leaf=lambda x: isinstance(x, PartitionSpec),
    )


def shard_and_gather(table, abstract_state: Any, mesh):
    """Rule-driven ``(shard, gather)`` pair for a state pytree:
    ``shard(tree)`` device_puts every leaf into the table's placement
    (optimizer moments included, via path-embedding), ``gather(tree)``
    pulls every leaf fully to host numpy.  The snapshot-interop bridge:
    gather a ZeRO-sharded state to compare/save it replicated-style,
    shard a host-restored one back onto the mesh."""
    from ddl_tpu.parallel import rules as prules

    specs = prules.match_partition_rules(table, abstract_state, strict=False)
    return prules.make_shard_and_gather_fns(mesh, specs)


def load_snapshot(
    checkpoint_dir: str | os.PathLike,
    job_id: str,
    epoch: int,
    abstract_state: Any,
    verify: bool = True,
    shardings: Any | None = None,
) -> tuple[Any, int]:
    """Restore a snapshot; returns ``(state, epochs_run)`` where training
    resumes at ``epochs_run = saved_epoch + 1`` (reference ``single.py:124``).

    Snapshots from before the vocab-major lm_head (round 4) are migrated
    on load: the kernel and its optimizer moments restore in their saved
    (d_model, vocab) orientation and are transposed into the requested
    tree (with the requested sharding, when the abstract leaf carries
    one).

    ``shardings`` (e.g. ``state_rule_shardings(...)``) overrides the
    abstract tree's placements leaf-by-leaf: Orbax writes GLOBAL arrays,
    so a replicated-era snapshot restores directly into a ZeRO-sharded
    layout and vice versa — resharding happens inside the restore, no
    full-size host copy."""
    path = snapshot_path(checkpoint_dir, job_id, epoch)
    # callers that just picked this epoch via latest_valid_epoch pass
    # verify=False — the manifest CRC pass reads every byte, and doing
    # it twice back-to-back doubles resume latency on the very NAS the
    # check defends against
    if verify:
        ok, reason = verify_snapshot(path)
        if not ok:
            raise SnapshotCorruptError(
                f"snapshot at {path} failed its integrity check: {reason}"
            )
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, abstract_state)
    if shardings is not None:
        abstract = jax.tree.map(
            lambda sds, sh: jax.ShapeDtypeStruct(
                sds.shape, sds.dtype, sharding=sh
            ),
            abstract,
            shardings,
        )
    with ocp.StandardCheckpointer() as ckptr:
        saved_md = None
        try:
            saved_md = _metadata_tree(ckptr, path)
        except (OSError, ValueError, KeyError, AttributeError) as e:
            # metadata is only needed for the format/orientation checks;
            # restore still works without it — but say so, or a needed
            # lm_head migration would be skipped with only an opaque
            # shape-mismatch error later
            import warnings

            warnings.warn(
                f"could not read snapshot metadata at {path} ({e!r}); "
                "restoring without format/orientation checks",
                stacklevel=2,
            )
        # snapshots carrying the explicit format field are vocab-major by
        # definition — no shape sniffing; legacy ones get the migration
        # detection (and its square-head ambiguity warning)
        has_format = isinstance(saved_md, dict) and "format" in saved_md
        migrated = None
        if (
            isinstance(saved_md, dict)
            and not has_format
            and "state" in saved_md
        ):
            migrated = _head_migration_abstract(saved_md["state"], abstract)
        skeleton_extra = {"format": 0} if has_format else {}
        if migrated is None:
            restored = ckptr.restore(
                path, {"state": abstract, "epoch": 0, **skeleton_extra}
            )
        else:
            restored = ckptr.restore(
                path, {"state": migrated, "epoch": 0, **skeleton_extra}
            )

            def untranspose(kp, leaf, want):
                if not hasattr(leaf, "shape") or leaf.shape == getattr(
                    want, "shape", None
                ):
                    return leaf
                out = jnp.transpose(leaf)
                sharding = getattr(want, "sharding", None)
                return jax.device_put(out, sharding) if sharding else out

            restored["state"] = jax.tree_util.tree_map_with_path(
                untranspose, restored["state"], abstract
            )
    saved_format = int(restored.get("format", 0))
    if saved_format > SNAPSHOT_FORMAT:
        import warnings

        warnings.warn(
            f"snapshot at {path} has format {saved_format}, newer than "
            f"this code's {SNAPSHOT_FORMAT} — it was written by a newer "
            "version and may use a layout this loader does not know "
            "about; restored values may be misinterpreted",
            stacklevel=2,
        )
    return restored["state"], int(restored["epoch"]) + 1


def load_params(
    checkpoint_dir: str | os.PathLike,
    job_id: str,
    epoch: int,
    vocab_size: int | None = None,
) -> Any:
    """Restore ONLY the parameter tree of a snapshot.

    The restore skeleton is derived from the snapshot's own metadata
    (shape/dtype per leaf), so no optimizer needs reconstructing — the
    decode/eval tools (``bench/decode_quality.py``) cannot know the
    training run's optax chain (schedules/weight-decay change the
    opt_state structure, and a mismatched skeleton fails the restore).
    Only the ``params`` subtree's bytes are read (a partial-tree
    restore — the opt_state, at ~2x the params bytes for Adam, stays on
    disk), and the snapshot ``format`` field gets the same treatment as
    ``load_snapshot``: newer-writer snapshots warn, and format-less
    snapshots get the lm_head orientation check.  Unlike
    ``load_snapshot`` there is no caller-supplied abstract tree to
    shape-compare against, so pass ``vocab_size`` (the decode tools
    know their LMConfig) to resolve a format-less head's orientation
    exactly; without it, a format-less non-square head restores
    as-saved with a loud warning rather than being guessed at — a
    format-less snapshot may be either orientation (the field and the
    vocab-major layout did not land in the same snapshot population)."""
    import numpy as np

    path = snapshot_path(checkpoint_dir, job_id, epoch)
    md = snapshot_metadata(checkpoint_dir, job_id, epoch)
    params_md = md["state"]["params"]

    def to_abstract(leaf):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            return jax.ShapeDtypeStruct(tuple(leaf.shape), leaf.dtype)
        return leaf

    abstract = jax.tree.map(to_abstract, params_md)
    has_format = isinstance(md, dict) and "format" in md
    skeleton: dict = {"state": {"params": abstract}}
    restore_args: dict = {
        "state": {
            "params": jax.tree.map(
                lambda _: ocp.RestoreArgs(restore_type=np.ndarray), abstract
            )
        }
    }
    if has_format:
        skeleton["format"] = 0
        restore_args["format"] = ocp.RestoreArgs(restore_type=int)
    # transforms={} puts the handler in partial-restore mode: saved
    # subtrees absent from the skeleton (opt_state, batch_stats, epoch)
    # are skipped, not read
    with ocp.PyTreeCheckpointer() as ckptr:
        restored = ckptr.restore(
            path,
            args=ocp.args.PyTreeRestore(
                item=skeleton, transforms={}, restore_args=restore_args
            ),
        )
    saved_format = int(restored.get("format", 0)) if has_format else 0
    if saved_format > SNAPSHOT_FORMAT:
        import warnings

        warnings.warn(
            f"snapshot at {path} has format {saved_format}, newer than "
            f"this code's {SNAPSHOT_FORMAT} — it was written by a newer "
            "version and may use a layout this loader does not know "
            "about; restored values may be misinterpreted",
            stacklevel=2,
        )
    params = restored["state"]["params"]
    if not has_format:
        # Format-less snapshot: the head may be either orientation (the
        # skeleton here comes from the snapshot's own metadata, so
        # load_snapshot's shape comparison has nothing to compare
        # against).  With the caller's vocab_size the orientation is
        # decidable exactly; without it, restore as-saved and say so.
        def migrate(kp, leaf):
            if (
                _is_head_kernel_path(kp)
                and len(getattr(leaf, "shape", ())) == 2
            ):
                import warnings

                if leaf.shape[0] == leaf.shape[1]:
                    warnings.warn(
                        "format-less snapshot with a SQUARE lm_head "
                        f"kernel {leaf.shape}: orientation cannot be "
                        "inferred; restoring as-is.  If this snapshot "
                        "predates the vocab-major head layout, the "
                        "restored kernel is transposed.",
                        stacklevel=3,
                    )
                    return leaf
                if vocab_size is None:
                    warnings.warn(
                        "format-less snapshot: lm_head kernel "
                        f"{leaf.shape} orientation unverified (pass "
                        "vocab_size= to migrate a pre-vocab-major "
                        "snapshot exactly); restoring as-saved",
                        stacklevel=3,
                    )
                    return leaf
                if leaf.shape[0] != vocab_size and leaf.shape[1] == vocab_size:
                    return np.transpose(leaf)  # saved (d_model, vocab)
            return leaf

        params = jax.tree_util.tree_map_with_path(migrate, params)
    return params


def snapshot_metadata(
    checkpoint_dir: str | os.PathLike, job_id: str, epoch: int
) -> Any:
    """Structure of a saved snapshot — the ``{state, epoch}`` tree with
    shape/dtype/sharding metadata leaves, read without touching array data.
    Lets a resuming run discover how a snapshot was laid out (e.g. its
    pipeline stage count) instead of being told via flags."""
    path = snapshot_path(checkpoint_dir, job_id, epoch)
    if not path.is_dir():
        have = latest_epoch(checkpoint_dir, job_id)
        raise FileNotFoundError(
            f"no snapshot at {path}"
            + (f" (latest for job {job_id!r}: {have})" if have is not None
               else f" (job {job_id!r} has no snapshots)")
        )
    with ocp.StandardCheckpointer() as ckptr:
        return _metadata_tree(ckptr, path)


def resolve_resume(
    checkpoint_dir: str | os.PathLike | None,
    job_id: str,
    explicit: int | None = None,
    auto: bool = True,
    unit: str = "epoch",
) -> int | None:
    """Which snapshot a run should resume from — the one resume policy all
    three trainer families share (VERDICT round 3 #8): an explicit flag
    wins; otherwise (with ``auto``) the job id's latest snapshot, so a
    JobSet/SIGTERM relaunch with the same job id continues training with
    no extra arguments; otherwise None (fresh start).  The reference's
    manual ``snapshot_job_id``/``snapshot_epoch`` args (``ddp.py:109-110``)
    made automatic.

    Under pod supervision (``DDL_COORD_*`` set, >1 host) the epoch is
    chosen by RANK 0 and published through the shared-directory
    rendezvous (``coord.agreed_resume_epoch``): a torn NAS write can
    leave hosts seeing different ``latest_valid_epoch``, and hosts
    restoring different snapshots into one SPMD world diverge silently
    — one decider, one snapshot, every host."""
    if explicit is not None:
        return explicit
    if not auto or not checkpoint_dir:
        return None
    from ddl_tpu import coord

    last = coord.agreed_resume_epoch(
        job_id, lambda: latest_valid_epoch(checkpoint_dir, job_id)
    )
    if last is not None:
        print(
            f"auto-resume: job {job_id!r} has a snapshot at {unit} {last} "
            f"(disable auto_resume to start fresh)"
        )
    return last


def run_resume_load(load_fn, auto: bool, desc: str, hint: str):
    """Run a resume load, converting AUTO-resume failures into actionable
    advice.  An explicitly requested resume (``auto=False``) propagates the
    raw error — the user named a snapshot and should see exactly why it
    failed; an auto-discovered one most likely mismatches because the job
    id was reused with a different config, so say that and how to opt out."""
    try:
        return load_fn()
    except Exception as e:
        if not auto:
            raise
        raise RuntimeError(
            f"auto-resume from {desc} failed — the saved run's "
            f"model/optimizer/mesh config may not match this one; "
            f"{hint} or use a fresh job id to start fresh"
        ) from e


class SnapshotManager:
    """Asynchronous snapshot writer (SURVEY.md section 5: the TPU-native
    equivalent of DCP is *async* sharded checkpointing — training continues
    while the previous snapshot commits to storage in the background)."""

    def __init__(self, checkpoint_dir: str | os.PathLike, job_id: str) -> None:
        self.checkpoint_dir = checkpoint_dir
        self.job_id = job_id
        self._ckptr = ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
        # the in-flight save whose manifest is still owed: the manifest
        # (= commit marker) may only be written after the async write
        # finishes, or verification would bless a half-written snapshot
        self._pending: Path | None = None
        self._pending_cursor: dict | None = None

    def _finish_pending(self) -> None:
        if self._pending is not None:
            extra = (
                {"cursor": self._pending_cursor}
                if self._pending_cursor is not None else {}
            )
            write_manifest(self._pending, **extra)
            faultinject.corrupt_check(self._pending)
            self._pending = None
            self._pending_cursor = None

    def save(self, epoch: int, state: Any, cursor: dict | None = None) -> Path:
        path = snapshot_path(self.checkpoint_dir, self.job_id, epoch)
        path.parent.mkdir(parents=True, exist_ok=True)
        # one outstanding save at a time: wait for the previous commit
        self._ckptr.wait_until_finished()
        self._finish_pending()
        self._ckptr.save(
            path,
            args=ocp.args.StandardSave(
                {"state": state, "epoch": epoch, "format": SNAPSHOT_FORMAT}
            ),
            force=True,
        )
        self._pending = path
        self._pending_cursor = cursor
        return path

    def wait(self) -> None:
        self._ckptr.wait_until_finished()
        self._finish_pending()

    def close(self) -> None:
        self._ckptr.wait_until_finished()
        self._finish_pending()
        self._ckptr.close()


def latest_epoch(checkpoint_dir: str | os.PathLike, job_id: str) -> int | None:
    """Highest epoch snapshot available for a job, or None."""
    epochs = snapshot_epochs(checkpoint_dir, job_id)
    return epochs[-1] if epochs else None


def snapshot_epochs(
    checkpoint_dir: str | os.PathLike, job_id: str
) -> list[int]:
    """All snapshot epochs for a job, ascending (validity not checked)."""
    job_dir = Path(checkpoint_dir) / job_id
    if not job_dir.is_dir():
        return []
    return sorted(
        int(p.name.removeprefix("epoch_"))
        for p in job_dir.iterdir()
        if p.name.startswith("epoch_") and p.name.removeprefix("epoch_").isdigit()
    )


# Snapshots this process already CRC-verified (immutable after commit,
# so per-save GC re-verification of the keep window would re-read every
# byte of every kept snapshot — ~keep x snapshot-size of NAS traffic
# per save for nothing).  Only positive results are cached: a corrupt
# snapshot gets deleted, and restore-time verification still reads the
# real bytes, so later bit rot is caught where it matters.
_gc_verified: set[tuple[str, str, int]] = set()


def gc_snapshots(
    checkpoint_dir: str | os.PathLike,
    job_id: str,
    keep: int,
    protect: Sequence[int] = (),
) -> list[tuple[Path, str]]:
    """Delete old snapshots, keeping the newest ``keep`` **valid** ones.

    Corrupt snapshots never count toward ``keep``: a multi-day run with
    ``keep=2`` whose newest write was torn by a NAS flake must still
    hold two *restorable* snapshots, not one good one plus a corpse —
    the exact fallback chain ``latest_valid_epoch`` walks on rollback/
    auto-resume.  Corrupt snapshots are deleted (they can never be
    restored) along with valid ones older than the keep window.
    ``protect`` epochs (the best-eval-metric snapshot the save gate just
    wrote) are never deleted and occupy no keep slot — ``keep`` bounds
    the *cadence* retention, not the gated one.

    An in-flight async save is safe: Orbax commits atomically (tmp-dir
    rename), so an uncommitted snapshot is invisible to
    ``snapshot_epochs``, and a committed-but-manifestless one counts as
    valid ("legacy") and is the newest — inside the keep window.

    Returns ``[(path, reason), ...]`` for what was removed."""
    import shutil

    if keep is None or keep <= 0:
        return []
    protected = set(protect)
    removed: list[tuple[Path, str]] = []
    valid_kept = 0
    for epoch in reversed(snapshot_epochs(checkpoint_dir, job_id)):
        if epoch in protected:
            continue
        path = snapshot_path(checkpoint_dir, job_id, epoch)
        if valid_kept < keep:
            cache_key = (str(Path(checkpoint_dir).absolute()), job_id, epoch)
            if cache_key in _gc_verified:
                valid_kept += 1
                continue
            ok, reason = verify_snapshot(path)
            if ok:
                _gc_verified.add(cache_key)
                valid_kept += 1
                continue
            reason = f"corrupt ({reason}); does not count toward keep={keep}"
        else:
            reason = f"older than the {keep} newest valid snapshots"
        try:
            shutil.rmtree(path)
        except OSError as e:
            print(f"snapshot GC could not remove {path}: {e}")
            continue
        removed.append((path, reason))
    return removed


def latest_valid_epoch(
    checkpoint_dir: str | os.PathLike, job_id: str
) -> int | None:
    """Newest snapshot that passes integrity verification — the rollback/
    auto-resume target.  Corrupt or partial snapshots are skipped with a
    loud note (the fallback the issue of a torn NAS write demands);
    legacy manifest-less snapshots count as valid."""
    for epoch in reversed(snapshot_epochs(checkpoint_dir, job_id)):
        path = snapshot_path(checkpoint_dir, job_id, epoch)
        ok, reason = verify_snapshot(path)
        if ok:
            return epoch
        print(
            f"skipping snapshot at {path}: {reason} — "
            "falling back to the previous snapshot"
        )
    return None
