from ddl_tpu.launcher.tpu_pod import JobSpec, kubernetes_manifest, pod_commands

__all__ = ["JobSpec", "kubernetes_manifest", "pod_commands"]
