"""TPU pod launcher: the TorchX/Kubernetes replacement.

The reference launches through TorchX (`torchx run -s kubernetes dist.ddp
-j NxG --script ddp.py`, reference ``command:5-34``, with scheduler defaults
in ``.torchxconfig`` and a custom single-GPU component in
``torchx_component/submit_single.py``).  The TPU equivalent needs far less
machinery: a slice is already a gang-scheduled unit, so a "job" is the same
command run once per TPU host with coordinator env vars.  This module emits

* ``pod_commands`` — per-host shell commands (for ``gcloud compute tpus
  tpu-vm ssh --worker=all`` style fan-out), and
* ``kubernetes_manifest`` — a JobSet-style YAML for GKE TPU slices
  (completions == host count, one pod per host), mirroring the reference's
  k8s deployment but with the TPU device plugin instead of per-GPU ranks.

Job identity flows through ``DDL_JOB_ID`` (the TORCHX_JOB_ID analog,
reference ``single.py:102``).
"""

from __future__ import annotations

import dataclasses
import shlex
import uuid

__all__ = ["JobSpec", "pod_commands", "kubernetes_manifest"]


@dataclasses.dataclass
class JobSpec:
    name: str = "ddl"
    preset: str = "dp_pp"
    overrides: tuple[str, ...] = ()
    num_hosts: int = 4  # v4-32 = 4 hosts x 4 chips
    coordinator_port: int = 8476
    image: str = "ddl-tpu:latest"
    workdir: str = "/workspace"
    env: tuple[tuple[str, str], ...] = ()

    @property
    def job_id(self) -> str:
        return f"{self.preset}-{self.name}-{uuid.uuid4().hex[:10]}"


def _train_argv(spec: JobSpec) -> list[str]:
    argv = ["python", "-m", "ddl_tpu.cli", "--preset", spec.preset]
    if spec.overrides:
        argv += ["--set", *spec.overrides]
    return argv


def pod_commands(spec: JobSpec, coordinator_host: str = "$(hostname -i)") -> list[str]:
    """One shell command per TPU host (worker i runs commands[i])."""
    job_id = spec.job_id
    cmds = []
    for host in range(spec.num_hosts):
        env = {
            "DDL_JOB_ID": job_id,
            "DDL_COORDINATOR": f"{coordinator_host}:{spec.coordinator_port}",
            "DDL_NUM_PROCESSES": str(spec.num_hosts),
            "DDL_PROCESS_ID": str(host),
            **dict(spec.env),
        }
        envs = " ".join(f"{k}={shlex.quote(v)}" for k, v in env.items())
        cmds.append(f"{envs} {' '.join(_train_argv(spec))}")
    return cmds


def kubernetes_manifest(spec: JobSpec, tpu_topology: str = "2x2x4") -> str:
    """GKE JobSet-style manifest for a multi-host TPU slice job."""
    job_id = spec.job_id
    args = ", ".join(f'"{a}"' for a in _train_argv(spec))
    extra_env = "\n".join(
        f'            - {{name: "{k}", value: "{v}"}}' for k, v in spec.env
    )
    return f"""\
apiVersion: jobset.x-k8s.io/v1alpha2
kind: JobSet
metadata:
  name: {spec.name}
spec:
  replicatedJobs:
  - name: workers
    template:
      spec:
        parallelism: {spec.num_hosts}
        completions: {spec.num_hosts}
        backoffLimit: 0
        template:
          spec:
            restartPolicy: Never
            nodeSelector:
              cloud.google.com/gke-tpu-topology: {tpu_topology}
            containers:
            - name: train
              image: {spec.image}
              workingDir: {spec.workdir}
              command: [{args}]
              env:
              - {{name: "DDL_JOB_ID", value: "{job_id}"}}
              - {{name: "DDL_MULTIHOST", value: "1"}}
{extra_env if spec.env else ''}
              resources:
                limits:
                  google.com/tpu: 4
"""
