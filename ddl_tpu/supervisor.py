"""Auto-resume supervisor: relaunch the trainer until the run completes.

The reference's recovery story is a human re-submitting the job with
manual ``snapshot_job_id``/``snapshot_epoch`` args (SURVEY.md §5).  On
preemptible TPU pods that human is woken several times a night, so this
module closes the loop: ``ddl_tpu train --supervise --max-restarts N``
runs the trainer as a child process and relaunches it after a preemption
or crash.  Resume needs no arguments — the trainers auto-discover the
latest *valid* snapshot for their job id (``checkpoint.resolve_resume``
skips corrupt/partial ones), so relaunch == resume by construction.

Exit-code protocol (how the child tells the supervisor what happened):

    0                run complete — stop
    EXIT_PREEMPTED   resumable interruption: SIGTERM-style preemption
    (75, EX_TEMPFAIL) after a committed snapshot, or the stall watchdog's
                     dump-then-exit escalation.  Relaunched immediately
                     (the interruption was external; backing off would
                     only lose training time), and does NOT consume the
                     crash budget — a multi-day run on preemptible pods
                     is evicted routinely, and each eviction made
                     snapshot progress.  A *streak* of resumable exits
                     with no progress signal in between does back off
                     (a watchdog deadline set below the first-step
                     compile must not burn relaunches at full speed),
                     and a generous safety cap (``max_preemptions``,
                     default 1000) bounds the pathological always-75
                     loop.
    anything else    a crash.  Relaunched after exponential backoff with
                     jitter (``utils/backoff.Backoff``) so a crash-looping
                     job doesn't hammer the scheduler/NAS, up to
                     ``max_restarts`` crash relaunches.

The restart policy is separated from process management: ``Supervisor``
drives any ``attempt_fn(restart_index) -> exit_code`` (tests inject
callables and fake clocks), while ``supervise_command`` supplies the
subprocess runner the CLI uses.  Children get ``DDL_SUPERVISED=1`` (the
trainer exits ``EXIT_PREEMPTED`` after a preemption snapshot instead of
0), ``DDL_RESTART_COUNT``, and — unless the operator overrides it —
``DDL_WATCHDOG_ACTION=exit``, escalating the stall watchdog from
dump-stacks to dump-then-exit-resumable so a hung collective is
restarted instead of hanging forever.
"""

from __future__ import annotations

import os
import subprocess
import time
from typing import Callable

from ddl_tpu.utils.backoff import Backoff

__all__ = ["EXIT_PREEMPTED", "Supervisor", "supervise_command"]

# EX_TEMPFAIL from sysexits.h: "temporary failure, retry later" — exactly
# a preemption's semantics, and distinguishable from crash exit codes
# (1, 2, 134, 139, ...) without inventing a private protocol.
EXIT_PREEMPTED = 75


class Supervisor:
    """Run ``attempt_fn`` until it returns 0 or restarts are exhausted.

    ``attempt_fn(restart_index)`` returns the attempt's exit code; an
    exception it raises counts as a crash (exit code 1).  ``sleep`` and
    ``backoff`` are injectable so tests run in virtual time.
    """

    def __init__(
        self,
        attempt_fn: Callable[[int], int],
        max_restarts: int = 5,
        max_preemptions: int = 1000,
        backoff: Backoff | None = None,
        sleep: Callable[[float], None] = time.sleep,
        log: Callable[[str], None] = print,
        streak_window_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        events=None,
    ) -> None:
        self.attempt_fn = attempt_fn
        # an obs EventWriter (or None): restart decisions land in the
        # same structured stream the trainers write, so `obs summarize`
        # shows WHY a run has three run_start segments — the ROADMAP
        # item "surface supervisor restarts as obs events from the
        # supervisor itself" (it previously only printed)
        self.events = events
        self.max_restarts = max_restarts
        self.max_preemptions = max_preemptions
        # an attempt that ran at least this long before its resumable
        # exit made real progress (compiled, trained, snapshotted) — it
        # is a genuine eviction, not a livelock iteration, and ends the
        # backoff streak
        self.streak_window_s = streak_window_s
        self.clock = clock
        self.backoff = backoff if backoff is not None else Backoff(
            base=1.0, factor=2.0, max_delay=120.0, jitter=0.5
        )
        self.sleep = sleep
        self.log = log
        self.restarts = 0
        self.crashes = 0
        self.preemptions = 0
        # consecutive resumable exits with no crash in between: the
        # first relaunches immediately (a real eviction), but a STREAK
        # backs off like a crash loop — e.g. a watchdog deadline set
        # below the first-step compile would otherwise burn
        # max_preemptions full recompiles at full speed
        self._consec_resumable = 0

    def _emit(self, kind: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(
                kind,
                restarts=self.restarts,
                crashes=self.crashes,
                preemptions=self.preemptions,
                **fields,
            )

    def run(self) -> int:
        self._emit(
            "supervisor_start",
            max_restarts=self.max_restarts,
            max_preemptions=self.max_preemptions,
        )
        while True:
            t0 = self.clock()
            try:
                rc = int(self.attempt_fn(self.restarts))
            # any attempt_fn exception IS the crash signal (rc=1): the
            # supervisor must outlive whatever the child runner throws
            except Exception as e:  # ddl-lint: disable=broad-except
                self.log(f"[supervisor] attempt raised {type(e).__name__}: {e}")
                rc = 1
            if self.clock() - t0 >= self.streak_window_s:
                # long-lived attempt = forward progress: the next
                # resumable exit relaunches immediately again
                self._consec_resumable = 0
            if rc == 0:
                if self.restarts:
                    self.log(
                        f"[supervisor] run complete after {self.restarts} "
                        f"relaunch(es) ({self.preemptions} preemption(s), "
                        f"{self.crashes} crash(es))"
                    )
                self._emit("supervisor_done", rc=0, gave_up=False)
                return 0
            self.restarts += 1
            if rc == EXIT_PREEMPTED:
                self.preemptions += 1
                self._consec_resumable += 1
                if self.preemptions > self.max_preemptions:
                    self.log(
                        f"[supervisor] giving up: {self.max_preemptions} "
                        "resumable exits — something re-preempts every "
                        "attempt"
                    )
                    self._emit("supervisor_done", rc=rc, gave_up=True)
                    return rc
                delay = (
                    0.0 if self._consec_resumable == 1
                    else self.backoff.delay(self._consec_resumable - 2)
                )
                self.log(
                    f"[supervisor] resumable exit ({rc}); relaunching"
                    + (f" in {delay:.1f}s" if delay else "")
                    + f" (preemption {self.preemptions}, crash budget "
                    f"untouched at {self.crashes}/{self.max_restarts})"
                )
                self._emit(
                    "supervisor_relaunch", reason="preempt", rc=rc,
                    delay=delay,
                )
                if delay > 0:
                    self.sleep(delay)
                continue
            self._consec_resumable = 0
            self.crashes += 1
            if self.crashes > self.max_restarts:
                self.log(
                    f"[supervisor] giving up: exit code {rc} after "
                    f"{self.max_restarts} crash relaunches"
                )
                self._emit("supervisor_done", rc=rc, gave_up=True)
                return rc
            delay = self.backoff.delay(self.crashes - 1)
            self.log(
                f"[supervisor] crash (exit {rc}); relaunching in "
                f"{delay:.1f}s (crash {self.crashes}/{self.max_restarts})"
            )
            self._emit(
                "supervisor_relaunch", reason="crash", rc=rc, delay=delay,
            )
            if delay > 0:
                self.sleep(delay)


def _supervisor_events(env_map):
    """An EventWriter aimed at the same log tree the child trainer
    writes (DDL_LOG_DIR / DDL_JOB_ID, matching config.py's env-driven
    defaults), so supervisor restart events land in the job's stream.
    The supervisor process must never initialise JAX — the child owns
    the devices — hence ``host=0`` is passed explicitly (EventWriter's
    host auto-detection goes through ``launch.host_id``).  Returns None
    when the log directory is unwritable (events are telemetry, not a
    reason to refuse supervision)."""
    from ddl_tpu.obs.events import EventWriter

    log_dir = env_map.get("DDL_LOG_DIR", "training_logs")
    job_id = (
        env_map.get("DDL_JOB_ID")
        or env_map.get("TORCHX_JOB_ID")
        or "local"
    ).split("/")[-1]
    try:
        return EventWriter(log_dir, job_id, host=0)
    except OSError as e:
        print(f"[supervisor] obs events disabled ({e})")
        return None


def supervise_command(
    argv: list[str],
    max_restarts: int = 5,
    env: dict | None = None,
    **kwargs,
) -> int:
    """Supervise ``argv`` as a child process (the CLI's ``--supervise``).

    Each attempt inherits the environment plus the supervision contract
    vars; the child's own auto-resume does the snapshot discovery."""

    def attempt(restart_index: int) -> int:
        child_env = dict(os.environ if env is None else env)
        child_env["DDL_SUPERVISED"] = "1"
        child_env["DDL_RESTART_COUNT"] = str(restart_index)
        # escalate the watchdog so a hung collective becomes a relaunch;
        # the operator's explicit setting wins
        child_env.setdefault("DDL_WATCHDOG_ACTION", "exit")
        # injected faults model one-off events (an eviction does not
        # recur on relaunch); fault specs count per process, so drop
        # them for relaunches unless explicitly pinned
        if restart_index > 0 and not child_env.get("DDL_FAULT_PERSIST"):
            child_env.pop("DDL_FAULT", None)
        return subprocess.call(argv, env=child_env)

    kwargs.setdefault(
        "events", _supervisor_events(os.environ if env is None else env)
    )
    sup = Supervisor(attempt, max_restarts=max_restarts, **kwargs)
    try:
        return sup.run()
    finally:
        if sup.events is not None:
            sup.events.close()
