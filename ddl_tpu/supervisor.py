"""Auto-resume supervisor: relaunch the trainer until the run completes.

The reference's recovery story is a human re-submitting the job with
manual ``snapshot_job_id``/``snapshot_epoch`` args (SURVEY.md §5).  On
preemptible TPU pods that human is woken several times a night, so this
module closes the loop: ``ddl_tpu train --supervise --max-restarts N``
runs the trainer as a child process and relaunches it after a preemption
or crash.  Resume needs no arguments — the trainers auto-discover the
latest *valid* snapshot for their job id (``checkpoint.resolve_resume``
skips corrupt/partial ones), so relaunch == resume by construction.

Exit-code protocol (how the child tells the supervisor what happened):

    0                run complete — stop
    EXIT_PREEMPTED   resumable interruption: SIGTERM-style preemption
    (75, EX_TEMPFAIL) after a committed snapshot, or the stall watchdog's
                     dump-then-exit escalation.  Relaunched immediately
                     (the interruption was external; backing off would
                     only lose training time), and does NOT consume the
                     crash budget — a multi-day run on preemptible pods
                     is evicted routinely, and each eviction made
                     snapshot progress.  A *streak* of resumable exits
                     with no progress signal in between does back off
                     (a watchdog deadline set below the first-step
                     compile must not burn relaunches at full speed),
                     and a generous safety cap (``max_preemptions``,
                     default 1000) bounds the pathological always-75
                     loop.
    anything else    a crash.  Relaunched after exponential backoff with
                     jitter (``utils/backoff.Backoff``) so a crash-looping
                     job doesn't hammer the scheduler/NAS, up to
                     ``max_restarts`` crash relaunches.

The restart policy is separated from process management: ``Supervisor``
drives any ``attempt_fn(restart_index) -> exit_code`` (tests inject
callables and fake clocks), while ``supervise_command`` supplies the
subprocess runner the CLI uses.  Children get ``DDL_SUPERVISED=1`` (the
trainer exits ``EXIT_PREEMPTED`` after a preemption snapshot instead of
0), ``DDL_RESTART_COUNT``, and — unless the operator overrides it —
``DDL_WATCHDOG_ACTION=exit``, escalating the stall watchdog from
dump-stacks to dump-then-exit-resumable so a hung collective is
restarted instead of hanging forever.

Injected faults (``DDL_FAULT``) follow consume-on-fire across
relaunches: a spec that FIRED in the previous attempt is dropped from
the relaunch env (an eviction does not recur), while specs that have not
fired yet are preserved — so multi-fault scenarios (a second
``preempt@step`` beyond the resume point) stay expressible.  The child
records fired specs into ``DDL_FAULT_STATE``
(``utils/faultinject.fire``); ``DDL_FAULT_PERSIST=1`` pins the full spec
on every attempt instead.

**Pod mode** (``PodSupervisor`` / ``supervise_pod_command``, CLI
``--supervise --pod DIR --hosts N --host-id I``): on a multihost pod the
trainers form ONE SPMD world, so restarting one host's child just hangs
at the next collective.  Each host runs a PodSupervisor over a shared-
directory rendezvous (``ddl_tpu/coord.py``): heartbeats while the child
runs, exit-intent markers when it stops, a first-writer-wins restart-
epoch ledger (crash budgets and the backoff delay are fields of the
atomically-created epoch record — hosts cannot split-brain on either),
a join barrier so every host kills and relaunches together, stale-peer
detection (a host whose heartbeat ages out while "running" triggers a
pod restart instead of an eternal collective hang), and a pod-wide
abort marker so giving up is also a coordinated event.

**Elastic mode** (``elastic=True``, CLI ``--elastic``): permanent host
loss no longer kills the pod.  A peer whose heartbeat ages past
``stale_after_s + elastic_grace_s`` — or that never reaches a restart
epoch's join barrier — is *evicted*: the survivors propose a shrunken
membership through the same first-writer-wins epoch ledger (the record
carries ``hosts``/``world``), adopt it, and relaunch N−1 children with
``DDL_COORD_MEMBERS`` plus a respecced SPMD bootstrap
(``DDL_NUM_PROCESSES``/``DDL_PROCESS_ID`` renumber the survivors
contiguously).  The relaunched trainers re-derive the data axis from
the smaller world (``parallel/rules.py``), resume the rank-0-agreed
snapshot, and re-split the exact-resume cursor across survivors — no
batch lost or replayed.  A host that finds itself evicted by an
adopted record exits cleanly instead of aborting the pod — unless it
can rejoin (below).

**Elastic scale-UP** (the grow half): an evicted host — or a fresh
replacement supervisor started into the same launch — does not exit
under ``elastic``.  It publishes a ``joins/h<i>.json`` marker
(``coord.Rendezvous.publish_join_request``, refreshed like a
heartbeat) and waits.  The LEADER observes fresh join requests during
its signal polls and answers with a ``peer_join`` restart epoch whose
ledger record carries the GROWN membership — the same first-writer-
wins atomic-create protocol that agrees shrink memberships, so there
is no split-brain window between "which epoch" and "who is in it".
Every member (survivors and joiner) adopts the record, meets at the
``e<E>-join`` barrier, and relaunches into the larger world: the
spawn env renumbers ``DDL_NUM_PROCESSES``/``DDL_PROCESS_ID``/
``DDL_COORD_MEMBERS`` from the adopted membership, the relaunched
trainers re-derive the bigger data axis, restore the rank-0-agreed
snapshot (``checkpoint.state_rule_shardings`` reshards ZeRO optimizer
moments into the new layout), and re-split the data cursor.  The
restart boundary IS the safe boundary: the grow epoch resumes from
the last committed snapshot, so membership only ever changes at a
snapshot commit.  ``EXIT_REJOIN`` (76) is the drill hook: an elastic
child exiting with it asks its own host to step OUT of the pod and
return through the join path (``DDL_FAULT=rejoin@epoch:K``).
"""

from __future__ import annotations

import collections
import os
import subprocess
import sys
import time
from pathlib import Path
from typing import Callable

from ddl_tpu.utils.backoff import Backoff

__all__ = [
    "EXIT_PREEMPTED",
    "EXIT_REJOIN",
    "PodSupervisor",
    "Supervisor",
    "supervise_command",
    "supervise_pod_command",
]

# EX_TEMPFAIL from sysexits.h: "temporary failure, retry later" — exactly
# a preemption's semantics, and distinguishable from crash exit codes
# (1, 2, 134, 139, ...) without inventing a private protocol.
EXIT_PREEMPTED = 75
# Voluntary leave-and-return (elastic pods only): the child asks its
# host to step out of the membership and come back through the
# join_request path — the scripted shape of "this host is being
# recycled; the pod should shrink now and grow when it returns".
# Driven by the rejoin fault (DDL_FAULT=rejoin@epoch:K) in the pod-sim
# drill; outside elastic mode the code classifies as a plain crash.
EXIT_REJOIN = 76


class Supervisor:
    """Run ``attempt_fn`` until it returns 0 or restarts are exhausted.

    ``attempt_fn(restart_index)`` returns the attempt's exit code; an
    exception it raises counts as a crash (exit code 1).  ``sleep`` and
    ``backoff`` are injectable so tests run in virtual time.
    """

    def __init__(
        self,
        attempt_fn: Callable[[int], int],
        max_restarts: int = 5,
        max_preemptions: int = 1000,
        backoff: Backoff | None = None,
        sleep: Callable[[float], None] = time.sleep,
        log: Callable[[str], None] = print,
        streak_window_s: float = 60.0,
        clock: Callable[[], float] = time.monotonic,
        events=None,
    ) -> None:
        self.attempt_fn = attempt_fn
        # an obs EventWriter (or None): restart decisions land in the
        # same structured stream the trainers write, so `obs summarize`
        # shows WHY a run has three run_start segments — the ROADMAP
        # item "surface supervisor restarts as obs events from the
        # supervisor itself" (it previously only printed)
        self.events = events
        self.max_restarts = max_restarts
        self.max_preemptions = max_preemptions
        # an attempt that ran at least this long before its resumable
        # exit made real progress (compiled, trained, snapshotted) — it
        # is a genuine eviction, not a livelock iteration, and ends the
        # backoff streak
        self.streak_window_s = streak_window_s
        self.clock = clock
        self.backoff = backoff if backoff is not None else Backoff(
            base=1.0, factor=2.0, max_delay=120.0, jitter=0.5
        )
        self.sleep = sleep
        self.log = log
        self.restarts = 0
        self.crashes = 0
        self.preemptions = 0
        # wall clock of the latest relaunch DECISION (always time.time(),
        # not the injectable monotonic `clock`: it crosses process
        # boundaries).  supervise_command stamps it into the relaunched
        # child's env as DDL_RELAUNCH_TS; the trainer's first completed
        # step emits a `restart_latency` obs event against it — the
        # relaunch-to-step metric the elastic-restart ROADMAP direction
        # gates on (compile-cache wins must show up HERE).
        self.last_relaunch_ts: float | None = None
        # consecutive resumable exits with no crash in between: the
        # first relaunches immediately (a real eviction), but a STREAK
        # backs off like a crash loop — e.g. a watchdog deadline set
        # below the first-step compile would otherwise burn
        # max_preemptions full recompiles at full speed
        self._consec_resumable = 0

    def _emit(self, kind: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(
                kind,
                restarts=self.restarts,
                crashes=self.crashes,
                preemptions=self.preemptions,
                **fields,
            )

    def run(self) -> int:
        self._emit(
            "supervisor_start",
            max_restarts=self.max_restarts,
            max_preemptions=self.max_preemptions,
        )
        while True:
            t0 = self.clock()
            try:
                rc = int(self.attempt_fn(self.restarts))
            # any attempt_fn exception IS the crash signal (rc=1): the
            # supervisor must outlive whatever the child runner throws
            except Exception as e:  # ddl-lint: disable=broad-except
                self.log(f"[supervisor] attempt raised {type(e).__name__}: {e}")
                rc = 1
            if self.clock() - t0 >= self.streak_window_s:
                # long-lived attempt = forward progress: the next
                # resumable exit relaunches immediately again
                self._consec_resumable = 0
            if rc == 0:
                if self.restarts:
                    self.log(
                        f"[supervisor] run complete after {self.restarts} "
                        f"relaunch(es) ({self.preemptions} preemption(s), "
                        f"{self.crashes} crash(es))"
                    )
                self._emit("supervisor_done", rc=0, gave_up=False)
                return 0
            self.restarts += 1
            if rc == EXIT_PREEMPTED:
                self.preemptions += 1
                self._consec_resumable += 1
                if self.preemptions > self.max_preemptions:
                    self.log(
                        f"[supervisor] giving up: {self.max_preemptions} "
                        "resumable exits — something re-preempts every "
                        "attempt"
                    )
                    self._emit("supervisor_done", rc=rc, gave_up=True)
                    return rc
                delay = (
                    0.0 if self._consec_resumable == 1
                    else self.backoff.delay(self._consec_resumable - 2)
                )
                self.log(
                    f"[supervisor] resumable exit ({rc}); relaunching"
                    + (f" in {delay:.1f}s" if delay else "")
                    + f" (preemption {self.preemptions}, crash budget "
                    f"untouched at {self.crashes}/{self.max_restarts})"
                )
                self.last_relaunch_ts = time.time()
                self._emit(
                    "supervisor_relaunch", reason="preempt", rc=rc,
                    delay=delay, decision_ts=self.last_relaunch_ts,
                )
                if delay > 0:
                    self.sleep(delay)
                continue
            self._consec_resumable = 0
            self.crashes += 1
            if self.crashes > self.max_restarts:
                self.log(
                    f"[supervisor] giving up: exit code {rc} after "
                    f"{self.max_restarts} crash relaunches"
                )
                self._emit("supervisor_done", rc=rc, gave_up=True)
                return rc
            delay = self.backoff.delay(self.crashes - 1)
            self.log(
                f"[supervisor] crash (exit {rc}); relaunching in "
                f"{delay:.1f}s (crash {self.crashes}/{self.max_restarts})"
            )
            self.last_relaunch_ts = time.time()
            self._emit(
                "supervisor_relaunch", reason="crash", rc=rc, delay=delay,
                decision_ts=self.last_relaunch_ts,
            )
            if delay > 0:
                self.sleep(delay)


def _supervisor_events(env_map, host: int = 0):
    """An EventWriter aimed at the same log tree the child trainer
    writes (DDL_LOG_DIR / DDL_JOB_ID, matching config.py's env-driven
    defaults), so supervisor restart events land in the job's stream.
    The supervisor process must never initialise JAX — the child owns
    the devices — hence ``host`` is passed explicitly (EventWriter's
    host auto-detection goes through ``launch.host_id``).  Returns None
    when the log directory is unwritable (events are telemetry, not a
    reason to refuse supervision)."""
    from ddl_tpu.obs.events import EventWriter

    log_dir = env_map.get("DDL_LOG_DIR", "training_logs")
    job_id = (
        env_map.get("DDL_JOB_ID")
        or env_map.get("TORCHX_JOB_ID")
        or "local"
    ).split("/")[-1]
    try:
        return EventWriter(log_dir, job_id, host=host)
    except OSError as e:
        print(f"[supervisor] obs events disabled ({e})")
        return None


# ---------------------------------------------------------------------------
# fault-spec survival across relaunches (consume-on-fire)
# ---------------------------------------------------------------------------


def _surviving_faults(spec_text: str, state_path) -> str:
    """The DDL_FAULT specs that have NOT been recorded as fired in
    ``state_path`` (one canonical spec key per line, appended by
    ``utils/faultinject.fire`` at exhaustion).  Duplicate identical
    specs are matched one-for-one.  A missing/unreadable state file
    means nothing fired — everything survives (a child that crashed
    before its fault is not a reason to disarm the fault)."""
    from ddl_tpu.utils.faultinject import FaultSpec

    consumed: collections.Counter = collections.Counter()
    try:
        for line in Path(state_path).read_text().splitlines():
            if line.strip():
                consumed[line.strip()] += 1
    except OSError:
        pass
    kept = []
    for part in spec_text.split(","):
        part = part.strip()
        if not part:
            continue
        key = FaultSpec.parse(part).key
        if consumed[key] > 0:
            consumed[key] -= 1
        else:
            kept.append(part)
    return ",".join(kept)


def _prepare_fault_env(child_env: dict, restart_index: int, state_path) -> None:
    """Apply the consume-on-fire relaunch rule to a child environment:
    fired specs are dropped, unfired ones preserved; ``DDL_FAULT_PERSIST``
    pins the full spec instead."""
    if not child_env.get("DDL_FAULT") or child_env.get("DDL_FAULT_PERSIST"):
        return
    if state_path is None:
        # no tracking available: fall back to the conservative rule
        # (injected faults model one-off events)
        if restart_index > 0:
            child_env.pop("DDL_FAULT", None)
        return
    child_env["DDL_FAULT_STATE"] = str(state_path)
    if restart_index > 0:
        kept = _surviving_faults(child_env["DDL_FAULT"], state_path)
        if kept:
            child_env["DDL_FAULT"] = kept
        else:
            child_env.pop("DDL_FAULT", None)
            child_env.pop("DDL_FAULT_STATE", None)


def _fault_state_path(base_env: dict, hint: str):
    """A writable per-run fault-state file, or None when no faults are
    armed (or they are pinned)."""
    if not base_env.get("DDL_FAULT") or base_env.get("DDL_FAULT_PERSIST"):
        return None
    import tempfile

    fd, path = tempfile.mkstemp(prefix=f"ddl_fault_state_{hint}_")
    os.close(fd)
    return path


def supervise_command(
    argv: list[str],
    max_restarts: int = 5,
    env: dict | None = None,
    **kwargs,
) -> int:
    """Supervise ``argv`` as a child process (the CLI's ``--supervise``).

    Each attempt inherits the environment plus the supervision contract
    vars; the child's own auto-resume does the snapshot discovery."""
    base_env = dict(os.environ if env is None else env)
    fault_state = _fault_state_path(base_env, "h0")

    sup_ref: list = []  # filled after construction; attempt closes over it

    def attempt(restart_index: int) -> int:
        child_env = dict(base_env)
        child_env["DDL_SUPERVISED"] = "1"
        child_env["DDL_RESTART_COUNT"] = str(restart_index)
        # escalate the watchdog so a hung collective becomes a relaunch;
        # the operator's explicit setting wins
        child_env.setdefault("DDL_WATCHDOG_ACTION", "exit")
        # restart-latency accounting: the relaunched child stamps its
        # first completed step against the relaunch decision's wall
        # clock (obs `restart_latency` event, emitted by StepTrace); a
        # stale value inherited from an outer supervisor must not leak
        # into attempt 0
        child_env.pop("DDL_RELAUNCH_TS", None)
        if restart_index > 0 and sup_ref and sup_ref[0].last_relaunch_ts:
            child_env["DDL_RELAUNCH_TS"] = repr(sup_ref[0].last_relaunch_ts)
        # consume-on-fire: fired specs are one-off events and do not
        # recur on relaunch; unfired specs (a second preempt@step beyond
        # the resume point) are preserved
        _prepare_fault_env(child_env, restart_index, fault_state)
        return subprocess.call(argv, env=child_env)

    kwargs.setdefault("events", _supervisor_events(base_env))
    sup = Supervisor(attempt, max_restarts=max_restarts, **kwargs)
    sup_ref.append(sup)
    try:
        return sup.run()
    finally:
        if sup.events is not None:
            sup.events.close()
        if fault_state is not None:
            try:
                os.unlink(fault_state)
            except OSError:
                pass


# ---------------------------------------------------------------------------
# pod mode: N hosts, one SPMD world, all-together restarts
# ---------------------------------------------------------------------------


class PodSupervisor:
    """One host's share of a pod-wide coordinated-restart protocol.

    ``spawn_fn(restart_epoch, restart_index)`` launches this host's
    trainer child and returns a handle with ``poll()`` / ``terminate()``
    / ``kill()`` / ``wait(timeout=...)`` (a ``subprocess.Popen`` in
    production; tests inject scripted fakes).  ``rv`` is the shared
    ``coord.Rendezvous``.

    The invariant the protocol maintains: **children of different
    restart epochs never coexist.**  Any host's resumable exit, crash,
    watchdog hang, or aged-out heartbeat leads every host through the
    same sequence — kill the local child, agree on restart epoch E (one
    atomically-created ledger record carrying reason, cumulative crash/
    preemption counts, and the backoff delay), wait at the ``e<E>-join``
    barrier until all hosts have killed theirs, sleep the agreed delay,
    relaunch.  Budget enforcement applies the same rule to the same
    record on every host, so give-up is pod-wide too (``abort.json``).
    A host whose run completes (child exit 0) parks at the epoch's done
    barrier and still joins any restart proposed while it waits — a
    finished host must retrain alongside its peers, because the resumed
    collective needs all of them.
    """

    def __init__(
        self,
        spawn_fn: Callable,
        rv,
        max_restarts: int = 5,
        max_preemptions: int = 1000,
        backoff: Backoff | None = None,
        poll_s: float = 0.05,
        signal_poll_s: float | None = None,
        heartbeat_s: float = 1.0,
        stale_after_s: float = 30.0,
        elastic: bool = False,
        elastic_grace_s: float | None = None,
        rejoin_timeout_s: float | None = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.monotonic,
        log: Callable[[str], None] = print,
        events=None,
    ) -> None:
        self.spawn_fn = spawn_fn
        self.rv = rv
        self.max_restarts = max_restarts
        self.max_preemptions = max_preemptions
        # elastic scale-down: a peer silent past stale_after_s gets an
        # extra grace window to come back before the pod agrees it is
        # PERMANENTLY gone and continues on the survivors; non-elastic
        # pods keep the all-or-nothing protocol (stale peer -> pod
        # restart, absent peer at the join barrier -> abort)
        self.elastic = elastic
        self.elastic_grace_s = (
            2.0 * stale_after_s if elastic_grace_s is None
            else float(elastic_grace_s)
        )
        # elastic scale-up: how long an evicted/returning host keeps its
        # join_request alive waiting for a grow epoch before giving up
        # and exiting the way a plain eviction would (default: the
        # rendezvous timeout — the same patience as a barrier)
        self.rejoin_timeout_s = rejoin_timeout_s
        # (epoch, host) pairs already logged as stale-within-grace, so
        # the hold-the-grace decision is announced once, not per poll
        self._grace_noted: set = set()
        self.backoff = backoff if backoff is not None else Backoff(
            base=1.0, factor=2.0, max_delay=120.0, jitter=0.5
        )
        self.poll_s = poll_s
        # the child is polled at poll_s (local, free); the NAS signals
        # (abort/epoch/intents/heartbeats — four metadata reads) at the
        # slower signal_poll_s, so steady-state supervision doesn't load
        # the same NAS the checkpoints ride on.  The real signal cadence
        # is bounded by heartbeat_s/stale_after_s anyway.
        self.signal_poll_s = (
            10.0 * poll_s if signal_poll_s is None else signal_poll_s
        )
        self.heartbeat_s = heartbeat_s
        self.stale_after_s = stale_after_s
        self.sleep = sleep
        self.clock = clock
        self.log = log
        self.events = events
        self.restarts = 0
        # wall clock of the latest restart decision (the epoch record's
        # proposal stamp — one pod-wide instant, so every host's
        # restart_latency measures against the SAME origin); stamped
        # into relaunched children as DDL_RELAUNCH_TS by
        # supervise_pod_command's spawn
        self.last_relaunch_ts: float | None = None

    def _emit(self, kind: str, **fields) -> None:
        if self.events is not None:
            self.events.emit(kind, pod_host=self.rv.host, **fields)

    def _log(self, msg: str) -> None:
        self.log(f"[pod-supervisor h{self.rv.host}] {msg}")

    # -------------------------------------------------------------- watch

    def _signals(self, epoch: int):
        """A pod-level reason to stop waiting, or None: pod abort, a
        newer restart epoch, a peer's exit intent, a stale peer."""
        rv = self.rv
        ab = rv.aborted()
        if ab is not None:
            return ("abort", ab)
        rec = rv.epoch_record(epoch + 1)
        if rec is not None:
            return ("peer_epoch", rec)
        intents = rv.intents(epoch)
        if intents:
            return ("peer_intent", intents[0])
        if self.elastic and rv.host == rv.leader:
            # scale-up: a non-member published a fresh join_request.
            # Only the leader answers (one proposer, not a racing herd),
            # and the restart boundary it proposes resumes from the last
            # committed snapshot — the "next safe boundary" by
            # construction.  Staler-than-stale_after_s requests are a
            # joiner that died mid-wait; ignored.
            joins = rv.join_requests(fresh_s=self.stale_after_s or None)
            if joins:
                hosts = sorted({int(r["host"]) for r in joins})
                self._emit("peer_join", join_hosts=hosts, epoch=epoch)
                self._log(
                    f"join request(s) from host(s) {hosts}; growing the "
                    "pod at the next restart boundary"
                )
                return ("peer_join", hosts)
        if self.stale_after_s:
            stale = rv.stale_peers(self.stale_after_s)
            if stale and self.elastic:
                # elastic: a stale peer gets elastic_grace_s to come
                # back before eviction.  Restarting the pod meanwhile
                # would not help — staleness means the peer's SUPERVISOR
                # is silent, so it could not rejoin a restart anyway.
                lost = rv.stale_peers(
                    self.stale_after_s + self.elastic_grace_s
                )
                if lost:
                    self._emit("peer_lost", lost_hosts=lost, epoch=epoch)
                    self._log(
                        f"peer(s) {lost} silent past the eviction grace "
                        f"(> {self.stale_after_s + self.elastic_grace_s:.0f}s"
                        "); continuing on the survivors"
                    )
                    return ("peer_lost", lost)
                for h in stale:
                    if (epoch, h) not in self._grace_noted:
                        self._grace_noted.add((epoch, h))
                        self._emit(
                            "peer_stale", stale_host=h, epoch=epoch,
                            in_grace=True,
                        )
                        self._log(
                            f"peer h{h} heartbeat aged out "
                            f"(> {self.stale_after_s:.0f}s); holding "
                            f"{self.elastic_grace_s:.0f}s eviction grace "
                            "before scaling down"
                        )
                return None
            if stale:
                self._emit("peer_stale", stale_host=stale[0], epoch=epoch)
                self._log(
                    f"peer h{stale[0]} heartbeat aged out "
                    f"(> {self.stale_after_s:.0f}s); escalating to pod "
                    "restart instead of hanging in its collective"
                )
                return ("peer_stale", stale[0])
        return None

    def _watch(self, child, epoch: int):
        """Run until the local child exits or a pod signal arrives."""
        last_hb = -float("inf")
        # the first signal poll waits a full signal_poll_s: a freshly
        # relaunched incarnation must get past child startup before a
        # pending join_request (or any other non-fatal signal) can pull
        # the pod through ANOTHER restart — otherwise a joiner that
        # asked during the previous boundary preempts the epoch it was
        # excluded from before that epoch runs a single step
        last_sig = self.clock()
        while True:
            rc = child.poll()
            if rc is not None:
                return ("exit", int(rc))
            now = self.clock()
            if now - last_hb >= self.heartbeat_s:
                self.rv.publish_heartbeat("running", epoch)
                last_hb = now
            if now - last_sig >= self.signal_poll_s:
                sig = self._signals(epoch)
                if sig is not None:
                    return sig
                last_sig = now
            self.sleep(self.poll_s)

    def _wait_done(self, epoch: int):
        """Completed host: park at the done barrier, but keep watching —
        a restart proposed while we wait pulls us back in."""
        rv = self.rv
        rv.publish_heartbeat("done", epoch)
        name = f"done-e{epoch}"
        rv.arrive(name)
        # nothing local to poll here — everything is a NAS read, so the
        # whole loop runs at the slower signal cadence
        while True:
            if rv.barrier_complete(name):
                return ("done", None)
            sig = self._signals(epoch)
            if sig is not None:
                return sig
            self.sleep(self.signal_poll_s)

    def _await_rejoin(self, rec: dict) -> dict | None:
        """Elastic scale-up, joiner side: this host is outside ``rec``'s
        membership (evicted earlier, or a replacement supervisor started
        into a shrunken launch).  Publish a join_request — refreshed
        like a heartbeat, so the leader can tell a live joiner from a
        dead one's leftover marker — and watch the epoch ledger for a
        record whose ``hosts`` re-admits this host.  Returns that record
        (the caller joins it like any other restart epoch), or None when
        the pod aborted/finished or the rejoin timeout lapsed."""
        rv = self.rv
        evict_epoch = int(rec["epoch"])
        self._log(
            f"evicted by restart epoch {evict_epoch} (membership "
            f"{rec.get('hosts')}); publishing join_request and waiting "
            "to be re-admitted"
        )
        self._emit(
            "join_request", epoch=evict_epoch, members=rec.get("hosts"),
        )
        timeout = (
            rv.timeout_s if self.rejoin_timeout_s is None
            else self.rejoin_timeout_s
        )
        deadline = self.clock() + timeout
        last_pub = -float("inf")
        seen = evict_epoch  # newest ledger epoch this joiner has read
        while True:
            now = self.clock()
            if now - last_pub >= self.heartbeat_s:
                rv.publish_join_request(seen)
                rv.publish_heartbeat("joining", seen)
                last_pub = now
            if rv.aborted() is not None or rv.finished() is not None:
                self._log("pod ended while waiting to rejoin; giving up")
                rv.clear_join_request()
                return None
            # scan forward: the pod may restart several times (even
            # shrink further) before an epoch admits us
            while True:
                nxt = rv.epoch_record(seen + 1)
                if nxt is None:
                    break
                seen += 1
                if rv.host in (nxt.get("hosts") or []):
                    self._log(
                        f"re-admitted by restart epoch {seen} "
                        f"(membership {nxt.get('hosts')})"
                    )
                    rv.clear_join_request()
                    return nxt
            if now > deadline:
                self._log(
                    f"no grow epoch admitted this host within "
                    f"{timeout:.0f}s; giving up the rejoin"
                )
                rv.clear_join_request()
                return None
            self.sleep(self.signal_poll_s)

    def _reap(self, child) -> None:
        try:
            if child.poll() is None:
                child.terminate()
                try:
                    child.wait(timeout=10.0)
                except subprocess.TimeoutExpired:
                    child.kill()
                    child.wait(timeout=10.0)
        except (OSError, subprocess.TimeoutExpired):
            pass

    def _finish_abort(self, record: dict) -> int:
        rc = int(record.get("rc", 1))
        self._log(
            f"pod aborted by h{record.get('host')}: "
            f"{record.get('reason')} (exit {rc})"
        )
        self._emit("supervisor_done", rc=rc, gave_up=True, pod_abort=True)
        return rc

    # ---------------------------------------------------------------- run

    def run(self) -> int:
        from ddl_tpu.coord import BarrierTimeout, PodAborted

        rv = self.rv
        # a pre-existing abort marker is STALE state from a previous run
        # of this coord dir: silently returning its rc (or silently
        # clearing it) would hide that coordination never started — be
        # loud and actionable instead
        stale = rv.aborted()
        if stale is not None:
            raise RuntimeError(
                f"coordination dir {rv.root} carries an abort marker from "
                f"a previous run (h{stale.get('host')}: "
                f"{stale.get('reason')}); use a fresh --pod directory per "
                "launch (or delete the old one) so stale markers cannot "
                "poison this pod's protocol"
            )
        self._emit(
            "supervisor_start",
            pod=True,
            hosts=rv.n_hosts,
            max_restarts=self.max_restarts,
            max_preemptions=self.max_preemptions,
        )
        epoch = rv.current_epoch()
        rv.publish_heartbeat("booting", epoch)
        try:
            t0 = self.clock()
            done_ts = rv.barrier("start")
            # completed_ts: the wall-clock instant this host OBSERVED the
            # barrier complete — every host sees it within one poll
            # interval of the same true instant, which is what the
            # obs-side clock-skew fit regresses on (obs/fold.py)
            self._emit(
                "coord_barrier", name="start", wait=self.clock() - t0,
                completed_ts=done_ts, arrive_ts=rv.last_arrive_ts,
            )
        except BarrierTimeout as e:
            ab = rv.abort(f"h{rv.host}: start barrier: {e}", 1)
            return self._finish_abort(ab)
        except PodAborted as e:
            return self._finish_abort(e.record)
        restart_index = 0
        if epoch > 0:
            # starting into a launch that already restarted: adopt the
            # current membership.  A host OUTSIDE it (a replacement, or
            # this same host's supervisor restarted after eviction) is
            # the scale-up entry point — under --elastic it publishes a
            # join_request and waits to be grown back in instead of
            # exiting.
            rec0 = rv.epoch_record(epoch)
            if rec0 is not None:
                try:
                    rv.adopt_membership(rec0.get("hosts") or rv.members)
                except ValueError:
                    if self.elastic:
                        rec0 = self._await_rejoin(rec0)
                    else:
                        self._log(
                            f"evicted by restart epoch {rec0['epoch']} "
                            f"(membership {rec0.get('hosts')}); exiting "
                            "cleanly — the pod continues without us"
                        )
                        rec0 = None
                    if rec0 is None:
                        self._emit(
                            "supervisor_done", rc=0, gave_up=False,
                            evicted=True, epoch=epoch,
                        )
                        return 0
                    status, res = self._join_restart(rec0, epoch)
                    if status == "exit":
                        return res
                    rec0 = res
                    if rec0["delay"] > 0:
                        self.sleep(rec0["delay"])
                    self.last_relaunch_ts = float(
                        rec0.get("ts") or time.time()
                    )
                    epoch = int(rec0["epoch"])
                    restart_index = 1
                    self.restarts = restart_index
        while True:
            ab = rv.aborted()
            if ab is not None:
                return self._finish_abort(ab)
            child = self.spawn_fn(epoch, restart_index)
            self._log(
                f"launched child (restart epoch {epoch}, "
                f"attempt {restart_index})"
            )
            kind, detail = self._watch(child, epoch)
            if kind == "exit" and detail == 0:
                self._log("child complete; waiting for the pod")
                kind, detail = self._wait_done(epoch)
                if kind == "done":
                    self._log("pod complete")
                    # close the launch: coord.acquire_launch refuses to
                    # re-admit hosts into a finished launch's markers, so
                    # a lone relaunch opens a fresh subdir instead of
                    # sailing through this run's start barrier
                    rv.mark_finished(0)
                    self._emit("supervisor_done", rc=0, gave_up=False)
                    return 0
            if kind == "abort":
                self._reap(child)
                return self._finish_abort(detail)

            # ---- coordinate a pod-wide restart -------------------------
            survivors = None  # elastic: a shrunken membership to propose
            if kind == "exit":
                rc = int(detail)
                if (
                    self.elastic and rc == EXIT_REJOIN
                    and len(rv.members) > 1
                ):
                    # voluntary leave-and-return (the rejoin drill): the
                    # child asked to leave the pod, so propose our OWN
                    # eviction — the pod continues at N-1 — and then
                    # take the joiner path to be re-admitted.  Burns no
                    # budget: leaving on purpose is neither a crash nor
                    # a preemption.
                    crash = False
                    preempt = False
                    reason = "rejoin"
                    survivors = [m for m in rv.members if m != rv.host]
                else:
                    crash = rc not in (0, EXIT_PREEMPTED)
                    preempt = rc == EXIT_PREEMPTED
                    reason = "crash" if crash else (
                        "preempt" if preempt else "complete"
                    )
                # tell peers promptly — they kill their children off this
                # marker instead of waiting for our heartbeat to age out
                rv.publish_intent(reason, rc, epoch)
            elif kind == "peer_intent":
                rc = int(detail.get("rc", 1))
                if self.elastic and detail.get("reason") == "rejoin":
                    # the peer is leaving on purpose to rejoin later:
                    # continue without it, no budget consumed — mirrors
                    # the leaver's own classification so the agreed
                    # record is identical whoever wins the proposal race
                    crash = False
                    preempt = False
                    reason = "peer_rejoin"
                    gone = int(detail.get("host", -1))
                    survivors = [m for m in rv.members if m != gone]
                else:
                    # classify from the INTENT (the peer that actually
                    # died), so the crash budget is consumed even when a
                    # bystander host wins the proposal race
                    crash = rc not in (0, EXIT_PREEMPTED)
                    preempt = rc == EXIT_PREEMPTED
                    reason = f"peer_{detail.get('reason', 'exit')}"
                self._reap(child)
            elif kind == "peer_join":
                # elastic scale-UP: the leader observed fresh
                # join_request markers.  Propose the next epoch WITH the
                # joiners — the atomically-created record IS the
                # membership agreement (coord.propose_restart), exactly
                # the shrink protocol run in reverse.
                rc = EXIT_PREEMPTED
                crash = False
                preempt = False
                reason = "peer_join"
                survivors = sorted(set(rv.members) | set(detail))
                self._reap(child)
            elif kind == "peer_lost":
                # elastic eviction: propose the next epoch WITHOUT the
                # lost hosts — the atomically-created record IS the
                # membership agreement (coord.propose_restart)
                rc = EXIT_PREEMPTED
                crash = False
                preempt = True
                reason = "peer_lost"
                gone = set(detail)
                survivors = [m for m in rv.members if m not in gone]
                self._reap(child)
            else:
                rc = EXIT_PREEMPTED
                crash = False
                # a wedged peer consumes the preemption budget, so a host
                # that wedges every epoch eventually aborts the pod
                preempt = kind == "peer_stale"
                reason = kind
                self._reap(child)
            rv.publish_heartbeat("restarting", epoch)
            if kind == "peer_epoch":
                rec = detail
            else:
                try:
                    rec = rv.propose_restart(
                        epoch, reason, crash, preempt, rc=rc,
                        delay_fn=lambda c: self.backoff.delay(c - 1),
                        hosts=survivors,
                    )
                except BarrierTimeout as e:
                    ab = rv.abort(f"h{rv.host}: {e}", 1)
                    return self._finish_abort(ab)
            status, res = self._join_restart(rec, epoch)
            if status == "exit":
                return res
            rec = res
            if rec["delay"] > 0:
                self.sleep(rec["delay"])
            # the restart decision instant: the epoch record's proposal
            # stamp (rv.clock — wall time), identical on every host
            self.last_relaunch_ts = float(rec.get("ts") or time.time())
            epoch = int(rec["epoch"])
            restart_index += 1
            self.restarts = restart_index

    def _join_restart(self, rec: dict, epoch: int):
        """Join the agreed restart epoch ``rec``: adopt its membership,
        enforce the budgets its record carries, and meet the pod at its
        join barrier.  Returns ``("ok", rec)`` with the (possibly
        re-proposed) record to relaunch under, or ``("exit", rc)``.

        This is a loop only in elastic mode, in two directions: a join
        barrier that times out on a host whose supervisor died outright
        is answered by proposing the NEXT epoch over the hosts that DID
        arrive; and a host EVICTED by the adopted record — instead of
        exiting — publishes a join_request and, when a later epoch
        re-admits it, loops back to join that grow epoch."""
        from ddl_tpu.coord import BarrierTimeout, PodAborted

        rv = self.rv
        while True:
            try:
                # the record's membership is the pod's truth: adopt
                # it BEFORE judging the join barrier, so a shrunken
                # epoch only waits on its survivors
                rv.adopt_membership(rec.get("hosts") or rv.members)
            except ValueError:
                if self.elastic:
                    # scale-up, joiner side: stay around, ask back in
                    newrec = self._await_rejoin(rec)
                    if newrec is not None:
                        rec = newrec
                        continue
                else:
                    self._log(
                        f"evicted by restart epoch {rec['epoch']} "
                        f"(membership {rec.get('hosts')}); exiting — the "
                        "pod continues without this host"
                    )
                self._emit(
                    "supervisor_done", rc=0, gave_up=False,
                    evicted=True, epoch=rec["epoch"],
                )
                return ("exit", 0)
            if rec["crashes"] > self.max_restarts:
                # the abort rc comes from the RECORD, not this
                # host's local view: a bystander that adopted a
                # peer's proposal must still surface the crashing
                # child's exit code
                ab = rv.abort(
                    f"crash budget exhausted "
                    f"({rec['crashes']} > {self.max_restarts})",
                    int(rec.get("rc", 1)) if rec.get("crash") else 1,
                )
                return ("exit", self._finish_abort(ab))
            if rec["preemptions"] > self.max_preemptions:
                ab = rv.abort(
                    f"resumable-exit budget exhausted "
                    f"({rec['preemptions']} > {self.max_preemptions})",
                    EXIT_PREEMPTED,
                )
                return ("exit", self._finish_abort(ab))
            self._emit(
                "pod_restart",
                epoch=rec["epoch"],
                reason=rec["reason"],
                proposer=rec["proposer"],
                crashes=rec["crashes"],
                preemptions=rec["preemptions"],
                delay=rec["delay"],
                hosts=rec.get("hosts"),
                world=rec.get("world"),
                # the pod-wide decision instant (epoch-record
                # proposal stamp) — the flow-arrow origin the
                # incident trace draws to every host's join-barrier
                # span
                decision_ts=rec.get("ts"),
            )
            self._log(
                f"joining restart epoch {rec['epoch']} "
                f"(reason={rec['reason']} by h{rec['proposer']}, "
                f"world {rec.get('world', rv.world)}, "
                f"crashes {rec['crashes']}/{self.max_restarts}, "
                f"delay {rec['delay']:.1f}s)"
            )
            # heartbeat while waiting at the join barrier —
            # throttled to heartbeat_s (on_wait fires every poll
            # iteration, and an unthrottled atomic write per poll
            # would load the NAS the signal_poll_s split exists to
            # protect)
            last_hb = [-float("inf")]

            def _hb_while_waiting(epoch=epoch):
                now = self.clock()
                if now - last_hb[0] >= self.heartbeat_s:
                    rv.publish_heartbeat("restarting", epoch)
                    last_hb[0] = now

            join = f"e{rec['epoch']}-join"
            try:
                t0 = self.clock()
                done_ts = rv.barrier(join, on_wait=_hb_while_waiting)
                self._emit(
                    "coord_barrier",
                    name=join,
                    wait=self.clock() - t0,
                    completed_ts=done_ts,
                    arrive_ts=rv.last_arrive_ts,
                )
                return ("ok", rec)
            except BarrierTimeout as e:
                arrivals = rv.barrier_arrivals(join)
                if not self.elastic or not arrivals or (
                    len(arrivals) >= len(rv.members)
                ):
                    # a peer never joined: its supervisor is gone,
                    # and a partial relaunch would just hang — give
                    # the pod up
                    ab = rv.abort(f"h{rv.host}: {e}", 1)
                    return ("exit", self._finish_abort(ab))
                # elastic: the arrived hosts ARE the pod now.  All
                # of them hit this timeout within a poll interval of
                # each other and race the same next-epoch proposal;
                # first writer wins, the rest adopt.
                self._log(
                    f"join barrier {join} timed out with arrivals "
                    f"{arrivals}; proposing continue-on-survivors"
                )
                self._emit(
                    "peer_lost", epoch=rec["epoch"],
                    lost_hosts=[
                        m for m in rv.members if m not in arrivals
                    ],
                    at_barrier=join,
                )
                try:
                    rec = rv.propose_restart(
                        int(rec["epoch"]), "peer_lost",
                        crash=False, preempt=True, rc=EXIT_PREEMPTED,
                        delay_fn=lambda c: self.backoff.delay(c - 1),
                        hosts=arrivals,
                    )
                except BarrierTimeout as e2:
                    ab = rv.abort(f"h{rv.host}: {e2}", 1)
                    return ("exit", self._finish_abort(ab))
                continue
            except PodAborted as e:
                return ("exit", self._finish_abort(e.record))


def supervise_pod_command(
    argv: list[str],
    coord_dir: str | os.PathLike,
    host: int,
    n_hosts: int,
    max_restarts: int = 5,
    env: dict | None = None,
    **kwargs,
) -> int:
    """Pod-mode supervision of ``argv`` (the CLI's ``--supervise --pod``).

    ``coord_dir`` must be one directory every host of the pod sees (the
    checkpoint/log NAS), scoped by job (``/nas/<job>/coord``).  The
    rendezvous state itself is run-scoped below it:
    ``coord.acquire_launch`` places each launch's markers (barriers,
    epoch ledger, abort) in their own ``launches/`` subdir — joined by
    token when the operator/scheduler provides ``DDL_LAUNCH_TOKEN``
    (same value on every host, fresh per launch), else agreed
    leaderlessly by atomic create — so a completed previous run's
    markers can never admit a lone relaunched host into a pod that
    isn't there (it opens a fresh launch, times out at its start
    barrier, and aborts loudly).  An *unfinished* previous launch is
    still joined as-is — relaunching into a crashed pod's directory
    remains "use a fresh --pod dir" territory, and its stale abort
    marker is refused loudly.  Children additionally get the rendezvous
    env (``DDL_COORD_*``, pointing at the launch subdir) so the stall
    watchdog can publish exit intent and ``checkpoint.resolve_resume``
    can run the rank-0 resume agreement, plus ``DDL_RESTART_EPOCH`` for
    obs metadata."""
    from ddl_tpu import coord

    base_env = dict(os.environ if env is None else env)
    try:
        launch_root = coord.acquire_launch(
            coord_dir, token=base_env.get("DDL_LAUNCH_TOKEN")
        )
    except RuntimeError as e:
        # stale DDL_LAUNCH_TOKEN naming a closed launch: an operator
        # error, not a crash — report it without a traceback
        print(f"[pod-supervisor h{host}] {e}", file=sys.stderr)
        return 1
    rv = coord.Rendezvous(
        launch_root, host, n_hosts,
        timeout_s=float(
            base_env.get(coord.ENV_TIMEOUT) or coord.DEFAULT_TIMEOUT_S
        ),
    )
    fault_state = _fault_state_path(base_env, f"h{host}")

    sup_ref: list = []  # filled after construction; spawn closes over it

    def spawn(restart_epoch: int, restart_index: int):
        child_env = dict(base_env)
        child_env["DDL_SUPERVISED"] = "1"
        child_env["DDL_RESTART_COUNT"] = str(restart_index)
        child_env.pop("DDL_RELAUNCH_TS", None)
        if restart_index > 0 and sup_ref and sup_ref[0].last_relaunch_ts:
            # restart-latency origin: the pod-wide restart decision
            # (epoch-record proposal time) — the child's first completed
            # step emits `restart_latency` against it
            child_env["DDL_RELAUNCH_TS"] = repr(sup_ref[0].last_relaunch_ts)
        child_env[coord.ENV_EPOCH] = str(restart_epoch)
        child_env[coord.ENV_DIR] = str(launch_root)
        child_env[coord.ENV_HOSTS] = str(n_hosts)
        child_env[coord.ENV_HOST] = str(host)
        # live membership (elastic scale-down may have shrunk it): the
        # child's own Rendezvous (watchdog intent, resume agreement)
        # must judge barriers over the SAME member set the supervisors
        # agreed, or it would wait on evicted hosts forever
        child_env[coord.ENV_MEMBERS] = ",".join(
            str(m) for m in rv.members
        )
        if rv.world < n_hosts:
            # data-axis respec: survivors renumber contiguously for the
            # SPMD bootstrap (launch.init_distributed reads these) while
            # keeping their original pod host ids for coordination —
            # jax.process_count() shrinks to the agreed world, so
            # parallel/rules.py derives a smaller `data` axis and the
            # data loader re-splits the resumed cursor over survivors
            child_env["DDL_NUM_PROCESSES"] = str(rv.world)
            child_env["DDL_PROCESS_ID"] = str(rv.members.index(host))
        child_env.setdefault("DDL_HOST_ID", str(host))
        child_env.setdefault("DDL_WATCHDOG_ACTION", "exit")
        _prepare_fault_env(child_env, restart_index, fault_state)
        return subprocess.Popen(argv, env=child_env)

    kwargs.setdefault("events", _supervisor_events(base_env, host=host))
    sup = PodSupervisor(
        spawn, rv, max_restarts=max_restarts, **kwargs
    )
    sup_ref.append(sup)
    try:
        return sup.run()
    finally:
        if sup.events is not None:
            sup.events.close()
        if fault_state is not None:
            try:
                os.unlink(fault_state)
            except OSError:
                pass
