"""Pod-level coordination over a shared directory (NAS rendezvous).

On a real TPU pod every host is one SPMD world: ``launch.bootstrap``
runs one ``jax.distributed.initialize`` handshake and the mesh spans all
hosts' chips.  That makes single-host recovery (PR 2's supervisor)
insufficient — a lone restarted process rejoins nothing and hangs at its
first collective while the surviving hosts block in the *previous*
incarnation's all-reduce.  Recovery must be a coordinated, all-hosts-
together event, and at pod scale stalls/stragglers dominate clean
crashes (arXiv:2510.20171), so the coordination layer must also detect a
host that stopped making progress without ever exiting.

This module is that layer, built on the one medium every host of a pod
already shares: the checkpoint/log NAS.  ``Rendezvous`` is a small
marker-file protocol under one directory — no sockets, no leader
election, no extra service — with four primitives:

``hosts/h<i>.json``      liveness heartbeats (wall-clock ts + status +
                         current restart epoch).  A peer whose heartbeat
                         ages past ``stale_after_s`` while "running" is
                         presumed wedged/dead: grounds for escalation
                         instead of an eternal collective hang.
``intents/h<i>.e<E>.json``  exit-intent markers, scoped to restart epoch
                         ``E``.  Published by a supervisor whose child
                         exited, and by the stall watchdog *before* its
                         ``os._exit(75)`` — so peers learn a host is
                         going down even if that host's supervisor is
                         itself wedged.
``epochs/e<E>.json``     the restart-epoch ledger.  Proposing epoch
                         ``E`` is an ``O_CREAT|O_EXCL`` create of
                         ``e<E>.json`` — exactly one proposer wins, and
                         losers adopt the winner's record (reason,
                         cumulative crash/preemption counts, agreed
                         backoff delay).  Hosts can never split-brain on
                         "which restart are we in" or "how long do we
                         back off": both are fields of one atomically-
                         created file.
``barriers/<name>/h<i>`` arrival markers; a barrier completes when all
                         ``n_hosts`` files exist.  Used to make every
                         host kill + rejoin before *any* host relaunches
                         (the relaunch barrier), and to hold completed
                         hosts until the whole pod is done.

plus ``agree/<key>.json`` (rank-0 publishes a value, peers wait — how
the resume snapshot epoch is agreed even when a torn NAS write leaves
hosts seeing different ``latest_valid_epoch``), ``abort.json`` (a
give-up is pod-wide, never one host quietly exiting), and
``joins/h<i>.json`` (elastic scale-UP: a returning/replacement host —
outside the live membership, so invisible to every member-scoped
primitive — asks to be admitted; the leader answers with a restart
epoch whose ledger record carries the GROWN ``hosts`` set).

Atomicity: every marker is written tmp-file + ``os.replace`` (the same
pattern as ``checkpoint.write_manifest``), so readers never observe a
torn JSON.  Heartbeat freshness uses the *writer's* wall clock embedded
in the payload, compared against the reader's — pod hosts are NTP-synced
and ``stale_after_s`` is tens of seconds, so sub-second skew is noise.

This module must stay importable without JAX: it runs in supervisor
processes (which must never initialise the devices their children own)
and inside the watchdog's escalation path.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

__all__ = [
    "BarrierTimeout",
    "PodAborted",
    "Rendezvous",
    "acquire_launch",
    "active_launch_root",
    "agreed_resume_epoch",
    "agreed_rollback_epoch",
    "from_env",
    "publish_exit_intent_from_env",
]

# Environment contract (set by supervise_pod_command for both the
# supervisor's own helpers and the trainer child it spawns):
ENV_DIR = "DDL_COORD_DIR"
ENV_HOSTS = "DDL_COORD_HOSTS"
ENV_HOST = "DDL_COORD_HOST"
ENV_EPOCH = "DDL_RESTART_EPOCH"
ENV_TIMEOUT = "DDL_COORD_TIMEOUT_S"
# Comma-separated live host ids after an elastic scale-down (e.g.
# "0,2").  ENV_HOSTS stays the ORIGINAL pod size and ENV_HOST the
# original host id — membership shrinks, identities do not renumber —
# so host ids in barriers/heartbeats/intents stay stable across
# evictions.
ENV_MEMBERS = "DDL_COORD_MEMBERS"
# How stale an OPEN launch's markers may be before acquire_launch
# refuses to join it (seconds; see _launch_stale).
ENV_LAUNCH_STALE = "DDL_LAUNCH_STALE_S"

DEFAULT_TIMEOUT_S = 300.0
DEFAULT_LAUNCH_STALE_S = 600.0


class BarrierTimeout(RuntimeError):
    """A peer never reached the barrier — its supervisor is gone, not
    merely slow.  The caller aborts the pod rather than hanging."""


class PodAborted(RuntimeError):
    """The pod-wide give-up marker exists; stop waiting."""

    def __init__(self, record: dict) -> None:
        super().__init__(record.get("reason", "pod aborted"))
        self.record = record


def _write_json(path: Path, payload: dict) -> None:
    """Atomic marker write: a reader sees the old file or the new one,
    never a torn line (tmp + rename, the write_manifest pattern).  The
    tmp name carries pid AND thread id so two writers racing on the
    same marker (idempotent ones: barriers, finished) never clobber
    each other's tmp — the in-process pod tests run N "hosts" as
    threads of one pid, where pid alone collides."""
    import threading

    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_name(
        f".{path.name}.{os.getpid()}.{threading.get_ident()}.tmp"
    )
    tmp.write_text(json.dumps(payload))
    os.replace(tmp, path)


def _read_json(path: Path) -> dict | None:
    """None for missing or torn-beyond-parse markers (the writer is
    mid-replace or the NAS flaked; the caller polls again)."""
    try:
        return json.loads(path.read_text())
    except (OSError, ValueError):
        return None


class Rendezvous:
    """One host's handle on the shared coordination directory."""

    def __init__(
        self,
        root: str | os.PathLike,
        host: int,
        n_hosts: int,
        timeout_s: float = DEFAULT_TIMEOUT_S,
        poll_s: float = 0.05,
        sleep=time.sleep,
        clock=time.time,
        members=None,
    ) -> None:
        if not 0 <= host < n_hosts:
            raise ValueError(f"host {host} out of range for {n_hosts}")
        self.root = Path(root)
        self.host = int(host)
        self.n_hosts = int(n_hosts)
        # live membership (elastic scale-down): host ids still in the
        # pod.  ``n_hosts`` stays the ORIGINAL pod size — ids never
        # renumber — while barriers/peers/agreement run over members
        # only.  Default: everyone.
        if members is None:
            members = range(n_hosts)
        self.members = tuple(sorted({int(m) for m in members}))
        if self.host not in self.members:
            raise ValueError(
                f"host {host} not in membership {self.members}"
            )
        if any(not 0 <= m < n_hosts for m in self.members):
            raise ValueError(
                f"membership {self.members} out of range for {n_hosts}"
            )
        self.timeout_s = float(timeout_s)
        self.poll_s = float(poll_s)
        # wall clock, not monotonic: heartbeat ages are compared across
        # processes/hosts, which share NTP time but not a monotonic base
        self.clock = clock
        self.sleep = sleep
        # wall-clock instant of this host's most recent barrier arrival
        # (barrier()/arrive()): paired with barrier()'s completion stamp
        # it bounds the barrier span in ONE clock domain — what the
        # causal trace (obs/trace.py) renders, instead of mixing a
        # monotonic wait duration into wall time
        self.last_arrive_ts: float | None = None
        self.root.mkdir(parents=True, exist_ok=True)

    # --------------------------------------------------------- membership

    @property
    def world(self) -> int:
        """Live pod size — the data-axis world after any scale-down."""
        return len(self.members)

    @property
    def leader(self) -> int:
        """The agreement publisher: the lowest LIVE host id, so rank-0
        duties survive rank 0's own eviction."""
        return self.members[0]

    def adopt_membership(self, hosts) -> None:
        """Shrink, GROW, or restate the live membership — called after
        an epoch record carrying an agreed ``hosts`` set wins the
        ledger race (a grow epoch's set is larger than the current
        one; nothing here is direction-sensitive).  Raises if this host
        is not among the members (its supervisor must exit — or, under
        ``--elastic``, publish a join_request and wait to be grown back
        in instead of relaunching)."""
        members = tuple(sorted({int(h) for h in hosts}))
        if self.host not in members:
            raise ValueError(
                f"host {self.host} evicted by membership {members}"
            )
        if any(not 0 <= m < self.n_hosts for m in members):
            raise ValueError(
                f"membership {members} out of range for {self.n_hosts}"
            )
        self.members = members

    # --------------------------------------------------------- join intake
    #
    # The grow half of elasticity: a returning (or replacement) host is
    # OUTSIDE the live membership, so none of the member-scoped
    # primitives can carry its voice — its heartbeats are invisible and
    # it may not arrive at barriers.  It announces itself through a
    # dedicated ``joins/h<i>.json`` marker instead; the leader folds
    # pending requests into the next restart epoch's ``hosts`` set (the
    # same atomically-created ledger record that agrees shrink
    # memberships agrees grown ones), and the joiner watches the ledger
    # for an epoch that admits it.

    def publish_join_request(self, epoch: int, **fields) -> None:
        """Ask to be (re-)admitted to the pod.  ``epoch`` is the newest
        restart epoch the joiner has observed.  Refreshed periodically
        while waiting — the leader ignores requests whose writer went
        silent (``fresh_s`` below), so a joiner that died after asking
        cannot drag the pod through a grow epoch it will never join."""
        _write_json(
            self.root / "joins" / f"h{self.host:03d}.json",
            {
                "ts": self.clock(),
                "host": self.host,
                "epoch": int(epoch),
                **fields,
            },
        )

    def join_requests(self, fresh_s: float | None = None) -> list[dict]:
        """Pending join requests from live NON-members (a member's
        leftover marker is void by definition), each with an ``age``;
        requests staler than ``fresh_s`` are dropped."""
        joins_dir = self.root / "joins"
        if not joins_dir.is_dir():
            return []
        now = self.clock()
        out = []
        for p in sorted(joins_dir.glob("h*.json")):
            rec = _read_json(p)
            if rec is None:
                continue
            h = int(rec.get("host", -1))
            if h in self.members or not 0 <= h < self.n_hosts:
                continue
            rec["age"] = now - float(rec.get("ts", 0.0))
            if fresh_s is not None and rec["age"] > fresh_s:
                continue
            out.append(rec)
        return out

    def clear_join_request(self, host: int | None = None) -> None:
        """Withdraw a join request (the joiner's own, by default) —
        called once an epoch record admits the host, or when it gives
        up.  Best-effort: a leftover marker from an admitted host is
        filtered by ``join_requests`` anyway."""
        h = self.host if host is None else int(host)
        try:
            (self.root / "joins" / f"h{h:03d}.json").unlink()
        except OSError:
            pass

    # ------------------------------------------------------------ liveness

    def publish_heartbeat(self, status: str, epoch: int, **fields) -> None:
        _write_json(
            self.root / "hosts" / f"h{self.host:03d}.json",
            {
                "ts": self.clock(),
                "host": self.host,
                "pid": os.getpid(),
                "status": status,
                "epoch": int(epoch),
                **fields,
            },
        )

    def peers(self) -> dict[int, dict]:
        """Other LIVE hosts' latest heartbeats, keyed by host id, each
        with an ``age`` (seconds since the writer stamped it).  Evicted
        hosts' leftover heartbeat files are invisible — a scaled-down
        pod must not keep re-judging its casualty."""
        out: dict[int, dict] = {}
        hosts_dir = self.root / "hosts"
        if not hosts_dir.is_dir():
            return out
        now = self.clock()
        for p in hosts_dir.iterdir():
            rec = _read_json(p)
            if rec is None or rec.get("host") == self.host:
                continue
            if int(rec.get("host", -1)) not in self.members:
                continue
            rec["age"] = now - float(rec.get("ts", 0.0))
            out[int(rec["host"])] = rec
        return out

    def stale_peers(self, stale_after_s: float) -> list[int]:
        """Peers presumed wedged or dead: still marked ``running`` but
        silent past the deadline.  Hosts in any other status ("done",
        "restarting", "booting") are between beats by design and judged
        by barriers instead."""
        return sorted(
            h for h, rec in self.peers().items()
            if rec.get("status") == "running"
            and rec["age"] > stale_after_s
        )

    # --------------------------------------------------------- exit intent

    def publish_intent(self, reason: str, rc: int, epoch: int) -> None:
        """Announce this host is going down (or its child already did).
        Scoped to the restart epoch so a stale intent from a previous
        incarnation cannot retrigger a restart after everyone moved on."""
        _write_json(
            self.root / "intents" / f"h{self.host:03d}.e{int(epoch)}.json",
            {
                "ts": self.clock(),
                "host": self.host,
                "reason": reason,
                "rc": int(rc),
                "epoch": int(epoch),
            },
        )

    def intents(self, epoch: int, include_self: bool = False) -> list[dict]:
        intents_dir = self.root / "intents"
        if not intents_dir.is_dir():
            return []
        out = []
        for p in sorted(intents_dir.glob(f"*.e{int(epoch)}.json")):
            rec = _read_json(p)
            if rec is None:
                continue
            if not include_self and rec.get("host") == self.host:
                continue
            out.append(rec)
        return out

    # ------------------------------------------------- restart-epoch ledger

    def _epoch_path(self, epoch: int) -> Path:
        return self.root / "epochs" / f"e{int(epoch)}.json"

    def epoch_record(self, epoch: int) -> dict | None:
        return _read_json(self._epoch_path(epoch))

    def current_epoch(self) -> int:
        """Highest restart epoch any host has proposed (0 = the initial
        launch, which has no ledger entry)."""
        epochs_dir = self.root / "epochs"
        if not epochs_dir.is_dir():
            return 0
        best = 0
        for p in epochs_dir.glob("e*.json"):
            try:
                best = max(best, int(p.stem[1:]))
            except ValueError:
                continue
        return best

    def propose_restart(
        self,
        cur_epoch: int,
        reason: str,
        crash: bool,
        preempt: bool,
        rc: int = 1,
        delay_fn=None,
        hosts=None,
    ) -> dict:
        """First-writer-wins proposal of restart epoch ``cur_epoch + 1``.

        The winning record carries everything the pod must agree on to
        avoid split-brain: cumulative crash/preemption counts (rolled
        forward from the previous epoch's record) and the backoff delay
        every host sleeps before relaunching (``delay_fn(crash_count)``,
        computed once by the proposer — N hosts must not each draw their
        own jitter).  Losers adopt the winner's record unchanged, even if
        they raced with a different reason: one restart event, one
        classification.

        ``hosts`` (elastic) proposes a CHANGED membership — shrunken
        (scale-down: survivors of an eviction) or GROWN (scale-up: the
        current members plus admitted joiners, see ``join_requests``):
        the record carries the agreed live host set and world size, and
        because the record is atomically created, the membership
        agreement rides the same first-writer-wins ledger — no second
        agreement round, no split-brain window between "which epoch" and
        "who is still in it", in either direction.  Omitted, the
        proposer's current membership is recorded (a plain same-world
        restart)."""
        nxt = int(cur_epoch) + 1
        prev = self.epoch_record(cur_epoch) if cur_epoch else None
        crashes = (prev or {}).get("crashes", 0) + (1 if crash else 0)
        preemptions = (prev or {}).get("preemptions", 0) + (
            1 if preempt else 0
        )
        delay = float(delay_fn(crashes) if (crash and delay_fn) else 0.0)
        members = (
            sorted({int(h) for h in hosts}) if hosts is not None
            else list(self.members)
        )
        record = {
            "ts": self.clock(),
            "epoch": nxt,
            "proposer": self.host,
            "reason": reason,
            "crash": bool(crash),
            # the triggering exit code rides in the record so budget
            # aborts carry it no matter WHICH host trips the budget (an
            # adopting bystander must not replace rc=7 with a generic 1)
            "rc": int(rc),
            "crashes": int(crashes),
            "preemptions": int(preemptions),
            "delay": delay,
            "hosts": members,
            "world": len(members),
        }
        path = self._epoch_path(nxt)
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_name(f".{path.name}.{self.host}.tmp")
        tmp.write_text(json.dumps(record))
        try:
            # hard link onto the final name: atomic create-if-absent even
            # on NFS (O_EXCL open is not reliably atomic there)
            os.link(tmp, path)
        except FileExistsError:
            # lost the race: the winner's record is the pod's truth
            os.unlink(tmp)
            won = None
            deadline = self.clock() + self.timeout_s
            while won is None:  # the winner may still be mid-replace
                won = _read_json(path)
                if won is None:
                    if self.clock() > deadline:
                        raise BarrierTimeout(
                            f"unreadable epoch record {path}"
                        )
                    self.sleep(self.poll_s)
            return won
        os.unlink(tmp)
        return record

    # ------------------------------------------------------------ barriers

    def barrier(
        self, name: str, timeout_s: float | None = None, on_wait=None
    ) -> float:
        """Mark arrival and wait until all ``n_hosts`` arrive; returns
        the wall-clock instant THIS host observed the barrier complete.
        All hosts observe completion within one poll interval of the
        same true instant, which makes the returned stamp the input to
        the cross-host clock-skew fit (``obs/fold.estimate_clock_offsets``
        — per-host offsets are least squares over the shared barriers).
        Raises ``BarrierTimeout`` if a peer never shows (its supervisor
        is gone — the caller aborts the pod instead of hanging the way
        the collective it replaces would have), and ``PodAborted`` if
        the give-up marker appears while waiting."""
        d = self.root / "barriers" / name
        self.last_arrive_ts = self.clock()
        _write_json(d / f"h{self.host:03d}", {"ts": self.last_arrive_ts})
        deadline = self.clock() + (
            self.timeout_s if timeout_s is None else timeout_s
        )
        while True:
            missing = self._missing_members(d)
            if not missing:
                return self.clock()
            ab = self.aborted()
            if ab is not None:
                raise PodAborted(ab)
            if self.clock() > deadline:
                raise BarrierTimeout(
                    f"barrier {name!r}: "
                    f"{len(self.members) - len(missing)}/{len(self.members)}"
                    " hosts after "
                    f"{self.timeout_s if timeout_s is None else timeout_s:.0f}s"
                    f" (missing {missing})"
                )
            if on_wait is not None:
                on_wait()
            self.sleep(self.poll_s)

    def _missing_members(self, barrier_dir: Path) -> list[int]:
        """Live members with no arrival marker yet.  Presence is judged
        per member id (not a count): an evicted host's stale marker in a
        reused barrier name must neither complete a barrier early nor
        block one."""
        return [
            m for m in self.members
            if not (barrier_dir / f"h{m:03d}").exists()
        ]

    def barrier_arrivals(self, name: str) -> list[int]:
        """Host ids with an arrival marker at ``name`` (members only) —
        what an elastic supervisor scales down to when the join barrier
        times out on a host whose supervisor died outright."""
        d = self.root / "barriers" / name
        if not d.is_dir():
            return []
        return [
            m for m in self.members if (d / f"h{m:03d}").exists()
        ]

    def arrive(self, name: str) -> None:
        """Mark arrival at a barrier WITHOUT waiting (callers that must
        keep watching other signals poll ``barrier_complete``)."""
        self.last_arrive_ts = self.clock()
        _write_json(
            self.root / "barriers" / name / f"h{self.host:03d}",
            {"ts": self.last_arrive_ts},
        )

    def barrier_complete(self, name: str) -> bool:
        d = self.root / "barriers" / name
        return d.is_dir() and not self._missing_members(d)

    # ----------------------------------------------- rank-0 value agreement

    def agree(self, key: str, compute_fn, timeout_s: float | None = None):
        """The LEADER (lowest live host id — rank 0 until rank 0 is
        evicted) computes and publishes a value; every other host waits
        for it and returns the same value.  The single-decider shape that
        keeps a torn NAS view (hosts disagreeing on ``latest_valid_epoch``)
        from restoring different snapshots on different hosts."""
        path = self.root / "agree" / f"{key}.json"
        if self.host == self.leader:
            value = compute_fn()
            _write_json(path, {"ts": self.clock(), "value": value})
            return value
        deadline = self.clock() + (
            self.timeout_s if timeout_s is None else timeout_s
        )
        while True:
            rec = _read_json(path)
            if rec is not None and "value" in rec:
                return rec["value"]
            ab = self.aborted()
            if ab is not None:
                raise PodAborted(ab)
            if self.clock() > deadline:
                raise BarrierTimeout(
                    f"host 0 never published agreement {key!r}"
                )
            self.sleep(self.poll_s)

    # ------------------------------------------------------------ finished

    def mark_finished(self, rc: int, reason: str = "complete") -> dict:
        """Stamp this launch as over (clean completion).  First writer
        wins, like ``abort``; ``acquire_launch`` treats a launch with
        either marker as closed, so a lone relaunched host can never
        join a completed run's stale barriers."""
        path = self.root / "finished.json"
        existing = _read_json(path)
        if existing is not None:
            return existing
        record = {
            "ts": self.clock(),
            "host": self.host,
            "reason": reason,
            "rc": int(rc),
        }
        _write_json(path, record)
        return record

    def finished(self) -> dict | None:
        return _read_json(self.root / "finished.json")

    # --------------------------------------------------------------- abort

    def abort(self, reason: str, rc: int) -> dict:
        """Pod-wide give-up.  First writer wins; later aborts keep the
        original record (one coherent story in the logs)."""
        path = self.root / "abort.json"
        existing = _read_json(path)
        if existing is not None:
            return existing
        record = {
            "ts": self.clock(),
            "host": self.host,
            "reason": reason,
            "rc": int(rc),
        }
        _write_json(path, record)
        return record

    def aborted(self) -> dict | None:
        return _read_json(self.root / "abort.json")


# ---------------------------------------------------------------------------
# run-scoped rendezvous state (launch-token subdirs)
# ---------------------------------------------------------------------------


def _launch_closed(root: Path) -> bool:
    return (root / "finished.json").is_file() or (root / "abort.json").is_file()


def _launch_stale(root: Path, stale_after_s: float) -> bool:
    """An OPEN launch whose markers have all gone silent: every
    heartbeat's writer-stamped ts (and the creation stamp) is older than
    ``stale_after_s``.  Such a launch is a dead pod's leftover — its
    supervisors crashed without closing it — and joining it would trust
    fully-arrived barriers no live peer will ever re-cross (the same
    hang ``acquire_launch`` scoping defused for CLOSED launches).  A
    launch with no markers at all is a peer mid-creation, not stale."""
    newest = None
    for p in (root / "hosts").glob("h*.json") if (
        root / "hosts"
    ).is_dir() else ():
        rec = _read_json(p)
        if rec is not None:
            ts = float(rec.get("ts", 0.0))
            newest = ts if newest is None else max(newest, ts)
    if newest is None:
        rec = _read_json(root / "launch.json")
        if rec is None:
            return False  # nothing written yet: a fresh launch, joinable
        newest = float(rec.get("ts", 0.0))
    return (time.time() - newest) > stale_after_s


def acquire_launch(
    pod_dir: str | os.PathLike,
    token: str | None = None,
    stale_after_s: float | None = None,
) -> Path:
    """The rendezvous root for THIS launch: a token subdir under
    ``<pod_dir>/launches/``, so one ``--pod`` directory can serve
    successive launches without stale markers crossing between them.

    The failure this closes (ROADMAP): the protocol's markers describe
    one pod lifetime, and with everything at the pod root a lone host
    relaunched after a COMPLETED run would sail through the previous
    run's fully-arrived start barrier and hang alone at its first
    collective.  Scoped, that host opens a *new* launch subdir (the old
    one carries ``finished.json``/``abort.json``), waits at a fresh
    start barrier its absent peers never arrive at, and aborts loudly.

    With an explicit ``token`` (the ``DDL_LAUNCH_TOKEN`` env —
    a scheduler incarnation id the operator guarantees is shared across
    hosts and fresh per launch) the subdir is exactly that token.
    Otherwise hosts agree leaderlessly: join the highest-numbered launch
    that is not yet closed, else atomically ``mkdir`` the next number —
    losers of the create race re-read and join the winner's.

    An UNFINISHED launch is only joinable while its markers are alive:
    heartbeat ages are re-validated first (``stale_after_s``, default
    ``DDL_LAUNCH_STALE_S`` env or 10 minutes), so a host restarted after
    every supervisor of a crashed pod is long gone opens a fresh launch
    (numbered path) or errors loudly (explicit token) instead of sailing
    into the dead pod's rendezvous state."""
    launches = Path(pod_dir) / "launches"
    launches.mkdir(parents=True, exist_ok=True)
    if stale_after_s is None:
        try:
            stale_after_s = float(
                os.environ.get(ENV_LAUNCH_STALE)
                or DEFAULT_LAUNCH_STALE_S
            )
        except ValueError:
            stale_after_s = DEFAULT_LAUNCH_STALE_S
    if token:
        d = launches / f"t-{token}"
        if _launch_closed(d):
            # same staleness the numbered path defuses: a host relaunched
            # with the finished run's token must not re-enter its barriers
            raise RuntimeError(
                f"launch token {token!r} names a finished/aborted launch "
                f"({d}) — DDL_LAUNCH_TOKEN must be fresh per launch; "
                "refusing to rejoin a closed run's rendezvous state"
            )
        if d.is_dir() and _launch_stale(d, stale_after_s):
            raise RuntimeError(
                f"launch token {token!r} names an open launch ({d}) whose "
                f"markers have been silent > {stale_after_s:.0f}s — the "
                "pod that owned it is gone.  Use a fresh DDL_LAUNCH_TOKEN "
                "(or raise DDL_LAUNCH_STALE_S if the pod is merely slow); "
                "refusing to trust a dead launch's barriers"
            )
        d.mkdir(exist_ok=True)
        return d
    while True:
        nums = sorted(
            int(p.name[1:]) for p in launches.glob("L*")
            if p.name[1:].isdigit()
        )
        cur = nums[-1] if nums else 0
        if cur:
            d = launches / f"L{cur:04d}"
            if not _launch_closed(d) and not _launch_stale(
                d, stale_after_s
            ):
                return d
        nxt = launches / f"L{cur + 1:04d}"
        try:
            nxt.mkdir()
        except FileExistsError:
            continue  # lost the create race: re-read, join the winner's
        _write_json(
            nxt / "launch.json",
            {"ts": time.time(), "creator_pid": os.getpid()},
        )
        return nxt


def active_launch_root(pod_dir: str | os.PathLike) -> Path | None:
    """The newest launch subdir of a ``--pod`` directory (for
    inspection/tests), or None when nothing ever launched there."""
    launches = Path(pod_dir) / "launches"
    if not launches.is_dir():
        return None
    dirs = [p for p in launches.iterdir() if p.is_dir()]
    # newest by creation order (mtime), so numbered and token launches
    # rank together
    return max(dirs, key=lambda p: p.stat().st_mtime, default=None)


# ---------------------------------------------------------------------------
# environment-driven entry points (trainer children, watchdog escalation)
# ---------------------------------------------------------------------------


def from_env(env=os.environ) -> Rendezvous | None:
    """The rendezvous this process belongs to, or None outside pod mode.
    ``supervise_pod_command`` sets the env for both the supervisor's own
    helpers and the trainer child it spawns.  ``DDL_COORD_MEMBERS``
    (set after an elastic scale-down) restricts barriers/agreement to
    the surviving hosts while ids keep their original numbering."""
    root = env.get(ENV_DIR)
    if not root:
        return None
    n_hosts = int(env.get(ENV_HOSTS) or 1)
    host = int(env.get(ENV_HOST) or env.get("DDL_HOST_ID") or 0)
    timeout = float(env.get(ENV_TIMEOUT) or DEFAULT_TIMEOUT_S)
    members = None
    raw = env.get(ENV_MEMBERS)
    if raw:
        try:
            members = [int(x) for x in raw.split(",") if x.strip() != ""]
        except ValueError:
            members = None  # malformed: fall back to full membership
    return Rendezvous(
        root, host, n_hosts, timeout_s=timeout, members=members
    )


def restart_epoch(env=os.environ) -> int:
    """The pod restart epoch this process was launched under (0 for the
    initial launch / non-pod runs) — stamped into obs metadata."""
    try:
        return int(env.get(ENV_EPOCH) or 0)
    except ValueError:
        return 0


def publish_exit_intent_from_env(reason: str, rc: int) -> bool:
    """Best-effort exit-intent publication for escalation paths that are
    about to hard-exit (the stall watchdog's ``os._exit(75)``): peers'
    supervisors react to the marker instead of waiting for this host's
    heartbeat to age out.  No-op outside pod mode; NOTHING here may
    escape — the caller is about to ``os._exit`` a wedged process, and
    an exception (unwritable NAS, malformed env) that aborts the
    escalation leaves the hang this path exists to break."""
    try:
        rv = from_env()
        if rv is None:
            return False
        rv.publish_intent(reason, rc, restart_epoch())
        return True
    # deliberate catch-all: see the docstring — failing to publish must
    # degrade to heartbeat-ageout detection, never to a live hang
    except Exception:  # ddl-lint: disable=broad-except
        return False


def agreed_resume_epoch(job_id: str, compute_fn):
    """Pod-consistent resume target: rank 0 computes (its view of
    ``checkpoint.latest_valid_epoch``) and publishes through the
    rendezvous; every host restores the same snapshot.  Scoped by restart
    epoch so each coordinated relaunch re-agrees against the then-current
    snapshot store.  Falls back to the local computation outside pod mode
    or on a single-host pod."""
    rv = from_env()
    if rv is None or rv.world < 2:
        return compute_fn()
    key = f"resume-{job_id}-e{restart_epoch()}"
    return rv.agree(key, compute_fn)


def agreed_rollback_epoch(job_id: str, compute_fn, seq: int):
    """Pod-consistent IN-LOOP rollback target (the NaN-recovery path):
    the leader computes which snapshot to roll back to and publishes it;
    every host restores the same one.  The same single-decider shape as
    ``agreed_resume_epoch``, but rollback can fire repeatedly within one
    incarnation and ``agree`` keys are write-once — so the key carries a
    per-process rollback sequence number.  ``seq`` is identical across
    hosts because the rollback decision is SPMD: every host sees the
    same non-finite loss at the same step, so their counters advance in
    lock-step.  Falls back to the local computation outside pod mode."""
    rv = from_env()
    if rv is None or rv.world < 2:
        return compute_fn()
    key = f"rollback-{job_id}-e{restart_epoch()}-{int(seq)}"
    return rv.agree(key, compute_fn)
