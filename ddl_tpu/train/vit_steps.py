"""Jitted train/eval steps for the ViT family (models/vit.py).

Same SPMD pattern as the LM steps (``train/lm_steps.py``): parameter
placement from logical-axis annotations over a ``(data, model)`` mesh —
batch sharded over ``data``, attention heads / MLP hidden over ``model``
(TP), optional FSDP — one jitted, donated step.  Input is the CNN data
path's uint8 batch; /255 normalisation runs on device (``ops/image.py``)
so the wire format matches the DenseNet trainer's.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddl_tpu.models.vit import ViT, ViTConfig
from ddl_tpu.ops import normalize_images
from ddl_tpu.ops.losses import cross_entropy_loss
# Jit-boundary batch spec + the family rule table come from the
# partition-rule engine — this module is lint-banned from hand-writing
# PartitionSpec axis literals (astlint 'pspec-hand-rolled').
from ddl_tpu.parallel.rules import IMAGE_SPEC, PIPELINE_SCHEDULES, vit_rules
from ddl_tpu.parallel.sharding import (
    LMMeshSpec,
    build_lm_mesh,
    lm_logical_rules,
    validate_kv_head_sharding,
)

__all__ = ["ViTTrainState", "ViTStepFns", "IMAGE_SPEC", "make_vit_step_fns"]


class ViTTrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: optax.OptState


class ViTStepFns(NamedTuple):
    """train(state, images_u8, labels) -> (state, metrics);
    evaluate(state, images_u8) -> logits; init_state() -> sharded state.
    ``train`` donates its state argument — always rebind."""

    train: Callable
    evaluate: Callable
    init_state: Callable
    mesh: Mesh


def make_vit_step_fns(
    cfg: ViTConfig,
    spec: LMMeshSpec,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    batch: int,
    devices=None,
    num_microbatches: int = 0,
    accum_steps: int = 1,
    pipeline_schedule: str = "gpipe",
    virtual_stages: int = 1,
    zero_sharding: bool = False,
) -> ViTStepFns:
    if spec.seq > 1 or spec.expert > 1:
        raise ValueError(
            "ViT steps shard over (data, model, pipe); got "
            f"seq={spec.seq} expert={spec.expert}"
        )
    validate_kv_head_sharding(cfg.block_config(), spec)
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if pipeline_schedule not in PIPELINE_SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {pipeline_schedule!r}")
    if spec.pipe > 1:
        if accum_steps > 1:
            raise ValueError(
                "accum_steps > 1 is the non-pipelined path's microbatching; "
                "with spec.pipe > 1 use num_microbatches instead"
            )
        if zero_sharding:
            raise ValueError(
                "zero_sharding requires the flat (non-pipelined) ViT "
                "step (the pipeline optimizer runs inside a manual "
                "shard_map region)"
            )
        return _make_vit_pipeline_step_fns(
            cfg, spec, tx, rng, batch,
            num_microbatches=num_microbatches or spec.pipe,
            devices=devices,
            schedule=pipeline_schedule,
            virtual_stages=virtual_stages,
        )
    if pipeline_schedule != "gpipe":
        raise ValueError(
            f"pipeline_schedule={pipeline_schedule!r} requires a pipe mesh "
            "axis (spec.pipe > 1)"
        )
    if virtual_stages != 1:
        raise ValueError(
            f"virtual_stages={virtual_stages} requires a pipe mesh axis "
            "(spec.pipe > 1)"
        )
    if num_microbatches > 1:
        raise ValueError("num_microbatches needs spec.pipe > 1")
    if accum_steps > 1 and (
        batch % accum_steps or (batch // accum_steps) % spec.data
    ):
        raise ValueError(
            f"batch {batch} must split into accum_steps={accum_steps} chunks "
            f"divisible by mesh data={spec.data}"
        )
    if batch % spec.data:
        raise ValueError(f"batch {batch} must divide by mesh data={spec.data}")
    mesh = build_lm_mesh(spec, devices)
    rules = lm_logical_rules(cfg.fsdp)
    model = ViT(cfg)
    dummy = jnp.zeros((batch, cfg.image_size, cfg.image_size, 3), jnp.float32)

    def init_params(rng):
        return model.init(rng, dummy)["params"]

    abs_params = jax.eval_shape(init_params, rng)
    # parameter placement from the family rule table (parallel/rules.py)
    # — the former patch/pos-embedding contract waivers are explicit
    # replication rules there
    table = vit_rules(cfg.fsdp)
    abs_unboxed = nn.meta.unbox(abs_params)
    param_specs = table.specs(abs_unboxed)
    param_shardings = table.shardings(abs_unboxed, mesh)
    if zero_sharding:
        from ddl_tpu.train.fused_optim import with_zero

        tx = with_zero(tx, mesh, param_specs)

    def create_state(rng):
        params = nn.meta.unbox(init_params(rng))
        params = jax.lax.with_sharding_constraint(params, param_shardings)
        return ViTTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )

    def forward(params, images, step=None):
        from ddl_tpu.train.lm_steps import dropout_kwargs

        kw = dropout_kwargs(rng, step, cfg.dropout_rate)
        x = normalize_images(images, cfg.dtype)
        with nn.logical_axis_rules(rules):
            return model.apply(
                {"params": params},
                x,
                deterministic=kw["deterministic"],
                rngs=kw["rngs"],
            )

    return _finalize_vit(mesh, tx, forward, create_state, rng,
                         accum_steps=accum_steps, contract=table.contract(),
                         probe_inputs=_vit_probe_inputs(cfg))


def _vit_probe_inputs(cfg: ViTConfig):
    """Abstract batch structs for the compiled-IR probes
    (analysis/hlolint.py) — the family knows its image extent from the
    config, so two-shape lowering needs only a batch size."""
    return lambda n=8: (
        jax.ShapeDtypeStruct(
            (n, cfg.image_size, cfg.image_size, 3), jnp.uint8
        ),
        jax.ShapeDtypeStruct((n,), jnp.int32),
    )


def _finalize_vit(mesh, tx, forward, create_state, rng,
                  accum_steps: int = 1, manual_grad_fn=None,
                  contract: dict | None = None,
                  probe_inputs=None) -> ViTStepFns:
    """Shared jit tail for the plain and pipelined ViT paths: wraps a
    ``forward(params, images, step=None) -> logits`` (``step`` drives the
    train-mode dropout rng; eval passes nothing) and a
    ``create_state(rng)``.  ``accum_steps > 1``: gradient accumulation
    over equal batch chunks inside one jitted step (identical update to
    the full-batch step; see ``lm_steps.finalize_step_fns``).
    ``manual_grad_fn(params, images, labels, step) -> (grads, metrics)``
    replaces autodiff in the train step (the 1F1B pipeline schedule);
    ``forward`` still drives evaluation."""

    def loss_fn(params, images, labels, step=None):
        logits = forward(params, images, step)
        loss = cross_entropy_loss(logits, labels)
        acc = (jnp.argmax(logits, -1) == labels).mean()
        return loss, (logits, {"loss": loss, "accuracy": acc})

    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    # `nan@grad:K` fault injection, compiled into the jitted step (see
    # lm_steps.finalize_step_fns — same consume-at-build semantics)
    from ddl_tpu.train.lm_steps import poison_nan_grads
    from ddl_tpu.utils import faultinject

    nan_grad_step = faultinject.traced_nan_step()
    # single-pass fused Adam + ZeRO constraints, as in the LM tail
    fused_apply = getattr(tx, "fused_apply", None)

    def train_step(state, images, labels):
        if manual_grad_fn is not None:
            grads, metrics = manual_grad_fn(
                state.params, images, labels, state.step
            )
        elif accum_steps == 1:
            (_, (_, metrics)), grads = grad_fn(
                state.params, images, labels, state.step
            )
        else:
            from ddl_tpu.train.lm_steps import accumulate_grads

            k = accum_steps
            b = images.shape[0]
            # the chunked batch is IMAGE_SPEC with a leading scan axis
            # (trailing dims replicate implicitly)
            chunk_sh = NamedSharding(mesh, P(None, *IMAGE_SPEC))
            img_c = jax.lax.with_sharding_constraint(
                images.reshape(k, b // k, *images.shape[1:]), chunk_sh
            )
            lab_c = jax.lax.with_sharding_constraint(
                labels.reshape(k, b // k), chunk_sh
            )
            steps = state.step * k + jnp.arange(k)
            grads, metrics = accumulate_grads(
                grad_fn, state.params, (img_c, lab_c, steps), k
            )
        grads = poison_nan_grads(state.step, grads, nan_grad_step)
        if fused_apply is not None:
            new_params, new_opt = fused_apply(
                grads, state.opt_state, state.params
            )
        else:
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
        return (
            state.replace(
                step=state.step + 1,
                params=new_params,
                opt_state=new_opt,
            ),
            metrics,
        )

    def eval_step(state, images):
        return forward(state.params, images)

    img_sharding = NamedSharding(mesh, IMAGE_SPEC)
    replicated = NamedSharding(mesh, P())

    from ddl_tpu.parallel.mesh import with_ambient_mesh

    def _with_mesh(fn):
        return with_ambient_mesh(mesh, fn)

    train = _with_mesh(jax.jit(
        train_step,
        in_shardings=(None, img_sharding, img_sharding),
        out_shardings=(None, replicated),
        donate_argnums=(0,),
    ))
    # sharding contract for `ddl_tpu lint` (analysis/contracts.py),
    # derived from the family rule table — the patch/position embeddings
    # replicate by explicit rule there (formerly hand-spec waivers), so
    # the checker reads the table instead of a waiver list.
    _zero = getattr(tx, "zero", None)
    train.contract = dict(
        contract if contract is not None else vit_rules().contract(),
        fused_optimizer_update=fused_apply is not None,
        zero_sharding=_zero is not None,
        zero_threshold=_zero.resolved_threshold() if _zero is not None else None,
    )
    train.probe_inputs = probe_inputs
    return ViTStepFns(
        train=train,
        evaluate=_with_mesh(jax.jit(
            eval_step, in_shardings=(None, img_sharding),
        )),
        init_state=lambda: _with_mesh(jax.jit(create_state))(rng),
        mesh=mesh,
    )


def _make_vit_pipeline_step_fns(
    cfg: ViTConfig,
    spec: LMMeshSpec,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    batch: int,
    num_microbatches: int,
    devices=None,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
) -> ViTStepFns:
    """Pipeline-parallel ViT: the encoder blocks run as a GPipe schedule
    over the ``pipe`` mesh axis (the shared clock loop,
    ``parallel/lm_pipeline.py::make_blocks_pipeline``) with stage-stacked,
    pipe-sharded params; the patch embedding and pooled head run outside
    the manual region in plain GSPMD land.  Composes with DP over ``data``
    and TP over ``model`` — the DP x PP hybrid of the reference's
    north-star config (``ddp_n_pp.py``), on a transformer vision model."""
    from ddl_tpu.models.transformer import RMSNorm, remat_block
    from ddl_tpu.ops.losses import onehot_cross_entropy_mean
    from ddl_tpu.parallel.lm_pipeline import (
        make_blocks_pipeline,
        stack_block_params,
    )
    from ddl_tpu.parallel.sharding import PIPE_AXIS
    from ddl_tpu.train.lm_steps import dropout_step_key

    n_stages, M = spec.pipe, num_microbatches
    V = virtual_stages
    if schedule not in PIPELINE_SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {schedule!r}")
    if V < 1:
        raise ValueError(f"virtual_stages must be >= 1, got {V}")
    if schedule == "zb" and V > 1:
        raise ValueError(
            f"virtual_stages={V} requires schedule='gpipe' or '1f1b' "
            "(the zero-bubble B/W-split clock loop is single-chunk)"
        )
    if V > 1 and M % n_stages:
        raise ValueError(
            f"num_microbatches {M} % pipe {n_stages} != 0 (the interleaved "
            "schedule advances microbatches in groups of pipe)"
        )
    if M < 1:
        raise ValueError(f"num_microbatches must be >= 1, got {M}")
    if cfg.n_layers % (n_stages * V):
        raise ValueError(
            f"n_layers {cfg.n_layers} % (pipe {n_stages} * virtual {V}) != 0"
        )
    if batch % M:
        raise ValueError(f"batch {batch} % microbatches {M} != 0")
    mb = batch // M
    if mb % spec.data:
        raise ValueError(f"microbatch {mb} % mesh data={spec.data} != 0")
    mesh = build_lm_mesh(spec, devices)
    rules = lm_logical_rules(cfg.fsdp)
    bc = cfg.block_config()
    block_cls = remat_block(bc)
    block_mod = block_cls(bc, None)
    T, d = cfg.num_patches, cfg.d_model

    use_dropout = cfg.dropout_rate > 0.0
    pipe_kwargs = dict(
        n_stages=n_stages, num_microbatches=M, mb=mb,
        d_model=d, compute_dtype=cfg.dtype,
    )
    from ddl_tpu.parallel.lm_pipeline import blocks_pipeline_api

    make_pipe, wrap_blocks, unwrap_blocks = blocks_pipeline_api(V)
    pipeline = make_pipe(mesh, block_mod, **pipe_kwargs)
    pipeline_drop = (
        make_pipe(mesh, block_mod, dropout=True, **pipe_kwargs)
        if use_dropout
        else None
    )

    # the same submodule constructors ViT composes, applied with the
    # corresponding param subtrees — shared source, no drift
    from ddl_tpu.models.vit import make_patch_embed, make_vit_head

    conv_mod = make_patch_embed(cfg)
    norm_mod = RMSNorm(cfg.dtype)
    head_mod = make_vit_head(cfg)

    def split_vit_params(full):
        blocks = stack_block_params(full, n_stages, V)
        return {
            "embed": {"patch_embed": full["patch_embed"],
                      "pos_embed": full["pos_embed"]},
            "blocks": wrap_blocks(blocks),
            "head": {"norm_f": full["norm_f"], "head": full["head"]},
        }

    full_model = ViT(cfg)
    dummy = jnp.zeros((batch, cfg.image_size, cfg.image_size, 3), jnp.float32)

    abs_params = jax.eval_shape(lambda r: full_model.init(r, dummy)["params"], rng)
    table = vit_rules(cfg.fsdp)
    mesh_sharding = table.shardings(nn.meta.unbox(abs_params), mesh)
    block0 = mesh_sharding["block0"]
    stack_dims = (None,) * (1 if V == 1 else 2)
    blocks_sharding = jax.tree.map(
        lambda sh: NamedSharding(mesh, P(PIPE_AXIS, *stack_dims, *sh.spec)),
        block0,
    )
    param_shardings = {
        "embed": {"patch_embed": mesh_sharding["patch_embed"],
                  "pos_embed": mesh_sharding["pos_embed"]},
        "blocks": wrap_blocks(blocks_sharding),
        "head": {"norm_f": mesh_sharding["norm_f"],
                 "head": mesh_sharding["head"]},
    }

    def create_state(rng):
        params = split_vit_params(
            nn.meta.unbox(full_model.init(rng, dummy)["params"])
        )
        params = jax.lax.with_sharding_constraint(params, param_shardings)
        return ViTTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )

    # microbatched activations/labels: IMAGE_SPEC behind the leading
    # microbatch axis
    mb_spec = NamedSharding(mesh, P(None, *IMAGE_SPEC))

    def embed_fn(embed_params, images):
        x = normalize_images(images, cfg.dtype)
        x = conv_mod.apply({"params": embed_params["patch_embed"]}, x)
        x = x.reshape(batch, T, d)
        x = x + embed_params["pos_embed"].astype(cfg.dtype)
        return nn.with_logical_constraint(x, ("batch", "act_seq", "act_embed"))

    def blocks_of(params):
        return unwrap_blocks(params["blocks"])

    def forward(params, images, step=None):
        with nn.logical_axis_rules(rules):
            x = embed_fn(params["embed"], images)
            x = x.reshape(M, mb, T, d)
            x = jax.lax.with_sharding_constraint(x, mb_spec)
            if use_dropout and step is not None:
                acc, _aux = pipeline_drop(
                    blocks_of(params), x, dropout_step_key(rng, step)
                )
            else:
                acc, _aux = pipeline(blocks_of(params), x)
            x_out = acc[-1].reshape(batch, T, d)
            x_out = norm_mod.apply({"params": params["head"]["norm_f"]}, x_out)
            pooled = x_out.mean(axis=1)
            return head_mod.apply(
                {"params": params["head"]["head"]}, pooled.astype(jnp.float32)
            )

    manual_grad_fn = None
    if schedule in ("1f1b", "zb"):
        from ddl_tpu.parallel.lm_pipeline import (
            make_blocks_pipeline_1f1b,
            make_blocks_pipeline_zb,
        )

        def head_loss(head_p, y, tgt):
            with nn.logical_axis_rules(rules):
                x = norm_mod.apply({"params": head_p["norm_f"]}, y)
                pooled = x.mean(axis=1)
                logits = head_mod.apply(
                    {"params": head_p["head"]}, pooled.astype(jnp.float32)
                )
            ce, logits = onehot_cross_entropy_mean(logits, tgt)
            acc = (jnp.argmax(logits, -1) == tgt).mean()
            return ce / M, jnp.stack([ce, acc])

        bw_kwargs = dict(
            n_stages=n_stages, num_microbatches=M, mb=mb,
            d_model=d, compute_dtype=cfg.dtype,
            aux_cotangent=0.0,  # ViT blocks have no MoE aux
            zero_metrics=jnp.zeros((2,), jnp.float32),
            dropout=use_dropout,
        )
        if schedule == "zb":
            pipeline_bw = make_blocks_pipeline_zb(
                mesh, block_mod, head_loss, **bw_kwargs
            )
        else:
            pipeline_bw = make_blocks_pipeline_1f1b(
                mesh, block_mod, head_loss, virtual=V, **bw_kwargs
            )

        def manual_grad_fn(params, images, labels, step=None):
            with nn.logical_axis_rules(rules):
                x, embed_vjp = jax.vjp(
                    lambda ep: embed_fn(ep, images), params["embed"]
                )
                x_mb = jax.lax.with_sharding_constraint(
                    x.reshape(M, mb, T, d), mb_spec
                )
                lab_mb = jax.lax.with_sharding_constraint(
                    labels.reshape(M, mb), mb_spec
                )
                key_args = (
                    (dropout_step_key(rng, step),) if use_dropout else ()
                )
                g_blocks, g_head, dx_mb, met, _aux = pipeline_bw(
                    blocks_of(params), params["head"],
                    x_mb, lab_mb, *key_args
                )
                (g_embed,) = embed_vjp(
                    dx_mb.reshape(batch, T, d).astype(x.dtype)
                )
            grads = {
                "embed": g_embed,
                "blocks": wrap_blocks(g_blocks),
                "head": g_head,
            }
            return grads, {"loss": met[0] / M, "accuracy": met[1] / M}

    return _finalize_vit(mesh, tx, forward, create_state, rng,
                         manual_grad_fn=manual_grad_fn,
                         contract=table.contract(
                             pipeline_schedule=schedule,
                             pipeline_stages=n_stages,
                             virtual_stages=V,
                         ),
                         probe_inputs=_vit_probe_inputs(cfg))
