"""Jitted train/eval steps for the ViT family (models/vit.py).

Same SPMD pattern as the LM steps (``train/lm_steps.py``): parameter
placement from logical-axis annotations over a ``(data, model)`` mesh —
batch sharded over ``data``, attention heads / MLP hidden over ``model``
(TP), optional FSDP — one jitted, donated step.  Input is the CNN data
path's uint8 batch; /255 normalisation runs on device (``ops/image.py``)
so the wire format matches the DenseNet trainer's.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddl_tpu.models.vit import ViT, ViTConfig
from ddl_tpu.ops import normalize_images
from ddl_tpu.ops.losses import cross_entropy_loss
from ddl_tpu.parallel.sharding import LMMeshSpec, build_lm_mesh, lm_logical_rules

__all__ = ["ViTTrainState", "ViTStepFns", "make_vit_step_fns"]


class ViTTrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: optax.OptState


class ViTStepFns(NamedTuple):
    """train(state, images_u8, labels) -> (state, metrics);
    evaluate(state, images_u8) -> logits; init_state() -> sharded state.
    ``train`` donates its state argument — always rebind."""

    train: Callable
    evaluate: Callable
    init_state: Callable
    mesh: Mesh


def make_vit_step_fns(
    cfg: ViTConfig,
    spec: LMMeshSpec,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    batch: int,
    devices=None,
) -> ViTStepFns:
    if spec.seq > 1 or spec.expert > 1 or spec.pipe > 1:
        raise ValueError(
            "ViT steps shard over (data, model) only; got "
            f"seq={spec.seq} expert={spec.expert} pipe={spec.pipe}"
        )
    if batch % spec.data:
        raise ValueError(f"batch {batch} must divide by mesh data={spec.data}")
    mesh = build_lm_mesh(spec, devices)
    rules = lm_logical_rules(cfg.fsdp)
    model = ViT(cfg)
    dummy = jnp.zeros((batch, cfg.image_size, cfg.image_size, 3), jnp.float32)

    def init_params(rng):
        return model.init(rng, dummy)["params"]

    abs_params = jax.eval_shape(init_params, rng)
    logical = nn.get_partition_spec(abs_params)
    param_shardings = nn.logical_to_mesh_sharding(logical, mesh, rules)

    def create_state(rng):
        params = nn.meta.unbox(init_params(rng))
        params = jax.lax.with_sharding_constraint(params, param_shardings)
        return ViTTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )

    def loss_fn(params, images, labels):
        x = normalize_images(images, cfg.dtype)
        with nn.logical_axis_rules(rules):
            logits = model.apply({"params": params}, x)
        loss = cross_entropy_loss(logits, labels)
        acc = (jnp.argmax(logits, -1) == labels).mean()
        return loss, (logits, {"loss": loss, "accuracy": acc})

    def train_step(state, images, labels):
        (_, (_, metrics)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params, images, labels
        )
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        return (
            state.replace(
                step=state.step + 1,
                params=optax.apply_updates(state.params, updates),
                opt_state=new_opt,
            ),
            metrics,
        )

    def eval_step(state, images):
        x = normalize_images(images, cfg.dtype)
        with nn.logical_axis_rules(rules):
            return model.apply({"params": state.params}, x)

    img_sharding = NamedSharding(mesh, P("data"))
    replicated = NamedSharding(mesh, P())

    def _with_mesh(fn):
        def wrapped(*args):
            with jax.set_mesh(mesh):
                return fn(*args)

        return wrapped

    return ViTStepFns(
        train=_with_mesh(jax.jit(
            train_step,
            in_shardings=(None, img_sharding, img_sharding),
            out_shardings=(None, replicated),
            donate_argnums=(0,),
        )),
        evaluate=_with_mesh(jax.jit(
            eval_step, in_shardings=(None, img_sharding),
        )),
        init_state=lambda: _with_mesh(jax.jit(create_state))(rng),
        mesh=mesh,
    )
