"""ViT-family trainer: the vision transformer on the shared training loop.

Same shape as the CNN Trainer (epoch periods, APTOS-style image loaders,
masked full-coverage eval, QWK-gated snapshots) but driving the
transformer-family step functions (``train/vit_steps.py``) over the 5-axis
LM mesh.  Replaces the bespoke loop that lived in ``examples/train_vit.py``
through round 2, which had no preemption guard, NaN watchdog, profiler
hook, or checkpointing at all; the example is now an argparse shim.
"""

from __future__ import annotations

import dataclasses

import jax
import numpy as np

from ddl_tpu import checkpoint as ckpt
from ddl_tpu.config import DataConfig
from ddl_tpu.data import (
    DataLoader,
    ShardedEpochSampler,
    build_datasets,
    shard_batch,
)
from ddl_tpu.models.vit import ViTConfig
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.loop import BaseTrainer, _phase
from ddl_tpu.train.vit_steps import make_vit_step_fns
from ddl_tpu.utils import MetricLogger, faultinject, masked_classification_eval

__all__ = ["ViTRunConfig", "ViTTrainer"]


@dataclasses.dataclass
class ViTRunConfig:
    batch: int = 32
    epochs: int = 3
    num_microbatches: int = 0
    accum_steps: int = 1
    # "gpipe" | "1f1b" | "zb" (parallel/rules.PIPELINE_SCHEDULES)
    pipeline_schedule: str = "gpipe"
    virtual_stages: int = 1
    # ZeRO-1 optimizer-state sharding over 'data' (requires a fused Adam
    # tx and the flat step path — see TrainConfig.zero_sharding)
    zero_sharding: bool = False
    checkpoint_dir: str | None = "checkpoints"
    # keep only the newest K valid snapshots (0 = all); corrupt ones
    # never count toward K — see checkpoint.gc_snapshots
    keep_snapshots: int = 0
    resume_epoch: int | None = None
    # With no explicit resume_epoch, continue from this job id's latest
    # snapshot automatically when one exists (relaunch == resume).
    auto_resume: bool = True
    save_best_qwk: bool = True
    job_id: str = "vit"
    log_dir: str | None = "training_logs"  # default-on CSV observability
    halt_on_nan: bool = True
    # "halt" | "recover" — see LMRunConfig.nan_policy
    nan_policy: str = "halt"
    nan_max_consecutive: int = 3
    nan_grace_scale: float = 0.1
    nan_grace_periods: int = 2
    preemption_save: bool = True
    profile_dir: str | None = None


class ViTTrainer(BaseTrainer):
    best_metric = "qwk"
    best_mode = "max"
    best_label = "QWK"

    def __init__(
        self,
        cfg: ViTConfig,
        spec: LMMeshSpec,
        tx,
        run: ViTRunConfig,
        data: DataConfig | None = None,
        datasets=None,
        rng: jax.Array | None = None,
    ) -> None:
        self.cfg, self.spec, self.run = cfg, spec, run
        self.job_id = run.job_id
        self.tx = tx
        self._rng = rng if rng is not None else jax.random.key(0)
        self.fns = self._make_fns()

        dc = data if data is not None else DataConfig(
            image_size=cfg.image_size,
            global_batch_size=run.batch,
            eval_batch_size=run.batch,
        )
        train_ds, test_ds = (
            datasets if datasets is not None else build_datasets(dc)
        )
        n_proc, proc = jax.process_count(), jax.process_index()
        self.train_loader = DataLoader(
            train_ds, run.batch // n_proc,
            sampler=ShardedEpochSampler(len(train_ds), n_proc, proc, seed=0),
            on_retry=self._note_io_retry,
        )
        # deterministic full-coverage eval: ordered, sentinel-padded to
        # static shapes, padded rows (label -1) masked out — same contract
        # as the CNN Trainer's eval loop
        self.test_loader = DataLoader(
            test_ds, run.batch // n_proc,
            sampler=ShardedEpochSampler(
                len(test_ds), n_proc, proc,
                shuffle=False, drop_last=False, pad_mode="sentinel", seed=1,
            ),
            drop_last=False, pad_last_batch=True,
            on_retry=self._note_io_retry,
        )

        self.is_logging_process = proc == 0
        self.logger = (
            MetricLogger(run.log_dir, run.job_id, global_rank=proc,
                         local_rank=proc)
            if run.log_dir
            else None
        )
        self._init_obs(run.log_dir, run.job_id, "vit")
        self._emit_pipe_schedule(
            run.pipeline_schedule, self.spec.pipe,
            run.num_microbatches or self.spec.pipe, run.virtual_stages,
        )
        self.num_periods = run.epochs
        self.halt_on_nan = run.halt_on_nan
        from ddl_tpu.train.recovery import make_policy

        self.recovery = make_policy(run)
        self.keep_snapshots = run.keep_snapshots
        self.preemption_save = run.preemption_save and bool(run.checkpoint_dir)
        self.profile_dir = run.profile_dir
        self.save_best = run.save_best_qwk and bool(run.checkpoint_dir)
        self.best_value = -1.0

        self.state = self.fns.init_state()
        self.periods_run = 0
        resume_epoch = ckpt.resolve_resume(
            run.checkpoint_dir, run.job_id, run.resume_epoch, run.auto_resume
        )
        if run.checkpoint_dir and resume_epoch is not None:
            from time import perf_counter

            t0 = perf_counter()
            self.state, self.periods_run = ckpt.run_resume_load(
                # auto-discovered epochs were verified by resolve_resume
                lambda: ckpt.load_snapshot(
                    run.checkpoint_dir, run.job_id, resume_epoch, self.state,
                    verify=run.resume_epoch is not None,
                ),
                auto=run.resume_epoch is None,
                desc=f"job {run.job_id!r} epoch {resume_epoch}",
                hint="pass --fresh (auto_resume=False)",
            )
            self._apply_cursor(resume_epoch)
            self._emit_snapshot_restore(
                perf_counter() - t0, resume_epoch,
                self.periods_run, self._resume_offset,
            )
            print(f"resumed; continuing at epoch {self.periods_run}")

    def _make_fns(self):
        run = self.run
        from ddl_tpu.train.recovery import scale_tx

        return make_vit_step_fns(
            self.cfg, self.spec, scale_tx(self.tx, self.update_scale),
            self._rng, run.batch,
            num_microbatches=run.num_microbatches,
            accum_steps=run.accum_steps,
            pipeline_schedule=run.pipeline_schedule,
            virtual_stages=run.virtual_stages,
            zero_sharding=run.zero_sharding,
        )

    def _rebuild_step_fns(self) -> None:
        self.fns = self._make_fns()

    def _snapshot_store(self):
        run = self.run
        return (run.checkpoint_dir, run.job_id) if run.checkpoint_dir else None

    def _rollback_restore(self, epoch: int) -> None:
        self.state, self.periods_run = ckpt.load_snapshot(
            self.run.checkpoint_dir, self.run.job_id, epoch, self.state,
            verify=False,
        )
        self._apply_cursor(epoch)

    def _apply_cursor(self, epoch: int) -> None:
        """Exact resume: a mid-epoch preemption snapshot re-enters its
        epoch at the recorded batch offset (same mechanism as the CNN
        family — see Trainer._apply_cursor)."""
        cur = ckpt.read_cursor(
            self.run.checkpoint_dir, self.run.job_id, epoch
        )
        if cur and int(cur.get("offset", 0)) > 0:
            self.periods_run = int(cur.get("period", self.periods_run))
            self._resume_offset = int(cur["offset"])
            print(
                f"[resume] data cursor: re-entering epoch "
                f"{self.periods_run} at batch {self._resume_offset}"
            )

    # ------------------------------------------------------- loop hooks

    def run_period(self, epoch: int, guard=None):
        self.train_loader.set_epoch(epoch)
        # exact resume: skip batches a preemption snapshot already
        # consumed this epoch (one-shot index-level skip)
        skip = self.consume_resume_offset()
        if skip:
            self.train_loader.set_start_batch(skip)
        losses, steps = [], 0
        # global event steps (epoch * steps/epoch + i) — one monotone
        # counter per host for the obs liveness/straggler comparison
        step_base = epoch * len(self.train_loader) + skip
        it = iter(self.train_loader)
        while True:
            with _phase(self.obs, "data_wait", step=step_base + steps):
                batch = next(it, None)
            if batch is None:
                break
            images, labels = batch
            with _phase(self.obs, "h2d", step=step_base + steps):
                gi, gl = shard_batch(self.fns.mesh, images, labels)
            with _phase(self.obs, "step", step=step_base + steps):
                self.state, m = self.fns.train(self.state, gi, gl)
            # HBM ledger: stamp the train step's static memory budget
            # once, after its first dispatch (obs/hbm.py hbm_plan)
            self.emit_hbm_plan("train_step", self.fns.train,
                               self.state, gi, gl)
            # keep the per-step loss ON DEVICE: float()-ing it here would
            # block every step on the compiled program (the host-sync
            # anti-pattern `ddl_tpu lint` flags) — fetch once per epoch,
            # like the CNN/LM families
            losses.append(m["loss"])
            steps += 1
            faultinject.check_step(step_base + steps - 1, guard)
            if guard is not None and guard.requested:
                break
        if steps == 0:
            raise RuntimeError("empty epoch: dataset smaller than one batch")
        with _phase(self.obs, "fence", step=step_base + steps - 1):
            loss = float(np.mean([np.asarray(l) for l in losses]))
        return {"loss": loss}, steps

    def evaluate_period(self, epoch: int) -> dict:
        self.test_loader.set_epoch(epoch)
        logits, targets = [], []
        for images, labels in self.test_loader:
            gi, gl = shard_batch(self.fns.mesh, images, labels)
            logits.append(np.asarray(self.fns.evaluate(self.state, gi)))
            targets.append(np.asarray(gl))
        return masked_classification_eval(
            np.concatenate(logits), np.concatenate(targets)
        )

    def rate_metrics(self, steps: int, elapsed: float) -> dict:
        return {"img_per_sec": steps * self.run.batch / elapsed}

    def format_train_line(self, epoch, elapsed, steps, m) -> str:
        return (
            f"epoch {epoch}: loss {m['loss']:.4f} ({steps} steps, "
            f"{elapsed:.1f}s, {steps / elapsed:.2f} steps/s)"
        )

    def format_eval_line(self, epoch, m) -> str:
        return (
            f"epoch {epoch}: val_acc {m['val_accuracy']:.4f} "
            f"qwk {m['qwk']:.4f}"
        )

    def save_snapshot(self, epoch: int) -> None:
        cursor = self.data_cursor
        if cursor and cursor.get("offset", 0) >= len(self.train_loader):
            # preempted exactly at the epoch boundary: a clean next-epoch
            # start, not an empty-remainder resume
            cursor = {"period": int(cursor["period"]) + 1, "offset": 0}
        path = ckpt.save_snapshot(
            self.run.checkpoint_dir, self.job_id, epoch, self.state,
            cursor=cursor,
        )
        print(f"epoch {epoch} | saved snapshot to {path}")

    def last_snapshot_hint(self):
        if not self.run.checkpoint_dir:
            return "none (set checkpoint_dir)"
        return ckpt.latest_epoch(self.run.checkpoint_dir, self.job_id)

    def resume_hint(self, epoch: int) -> str:
        return f"--job-id {self.job_id} --resume-epoch {epoch}"
