from ddl_tpu.train.loop import BaseTrainer
from ddl_tpu.train.state import TrainState, create_train_state, make_optimizer
from ddl_tpu.train.trainer import Trainer, resolve_job_id

__all__ = [
    "BaseTrainer",
    "TrainState",
    "create_train_state",
    "make_optimizer",
    "Trainer",
    "resolve_job_id",
    # LMTrainer / ViTTrainer import their model families; reach them via
    # ddl_tpu.train.lm_trainer / ddl_tpu.train.vit_trainer directly.
]
