from ddl_tpu.train.state import TrainState, create_train_state, make_optimizer
from ddl_tpu.train.trainer import Trainer, resolve_job_id

__all__ = [
    "TrainState",
    "create_train_state",
    "make_optimizer",
    "Trainer",
    "resolve_job_id",
]
