"""In-loop recovery policy: what a run does about a non-finite loss.

``halt_on_nan`` (round 1) turned a NaN excursion into a clean death with
a pointer at the last snapshot — a human still had to react.  This
module is the no-human version, driven by ``train/loop.BaseTrainer``:

* a non-finite period loss is recorded as an ``anomaly`` event (the
  ``obs/anomaly.py`` stream CI and ``obs summarize`` already read) and
  the period's metrics/eval/snapshot are **skipped** — a transient spike
  costs one period, not the run;
* after ``max_consecutive`` non-finite periods the policy declares the
  optimizer state poisoned and asks the trainer to **roll back** to the
  latest *valid* snapshot (``checkpoint.latest_valid_epoch`` — corrupt
  ones are skipped), entering a **reduced-LR grace window**: the next
  ``grace_periods`` finite periods run with updates scaled by
  ``grace_scale``, stepping gently off the cliff edge that produced the
  excursion instead of re-walking straight back into it;
* rollbacks are bounded (``max_rollbacks``): a run that NaNs through
  repeated rollback+grace cycles has a real bug and dies loudly.

``scale_tx`` implements the grace mechanically: it wraps an optax
transformation so the *updates* (not the gradients — Adam's moment
normalisation is preserved) are multiplied by a constant, with an
**unchanged state tree**, so a snapshot written before the wrap restores
into the wrapped optimizer and vice versa.  Entering/leaving grace costs
one step-function rebuild (a recompile) — rollbacks are rare enough
that simplicity wins over a traced hyperparameter.
"""

from __future__ import annotations

__all__ = ["RecoveryPolicy", "make_policy", "scale_tx"]


def make_policy(run) -> "RecoveryPolicy | None":
    """Build the policy a run config asks for — ``None`` for ``"halt"``,
    a ``RecoveryPolicy`` for ``"recover"``, a loud error for anything
    else (a typo'd policy name must not silently fall back to halting)."""
    if run.nan_policy not in ("halt", "recover"):
        raise ValueError(
            f"unknown nan_policy {run.nan_policy!r} "
            "(want 'halt' or 'recover')"
        )
    if run.nan_policy == "halt":
        return None
    return RecoveryPolicy(
        max_consecutive=run.nan_max_consecutive,
        grace_scale=run.nan_grace_scale,
        grace_periods=run.nan_grace_periods,
    )


class RecoveryPolicy:
    """Consecutive-failure counter + rollback/grace bookkeeping.

    The loop calls ``on_nonfinite()`` per bad period (returns ``"skip"``
    or ``"rollback"``), ``on_rollback()`` when the trainer restored a
    snapshot, and ``on_finite()`` per good period (returns True exactly
    when a grace window just ended and the update scale must return to
    1).
    """

    def __init__(
        self,
        max_consecutive: int = 3,
        grace_scale: float = 0.1,
        grace_periods: int = 2,
        max_rollbacks: int = 2,
    ) -> None:
        if max_consecutive < 1:
            raise ValueError(
                f"max_consecutive must be >= 1, got {max_consecutive}"
            )
        self.max_consecutive = max_consecutive
        self.grace_scale = grace_scale
        self.grace_periods = grace_periods
        self.max_rollbacks = max_rollbacks
        self.consecutive = 0
        self.grace_left = 0
        self.rollbacks = 0
        self.skipped = 0

    @property
    def in_grace(self) -> bool:
        return self.grace_left > 0

    def on_nonfinite(self) -> str:
        self.consecutive += 1
        if self.consecutive >= self.max_consecutive:
            return "rollback"
        self.skipped += 1
        return "skip"

    def on_rollback(self) -> None:
        self.rollbacks += 1
        self.consecutive = 0
        self.grace_left = self.grace_periods

    def on_finite(self) -> bool:
        self.consecutive = 0
        if self.grace_left > 0:
            self.grace_left -= 1
            return self.grace_left == 0
        return False


def scale_tx(tx, scale: float):
    """``tx`` with its emitted updates multiplied by ``scale``, keeping
    ``tx``'s state tree bit-identical (snapshot-compatible both ways:
    ``scale == 1`` wraps are free to skip).

    A fused Adam (``train/fused_optim.FusedAdam``) is rebuilt with the
    scale baked in instead of wrapped — the grace window then keeps both
    the single-pass ``fused_apply`` path and any attached ZeRO-1
    placement (a generic wrap would hide them and silently fall back to
    the two-pass replicated update)."""
    if scale == 1.0:
        return tx
    rebuild = getattr(tx, "rebuild", None)
    if rebuild is not None:
        return rebuild(scale=scale)
    import jax
    import optax

    def update(grads, state, params=None):
        updates, new_state = tx.update(grads, state, params)
        scaled = jax.tree.map(lambda u: u * scale, updates)
        return scaled, new_state

    return optax.GradientTransformation(tx.init, update)
