"""Jitted train/eval steps for the transformer LM family.

One jitted SPMD program per step, exactly like the CNN path
(``train/steps.py``), but over the 4-axis ``(data, seq, model, expert)``
mesh (``parallel/sharding.py``).  Parameter placement comes from the model's
logical axis annotations resolved through the rule table; XLA's partitioner
then inserts every collective the strategy needs — gradient all-reduce over
``data`` (the DDP reducer, reference ``ddp.py:127``), TP all-reduces over
``model``, MoE all-to-alls over ``expert``, FSDP all-gather/reduce-scatter
when ``fsdp=True`` — from sharding propagation alone.  The only manual
collective is ring attention's ``ppermute`` over ``seq``, injected as the
attention core inside an otherwise-auto jit program via ``shard_map``.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from flax import struct
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddl_tpu.models.transformer import LMConfig, TransformerLM
from ddl_tpu.ops.flash_attention import flash_attention
from ddl_tpu.ops.quant import head_kernel
from ddl_tpu.parallel.ring_attention import make_ring_self_attention
# Jit-boundary specs + the family rule table come from the partition-
# rule engine — this module is lint-banned from hand-writing
# PartitionSpec axis literals (astlint 'pspec-hand-rolled').
from ddl_tpu.parallel.rules import (
    LM_MANUAL_ATTN_SPEC,
    PIPELINE_SCHEDULES,
    TOKEN_SPEC,
    lm_rules,
)
from ddl_tpu.parallel.sharding import (
    FLASH_AUTO_MIN_T,  # noqa: F401  (re-exported: measured dispatch bound)
    LMMeshSpec,
    build_lm_mesh,
    lm_logical_rules,
    normalize_flash,
    resolve_auto_flash,  # noqa: F401  (re-exported for tests/tools)
    validate_kv_head_sharding,
    validate_ulysses_kv_heads,
)
from ddl_tpu.parallel.ulysses import make_ulysses_self_attention

__all__ = [
    "LMTrainState",
    "LMStepFns",
    "TOKEN_SPEC",
    "make_lm_step_fns",
    "make_ring_core",
    "finalize_step_fns",
    "poison_nan_grads",
]


def poison_nan_grads(step, grads, nan_step: int | None):
    """Traced ``nan@grad`` fault injection, shared by the LM and ViT
    step factories: when ``nan_step`` (from
    ``faultinject.traced_nan_step()``, consumed at factory-build time)
    is armed, a ``lax.cond`` on the step counter replaces every gradient
    leaf with NaN at exactly that step — a real diverged update inside
    the compiled program.  No-op (and nothing traced in) when unarmed."""
    if nan_step is None:
        return grads
    return jax.lax.cond(
        step == nan_step,
        lambda g: jax.tree.map(lambda x: jnp.full_like(x, jnp.nan), g),
        lambda g: g,
        grads,
    )

# The jit-boundary sharding for token batches (inputs AND targets):
# batch over data x expert, sequence over seq — defined once in
# parallel/rules.py (re-exported here for the factories' callers).


class LMTrainState(struct.PyTreeNode):
    step: jax.Array
    params: Any
    opt_state: optax.OptState


class LMStepFns(NamedTuple):
    """train(state, inputs, targets) -> (state, metrics);
    evaluate(state, inputs, targets) -> metrics;
    init_state() -> a fresh sharded LMTrainState; mesh: the device mesh.

    ``train`` donates its state argument (the TPU-memory-friendly pattern),
    so a state that has been passed to ``train`` is consumed — always
    rebind: ``state = fns.init_state()``, ``state, m = fns.train(state, ...)``.
    """

    train: Callable
    evaluate: Callable
    init_state: Callable
    mesh: Mesh


def make_ring_core(
    mesh: Mesh, causal: bool = True, use_flash: bool = False,
    window: int = 0,
) -> Callable:
    """Ring-attention core for injection into ``TransformerLM``: batch local
    per ``data`` shard, heads local per ``model`` shard, K/V rotating over
    the ``seq`` ring (``parallel/ring_attention.py``).  ``use_flash`` runs
    each per-device block through the Pallas kernel (flash inside ring —
    the long-context composition where T_local is itself long)."""
    return make_ring_self_attention(
        mesh,
        causal=causal,
        spec=LM_MANUAL_ATTN_SPEC,
        jit=False,
        use_flash=use_flash,
        window=window,
    )


def chunked_ce_loss(cfg, hidden, kernel, targets, aux, with_accuracy):
    """Shared tail of the ce_chunk / ce_vocab_chunk paths (flat loss and
    GPipe pipeline loss): fused chunked head+CE over post-norm hidden
    states — token-chunked (ops/losses.fused_chunked_ce) or
    vocab-streamed (fused_vocab_chunked_ce) per the config — assembled
    into the ``(loss, (None, metrics))`` contract ``finalize_step_fns``
    expects (``None`` logits signal the eval step that accuracy is already
    in the metrics).  Call inside an ``nn.logical_axis_rules`` scope."""
    from ddl_tpu.ops.losses import fused_chunked_ce

    if cfg.ce_vocab_chunk:
        from ddl_tpu.ops.losses import fused_vocab_chunked_ce

        ce, acc = fused_vocab_chunked_ce(
            hidden, kernel, targets, cfg.ce_vocab_chunk, with_accuracy
        )
    else:
        ce, acc = fused_chunked_ce(
            hidden,
            kernel,
            targets,
            cfg.ce_chunk,
            with_accuracy=with_accuracy,
            constrain=lambda z: nn.with_logical_constraint(
                z, ("batch", "act_seq", "act_vocab")
            ),
        )
    loss = ce + cfg.moe_aux_weight * aux
    metrics = {"loss": loss, "ce": ce, "moe_aux": aux}
    if acc is not None:
        metrics["accuracy"] = acc
    return loss, (None, metrics)


def moe_router_metrics(intermediates) -> dict:
    """Aggregate the per-block router stats ``MoeMlp`` sows into scalar
    step metrics: mean token-drop fraction (capacity overflow silently
    drops tokens — a run must see it) and the expert-load spread
    (min/max share of kept token-choices; uniform = 1/E).

    Under gradient accumulation (``accum_steps > 1``) the step metrics are
    chunk means, so ``moe_load_max`` is a mean-of-maxes — it understates a
    single hot microbatch; watch per-chunk logs (accum=1) when hunting
    routing collapse."""
    drops, loads = [], []
    for path, leaf in jax.tree_util.tree_leaves_with_path(intermediates):
        name = jax.tree_util.keystr(path)
        if "moe_drop_frac" in name:
            drops.append(leaf)
        elif "moe_expert_load" in name:
            loads.append(leaf)
    if not drops:
        return {}
    load = jnp.stack(loads).mean(0)
    return {
        "moe_drop_frac": jnp.stack(drops).mean(),
        "moe_load_max": load.max(),
        "moe_load_min": load.min(),
    }


def _token_ce(logits, targets):
    """Mean next-token cross-entropy (f32, stable)."""
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    picked = jnp.take_along_axis(
        logits, targets[..., None].astype(jnp.int32), axis=-1
    )[..., 0]
    return (lse - picked).mean()


def dropout_step_key(rng: jax.Array, step) -> jax.Array:
    """Per-step dropout base key, decorrelated from init by the 0x0D0 fold.
    The non-pipelined paths hand it to flax as the ``dropout`` rng stream;
    the pipeline schedules fold in (microbatch, stage, layer) so a
    microbatch's mask is identical wherever and whenever its forward is
    (re)computed — forward-for-handoff, GPipe's autodiff replay, and 1F1B's
    backward-tick recompute all agree."""
    return jax.random.fold_in(jax.random.fold_in(rng, 0x0D0), step)


def dropout_kwargs(rng: jax.Array, step, rate: float) -> dict:
    """``model.apply`` kwargs for optional train-mode dropout: active iff a
    ``step`` is given and ``rate > 0``; the rng is derived from the
    builder's key via ``dropout_step_key``.  Single source shared by the LM
    and ViT paths."""
    train = step is not None and rate > 0.0
    if not train:
        return {"deterministic": True, "rngs": None}
    return {"deterministic": False, "rngs": {"dropout": dropout_step_key(rng, step)}}


def accumulate_grads(grad_fn, params, chunked_args, k: int):
    """Mean gradients and metrics of ``grad_fn(params, *chunk)`` over the
    ``k`` leading-axis chunks of ``chunked_args`` — ONE compiled
    forward+backward (the scan body), carry zero-initialised from
    ``eval_shape``.  Shared by the LM and ViT accumulation paths."""
    (_, (_, abs_m)), abs_g = jax.eval_shape(
        grad_fn, params, *(a[0] for a in chunked_args)
    )

    def zeros(tree):
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), tree)

    def body(carry, chunk):
        g_acc, m_acc = carry
        (_, (_, m)), g = grad_fn(params, *chunk)
        return (
            jax.tree.map(jnp.add, g_acc, g),
            jax.tree.map(jnp.add, m_acc, m),
        ), None

    (g, m), _ = jax.lax.scan(body, (zeros(abs_g), zeros(abs_m)), chunked_args)
    return jax.tree.map(lambda x: x / k, g), jax.tree.map(lambda x: x / k, m)


def finalize_step_fns(
    mesh: Mesh,
    tx: optax.GradientTransformation,
    loss_fn,
    create_state,
    rng: jax.Array,
    accum_steps: int = 1,
    manual_grad_fn=None,
    contract: dict | None = None,
    probe_inputs=None,
) -> LMStepFns:
    """Shared tail for the non-pipelined and pipelined LM paths: wrap a
    ``loss_fn(params, inputs, targets, step=None) -> (loss, (logits,
    metrics))`` and a ``create_state(rng)`` into jitted, donated,
    mesh-scoped step functions.  ``train`` passes ``state.step`` as
    ``step`` (dropout rng derivation); eval passes nothing
    (deterministic).

    ``accum_steps > 1`` splits the batch into that many equal chunks and
    accumulates their gradients inside one jitted step (``lax.scan``)
    before a single optimizer update — peak activation memory drops by the
    chunk factor.  For dense models the update equals the full-batch step
    exactly (mean-CE gradients of equal chunks average to the full-batch
    gradient; tested); with MoE the load-balancing aux loss is nonlinear
    in batch composition, so chunked routing statistics make it a close
    but not bitwise-equal approximation.

    ``manual_grad_fn(params, inputs, targets, step) -> (grads, metrics)``,
    when given, replaces autodiff of ``loss_fn`` in the train step — for
    paths that compute their gradients explicitly (the 1F1B pipeline
    schedule, whose interleaved backward cannot be derived by differentiating
    a forward pass).  ``loss_fn`` still drives evaluation.

    ``contract`` (a dict from ``RuleTable.contract``) overrides the
    default boundary contract — the family factories derive it from
    their rule table so the contract checker validates rules, not
    hand-specs.

    ``jax.set_mesh`` wraps every call because ``nn.with_logical_constraint``
    lowers to bare-PartitionSpec sharding constraints, which resolve against
    the ambient mesh at trace time.
    """
    tok_sharding = NamedSharding(mesh, TOKEN_SPEC)
    replicated = NamedSharding(mesh, P())
    grad_fn = jax.value_and_grad(loss_fn, has_aux=True)
    # single-pass fused Adam when the transformation offers it (and the
    # one place ZeRO's reduce-scatter/all-gather constraints live); the
    # grace-window rebuild (recovery.scale_tx) preserves it
    fused_apply = getattr(tx, "fused_apply", None)
    # fault injection, compiled IN: `nan@grad:K` bakes a traced cond on
    # the step counter into the jitted program, so nan_policy="recover"
    # is exercised against an actual non-finite update (consumed at
    # build time — the post-rollback rebuild compiles it out)
    from ddl_tpu.utils import faultinject

    nan_grad_step = faultinject.traced_nan_step()

    def train_step(state, inputs, targets):
        if manual_grad_fn is not None:
            grads, metrics = manual_grad_fn(
                state.params, inputs, targets, state.step
            )
        elif accum_steps == 1:
            (_, (_, metrics)), grads = grad_fn(
                state.params, inputs, targets, state.step
            )
        else:
            k = accum_steps
            b = inputs.shape[0]
            # the chunked batch is TOKEN_SPEC with a leading scan axis
            chunk_sh = NamedSharding(mesh, P(None, *TOKEN_SPEC))
            inp_c = jax.lax.with_sharding_constraint(
                inputs.reshape(k, b // k, *inputs.shape[1:]), chunk_sh
            )
            tgt_c = jax.lax.with_sharding_constraint(
                targets.reshape(k, b // k, *targets.shape[1:]), chunk_sh
            )
            # distinct dropout streams per chunk
            steps = state.step * k + jnp.arange(k)
            grads, metrics = accumulate_grads(
                grad_fn, state.params, (inp_c, tgt_c, steps), k
            )
        grads = poison_nan_grads(state.step, grads, nan_grad_step)
        if fused_apply is not None:
            new_params, new_opt = fused_apply(
                grads, state.opt_state, state.params
            )
        else:
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
        return (
            state.replace(
                step=state.step + 1, params=new_params, opt_state=new_opt
            ),
            metrics,
        )

    def eval_step(state, inputs, targets):
        _, (logits, metrics) = loss_fn(state.params, inputs, targets)
        if logits is None:  # fused CE path computed accuracy in-pass
            return dict(metrics)
        acc = (jnp.argmax(logits, -1) == targets).mean()
        return dict(metrics, accuracy=acc)

    from ddl_tpu.parallel.mesh import with_ambient_mesh

    def _with_mesh(fn):
        return with_ambient_mesh(mesh, fn)

    create = _with_mesh(jax.jit(create_state))
    train = _with_mesh(
        jax.jit(
            train_step,
            in_shardings=(None, tok_sharding, tok_sharding),
            out_shardings=(None, replicated),
            donate_argnums=(0,),
        )
    )
    evaluate = _with_mesh(
        jax.jit(
            eval_step,
            in_shardings=(None, tok_sharding, tok_sharding),
            out_shardings=replicated,
        )
    )
    # machine-readable sharding contract: what this factory promises at
    # its jit boundary, validated by `ddl_tpu lint` (analysis/contracts).
    # Factories pass their rule-table-derived contract (the default
    # covers pipeline callers); optimizer facts are stamped here where
    # the transformation is in hand.
    _zero = getattr(tx, "zero", None)
    train.contract = dict(
        contract if contract is not None else lm_rules().contract(),
        fused_optimizer_update=fused_apply is not None,
        zero_sharding=_zero is not None,
        zero_threshold=_zero.resolved_threshold() if _zero is not None else None,
    )
    # abstract batch structs at an arbitrary batch size, for the
    # compiled-IR probes (analysis/hlolint.py): lowering the same
    # program at two batch shapes and diffing structural fingerprints
    # is how shape-specialized constants are caught
    train.probe_inputs = probe_inputs
    return LMStepFns(
        train=train,
        evaluate=evaluate,
        init_state=lambda: create(rng),
        mesh=mesh,
    )


def make_lm_step_fns(
    cfg: LMConfig,
    spec: LMMeshSpec,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    batch: int,
    seq_len: int,
    devices=None,
    num_microbatches: int = 0,
    accum_steps: int = 1,
    pipeline_schedule: str = "gpipe",
    virtual_stages: int = 1,
    zero_sharding: bool = False,
) -> LMStepFns:
    """Build the sharded train state and jitted step functions.

    ``zero_sharding`` attaches ZeRO-1 weight-update sharding to a fused
    Adam ``tx`` (``train/fused_optim.with_zero`` over the family rule
    table): large leaves' moments and update live on a 1/dp shard of
    ``data``.  Requires the flat (non-pipelined) path and a fused Adam.

    ``batch`` must divide by ``spec.data`` and ``seq_len`` by ``spec.seq``
    (static SPMD shapes).  The manual attention cores are head-parallel over
    ``model``, so ``attn_impl='ring'`` and ``'ulysses'`` need ``cfg.n_heads``
    divisible by ``spec.model``; ``'ulysses'`` additionally needs the local
    head count ``n_heads / model`` divisible by ``spec.seq`` (its all-to-all
    splits heads across the sequence axis).

    With ``spec.pipe > 1`` this delegates to the pipeline-parallel
    implementation (``parallel/lm_pipeline.py``), which runs the decoder
    stack as a GPipe schedule over the ``pipe`` mesh axis with
    ``num_microbatches`` microbatches per step (0 = default to one
    microbatch per stage).
    """
    if accum_steps < 1:
        raise ValueError(f"accum_steps must be >= 1, got {accum_steps}")
    if pipeline_schedule not in PIPELINE_SCHEDULES:
        raise ValueError(f"unknown pipeline schedule {pipeline_schedule!r}")
    cfg = normalize_flash(cfg, spec, seq_len)
    validate_kv_head_sharding(cfg, spec)
    if cfg.ce_vocab_chunk and spec.model > 1:
        raise ValueError(
            f"ce_vocab_chunk={cfg.ce_vocab_chunk} requires mesh model=1 "
            "(the vocab scan slices the head kernel; use ce_chunk, whose "
            "per-chunk matmul shards over 'model')"
        )
    if cfg.ce_chunk and spec.seq > 1:
        raise ValueError(
            f"ce_chunk={cfg.ce_chunk} requires mesh seq=1 (the chunked CE "
            "scans over sequence positions, which conflicts with sequence "
            "sharding — and under SP the per-device logits are already "
            "T/seq smaller, so use the dense CE there)"
        )
    if spec.pipe > 1:
        if accum_steps > 1:
            raise ValueError(
                "accum_steps > 1 is the non-pipelined path's microbatching; "
                "with spec.pipe > 1 use num_microbatches instead"
            )
        if zero_sharding:
            raise ValueError(
                "zero_sharding requires the flat (non-pipelined) step: "
                "the pipeline schedule applies its optimizer inside a "
                "manual shard_map region where the ZeRO sharding "
                "constraints cannot be planted"
            )
        from ddl_tpu.parallel.lm_pipeline import make_lm_pipeline_step_fns

        return make_lm_pipeline_step_fns(
            cfg,
            spec,
            tx,
            rng,
            batch,
            seq_len,
            num_microbatches=num_microbatches or spec.pipe,
            devices=devices,
            schedule=pipeline_schedule,
            virtual_stages=virtual_stages,
        )
    if pipeline_schedule != "gpipe":
        raise ValueError(
            f"pipeline_schedule={pipeline_schedule!r} requires a pipe mesh "
            "axis (spec.pipe > 1)"
        )
    if virtual_stages != 1:
        raise ValueError(
            f"virtual_stages={virtual_stages} requires a pipe mesh axis "
            "(spec.pipe > 1)"
        )
    if num_microbatches > 1:
        raise ValueError(
            f"num_microbatches={num_microbatches} requires a pipe mesh axis "
            "(spec.pipe > 1); the non-pipelined step has no microbatching"
        )
    if accum_steps > 1:
        if batch % accum_steps:
            raise ValueError(
                f"batch {batch} % accum_steps {accum_steps} != 0"
            )
        if (batch // accum_steps) % (spec.data * spec.expert):
            raise ValueError(
                f"accumulation chunk {batch // accum_steps} must divide by "
                f"mesh data*expert={spec.data * spec.expert} (batch shards "
                "over both)"
            )
    if cfg.attn_impl not in ("dense", "ring", "ulysses"):
        raise ValueError(
            f"unknown attn_impl {cfg.attn_impl!r} "
            "(expected 'dense', 'ring', or 'ulysses')"
        )
    if not cfg.causal and (cfg.attn_impl != "dense" or cfg.flash):
        raise ValueError(
            "causal=False (bidirectional encoder) is only implemented for "
            "the XLA dense attention path; the ring/Ulysses/flash cores "
            "are built causal"
        )
    if batch % (spec.data * spec.expert):
        raise ValueError(
            f"batch {batch} must divide by mesh data*expert="
            f"{spec.data * spec.expert} (batch shards over both axes — "
            "outside MoE layers the expert axis is extra data parallelism)"
        )
    if seq_len % spec.seq:
        raise ValueError(f"seq_len {seq_len} must divide by mesh seq={spec.seq}")
    uses_manual_core = cfg.attn_impl in ("ring", "ulysses") or cfg.flash
    if uses_manual_core and cfg.n_heads % spec.model:
        raise ValueError(
            f"n_heads {cfg.n_heads} must divide by mesh model={spec.model} "
            "for the head-parallel manual attention cores"
        )
    if cfg.attn_impl == "ulysses" and (cfg.n_heads // spec.model) % spec.seq:
        raise ValueError(
            f"local head count {cfg.n_heads // spec.model} (n_heads/model) "
            f"must divide by mesh seq={spec.seq} for Ulysses all-to-all "
            "attention (use attn_impl='ring' otherwise)"
        )
    if cfg.attn_impl == "ulysses":
        validate_ulysses_kv_heads(cfg, spec)
    if cfg.num_experts and cfg.num_experts % spec.expert:
        raise ValueError(
            f"num_experts {cfg.num_experts} must divide by mesh "
            f"expert={spec.expert}"
        )
    if cfg.flash and cfg.attn_impl == "dense" and spec.seq > 1:
        raise ValueError(
            "flash=True with attn_impl='dense' requires mesh seq=1 "
            "(the kernel attends within one device's sequence; use "
            "attn_impl='ulysses' to combine flash with sequence parallelism)"
        )
    mesh = build_lm_mesh(spec, devices)
    rules = lm_logical_rules(cfg.fsdp)
    # batch over data AND expert — the same placement as the 'batch'
    # logical rule, so the manual attention cores see the local batch
    # shard instead of forcing an ep-fold replication at their boundary
    manual_spec = LM_MANUAL_ATTN_SPEC
    if cfg.attn_impl == "ring":
        attn_core = make_ring_core(
            mesh, use_flash=bool(cfg.flash), window=cfg.attn_window
        )
    elif cfg.attn_impl == "ulysses":
        attn_core = make_ulysses_self_attention(
            mesh,
            causal=True,
            spec=manual_spec,
            jit=False,
            attn_fn=flash_attention if cfg.flash else None,
            window=cfg.attn_window,
        )
    elif cfg.flash:
        # dense + flash: manual shard_map so the Pallas call sees the local
        # (batch, full seq, local heads) block — GSPMD cannot partition a
        # custom kernel, so it must live inside the manual region.
        attn_core = jax.shard_map(
            partial(flash_attention, causal=True, window=cfg.attn_window),
            mesh=mesh,
            in_specs=(manual_spec,) * 3,
            out_specs=manual_spec,
            check_vma=False,
        )
    else:
        attn_core = None
    model = TransformerLM(cfg, attn_core)

    dummy = jnp.zeros((batch, seq_len), jnp.int32)

    def init_params(rng):
        return model.init(rng, dummy)["params"]

    abs_params = jax.eval_shape(init_params, rng)
    # parameter placement from the family rule table (regex over param
    # path, parallel/rules.py) — leaf-for-leaf the resolution the
    # model's logical annotations used to produce, but declarative,
    # probe-validated, and the base the ZeRO shard derivation reads
    table = lm_rules(cfg.fsdp)
    abs_unboxed = nn.meta.unbox(abs_params)
    param_specs = table.specs(abs_unboxed)
    param_shardings = table.shardings(abs_unboxed, mesh)
    if zero_sharding:
        from ddl_tpu.train.fused_optim import with_zero

        tx = with_zero(tx, mesh, param_specs)

    def create_state(rng):
        params = nn.meta.unbox(init_params(rng))
        params = jax.lax.with_sharding_constraint(params, param_shardings)
        return LMTrainState(
            step=jnp.zeros((), jnp.int32),
            params=params,
            opt_state=tx.init(params),
        )

    def loss_fn(params, inputs, targets, step=None):
        kw = dropout_kwargs(rng, step, cfg.dropout_rate)
        # MoE runs also collect the router stats MoeMlp sows (drop
        # fraction, expert load) into the step metrics
        mutable = ["intermediates"] if cfg.num_experts else False
        router = {}
        with nn.logical_axis_rules(rules):
            if cfg.ce_chunk or cfg.ce_vocab_chunk:
                # chunked head+CE fusion: the model stops at the final
                # norm and the vocab projection runs chunk by chunk inside
                # the loss — the (B, T, V) logits never materialise
                # (ops/losses.fused_chunked_ce token-chunked, or
                # fused_vocab_chunked_ce vocab-streamed).  Eval
                # (step=None) folds next-token accuracy into the pass.
                out = model.apply(
                    {"params": params},
                    inputs,
                    deterministic=kw["deterministic"],
                    rngs=kw["rngs"],
                    return_hidden=True,
                    mutable=mutable,
                )
                if cfg.num_experts:
                    (hidden, aux), col = out
                    router = moe_router_metrics(col["intermediates"])
                else:
                    hidden, aux = out
                loss, (none, metrics) = chunked_ce_loss(
                    cfg, hidden, head_kernel(params["lm_head"]), targets, aux,
                    with_accuracy=step is None,
                )
                return loss, (none, dict(metrics, **router))
            out = model.apply(
                {"params": params},
                inputs,
                deterministic=kw["deterministic"],
                rngs=kw["rngs"],
                mutable=mutable,
            )
            if cfg.num_experts:
                (logits, aux), col = out
                router = moe_router_metrics(col["intermediates"])
            else:
                logits, aux = out
        ce = _token_ce(logits, targets)
        loss = ce + cfg.moe_aux_weight * aux
        metrics = {"loss": loss, "ce": ce, "moe_aux": aux, **router}
        return loss, (logits, metrics)

    return finalize_step_fns(
        mesh, tx, loss_fn, create_state, rng, accum_steps=accum_steps,
        contract=table.contract(),
        probe_inputs=lambda n=batch: (
            jax.ShapeDtypeStruct((n, seq_len), jnp.int32),
            jax.ShapeDtypeStruct((n, seq_len), jnp.int32),
        ),
    )
