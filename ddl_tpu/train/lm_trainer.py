"""LM-family trainer: the transformer LM on the shared training loop.

Round 1-2 trained this family from a bespoke loop in ``examples/train_lm.py``
— 380 lines re-implementing stepping, logging, eval, and checkpointing,
*without* the aux subsystems the CNN Trainer has (no preemption guard, no
NaN watchdog, no profiler hook, opt-in CSV).  That reproduced the
per-script-trainer defect SURVEY.md §1 documents in the reference
(``single.py:92-269`` vs ``ddp.py:102-326``).  This module puts the
flagship family on ``train/loop.BaseTrainer`` instead: SIGTERM now leaves
a resumable snapshot, NaN halts with a pointer at the last good one, CSV
observability is default-on, and ``examples/train_lm.py`` shrinks to an
argparse shim.

The LM is step-based, not epoch-based, so a loop *period* here is a step
window ending at the next cadence boundary — the union of the logging,
eval, and snapshot cadences' multiples — so each cadence fires exactly at
its own multiples (no more, no less; coprime cadences do not collapse the
window to one step).  The CSV 'epoch' column carries the global step at
the period end; per-window walls log as ``window_time`` while
``epoch_time`` keeps its whole-run meaning for cross-family aggregation
(``bench/analysis.epoch_time_per_job``).
"""

from __future__ import annotations

import bisect
import dataclasses
import math
import os
from time import perf_counter

import jax
import jax.numpy as jnp
import numpy as np

from ddl_tpu import checkpoint as ckpt
from ddl_tpu.models.transformer import LMConfig
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.lm_steps import make_lm_step_fns
from ddl_tpu.train.loop import BaseTrainer, _phase
from ddl_tpu.utils import MetricLogger, faultinject

__all__ = ["LMRunConfig", "LMTrainer"]


@dataclasses.dataclass
class LMRunConfig:
    """Run-level settings for the LM family (model/mesh live in
    ``LMConfig`` / ``LMMeshSpec``; this is everything else the old bespoke
    loop took from the command line)."""

    batch: int = 16
    seq_len: int = 256
    steps: int = 100
    num_microbatches: int = 0
    accum_steps: int = 1
    # "gpipe" | "1f1b" | "zb" (parallel/rules.PIPELINE_SCHEDULES): zb is
    # the zero-bubble B/W-split 1F1B — weight grads deferred into the
    # cooldown ticks; requires virtual_stages == 1
    pipeline_schedule: str = "gpipe"
    virtual_stages: int = 1
    # ZeRO-1 optimizer-state sharding over 'data' (requires a fused Adam
    # tx and the flat step path — see TrainConfig.zero_sharding)
    zero_sharding: bool = False
    # data: token corpus path (.npy or raw text; encoded on first use) or
    # None for the synthetic Markov-chain byte stream
    corpus: str | None = None
    eval_every: int = 0  # held-out eval cadence in steps (0 = off)
    eval_frac: float = 0.05  # tail fraction of corpus windows held out
    checkpoint_dir: str | None = None
    save_every: int = 50  # snapshot cadence in steps
    # keep only the newest K valid snapshots (0 = all); corrupt ones
    # never count toward K — see checkpoint.gc_snapshots
    keep_snapshots: int = 0
    resume_step: int | None = None
    # With no explicit resume_step, continue from this job id's latest
    # snapshot automatically when one exists (relaunch == resume).
    auto_resume: bool = True
    job_id: str = "lm"
    log_dir: str | None = "training_logs"  # default-on CSV observability
    log_every: int = 10  # console/CSV cadence in steps
    halt_on_nan: bool = True
    # Non-finite-loss policy: "halt" (round-1 behaviour, honors
    # halt_on_nan) or "recover" (skip the bad window; after
    # nan_max_consecutive hits, roll back to the latest valid snapshot
    # with a reduced-LR grace window — train/recovery.RecoveryPolicy).
    nan_policy: str = "halt"
    nan_max_consecutive: int = 3
    nan_grace_scale: float = 0.1
    nan_grace_periods: int = 2
    preemption_save: bool = True
    profile_dir: str | None = None


class LMTrainer(BaseTrainer):
    period_label = "window"
    time_metric = "window_time"  # epoch_time logs once, as whole-run wall
    best_metric = "val_ppl"
    best_mode = "min"
    best_label = "PPL"

    def __init__(
        self,
        cfg: LMConfig,
        spec: LMMeshSpec,
        tx,
        run: LMRunConfig,
        rng: jax.Array | None = None,
    ) -> None:
        self.cfg, self.spec, self.run = cfg, spec, run
        self.job_id = run.job_id
        self._rng = rng if rng is not None else jax.random.key(0)
        self.tx = tx
        self.fns = self._make_fns(cfg)

        # periods end at the union of the cadences' multiples, so each
        # cadence fires exactly at its own multiples (log 10 / eval 4 ->
        # boundaries 4, 8, 10, 12, ...) and coprime cadences never
        # collapse the window to single steps
        if run.log_every < 1:
            raise ValueError(f"log_every must be >= 1, got {run.log_every}")
        cadences = [run.log_every]
        if run.eval_every:
            cadences.append(run.eval_every)
        if run.checkpoint_dir and run.save_every:
            cadences.append(run.save_every)
        bounds = {run.steps}
        for c in cadences:
            bounds.update(range(c, run.steps + 1, c))
        self._boundaries = sorted(bounds)
        self.num_periods = len(self._boundaries)

        self._build_data()

        proc = jax.process_index()
        self.is_logging_process = proc == 0
        self.logger = (
            MetricLogger(run.log_dir, run.job_id, global_rank=proc,
                         local_rank=proc)
            if run.log_dir
            else None
        )
        self._init_obs(run.log_dir, run.job_id, "lm")
        self._emit_pipe_schedule(
            run.pipeline_schedule, self.spec.pipe,
            run.num_microbatches or self.spec.pipe, run.virtual_stages,
        )
        self.halt_on_nan = run.halt_on_nan
        from ddl_tpu.train.recovery import make_policy

        self.recovery = make_policy(run)
        self.keep_snapshots = run.keep_snapshots
        self.preemption_save = run.preemption_save
        self.profile_dir = run.profile_dir
        self.save_best = bool(run.checkpoint_dir) and bool(run.eval_every)
        self.best_value = float("inf")

        self.state = self.fns.init_state()
        self._start_step = 0
        resume_step = ckpt.resolve_resume(
            run.checkpoint_dir, run.job_id, run.resume_step,
            run.auto_resume, unit="step",
        )
        restore_dur = None
        if run.checkpoint_dir and resume_step is not None:
            from time import perf_counter

            t0 = perf_counter()
            # cross-LAYOUT resume is handled inside _resume; what fails
            # here is a genuinely different model config
            ckpt.run_resume_load(
                lambda: self._resume(resume_step),
                auto=run.resume_step is None,
                desc=f"job {run.job_id!r} step {resume_step}",
                hint="pass --fresh (auto_resume=False)",
            )
            restore_dur = perf_counter() - t0
        # first period whose boundary lies beyond the resume step
        self.periods_run = bisect.bisect_right(
            self._boundaries, self._start_step
        )
        if restore_dur is not None:
            # offset: steps into the resume window already covered by
            # the snapshot (LM periods are step windows, so a step-keyed
            # resume inside a window is the mid-period-cursor analog).
            # Also seed the loop's period-event offset with it, so the
            # resumed window's event states the slice it describes —
            # what the goodput ledger's replay charging compares resume
            # cursors against (_period_bounds already resumes by
            # _start_step; run_period just consumes the one-shot value)
            window_start = (
                self._boundaries[self.periods_run - 1]
                if self.periods_run else 0
            )
            self._resume_offset = max(
                0, self._start_step - window_start
            )
            self._emit_snapshot_restore(
                restore_dur, resume_step, self.periods_run,
                self._resume_offset,
            )

    def _make_fns(self, cfg: LMConfig):
        run = self.run
        from ddl_tpu.train.recovery import scale_tx

        return make_lm_step_fns(
            cfg, self.spec, scale_tx(self.tx, self.update_scale), self._rng,
            run.batch, run.seq_len,
            num_microbatches=run.num_microbatches,
            accum_steps=run.accum_steps,
            pipeline_schedule=run.pipeline_schedule,
            virtual_stages=run.virtual_stages,
            zero_sharding=run.zero_sharding,
        )

    def _rebuild_step_fns(self) -> None:
        self.fns = self._make_fns(self.cfg)

    def _snapshot_store(self):
        run = self.run
        return (run.checkpoint_dir, run.job_id) if run.checkpoint_dir else None

    def _rollback_restore(self, step: int) -> None:
        run = self.run
        self.state, _ = ckpt.load_snapshot(
            run.checkpoint_dir, run.job_id, step, self.state, verify=False
        )
        self._start_step = int(self.state.step)
        self._anchor_shuffle(step)
        self.periods_run = bisect.bisect_right(
            self._boundaries, self._start_step
        )

    def _maybe_anneal_capacity(self, m: dict) -> None:
        """Post-warm-up MoE capacity anneal, keyed off the LIVE router
        drop fraction: once ``moe_drop_frac`` falls under
        ``cfg.capacity_anneal_drop`` the warm-up headroom
        (``capacity_factor``) is pure overhead — drop to
        ``capacity_factor_min`` and rebuild the step functions (one
        recompile; params/optimizer state are capacity-independent, so
        the train state carries over untouched).  See LMConfig's
        capacity_factor_min docs for the measured warm-up/steady-state
        numbers."""
        cfg = self.cfg
        if not cfg.num_experts:
            return
        target = min(cfg.capacity_factor_min, cfg.capacity_factor)
        if cfg.capacity_factor <= target:
            return
        step = int(self.state.step)
        drop = m.get("moe_drop_frac")
        by_metric = drop is not None and drop <= cfg.capacity_anneal_drop
        # step-count fallback: the pipeline path doesn't surface the live
        # drop metric (router stats sown inside the manual pipe region)
        by_step = (
            cfg.capacity_anneal_step and step >= cfg.capacity_anneal_step
        )
        if not (by_metric or by_step):
            return
        reason = (
            f"router drop_frac {drop:.4f} <= {cfg.capacity_anneal_drop}"
            if by_metric
            else f"step {step} >= capacity_anneal_step "
                 f"{cfg.capacity_anneal_step}"
        )
        import dataclasses as _dc

        self.cfg = _dc.replace(cfg, capacity_factor=target)
        self.fns = self._make_fns(self.cfg)
        if self.is_logging_process:
            print(
                f"step {step:4d} | capacity anneal: {reason} — "
                f"capacity_factor {cfg.capacity_factor} -> {target} "
                "(one-time recompile)"
            )

    # ------------------------------------------------------------- data

    def _build_data(self) -> None:
        run = self.run
        self._eval_batches = None
        self._batches = None  # TokenBatches on the corpus path, for
        # shuffle-cursor persistence (save_snapshot/_anchor_shuffle)
        n_proc, proc = jax.process_count(), jax.process_index()
        self._n_proc = n_proc
        if run.corpus:
            # real corpus: memmapped token windows, host-sharded per
            # process; each process loads 1/n_proc of the global batch and
            # the shards are assembled into one global jax.Array
            from ddl_tpu.data.lm_corpus import (
                TokenBatches,
                TokenCorpus,
                encode_text_file,
            )

            if run.batch % n_proc:
                raise ValueError(
                    f"batch {run.batch} must divide by process count {n_proc}"
                )
            path = run.corpus
            if not path.endswith(".npy"):
                npy = path + ".npy"
                stale = not os.path.exists(npy) or (
                    os.path.getmtime(npy) < os.path.getmtime(path)
                )
                if stale and proc == 0:  # encode once, one writer
                    encode_text_file(path, npy)
                if n_proc > 1:
                    from jax.experimental import multihost_utils

                    multihost_utils.sync_global_devices("corpus_encode")
                path = npy
            corpus = TokenCorpus(path, run.seq_len)
            if corpus.max_token() >= self.cfg.vocab_size:
                raise ValueError(
                    f"corpus has token id {corpus.max_token()} but the "
                    f"model's vocab_size is {self.cfg.vocab_size}; "
                    "out-of-range ids would be silently clamped by the "
                    "embedding gather"
                )
            eval_view = None
            if run.eval_every:
                train_view, ev = corpus.split(run.eval_frac)
                if len(ev) >= run.batch:
                    eval_view = ev
                else:
                    # too small to fill one batch: keep every window
                    print(
                        f"note: eval split ({len(ev)} windows) smaller than "
                        f"one batch of {run.batch}; held-out eval disabled — "
                        "grow eval_frac or shrink batch"
                    )
                    train_view = corpus
            else:
                train_view = corpus
            batches = TokenBatches(
                train_view, run.batch // n_proc, n_proc, proc, seed=0
            )
            self._batches = batches
            self._eval_batches = (
                TokenBatches(eval_view, run.batch // n_proc, n_proc, proc,
                             shuffle=False, seed=0)
                if eval_view is not None
                else None
            )
            print(
                f"corpus: {len(corpus)} windows of {run.seq_len}+1 tokens, "
                f"{len(batches)} train batches/epoch/host"
                + (f", {len(self._eval_batches)} eval batches"
                   if self._eval_batches else "")
            )
            self._gspec = None
            if n_proc > 1:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                self._gspec = NamedSharding(
                    self.fns.mesh, P(("data", "expert"), "seq")
                )

            def sample_batch(step):
                # pure in step -> a resumed run continues the stream exactly
                inp, tgt = batches.batch_at(step)
                return self._to_global(inp), self._to_global(tgt)

        else:
            # synthetic corpus: byte sequences from a fixed order-1 Markov
            # chain — learnable structure with a known entropy floor
            # (shared with generate_lm.py via ddl_tpu.data.synthetic_lm)
            from ddl_tpu.data.synthetic_lm import MarkovChain

            if self.cfg.vocab_size < 256:
                raise ValueError(
                    f"synthetic Markov stream emits byte ids 0..255 but "
                    f"vocab_size is {self.cfg.vocab_size}; out-of-range "
                    "targets corrupt the loss — use vocab_size >= 256 or "
                    "pass a corpus"
                )
            chain = MarkovChain()

            def sample_batch(step):
                # seeded by step so a resumed run continues the stream
                # instead of re-consuming batches already trained on
                rng = np.random.default_rng(1000 + step)
                seqs = chain.sample(rng, run.batch, run.seq_len + 1)
                return jnp.asarray(seqs[:, :-1]), jnp.asarray(seqs[:, 1:])

        self._sample_batch = sample_batch

    def _to_global(self, x):
        # multi-host: assemble host shards into one global array
        if self._n_proc > 1:
            return jax.make_array_from_process_local_data(self._gspec, x)
        return jnp.asarray(x)

    # ----------------------------------------------------------- resume

    def _resume(self, resume_step: int) -> None:
        run = self.run
        from ddl_tpu.parallel.lm_pipeline import (
            saved_pipe_stages,
            saved_virtual_stages,
        )

        # The snapshot itself records its layout (pipe stages AND
        # interleaved virtual count) — no flag to get wrong.
        saved_md = ckpt.snapshot_metadata(
            run.checkpoint_dir, run.job_id, resume_step
        )
        saved_pipe = saved_pipe_stages(saved_md["state"]["params"])
        saved_virtual = saved_virtual_stages(saved_md["state"]["params"])
        # auto-discovered steps were integrity-verified by resolve_resume;
        # only an explicit --resume-step still needs the check here
        verify = run.resume_step is not None
        if saved_pipe == self.spec.pipe and saved_virtual == run.virtual_stages:
            self.state, _ = ckpt.load_snapshot(
                run.checkpoint_dir, run.job_id, resume_step, self.state,
                verify=verify,
            )
            print("resumed (snapshots are mesh-independent)")
        else:
            # Cross-layout resume: the snapshot was written with a
            # different pipe stage count (possibly none).  Restore through
            # an abstract skeleton of the saved layout (no init, no step
            # functions — the saved run's batch/mesh/flash settings are
            # irrelevant to the state tree), then restructure params +
            # optimizer state and re-place onto this run's mesh.
            from ddl_tpu.parallel.lm_pipeline import (
                abstract_lm_state,
                convert_lm_state,
            )

            restored, _ = ckpt.load_snapshot(
                run.checkpoint_dir, run.job_id, resume_step,
                abstract_lm_state(
                    self.cfg, self.tx, saved_pipe, mesh=self.fns.mesh,
                    virtual=saved_virtual,
                ),
                verify=verify,
            )
            if self.spec.pipe > 1:
                if saved_pipe > 1:  # restage: merge, then re-split below
                    restored = convert_lm_state(restored)
                self.state = convert_lm_state(
                    restored, n_stages=self.spec.pipe,
                    virtual=run.virtual_stages, like=self.state,
                )
            else:  # saved_pipe > 1 here (layouts differ): merge + place
                self.state = convert_lm_state(restored, like=self.state)
            print(
                f"resumed across layouts (saved pipe={saved_pipe} "
                f"virtual={saved_virtual} -> run pipe={self.spec.pipe} "
                f"virtual={run.virtual_stages})"
            )
        self._start_step = int(self.state.step)
        self._anchor_shuffle(resume_step)
        print(f"continuing from step {self._start_step}")

    def _anchor_shuffle(self, snap_step: int) -> None:
        """Re-anchor the corpus shuffle from the restored snapshot's
        cursor: the persisted (shuffle_epoch, epoch_pos) pins the epoch
        reshuffle trajectory across restarts — including elastic ones
        where the shard layout changed batches/epoch.  Pre-shuffle-cursor
        snapshots anchor nothing (divmod fallback, the old behaviour)."""
        if self._batches is None:
            return
        cur = ckpt.read_cursor(
            self.run.checkpoint_dir, self.run.job_id, snap_step
        )
        if cur and "shuffle_epoch" in cur:
            self._batches.anchor_resume(
                snap_step, cur["shuffle_epoch"], cur.get("epoch_pos", 0)
            )

    # ------------------------------------------------------- loop hooks

    def _period_bounds(self, period: int) -> tuple[int, int]:
        p0 = self._boundaries[period - 1] if period else 0
        return max(p0, self._start_step), self._boundaries[period]

    def run_period(self, period: int, guard=None):
        # one-shot: the resume offset only describes the FIRST resumed
        # window (the loop stamps it into that window's period event;
        # _period_bounds resumes by _start_step regardless)
        self.consume_resume_offset()
        p0, p1 = self._period_bounds(period)
        metrics, steps = {}, 0
        for i in range(p0, p1):
            # data_wait covers corpus sampling AND the host->device /
            # global-array assembly (they are one call here); step is the
            # compiled-step dispatch, whose hidden device time lands in
            # the period-end fence below
            with _phase(self.obs, "data_wait", step=i):
                inp, tgt = self._sample_batch(i)
            with _phase(self.obs, "step", step=i):
                self.state, m = self.fns.train(self.state, inp, tgt)
            # HBM ledger: stamp the train step's static memory budget
            # once, after its first dispatch (obs/hbm.py hbm_plan)
            self.emit_hbm_plan("train_step", self.fns.train,
                               self.state, inp, tgt)
            steps += 1
            faultinject.check_step(i, guard)
            if guard is not None and guard.requested:
                break
        if steps:
            with _phase(self.obs, "fence", step=p0 + steps - 1):
                metrics = {k: float(v) for k, v in m.items()}
            self._maybe_anneal_capacity(metrics)
        return metrics, steps

    def log_index(self, period: int) -> int:
        return self._period_bounds(period)[1]

    def log_due(self, period: int) -> bool:
        # log only at log_every multiples (and the final step), so eval and
        # snapshot boundaries don't densify the CSV/console cadence
        p1 = self._period_bounds(period)[1]
        return p1 % self.run.log_every == 0 or p1 == self.run.steps

    def format_train_line(self, period, elapsed, steps, m) -> str:
        p0, p1 = self._period_bounds(period)
        body = " ".join(f"{k} {v:.4f}" for k, v in m.items())
        return f"step {p1 - 1:4d} {body} ({steps / elapsed:.2f} steps/s)"

    def format_eval_line(self, period, m) -> str:
        return (
            f"  heldout: ce {m['val_loss']:.4f} ppl {m['val_ppl']:.2f}"
        )

    def rate_metrics(self, steps: int, elapsed: float) -> dict:
        tok_s = (steps / elapsed) * self.run.batch * self.run.seq_len
        out = {"tokens_per_sec": tok_s}
        u = self._mfu_estimate(tok_s)
        if u is not None:
            out["mfu"] = u
        return out

    def _mfu_estimate(self, tokens_per_sec: float) -> float | None:
        """Steady-state MFU from the 6ND estimate: ``6 * params *
        tokens/s`` achieved FLOP/s over the pod's peak dense bf16
        FLOP/s.  The analytic transformer train-step cost (fwd 2ND +
        bwd 4ND, attention-core excluded) — coarser than the bench's
        cost-analysis number but free every period, which is what the
        fleet rollup needs.  None off-TPU (peak unknown) — the metric
        is meaningless on the CPU sim."""
        import jax

        from ddl_tpu.bench.mfu import device_peak_flops

        peak = device_peak_flops()
        if peak is None or tokens_per_sec <= 0:
            return None
        if getattr(self, "_param_count", None) is None:
            self._param_count = sum(
                x.size for x in jax.tree_util.tree_leaves(self.state.params)
            )
        total_peak = peak * max(1, jax.device_count())
        return 6.0 * self._param_count * tokens_per_sec / total_peak

    def evaluate_period(self, period: int) -> dict | None:
        run = self.run
        p1 = self._period_bounds(period)[1]
        if (
            self._eval_batches is None
            or not run.eval_every
            or p1 % run.eval_every
        ):
            return None
        ces = []
        for e_inp, e_tgt in self._eval_batches:
            em = self.fns.evaluate(
                self.state, self._to_global(e_inp), self._to_global(e_tgt)
            )
            ces.append(float(em["ce"]))
        ce = float(np.mean(ces))
        return {"val_loss": ce, "val_ppl": math.exp(ce)}

    def snapshot_due(self, period: int) -> bool:
        if not self.run.checkpoint_dir or not self.run.save_every:
            return False
        return self._period_bounds(period)[1] % self.run.save_every == 0

    def save_snapshot(self, period: int) -> None:
        # label with the true optimizer step (preemption can end a period
        # early), so resume_step and the training stream line up exactly
        step = int(jax.device_get(self.state.step))
        # the LM data stream is keyed by global step (sample_batch is
        # pure in step), so step IS the exact-resume cursor; period/
        # offset ride along for the pod sim's no-dup/no-skip audit
        cursor = dict(self.data_cursor or {}, step=step)
        if self._batches is not None:
            # persist the shuffle trajectory too (epoch of the global
            # reshuffle + position within it), so a resume beyond one
            # corpus pass — or under a respec'd data axis, where
            # batches/epoch changed — reseeds the SAME permutation
            # sequence instead of re-deriving it from a divmod against
            # the new epoch length
            cursor.update(self._batches.cursor_state(step))
        path = ckpt.save_snapshot(
            self.run.checkpoint_dir, self.job_id, step, self.state,
            cursor=cursor,
        )
        print(f"step {step} | saved snapshot to {path}")

    def last_snapshot_hint(self):
        if not self.run.checkpoint_dir:
            return "none (set checkpoint_dir)"
        return ckpt.latest_epoch(self.run.checkpoint_dir, self.job_id)

    def resume_hint(self, period: int) -> str:
        step = int(jax.device_get(self.state.step))
        return f"--job-id {self.job_id} --resume-step {step}"

    # --------------------------------------------------------------- run

    def train(self, max_periods: int | None = None, guard=None) -> None:
        if self.run.checkpoint_dir is None and self.preemption_save:
            # nothing to save into: the guard would catch SIGTERM and then
            # fail in save_snapshot — run unguarded instead
            self.preemption_save = False
        t0 = perf_counter()
        super().train(max_periods, guard)
        dt = perf_counter() - t0
        steps_run = int(jax.device_get(self.state.step)) - self._start_step
        if steps_run:
            print(
                f"{steps_run} steps in {dt:.1f}s ({steps_run / dt:.2f} steps/s)"
            )
        if self.logger is not None and self.is_logging_process:
            # whole run as one epoch row, so epoch_time keeps the same unit
            # across families in bench/analysis.epoch_time_per_job
            self.logger.log("epoch_time", dt, 0)
