"""The family-agnostic training loop: one loop for CNN, LM, and ViT.

The reference re-implements its trainer once per entry point (``single.py``
/ ``ddp.py`` / ``pp.py`` / ``ddp_n_pp.py`` each carry a near-identical
``Trainer`` class — SURVEY.md §1); round 1-2 of this framework fixed that
for the CNN family but re-grew the disease for the beyond-parity LM/ViT
families as bespoke example loops.  This module is the fix: every generic
concern lives here exactly once —

* the period loop (a period is an epoch for the vision families, a fixed
  step window for the LM family) with wall-clock timing,
* default-on CSV metric logging (``utils/csv_logger.MetricLogger``),
* the NaN policy: halt with a pointer at the last good snapshot
  (``nan_policy="halt"``), or recover in-loop (``"recover"``): skip the
  bad period's metrics/eval/snapshot, and after K consecutive hits roll
  back to the last valid snapshot with a reduced-LR grace window
  (``train/recovery.RecoveryPolicy``),
* the ``jax.profiler`` trace hook (one post-warmup period),
* preemption handling (SIGTERM → finish the in-flight period → snapshot →
  clean exit, ``utils/preemption.PreemptionGuard``),
* snapshot gating: best-eval-metric improvements (QWK for the vision
  families, val perplexity for the LM) and/or a fixed cadence,
* HBM watermark logging (``utils/memory.hbm_stats``),
* fault-injection hooks (``utils/faultinject``) so every recovery path
  above is provable by a CPU-only test.

Families subclass :class:`BaseTrainer` and implement only what is genuinely
family-specific: how to run one period, how to evaluate, and how to write a
snapshot.  ``train/trainer.py`` (CNN), ``train/lm_trainer.py`` and
``train/vit_trainer.py`` are the three instantiations.
"""

from __future__ import annotations

import os
from contextlib import nullcontext
from time import perf_counter

import jax
import numpy as np

from ddl_tpu.utils import faultinject
from ddl_tpu.utils.memory import hbm_stats

__all__ = ["BaseTrainer"]


def _phase(obs, name: str, step: int | None = None):
    """Obs phase context, or a no-op when the trainer runs untraced."""
    return obs.phase(name, step=step) if obs is not None else nullcontext()


class BaseTrainer:
    """Template-method training loop.

    Subclass contract — attributes (set in ``__init__``):
      ``state``              the (donated/rebound) train state
      ``job_id``             job identity for logs and snapshots
      ``logger``             a ``MetricLogger`` or ``None``
      ``is_logging_process`` whether this host writes CSV rows
      ``periods_run``        resume cursor (first period to run)
      ``num_periods``        total periods in a full run
      ``halt_on_nan``        raise on non-finite training loss
      ``preemption_save``    install a SIGTERM guard around the run
      ``profile_dir``        trace one post-warmup period here (or None)
      ``save_best``          gate snapshots on eval-metric improvements
      ``best_metric``        eval-dict key for the gate (or None)
      ``best_mode``          "max" (accuracy-like) or "min" (loss-like)
      ``best_value``         current best (init -inf for max, +inf for min)

    and methods:
      ``run_period(period, guard) -> (train_metrics: dict, steps: int)``
          run one period, rebinding ``self.state``; poll
          ``guard.requested`` at step boundaries and stop early when set.
      ``evaluate_period(period) -> dict | None``
          eval metrics for this period boundary, or None to skip.
      ``save_snapshot(period) -> None``
          write a resumable snapshot for this period.
      ``wait_for_saves() -> None``
          block until async snapshot writes commit (default no-op).

    Optional overrides: ``rate_metrics`` (extra throughput rows),
    ``snapshot_due`` (fixed save cadence), ``format_train_line`` /
    ``format_eval_line`` (console output), ``period_label``,
    ``best_label``, ``resume_hint``.
    """

    period_label = "Epoch"
    # CSV name for the per-period wall time; step-based families relabel it
    # (their periods are windows, not epochs) and log their own epoch_time.
    time_metric = "epoch_time"
    # Structured event tracing (obs/steptrace.StepTrace), set by families
    # that construct an EventWriter; None runs the loop untraced.
    obs = None
    # Hung-step watchdog deadline in seconds (0/None = off); families may
    # set it, and the DDL_WATCHDOG_S env var is the operator override.
    watchdog_s = None
    # In-loop non-finite-loss recovery (train/recovery.RecoveryPolicy) or
    # None; with None, halt_on_nan keeps its round-1 halt semantics.
    recovery = None
    # Update scaling during a post-rollback grace window; families that
    # can honor it override set_update_scale (one step-fn rebuild).
    update_scale = 1.0
    # True after a preemption-triggered early exit — the CLI turns this
    # into the supervisor's resumable exit code when supervised.
    preempted = False
    # Snapshot garbage collection: keep the newest K *valid* snapshots
    # (corrupt ones never count toward K — checkpoint.gc_snapshots);
    # 0 = unlimited.  Families set it from their run config.
    keep_snapshots = 0
    # The best-eval-metric snapshot's store key (set by the loop when a
    # save was gated on improvement): GC never deletes it — keep bounds
    # the cadence retention, not the best-model one.
    best_snapshot_epoch = None
    # The data-stream position the NEXT snapshot represents, set by the
    # loop before every save_snapshot call: {"period", "offset"} where
    # offset is the number of batches this period had consumed when the
    # state was captured (0 for a period-boundary save, partial for a
    # preemption save).  Families record it in the snapshot manifest
    # (checkpoint.save_snapshot(cursor=...)) so an exact resume replays
    # no batch and skips none (checkpoint.read_cursor).
    data_cursor = None
    # Batches of the resume period already consumed by the snapshot being
    # restored (from its cursor); the family's run_period skips them.
    _resume_offset = 0

    def consume_resume_offset(self) -> int:
        """The batch offset the first resumed period starts at; one-shot
        (subsequent periods start at 0)."""
        offset, self._resume_offset = self._resume_offset, 0
        return offset

    # ---------------------------------------------------------- overrides

    def rate_metrics(self, steps: int, elapsed: float) -> dict:
        """Extra per-period throughput metrics (tokens/sec, img/sec, ...)."""
        return {}

    # Measured once per process (placement is static after build); the
    # loop stamps it into every period event's rates so `obs export`/
    # `obs fleet` can gauge per-device optimizer-state HBM — the number
    # ZeRO sharding exists to shrink.
    _opt_hbm_cache = None

    def opt_state_hbm_bytes(self) -> int | None:
        """Per-device bytes of this run's live optimizer state: each
        leaf's actual shard shape (so ZeRO/TP sharding is reflected)
        times its dtype width.  None when no state is held."""
        if self._opt_hbm_cache is not None:
            return self._opt_hbm_cache
        import math

        opt_state = getattr(getattr(self, "state", None), "opt_state", None)
        if opt_state is None:
            return None
        total = 0
        for leaf in jax.tree.leaves(opt_state):
            shape = getattr(leaf, "shape", None)
            dtype = getattr(leaf, "dtype", None)
            if shape is None or dtype is None:
                continue
            sharding = getattr(leaf, "sharding", None)
            try:
                shard_shape = (
                    sharding.shard_shape(shape)
                    if sharding is not None else shape
                )
            except (TypeError, ValueError):
                shard_shape = shape
            total += math.prod(shard_shape) * dtype.itemsize
        self._opt_hbm_cache = total
        return total

    # Param-shard bytes, measured once like the optimizer gauge; the
    # second tracked category of the HBM ledger (obs/hbm.py).
    _param_hbm_cache = None
    # program labels this trainer has already stamped an hbm_plan for
    _hbm_planned = None

    def param_hbm_bytes(self) -> int | None:
        """Per-device bytes of this run's live parameters (actual shard
        shapes, so ZeRO-3/TP sharding is reflected); None when no
        parameter tree is held."""
        if self._param_hbm_cache is not None:
            return self._param_hbm_cache
        from ddl_tpu.obs.hbm import tree_shard_bytes

        params = getattr(getattr(self, "state", None), "params", None)
        self._param_hbm_cache = tree_shard_bytes(params)
        return self._param_hbm_cache

    def emit_hbm_plan(self, label: str, fn, *args, **kwargs) -> None:
        """Stamp one ``hbm_plan`` static budget for a compiled program,
        once per label per trainer.  Families call it right AFTER the
        program's first dispatch (the run's own compile has happened;
        the plan's AOT lower->compile then rides the XLA compile caches
        instead of racing the first step).  Costs one extra backend
        compile per program when the persistent cache is cold —
        ``DDL_HBM_PLAN=off`` disables, ``=aval`` keeps the cheap
        shape-arithmetic budget without the executable analysis."""
        if self.obs is None:
            return
        if self._hbm_planned is None:
            self._hbm_planned = set()
        if label in self._hbm_planned:
            return
        self._hbm_planned.add(label)
        mode = os.environ.get("DDL_HBM_PLAN", "").lower()
        if mode in ("0", "off", "false"):
            return
        from ddl_tpu.obs import hbm

        hbm.plan_program(
            self.obs.writer, label, fn, args, kwargs,
            mode="aval" if mode == "aval" else "full",
        )

    def _emit_hbm_sample(self, step=None, context=None) -> None:
        """One ``hbm_sample`` live breakdown: tracked params/optimizer
        bytes against the device watermark (obs/hbm.live_sample)."""
        if self.obs is None:
            return
        from ddl_tpu.obs import hbm

        hbm.live_sample(
            self.obs.writer,
            params_bytes=self.param_hbm_bytes(),
            opt_bytes=self.opt_state_hbm_bytes(),
            step=step,
            context=context,
        )

    def snapshot_due(self, period: int) -> bool:
        """Fixed-cadence snapshots, independent of the best-metric gate."""
        return False

    def log_due(self, period: int) -> bool:
        """Whether this period boundary is a logging/printing point.  Epoch
        families log every epoch; the LM gates on its ``log_every`` cadence
        so eval/save boundaries don't add extra log rows."""
        return True

    def wait_for_saves(self) -> None:
        return None

    def _snapshot_store(self) -> tuple | None:
        """``(checkpoint_dir, job_id)`` when this trainer checkpoints,
        else None — the handle the rollback template walks for valid
        snapshots.  Families with checkpointing override; the default
        keeps checkpoint-less runs on the halt path."""
        return None

    def _rebuild_step_fns(self) -> None:
        """Rebuild the compiled step functions after the optimizer wrap
        changed (grace entry/exit, ``recovery.scale_tx``).  Default
        no-op for stubs/tests."""

    def _rollback_restore(self, epoch: int) -> None:
        """Restore ``self.state`` from the (already-verified) snapshot
        ``epoch`` and rewind the family's resume cursor."""
        raise NotImplementedError

    # one agreement key per in-loop rollback this process performs: the
    # NaN-recovery path is SPMD-identical across hosts (every host sees
    # the same non-finite loss at the same period), so the counter
    # advances in lockstep and scopes each rollback's rank-0 agreement
    _rollback_seq = 0

    def rollback_to_snapshot(self) -> bool:
        """Restore the latest *valid* snapshot and rewind the resume
        cursor; return False when there is nothing to roll back to.

        On a pod, WHICH snapshot is the rollback target is a rank-0
        agreement (``coord.agreed_rollback_epoch``), not a per-host
        ``latest_valid_epoch`` walk: under a torn NAS view (host A sees
        snapshot 12 committed, host B still sees 11) per-host choices
        diverge and the restored worlds silently fork."""
        store = self._snapshot_store()
        if store is None:
            return False
        self.wait_for_saves()  # commit any in-flight async snapshot first
        from ddl_tpu import checkpoint as ckpt
        from ddl_tpu import coord

        seq = self._rollback_seq
        self._rollback_seq = seq + 1
        epoch = coord.agreed_rollback_epoch(
            store[1], lambda: ckpt.latest_valid_epoch(*store), seq
        )
        if epoch is None:
            return False
        self._rollback_restore(epoch)
        print(f"[recovery] restored snapshot {epoch}")
        return True

    def _gc_snapshots(self) -> None:
        """Keep-last-K snapshot GC after a save (no-op unless the family
        checkpoints and ``keep_snapshots`` > 0).  Only the logging
        process prunes — every host shares the snapshot store."""
        store = self._snapshot_store()
        if (
            not self.keep_snapshots
            or store is None
            or not getattr(self, "is_logging_process", True)
        ):
            return
        from ddl_tpu import checkpoint as ckpt

        protect = (
            (self.best_snapshot_epoch,)
            if self.best_snapshot_epoch is not None else ()
        )
        for path, reason in ckpt.gc_snapshots(
            *store, keep=self.keep_snapshots, protect=protect
        ):
            print(f"[gc] removed snapshot {path}: {reason}")

    def set_update_scale(self, scale: float) -> None:
        """Scale subsequent optimizer updates by ``scale`` (the
        reduced-LR grace after a rollback): one step-function rebuild
        per dial turn, state-tree-identical (``recovery.scale_tx``)."""
        if scale == self.update_scale:
            return
        self.update_scale = scale
        self._rebuild_step_fns()

    def _note_io_retry(self, exc: BaseException, attempt: int) -> None:
        """Data-loader retry callback: count transient-I/O retries into
        the obs event stream so a degrading NAS is visible before it
        becomes an outage."""
        self.io_retries = getattr(self, "io_retries", 0) + 1
        if self.obs is not None:
            self.obs.writer.emit(
                "io_retry", error=str(exc), attempt=attempt
            )

    def _init_obs(self, log_dir, job_id: str, family: str) -> None:
        """Shared trainer wiring for the structured event stream (every
        host writes its own file; obs/events.py).  No-op without a log
        dir, so the obs story tracks the CSV one.

        File attribution goes through ``launch.host_id`` — the launcher
        env (``DDL_HOST_ID``/``DDL_PROCESS_ID``) wins over the JAX
        process index.  Identical on a real multihost pod, but sim-pod
        children are each JAX process 0 and must not merge into one
        stream (``obs pod`` attributes skew by stream)."""
        if log_dir:
            from ddl_tpu.launch import host_id
            from ddl_tpu.obs import StepTrace

            self.obs = StepTrace.create(log_dir, job_id, family, host=host_id())
            # warm-restart observability: one compile_cache event per
            # incarnation (no-op when the persistent cache is off) — the
            # warm-relaunch drill reads warm/entries_before next to
            # restart_latency and the recompile goodput bucket
            from ddl_tpu.utils.compile_cache import emit_cache_event

            emit_cache_event(self.obs.writer)

    def _emit_snapshot_restore(
        self, dur: float, epoch, period: int, offset: int = 0
    ) -> None:
        """One ``snapshot_restore`` event per startup restore: how long
        the restore took (the goodput ledger's ``checkpoint`` bucket —
        today only the in-loop save is a traced phase) plus the resume
        cursor the restored state represents (``period``/``offset``),
        from which the ledger charges a prior incarnation's periods
        beyond the cursor as rolled-back (replayed) work.  Families call
        it right after their startup restore; the in-loop rollback path
        stays on the ``rollback`` event instead (emitting both would
        double-charge the replay)."""
        if self.obs is None:
            return
        self.obs.writer.emit(
            "snapshot_restore",
            dur=dur,
            epoch=epoch,
            period=int(period),
            offset=int(offset),
        )
        # the restored state is the startup-resident memory: account it
        # before the first period's sample (the ledger's restore column)
        self._emit_hbm_sample(context="restore")

    def _emit_pipe_schedule(
        self, schedule: str, pipe: int, microbatches: int, virtual: int = 1
    ) -> None:
        """One ``pipe_schedule`` event per run when pipeline parallelism
        is active: the schedule's identity plus the modeled per-stage
        F/B/W/idle accounting (``obs/schedule_model.py``).  The schedule
        is static for the whole run, so one event suffices — ``obs
        trace --step`` recomputes the lanes from these parameters and
        scales them into any step's measured window, and ``obs
        summarize`` renders the bubble line.  Combinations the model
        does not cover (interleaved 1F1B) emit the identity fields with
        the modeled ones null."""
        if self.obs is None or pipe <= 1:
            return
        from ddl_tpu.obs.schedule_model import schedule_summary

        try:
            summ = schedule_summary(schedule, pipe, microbatches, virtual)
        except ValueError:
            summ = {}
        self.obs.writer.emit(
            "pipe_schedule",
            schedule=schedule,
            pipe=pipe,
            microbatches=microbatches,
            virtual=virtual,
            makespan=summ.get("makespan"),
            idle_units=summ.get("idle_units"),
            bubble_fraction=summ.get("bubble_fraction"),
            per_stage=summ.get("per_stage"),
        )

    @property
    def best_label(self) -> str:
        return (self.best_metric or "metric").upper()

    def resume_hint(self, period: int) -> str:
        return f"job_id={self.job_id} {self.period_label.lower()}={period}"

    def format_train_line(
        self, period: int, elapsed: float, steps: int, metrics: dict
    ) -> str:
        body = " | ".join(f"{k}: {v:.4f}" for k, v in metrics.items())
        return (
            f"{self.period_label} {period} | Time: {elapsed:.2f}s | "
            f"Steps: {steps} | {body}"
        )

    def format_eval_line(self, period: int, metrics: dict) -> str:
        body = " | ".join(f"{k}: {v:.4f}" for k, v in metrics.items())
        return f"{self.period_label} {period} | {body}"

    def log_index(self, period: int) -> int:
        """CSV 'epoch' column for this period (LM maps periods to steps)."""
        return period

    # ------------------------------------------------------------- gating

    def _improved(self, eval_metrics: dict | None) -> bool:
        if (
            not self.save_best
            or self.best_metric is None
            or not eval_metrics
            or self.best_metric not in eval_metrics
        ):
            return False
        value = float(eval_metrics[self.best_metric])
        better = value > self.best_value if self.best_mode == "max" else (
            value < self.best_value
        )
        if better:
            self.best_value = value
            print(f"New Best Validation {self.best_label}: {value:.4f}")
        return better

    # ---------------------------------------------------------- the loop

    def train(self, max_periods: int | None = None, guard=None) -> None:
        from ddl_tpu.utils.preemption import PreemptionGuard

        if guard is None and self.preemption_save:
            # enter the loop directly (not through self.train) so family
            # overrides wrapping train() run exactly once
            with PreemptionGuard() as installed:
                return self._train_loop(max_periods, installed)
        return self._train_loop(max_periods, guard)

    def _train_loop(self, max_periods: int | None, guard) -> None:
        max_periods = max_periods or self.num_periods
        obs = self.obs
        watchdog = None
        if obs is not None:
            # the env var is the operator OVERRIDE (set it to raise the
            # deadline past a long first compile, or to 0 to disable),
            # so it wins over a family-set watchdog_s
            env = os.environ.get("DDL_WATCHDOG_S")
            if env not in (None, ""):
                deadline = float(env)
            else:
                deadline = self.watchdog_s or 0
            if deadline > 0:
                from ddl_tpu.obs.watchdog import Watchdog

                # under supervision (DDL_SUPERVISED) the supervisor sets
                # DDL_WATCHDOG_ACTION=exit: stall -> dump stacks -> exit
                # resumable -> relaunch, instead of hanging forever
                action = os.environ.get("DDL_WATCHDOG_ACTION", "dump")
                watchdog = Watchdog(
                    obs.writer, deadline, on_stall=action,
                    capturer=obs.capturer,
                ).start()
                obs.watchdog = watchdog
        try:
            self._run_periods(max_periods, guard, obs)
        except Exception as exc:
            # allocation failure: dump the forensic memory snapshot
            # (resident buffers + the plans that predicted them) into
            # the event stream before the process dies — the memory
            # analogue of the watchdog's stack dump
            if obs is not None:
                from ddl_tpu.obs import hbm

                if hbm.is_oom_error(exc):
                    hbm.dump_oom(
                        obs.writer, exc,
                        params_bytes=self.param_hbm_bytes(),
                        opt_bytes=self.opt_state_hbm_bytes(),
                    )
            raise
        finally:
            if watchdog is not None:
                watchdog.stop()
            if obs is not None:
                obs.finish(verbose=getattr(self, "is_logging_process", True))

    def _run_periods(self, max_periods: int, guard, obs) -> None:
        # Profile one post-warmup period when configured (the reference's
        # only timing is perf_counter epoch walls, single.py:171-174; this
        # captures a full XLA device trace instead).
        profile_period = None
        if self.profile_dir:
            profile_period = min(self.periods_run + 1, max_periods - 1)
        # a while over the resume cursor, not a for over a frozen range:
        # the recovery policy's rollback rewinds periods_run mid-run
        while self.periods_run < max_periods:
            period = self.periods_run
            if period == profile_period:
                jax.profiler.start_trace(self.profile_dir)
            if obs is not None:
                obs.begin_period(period)
            start = perf_counter()
            # where this period's data stream starts (nonzero only for
            # the first period after an exact mid-period resume) — a
            # preemption cursor must record skip + steps, not just steps
            offset_base = self._resume_offset
            train_metrics, steps = self.run_period(period, guard)
            elapsed = perf_counter() - start
            if period == profile_period:
                jax.profiler.stop_trace()
                self._print_profile_digest()
            train_metrics = faultinject.poison_loss(train_metrics)
            loss = train_metrics.get("loss")
            idx = self.log_index(period)
            # one rate_metrics call per period, shared by the CSV rows
            # and the period obs event (the fleet rollup reads MFU and
            # the family throughput rates from the event stream)
            rates = self.rate_metrics(steps, elapsed)
            opt_hbm = self.opt_state_hbm_bytes()
            if opt_hbm:
                rates.setdefault("opt_hbm_bytes", opt_hbm)
            if loss is not None and not np.isfinite(loss):
                handled = self._handle_nonfinite(period, idx, loss, obs)
                if handled:
                    # the bad period is not logged/evaluated/snapshotted;
                    # its period event still flows (the obs stream must
                    # show the excursion, not hide it)
                    if obs is not None:
                        obs.end_period(
                            period, idx, elapsed, steps, train_metrics,
                            rates=rates, offset=offset_base,
                        )
                    if guard is not None and guard.requested:
                        # preempted mid-recovery: exit inside the grace
                        # window NOW, without snapshotting the poisoned
                        # period — the relaunch resumes from the last
                        # good snapshot
                        self.preempted = True
                        self.wait_for_saves()
                        print(
                            f"Preempted during non-finite-loss recovery "
                            f"at {self.period_label.lower()} {period}; "
                            f"exiting without snapshotting the poisoned "
                            f"period. Last good snapshot: "
                            f"{self.last_snapshot_hint()}"
                        )
                        return
                    continue
                if self.halt_on_nan:
                    raise RuntimeError(
                        f"Non-finite training loss {loss} at "
                        f"{self.period_label.lower()} {period}; halting. "
                        f"Last snapshot: {self.last_snapshot_hint()}"
                    )
            elif self.recovery is not None and self.recovery.on_finite():
                self.set_update_scale(1.0)
                print(
                    "[recovery] grace window over; update scale back to 1.0"
                )
            if self.log_due(period):
                with _phase(obs, "logging", step=idx):
                    print(
                        self.format_train_line(
                            period, elapsed, steps, train_metrics
                        )
                    )
                    if self.logger is not None and self.is_logging_process:
                        self.logger.log_many(train_metrics, idx)
                        self.logger.log(self.time_metric, elapsed, idx)
                        # steps/sec/chip is BASELINE.json's target metric;
                        # the reference only logs epoch_time (steps derived
                        # offline).
                        self.logger.log("steps_per_sec", steps / elapsed, idx)
                        self.logger.log_many(rates, idx)
                        # HBM watermark (no reference analog; utils/memory.py)
                        mem = hbm_stats()
                        if mem is not None:
                            self.logger.log(
                                "hbm_peak_bytes", mem["peak_bytes_in_use"], idx
                            )

            with _phase(obs, "eval", step=idx):
                eval_metrics = self.evaluate_period(period)
            if eval_metrics:
                with _phase(obs, "logging", step=idx):
                    print(self.format_eval_line(period, eval_metrics))
                    if self.logger is not None and self.is_logging_process:
                        self.logger.log_many(eval_metrics, idx)

            improved = self._improved(eval_metrics)
            if improved or self.snapshot_due(period):
                with _phase(obs, "checkpoint", step=idx):
                    # a boundary save: the period's data is fully consumed
                    self.data_cursor = {"period": period + 1, "offset": 0}
                    self.save_snapshot(period)
                    if improved:
                        # idx is the snapshot's store key in every
                        # family (epoch for CNN/ViT, the boundary step
                        # for the LM — the same mapping save_snapshot
                        # uses); GC must never reap the best model
                        self.best_snapshot_epoch = idx
                    self._gc_snapshots()
            preempted = guard is not None and guard.requested
            if preempted:
                # Preempted (SIGTERM): checkpoint what we have and exit
                # cleanly; the partially-trained period is saved under its
                # own number, so the relaunch resumes at the next one.
                # Save BEFORE end_period so the blocking final commit —
                # the interesting cost of a preempted run — lands in this
                # period's checkpoint phase total.
                with _phase(obs, "checkpoint", step=idx):
                    # a mid-period save: record how far into the period's
                    # data stream the state got, so the resumed run
                    # re-enters THIS period at that offset instead of
                    # skipping the period's remaining batches
                    self.data_cursor = {
                        "period": period, "offset": offset_base + steps
                    }
                    self.save_snapshot(period)
                    self.wait_for_saves()
                    self._gc_snapshots()
            if obs is not None:
                obs.end_period(
                    period, idx, elapsed, steps, train_metrics,
                    rates=rates, offset=offset_base,
                )
                # HBM ledger: one live per-category breakdown per period
                # beside the period event's bare watermark (obs/hbm.py)
                self._emit_hbm_sample(step=idx)
            self.periods_run = period + 1
            if preempted:
                self.preempted = True
                print(
                    f"Preempted at {self.period_label.lower()} {period}; "
                    f"snapshot committed. Resume with {self.resume_hint(period)}"
                )
                return
        self.wait_for_saves()

    def _print_profile_digest(self) -> None:
        """Render the captured period's per-op digest right at the run
        (the ROADMAP's "open every perf PR with a digest" rule: the
        trainer's own ``profile_dir`` hook now hands over the top-op
        table instead of a bare trace directory — same renderer as
        ``ddl_tpu bench digest``).  Digest failures never cost the run."""
        if not getattr(self, "is_logging_process", True):
            return
        try:
            from ddl_tpu.bench.xprof import op_digest

            dig = op_digest(self.profile_dir, top=5)
            ops = "  ".join(
                f"{k}={v:.1f}ms" for k, v in dig["ops"].items()
            )
            print(
                f"[profile] trace {self.profile_dir}: "
                f"total {dig['total_ms']:.1f}ms — {ops}"
            )
            print(
                f"[profile] full table: ddl_tpu bench digest "
                f"{self.profile_dir}"
            )
        except Exception as e:  # ddl-lint: disable=broad-except — a
            # digest render failure (exotic trace layout, missing plane)
            # must never kill a training run; the trace itself is already
            # on disk and the message points at it
            print(f"[profile] digest unavailable ({e}); trace in "
                  f"{self.profile_dir}")

    def _handle_nonfinite(self, period, idx, loss, obs) -> bool:
        """Recovery-policy reaction to a non-finite period loss; returns
        True when the policy absorbed it (skip or rollback), False to
        fall through to halt_on_nan."""
        if self.recovery is None:
            return False
        pol = self.recovery
        action = pol.on_nonfinite()
        if obs is not None:
            obs.anomaly.record(
                idx,
                "nonfinite_loss",
                value=float(loss),
                consecutive=pol.consecutive,
                action=action,
            )
        label = self.period_label.lower()
        if action == "skip":
            print(
                f"[recovery] non-finite loss ({loss}) at {label} {period}: "
                f"skipping the period "
                f"({pol.consecutive}/{pol.max_consecutive} consecutive)"
            )
            self.periods_run = period + 1
            return True
        if pol.rollbacks >= pol.max_rollbacks:
            raise RuntimeError(
                f"Non-finite training loss persisted through "
                f"{pol.rollbacks} rollback(s); giving up. "
                f"Last snapshot: {self.last_snapshot_hint()}"
            )
        restore_t0 = perf_counter()
        if not self.rollback_to_snapshot():
            raise RuntimeError(
                f"Non-finite training loss for {pol.consecutive} "
                f"consecutive {label}s and no snapshot to roll back to. "
                f"Last snapshot: {self.last_snapshot_hint()}"
            )
        hits = pol.consecutive
        pol.on_rollback()
        self.set_update_scale(pol.grace_scale)
        if obs is not None:
            # period: the bad period in PERIOD units (step=idx is the
            # CSV/log index, a step number for the LM family) — the
            # goodput ledger charges the rolled-back periods >= resumed_at
            # plus this pending bad one as replayed work; restore_dur
            # books the rollback restore into the checkpoint bucket
            obs.writer.emit(
                "rollback",
                step=idx,
                period=period,
                resumed_at=self.periods_run,
                restore_dur=perf_counter() - restore_t0,
                grace_scale=pol.grace_scale,
                grace_periods=pol.grace_periods,
            )
        print(
            f"[recovery] non-finite loss for {hits} consecutive {label}s: "
            f"rolled back to {label} {self.periods_run}; reduced-LR grace "
            f"x{pol.grace_scale} for {pol.grace_periods} {label}(s)"
        )
        return True

    def last_snapshot_hint(self):
        return "none"
