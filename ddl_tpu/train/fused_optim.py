"""Fused Adam: the optimizer update as one fusible expression per leaf.

The round-4 DenseNet op digest puts "elementwise/reduce fusions" (BN
stats, Adam, loss) at 17.5% of device time.  ``optax.adam``'s update is
structured as a *chain of tree passes* — ``scale_by_adam`` builds a new
``mu`` tree, a new ``nu`` tree, a bias-corrected updates tree, then
``scale`` and ``optax.apply_updates`` each walk the tree again — which
hands XLA several independent per-leaf HLO chains with materialised
updates trees between them.  This module computes the whole update —
new ``mu``, new ``nu``, and the new *parameter* — in ONE ``tree_map``
pass per leaf (``fused_apply``), so each parameter's update lowers to a
single fusible elementwise expression reading (g, mu, nu, p) and
writing (mu', nu', p') with no intermediate updates tensor, and XLA is
free to fuse it straight onto the last gradient reduction that produced
``g``.

Drop-in constraints, both load-bearing:

* **State tree is bit-identical to ``optax.adam``'s** (``init``
  delegates to it): ``(ScaleByAdamState(count, mu, nu), ScaleState)``
  for a constant lr, ``(..., ScaleByScheduleState(count))`` for a
  schedule — existing snapshots restore into the fused optimizer and
  vice versa.
* **The math is ``optax.adam``'s exactly** (same b1/b2/eps, same
  ``1 - b**count_inc`` bias correction, ``eps_root=0``), asserted by
  ``tests/test_optimizer.py`` against optax step by step.

The standard ``update`` endpoint (returns an updates tree, for
``optax.apply_updates``) is also provided so the transformation works
anywhere a ``GradientTransformation`` does — ``recovery.scale_tx``, the
pipeline step factories — while step factories that know about
``fused_apply`` (``train/steps.py``) take the single-pass path.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

__all__ = ["FusedAdam", "fused_adam"]


class FusedAdam(NamedTuple):
    """``optax.GradientTransformation`` surface (init/update) plus the
    single-pass ``fused_apply(grads, state, params) -> (new_params,
    new_state)`` endpoint step factories fuse into the jitted step."""

    init: Callable[..., Any]
    update: Callable[..., Any]
    fused_apply: Callable[..., Any]


def fused_adam(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
) -> FusedAdam:
    """Adam with ``optax.adam``-identical math and state tree, computed
    in one tree pass.  ``learning_rate`` may be a float or an optax
    schedule (callable of the step count)."""
    ref = optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
    schedule = callable(learning_rate)

    def init(params):
        return ref.init(params)

    def _step(grads, state, params):
        """One fused pass.  Returns (out, new_state) where ``out`` is the
        new params tree when ``params`` is given (fused_apply) and the
        updates tree otherwise (the optax ``update`` endpoint)."""
        adam_state, lr_state = state
        count_inc = optax.safe_int32_increment(adam_state.count)
        if schedule:
            # scale_by_schedule semantics: scale by f(count), then inc
            lr_now = learning_rate(lr_state.count)
            new_lr_state = lr_state._replace(
                count=optax.safe_int32_increment(lr_state.count)
            )
        else:
            lr_now = learning_rate
            new_lr_state = lr_state
        c1 = 1.0 - b1 ** count_inc.astype(jnp.float32)
        c2 = 1.0 - b2 ** count_inc.astype(jnp.float32)

        def leaf(g, mu, nu, p):
            mu2 = b1 * mu + (1.0 - b1) * g
            nu2 = b2 * nu + (1.0 - b2) * (g * g)
            u = -lr_now * (mu2 / c1) / (jnp.sqrt(nu2 / c2) + eps)
            return (u if p is None else p + u), mu2, nu2

        g_leaves, treedef = jax.tree.flatten(grads)
        mu_leaves = jax.tree.leaves(adam_state.mu)
        nu_leaves = jax.tree.leaves(adam_state.nu)
        p_leaves = (
            jax.tree.leaves(params) if params is not None
            else [None] * len(g_leaves)
        )
        trips = [
            leaf(g, m, n, p)
            for g, m, n, p in zip(g_leaves, mu_leaves, nu_leaves, p_leaves)
        ]
        out = treedef.unflatten([t[0] for t in trips])
        new_state = (
            adam_state._replace(
                count=count_inc,
                mu=treedef.unflatten([t[1] for t in trips]),
                nu=treedef.unflatten([t[2] for t in trips]),
            ),
            new_lr_state,
        )
        return out, new_state

    def update(grads, state, params=None):
        # optax endpoint: the first tuple element is the updates tree
        del params  # adam's update does not read params
        return _step(grads, state, None)

    def fused_apply(grads, state, params):
        return _step(grads, state, params)

    return FusedAdam(init=init, update=update, fused_apply=fused_apply)
