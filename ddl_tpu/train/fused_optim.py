"""Fused Adam: the optimizer update as one fusible expression per leaf,
optionally ZeRO-1-sharded over the data axis.

The round-4 DenseNet op digest puts "elementwise/reduce fusions" (BN
stats, Adam, loss) at 17.5% of device time.  ``optax.adam``'s update is
structured as a *chain of tree passes* — ``scale_by_adam`` builds a new
``mu`` tree, a new ``nu`` tree, a bias-corrected updates tree, then
``scale`` and ``optax.apply_updates`` each walk the tree again — which
hands XLA several independent per-leaf HLO chains with materialised
updates trees between them.  This module computes the whole update —
new ``mu``, new ``nu``, and the new *parameter* — in ONE ``tree_map``
pass per leaf (``fused_apply``), so each parameter's update lowers to a
single fusible elementwise expression reading (g, mu, nu, p) and
writing (mu', nu', p') with no intermediate updates tensor, and XLA is
free to fuse it straight onto the last gradient reduction that produced
``g``.

**ZeRO-1** (``zero=ZeroConfig(...)``, PAPERS.md "Automatic Cross-Replica
Sharding of Weight Update in Data-Parallel Training"): with plain data
parallelism the moments are replicated over ``data`` — the dominant
optimizer HBM cost at scale (2x the parameter bytes, times dp copies
pod-wide).  With a ZeRO config, every parameter leaf at or above
``threshold`` elements gets its moments and its update computed on a
``1/dp`` shard (``parallel/rules.zero_shard_spec`` picks the dimension
from the rule-table-resolved parameter spec): the sharding constraint on
the incoming gradient turns XLA's gradient all-reduce into a
**reduce-scatter**, the fused Adam expression runs on the shard, and the
constraint back to the parameter's own spec **all-gathers** the new
parameters — all inserted by the SPMD partitioner from the constraints,
no manual collectives.  The math is element-identical to the replicated
path (same expression, same reduction operands — asserted to 1e-6 over
multi-step trajectories by ``tests/test_zero_sharding.py``); only
placement changes, so snapshots interoperate both ways (Orbax restores
global arrays into whatever sharding the live state carries).  Because
placement is derived per-world from the rule table, that interop also
covers elastic membership churn in BOTH directions: moments sharded
over a dp=2 data axis restore bit-identically into a dp=4 layout (the
scale-up grow epoch, round 24) and back — pinned by
``test_zero_snapshot_reshards_across_data_axis_grow``.

Drop-in constraints, both load-bearing:

* **State tree is bit-identical to ``optax.adam``'s** (``init``
  delegates to it): ``(ScaleByAdamState(count, mu, nu), ScaleState)``
  for a constant lr, ``(..., ScaleByScheduleState(count))`` for a
  schedule — existing snapshots restore into the fused optimizer and
  vice versa, replicated or ZeRO-sharded.
* **The math is ``optax.adam``'s exactly** (same b1/b2/eps, same
  ``1 - b**count_inc`` bias correction, ``eps_root=0``), asserted by
  ``tests/test_optimizer.py`` against optax step by step.

The standard ``update`` endpoint (returns an updates tree, for
``optax.apply_updates``) is also provided so the transformation works
anywhere a ``GradientTransformation`` does — under ZeRO it constrains
the emitted updates back to the parameter spec, so the two-pass path is
sharded identically to the fused one.  ``rebuild(**overrides)`` returns
a re-parameterised twin with the same state tree: ``recovery.scale_tx``
uses it to enter a grace window (``scale=``) without losing the fused
path or the ZeRO placement, and the step factories use it to attach a
``ZeroConfig`` (``with_zero``).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax

__all__ = ["FusedAdam", "ZeroConfig", "fused_adam", "with_zero"]


@dataclasses.dataclass(frozen=True)
class ZeroConfig:
    """ZeRO-1 placement for the fused update.

    ``param_specs`` is the rule-table-resolved PartitionSpec pytree for
    the parameters (None = all-replicated, the CNN DDP family);
    ``zero_shard_spec`` derives each eligible leaf's moment/update shard
    from it.  Frozen so a rebuilt optimizer shares it."""

    mesh: Any
    param_specs: Any = None
    axis: str = "data"
    threshold: int | None = None

    def resolved_threshold(self) -> int:
        from ddl_tpu.parallel.rules import ZERO_THRESHOLD

        return ZERO_THRESHOLD if self.threshold is None else self.threshold


class FusedAdam(NamedTuple):
    """``optax.GradientTransformation`` surface (init/update) plus the
    single-pass ``fused_apply(grads, state, params) -> (new_params,
    new_state)`` endpoint step factories fuse into the jitted step.
    ``rebuild(scale=..., zero=...)`` re-parameterises without changing
    the state tree; ``zero`` is the active ``ZeroConfig`` (or None)."""

    init: Callable[..., Any]
    update: Callable[..., Any]
    fused_apply: Callable[..., Any]
    rebuild: Callable[..., "FusedAdam"]
    zero: ZeroConfig | None


def _constrain(x, mesh, spec):
    """Pin ``x`` to ``spec`` on ``mesh``: a sharding constraint under a
    trace (the SPMD partitioner turns it into the reduce-scatter /
    all-gather), a device_put on concrete arrays (eager ``init``)."""
    from jax.sharding import NamedSharding

    sharding = NamedSharding(mesh, spec)
    if isinstance(x, jax.core.Tracer):
        return jax.lax.with_sharding_constraint(x, sharding)
    return jax.device_put(x, sharding)


def _zero_leaf_specs(zero: ZeroConfig, shaped_leaves):
    """Per-leaf ``(param_spec, zero_spec_or_None)`` aligned with
    ``shaped_leaves`` (the flattened grads/params — same structure the
    ``param_specs`` tree was resolved from)."""
    from jax.sharding import PartitionSpec as P

    from ddl_tpu.parallel.rules import zero_shard_spec

    if zero.param_specs is None:
        pspecs = [P()] * len(shaped_leaves)
    else:
        pspecs = jax.tree.flatten(
            zero.param_specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
        if len(pspecs) != len(shaped_leaves):
            raise ValueError(
                f"ZeroConfig.param_specs has {len(pspecs)} leaves but the "
                f"gradient tree has {len(shaped_leaves)}; the spec tree "
                "must be resolved from the same parameter tree"
            )
    threshold = zero.resolved_threshold()
    return [
        (
            ps,
            zero_shard_spec(
                ps, tuple(leaf.shape), zero.mesh, zero.axis, threshold
            ),
        )
        for ps, leaf in zip(pspecs, shaped_leaves)
    ]


def fused_adam(
    learning_rate,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    *,
    scale: float = 1.0,
    zero: ZeroConfig | None = None,
) -> FusedAdam:
    """Adam with ``optax.adam``-identical math and state tree, computed
    in one tree pass.  ``learning_rate`` may be a float or an optax
    schedule (callable of the step count).  ``scale`` multiplies the
    emitted update (the grace-window dial, ``recovery.scale_tx``);
    ``zero`` ZeRO-1-shards moments and update (see module docstring)."""
    ref = optax.adam(learning_rate, b1=b1, b2=b2, eps=eps)
    schedule = callable(learning_rate)

    def init(params):
        state = ref.init(params)
        if zero is None:
            return state
        # place the moments at their ZeRO shard from birth — eagerly
        # (CNN create_train_state) or as trace constraints (the jitted
        # LM/ViT create_state); either way tx.init IS the placement.
        adam_state, lr_state = state
        zspecs = _zero_leaf_specs(zero, jax.tree.leaves(params))

        def place(tree):
            leaves, treedef = jax.tree.flatten(tree)
            placed = [
                _constrain(m, zero.mesh, zs) if zs is not None else m
                for m, (_ps, zs) in zip(leaves, zspecs)
            ]
            return treedef.unflatten(placed)

        return (
            adam_state._replace(mu=place(adam_state.mu), nu=place(adam_state.nu)),
            lr_state,
        )

    def _step(grads, state, params):
        """One fused pass.  Returns (out, new_state) where ``out`` is the
        new params tree when ``params`` is given (fused_apply) and the
        updates tree otherwise (the optax ``update`` endpoint)."""
        adam_state, lr_state = state
        count_inc = optax.safe_int32_increment(adam_state.count)
        if schedule:
            # scale_by_schedule semantics: scale by f(count), then inc
            lr_now = learning_rate(lr_state.count)
            new_lr_state = lr_state._replace(
                count=optax.safe_int32_increment(lr_state.count)
            )
        else:
            lr_now = learning_rate
            new_lr_state = lr_state
        c1 = 1.0 - b1 ** count_inc.astype(jnp.float32)
        c2 = 1.0 - b2 ** count_inc.astype(jnp.float32)

        def leaf(g, mu, nu, p, pspec=None, zspec=None):
            if zspec is not None:
                # reduce-scatter: the constraint on the incoming gradient
                # makes XLA materialise only this device's 1/dp shard of
                # the data-axis reduction
                g = _constrain(g, zero.mesh, zspec)
                mu = _constrain(mu, zero.mesh, zspec)
                nu = _constrain(nu, zero.mesh, zspec)
            mu2 = b1 * mu + (1.0 - b1) * g
            nu2 = b2 * nu + (1.0 - b2) * (g * g)
            u = -lr_now * (mu2 / c1) / (jnp.sqrt(nu2 / c2) + eps)
            if scale != 1.0:
                u = scale * u
            if zspec is None:
                return (u if p is None else p + u), mu2, nu2
            if p is None:
                # updates-tree endpoint: hand back a full update in the
                # parameter's own placement (all-gather)
                return _constrain(u, zero.mesh, pspec), mu2, nu2
            # fused endpoint: add on the shard, then all-gather the new
            # parameters back to their rule-table placement
            new_p = _constrain(p, zero.mesh, zspec) + u
            return _constrain(new_p, zero.mesh, pspec), mu2, nu2

        g_leaves, treedef = jax.tree.flatten(grads)
        mu_leaves = jax.tree.leaves(adam_state.mu)
        nu_leaves = jax.tree.leaves(adam_state.nu)
        p_leaves = (
            jax.tree.leaves(params) if params is not None
            else [None] * len(g_leaves)
        )
        if zero is not None:
            specs = _zero_leaf_specs(zero, g_leaves)
            trips = [
                leaf(g, m, n, p, pspec=ps, zspec=zs)
                for (g, m, n, p), (ps, zs) in zip(
                    zip(g_leaves, mu_leaves, nu_leaves, p_leaves), specs
                )
            ]
        else:
            trips = [
                leaf(g, m, n, p)
                for g, m, n, p in zip(g_leaves, mu_leaves, nu_leaves, p_leaves)
            ]
        out = treedef.unflatten([t[0] for t in trips])
        new_state = (
            adam_state._replace(
                count=count_inc,
                mu=treedef.unflatten([t[1] for t in trips]),
                nu=treedef.unflatten([t[2] for t in trips]),
            ),
            new_lr_state,
        )
        return out, new_state

    def update(grads, state, params=None):
        # optax endpoint: the first tuple element is the updates tree
        del params  # adam's update does not read params
        return _step(grads, state, None)

    def fused_apply(grads, state, params):
        return _step(grads, state, params)

    def rebuild(**overrides) -> FusedAdam:
        kw = dict(scale=scale, zero=zero)
        kw.update(overrides)
        return fused_adam(learning_rate, b1=b1, b2=b2, eps=eps, **kw)

    return FusedAdam(
        init=init, update=update, fused_apply=fused_apply,
        rebuild=rebuild, zero=zero,
    )


def with_zero(
    tx,
    mesh,
    param_specs=None,
    axis: str = "data",
    threshold: int | None = None,
):
    """``tx`` with ZeRO-1 weight-update sharding attached.

    A no-op on meshes where ``axis`` is trivial (single chip, pp-only)
    — the replicated path IS the sharded path at dp=1.  Only the fused
    Adam supports it: optax chains (weight decay, gradient clipping)
    hide their moments behind opaque tree passes this module cannot
    constrain, so asking for ZeRO there is a loud error rather than a
    silent replication."""
    if getattr(mesh, "shape", {}).get(axis, 1) <= 1:
        return tx
    rebuild = getattr(tx, "rebuild", None)
    if rebuild is None:
        raise ValueError(
            "zero_sharding requires the fused Adam optimizer "
            "(train/fused_optim.fused_adam — the default for plain Adam "
            "configs; weight_decay/grad_clip_norm configs keep the optax "
            f"chain and cannot be ZeRO-sharded); got {type(tx).__name__}"
        )
    return rebuild(
        zero=ZeroConfig(
            mesh=mesh, param_specs=param_specs, axis=axis, threshold=threshold
        )
    )
