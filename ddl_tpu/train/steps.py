"""Jitted train/eval steps for the single-device and data-parallel strategies.

This is the TPU-native replacement for the reference's DDP path
(``ddp.py:127,150-170``): instead of wrapping the model in a DDP reducer that
fires bucketed NCCL allreduces during ``loss.backward()``, the *whole* train
step — normalize, forward, loss, backward, Adam update — is one jitted SPMD
program in which the batch is sharded over the ``data`` mesh axis and the
replicated-parameter gradient reduction is inserted by XLA's partitioner
(computation-follows-sharding; the collective rides ICI).  With
``mesh.data == 1`` the same program is the single-device trainer
(``single.py:136-154``), so "single" vs "DP" is a mesh shape, not a code path.

A semantic upgrade over the reference: because the global batch is one logical
array, BatchNorm statistics are computed over the *global* batch (SyncBN
semantics) rather than per-replica as torch DDP defaults to — DP training is
therefore exactly equivalent to single-device training on the same global
batch, which the parity test asserts to float tolerance.
"""

from __future__ import annotations

from typing import Callable, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ddl_tpu.models.densenet import forward_stages
from ddl_tpu.ops import cross_entropy_loss, normalize_images
# Jit-boundary batch spec + the family rule table come from the
# partition-rule engine — this module is lint-banned from hand-writing
# PartitionSpec axis literals (astlint 'pspec-hand-rolled').
from ddl_tpu.parallel.rules import BATCH_SPEC, cnn_rules
from ddl_tpu.train.state import TrainState

__all__ = ["StepFns", "BATCH_SPEC", "make_dp_step_fns", "make_grad_stats_fn"]


class StepFns(NamedTuple):
    """train(state, images, labels) -> (state, loss, preds);
    evaluate(state, images) -> logits."""

    train: Callable
    evaluate: Callable


def make_dp_step_fns(
    stages,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    compute_dtype,
    normalizer=normalize_images,
) -> StepFns:
    # Single-pass optimizer application when the transformation offers
    # it (train/fused_optim.FusedAdam): new params come out of the same
    # per-leaf expression as the new moments, with no materialised
    # updates tree between the gradient reduction and the weight write.
    # The grace-window wrap (recovery.scale_tx) rebuilds the fused Adam
    # with the scale baked in, so grace periods keep this path too.
    fused_apply = getattr(tx, "fused_apply", None)
    # ZeRO-1 (train/fused_optim.with_zero, attached by the trainer):
    # moments + update live on a 1/dp shard of each large leaf.  The
    # state then crosses the jit boundary in its committed (sharded)
    # placement — a blanket replicated in_sharding would all-gather the
    # moments right back every step.
    zero = getattr(tx, "zero", None)

    def train_step(state: TrainState, images, labels):
        x = normalizer(images, compute_dtype)

        def loss_fn(params):
            logits, new_stats = forward_stages(
                stages, params, state.batch_stats, x, train=True
            )
            return cross_entropy_loss(logits, labels), (logits, new_stats)

        (loss, (logits, new_stats)), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state.params
        )
        if fused_apply is not None:
            new_params, new_opt = fused_apply(
                grads, state.opt_state, state.params
            )
        else:
            updates, new_opt = tx.update(grads, state.opt_state, state.params)
            new_params = optax.apply_updates(state.params, updates)
        new_state = state.replace(
            step=state.step + 1,
            params=new_params,
            batch_stats=new_stats,
            opt_state=new_opt,
        )
        return new_state, loss, jnp.argmax(logits, axis=-1)

    def eval_step(state: TrainState, images):
        x = normalizer(images, compute_dtype)
        logits, _ = forward_stages(
            stages, state.params, state.batch_stats, x, train=False
        )
        return logits

    replicated = NamedSharding(mesh, P())
    batch_sharding = NamedSharding(mesh, BATCH_SPEC)
    # With ZeRO the state's committed placement (params replicated,
    # large moments data-sharded — created that way by
    # state.create_train_state(mesh=...)) is the boundary contract;
    # None lets it through untouched in AND out.
    state_in = None if zero is not None else replicated
    state_out = None if zero is not None else replicated

    train = jax.jit(
        train_step,
        in_shardings=(state_in, batch_sharding, batch_sharding),
        out_shardings=(state_out, replicated, batch_sharding),
        donate_argnums=(0,),
    )
    evaluate = jax.jit(
        eval_step,
        in_shardings=(state_in, batch_sharding),
        out_shardings=batch_sharding,
    )
    # sharding contract for `ddl_tpu lint` (analysis/contracts.py),
    # derived from the family rule table: DDP keeps full parameter
    # replicas by design, so replicated params are contractual here —
    # the checker skips its replication rule
    train.contract = cnn_rules().contract(
        # informational: whether the optimizer applied in one fused pass
        fused_optimizer_update=fused_apply is not None,
        zero_sharding=zero is not None,
        zero_threshold=zero.resolved_threshold() if zero is not None else None,
    )
    # abstract batch structs for the compiled-IR probes
    # (analysis/hlolint.py): the factory doesn't know the image extent,
    # so the probe supplies it; two-shape lowering diffs the structural
    # fingerprints to catch batch-specialized constants
    train.probe_inputs = lambda n=8, hw=(16, 16): (
        jax.ShapeDtypeStruct((n, *hw, 3), jnp.uint8),
        jax.ShapeDtypeStruct((n,), jnp.int32),
    )
    return StepFns(train=train, evaluate=evaluate)


def make_grad_stats_fn(stages, mesh: Mesh, compute_dtype,
                       zero_sharding: bool = False):
    """Per-parameter |grad| statistics, computed on-device.

    Observability parity with the reference's ``_log_gradient``
    (``ddp.py:310-326``): min / mean / max / 25th / median / 75th / std of
    the absolute gradient for every named parameter.  Returns
    ``{qualified_name: (7,) float32}``; only the 7 summary scalars leave the
    device (the reference pulls every full gradient tensor to host).
    """

    def stats_step(state: TrainState, images, labels):
        x = normalize_images(images, compute_dtype)

        def loss_fn(params):
            logits, _ = forward_stages(
                stages, params, state.batch_stats, x, train=True
            )
            return cross_entropy_loss(logits, labels)

        grads = jax.grad(loss_fn)(state.params)

        def summarize(g):
            a = jnp.abs(g.astype(jnp.float32)).ravel()
            q = jnp.quantile(a, jnp.asarray([0.25, 0.5, 0.75]))
            return jnp.stack([a.min(), a.mean(), a.max(), q[0], q[1], q[2], a.std()])

        return {
            f"stage{i}/{jax.tree_util.keystr(path, simple=True, separator='/')}": summarize(g)
            for i, stage_grads in enumerate(grads)
            for path, g in jax.tree_util.tree_leaves_with_path(stage_grads)
        }

    replicated = NamedSharding(mesh, P())
    batch_sharding = NamedSharding(mesh, BATCH_SPEC)
    # under ZeRO the state arrives committed (sharded moments) — do not
    # force a replicating boundary transfer just to read gradients
    state_in = None if zero_sharding else replicated
    return jax.jit(
        stats_step,
        in_shardings=(state_in, batch_sharding, batch_sharding),
        out_shardings=replicated,
    )
