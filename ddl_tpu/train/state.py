"""Training state: the ``{model, optim, epoch}`` triple the reference
checkpoints through DCP (``single.py:74-80``), as one immutable pytree.

``params`` and ``batch_stats`` are *tuples with one entry per pipeline stage*
(see ``ddl_tpu.models.densenet.init_stages``) — the same per-stage
decomposition the reference's PP checkpoints express by keying state dicts
with the stage rank (``pp.py:84-90``), but here it is a first-class structure
that works identically for 1 stage (single/DP) and N stages (PP/hybrid).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import optax
from flax import struct

__all__ = ["TrainState", "make_optimizer", "build_optimizer", "create_train_state"]


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Tuple[Any, ...]
    batch_stats: Tuple[Any, ...]
    opt_state: optax.OptState


def build_optimizer(
    learning_rate: float,
    *,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    grad_clip_norm: float = 0.0,
    lr_schedule: str = "constant",
    warmup_steps: int = 0,
    decay_steps: int = 0,
    fused: bool = False,
) -> optax.GradientTransformation:
    """Adam(W) with the standard training-schedule surface the reference
    lacks (it runs ``optim.Adam`` unconfigured, ``single.py:305``):
    global-norm gradient clipping, decoupled weight decay, linear warmup,
    and cosine decay.  With all defaults this returns plain ``optax.adam``
    — bitwise the reference's optimizer, and the same opt-state tree
    structure existing snapshots were written with.

    ``lr_schedule``: 'constant' or 'cosine' (requires ``decay_steps`` —
    total steps including warmup); ``warmup_steps`` prepends a 0 -> lr
    linear ramp to either.

    ``fused=True`` swaps plain Adam for ``train/fused_optim.fused_adam``
    — same math, same state tree (snapshots interoperate), but the whole
    update collapses to one fusible expression per leaf and step
    factories that know ``fused_apply`` skip the separate updates tree
    entirely.  Configs that chain extra transforms (weight decay,
    gradient clipping) keep the optax chain — those paths are not the
    headline hot path and correctness beats fusion there.
    """
    if lr_schedule == "cosine":
        if decay_steps <= 0:
            raise ValueError("lr_schedule='cosine' requires decay_steps > 0")
        if warmup_steps >= decay_steps:
            raise ValueError(
                f"decay_steps ({decay_steps}) must exceed warmup_steps "
                f"({warmup_steps}) — it counts total steps including warmup"
            )
        lr = optax.warmup_cosine_decay_schedule(
            init_value=0.0 if warmup_steps else learning_rate,
            peak_value=learning_rate,
            warmup_steps=warmup_steps,
            decay_steps=decay_steps,
        )
    elif lr_schedule == "constant":
        if warmup_steps:
            lr = optax.schedules.join_schedules(
                [
                    optax.linear_schedule(0.0, learning_rate, warmup_steps),
                    optax.constant_schedule(learning_rate),
                ],
                [warmup_steps],
            )
        else:
            lr = learning_rate
    else:
        raise ValueError(f"unknown lr_schedule {lr_schedule!r}")

    if fused and weight_decay <= 0.0 and grad_clip_norm <= 0.0:
        from ddl_tpu.train.fused_optim import fused_adam

        return fused_adam(lr, b1=b1, b2=b2, eps=eps)
    if weight_decay > 0.0:
        base = optax.adamw(lr, b1=b1, b2=b2, eps=eps, weight_decay=weight_decay)
    else:
        base = optax.adam(lr, b1=b1, b2=b2, eps=eps)
    if grad_clip_norm > 0.0:
        return optax.chain(optax.clip_by_global_norm(grad_clip_norm), base)
    return base


def make_optimizer(train_cfg) -> optax.GradientTransformation:
    """Optimizer from a ``TrainConfig`` — defaults are torch's unconfigured
    Adam (reference ``single.py:305``: lr=1e-3, betas=(0.9,0.999), eps=1e-8),
    computed fused (``train/fused_optim``) unless ``fused_adam=false``."""
    return build_optimizer(
        train_cfg.learning_rate,
        b1=train_cfg.b1,
        b2=train_cfg.b2,
        eps=train_cfg.eps,
        weight_decay=train_cfg.weight_decay,
        grad_clip_norm=train_cfg.grad_clip_norm,
        lr_schedule=train_cfg.lr_schedule,
        warmup_steps=train_cfg.warmup_steps,
        decay_steps=train_cfg.decay_steps,
        fused=getattr(train_cfg, "fused_adam", True),
    )


def create_train_state(stages, tx, rng, image_size: int, mesh=None) -> TrainState:
    """Fresh CNN train state.  With ``mesh`` the state is *committed*:
    params/batch_stats/step device_put replicated over the mesh and the
    optimizer state placed by ``tx.init`` itself — a ZeRO fused Adam
    (``train/fused_optim.with_zero``) puts each large leaf's moments on
    their 1/dp data-axis shard, which is exactly the placement the step
    factory's ``in_shardings=None`` boundary then preserves."""
    from ddl_tpu.models.densenet import init_stages
    import jax.numpy as jnp

    params, batch_stats = init_stages(stages, rng, image_size)
    step = jnp.zeros((), jnp.int32)
    if mesh is not None:
        from jax.sharding import NamedSharding, PartitionSpec

        replicated = NamedSharding(mesh, PartitionSpec())
        params, batch_stats, step = jax.tree.map(
            lambda x: jax.device_put(x, replicated),
            (params, batch_stats, step),
        )
    return TrainState(
        step=step,
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
    )
