"""Training state: the ``{model, optim, epoch}`` triple the reference
checkpoints through DCP (``single.py:74-80``), as one immutable pytree.

``params`` and ``batch_stats`` are *tuples with one entry per pipeline stage*
(see ``ddl_tpu.models.densenet.init_stages``) — the same per-stage
decomposition the reference's PP checkpoints express by keying state dicts
with the stage rank (``pp.py:84-90``), but here it is a first-class structure
that works identically for 1 stage (single/DP) and N stages (PP/hybrid).
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import optax
from flax import struct

__all__ = ["TrainState", "make_optimizer", "create_train_state"]


class TrainState(struct.PyTreeNode):
    step: jax.Array
    params: Tuple[Any, ...]
    batch_stats: Tuple[Any, ...]
    opt_state: optax.OptState


def make_optimizer(train_cfg) -> optax.GradientTransformation:
    """Adam with torch defaults (reference ``single.py:305`` uses
    ``optim.Adam`` unconfigured: lr=1e-3, betas=(0.9,0.999), eps=1e-8)."""
    return optax.adam(
        learning_rate=train_cfg.learning_rate,
        b1=train_cfg.b1,
        b2=train_cfg.b2,
        eps=train_cfg.eps,
    )


def create_train_state(stages, tx, rng, image_size: int) -> TrainState:
    from ddl_tpu.models.densenet import init_stages
    import jax.numpy as jnp

    params, batch_stats = init_stages(stages, rng, image_size)
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats=batch_stats,
        opt_state=tx.init(params),
    )
