"""The CNN Trainer: the DenseNet family on the shared training loop.

One trainer for all four reference entry points (``single.py`` / ``ddp.py`` /
``pp.py`` / ``ddp_n_pp.py`` each re-implement their own ``Trainer`` class —
SURVEY.md section 1): strategy is the mesh shape, the rest of the loop is
shared.  Per-epoch behaviour mirrors the reference trainer
(``single.py:169-197``): timed epoch, mean train loss, epoch-accumulated
train accuracy, full eval metric suite, CSV logging, QWK-gated snapshot
(``ddp.py:292-295`` — and unlike the reference, the save is actually wired
up).  Metric aggregation across data-parallel replicas needs no explicit
``all_gather`` (reference ``ddp.py:194-199``): step outputs are global
``jax.Array``s already, fetched to host once per epoch.

The epoch loop itself — timing, CSV logging, NaN watchdog, profiler hook,
preemption handling, snapshot gating — lives in ``train/loop.BaseTrainer``,
shared with the LM (``train/lm_trainer.py``) and ViT
(``train/vit_trainer.py``) families; this class supplies only the
CNN-specific pieces (data loaders, step functions, eval metrics, Orbax
snapshots keyed by epoch).
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from ddl_tpu import checkpoint as ckpt
from ddl_tpu.config import Config
from ddl_tpu.data import DataLoader, ShardedEpochSampler, build_datasets, shard_batch
from ddl_tpu.models import build_stages, stage_boundary_shapes
from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
from ddl_tpu.train.loop import BaseTrainer, _phase
from ddl_tpu.train.state import create_train_state, make_optimizer
from ddl_tpu.train.steps import make_dp_step_fns
from ddl_tpu.utils import MetricLogger, faultinject, masked_classification_eval

__all__ = ["Trainer", "resolve_job_id"]


def resolve_job_id() -> str:
    """Job identity from the launcher env (reference reads TORCHX_JOB_ID,
    ``single.py:102``); the last path segment is the job name."""
    raw = os.environ.get("DDL_JOB_ID") or os.environ.get("TORCHX_JOB_ID") or "local"
    return raw.split("/")[-1]


def _to_host(x) -> np.ndarray:
    """Fetch a (possibly multi-host-sharded) jax.Array fully to this host."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


class Trainer(BaseTrainer):
    best_metric = "qwk"
    best_mode = "max"
    best_label = "QWK"

    def __init__(self, cfg: Config, mesh=None, datasets=None) -> None:
        cfg.validate()
        self.cfg = cfg
        self.job_id = resolve_job_id()
        self.mesh = mesh if mesh is not None else build_mesh(
            MeshSpec(cfg.mesh.data, cfg.mesh.pipe)
        )

        pipelined = cfg.strategy in ("pp", "dp_pp")
        self.stages = build_stages(cfg.model, num_stages=None if pipelined else 1)
        self.tx = make_optimizer(cfg.train)
        self._zero = False
        if cfg.train.zero_sharding:
            from ddl_tpu.train.fused_optim import with_zero

            # CNN DDP params are replicated (cnn_rules: everything P()),
            # so param_specs=None; with_zero no-ops at mesh data=1
            self.tx = with_zero(self.tx, self.mesh)
            self._zero = getattr(self.tx, "zero", None) is not None
        rng = jax.random.key(cfg.train.seed)
        self.state = create_train_state(
            self.stages, self.tx, rng, cfg.data.image_size,
            mesh=self.mesh if self._zero else None,
        )
        if cfg.model.pretrained_path:
            from ddl_tpu.models.convert import load_torch_checkpoint

            p, bs, skipped = load_torch_checkpoint(
                cfg.model.pretrained_path, self.state.params, self.state.batch_stats
            )
            self.state = self.state.replace(params=p, batch_stats=bs)
            if skipped:
                print(f"[ddl_tpu] pretrained overlay skipped keys: {skipped}")
        self._rebuild_step_fns()
        self.grad_stats_fn = None
        if cfg.train.log_gradient_stats and not pipelined:
            from ddl_tpu.train.steps import make_grad_stats_fn

            self.grad_stats_fn = make_grad_stats_fn(
                self.stages, self.mesh, jnp.dtype(cfg.model.compute_dtype),
                zero_sharding=self._zero,
            )

        train_ds, test_ds = datasets if datasets is not None else build_datasets(cfg.data)
        # Host-level sharding (DistributedSampler analog, ddp.py:343): each
        # process loads 1/process_count of the global batch; per-chip
        # sharding happens on-device via NamedSharding.
        n_proc, proc = jax.process_count(), jax.process_index()
        if cfg.data.global_batch_size % n_proc:
            raise ValueError("global_batch_size must divide by process count")
        per_proc_batch = cfg.data.global_batch_size // n_proc
        per_proc_eval = cfg.data.eval_batch_size // n_proc
        self.train_loader = DataLoader(
            train_ds,
            per_proc_batch,
            sampler=ShardedEpochSampler(
                len(train_ds), n_proc, proc,
                shuffle=cfg.data.shuffle, drop_last=cfg.data.drop_last,
                seed=cfg.train.seed,
            ),
            num_workers=cfg.data.num_workers,
            drop_last=cfg.data.drop_last,
            on_retry=self._note_io_retry,
        )
        # Eval is deterministic and full-coverage: ordered (no shuffle), no
        # dropped tail — sentinel padding keeps batch shapes static (one
        # compiled eval fn) and every test sample is counted exactly once,
        # the SPMD analog of the reference evaluating everything
        # (single.py:199-258).  Round 1 inherited shuffle+drop_last here,
        # which made eval metrics (and the QWK save gate) a shifting subset.
        self.test_loader = DataLoader(
            test_ds,
            per_proc_eval,
            sampler=ShardedEpochSampler(
                len(test_ds), n_proc, proc,
                shuffle=False, drop_last=False, pad_mode="sentinel",
                seed=cfg.train.seed + 1,
            ),
            num_workers=cfg.data.num_workers,
            drop_last=False,
            pad_last_batch=True,
            on_retry=self._note_io_retry,
        )
        if len(test_ds) == 0:
            raise ValueError("empty eval set")

        # resume decision happens BEFORE the logger so the CSV lineage
        # column records auto-resumed runs too, not just flag-resumed ones
        self._resume_job = cfg.train.snapshot_job_id
        self._resume_epoch = cfg.train.snapshot_epoch
        self._resume_auto = False
        if self._resume_job is None:
            # snapshot_epoch without a job id means THIS job at that epoch
            found = ckpt.resolve_resume(
                cfg.train.checkpoint_dir, self.job_id,
                explicit=cfg.train.snapshot_epoch,
                auto=cfg.train.auto_resume,
            )
            if found is not None:
                self._resume_job, self._resume_epoch = self.job_id, found
                self._resume_auto = cfg.train.snapshot_epoch is None
        self.logger = MetricLogger(
            cfg.train.log_dir,
            self.job_id,
            global_rank=proc,
            local_rank=proc,
            model_start_job_id=self._resume_job,
        )
        self.is_logging_process = proc == 0
        self._init_obs(cfg.train.log_dir, self.job_id, "cnn")
        self.epochs_run = 0
        # shared-loop knobs (train/loop.BaseTrainer)
        self.num_periods = cfg.train.max_epochs
        self.halt_on_nan = cfg.train.halt_on_nan
        from ddl_tpu.train.recovery import make_policy

        self.recovery = make_policy(cfg.train)
        self.keep_snapshots = cfg.train.keep_snapshots
        self.preemption_save = cfg.train.preemption_save
        self.profile_dir = cfg.train.profile_dir
        self.save_best = cfg.train.save_best_qwk
        self.best_value = -1.0
        self._snapshot_mgr = None
        if self._resume_job is not None:
            self._load_snapshot()

    def _rebuild_step_fns(self) -> None:
        """(Re)build the compiled step functions — also the grace dial:
        during a post-rollback grace window the optimizer is wrapped so
        its updates are scaled by ``update_scale`` (state-tree-identical,
        ``train/recovery.scale_tx``)."""
        cfg = self.cfg
        from ddl_tpu.train.recovery import scale_tx

        tx = scale_tx(self.tx, self.update_scale)
        compute_dtype = jnp.dtype(cfg.model.compute_dtype)
        if cfg.strategy in ("pp", "dp_pp"):
            from ddl_tpu.parallel.pipeline import make_pipeline_step_fns

            self.step_fns = make_pipeline_step_fns(
                self.stages,
                tx,
                self.mesh,
                compute_dtype,
                num_microbatches=cfg.train.num_microbatches,
                boundary_shapes=stage_boundary_shapes(cfg.model, cfg.data.image_size),
                num_classes=cfg.model.num_classes,
                remat=cfg.model.remat,
                schedule=cfg.train.pipeline_schedule,
            )
        else:
            from ddl_tpu.ops import get_normalizer

            self.step_fns = make_dp_step_fns(
                self.stages,
                tx,
                self.mesh,
                compute_dtype,
                normalizer=get_normalizer(cfg.model.pallas_normalize),
            )

    def _snapshot_store(self):
        t = self.cfg.train
        return (t.checkpoint_dir, self.job_id) if t.checkpoint_dir else None

    def _rollback_restore(self, epoch: int) -> None:
        self.state, self.epochs_run = ckpt.load_snapshot(
            self.cfg.train.checkpoint_dir, self.job_id, epoch, self.state,
            verify=False,
        )
        self._apply_cursor(self.job_id, epoch)

    def _apply_cursor(self, job_id: str, epoch: int) -> None:
        """Exact-resume refinement: if the snapshot's manifest carries a
        mid-epoch data cursor (a preemption landed partway through the
        epoch), re-enter THAT epoch at the recorded batch offset instead
        of skipping its remaining batches — the resumed stream replays
        no batch and skips none."""
        cur = ckpt.read_cursor(
            self.cfg.train.checkpoint_dir, job_id, epoch
        )
        if cur and int(cur.get("offset", 0)) > 0:
            self.epochs_run = int(cur.get("period", self.epochs_run))
            self._resume_offset = int(cur["offset"])
            print(
                f"[resume] data cursor: re-entering epoch "
                f"{self.epochs_run} at batch {self._resume_offset}"
            )

    # ------------------------------------------------------------------

    # ``epochs_run`` is this family's public name for the loop's resume
    # cursor (tests and the CLI read it); keep both views in sync.
    @property
    def periods_run(self) -> int:
        return self.epochs_run

    @periods_run.setter
    def periods_run(self, value: int) -> None:
        self.epochs_run = value

    def _load_snapshot(self) -> None:
        t = self.cfg.train
        path = ckpt.snapshot_path(
            t.checkpoint_dir, self._resume_job, self._resume_epoch
        )
        if not path.exists():
            print(f"No snapshot at {path}; starting fresh")
            return
        print(f"Loading snapshot from {path}")
        from time import perf_counter

        t0 = perf_counter()
        self.state, self.epochs_run = ckpt.run_resume_load(
            # an auto-discovered epoch was integrity-verified by
            # resolve_resume moments ago; only explicit resumes re-verify
            lambda: ckpt.load_snapshot(
                t.checkpoint_dir, self._resume_job, self._resume_epoch,
                self.state, verify=not self._resume_auto,
            ),
            auto=self._resume_auto,
            desc=str(path),
            hint="pass train.auto_resume=false",
        )
        self._apply_cursor(self._resume_job, self._resume_epoch)
        self._emit_snapshot_restore(
            perf_counter() - t0, self._resume_epoch,
            self.epochs_run, self._resume_offset,
        )
        print(f"Resuming training from epoch {self.epochs_run}")

    def save_snapshot(self, epoch: int) -> None:
        cursor = self.data_cursor
        if cursor and cursor.get("offset", 0) >= len(self.train_loader):
            # preempted exactly at the epoch's end: the stream is fully
            # consumed, so the cursor is a clean next-epoch start (a
            # literal offset would resume into an empty remainder)
            cursor = {"period": int(cursor["period"]) + 1, "offset": 0}
        if self.cfg.train.async_checkpoint:
            if self._snapshot_mgr is None:
                self._snapshot_mgr = ckpt.SnapshotManager(
                    self.cfg.train.checkpoint_dir, self.job_id
                )
            path = self._snapshot_mgr.save(epoch, self.state, cursor=cursor)
        else:
            path = ckpt.save_snapshot(
                self.cfg.train.checkpoint_dir, self.job_id, epoch,
                self.state, cursor=cursor,
            )
        print(f"Epoch {epoch} | Saved snapshot to {path}")

    def wait_for_saves(self) -> None:
        if self._snapshot_mgr is not None:
            self._snapshot_mgr.wait()

    def last_snapshot_hint(self):
        return ckpt.latest_epoch(self.cfg.train.checkpoint_dir, self.job_id)

    def resume_hint(self, epoch: int) -> str:
        return (
            f"train.snapshot_job_id={self.job_id} "
            f"train.snapshot_epoch={epoch}"
        )

    # ------------------------------------------------------------------

    def run_period(self, epoch: int, guard=None):
        """One training epoch; returns (metric dict, steps).

        ``guard`` (a ``PreemptionGuard``) stops the epoch after the
        in-flight step when a preemption signal has arrived.
        """
        self.train_loader.set_epoch(epoch)
        # exact resume: skip the batches a preemption snapshot already
        # consumed this epoch (index-level skip — nothing is loaded and
        # discarded; one-shot, later epochs start at 0)
        skip = self.consume_resume_offset()
        if skip:
            self.train_loader.set_start_batch(skip)
        losses, preds, targets = [], [], []
        steps = 0
        # event steps are GLOBAL (epoch * steps/epoch + i) so the obs
        # liveness/straggler comparison sees one monotone counter per
        # host, the same unit the LM family's global step gives it
        step_base = epoch * len(self.train_loader) + skip
        it = iter(self.train_loader)
        while True:
            # data_wait = host-side batch production (the loader), h2d =
            # device placement, step = compiled-step dispatch; the device
            # time dispatch hides surfaces in the period-end fence phase
            with _phase(self.obs, "data_wait", step=step_base + steps):
                batch = next(it, None)
            if batch is None:
                break
            images, labels = batch
            with _phase(self.obs, "h2d", step=step_base + steps):
                gi, gl = shard_batch(self.mesh, images, labels)
            if self.grad_stats_fn is not None and self.is_logging_process:
                # before the train step: it donates (consumes) self.state
                stats = jax.device_get(self.grad_stats_fn(self.state, gi, gl))
                self.logger.log_gradient_stats(stats, step=steps)
            with _phase(self.obs, "step", step=step_base + steps):
                self.state, loss, pred = self.step_fns.train(self.state, gi, gl)
            # HBM ledger: stamp the train step's static memory budget
            # once, after its first dispatch (obs/hbm.py hbm_plan)
            self.emit_hbm_plan("train_step", self.step_fns.train,
                               self.state, gi, gl)
            losses.append(loss)
            preds.append(pred)
            targets.append(gl)
            steps += 1
            faultinject.check_step(step_base + steps - 1, guard)
            if guard is not None and guard.requested:
                break
        if steps == 0:
            raise RuntimeError("empty epoch: dataset smaller than one batch")
        with _phase(self.obs, "fence", step=step_base + steps):
            mean_loss = float(np.mean([_to_host(l) for l in losses]))
            y_pred = np.concatenate([_to_host(p) for p in preds])
            y_true = np.concatenate([_to_host(t) for t in targets])
        accuracy = float(np.mean(y_pred == y_true))
        return {"loss": mean_loss, "train_accuracy": accuracy}, steps

    def evaluate(self, epoch: int) -> dict:
        """Eval loop -> metric dict (reference ``_evaluate``, single.py:199-251).

        Deterministic and full-coverage: rows padded to static shape carry
        label -1 and are masked out, so metrics are computed over every test
        sample exactly once and are epoch-order invariant."""
        self.test_loader.set_epoch(epoch)
        logits, targets = [], []
        for images, labels in self.test_loader:
            gi, gl = shard_batch(self.mesh, images, labels)
            logits.append(self.step_fns.evaluate(self.state, gi))
            targets.append(gl)
        all_logits = np.concatenate([_to_host(l) for l in logits])
        all_targets = np.concatenate([_to_host(t) for t in targets])
        return masked_classification_eval(all_logits, all_targets)

    # -------------------------------------------------- loop hooks

    def evaluate_period(self, epoch: int) -> dict:
        return self.evaluate(epoch)

    def format_train_line(self, epoch, elapsed, steps, m) -> str:
        return (
            f"Epoch {epoch} | Time: {elapsed:.2f}s | Steps: {steps} | "
            f"Loss: {m['loss']:.4f} | Training Accuracy: {m['train_accuracy']:.4f}"
        )

    def format_eval_line(self, epoch, m) -> str:
        return (
            f"Epoch {epoch} | Validation Loss: {m['val_loss']:.4f} | "
            f"Accuracy: {m['val_accuracy']:.4f} | QWK: {m['qwk']:.4f}"
        )
