"""Headline benchmark: DenseNet121 training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline derivation (BASELINE.md): the reference's best single-GPU run
averages 90.77 s/epoch; the preprocessed APTOS train split at batch 30 gives
~97 steps/epoch (2930 images — 80% of the 3662-image APTOS-2019 train set,
the standard preprocessed split; the reference logs epoch_time, not
steps/sec, so step count is derived).  That is 97 / 90.77 = 1.069 train
steps/sec at global batch 30 on the reference's best single GPU.

This bench times the same workload — DenseNet121, 224x224x3 uint8 in,
5-class head, batch 30, full train step (normalize + forward + backward +
Adam) — on one TPU chip in bfloat16 compute, steady-state (post-compile),
with device-resident input batches (host data feed overlaps compute in the
real trainer via the prefetching loader).
"""

from __future__ import annotations

import json
import time

import numpy as np

BASELINE_STEPS_PER_SEC = 97 / 90.77  # best single-GPU reference run


def main() -> None:
    import os

    import jax

    # Persistent compile cache: repeated bench runs (and the trainer) skip
    # the ~30s DenseNet121 XLA compile.
    from ddl_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache()

    import jax.numpy as jnp

    from ddl_tpu.config import ModelConfig, TrainConfig
    from ddl_tpu.models import build_stages
    from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
    from ddl_tpu.train.state import create_train_state, make_optimizer
    from ddl_tpu.train.steps import make_dp_step_fns
    from ddl_tpu.utils.timing import fence

    batch = 30
    # DDL_BENCH_IMPL enables same-session A/Bs of the dense-block impls
    # (packed default; "fused" = the round-6 Pallas block) without
    # editing the bench — the knob the gate/PERF.md protocol names.
    cfg = ModelConfig(
        compute_dtype="bfloat16",
        dense_block_impl=os.environ.get("DDL_BENCH_IMPL", "packed"),
    )
    stages = build_stages(cfg, num_stages=1)
    tx = make_optimizer(TrainConfig())
    state = create_train_state(stages, tx, jax.random.key(0), image_size=224)
    mesh = build_mesh(MeshSpec(1, 1))
    fns = make_dp_step_fns(stages, tx, mesh, jnp.bfloat16)

    rng = np.random.default_rng(0)
    images = jnp.asarray(rng.integers(0, 255, (batch, 224, 224, 3)), jnp.uint8)
    labels = jnp.asarray(rng.integers(0, 5, (batch,)), jnp.int32)

    # warmup: compile + 2 steady steps
    for _ in range(3):
        state, loss, _ = fns.train(state, images, labels)
    fence(loss)

    def timed(n):
        nonlocal state
        t0 = time.perf_counter()
        loss = None
        for _ in range(n):
            state, loss, _ = fns.train(state, images, labels)
        fence(loss)  # true fence: readback, not just block_until_ready
        return time.perf_counter() - t0

    # Each timed run carries a fixed cost (final fence readback + pipeline
    # drain, ~150 ms through the dev tunnel) that a single n/elapsed quote
    # folds into the rate, making it grow with the iteration count.  Timing
    # two run lengths and differencing cancels it — the slope is the true
    # per-step time — and the median of three slopes rides out host
    # contention during any one run.
    iters = int(os.environ.get("DDL_BENCH_ITERS", "50"))
    n1 = max(iters // 5, 2)
    runs = []  # (slope, undifferenced long-run rate)
    for _ in range(5):  # up to 2 retries for contention-corrupted runs
        t_long, t_short = timed(iters), timed(n1)
        s = (t_long - t_short) / (iters - n1)
        if s > 0:
            runs.append((s, iters / t_long))
        if len(runs) == 3:
            break
    if len(runs) < 3:
        raise RuntimeError(
            f"host contention: could not collect 3 positive slopes ({runs})"
        )
    runs.sort()
    slope, undiff = runs[1]
    steps_per_sec = 1.0 / slope
    out = {
        "metric": "densenet121_train_steps_per_sec_bs30_1chip",
        "value": round(steps_per_sec, 4),
        "unit": "steps/sec",
        "vs_baseline": round(steps_per_sec / BASELINE_STEPS_PER_SEC, 4),
        # the plain wall-clock quote of the same median run, fixed fence/
        # drain cost INCLUDED (the reference's epoch_time is this kind of
        # number) — the honest bracket is [undifferenced, slope]
        "value_undifferenced": round(undiff, 4),
    }
    # chip utilization: executed FLOPs from XLA cost analysis / peak bf16
    from ddl_tpu.bench.mfu import append_mfu, fused_dense_block_train_flops

    extra = 0.0
    if cfg.dense_block_impl == "fused":
        # cost analysis sees zero FLOPs in a Pallas custom call; restore
        # the fused blocks' work analytically (model convention)
        extra = fused_dense_block_train_flops(
            batch, 224, cfg.block_config, cfg.growth_rate, cfg.bn_size,
            cfg.num_init_features, cfg.dense_block_fused_blocks,
        )
        out["impl"] = cfg.dense_block_impl
    append_mfu(out, fns.train, slope, state, images, labels,
               extra_flops=extra)
    # per-device optimizer-state HBM estimate (rule-table-derived Adam
    # moment bytes, replicated vs ZeRO at the dp=8 reference mesh) —
    # informational column; the gate/baseline headline ignores it
    from ddl_tpu.bench.gate import opt_hbm_rows

    (cnn_row,) = opt_hbm_rows(dp=8, families=("cnn",))
    out["opt_hbm_bytes"] = {
        "replicated": cnn_row["replicated_bytes"],
        "zero": cnn_row["zero_bytes"],
        "dp": cnn_row["dp"],
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
