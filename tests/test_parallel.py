"""Parallelism correctness: DP/PP/hybrid vs exact sequential references.

The reference validates its parallelism only empirically — metric parity of
final-epoch stats across strategies, averaged over 10 cluster runs
(``ipynb/main.ipynb`` cell 5; SURVEY.md section 4).  Here every strategy is
checked *numerically* against a from-scratch sequential implementation on a
simulated 8-device CPU mesh: one optimizer step must produce (near-)identical
parameters, loss, and predictions.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl_tpu.config import TrainConfig
from ddl_tpu.models import apply_stage, build_stages, stage_boundary_shapes
from ddl_tpu.ops import softmax_cross_entropy
from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
from ddl_tpu.parallel.pipeline import make_pipeline_step_fns
from ddl_tpu.train.state import create_train_state, make_optimizer
from ddl_tpu.train.steps import make_dp_step_fns

IMG = 16
B = 8
NUM_CLASSES = 5


@pytest.fixture(scope="module")
def batch():
    rng = np.random.default_rng(0)
    images = rng.integers(0, 255, (B, IMG, IMG, 3)).astype(np.uint8)
    labels = rng.integers(0, NUM_CLASSES, (B,)).astype(np.int32)
    return images, labels


def _fresh(tiny_model_cfg, num_stages=None, sgd=False):
    stages = build_stages(tiny_model_cfg, num_stages=num_stages)
    # Parity tests compare post-update params with SGD: Adam's first step is
    # +-lr * sign(grad), which amplifies reduction-order fp noise on
    # near-zero grads into full-lr sign flips.  SGD keeps the comparison
    # proportional to the (tiny) gradient difference.
    tx = optax.sgd(0.1) if sgd else make_optimizer(TrainConfig())
    state = create_train_state(stages, tx, jax.random.key(0), IMG)
    return stages, tx, state


def _clone(state):
    return jax.tree.map(jnp.copy, state)


def sequential_reference_step(stages, tx, state, images, labels, M, D):
    """Ground truth: loop over D data shards x M microbatches, grad of the
    averaged loss, single Adam update — pure jax.numpy, no mesh."""
    shard = images.shape[0] // D
    mb = shard // M

    def total_loss(params):
        shard_losses, shard_stats, logits_cat = [], [], []
        for d in range(D):
            stats = state.batch_stats
            loss_d = 0.0
            for m in range(M):
                lo = d * shard + m * mb
                x = images[lo : lo + mb].astype(jnp.float32) / 255.0
                new_stats = []
                for i, st in enumerate(stages):
                    x, ns = apply_stage(st, params[i], stats[i], x, train=True)
                    new_stats.append(ns)
                stats = tuple(new_stats)
                loss_d = loss_d + softmax_cross_entropy(x, labels[lo : lo + mb]).mean()
                logits_cat.append(x)
            shard_losses.append(loss_d / M)
            shard_stats.append(stats)
        loss = sum(shard_losses) / D
        return loss, (jnp.concatenate(logits_cat), shard_stats)

    (loss, (logits, shard_stats)), grads = jax.value_and_grad(
        total_loss, has_aux=True
    )(state.params)
    updates, new_opt = tx.update(grads, state.opt_state, state.params)
    new_params = optax.apply_updates(state.params, updates)
    mean_stats = jax.tree.map(lambda *xs: jnp.mean(jnp.stack(xs), 0), *shard_stats)
    return new_params, mean_stats, float(loss), np.argmax(np.asarray(logits), -1)


def _assert_tree_close(a, b, atol):
    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), atol=atol, rtol=1e-4)


def test_dp_matches_single(tiny_model_cfg, batch):
    """DP over ('data',) is bit-compatible with single-device on the same
    global batch — same jit program, just sharded (SyncBN semantics)."""
    images, labels = batch
    stages, tx, state0 = _fresh(tiny_model_cfg, num_stages=1, sgd=True)

    single = make_dp_step_fns(stages, tx, build_mesh(MeshSpec(1, 1)), jnp.float32)
    dp = make_dp_step_fns(stages, tx, build_mesh(MeshSpec(4, 1)), jnp.float32)

    s1, loss1, pred1 = single.train(_clone(state0), images, labels)
    s2, loss2, pred2 = dp.train(_clone(state0), images, labels)
    assert float(loss1) == pytest.approx(float(loss2), abs=1e-5)
    np.testing.assert_array_equal(np.asarray(pred1), np.asarray(pred2))
    _assert_tree_close(s1.params, s2.params, atol=1e-5)
    _assert_tree_close(s1.batch_stats, s2.batch_stats, atol=1e-5)


@pytest.mark.parametrize("data,microbatches", [(1, 2), (1, 4), (2, 2), (4, 2)])
def test_pipeline_matches_sequential(tiny_model_cfg, batch, data, microbatches):
    """GPipe schedule (+ optional DP axis) == sequential microbatched math."""
    images, labels = batch
    stages, tx, state0 = _fresh(tiny_model_cfg, sgd=True)
    mesh = build_mesh(MeshSpec(data, 2))
    fns = make_pipeline_step_fns(
        stages,
        tx,
        mesh,
        jnp.float32,
        num_microbatches=microbatches,
        boundary_shapes=stage_boundary_shapes(tiny_model_cfg, IMG),
        num_classes=NUM_CLASSES,
        remat=False,
    )
    new_state, loss, preds = fns.train(_clone(state0), images, labels)
    ref_params, ref_stats, ref_loss, ref_preds = sequential_reference_step(
        stages, tx, _clone(state0), images, labels, M=microbatches, D=data
    )
    assert float(loss) == pytest.approx(ref_loss, abs=1e-5)
    np.testing.assert_array_equal(np.asarray(preds), ref_preds)
    _assert_tree_close(new_state.params, ref_params, atol=2e-5)
    _assert_tree_close(new_state.batch_stats, tuple(ref_stats), atol=2e-5)


@pytest.mark.parametrize("data,microbatches", [(1, 2), (2, 2), (1, 4)])
def test_1f1b_matches_gpipe(tiny_model_cfg, batch, data, microbatches):
    """The hand-written 1F1B interleave must reproduce the autodiff-derived
    GPipe schedule exactly — same microbatch math, different clocking."""
    images, labels = batch
    stages, tx, state0 = _fresh(tiny_model_cfg, sgd=True)
    mesh = build_mesh(MeshSpec(data, 2))
    kwargs = dict(
        tx=tx,
        mesh=mesh,
        compute_dtype=jnp.float32,
        num_microbatches=microbatches,
        boundary_shapes=stage_boundary_shapes(tiny_model_cfg, IMG),
        num_classes=NUM_CLASSES,
        remat=False,
    )
    g = make_pipeline_step_fns(stages, schedule="gpipe", **kwargs)
    f = make_pipeline_step_fns(stages, schedule="1f1b", **kwargs)
    sg, lg, pg = g.train(_clone(state0), images, labels)
    sf, lf, pf = f.train(_clone(state0), images, labels)
    assert float(lg) == pytest.approx(float(lf), abs=1e-6)
    np.testing.assert_array_equal(np.asarray(pg), np.asarray(pf))
    _assert_tree_close(sg.params, sf.params, atol=1e-6)
    _assert_tree_close(sg.batch_stats, sf.batch_stats, atol=1e-6)


def test_pipeline_remat_matches_no_remat(tiny_model_cfg, batch):
    """jax.checkpoint on stages must not change the math."""
    images, labels = batch
    stages, tx, state0 = _fresh(tiny_model_cfg, sgd=True)
    mesh = build_mesh(MeshSpec(1, 2))
    kwargs = dict(
        tx=tx,
        mesh=mesh,
        compute_dtype=jnp.float32,
        num_microbatches=2,
        boundary_shapes=stage_boundary_shapes(tiny_model_cfg, IMG),
        num_classes=NUM_CLASSES,
    )
    a = make_pipeline_step_fns(stages, remat=False, **kwargs)
    b = make_pipeline_step_fns(stages, remat=True, **kwargs)
    sa, la, _ = a.train(_clone(state0), images, labels)
    sb, lb, _ = b.train(_clone(state0), images, labels)
    assert float(la) == pytest.approx(float(lb), abs=1e-6)
    _assert_tree_close(sa.params, sb.params, atol=1e-6)


def test_pipeline_eval_matches_sequential_eval(tiny_model_cfg, batch):
    images, _ = batch
    stages, tx, state0 = _fresh(tiny_model_cfg)
    mesh = build_mesh(MeshSpec(2, 2))
    fns = make_pipeline_step_fns(
        stages,
        tx,
        mesh,
        jnp.float32,
        num_microbatches=2,
        boundary_shapes=stage_boundary_shapes(tiny_model_cfg, IMG),
        num_classes=NUM_CLASSES,
        remat=False,
    )
    logits = np.asarray(fns.evaluate(_clone(state0), images))
    x = images.astype(jnp.float32) / 255.0
    for i, st in enumerate(stages):
        x, _ = apply_stage(st, state0.params[i], state0.batch_stats[i], x, train=False)
    np.testing.assert_allclose(logits, np.asarray(x), atol=1e-5, rtol=1e-4)


@pytest.mark.parametrize("spec", [MeshSpec(1, 1), MeshSpec(4, 1), MeshSpec(1, 2), MeshSpec(2, 2)])
def test_strategies_learn(tiny_model_cfg, spec):
    """Loss must descend on learnable synthetic data under every strategy
    (replaces the reference's strategy-vs-single metric-parity check)."""
    from ddl_tpu.data import SyntheticAptosDataset

    ds = SyntheticAptosDataset(B * 8, image_size=IMG, seed=0, noise=0.05)
    pipelined = spec.pipe > 1
    stages, tx, state = _fresh(tiny_model_cfg, num_stages=None if pipelined else 1)
    mesh = build_mesh(spec)
    if pipelined:
        fns = make_pipeline_step_fns(
            stages,
            tx,
            mesh,
            jnp.float32,
            num_microbatches=2,
            boundary_shapes=stage_boundary_shapes(tiny_model_cfg, IMG),
            num_classes=NUM_CLASSES,
            remat=False,
        )
    else:
        fns = make_dp_step_fns(stages, tx, mesh, jnp.float32)
    losses = []
    for step in range(20):
        idx = np.arange(B) + (step % 8) * B
        images = np.stack([ds[i][0] for i in idx])
        labels = np.asarray([ds[i][1] for i in idx], np.int32)
        state, loss, _ = fns.train(state, images, labels)
        losses.append(float(loss))
    assert np.mean(losses[-4:]) < np.mean(losses[:4]) * 0.9, losses
