"""Fused VMEM-resident dense-block kernel (ops/fused_dense_block.py) vs
the textbook concat / packed XLA forms — forward AND gradient parity,
interpreter mode and under jit.  (The chip measurements and go/no-go
analysis live in PERF.md rounds 5-6.)"""

import jax
import jax.numpy as jnp
import numpy as np

from ddl_tpu.config import ModelConfig
from ddl_tpu.models.densenet import (
    DenseBlock,
    build_stages,
    forward_stages,
    init_stages,
)
from ddl_tpu.ops.fused_dense_block import (
    block_pad,
    fused_dense_block,
    fused_dense_block_eval,
    pack_block_params,
)


def _tiny_cfg(**kw):
    return ModelConfig(
        growth_rate=4, block_config=(2, 2), num_init_features=8,
        bn_size=2, num_classes=5, split_blocks=(1,),
        compute_dtype="float32", remat=False, **kw,
    )


def test_fused_block_matches_concat_eval():
    c0, growth, bn_size, L = 16, 8, 2, 4
    b, h, w = 2, 6, 5
    block = DenseBlock(L, growth, bn_size, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (b, h, w, c0))
    variables = block.init(jax.random.key(1), x, train=False)
    # make running stats non-trivial: one train-mode step, keep mutations
    _, upd = block.apply(variables, x, train=True, mutable=["batch_stats"])
    variables = {"params": variables["params"], **upd}

    want = block.apply(variables, x, train=False)

    layers = [variables["params"][f"denselayer{i + 1}"] for i in range(L)]
    stats = [variables["batch_stats"][f"denselayer{i + 1}"] for i in range(L)]
    packed = pack_block_params(layers, stats, c0, growth)
    got = fused_dense_block_eval(
        x, packed, c0=c0, growth=growth, interpret=True
    )
    pad0, _ = block_pad(c0, L, growth)
    got = got[..., pad0:pad0 + c0 + L * growth]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
    )


def test_fused_block_gradients_match_concat_eval():
    """The custom-VJP backward kernel against autodiff of the concat
    reference (eval-mode affines): input gradients match, and the
    affine/weight gradients match autodiff of the folded-affine
    formulation — i.e. the kernel's hand-written backward is the true
    VJP of its own forward."""
    c0, growth, bn_size, L = 16, 8, 2, 4
    b, h, w = 2, 6, 5
    block = DenseBlock(L, growth, bn_size, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (b, h, w, c0))
    variables = block.init(jax.random.key(1), x, train=False)
    _, upd = block.apply(variables, x, train=True, mutable=["batch_stats"])
    variables = {"params": variables["params"], **upd}
    layers = [variables["params"][f"denselayer{i + 1}"] for i in range(L)]
    stats = [variables["batch_stats"][f"denselayer{i + 1}"] for i in range(L)]
    packed = pack_block_params(layers, stats, c0, growth)
    pad0, _ = block_pad(c0, L, growth)

    def loss_fused(x, pk):
        o = fused_dense_block(x, pk, c0=c0, growth=growth, interpret=True)
        return (o[..., pad0:pad0 + c0 + L * growth] ** 2).sum()

    def loss_ref(x):
        return (block.apply(variables, x, train=False) ** 2).sum()

    def loss_folded(x, pk):
        """The same folded-affine forward in plain jnp — autodiff
        reference for the affine/weight gradients."""
        feats = jnp.pad(x, ((0, 0), (0, 0), (0, 0), (pad0, 0)))
        p_total = pk["a1"].shape[-1]
        feats = jnp.pad(
            feats, ((0, 0), (0, 0), (0, 0), (0, p_total - feats.shape[-1]))
        )
        for i in range(L):
            z1 = feats * pk["a1"][i, 0] + pk["b1"][i, 0]
            y1 = jnp.einsum(
                "bhwc,co->bhwo", jnp.maximum(z1, 0.0), pk["w1"][i]
            )
            h2 = jnp.maximum(y1 * pk["a2"][i, 0] + pk["b2"][i, 0], 0.0)
            k = pk["w2"][i].reshape(3, 3, h2.shape[-1], growth)
            strip = jax.lax.conv_general_dilated(
                h2, k, (1, 1), ((1, 1), (1, 1)),
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            feats = jax.lax.dynamic_update_slice(
                feats, strip, (0, 0, 0, pad0 + c0 + i * growth)
            )
        return (feats[..., pad0:pad0 + c0 + L * growth] ** 2).sum()

    gx_f, gp_f = jax.grad(loss_fused, argnums=(0, 1))(x, packed)
    np.testing.assert_allclose(
        np.asarray(gx_f), np.asarray(jax.grad(loss_ref)(x)),
        atol=1e-3, rtol=1e-3,
    )
    gx_g, gp_g = jax.grad(loss_folded, argnums=(0, 1))(x, packed)
    np.testing.assert_allclose(np.asarray(gx_f), np.asarray(gx_g),
                               atol=1e-3, rtol=1e-3)
    for k in ("a1", "b1", "w1", "a2", "b2", "w2"):
        np.testing.assert_allclose(
            np.asarray(gp_f[k]), np.asarray(gp_g[k]),
            atol=1e-3, rtol=1e-3, err_msg=k,
        )


def test_fused_impl_matches_concat_train_grads():
    """dense_block_impl='fused' through the full model: identical param
    tree/init, forward, train-mode batch stats, and gradients — the
    two-phase BN means the gradient THROUGH the batch statistics is
    included (it flows through the stats pass + fold by autodiff)."""
    x = jax.random.normal(jax.random.key(2), (2, 16, 16, 3))
    outs = {}
    for impl in ("concat", "fused"):
        cfg = _tiny_cfg(
            dense_block_impl=impl, dense_block_fused_blocks=(0, 1)
        )
        stages = build_stages(cfg, num_stages=1)
        params, bstats = init_stages(stages, jax.random.key(0), image_size=16)

        def loss(params, bstats, x):
            logits, ns = forward_stages(stages, params, bstats, x, train=True)
            return (logits ** 2).sum(), ns

        (val, ns), grads = jax.value_and_grad(loss, has_aux=True)(
            params, bstats, x
        )
        outs[impl] = (val, ns, grads, params)
    ca = jax.tree.structure(outs["concat"][3])
    cb = jax.tree.structure(outs["fused"][3])
    assert ca == cb
    for a, b in zip(
        jax.tree.leaves(outs["concat"][3]), jax.tree.leaves(outs["fused"][3])
    ):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_allclose(outs["concat"][0], outs["fused"][0], rtol=1e-5)
    for a, b in zip(
        jax.tree.leaves(outs["concat"][1]), jax.tree.leaves(outs["fused"][1])
    ):
        np.testing.assert_allclose(a, b, atol=1e-5)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(outs["concat"][2]),
        jax.tree_util.tree_leaves_with_path(outs["fused"][2]),
    ):
        np.testing.assert_allclose(
            a, b, atol=1e-4, rtol=1e-4, err_msg=str(pa)
        )


def test_fused_impl_grads_under_jit():
    """The same parity with the whole loss+grad jitted (the compiled-mode
    path CI can exercise: XLA-compiled program around the interpret-mode
    kernels; Mosaic-compiled runs need the real chip — PERF.md)."""
    x = jax.random.normal(jax.random.key(3), (2, 16, 16, 3))
    vals, grads = {}, {}
    for impl in ("packed", "fused"):
        cfg = _tiny_cfg(
            dense_block_impl=impl, dense_block_fused_blocks=(0, 1)
        )
        stages = build_stages(cfg, num_stages=1)
        params, bstats = init_stages(stages, jax.random.key(0), image_size=16)

        @jax.jit
        def loss_grad(params, bstats, x):
            def loss(params):
                logits, _ = forward_stages(
                    stages, params, bstats, x, train=True
                )
                return (logits ** 2).sum()

            return jax.value_and_grad(loss)(params)

        vals[impl], grads[impl] = loss_grad(params, bstats, x)
    np.testing.assert_allclose(vals["packed"], vals["fused"], rtol=1e-5)
    for a, b in zip(
        jax.tree.leaves(grads["packed"]), jax.tree.leaves(grads["fused"])
    ):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_fused_train_steps_track_packed_loss_trajectory():
    """Train a few real steps (normalize + fwd + bwd + fused Adam via the
    DP step factory) with fused vs packed blocks: the loss trajectories
    and final params must agree — the end-to-end 'nothing drifts once
    the optimizer is in the loop' check on CPU interpret mode."""
    import numpy as _np

    from ddl_tpu.config import TrainConfig
    from ddl_tpu.parallel.mesh import MeshSpec, build_mesh
    from ddl_tpu.train.state import create_train_state, make_optimizer
    from ddl_tpu.train.steps import make_dp_step_fns

    rng = _np.random.default_rng(0)
    images = jnp.asarray(rng.integers(0, 255, (8, 16, 16, 3)), jnp.uint8)
    labels = jnp.asarray(rng.integers(0, 5, (8,)), jnp.int32)
    losses, finals = {}, {}
    for impl in ("packed", "fused"):
        cfg = _tiny_cfg(
            dense_block_impl=impl, dense_block_fused_blocks=(0, 1)
        )
        stages = build_stages(cfg, num_stages=1)
        tx = make_optimizer(TrainConfig())
        state = create_train_state(stages, tx, jax.random.key(0), 16)
        mesh = build_mesh(MeshSpec(1, 1))
        fns = make_dp_step_fns(stages, tx, mesh, jnp.float32)
        ls = []
        for _ in range(4):
            state, loss, _ = fns.train(state, images, labels)
            ls.append(float(loss))
        losses[impl] = ls
        finals[impl] = state.params
    np.testing.assert_allclose(
        losses["packed"], losses["fused"], atol=1e-4, rtol=1e-4
    )
    for a, b in zip(
        jax.tree.leaves(finals["packed"]), jax.tree.leaves(finals["fused"])
    ):
        np.testing.assert_allclose(a, b, atol=1e-4, rtol=1e-4)


def test_fused_eval_uses_running_stats():
    """After a train step mutates running averages, fused eval (running-
    stat affines, single kernel, no stats pass) matches packed eval."""
    x = jax.random.normal(jax.random.key(4), (2, 16, 16, 3))
    outs = {}
    for impl in ("packed", "fused"):
        cfg = _tiny_cfg(
            dense_block_impl=impl, dense_block_fused_blocks=(0, 1)
        )
        stages = build_stages(cfg, num_stages=1)
        params, bstats = init_stages(stages, jax.random.key(0), image_size=16)
        _, bstats = forward_stages(stages, params, bstats, x, train=True)
        logits, _ = forward_stages(stages, params, bstats, x, train=False)
        outs[impl] = np.asarray(logits)
    np.testing.assert_allclose(
        outs["packed"], outs["fused"], atol=1e-4, rtol=1e-4
    )


def test_fused_block_respects_conv_padding():
    """Edge pixels exercise the explicit zero halo of the in-kernel 3x3."""
    c0, growth, bn_size, L = 8, 8, 1, 2
    b, h, w = 1, 3, 3
    block = DenseBlock(L, growth, bn_size, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(2), (b, h, w, c0)) * 2.0
    variables = block.init(jax.random.key(3), x, train=False)
    want = block.apply(variables, x, train=False)
    layers = [variables["params"][f"denselayer{i + 1}"] for i in range(L)]
    stats = [variables["batch_stats"][f"denselayer{i + 1}"] for i in range(L)]
    packed = pack_block_params(layers, stats, c0, growth)
    got = fused_dense_block_eval(
        x, packed, c0=c0, growth=growth, interpret=True
    )
    pad0, _ = block_pad(c0, L, growth)
    got = got[..., pad0:pad0 + c0 + L * growth]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
    )
