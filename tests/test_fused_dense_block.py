"""Fused VMEM-resident dense-block kernel (ops/fused_dense_block.py) vs
the textbook concat DenseBlock — eval-mode forward parity, interpreter
mode.  (The experiment's chip measurements and go/no-go analysis live in
PERF.md round 5.)"""

import jax
import jax.numpy as jnp
import numpy as np

from ddl_tpu.models.densenet import DenseBlock
from ddl_tpu.ops.fused_dense_block import (
    block_pad,
    fused_dense_block_eval,
    pack_block_params,
)


def test_fused_block_matches_concat_eval():
    c0, growth, bn_size, L = 16, 8, 2, 4
    b, h, w = 2, 6, 5
    block = DenseBlock(L, growth, bn_size, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(0), (b, h, w, c0))
    variables = block.init(jax.random.key(1), x, train=False)
    # make running stats non-trivial: one train-mode step, keep mutations
    _, upd = block.apply(variables, x, train=True, mutable=["batch_stats"])
    variables = {"params": variables["params"], **upd}

    want = block.apply(variables, x, train=False)

    layers = [variables["params"][f"denselayer{i + 1}"] for i in range(L)]
    stats = [variables["batch_stats"][f"denselayer{i + 1}"] for i in range(L)]
    packed = pack_block_params(layers, stats, c0, growth)
    got = fused_dense_block_eval(
        x, packed, c0=c0, growth=growth, interpret=True
    )
    pad0, _ = block_pad(c0, L, growth)
    got = got[..., pad0:pad0 + c0 + L * growth]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
    )


def test_fused_block_respects_conv_padding():
    """Edge pixels exercise the explicit zero halo of the in-kernel 3x3."""
    c0, growth, bn_size, L = 8, 8, 1, 2
    b, h, w = 1, 3, 3
    block = DenseBlock(L, growth, bn_size, dtype=jnp.float32)
    x = jax.random.normal(jax.random.key(2), (b, h, w, c0)) * 2.0
    variables = block.init(jax.random.key(3), x, train=False)
    want = block.apply(variables, x, train=False)
    layers = [variables["params"][f"denselayer{i + 1}"] for i in range(L)]
    stats = [variables["batch_stats"][f"denselayer{i + 1}"] for i in range(L)]
    packed = pack_block_params(layers, stats, c0, growth)
    got = fused_dense_block_eval(
        x, packed, c0=c0, growth=growth, interpret=True
    )
    pad0, _ = block_pad(c0, L, growth)
    got = got[..., pad0:pad0 + c0 + L * growth]
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=1e-4, rtol=1e-4
    )
