"""KV-cached autoregressive decoding (infer/decode.py).

Parity discipline: incremental decode shares parameters with the training
model by construction, so its logits must match the full-sequence forward
bit-for-bit-close in f32 — both at prefill and after every cached step.
(The reference has no generation path at all; its only inference surface is
the loss-less eval schedule, ``pp.py:146-150``.)
"""

import jax
import jax.numpy as jnp
import numpy as np

from ddl_tpu.infer import LMDecode, init_kv_cache, make_lm_generator
from ddl_tpu.models.transformer import LMConfig, TransformerLM
from ddl_tpu.parallel.sharding import LMMeshSpec


def _cfg(**kw):
    base = dict(
        vocab_size=32,
        d_model=16,
        n_layers=2,
        n_heads=2,
        head_dim=8,
        d_ff=32,
        compute_dtype="float32",
        attn_impl="dense",
        remat=False,
    )
    base.update(kw)
    return LMConfig(**base)


def _params(cfg, batch=2, t=8, seed=0):
    model = TransformerLM(cfg, None)
    dummy = jnp.zeros((batch, t), jnp.int32)
    import flax.linen as nn

    return nn.meta.unbox(model.init(jax.random.key(seed), dummy)["params"])


def test_prefill_matches_full_forward():
    """Prefill through the cache path == the training forward."""
    cfg = _cfg()
    b, p = 2, 6
    params = _params(cfg, b, p)
    toks = jnp.asarray(np.random.default_rng(0).integers(0, 32, (b, p)))

    ref_logits, _ = TransformerLM(cfg, None).apply({"params": params}, toks)

    caches = init_kv_cache(cfg, b, p + 2)
    dec_logits, _ = LMDecode(cfg).apply({"params": params}, toks, caches, 0)
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(dec_logits), atol=1e-5
    )


def test_incremental_matches_full_forward():
    """Token-by-token cached decode reproduces the full forward's logits at
    every position."""
    cfg = _cfg()
    b, t = 2, 7
    params = _params(cfg, b, t)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 32, (b, t)))

    ref_logits, _ = TransformerLM(cfg, None).apply({"params": params}, toks)

    dec = LMDecode(cfg)
    caches = init_kv_cache(cfg, b, t)
    got = []
    for i in range(t):
        logits, caches = dec.apply(
            {"params": params}, toks[:, i : i + 1], caches, i
        )
        got.append(logits[:, 0])
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.stack([np.asarray(g) for g in got], 1),
        atol=1e-5,
    )


def test_greedy_generate_matches_teacher_forcing():
    """The jitted generate loop == a python loop re-running the full
    forward and taking argmax each step."""
    cfg = _cfg()
    b, p, n = 2, 4, 5
    params = _params(cfg, b, p)
    prompt = jnp.asarray(np.random.default_rng(2).integers(0, 32, (b, p)))

    model = TransformerLM(cfg, None)
    seq = prompt
    ref = []
    for _ in range(n):
        logits, _ = model.apply({"params": params}, seq)
        tok = jnp.argmax(logits[:, -1], -1).astype(jnp.int32)
        ref.append(np.asarray(tok))
        seq = jnp.concatenate([seq, tok[:, None]], axis=1)

    gen = make_lm_generator(
        cfg, prompt_len=p, max_new=n, batch=b, devices=jax.devices()[:1]
    )
    out = np.asarray(gen(params, prompt))
    assert out.shape == (b, n)
    np.testing.assert_array_equal(out, np.stack(ref, 1))


def test_tp_decode_matches_single_device():
    """Tensor-parallel decode on a (data=2, model=2) mesh == 1 device."""
    cfg = _cfg(n_heads=4)
    b, p, n = 4, 4, 4
    params = _params(cfg, b, p)
    prompt = jnp.asarray(np.random.default_rng(3).integers(0, 32, (b, p)))

    single = make_lm_generator(
        cfg, prompt_len=p, max_new=n, batch=b, devices=jax.devices()[:1]
    )
    tp = make_lm_generator(
        cfg,
        LMMeshSpec(data=2, model=2),
        prompt_len=p,
        max_new=n,
        batch=b,
        devices=jax.devices()[:4],
    )
    np.testing.assert_array_equal(
        np.asarray(single(params, prompt)), np.asarray(tp(params, prompt))
    )


def test_seq_sharded_decode_matches_single_device():
    """Context-parallel decode: the KV cache shards over the ``seq`` mesh
    axis (the same logical-axis rules as training), and GSPMD inserts the
    gather/reduce for the softmax over the sharded cache — long-prompt
    serving where one device cannot hold the cache.  Token-exact vs one
    device, composed with data and model parallelism."""
    cfg = _cfg(n_heads=4)
    b, p, n = 2, 16, 6
    params = _params(cfg, b, p)
    prompt = jnp.asarray(np.random.default_rng(5).integers(0, 32, (b, p)))

    single = make_lm_generator(
        cfg, prompt_len=p, max_new=n, batch=b, devices=jax.devices()[:1]
    )
    sp = make_lm_generator(
        cfg,
        LMMeshSpec(data=2, seq=2, model=2),
        prompt_len=p,
        max_new=n,
        batch=b,
    )
    np.testing.assert_array_equal(
        np.asarray(single(params, prompt)), np.asarray(sp(params, prompt))
    )


def test_sampled_generation_and_moe():
    """Temperature sampling is deterministic under a fixed key; MoE decode
    runs end-to-end (capacity-based routing makes incremental MoE logits
    legitimately diverge from teacher forcing, so only self-consistency is
    asserted)."""
    cfg = _cfg(num_experts=4, expert_top_k=2)
    b, p, n = 2, 4, 4
    params = _params(cfg, b, p)
    prompt = jnp.asarray(np.random.default_rng(4).integers(0, 32, (b, p)))

    gen = make_lm_generator(
        cfg, prompt_len=p, max_new=n, batch=b, temperature=0.8,
        devices=jax.devices()[:1],
    )
    a = np.asarray(gen(params, prompt, jax.random.key(7)))
    bb = np.asarray(gen(params, prompt, jax.random.key(7)))
    np.testing.assert_array_equal(a, bb)
    assert a.shape == (b, n)
    assert ((a >= 0) & (a < 32)).all()
    # different keys must eventually diverge (an untrained model's output
    # distribution is near-uniform over 32 tokens)
    others = [np.asarray(gen(params, prompt, jax.random.key(s)))
              for s in (8, 9, 10)]
    assert any(not np.array_equal(a, o) for o in others)


def test_top_k_sampling_restricts_support():
    """top_k=1 sampling == greedy decoding, for any temperature."""
    cfg = _cfg()
    b, p, n = 2, 4, 5
    params = _params(cfg, b, p)
    prompt = jnp.asarray(np.random.default_rng(5).integers(0, 32, (b, p)))
    greedy = make_lm_generator(
        cfg, prompt_len=p, max_new=n, batch=b, devices=jax.devices()[:1]
    )
    k1 = make_lm_generator(
        cfg, prompt_len=p, max_new=n, batch=b, temperature=1.3, top_k=1,
        devices=jax.devices()[:1],
    )
    np.testing.assert_array_equal(
        np.asarray(greedy(params, prompt)),
        np.asarray(k1(params, prompt, jax.random.key(3))),
    )


def test_gqa_incremental_matches_full_forward():
    """Grouped-query attention (n_kv_heads < n_heads): the reduced-head KV
    cache and grouped dense_attention reproduce the training forward's
    logits token by token."""
    cfg = _cfg(n_heads=4, n_kv_heads=2)
    b, t = 2, 6
    params = _params(cfg, b, t)
    toks = jnp.asarray(np.random.default_rng(1).integers(0, 32, (b, t)))
    ref_logits, _ = TransformerLM(cfg, None).apply({"params": params}, toks)

    caches = init_kv_cache(cfg, b, t)
    assert caches[0][0].shape == (b, t, 2 * 8)  # Hkv=2, half the MHA cache (fused Hkv*Dh storage)
    dec = LMDecode(cfg)
    for i in range(t):
        logits, caches = dec.apply(
            {"params": params}, toks[:, i : i + 1], caches, i
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, 0]), np.asarray(ref_logits[:, i]), atol=1e-5
        )


def test_decode_bench_smoke(capsys):
    """bench/decode.py runs end to end and reports the sweep fields (the
    real-chip numbers live in PERF.md; this guards the harness)."""
    import json
    import sys

    from ddl_tpu.bench import decode as bench_decode

    argv = sys.argv
    sys.argv = [
        "decode", "--batch", "1", "--prompt", "16", "--new", "4",
        "--d-model", "64", "--layers", "2", "--vocab", "64",
        "--kv-heads", "0", "--attn-window", "8", "--iters", "1",
    ]
    try:
        bench_decode.main()
    finally:
        sys.argv = argv
    row = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    # CPU walls are microseconds, so the two-length slope can come out
    # negative from noise; the bench then deterministically falls back to
    # the undifferenced quote (flagged `slope_fallback`) instead of
    # raising — the PR-6 "host contention" tier-1 flake.  Real timing
    # signs belong to the real-chip runs (PERF.md).
    assert row["decode_tok_per_sec"] > 0 and row["prefill_ms"] > 0
    # the windowed ring allocates O(window); its per-step read spans the
    # same window rows
    assert row["cache_bytes_per_layer"] < row["max_len"] * 2 * 64 * 4
    assert row["read_bytes_per_step_layer"] <= row["cache_bytes_per_layer"]


def test_rolling_cache_matches_linear_and_is_o_window():
    """The ring cache (rolling=True, O(window) allocation) decodes the
    exact same tokens as the linear cache, for prompts longer and shorter
    than the window, and really allocates only window rows."""
    import flax.linen as nn

    from ddl_tpu.infer.decode import init_kv_cache, make_lm_generator
    from ddl_tpu.models.transformer import LMConfig, TransformerLM

    cfg = LMConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, head_dim=8,
        d_ff=64, compute_dtype="float32", remat=False, attn_window=6,
    )
    params = nn.meta.unbox(
        TransformerLM(cfg, None).init(
            jax.random.key(0), jnp.zeros((1, 4), jnp.int32)
        )["params"]
    )
    caches = init_kv_cache(cfg, 2, 64, rolling=True)
    assert caches[0][0].shape == (2, 6, 4 * 8)  # (B, window, Hkv*Dh fused)
    rng = np.random.default_rng(0)
    for prompt_len, max_new in ((12, 10), (3, 15)):
        prompt = jnp.asarray(
            rng.integers(0, 64, (1, prompt_len)), jnp.int32
        )
        lin = make_lm_generator(
            cfg, prompt_len=prompt_len, max_new=max_new, rolling=False
        )
        rol = make_lm_generator(
            cfg, prompt_len=prompt_len, max_new=max_new, rolling=True
        )
        np.testing.assert_array_equal(
            np.asarray(lin(params, prompt)), np.asarray(rol(params, prompt))
        )

    # auto mode turns the ring on exactly when a window is set and smaller
    # than the cache; without a window it must reject rolling=True
    import pytest

    with pytest.raises(ValueError, match="attn_window"):
        make_lm_generator(
            dataclasses_replace_no_window(cfg), prompt_len=4, max_new=4,
            rolling=True,
        )


def dataclasses_replace_no_window(cfg):
    import dataclasses

    return dataclasses.replace(cfg, attn_window=0)


def test_flash_prefill_matches_dense_prefill():
    """Flash-kernel prefill (cfg.flash=True routes the prompt pass through
    the Pallas kernel; decode steps stay cached-dense) produces the same
    tokens as the dense prefill, for full-cache and windowed configs."""
    for kw in ({}, {"attn_window": 4}):
        cfg = _cfg(**kw)
        b, p, n = 2, 8, 5
        params = _params(cfg, b, p)
        prompt = jnp.asarray(
            np.random.default_rng(7).integers(0, 32, (b, p))
        )
        dense = make_lm_generator(
            cfg, prompt_len=p, max_new=n, batch=b,
            devices=jax.devices()[:1],
        )
        import dataclasses

        fcfg = dataclasses.replace(cfg, flash=True)
        flash = make_lm_generator(
            fcfg, prompt_len=p, max_new=n, batch=b,
            devices=jax.devices()[:1],
        )
        np.testing.assert_array_equal(
            np.asarray(dense(params, prompt)),
            np.asarray(flash(params, prompt)),
        )
