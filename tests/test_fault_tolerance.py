"""Fault tolerance: every recovery path proven end-to-end on CPU via the
deterministic fault-injection harness (``ddl_tpu/utils/faultinject.py``).

The headline scenarios (ISSUE 2 acceptance criteria):

* an injected ``preempt@step`` followed by a supervised relaunch resumes
  from a verified snapshot and finishes the run with no manual resume
  args (``test_injected_preempt_supervised_relaunch_resumes``);
* an injected ``corrupt_ckpt`` makes restore fall back to the previous
  good snapshot (``test_corrupt_snapshot_falls_back_to_previous``).

Everything here is CPU-only and fast-tier: proving recovery must not
cost a slow-tier run.
"""

import json
import math
import os
import random
import signal
import threading

import numpy as np
import pytest

from ddl_tpu import checkpoint as ckpt
from ddl_tpu.supervisor import EXIT_PREEMPTED, Supervisor
from ddl_tpu.train.loop import BaseTrainer
from ddl_tpu.utils import faultinject
from ddl_tpu.utils.backoff import Backoff, retry_with_backoff
from ddl_tpu.utils.preemption import PreemptionGuard


@pytest.fixture(autouse=True)
def _clean_injector():
    faultinject.deactivate()
    yield
    faultinject.deactivate()


def _tiny_lm(tmp_path, job_id, steps, **run_overrides):
    import optax

    from ddl_tpu.models.transformer import LMConfig
    from ddl_tpu.parallel.sharding import LMMeshSpec
    from ddl_tpu.train.lm_trainer import LMRunConfig, LMTrainer

    cfg = LMConfig(
        vocab_size=256, d_model=32, n_layers=2, n_heads=4, head_dim=8,
        d_ff=64, compute_dtype="float32", remat=False,
    )
    run_kwargs = dict(
        batch=4, seq_len=16, steps=steps, job_id=job_id,
        checkpoint_dir=str(tmp_path / "ckpt"),
        log_dir=str(tmp_path / "logs"),
    )
    run_kwargs.update(run_overrides)
    run = LMRunConfig(**run_kwargs)
    return LMTrainer(cfg, LMMeshSpec(), optax.adam(1e-3), run)


# ---------------------------------------------------------------------------
# fault-injection harness
# ---------------------------------------------------------------------------


def test_fault_spec_parsing():
    inj = faultinject.FaultInjector.parse(
        "preempt@step:12, crash@step:8, stall@step:4:30, io@save:1:2"
    )
    kinds = [s.kind for s in inj.specs]
    assert kinds == ["preempt", "crash", "stall", "io"]
    assert inj.specs[2].arg == 30.0
    assert inj.specs[3].repeat == 2
    with pytest.raises(ValueError, match="unknown fault kind"):
        faultinject.FaultInjector.parse("explode@step:1")
    with pytest.raises(ValueError, match="bad fault spec"):
        faultinject.FaultInjector.parse("preempt@step")
    # braces in a bad spec must not break the error message itself
    with pytest.raises(ValueError, match="bad fault spec"):
        faultinject.FaultInjector.parse("nan@step:{5}")


def test_fault_fires_exactly_once_and_kind_counters_are_independent(tmp_path):
    faultinject.activate("io@save:1,corrupt_ckpt@save:1")
    # the io spec fails the first save *attempt*; the corrupt spec fires
    # on the first *committed* save — independent counters, so one
    # save_snapshot call exercises both
    saved = ckpt.save_snapshot(tmp_path, "j", 0, {"w": np.ones((4,))})
    ok, reason = ckpt.verify_snapshot(saved)
    assert not ok and ("mismatch" in reason or "truncated" in reason)
    inj = faultinject.active()
    assert sorted(k for k, _, _ in inj.log) == ["corrupt_ckpt", "io"]


def test_crash_and_env_activation(monkeypatch):
    monkeypatch.setenv("DDL_FAULT", "crash@step:2")
    faultinject.deactivate()  # re-arm the env check
    faultinject.check_step(1)
    with pytest.raises(faultinject.InjectedCrash):
        faultinject.check_step(2)
    faultinject.deactivate()


def test_rejoin_fault_fires_at_epoch_and_is_consumed(monkeypatch, tmp_path):
    """``rejoin@epoch:K`` (the elastic scale-up drill): quiet below K,
    fires once the incarnation's restart epoch reaches K, and records
    itself consumed BEFORE the child acts on it — so the supervisor's
    relaunch filter drops the spec and the post-grow incarnation trains
    normally instead of leaving again."""
    state = tmp_path / "fault_state"
    monkeypatch.setenv("DDL_FAULT_STATE", str(state))
    faultinject.activate("rejoin@epoch:2")
    assert not faultinject.check_epoch(0)
    assert not faultinject.check_epoch(1)
    assert faultinject.check_epoch(2)
    # consume-on-fire, recorded before the exit the fault triggers
    assert state.read_text().splitlines() == ["rejoin@epoch:2"]
    assert not faultinject.check_epoch(2)  # exhausted in this injector
    # a relaunch that re-activated the spec verbatim would fire on any
    # later epoch too (``at >=``) — dropping consumed specs from the
    # relaunch env is what keeps the grown pod stable
    faultinject.activate("rejoin@epoch:2")
    assert faultinject.check_epoch(3)
    faultinject.deactivate()


# ---------------------------------------------------------------------------
# backoff
# ---------------------------------------------------------------------------


def test_backoff_jitter_bounds():
    b = Backoff(base=1.0, factor=2.0, max_delay=10.0, jitter=0.5,
                rng=random.Random(7))
    for attempt in range(12):
        cap = min(10.0, 2.0 ** attempt)
        d = b.delay(attempt)
        assert (1 - 0.5) * cap <= d <= cap
    # zero jitter is exact; delays are capped
    b0 = Backoff(base=1.0, factor=2.0, max_delay=10.0, jitter=0.0)
    assert [b0.delay(i) for i in range(5)] == [1.0, 2.0, 4.0, 8.0, 10.0]
    with pytest.raises(ValueError):
        Backoff(jitter=1.5)


def test_retry_with_backoff_bounded():
    calls, sleeps = [], []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("flake")
        return "ok"

    out = retry_with_backoff(
        flaky, retries=3, backoff=Backoff(base=0.1, jitter=0.0),
        sleep=sleeps.append,
    )
    assert out == "ok" and len(calls) == 3 and len(sleeps) == 2

    def always():
        raise OSError("down")

    with pytest.raises(OSError, match="down"):
        retry_with_backoff(
            always, retries=2, backoff=Backoff(base=0.1, jitter=0.0),
            sleep=sleeps.append,
        )


# ---------------------------------------------------------------------------
# preemption guard satellites
# ---------------------------------------------------------------------------


def test_preemption_guard_off_main_thread_degrades_gracefully():
    results = {}

    def worker():
        with pytest.warns(UserWarning, match="main thread"):
            with PreemptionGuard() as guard:
                results["installed"] = guard.installed
                guard.request()
                results["requested"] = guard.requested

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    assert results == {"installed": False, "requested": True}


def test_preemption_guard_catches_sigint():
    with PreemptionGuard() as guard:
        assert not guard.requested
        os.kill(os.getpid(), signal.SIGINT)
        assert guard.requested  # no KeyboardInterrupt, just the flag
        # second Ctrl-C is the escape hatch: a wedged main thread never
        # polls the flag, so the operator gets the standard interrupt
        with pytest.raises(KeyboardInterrupt):
            os.kill(os.getpid(), signal.SIGINT)


# ---------------------------------------------------------------------------
# data loader resilience
# ---------------------------------------------------------------------------


class _FlakyDataset:
    """Each sample read fails `fail_first` times before succeeding."""

    def __init__(self, n=8, fail_first=1):
        self.n = n
        self.fail_first = fail_first
        self.failures: dict[int, int] = {}
        self.labels = [i % 5 for i in range(n)]

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        seen = self.failures.get(i, 0)
        if seen < self.fail_first:
            self.failures[i] = seen + 1
            raise OSError(f"transient NAS flake on sample {i}")
        return np.zeros((4, 4, 3), np.uint8), self.labels[i]


def test_loader_retries_transient_io():
    from ddl_tpu.data.loader import DataLoader
    from ddl_tpu.data.sampler import ShardedEpochSampler

    retried = []
    ds = _FlakyDataset(n=8, fail_first=1)
    loader = DataLoader(
        ds, 4, sampler=ShardedEpochSampler(8, shuffle=False), num_workers=0,
        on_retry=lambda exc, attempt: retried.append(str(exc)),
    )
    batches = list(loader)
    assert len(batches) == 2  # the epoch survives
    assert loader.retry_count == 8 and len(retried) == 8

    # retries are bounded: a persistent failure still kills the epoch —
    # and reaches the consumer as the original error, not a silently
    # truncated epoch (the producer thread used to swallow it)
    ds_dead = _FlakyDataset(n=8, fail_first=99)
    loader_dead = DataLoader(
        ds_dead, 4, sampler=ShardedEpochSampler(8, shuffle=False),
        num_workers=0, io_retries=1,
    )
    with pytest.raises(OSError, match="transient NAS flake"):
        list(loader_dead)


# ---------------------------------------------------------------------------
# checkpoint integrity + rollback
# ---------------------------------------------------------------------------


def test_manifest_verify_and_latest_valid(tmp_path):
    state = {"w": np.arange(16.0)}
    p0 = ckpt.save_snapshot(tmp_path, "job", 0, state)
    p1 = ckpt.save_snapshot(tmp_path, "job", 1, state)
    assert ckpt.verify_snapshot(p0)[0] and ckpt.verify_snapshot(p1)[0]
    assert ckpt.latest_valid_epoch(tmp_path, "job") == 1

    faultinject.corrupt_snapshot(p1)
    ok, reason = ckpt.verify_snapshot(p1)
    assert not ok and ("mismatch" in reason or "truncated" in reason)
    # automatic fallback to the previous good snapshot
    assert ckpt.latest_valid_epoch(tmp_path, "job") == 0
    assert ckpt.resolve_resume(tmp_path, "job") == 0
    with pytest.raises(ckpt.SnapshotCorruptError):
        ckpt.load_snapshot(tmp_path, "job", 1, state)

    # a manifest-less snapshot (pre-integrity-layer) stays restorable
    (p0 / ckpt.MANIFEST_NAME).unlink()
    ok, reason = ckpt.verify_snapshot(p0)
    assert ok and "legacy" in reason
    restored, epochs = ckpt.load_snapshot(tmp_path, "job", 0, state)
    assert epochs == 1 and np.allclose(restored["w"], state["w"])


def test_save_retries_injected_io_error(tmp_path):
    faultinject.activate("io@save:1:2")  # first two attempts fail
    path = ckpt.save_snapshot(tmp_path, "job", 0, {"w": np.ones((4,))})
    assert ckpt.verify_snapshot(path)[0]

    faultinject.activate("io@save:1:99")  # beyond the retry budget
    with pytest.raises(OSError, match="injected"):
        ckpt.save_snapshot(tmp_path, "job", 1, {"w": np.ones((4,))})


def test_corrupt_snapshot_falls_back_to_previous(tmp_path):
    """Acceptance: a corrupted newest snapshot is skipped and auto-resume
    restores the previous good one — in a real trainer, end to end."""
    t = _tiny_lm(tmp_path, "lm-corrupt", steps=4, save_every=2,
                 log_dir=None)
    t.train()
    assert ckpt.latest_epoch(tmp_path / "ckpt", "lm-corrupt") == 4

    faultinject.corrupt_snapshot(
        ckpt.snapshot_path(tmp_path / "ckpt", "lm-corrupt", 4)
    )
    resumed = _tiny_lm(tmp_path, "lm-corrupt", steps=6, save_every=2,
                       log_dir=None)
    # fell back from the corrupt step-4 snapshot to step 2, no args
    assert resumed._start_step == 2
    resumed.train()
    assert int(resumed.state.step) == 6


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------


def test_supervisor_restarts_after_crash_with_backoff():
    sleeps = []
    attempts = []

    def attempt(restart_index):
        attempts.append(restart_index)
        if len(attempts) < 3:
            raise faultinject.InjectedCrash("boom")
        return 0

    sup = Supervisor(
        attempt, max_restarts=5,
        backoff=Backoff(base=1.0, factor=2.0, jitter=0.0),
        sleep=sleeps.append, log=lambda m: None,
    )
    assert sup.run() == 0
    assert attempts == [0, 1, 2]
    assert sleeps == [1.0, 2.0]  # exponential between crash relaunches
    assert sup.crashes == 2 and sup.preemptions == 0


def test_supervisor_gives_up_after_max_restarts():
    sup = Supervisor(
        lambda i: 1, max_restarts=3, backoff=Backoff(jitter=0.0),
        sleep=lambda d: None, log=lambda m: None,
    )
    assert sup.run() == 1
    assert sup.restarts == 4  # 1 initial + 3 relaunches counted


def test_supervisor_preemption_relaunch_backoff_policy():
    # a single eviction relaunches immediately; a STREAK of resumable
    # exits (e.g. a watchdog deadline below the first-step compile)
    # backs off like a crash loop, still without touching the crash
    # budget
    sleeps = []
    codes = [EXIT_PREEMPTED, EXIT_PREEMPTED, EXIT_PREEMPTED, 0]
    sup = Supervisor(
        lambda i: codes[i], max_restarts=5, sleep=sleeps.append,
        backoff=Backoff(base=1.0, factor=2.0, jitter=0.0),
        log=lambda m: None,
    )
    assert sup.run() == 0
    assert sup.preemptions == 3 and sup.crashes == 0
    assert sleeps == [1.0, 2.0]  # nothing before the first relaunch


def test_supervisor_preemptions_do_not_consume_crash_budget():
    # 10 routine evictions on a preemptible pod with max_restarts=2:
    # the run must still complete (and the pathological always-75 loop
    # is bounded by the max_preemptions safety cap)
    codes = [EXIT_PREEMPTED] * 10 + [0]
    sup = Supervisor(
        lambda i: codes[i], max_restarts=2, sleep=lambda d: None,
        log=lambda m: None,
    )
    assert sup.run() == 0
    assert sup.preemptions == 10 and sup.crashes == 0

    sup_loop = Supervisor(
        lambda i: EXIT_PREEMPTED, max_restarts=2, max_preemptions=5,
        sleep=lambda d: None, log=lambda m: None,
    )
    assert sup_loop.run() == EXIT_PREEMPTED
    assert sup_loop.preemptions == 6  # 5 relaunches + the give-up check


def test_supervise_command_subprocess_crash_then_success(tmp_path):
    """The real subprocess runner: child crashes once (an injected fault
    it CONSUMES), then completes; the supervisor env contract is
    visible, and the fired fault does not recur on relaunch."""
    import sys

    from ddl_tpu.supervisor import supervise_command

    marker = tmp_path / "attempts"
    prog = (
        "import os, pathlib, sys\n"
        "sys.path.insert(0, os.getcwd())\n"
        f"m = pathlib.Path({str(marker)!r})\n"
        "n = int(m.read_text()) if m.exists() else 0\n"
        "m.write_text(str(n + 1))\n"
        "assert os.environ['DDL_SUPERVISED'] == '1'\n"
        "assert os.environ['DDL_RESTART_COUNT'] == str(n)\n"
        "assert os.environ['DDL_WATCHDOG_ACTION'] == 'exit'\n"
        # consume-on-fire: the spec is present on the first attempt,
        # fires (recorded via DDL_FAULT_STATE), and is dropped from the
        # relaunch env because it fired — not because relaunch wipes all
        "assert ('DDL_FAULT' in os.environ) == (n == 0)\n"
        "from ddl_tpu.utils import faultinject\n"
        "try:\n"
        "    faultinject.check_step(1)\n"
        "except faultinject.InjectedCrash:\n"
        "    sys.exit(1)\n"
        "sys.exit(0)\n"
    )
    env = dict(os.environ)
    env["DDL_FAULT"] = "crash@step:1"
    env["DDL_LOG_DIR"] = str(tmp_path / "logs")
    env["DDL_JOB_ID"] = "supcmd"
    env.pop("DDL_FAULT_PERSIST", None)
    env.pop("DDL_FAULT_STATE", None)
    rc = supervise_command(
        [sys.executable, "-c", prog], max_restarts=2, env=env,
        backoff=Backoff(base=0.01, jitter=0.0), log=lambda m: None,
    )
    assert rc == 0 and marker.read_text() == "2"
    # the supervisor's own lifecycle events landed in the job's stream
    from ddl_tpu.obs import events_path, read_events

    kinds = [
        e["kind"]
        for e in read_events(events_path(tmp_path / "logs", "supcmd"))
    ]
    assert kinds[0] == "supervisor_start"
    assert "supervisor_relaunch" in kinds
    assert kinds[-1] == "supervisor_done"


def test_relaunch_preserves_non_consumed_fault_specs(tmp_path):
    """Multi-fault scenario: only the spec that FIRED is dropped on
    relaunch; the not-yet-fired one (a second fault beyond the resume
    point) survives and fires in the next attempt."""
    import sys

    from ddl_tpu.supervisor import supervise_command

    seen = tmp_path / "seen_faults"
    prog = (
        "import os, pathlib, sys\n"
        "sys.path.insert(0, os.getcwd())\n"
        f"s = pathlib.Path({str(seen)!r})\n"
        "with s.open('a') as fh:\n"
        "    fh.write(os.environ.get('DDL_FAULT', '<none>') + '\\n')\n"
        "from ddl_tpu.utils import faultinject\n"
        "try:\n"
        "    for step in range(8):\n"
        "        faultinject.check_step(step)\n"
        "except faultinject.InjectedCrash:\n"
        "    sys.exit(1)\n"
        "sys.exit(0)\n"
    )
    env = dict(os.environ)
    # two crashes at different steps: each attempt consumes exactly one
    env["DDL_FAULT"] = "crash@step:2,crash@step:5"
    env.pop("DDL_FAULT_PERSIST", None)
    env.pop("DDL_FAULT_STATE", None)
    env["DDL_LOG_DIR"] = str(tmp_path / "logs")
    rc = supervise_command(
        [sys.executable, "-c", prog], max_restarts=3, env=env,
        backoff=Backoff(base=0.01, jitter=0.0), log=lambda m: None,
    )
    assert rc == 0
    attempts = seen.read_text().splitlines()
    assert attempts == [
        "crash@step:2,crash@step:5",  # both armed
        "crash@step:5",               # first consumed, second preserved
        "<none>",                     # all consumed
    ]


def test_surviving_faults_filter_matches_duplicates_one_for_one(tmp_path):
    from ddl_tpu.supervisor import _surviving_faults

    state = tmp_path / "state"
    state.write_text("io@save:1\n")
    # two identical specs, one fired: exactly one survives
    assert _surviving_faults("io@save:1, io@save:1", state) == "io@save:1"
    # missing state file = nothing fired (a child that crashed before
    # its fault must not disarm it)
    assert _surviving_faults(
        "crash@step:3", tmp_path / "nope"
    ) == "crash@step:3"


def test_injected_preempt_supervised_relaunch_resumes(tmp_path):
    """Acceptance: preempt@step -> supervised relaunch -> auto-resume from
    a verified snapshot -> run completes at the same final step, with no
    manual resume args and loss continuing finitely."""
    total_steps = 8
    losses: list[float] = []

    def attempt(restart_index):
        if restart_index == 0:
            faultinject.activate("preempt@step:3")
        else:
            faultinject.deactivate()  # the eviction does not recur
        t = _tiny_lm(
            tmp_path, "lm-preempt-sup", steps=total_steps,
            save_every=10**9, log_dir=None, log_every=1,
        )
        orig = t.run_period

        def spy(period, guard=None):
            m, steps = orig(period, guard)
            if "loss" in m:
                losses.append(m["loss"])
            return m, steps

        t.run_period = spy
        t.train()
        if t.preempted:
            # the snapshot the relaunch will read is already verified
            step = ckpt.latest_valid_epoch(tmp_path / "ckpt", "lm-preempt-sup")
            assert step is not None
            path = ckpt.snapshot_path(tmp_path / "ckpt", "lm-preempt-sup", step)
            assert ckpt.verify_snapshot(path)[0]
            return EXIT_PREEMPTED
        assert int(t.state.step) == total_steps
        return 0

    sup = Supervisor(attempt, max_restarts=3, sleep=lambda d: None)
    assert sup.run() == 0
    assert sup.preemptions == 1 and sup.crashes == 0
    assert losses and all(math.isfinite(x) for x in losses)


def test_supervisor_restart_after_injected_crash_resumes_training(tmp_path):
    """crash@step -> relaunch with backoff -> auto-resume from the last
    cadence snapshot -> completion."""
    def attempt(restart_index):
        if restart_index == 0:
            faultinject.activate("crash@step:5")
        else:
            faultinject.deactivate()
        try:
            t = _tiny_lm(tmp_path, "lm-crash-sup", steps=8, save_every=2,
                         log_dir=None)
            t.train()
        except faultinject.InjectedCrash:
            return 1
        assert int(t.state.step) == 8
        # the relaunch resumed from the step-4 snapshot, not from scratch
        assert t._start_step == 4
        return 0

    sleeps = []
    sup = Supervisor(attempt, max_restarts=3, sleep=sleeps.append,
                     backoff=Backoff(base=0.01, jitter=0.0))
    assert sup.run() == 0
    assert sup.crashes == 1 and len(sleeps) == 1


# ---------------------------------------------------------------------------
# NaN recovery policy
# ---------------------------------------------------------------------------


class _PolicyStub(BaseTrainer):
    """Scripted-loss stub (the test_loop pattern) with a scripted
    rollback: restoring rewinds two periods and heals the loss stream."""

    period_label = "Epoch"

    def __init__(self, losses, recovery, rollback_to=None):
        self.state = None
        self.job_id = "stub"
        self.logger = None
        self.is_logging_process = True
        self.periods_run = 0
        self.num_periods = len(losses)
        self.halt_on_nan = True
        self.preemption_save = False
        self.profile_dir = None
        self.save_best = False
        self.best_metric = None
        self.best_mode = "max"
        self.best_value = -float("inf")
        self.recovery = recovery
        self._losses = list(losses)
        self._rollback_to = rollback_to
        self.rollback_calls = 0
        self.scales: list[float] = []
        self.saves: list[int] = []

    def run_period(self, period, guard=None):
        return {"loss": self._losses[period]}, 5

    def evaluate_period(self, period):
        return None

    def save_snapshot(self, period):
        self.saves.append(period)

    def set_update_scale(self, scale):
        self.scales.append(scale)
        self.update_scale = scale

    def rollback_to_snapshot(self):
        if self._rollback_to is None:
            return False
        self.rollback_calls += 1
        self.periods_run = self._rollback_to
        # post-rollback the stream is finite again
        self._losses = [0.5] * len(self._losses)
        return True


def test_nan_policy_skips_then_rolls_back():
    from ddl_tpu.train.recovery import RecoveryPolicy

    pol = RecoveryPolicy(max_consecutive=2, grace_scale=0.1,
                         grace_periods=2)
    t = _PolicyStub(
        [1.0, float("nan"), float("nan"), 1.0, 1.0, 1.0, 1.0],
        recovery=pol, rollback_to=1,
    )
    t.train()
    # one skip (period 1), then the second consecutive hit rolled back
    assert pol.skipped == 1 and t.rollback_calls == 1
    assert t.periods_run == t.num_periods
    # grace entered at 0.1 and restored to 1.0 after two finite periods
    assert t.scales == [0.1, 1.0]


def test_preemption_during_nan_recovery_exits_promptly():
    """SIGTERM landing on a period whose loss was non-finite must still
    exit inside the grace window — without snapshotting the poisoned
    period — instead of running another period + eval first."""
    from ddl_tpu.train.recovery import RecoveryPolicy

    t = _PolicyStub(
        [1.0, float("nan"), 1.0, 1.0],
        recovery=RecoveryPolicy(max_consecutive=3), rollback_to=None,
    )
    orig = t.run_period

    def preempt_during(period, guard=None):
        if period == 1 and guard is not None:
            guard.request()
        return orig(period, guard)

    t.run_period = preempt_during
    with PreemptionGuard() as guard:
        t.train(guard=guard)
    assert t.preempted
    assert t.periods_run == 2  # the skip committed, then clean exit
    assert t.saves == []  # the poisoned period was NOT snapshotted


def test_unknown_nan_policy_rejected():
    """A typo'd policy name must error loudly, not silently halt-on-NaN
    (every family funnels through recovery.make_policy)."""
    import types

    from ddl_tpu.train.recovery import make_policy

    with pytest.raises(ValueError, match="unknown nan_policy"):
        make_policy(types.SimpleNamespace(nan_policy="rollback"))
    assert make_policy(types.SimpleNamespace(nan_policy="halt")) is None


def test_nan_policy_without_snapshot_halts():
    from ddl_tpu.train.recovery import RecoveryPolicy

    t = _PolicyStub(
        [float("nan")] * 3,
        recovery=RecoveryPolicy(max_consecutive=2), rollback_to=None,
    )
    with pytest.raises(RuntimeError, match="no snapshot to roll back"):
        t.train()


def test_nan_policy_bounded_rollbacks():
    from ddl_tpu.train.recovery import RecoveryPolicy

    pol = RecoveryPolicy(max_consecutive=1, max_rollbacks=2)
    t = _PolicyStub([float("nan")] * 6, recovery=pol, rollback_to=0)

    # sabotage the healing so every re-run NaNs again
    orig = t.rollback_to_snapshot

    def bad_rollback():
        ok = orig()
        t._losses = [float("nan")] * len(t._losses)
        return ok

    t.rollback_to_snapshot = bad_rollback
    with pytest.raises(RuntimeError, match="persisted through 2 rollback"):
        t.train()
    assert t.rollback_calls == 2


def test_traced_nan_step_consume_at_build():
    """`nan@grad` is consumed when a factory builds: the first build gets
    the step, the rebuild (the post-rollback grace recompile) gets None —
    so replayed steps run clean."""
    faultinject.activate("nan@grad:7")
    assert faultinject.traced_nan_step() == 7
    assert faultinject.traced_nan_step() is None
    # the host-side step hook never sees grad-site specs
    faultinject.activate("nan@grad:0")
    faultinject.check_step(0)
    assert faultinject.active().nan_pending is False


def test_nan_grad_injected_inside_compiled_step_recovers(tmp_path):
    """The ROADMAP item made real: a non-finite value injected into the
    GRADIENT inside the jitted step (traced lax.cond on the step
    counter).  The poisoned update corrupts the params, the next window's
    loss goes NaN, and nan_policy="recover" rolls back to the last good
    snapshot; the grace rebuild compiles the injection out, so the
    replay completes with finite weights."""
    import jax

    faultinject.activate("nan@grad:5")
    t = _tiny_lm(
        tmp_path, "lm-nan-grad", steps=8, save_every=4, log_dir=None,
        log_every=1, nan_policy="recover", nan_max_consecutive=1,
        nan_grace_scale=0.1, nan_grace_periods=1,
    )
    t.train()
    assert int(t.state.step) == 8
    assert t.recovery.rollbacks == 1
    assert t.update_scale == 1.0
    leaves = jax.tree.leaves(jax.device_get(t.state.params))
    assert all(np.isfinite(np.asarray(x)).all() for x in leaves)


# ---------------------------------------------------------------------------
# exact-resume data cursor
# ---------------------------------------------------------------------------


def test_cursor_recorded_in_snapshot_manifest(tmp_path):
    path = ckpt.save_snapshot(
        tmp_path, "job", 0, {"w": np.ones((4,))},
        cursor={"period": 2, "offset": 3},
    )
    assert ckpt.verify_snapshot(path)[0]
    assert ckpt.read_cursor(tmp_path, "job", 0) == {
        "period": 2, "offset": 3,
    }
    # cursor-less and legacy (manifest-less) snapshots: None, not a crash
    ckpt.save_snapshot(tmp_path, "job", 1, {"w": np.ones((4,))})
    assert ckpt.read_cursor(tmp_path, "job", 1) is None
    (ckpt.snapshot_path(tmp_path, "job", 1) / ckpt.MANIFEST_NAME).unlink()
    assert ckpt.read_cursor(tmp_path, "job", 1) is None


def test_loader_start_batch_skips_exactly_and_is_one_shot():
    from ddl_tpu.data.loader import DataLoader
    from ddl_tpu.data.sampler import ShardedEpochSampler

    class _Seq:
        labels = list(range(12))

        def __len__(self):
            return 12

        def __getitem__(self, i):
            return np.full((2, 2, 3), i, np.uint8), i

    loader = DataLoader(
        _Seq(), 3, sampler=ShardedEpochSampler(12, shuffle=False),
        num_workers=0,
    )
    loader.set_start_batch(2)
    labels = [list(lb) for _, lb in loader]
    assert labels == [[6, 7, 8], [9, 10, 11]]  # first 2 batches skipped
    labels = [list(lb) for _, lb in loader]
    assert len(labels) == 4  # one-shot: the next epoch is full again


def test_cnn_mid_epoch_preempt_resumes_at_exact_batch(tmp_path):
    """Acceptance-grade exact resume for the epoch family: preempt
    mid-epoch -> the snapshot manifest carries {period, offset} -> the
    resumed run re-enters THAT epoch at THAT batch and consumes exactly
    the remaining batches (no replay, no skip)."""
    from ddl_tpu.config import preset
    from ddl_tpu.train import Trainer

    os.environ["DDL_JOB_ID"] = "cursor-exact"
    try:
        def make_cfg():
            return preset("single", **{
                "data.image_size": "32", "data.global_batch_size": "8",
                "data.eval_batch_size": "8",
                "data.synthetic_num_train": "48",
                "data.synthetic_num_test": "16", "data.num_workers": "0",
                "model.growth_rate": "4", "model.block_config": "[2,2]",
                "model.num_init_features": "8", "model.bn_size": "2",
                "train.max_epochs": "3", "train.save_best_qwk": "false",
                "train.log_dir": str(tmp_path / "logs"),
                "train.checkpoint_dir": str(tmp_path / "ckpt"),
            })

        # 6 batches/epoch; preempt at global step 8 = epoch 1, 3 batches in
        faultinject.activate("preempt@step:8")
        t = Trainer(make_cfg())
        t.train()
        assert t.preempted
        assert ckpt.read_cursor(tmp_path / "ckpt", "cursor-exact", 1) == {
            "period": 1, "offset": 3,
        }

        faultinject.deactivate()
        t2 = Trainer(make_cfg())
        assert t2.epochs_run == 1 and t2._resume_offset == 3
        consumed = []
        orig = t2.run_period

        def spy(epoch, guard=None):
            m, steps = orig(epoch, guard)
            consumed.append((epoch, steps))
            return m, steps

        t2.run_period = spy
        t2.train()
        # epoch 1's remaining 3 batches, then a full epoch 2 — nothing
        # replayed, nothing skipped
        assert consumed == [(1, 3), (2, 6)]
    finally:
        os.environ.pop("DDL_JOB_ID", None)


def test_nan_rollback_lm_end_to_end(tmp_path):
    """The real LM family: injected NaN at step 5 -> policy rolls back to
    the step-4 snapshot, applies the reduced-LR grace (step-fn rebuild via
    scale_tx), and completes the run with a finite final loss."""
    faultinject.activate("nan@step:5")
    t = _tiny_lm(
        tmp_path, "lm-nan", steps=8, save_every=2, log_dir=None,
        log_every=2, nan_policy="recover", nan_max_consecutive=1,
        nan_grace_scale=0.1, nan_grace_periods=1,
    )
    t.train()
    assert int(t.state.step) == 8
    assert t.recovery.rollbacks == 1
    assert t.update_scale == 1.0  # grace over, dial restored


# ---------------------------------------------------------------------------
# watchdog escalation
# ---------------------------------------------------------------------------


def test_watchdog_exit_escalation(tmp_path):
    import time

    from ddl_tpu.obs.events import EventWriter, read_events
    from ddl_tpu.obs.watchdog import Watchdog

    exits = []
    writer = EventWriter(tmp_path, "wd", host=0)
    wd = Watchdog(writer, deadline_s=0.05, interval_s=0.02,
                  on_stall="exit", exit_fn=exits.append)
    wd.start()
    try:
        deadline = time.monotonic() + 2.0
        while not exits and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        wd.stop()
        writer.close()
    assert exits == [EXIT_PREEMPTED]
    kinds = [e["kind"] for e in read_events(tmp_path / "by_job_id" / "wd" /
                                            "events-h000.jsonl")]
    assert "stall" in kinds and "watchdog_exit" in kinds


def test_watchdog_unknown_action_warns_and_dumps(tmp_path):
    from ddl_tpu.obs.events import EventWriter
    from ddl_tpu.obs.watchdog import Watchdog

    writer = EventWriter(tmp_path, "wd2", host=0)
    with pytest.warns(UserWarning, match="unknown watchdog action"):
        wd = Watchdog(writer, deadline_s=1.0, on_stall="reboot")
    assert wd.on_stall == "dump"
    writer.close()


# ---------------------------------------------------------------------------
# obs diff against a stored baseline (the CI gate)
# ---------------------------------------------------------------------------


def _write_period_events(log_dir, job, steps_per_sec):
    from ddl_tpu.obs.events import EventWriter

    w = EventWriter(log_dir, job, host=0)
    for i, sps in enumerate(steps_per_sec):
        w.emit("period", step=i, period=i, steps=10, elapsed=10.0 / sps,
               steps_per_sec=sps, phases={"step": 8.0 / sps,
                                          "data_wait": 2.0 / sps})
    w.close()


def test_obs_diff_against_stored_baseline(tmp_path, capsys):
    from ddl_tpu.obs.report import main as obs_main

    logs = tmp_path / "logs"
    _write_period_events(logs, "fast", [2.0, 2.0, 2.0])
    _write_period_events(logs, "slow", [0.5, 0.5, 0.5])
    base = tmp_path / "base.json"

    obs_main(["baseline", "fast", "--log-dir", str(logs),
              "--out", str(base)])
    stored = json.loads(base.read_text())
    assert stored["job_id"] == "fast" and stored["summary"]["periods"] == 3

    # within the gate: same run diffed against its own baseline
    obs_main(["diff", "fast", "--log-dir", str(logs),
              "--baseline", str(base), "--fail-slowdown", "0.5"])
    out = capsys.readouterr().out
    assert "OK: within the" in out and "steps/s" in out

    # regression beyond the gate fails loudly
    with pytest.raises(SystemExit, match="FAIL"):
        obs_main(["diff", "slow", "--log-dir", str(logs),
                  "--baseline", str(base), "--fail-slowdown", "0.5"])


# ---------------------------------------------------------------------------
# snapshot garbage collection (keep-last-K valid)
# ---------------------------------------------------------------------------


def test_gc_snapshots_keeps_newest_k_valid(tmp_path):
    state = {"w": np.arange(8.0)}
    paths = [ckpt.save_snapshot(tmp_path, "job", e, state) for e in range(5)]
    removed = ckpt.gc_snapshots(tmp_path, "job", keep=2)
    assert ckpt.snapshot_epochs(tmp_path, "job") == [3, 4]
    assert {p for p, _ in removed} == {paths[0], paths[1], paths[2]}
    # keep=0 disables GC entirely
    ckpt.save_snapshot(tmp_path, "job2", 0, state)
    assert ckpt.gc_snapshots(tmp_path, "job2", keep=0) == []
    assert ckpt.snapshot_epochs(tmp_path, "job2") == [0]


def test_gc_corrupt_snapshots_do_not_count_toward_keep(tmp_path):
    """Fault-injection acceptance: a snapshot corrupted at commit time
    (torn NAS write) must not occupy a keep slot — K means K
    *restorable* snapshots."""
    state = {"w": np.arange(16.0)}
    faultinject.activate("corrupt_ckpt@save:3")  # poison the 3rd save
    for e in range(4):
        ckpt.save_snapshot(tmp_path, "job", e, state)
    faultinject.deactivate()
    assert not ckpt.verify_snapshot(
        ckpt.snapshot_path(tmp_path, "job", 2)
    )[0]

    removed = ckpt.gc_snapshots(tmp_path, "job", keep=2)
    # epochs 3 and 1 are the two newest VALID; the corrupt 2 and the old
    # 0 are both removed
    assert ckpt.snapshot_epochs(tmp_path, "job") == [1, 3]
    reasons = {p.name: r for p, r in removed}
    assert "corrupt" in reasons["epoch_2"]
    assert "older" in reasons["epoch_0"]
    # what's left restores
    assert ckpt.latest_valid_epoch(tmp_path, "job") == 3


def test_trainer_gc_prunes_after_each_save(tmp_path):
    """End to end through the shared loop: keep_snapshots=2 leaves only
    the two newest snapshots after a run that saved three times."""
    t = _tiny_lm(tmp_path, "lm-gc", steps=6, save_every=2, log_dir=None,
                 keep_snapshots=2)
    t.train()
    assert ckpt.snapshot_epochs(tmp_path / "ckpt", "lm-gc") == [4, 6]
    # and the run still resumes from what was kept
    resumed = _tiny_lm(tmp_path, "lm-gc", steps=8, save_every=2,
                       log_dir=None, keep_snapshots=2)
    assert resumed._start_step == 6


# ---------------------------------------------------------------------------
# supervisor obs events
# ---------------------------------------------------------------------------


def test_supervisor_emits_lifecycle_obs_events(tmp_path):
    from ddl_tpu.obs import EventWriter, read_events

    w = EventWriter(tmp_path, "supjob", host=0)
    codes = iter([EXIT_PREEMPTED, 7, 0])
    sup = Supervisor(
        lambda i: next(codes), max_restarts=3,
        sleep=lambda s: None, log=lambda m: None, events=w,
    )
    assert sup.run() == 0
    w.close()
    events = read_events(w.path)
    kinds = [e["kind"] for e in events]
    assert kinds[0] == "supervisor_start"
    relaunches = [e for e in events if e["kind"] == "supervisor_relaunch"]
    assert [e["reason"] for e in relaunches] == ["preempt", "crash"]
    assert relaunches[1]["rc"] == 7
    done = events[-1]
    assert done["kind"] == "supervisor_done"
    assert done["rc"] == 0 and done["gave_up"] is False


def test_supervisor_emits_give_up_event(tmp_path):
    from ddl_tpu.obs import EventWriter, read_events

    w = EventWriter(tmp_path, "supjob2", host=0)
    sup = Supervisor(
        lambda i: 9, max_restarts=1,
        sleep=lambda s: None, log=lambda m: None, events=w,
    )
    assert sup.run() == 9
    w.close()
    done = read_events(w.path)[-1]
    assert done["kind"] == "supervisor_done"
    assert done["rc"] == 9 and done["gave_up"] is True


def test_gc_protects_best_metric_snapshot(tmp_path):
    """A snapshot saved because the eval metric improved must survive GC
    even when cadence saves push it out of the keep window."""
    state = {"w": np.arange(8.0)}
    for e in range(5):
        ckpt.save_snapshot(tmp_path, "job", e, state)
    removed = ckpt.gc_snapshots(tmp_path, "job", keep=2, protect=(1,))
    assert ckpt.snapshot_epochs(tmp_path, "job") == [1, 3, 4]
    assert {p.name for p, _ in removed} == {"epoch_0", "epoch_2"}


def test_trainer_gc_never_reaps_best_snapshot(tmp_path):
    """Through the shared loop: the best-val-perplexity snapshot is
    pinned (loop sets best_snapshot_epoch on improvement saves)."""
    t = _tiny_lm(tmp_path, "lm-best", steps=6, save_every=2, log_dir=None,
                 keep_snapshots=1, eval_every=2)
    # fake the held-out eval (the synthetic corpus has no eval split):
    # the first boundary registers as the all-time best, every later one
    # is worse, so the step-2 snapshot is the best model
    vals = iter([1.0, 9.0, 9.0, 9.0])

    def fake_eval(period):
        if t._period_bounds(period)[1] % 2:
            return None
        v = next(vals)
        return {"val_loss": v, "val_ppl": v}

    t.evaluate_period = fake_eval
    assert t.save_best  # eval_every + checkpoint_dir arm the gate
    t.train()
    kept = ckpt.snapshot_epochs(tmp_path / "ckpt", "lm-best")
    assert 2 in kept, f"best snapshot reaped; kept {kept}"
    assert kept[-1] == 6  # the cadence window still holds the newest
