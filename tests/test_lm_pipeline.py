"""Pipeline parallelism for the transformer LM (parallel/lm_pipeline.py).

Parity discipline matches the CNN pipeline tests: every pipelined
configuration must reproduce the single-device, non-pipelined run of the
same model/seed — same loss, same post-Adam parameters — on the simulated
8-device CPU mesh.  (The reference has no transformer at all; its pipeline
is validated only statistically, SURVEY.md §4.)
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl_tpu.models.transformer import LMConfig
from ddl_tpu.parallel.lm_pipeline import make_lm_pipeline_step_fns, split_lm_params
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.lm_steps import make_lm_step_fns

B, T = 8, 8


def _cfg(**kw):
    base = dict(
        vocab_size=32,
        d_model=16,
        n_layers=4,
        n_heads=2,
        head_dim=8,
        d_ff=32,
        compute_dtype="float32",
        attn_impl="dense",
        remat=False,
    )
    base.update(kw)
    return LMConfig(**base)


def _batch(seed=0):
    toks = np.random.default_rng(seed).integers(0, 32, (B, T + 1))
    return jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])


def _single_step(cfg, tx, rng, inp, tgt):
    """One non-pipelined single-device train step; returns
    (init params host copy, post-step params, loss)."""
    fns = make_lm_step_fns(cfg, LMMeshSpec(data=1), tx, rng, B, T,
                           devices=jax.devices()[:1])
    s0 = fns.init_state()
    p0 = jax.device_get(s0.params)
    s1, m = fns.train(s0, inp, tgt)
    return p0, jax.device_get(s1.params), float(m["loss"])


def _maxerr(a, b):
    return jax.tree.reduce(
        max,
        jax.tree.map(
            lambda x, y: float(np.abs(np.asarray(x) - np.asarray(y)).max()), a, b
        ),
    )


@pytest.mark.parametrize(
    "spec,microbatches",
    [
        (LMMeshSpec(data=2, pipe=2), 2),
        (LMMeshSpec(data=1, pipe=4), 4),
        (LMMeshSpec(data=2, pipe=2, model=2), 4),
    ],
    ids=["dp2_pp2", "pp4", "dp2_pp2_tp2"],
)
def test_lm_pipeline_matches_single_dense(spec, microbatches):
    cfg = _cfg()
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    inp, tgt = _batch()
    p0_ref, p1_ref, loss_ref = _single_step(cfg, tx, rng, inp, tgt)

    fns = make_lm_step_fns(
        cfg, spec, tx, rng, B, T,
        devices=jax.devices()[: spec.num_devices],
        num_microbatches=microbatches,
    )
    s0 = fns.init_state()
    assert _maxerr(split_lm_params(p0_ref, spec.pipe), jax.device_get(s0.params)) == 0.0
    s1, m = fns.train(s0, inp, tgt)
    assert abs(float(m["loss"]) - loss_ref) < 1e-5
    assert (
        _maxerr(split_lm_params(p1_ref, spec.pipe), jax.device_get(s1.params)) < 1e-3
    )
    em = fns.evaluate(s1, inp, tgt)
    assert np.isfinite(float(em["loss"])) and 0.0 <= float(em["accuracy"]) <= 1.0


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_lm_pipeline_with_sequence_parallel_attention(impl):
    """PP x SP x TP: the ring/Ulysses cores nest as inner shard_maps
    (manual over seq, inheriting the context mesh) inside the
    manual-over-pipe pipeline region.  Must match the single-device dense
    run — both cores are numerically full attention."""
    cfg = _cfg(n_heads=4, n_layers=4)
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    inp, tgt = _batch()
    _, p1_ref, loss_ref = _single_step(cfg, tx, rng, inp, tgt)

    spec = LMMeshSpec(data=1, pipe=2, seq=2, model=2)
    fns = make_lm_step_fns(
        dataclasses.replace(cfg, attn_impl=impl), spec, tx, rng, B, T,
        devices=jax.devices()[:8], num_microbatches=2,
    )
    s1, m = fns.train(fns.init_state(), inp, tgt)
    assert abs(float(m["loss"]) - loss_ref) < 1e-5
    assert _maxerr(split_lm_params(p1_ref, 2), jax.device_get(s1.params)) < 1e-3


def test_lm_pipeline_moe_composition():
    """PP x TP x EP x FSDP in one program.  MoE parity is approximate: the
    load-balance aux is a product of batch-means, so per-microbatch
    computation differs from the full-batch value at O(variance/M) — the
    same class of semantic shift as per-microbatch BatchNorm in the CNN
    pipeline (torch-GPipe semantics, parallel/pipeline.py docstring)."""
    cfg = _cfg(num_experts=2, expert_top_k=1, remat=True, fsdp=True)
    tx = optax.adam(1e-2)
    rng = jax.random.key(1)
    inp, tgt = _batch(1)
    _, p1_ref, loss_ref = _single_step(cfg, tx, rng, inp, tgt)

    spec = LMMeshSpec(data=1, pipe=2, model=2, expert=2)
    fns = make_lm_step_fns(
        cfg, spec, tx, rng, B, T, devices=jax.devices()[:8], num_microbatches=2
    )
    s1, m = fns.train(fns.init_state(), inp, tgt)
    assert int(jax.device_get(s1.step)) == 1
    assert abs(float(m["loss"]) - loss_ref) < 5e-3
    assert _maxerr(split_lm_params(p1_ref, 2), jax.device_get(s1.params)) < 5e-2


@pytest.mark.parametrize(
    "spec,microbatches,kw",
    [
        (LMMeshSpec(data=2, pipe=2), 4, {}),
        (
            LMMeshSpec(pipe=2, seq=2, model=2),
            2,
            dict(attn_impl="ring", n_heads=4),
        ),
        (
            LMMeshSpec(pipe=2, model=2, expert=2),
            2,
            dict(num_experts=2, expert_top_k=1, remat=True, fsdp=True),
        ),
    ],
    ids=["dp2_pp2", "pp2_sp2_tp2_ring", "pp2_tp2_ep2_moe"],
)
def test_lm_pipeline_1f1b_matches_gpipe(spec, microbatches, kw):
    """The 1F1B schedule's hand-written interleaved backward (per-tick
    jax.vjp, cotangents on the reverse hop, loss fused into the last
    stage's tick) computes the same gradients as GPipe-by-autodiff — same
    math, same microbatch order — across the nested-SP / TP / EP / FSDP
    compositions."""
    cfg = _cfg(**kw)
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    inp, tgt = _batch()
    states, losses = {}, {}
    for sched in ("gpipe", "1f1b"):
        fns = make_lm_step_fns(
            cfg, spec, tx, rng, B, T,
            devices=jax.devices()[: spec.num_devices],
            num_microbatches=microbatches,
            pipeline_schedule=sched,
        )
        s1, m = fns.train(fns.init_state(), inp, tgt)
        states[sched], losses[sched] = jax.device_get(s1.params), float(m["loss"])
    assert abs(losses["gpipe"] - losses["1f1b"]) < 1e-5
    assert _maxerr(states["gpipe"], states["1f1b"]) < 1e-5


@pytest.mark.parametrize(
    "spec,microbatches,kw",
    [
        (LMMeshSpec(data=2, pipe=2), 4, {}),
        (
            LMMeshSpec(pipe=2, seq=2),
            4,
            dict(attn_impl="ring", n_heads=4, fsdp=True, dropout_rate=0.1),
        ),
    ],
    ids=["dp2_pp2_v2", "pp2_sp2_ring_fsdp_dropout_v2"],
)
def test_lm_pipeline_interleaved_1f1b_matches_interleaved_gpipe(
    spec, microbatches, kw
):
    """The combined interleaved-1F1B (Megatron's schedule: V virtual chunks
    per device AND hand-written one-forward-one-backward ticks) computes
    the same gradients as the interleaved GPipe-by-autodiff — including
    with ring-attention SP nested inside the stages, FSDP sharding, and
    dropout (whose masks are keyed by (microbatch, global stage) so both
    schedules draw identical masks)."""
    cfg = _cfg(**kw)
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    inp, tgt = _batch()
    states, losses = {}, {}
    for sched in ("gpipe", "1f1b"):
        fns = make_lm_step_fns(
            cfg, spec, tx, rng, B, T,
            devices=jax.devices()[: spec.num_devices],
            num_microbatches=microbatches,
            pipeline_schedule=sched,
            virtual_stages=2,
        )
        s1, m = fns.train(fns.init_state(), inp, tgt)
        states[sched], losses[sched] = jax.device_get(s1.params), float(m["loss"])
    assert abs(losses["gpipe"] - losses["1f1b"]) < 1e-5
    assert _maxerr(states["gpipe"], states["1f1b"]) < 5e-5


@pytest.mark.parametrize(
    "spec,microbatches,kw",
    [
        (LMMeshSpec(data=2, pipe=2), 4, {}),
        (LMMeshSpec(data=2, pipe=2), 4, dict(dropout_rate=0.1)),
        (LMMeshSpec(data=1, pipe=2), 4, {}),
        (LMMeshSpec(data=1, pipe=2), 4, dict(dropout_rate=0.1)),
        (LMMeshSpec(data=1, pipe=4), 8, {}),
    ],
    ids=["dp2_pp2", "dp2_pp2_dropout", "pp2", "pp2_dropout", "pp4_m8"],
)
def test_lm_pipeline_zb_matches_gpipe_and_1f1b(spec, microbatches, kw):
    """The zero-bubble schedule's split backward (B-pass vjp w.r.t. the
    stage input, W-pass vjp w.r.t. the weights, applied to the same
    output cotangent) is exactly the joint vjp's two components, so a
    3-step fused-Adam trajectory must track BOTH reference schedules to
    1e-6 — loss and post-update parameters, dropout on or off (the W
    pass refolds the mask key from the queued microbatch index)."""
    from ddl_tpu.train.fused_optim import fused_adam

    cfg = _cfg(**kw)
    rng = jax.random.key(0)
    inp, tgt = _batch()
    traj = {}
    for sched in ("gpipe", "1f1b", "zb"):
        fns = make_lm_step_fns(
            cfg, spec, fused_adam(1e-2), rng, B, T,
            devices=jax.devices()[: spec.num_devices],
            num_microbatches=microbatches,
            pipeline_schedule=sched,
        )
        st = fns.init_state()
        losses = []
        for _ in range(3):
            st, m = fns.train(st, inp, tgt)
            losses.append(float(m["loss"]))
        traj[sched] = (losses, jax.device_get(st.params))
    for ref in ("gpipe", "1f1b"):
        dloss = max(
            abs(a - b) for a, b in zip(traj["zb"][0], traj[ref][0])
        )
        assert dloss <= 1e-6, (ref, dloss)
        derr = _maxerr(traj["zb"][1], traj[ref][1])
        assert derr <= 1e-6, (ref, derr)


def test_lm_pipeline_zb_w_queue_drains_all_microbatches():
    """M well past the deferral capacity (P=2: cap_s <= 1, M=6) forces
    the queue through every regime in one step — same-tick drains on
    stage 0, steady-state one-in-one-out on stage 1, and the cooldown
    tail — and a single dropped or double-counted W item would shift
    the block gradients, so gradient parity with GPipe proves every
    microbatch's deferred weight gradient landed exactly once.  (The
    drain ORDER is pinned by the schedule model:
    test_schedule_model.py asserts W units drain in microbatch
    order.)"""
    cfg = _cfg()
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    toks = np.random.default_rng(2).integers(0, 32, (12, T + 1))
    inp, tgt = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    out = {}
    for sched in ("gpipe", "zb"):
        fns = make_lm_step_fns(
            cfg, LMMeshSpec(data=1, pipe=2), tx, rng, 12, T,
            devices=jax.devices()[:2], num_microbatches=6,
            pipeline_schedule=sched,
        )
        s1, m = fns.train(fns.init_state(), inp, tgt)
        out[sched] = (float(m["loss"]), jax.device_get(s1.params))
    assert abs(out["zb"][0] - out["gpipe"][0]) <= 1e-6
    assert _maxerr(out["zb"][1], out["gpipe"][1]) <= 1e-6


def test_lm_pipeline_1f1b_matches_single():
    """1F1B end-to-end against the non-pipelined single-device run (not
    just against GPipe): two steps, loss and post-Adam parameter parity."""
    cfg = _cfg()
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    inp, tgt = _batch()
    p0_ref, p1_ref, loss_ref = _single_step(cfg, tx, rng, inp, tgt)

    fns = make_lm_step_fns(
        cfg, LMMeshSpec(data=1, pipe=4), tx, rng, B, T,
        devices=jax.devices()[:4], num_microbatches=4,
        pipeline_schedule="1f1b",
    )
    s0 = fns.init_state()
    assert _maxerr(split_lm_params(p0_ref, 4), jax.device_get(s0.params)) == 0.0
    s1, m = fns.train(s0, inp, tgt)
    assert abs(float(m["loss"]) - loss_ref) < 1e-5
    assert _maxerr(split_lm_params(p1_ref, 4), jax.device_get(s1.params)) < 1e-3


@pytest.mark.parametrize(
    "spec,virtual,microbatches,kw",
    [
        (LMMeshSpec(data=2, pipe=2), 2, 4, {}),
        (LMMeshSpec(pipe=2, model=2), 4, 2, {}),
        (
            LMMeshSpec(pipe=2, seq=2, model=2),
            2,
            2,
            dict(attn_impl="ring", n_heads=4),
        ),
    ],
    ids=["dp2_pp2_v2", "pp2_tp2_v4", "pp2_sp2_tp2_ring_v2"],
)
def test_lm_pipeline_interleaved_matches_single(spec, virtual, microbatches, kw):
    """The interleaved (virtual-stage) schedule: device s holds `virtual`
    non-contiguous layer chunks and each microbatch laps the ring V times,
    shrinking the fill/drain bubble by V.  Must reproduce the single-device
    run exactly, including with nested ring sequence parallelism."""
    cfg = _cfg(n_layers=8, **kw)
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    inp, tgt = _batch()
    _, p1_ref, loss_ref = _single_step(cfg, tx, rng, inp, tgt)

    fns = make_lm_step_fns(
        cfg, spec, tx, rng, B, T,
        devices=jax.devices()[: spec.num_devices],
        num_microbatches=microbatches,
        virtual_stages=virtual,
    )
    s1, m = fns.train(fns.init_state(), inp, tgt)
    assert abs(float(m["loss"]) - loss_ref) < 1e-5
    from ddl_tpu.parallel.lm_pipeline import merge_lm_params

    merged = merge_lm_params(jax.device_get(s1.params))
    assert _maxerr(merged, p1_ref) < 1e-3
    em = fns.evaluate(s1, inp, tgt)
    assert np.isfinite(float(em["loss"]))


def test_lm_pipeline_interleaved_checkpoint_interop(tmp_path):
    """The interleaved layout is self-describing (blocks nest under an
    'interleaved' marker), so a snapshot saved by a (pipe, virtual) run
    resumes under any other layout with the virtual count discovered from
    the snapshot — never from a flag."""
    from ddl_tpu.checkpoint import load_snapshot, save_snapshot, snapshot_metadata
    from ddl_tpu.parallel.lm_pipeline import (
        abstract_lm_state,
        convert_lm_state,
        saved_pipe_stages,
        saved_virtual_stages,
    )

    cfg = _cfg(n_layers=8)
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    batches = [_batch(seed) for seed in range(4)]

    def run(fns, state, bs):
        loss = None
        for inp, tgt in bs:
            state, m = fns.train(state, inp, tgt)
            loss = float(m["loss"])
        return state, loss

    iv_fns = make_lm_step_fns(
        cfg, LMMeshSpec(pipe=2), tx, rng, B, T,
        devices=jax.devices()[:2], num_microbatches=2, virtual_stages=2,
    )
    _, ref_loss = run(iv_fns, iv_fns.init_state(), batches)

    state, _ = run(iv_fns, iv_fns.init_state(), batches[:2])
    save_snapshot(tmp_path, "iv-job", 2, state)
    md = snapshot_metadata(tmp_path, "iv-job", 2)
    assert saved_pipe_stages(md["state"]["params"]) == 2
    assert saved_virtual_stages(md["state"]["params"]) == 2

    # resume as a plain DP run (full layout): merge auto-detects V
    full_fns = make_lm_step_fns(cfg, LMMeshSpec(data=2), tx, rng, B, T,
                                devices=jax.devices()[:2])
    restored, _ = load_snapshot(
        tmp_path, "iv-job", 2,
        abstract_lm_state(cfg, tx, 2, mesh=full_fns.mesh, virtual=2),
    )
    full_state = convert_lm_state(restored, like=full_fns.init_state())
    _, loss = run(full_fns, full_state, batches[2:])
    assert abs(loss - ref_loss) < 1e-4

    # and back: full -> interleaved via convert(n_stages, virtual); a fresh
    # restore because train donated the first one's leaves
    restored2, _ = load_snapshot(
        tmp_path, "iv-job", 2,
        abstract_lm_state(cfg, tx, 2, mesh=full_fns.mesh, virtual=2),
    )
    iv_state = convert_lm_state(
        convert_lm_state(restored2),
        n_stages=2, virtual=2, like=iv_fns.init_state(),
    )
    _, loss_iv = run(iv_fns, iv_state, batches[2:])
    assert abs(loss_iv - ref_loss) < 1e-4


def test_lm_pipeline_interleaved_validation():
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    with pytest.raises(ValueError, match="virtual"):
        make_lm_pipeline_step_fns(
            _cfg(n_layers=4), LMMeshSpec(pipe=2), tx, rng, B, T, 2,
            devices=jax.devices()[:2], virtual_stages=3,  # 4 % (2*3) != 0
        )
    with pytest.raises(ValueError, match="groups of pipe"):
        make_lm_pipeline_step_fns(
            _cfg(n_layers=8), LMMeshSpec(pipe=2), tx, rng, B, T, 1,
            devices=jax.devices()[:2], virtual_stages=2,  # M=1 % pipe=2
        )
    # virtual_stages x 1f1b is no longer an error: the combined
    # interleaved-1F1B schedule (see
    # test_lm_pipeline_interleaved_1f1b_matches_interleaved_gpipe)


@pytest.mark.parametrize("sched", ["gpipe", "1f1b"])
def test_lm_pipeline_flash_attention(sched):
    """The Pallas flash kernel composes with pipeline parallelism (both
    schedules) through a nested fully-manual (data, seq, model) region —
    here flash-under-Ulysses on a pipe x seq x model mesh, against the
    single-device dense run.  (Interpret mode on the CPU mesh; the real
    Mosaic lowering is validated on-chip, PERF.md.)"""
    cfg = _cfg(n_heads=4)
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    # T=16 so the kernel's block clamping exercises a non-trivial shape
    toks = np.random.default_rng(0).integers(0, 32, (B, 17))
    inp, tgt = jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])
    fns_ref = make_lm_step_fns(cfg, LMMeshSpec(data=1), tx, rng, B, 16,
                               devices=jax.devices()[:1])
    s_ref, m_ref = fns_ref.train(fns_ref.init_state(), inp, tgt)

    flash_cfg = dataclasses.replace(cfg, flash=True, attn_impl="ulysses")
    fns = make_lm_step_fns(
        flash_cfg, LMMeshSpec(pipe=2, seq=2, model=2), tx, rng, B, 16,
        devices=jax.devices()[:8], num_microbatches=2,
        pipeline_schedule=sched,
    )
    s1, m = fns.train(fns.init_state(), inp, tgt)
    assert abs(float(m["loss"]) - float(m_ref["loss"])) < 1e-4
    assert _maxerr(split_lm_params(jax.device_get(s_ref.params), 2),
                   jax.device_get(s1.params)) < 1e-3

    # flash inside ring inside the pipeline: same single-device reference
    ring_cfg = dataclasses.replace(cfg, flash=True, attn_impl="ring")
    fns_r = make_lm_step_fns(
        ring_cfg, LMMeshSpec(pipe=2, seq=2, model=2), tx, rng, B, 16,
        devices=jax.devices()[:8], num_microbatches=2,
        pipeline_schedule=sched,
    )
    s_r, m_r = fns_r.train(fns_r.init_state(), inp, tgt)
    assert abs(float(m_r["loss"]) - float(m_ref["loss"])) < 1e-4
    assert _maxerr(split_lm_params(jax.device_get(s_ref.params), 2),
                   jax.device_get(s_r.params)) < 1e-3

    # windowed flash-in-ring inside the pipeline (round 3): the per-hop
    # banded kernel + O(window) hop truncation under the nested manual
    # region, against the single-device dense-windowed run
    win_ref_cfg = dataclasses.replace(cfg, attn_window=6)
    fns_wref = make_lm_step_fns(win_ref_cfg, LMMeshSpec(data=1), tx, rng,
                                B, 16, devices=jax.devices()[:1])
    _, m_wref = fns_wref.train(fns_wref.init_state(), inp, tgt)
    win_cfg = dataclasses.replace(
        cfg, flash=True, attn_impl="ring", attn_window=6
    )
    fns_w = make_lm_step_fns(
        win_cfg, LMMeshSpec(pipe=2, seq=2, model=2), tx, rng, B, 16,
        devices=jax.devices()[:8], num_microbatches=2,
        pipeline_schedule=sched,
    )
    _, m_w = fns_w.train(fns_w.init_state(), inp, tgt)
    assert abs(float(m_w["loss"]) - float(m_wref["loss"])) < 1e-4


def test_lm_pipeline_checkpoint_interop(tmp_path):
    """The parallelism topology is a resume-time choice: a snapshot from a
    plain DP run (full layout) resumes as a pipelined run and vice versa —
    convert_lm_state restructures params AND Adam mu/nu; Orbax handles the
    mesh change.  Loss after resume must match the uninterrupted run."""
    from ddl_tpu.checkpoint import load_snapshot, save_snapshot, snapshot_metadata
    from ddl_tpu.parallel.lm_pipeline import (
        abstract_lm_state,
        convert_lm_state,
        saved_pipe_stages,
    )

    cfg = _cfg()
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    batches = [_batch(seed) for seed in range(5)]

    def run(fns, state, bs):
        loss = None
        for inp, tgt in bs:
            state, m = fns.train(state, inp, tgt)
            loss = float(m["loss"])
        return state, loss

    full_fns = make_lm_step_fns(cfg, LMMeshSpec(data=2), tx, rng, B, T,
                                devices=jax.devices()[:2])
    _, ref_loss = run(full_fns, full_fns.init_state(), batches)

    # full -> pipeline: saved on a 2-device mesh, restored onto a 4-device
    # one.  The restore target is an abstract skeleton built from config
    # alone — no init, no step functions, no saved-run mesh; attaching the
    # *restoring* mesh keeps Orbax off the save-time sharding file (which
    # only resolves on the exact saving topology).
    state, _ = run(full_fns, full_fns.init_state(), batches[:3])
    save_snapshot(tmp_path, "full-job", 3, state)
    # the snapshot records its own layout — discoverable from metadata alone
    md = snapshot_metadata(tmp_path, "full-job", 3)
    assert saved_pipe_stages(md["state"]["params"]) == 1
    pp_fns = make_lm_step_fns(cfg, LMMeshSpec(data=2, pipe=2), tx, rng, B, T,
                              devices=jax.devices()[:4], num_microbatches=2)
    restored, _ = load_snapshot(
        tmp_path, "full-job", 3, abstract_lm_state(cfg, tx, mesh=pp_fns.mesh)
    )
    pp_state = convert_lm_state(restored, n_stages=2, like=pp_fns.init_state())
    pp_state, pp_loss = run(pp_fns, pp_state, batches[3:])
    assert abs(pp_loss - ref_loss) < 1e-4
    assert int(jax.device_get(pp_state.step)) == 5

    # pipeline -> full: saved on 4 devices, restored onto 2
    save_snapshot(tmp_path, "pp-job", 5, pp_state)
    md = snapshot_metadata(tmp_path, "pp-job", 5)
    assert saved_pipe_stages(md["state"]["params"]) == 2
    restored_pp, _ = load_snapshot(
        tmp_path, "pp-job", 5,
        abstract_lm_state(cfg, tx, n_stages=2, mesh=full_fns.mesh),
    )
    back = convert_lm_state(restored_pp, like=full_fns.init_state())
    state2, loss2 = run(full_fns, back, [batches[-1]])
    assert np.isfinite(loss2)
    assert int(jax.device_get(state2.step)) == 6


def test_convert_lm_state_dict_opt_state():
    """convert_lm_state must reach param trees nested inside dict-valued
    optimizer states (e.g. optax.multi_transform's inner_states)."""
    from ddl_tpu.parallel.lm_pipeline import (
        _is_full_tree,
        _is_pipeline_tree,
        convert_lm_state,
    )

    def layouts(x, found):
        """Collect the layout of every param-shaped dict in an opt state."""
        if _is_pipeline_tree(x):
            found.append("pipe")
        elif _is_full_tree(x):
            found.append("full")
        elif isinstance(x, (tuple, list)):
            for f in x:
                layouts(f, found)
        elif isinstance(x, dict):
            for v in x.values():
                layouts(v, found)
        return found

    cfg = _cfg()
    tx = optax.multi_transform(
        {"all": optax.adam(1e-2)},
        lambda params: jax.tree.map(lambda _: "all", params),
    )
    fns = make_lm_step_fns(cfg, LMMeshSpec(data=1), tx, jax.random.key(0), B, T,
                           devices=jax.devices()[:1])
    state = fns.init_state()
    assert "full" in layouts(state.opt_state, [])  # adam mu/nu behind a dict

    pp = convert_lm_state(state, n_stages=2)
    found = layouts(pp.opt_state, [])
    assert found and all(l == "pipe" for l in found)

    back = convert_lm_state(pp)
    assert jax.tree.structure(back.params) == jax.tree.structure(state.params)
    assert jax.tree.structure(back.opt_state) == jax.tree.structure(state.opt_state)


def test_split_lm_params_stage_major():
    """Stage p must own layers [p*Lps, (p+1)*Lps) in order."""
    full = {
        "embed": {"embedding": jnp.zeros((4, 2))},
        "norm_f": {"scale": jnp.ones((2,))},
        "lm_head": {"kernel": jnp.zeros((2, 4))},
    }
    for i in range(4):
        full[f"block{i}"] = {"w": jnp.full((3,), float(i))}
    out = split_lm_params(full, 2)
    assert out["blocks"]["w"].shape == (2, 2, 3)
    np.testing.assert_array_equal(
        np.asarray(out["blocks"]["w"][:, :, 0]), [[0.0, 1.0], [2.0, 3.0]]
    )
    assert set(out) == {"embed", "blocks", "head"}


def test_lm_pipeline_validation_errors():
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    # flash + ring is supported (flash-in-ring,
    # test_lm_pipeline_flash_attention) — no longer a validation error
    with pytest.raises(ValueError, match="seq=1"):
        make_lm_pipeline_step_fns(
            _cfg(flash=True), LMMeshSpec(pipe=2, seq=2), tx, rng, B, T, 2,
            devices=jax.devices()[:4],
        )
    # flash kernel is built causal — a bidirectional config must be
    # rejected here exactly as on the non-pipelined path (lm_steps)
    with pytest.raises(ValueError, match="causal"):
        make_lm_pipeline_step_fns(
            _cfg(flash=True, causal=False), LMMeshSpec(pipe=2), tx,
            rng, B, T, 2, devices=jax.devices()[:2],
        )
    with pytest.raises(ValueError, match="n_layers"):
        make_lm_pipeline_step_fns(
            _cfg(n_layers=3), LMMeshSpec(pipe=2), tx, rng, B, T, 2,
            devices=jax.devices()[:2],
        )
    with pytest.raises(ValueError, match="microbatches"):
        make_lm_pipeline_step_fns(
            _cfg(), LMMeshSpec(pipe=2), tx, rng, B, T, 3,
            devices=jax.devices()[:2],
        )
    with pytest.raises(ValueError, match="schedule"):
        make_lm_pipeline_step_fns(
            _cfg(), LMMeshSpec(pipe=2), tx, rng, B, T, 2,
            devices=jax.devices()[:2], schedule="zb1",
        )
    # the zero-bubble B/W-split loop is single-chunk: zb x virtual
    # stages is rejected, not silently degraded
    with pytest.raises(ValueError, match="single-chunk|1f1b"):
        make_lm_pipeline_step_fns(
            _cfg(n_layers=8), LMMeshSpec(pipe=2), tx, rng, B, T, 2,
            devices=jax.devices()[:2], schedule="zb", virtual_stages=2,
        )
    with pytest.raises(ValueError, match="ce_vocab_chunk"):
        make_lm_pipeline_step_fns(
            _cfg(ce_vocab_chunk=8), LMMeshSpec(pipe=2), tx, rng, B, T, 2,
            devices=jax.devices()[:2], schedule="zb",
        )
    with pytest.raises(ValueError, match="pipeline_schedule"):
        make_lm_step_fns(
            _cfg(), LMMeshSpec(data=1), tx, rng, B, T,
            devices=jax.devices()[:1], pipeline_schedule="1f1b",
        )
