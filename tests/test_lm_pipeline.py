"""Pipeline parallelism for the transformer LM (parallel/lm_pipeline.py).

Parity discipline matches the CNN pipeline tests: every pipelined
configuration must reproduce the single-device, non-pipelined run of the
same model/seed — same loss, same post-Adam parameters — on the simulated
8-device CPU mesh.  (The reference has no transformer at all; its pipeline
is validated only statistically, SURVEY.md §4.)
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from ddl_tpu.models.transformer import LMConfig
from ddl_tpu.parallel.lm_pipeline import make_lm_pipeline_step_fns, split_lm_params
from ddl_tpu.parallel.sharding import LMMeshSpec
from ddl_tpu.train.lm_steps import make_lm_step_fns

B, T = 8, 8


def _cfg(**kw):
    base = dict(
        vocab_size=32,
        d_model=16,
        n_layers=4,
        n_heads=2,
        head_dim=8,
        d_ff=32,
        compute_dtype="float32",
        attn_impl="dense",
        remat=False,
    )
    base.update(kw)
    return LMConfig(**base)


def _batch(seed=0):
    toks = np.random.default_rng(seed).integers(0, 32, (B, T + 1))
    return jnp.asarray(toks[:, :-1]), jnp.asarray(toks[:, 1:])


def _single_step(cfg, tx, rng, inp, tgt):
    """One non-pipelined single-device train step; returns
    (init params host copy, post-step params, loss)."""
    fns = make_lm_step_fns(cfg, LMMeshSpec(data=1), tx, rng, B, T,
                           devices=jax.devices()[:1])
    s0 = fns.init_state()
    p0 = jax.device_get(s0.params)
    s1, m = fns.train(s0, inp, tgt)
    return p0, jax.device_get(s1.params), float(m["loss"])


def _maxerr(a, b):
    return jax.tree.reduce(
        max,
        jax.tree.map(
            lambda x, y: float(np.abs(np.asarray(x) - np.asarray(y)).max()), a, b
        ),
    )


@pytest.mark.parametrize(
    "spec,microbatches",
    [
        (LMMeshSpec(data=2, pipe=2), 2),
        (LMMeshSpec(data=1, pipe=4), 4),
        (LMMeshSpec(data=2, pipe=2, model=2), 4),
    ],
    ids=["dp2_pp2", "pp4", "dp2_pp2_tp2"],
)
def test_lm_pipeline_matches_single_dense(spec, microbatches):
    cfg = _cfg()
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    inp, tgt = _batch()
    p0_ref, p1_ref, loss_ref = _single_step(cfg, tx, rng, inp, tgt)

    fns = make_lm_step_fns(
        cfg, spec, tx, rng, B, T,
        devices=jax.devices()[: spec.num_devices],
        num_microbatches=microbatches,
    )
    s0 = fns.init_state()
    assert _maxerr(split_lm_params(p0_ref, spec.pipe), jax.device_get(s0.params)) == 0.0
    s1, m = fns.train(s0, inp, tgt)
    assert abs(float(m["loss"]) - loss_ref) < 1e-5
    assert (
        _maxerr(split_lm_params(p1_ref, spec.pipe), jax.device_get(s1.params)) < 1e-3
    )
    em = fns.evaluate(s1, inp, tgt)
    assert np.isfinite(float(em["loss"])) and 0.0 <= float(em["accuracy"]) <= 1.0


@pytest.mark.parametrize("impl", ["ring", "ulysses"])
def test_lm_pipeline_with_sequence_parallel_attention(impl):
    """PP x SP x TP: the ring/Ulysses cores nest as inner shard_maps
    (manual over seq, inheriting the context mesh) inside the
    manual-over-pipe pipeline region.  Must match the single-device dense
    run — both cores are numerically full attention."""
    cfg = _cfg(n_heads=4, n_layers=4)
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    inp, tgt = _batch()
    _, p1_ref, loss_ref = _single_step(cfg, tx, rng, inp, tgt)

    spec = LMMeshSpec(data=1, pipe=2, seq=2, model=2)
    fns = make_lm_step_fns(
        dataclasses.replace(cfg, attn_impl=impl), spec, tx, rng, B, T,
        devices=jax.devices()[:8], num_microbatches=2,
    )
    s1, m = fns.train(fns.init_state(), inp, tgt)
    assert abs(float(m["loss"]) - loss_ref) < 1e-5
    assert _maxerr(split_lm_params(p1_ref, 2), jax.device_get(s1.params)) < 1e-3


def test_lm_pipeline_moe_composition():
    """PP x TP x EP x FSDP in one program.  MoE parity is approximate: the
    load-balance aux is a product of batch-means, so per-microbatch
    computation differs from the full-batch value at O(variance/M) — the
    same class of semantic shift as per-microbatch BatchNorm in the CNN
    pipeline (torch-GPipe semantics, parallel/pipeline.py docstring)."""
    cfg = _cfg(num_experts=2, expert_top_k=1, remat=True, fsdp=True)
    tx = optax.adam(1e-2)
    rng = jax.random.key(1)
    inp, tgt = _batch(1)
    _, p1_ref, loss_ref = _single_step(cfg, tx, rng, inp, tgt)

    spec = LMMeshSpec(data=1, pipe=2, model=2, expert=2)
    fns = make_lm_step_fns(
        cfg, spec, tx, rng, B, T, devices=jax.devices()[:8], num_microbatches=2
    )
    s1, m = fns.train(fns.init_state(), inp, tgt)
    assert int(jax.device_get(s1.step)) == 1
    assert abs(float(m["loss"]) - loss_ref) < 5e-3
    assert _maxerr(split_lm_params(p1_ref, 2), jax.device_get(s1.params)) < 5e-2


def test_split_lm_params_stage_major():
    """Stage p must own layers [p*Lps, (p+1)*Lps) in order."""
    full = {
        "embed": {"embedding": jnp.zeros((4, 2))},
        "norm_f": {"scale": jnp.ones((2,))},
        "lm_head": {"kernel": jnp.zeros((2, 4))},
    }
    for i in range(4):
        full[f"block{i}"] = {"w": jnp.full((3,), float(i))}
    out = split_lm_params(full, 2)
    assert out["blocks"]["w"].shape == (2, 2, 3)
    np.testing.assert_array_equal(
        np.asarray(out["blocks"]["w"][:, :, 0]), [[0.0, 1.0], [2.0, 3.0]]
    )
    assert set(out) == {"embed", "blocks", "head"}


def test_lm_pipeline_validation_errors():
    tx = optax.adam(1e-2)
    rng = jax.random.key(0)
    with pytest.raises(ValueError, match="flash"):
        make_lm_pipeline_step_fns(
            _cfg(flash=True), LMMeshSpec(pipe=2), tx, rng, B, T, 2,
            devices=jax.devices()[:2],
        )
    with pytest.raises(ValueError, match="n_layers"):
        make_lm_pipeline_step_fns(
            _cfg(n_layers=3), LMMeshSpec(pipe=2), tx, rng, B, T, 2,
            devices=jax.devices()[:2],
        )
    with pytest.raises(ValueError, match="microbatches"):
        make_lm_pipeline_step_fns(
            _cfg(), LMMeshSpec(pipe=2), tx, rng, B, T, 3,
            devices=jax.devices()[:2],
        )
