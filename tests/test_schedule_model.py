"""Modeled pipeline-schedule accounting (obs/schedule_model.py): the
dependency-respecting lane simulator behind the ``pipe_schedule`` obs
event, the ``obs trace --step`` schedule lanes, and the ``bench
digest`` bubble table.  Pure stdlib — no JAX, no mesh."""

import pytest

from ddl_tpu.obs.schedule_model import (
    SCHEDULES,
    schedule_lanes,
    schedule_summary,
    schedule_table,
)


def _by_task(lanes):
    return {
        (u["phase"], u["mb"], u["stage"]): u
        for lane in lanes
        for u in lane
    }


@pytest.mark.parametrize("schedule", ["gpipe", "1f1b", "zb"])
@pytest.mark.parametrize("P,M", [(2, 4), (4, 8), (4, 16)])
def test_lanes_are_complete_and_dependency_respecting(schedule, P, M):
    lanes = schedule_lanes(schedule, P, M)
    tasks = _by_task(lanes)
    # every (phase, microbatch) unit exactly once
    assert len(tasks) == 3 * M * P
    for lane in lanes:
        # a stage is a serial processor: no overlapping units
        ordered = sorted(lane, key=lambda u: u["t0"])
        for a, b in zip(ordered, ordered[1:]):
            assert a["t1"] <= b["t0"] + 1e-9
    for (phase, m, sig), u in tasks.items():
        if phase == "F" and sig > 0:
            assert tasks[("F", m, sig - 1)]["t1"] <= u["t0"] + 1e-9
        if phase == "B":
            assert tasks[("F", m, sig)]["t1"] <= u["t0"] + 1e-9
            if sig < P - 1:
                assert tasks[("B", m, sig + 1)]["t1"] <= u["t0"] + 1e-9
        if phase == "W":
            assert tasks[("B", m, sig)]["t1"] <= u["t0"] + 1e-9


def test_zb_w_passes_drain_in_microbatch_order_none_dropped():
    """The W queue drains oldest-first: per stage, the W units appear in
    strictly increasing microbatch order and all M are present — the
    deferred-weight-grad lifecycle the clock loop implements."""
    for P, M in ((2, 4), (4, 8), (2, 8)):
        lanes = schedule_lanes("zb", P, M)
        for lane in lanes:
            ws = [u for u in lane if u["phase"] == "W"]
            ws.sort(key=lambda u: u["t0"])
            assert [u["mb"] for u in ws] == list(range(M))


def test_zb_defers_w_into_the_bubble():
    """The last stage's first W runs strictly after its first B would
    have fused it in 1F1B — the deferral is visible in the lanes."""
    P, M = 4, 8
    zb = _by_task(schedule_lanes("zb", P, M))
    o = _by_task(schedule_lanes("1f1b", P, M))
    s = P - 1
    assert zb[("W", 0, s)]["t0"] > o[("W", 0, s)]["t0"]


@pytest.mark.parametrize("P", [2, 4, 8])
def test_zb_strictly_fewer_idle_units_than_1f1b_at_m_ge_2p(P):
    """The acceptance bound: at M >= 2P the zero-bubble schedule idles
    strictly less stage-time than 1F1B (and no schedule idles less
    than zb among the modeled four)."""
    for M in (2 * P, 4 * P):
        rows = {r["schedule"]: r for r in schedule_table(P, M)}
        zb, o = rows["zb"], rows["1f1b"]
        assert zb["idle_units"] < o["idle_units"]
        assert zb["makespan"] <= o["makespan"]
        # gpipe and 1f1b share the classic (P-1)(tF+tB+tW) bubble —
        # 1F1B buys memory, not bubble; zb buys bubble
        assert rows["gpipe"]["idle_units"] == o["idle_units"]
        assert min(
            r["idle_units"] for r in rows.values() if "skipped" not in r
        ) == zb["idle_units"]


def test_interleaved_shrinks_gpipe_bubble():
    g = schedule_summary("gpipe", 4, 8)
    iv = schedule_summary("interleaved", 4, 8, virtual=2)
    assert iv["bubble_fraction"] < g["bubble_fraction"]
    # "interleaved" implies >= 2 chunks; the recorded metadata must
    # match the V the numbers were modeled at, not the raw argument
    iv1 = schedule_summary("interleaved", 4, 8, virtual=1)
    assert iv1["virtual"] == 2
    assert iv1["makespan"] == iv["makespan"]


def test_summary_shape_and_table_rows():
    s = schedule_summary("zb", 2, 4)
    assert s["pipe"] == 2 and s["microbatches"] == 4
    assert len(s["per_stage"]) == 2
    for st in s["per_stage"]:
        assert st["F"] == st["B"] == st["W"] == 4.0
        assert st["idle"] >= 0.0
    assert 0.0 <= s["bubble_fraction"] < 1.0
    rows = schedule_table(2, 4)
    assert [r["schedule"] for r in rows] == list(SCHEDULES)
    # M % P != 0: the interleaved row reports itself skipped instead of
    # silently vanishing (the no-silent-caps rule)
    rows = schedule_table(2, 3)
    iv = next(r for r in rows if r["schedule"] == "interleaved")
    assert "skipped" in iv


def test_validation_errors():
    with pytest.raises(ValueError, match="unknown schedule"):
        schedule_lanes("zb1", 2, 4)
    with pytest.raises(ValueError, match="single|gpipe"):
        schedule_lanes("zb", 2, 4, virtual=2)
    with pytest.raises(ValueError, match="groups of pipe"):
        schedule_lanes("interleaved", 2, 3, virtual=2)
